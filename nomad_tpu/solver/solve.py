"""Solver orchestration: pack -> device solve -> unpack into placements.

The discrete leftovers the tensor solve can't express (exact port picking,
device instance IDs — SURVEY §7.3) are fixed up host-side here, walking the
kernel's top-K candidates per placement so a port/instance conflict falls
through to the next-best node instead of failing the eval.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..structs import (AllocatedDeviceResource, AllocatedResources,
                       AllocatedSharedResources, AllocatedTaskResources,
                       AllocMetric, DeviceAccounter, NetworkIndex, Node)
from .kernel import TOP_K, solve_kernel
from .tensorize import (NUM_R, ClusterDelta, PackedBatch, PlacementAsk,
                        Tensorizer, alloc_device_usage,
                        alloc_usage_vector, apply_node_delta_host,
                        evict_width,
                        R_CPU, R_DISK, R_MEM, R_NET)

_DIM_NAMES = {R_CPU: "cpu", R_MEM: "memory", R_DISK: "disk", R_NET: "network"}

#: clusters below this size full-pack per eval (the walk is cheap and
#: every compiled shape stays identical to the seed behavior); at or
#: above it the Solver keeps a delta-updated resident world
RESIDENT_MIN_NODES = int(os.environ.get("NOMAD_TPU_RESIDENT_MIN_NODES",
                                        "512"))

#: brownout wave budget (serving tier, ISSUE 6): under sustained
#: overload the admission controller flips workers into degraded mode
#: and solves run with this reduced budget — undecided placements come
#: back retryable and follow the normal blocked/requeue path, trading
#: per-eval completeness for queue drain.  One extra cached compile
#: variant per shape (max_waves is a static kernel arg).
BROWNOUT_MAX_WAVES = int(os.environ.get("NOMAD_TPU_BROWNOUT_MAX_WAVES",
                                        "6"))


class LazyAllocsView(dict):
    """Proposed live allocs by node, filled lazily from the snapshot
    (live minus `excluded` alloc ids).  The steady-state scheduler only
    touches a handful of nodes per eval (chosen candidates' port/device
    fixups, sticky preferences), so the O(cluster) walk the eager dict
    pays per eval collapses to O(touched); anything that genuinely
    needs the whole world (full-pack fallback iterating items()) just
    materializes.  Once a key is filled it is a plain dict entry, so
    in-place mutation (sticky probes, preemption rewrites) behaves
    exactly like the eager dict."""

    def __init__(self, snapshot, excluded=frozenset()):
        super().__init__()
        self._snap = snapshot
        self.excluded = set(excluded)
        self._filled = set()
        self._all = False

    def _fill(self, nid) -> None:
        if self._all or nid in self._filled:
            return
        self._filled.add(nid)
        live = [a for a in self._snap.allocs_by_node(nid)
                if not a.terminal_status() and a.id not in self.excluded]
        if live:                 # eager dict only has non-empty keys
            dict.__setitem__(self, nid, live)

    def materialize(self) -> "LazyAllocsView":
        if not self._all:
            pending: Dict[str, list] = {}
            for a in self._snap.allocs():
                if (a.terminal_status() or a.id in self.excluded
                        or a.node_id in self._filled):
                    continue
                pending.setdefault(a.node_id, []).append(a)
            for nid, lst in pending.items():
                dict.__setitem__(self, nid, lst)
            self._all = True
        return self

    def get(self, nid, default=None):
        self._fill(nid)
        return dict.get(self, nid, default)

    def __getitem__(self, nid):
        self._fill(nid)
        return dict.__getitem__(self, nid)

    def __contains__(self, nid):
        self._fill(nid)
        return dict.__contains__(self, nid)

    def setdefault(self, nid, default=None):
        self._fill(nid)
        return dict.setdefault(self, nid, default)

    def items(self):
        return self.materialize() and dict.items(self)

    def keys(self):
        return self.materialize() and dict.keys(self)

    def values(self):
        return self.materialize() and dict.values(self)

    def __iter__(self):
        self.materialize()
        return dict.__iter__(self)

    def __len__(self):
        self.materialize()
        return dict.__len__(self)


class _ResidentWorld:
    """Delta-updated packed cluster state for a Solver (ISSUE 2
    tentpole, worker side): the node tensors are packed ONCE from a
    snapshot and then advanced by exact changesets — plan-apply results
    fed eagerly by the worker (note_plan_result) plus the state store's
    change log for everything written by other actors (client status
    updates, node joins/drains) — so steady-state scheduling never
    re-walks or re-tensorizes the world.  Falls back to a full rebuild
    when the change log was truncated, the delta escapes the interned
    universe, or it touches more than `delta_threshold` of the nodes."""

    def __init__(self, tz: Tensorizer, store, snapshot,
                 probe_asks: Sequence[PlacementAsk],
                 delta_threshold: float):
        self._tz = tz
        self.store = store
        self.delta_threshold = delta_threshold
        # probe asks define the ask universe; grown (dedup by spec
        # signature, capped) when an ask escapes it
        self._probe_sigs: Dict = {}
        self.probe_asks: List[PlacementAsk] = []
        self.add_probes(probe_asks)
        self.counters = {"delta_syncs": 0, "repack_fallbacks": 0,
                         "plan_feeds": 0, "last_delta_ratio": 0.0}
        self.drv_cache: Dict[str, np.ndarray] = {}
        self.row_cache: Dict = {}
        self.rebuild(snapshot)
        self.counters["repack_fallbacks"] = 0   # initial build is free

    def add_probes(self, asks: Sequence[PlacementAsk]) -> bool:
        added = False
        signer = self._tz.ask_signer()
        for a in asks:
            sig = signer(a)
            if sig not in self._probe_sigs and len(self.probe_asks) < 64:
                self._probe_sigs[sig] = True
                self.probe_asks.append(a)
                added = True
        return added

    def rebuild(self, snapshot) -> None:
        from ..utils.metrics import global_metrics as _m
        _m.incr_counter("solver.resident.rebuild")
        self.nodes = list(snapshot.nodes())          # join order
        by_node: Dict[str, list] = {}
        self.live: Dict[str, tuple] = {}             # id -> (nid, alloc)
        for a in snapshot.allocs():
            if not a.terminal_status():
                by_node.setdefault(a.node_id, []).append(a)
                self.live[a.id] = (a.node_id, a)
        # evictable planes ride on the template (in-kernel preemption,
        # ISSUE 7) and are delta-maintained with every other node plane
        self.template = self._tz.pack(self.nodes, self.probe_asks,
                                      by_node, evict_e=evict_width())
        # the template packs EVERY node; readiness (status, drain,
        # eligibility) lives in the valid mask instead of list filtering
        for i, n in enumerate(self.nodes):
            self.template.valid[i] = n.ready()
        self.node_index = {n.id: i for i, n in enumerate(self.nodes)}
        self.last_index = snapshot.index
        self.drv_cache.clear()
        self.row_cache.clear()
        self.counters["repack_fallbacks"] += 1

    def feed(self, delta: ClusterDelta) -> bool:
        """Apply an eagerly-fed changeset (plan-apply results).  The
        live map was already updated by the caller; only the tensors
        move here.  Returns False if the delta was inexpressible (the
        next sync() will rebuild)."""
        nd = self._tz.delta_pack(self.template, self.node_index, delta)
        if nd is None:
            return False
        apply_node_delta_host(self.template, nd, self.nodes,
                              self.node_index)
        if nd.touches_nodes():
            self.drv_cache.clear()
            self.row_cache.clear()
        return True

    def sync(self, snapshot) -> None:
        """Advance the world to `snapshot.index` via the store change
        log, building an exact ClusterDelta from the changed entities
        only."""
        if snapshot.index == self.last_index:
            return
        if snapshot.index < self.last_index:
            self.rebuild(snapshot)       # state moved backwards: a new
            return                       # snapshot from another store
        entries = self.store.changes_since(self.last_index,
                                           snapshot.index)
        if entries is None:              # ring truncated past us
            self.rebuild(snapshot)
            return
        delta = ClusterDelta()
        seen: set = set()
        for _ix, kind, key in reversed(entries):
            if (kind, key) in seen:      # newest entry per key wins
                continue
            seen.add((kind, key))
            if kind == "node":
                n = snapshot.node_by_id(key)
                if n is None:
                    if key in self.node_index:
                        delta.remove_node_ids.append(key)
                else:
                    delta.upsert_nodes.append(n)
            else:
                a = snapshot.alloc_by_id(key)
                live_now = a is not None and not a.terminal_status()
                tracked = self.live.get(key)
                if live_now and tracked is None:
                    delta.place.append((a.node_id, a))
                    self.live[key] = (a.node_id, a)
                elif tracked is not None and not live_now:
                    delta.stop.append(tracked)
                    del self.live[key]
                elif tracked is not None and live_now:
                    old_nid, old = tracked
                    if (old_nid != a.node_id
                            or not np.array_equal(
                                alloc_usage_vector(old),
                                alloc_usage_vector(a))):
                        delta.stop.append(tracked)
                        delta.place.append((a.node_id, a))
                    self.live[key] = (a.node_id, a)
        from ..utils.metrics import global_metrics as _m
        self.counters["delta_syncs"] += 1
        _m.incr_counter("solver.resident.delta_sync")
        if delta.empty():
            self.last_index = snapshot.index
            return
        nd = self._tz.delta_pack(self.template, self.node_index, delta)
        if nd is not None:
            ratio = nd.ratio(self.template.n_real)
            self.counters["last_delta_ratio"] = round(ratio, 6)
            if nd.touches_nodes() and ratio > self.delta_threshold:
                nd = None
        if nd is None:
            self.rebuild(snapshot)
            return
        apply_node_delta_host(self.template, nd, self.nodes,
                              self.node_index)
        if nd.touches_nodes():
            self.drv_cache.clear()
            self.row_cache.clear()
        self.last_index = snapshot.index


def _overlay_usage(world: _ResidentWorld, pb: PackedBatch,
                   proposed_delta) -> PackedBatch:
    """Copy-on-read overlay: apply this plan's proposed stops/probes to
    COPIES of the resident template's carried usage (and, for stops,
    the eviction candidate rows), leaving `world` bit-identical.  Both
    the steady-state solve and the what-if plan path
    (PlanSolverView) go through here — neither ever mutates
    _ResidentWorld state."""
    import copy as _copy
    pb = _copy.copy(pb)
    t = world.template
    used0 = t.used0.copy()
    dev_used0 = t.dev_used0.copy()
    stops, probes = proposed_delta or ((), ())
    D = dev_used0.shape[1]
    ev_gone: Dict[int, set] = {}
    for sign, group in ((-1.0, stops), (1.0, probes)):
        for a in group:
            i = world.node_index.get(a.node_id)
            if i is None:
                continue
            used0[i] += sign * alloc_usage_vector(a)
            drow = alloc_device_usage(t.dev_pattern_ids, D, a)
            if drow is not None:
                dev_used0[i] += sign * drow
            if sign < 0 and t.ev_lists is not None:
                ev_gone.setdefault(i, set()).add(a.id)
    pb.used0, pb.dev_used0 = used0, dev_used0
    if ev_gone and pb.ev_prio is not None:
        # an eager-stopped alloc's usage already left the overlay; it
        # must not ALSO be selectable as an eviction victim (its freed
        # capacity would double-count).  Rebuild the touched rows on
        # copies; sticky probes are additions and never candidates.
        from .tensorize import _evict_row
        ev_prio = pb.ev_prio.copy()
        ev_res = pb.ev_res.copy()
        ev_ids = list(pb.ev_ids)
        E = ev_prio.shape[1]
        for i, gone in ev_gone.items():
            cands = [c for c in t.ev_lists[i] if c[2] not in gone]
            ev_prio[i], ev_res[i], ev_ids[i] = _evict_row(cands, E)
        pb.ev_prio, pb.ev_res, pb.ev_ids = ev_prio, ev_res, ev_ids
    return pb


@dataclass
class Placement:
    ask_index: int
    node: Optional[Node]
    score: float
    metrics: AllocMetric
    resources: Optional[AllocatedResources] = None
    failed_reason: str = ""
    #: alloc ids the in-kernel preemption pass selected as victims for
    #: this placement (empty for normal placements) — the scheduler
    #: turns these into plan.node_preemptions
    evicted: List[str] = field(default_factory=list)


@dataclass
class SolveOutput:
    placements: List[Placement]
    class_eligibility: List[Dict[str, bool]] = field(default_factory=list)
    # ^ per ask: computed-class -> any feasible node of that class
    #: flight-recorder attributes for the solve span (ISSUE 10): device
    #: wave/rescore/evict counters, the two-tier modeled HBM bytes and
    #: the resident-world delta counters — callers attach this to the
    #: eval's trace instead of re-deriving it
    trace: Dict = field(default_factory=dict)


class PendingSolve:
    """An in-flight fused solve: packed and dispatched to the device,
    fetch + host fixup deferred.  `wait()` is the ONLY blocking step —
    it materializes the device result, runs the host fixup walk and
    returns the SolveOutput; idempotent, single-owner (the pipelined
    coordinator's drain leader).

    Timing stamps (perf_counter domain) let the caller account device
    time as interval unions under pipelining:

      t_dispatched     stamp right after the kernel launch returned
      dispatch_wall_s  pack + launch wall (host-side dispatch cost)
      fetch_wall_s     wall blocked inside wait() on the device result
      finish_wall_s    host fixup walk wall
    """

    __slots__ = ("_solver", "_pb", "_sol_nodes", "_asks",
                 "_allocs_by_node", "_by_dc", "_used_resident", "_res",
                 "_t0", "_out", "t_dispatched", "pack_wall_s",
                 "dispatch_wall_s", "fetch_wall_s", "finish_wall_s")

    def __init__(self, solver, pb=None, sol_nodes=None, asks=None,
                 allocs_by_node=None, by_dc=None,
                 used_resident: bool = False, res=None, t0: float = 0.0,
                 out: Optional[SolveOutput] = None):
        self._solver = solver
        self._pb = pb
        self._sol_nodes = sol_nodes
        self._asks = asks
        self._allocs_by_node = allocs_by_node
        self._by_dc = by_dc
        self._used_resident = used_resident
        self._res = res
        self._t0 = t0
        self._out = out
        self.t_dispatched = t0
        self.pack_wall_s = 0.0
        self.dispatch_wall_s = 0.0
        self.fetch_wall_s = 0.0
        self.finish_wall_s = 0.0

    def wait(self) -> SolveOutput:
        """Block until the device result lands, then run the host fixup.
        Safe to call again after completion (returns the cached output);
        NOT safe to call concurrently from two threads."""
        if self._out is not None:
            return self._out
        import time as _t
        t0 = _t.perf_counter()
        np.asarray(self._res.choice)   # blocks until the kernel is done
        t1 = _t.perf_counter()
        self.fetch_wall_s = t1 - t0
        out = self._solver._finish_solve(
            self._pb, self._sol_nodes, self._asks, self._res,
            self._used_resident, self._allocs_by_node, self._by_dc,
            self._t0)
        self.finish_wall_s = _t.perf_counter() - t1
        out.trace["dispatch_wall_s"] = round(self.dispatch_wall_s, 6)
        out.trace["fetch_wall_s"] = round(self.fetch_wall_s, 6)
        self._out = out
        # drop the packed batch + device refs so a long-lived pending
        # handle doesn't pin buffers
        self._res = self._pb = self._sol_nodes = self._asks = None
        self._allocs_by_node = self._by_dc = None
        return out


class Solver:
    """Stateful wrapper owning tensorizer memoization. One per scheduler
    worker (reference analog: the Stack owned by each scheduler).

    `host` picks the compute path: "auto" (default) solves small
    problems with the numpy twin of the kernel (host.py — identical
    placements, no device round trip; SURVEY §7.3's latency fallback),
    "never"/"always" pin a path (tests, benchmarks)."""

    def __init__(self, host: str = "auto", store=None,
                 resident: str = "auto",
                 resident_min_nodes: Optional[int] = None,
                 delta_threshold: float = 0.25) -> None:
        self._tensorizer = Tensorizer()
        self._host = host
        #: resident-world wiring (ISSUE 2): with a store attached, big
        #: clusters pack the node side once and advance it by changesets
        #: (plan-apply feed + store change log) instead of re-packing
        #: the world per eval.  "off" pins the seed behavior.
        self._store = store
        self._resident = resident if store is not None else "off"
        self._resident_min_nodes = (RESIDENT_MIN_NODES
                                    if resident_min_nodes is None
                                    else resident_min_nodes)
        self._delta_threshold = delta_threshold
        self._world: Optional[_ResidentWorld] = None
        self._degraded = False
        #: serializes resident-world access between the worker thread
        #: and overlay (what-if) solves from the HTTP plan endpoint
        self._world_lock = threading.Lock()

    # ---------------------------------------------------------- brownout
    def set_degraded(self, degraded: bool) -> None:
        """Serving-tier brownout: solve with the reduced
        BROWNOUT_MAX_WAVES budget while set (leftovers stay
        retryable)."""
        with self._world_lock:
            self._degraded = bool(degraded)

    @property
    def degraded(self) -> bool:
        with self._world_lock:
            return self._degraded

    # ------------------------------------------------- resident world
    def resident_active(self, snapshot=None) -> bool:
        """Whether the next solve against `snapshot` can take the
        resident-delta path (callers use this to pick the lazy allocs
        view over the eager world walk)."""
        if self._resident == "off" or self._store is None:
            return False
        if self._world is not None:
            return True
        if snapshot is None:
            return False
        return len(snapshot._t["nodes"]) >= self._resident_min_nodes

    def note_plan_result(self, plan, result) -> None:
        """Feed an applied plan's outcome into the resident world — the
        worker calls this right after submit_plan so the next eval's
        solve starts from already-advanced tensors and the change-log
        sync degenerates to a no-op dedup."""
        with self._world_lock:
            world = self._world
            if world is None or result is None:
                return
            delta = ClusterDelta()
            for nid, allocs in (result.node_update or {}).items():
                for a in allocs:
                    tracked = world.live.pop(a.id, None)
                    if tracked is not None:
                        delta.stop.append(tracked)
            for allocs in (result.node_preemptions or {}).values():
                for a in allocs:
                    tracked = world.live.pop(a.id, None)
                    if tracked is not None:
                        delta.stop.append(tracked)
            for nid, allocs in (result.node_allocation or {}).items():
                for a in allocs:
                    if a.id not in world.live \
                            and not a.terminal_status():
                        delta.place.append((nid, a))
                        world.live[a.id] = (nid, a)
            if delta.empty():
                return
            world.counters["plan_feeds"] += 1
            if not world.feed(delta):
                # inexpressible eagerly (e.g. alloc on an unknown
                # node): drop the world; the next solve rebuilds from
                # its snapshot
                self._world = None

    def resident_counters(self) -> Optional[Dict]:
        with self._world_lock:
            world = self._world
            return dict(world.counters) if world else None

    def health_counters(self):
        """Fleet health sample over the resident world's delta-
        maintained host template (ISSUE 15 telemetry tick).  Uses the
        numpy twin of the device health kernel — bit-identical by the
        telemetry property tests — so the server's 1 Hz beat never
        touches the device.  None while no resident world is active
        (small clusters host-walk; nothing to sample)."""
        with self._world_lock:
            world = self._world
            if world is None:
                return None
            from ..telemetry.health import health_host
            t = world.template
            return health_host(t, t.used0, t.dev_used0)

    def plan_view(self) -> "PlanSolverView":
        """Facade for dry-run (what-if) schedulers: same resident
        template, overlay-only solves, zero writes to carried state."""
        return PlanSolverView(self)

    def _resident_pack(self, snapshot, asks, proposed_delta,
                       overlay_only: bool = False):
        """The steady-state pack: sync the world to the snapshot via
        the change log, repack ONLY the ask side against the resident
        template, and overlay this plan's proposed stops/probes onto a
        copy of the maintained usage.  None -> caller full-packs.

        `overlay_only` (the what-if plan path): NEVER create, sync,
        rebuild, or grow the world — read the current template under
        the lock and overlay onto copies, so carried state stays
        bit-identical no matter how many plan solves interleave.
        Returns (pb, nodes) so callers never re-read self._world (a
        concurrent rebuild could swap the node list under them)."""
        if any(a.property_limits for a in asks):
            return None          # host-side walk the resident path skips
        with self._world_lock:
            if self._world is None:
                if overlay_only:
                    return None
                if len(snapshot._t["nodes"]) < self._resident_min_nodes:
                    return None
                self._world = _ResidentWorld(
                    self._tensorizer, self._store, snapshot, asks,
                    self._delta_threshold)
            world = self._world
            if not overlay_only:
                world.sync(snapshot)
            gp = max(self._pad(len(asks)), 1)
            kp = max(self._pad(sum(max(a.count, 1) for a in asks)), 1)
            pb = self._tensorizer.repack_asks(
                world.nodes, asks, world.template, gp=gp, kp=kp,
                drv_cache=world.drv_cache, row_cache=world.row_cache)
            if pb is None:
                if overlay_only:
                    return None
                # ask universe escape: grow the probes and rebuild once
                if not world.add_probes(asks):
                    return None
                world.rebuild(snapshot)
                pb = self._tensorizer.repack_asks(
                    world.nodes, asks, world.template, gp=gp, kp=kp,
                    drv_cache=world.drv_cache, row_cache=world.row_cache)
                if pb is None:
                    return None
            return (_overlay_usage(world, pb, proposed_delta),
                    world.nodes)

    @staticmethod
    def _pad(n: int) -> int:
        return 1 << max(0, (n - 1).bit_length())

    def solve(self, nodes: Sequence[Node], asks: Sequence[PlacementAsk],
              allocs_by_node: Optional[Dict[str, list]] = None,
              by_dc: Optional[Dict[str, int]] = None, *,
              snapshot=None, proposed_delta=None, preempt: bool = False,
              _overlay_only: bool = False) -> SolveOutput:
        """`preempt`: the scheduler resolved preemption as enabled for
        this eval — the resident path then runs the in-kernel eviction
        wave pass (ISSUE 7) and failed-capacity placements may come
        back with `Placement.evicted` victim ids instead of a failure.
        `_overlay_only`: what-if plan mode (see PlanSolverView)."""
        return self.solve_async(
            nodes, asks, allocs_by_node, by_dc, snapshot=snapshot,
            proposed_delta=proposed_delta, preempt=preempt,
            _overlay_only=_overlay_only).wait()

    def solve_async(self, nodes: Sequence[Node],
                    asks: Sequence[PlacementAsk],
                    allocs_by_node: Optional[Dict[str, list]] = None,
                    by_dc: Optional[Dict[str, int]] = None, *,
                    snapshot=None, proposed_delta=None,
                    preempt: bool = False,
                    _overlay_only: bool = False) -> "PendingSolve":
        """Dispatch phase of `solve`: pack and LAUNCH the kernel without
        fetching the result.  Returns a PendingSolve whose `wait()`
        blocks on the device fetch, runs the host fixup walk and yields
        the SolveOutput — the seam the pipelined coordinator rides to
        pack round b+1 while round b solves (the same dispatch/fetch
        split `solve_stream_async`/`finish_stream` and
        `device_health_raw`/`fetch_health` already use).

        When the solve resolves to the host kernel the "dispatch" runs
        it to completion (numpy has no async) and wait() is free; when
        the watchdog is armed the solve also degrades to eager, because
        the watchdog deadline must cover dispatch AND fetch as one
        window — a device wedge surfacing only at the fetch would
        escape a dispatch-only deadline."""
        import time as _t
        _solve_t0 = _t.perf_counter()
        if not asks:
            return PendingSolve(self, out=SolveOutput(placements=[]),
                                t0=_solve_t0)
        pb = None
        sol_nodes = nodes
        if snapshot is not None and self.resident_active(snapshot):
            packed = self._resident_pack(snapshot, asks, proposed_delta,
                                         overlay_only=_overlay_only)
            if packed is not None:
                pb, sol_nodes = packed
        used_resident = pb is not None
        if pb is None:
            with self._world_lock:
                # the tensorizer's interners are shared with concurrent
                # plan-view solves — serialize every pack through it
                pb = self._tensorizer.pack(nodes, asks, allocs_by_node)
        from .watchdog import global_watchdog
        _t_pack_done = _t.perf_counter()
        res = _run_kernel(pb, host_mode=self._host,
                          max_waves=BROWNOUT_MAX_WAVES
                          if self._degraded else 0,
                          preempt=preempt,
                          materialize=global_watchdog.enabled)
        pending = PendingSolve(self, pb=pb, sol_nodes=sol_nodes,
                               asks=list(asks),
                               allocs_by_node=allocs_by_node,
                               by_dc=by_dc,
                               used_resident=used_resident, res=res,
                               t0=_solve_t0)
        pending.t_dispatched = _t.perf_counter()
        pending.pack_wall_s = _t_pack_done - _solve_t0
        pending.dispatch_wall_s = pending.t_dispatched - _t_pack_done
        return pending

    def _finish_solve(self, pb: PackedBatch, sol_nodes, asks, res,
                      used_resident: bool, allocs_by_node, by_dc,
                      _solve_t0: float) -> SolveOutput:
        """Fetch-side half of `solve`: result materialization happened
        in PendingSolve.wait(); this walks the host fixup and builds
        the SolveOutput."""
        import time as _t
        trace_attrs = solve_trace_attrs(pb, res)
        trace_attrs["kernel_wall_s"] = round(
            _t.perf_counter() - _solve_t0, 6)
        trace_attrs["resident"] = used_resident
        if used_resident:
            world = self._world
            if world is not None:
                trace_attrs["world"] = dict(world.counters)

        choice = np.asarray(res.choice)
        choice_ok = np.asarray(res.choice_ok)
        score = np.asarray(res.score)
        n_feasible = np.asarray(res.n_feasible)
        n_exhausted = np.asarray(res.n_exhausted)
        dim_exhausted = np.asarray(res.dim_exhausted)
        feas = np.asarray(res.feas)
        cons_filtered = np.asarray(res.cons_filtered)
        unfinished = np.asarray(res.unfinished)
        evict = (np.asarray(res.evict) if res.evict is not None
                 else None)

        # host fixup state: per-node port/device accounting incl. in-batch.
        # host_used is the AUTHORITATIVE usage: when a placement falls through
        # to a lower-ranked candidate, the kernel's in-batch commit charged
        # the wrong node, so every candidate is re-checked against host_used
        # before acceptance. Likewise distinct_hosts / distinct_property are
        # re-enforced here across in-batch commits.
        net_cache: Dict[int, NetworkIndex] = {}
        dev_cache: Dict[int, DeviceAccounter] = {}
        host_used = pb.used0.copy()
        chosen_by_ask: Dict[int, set] = {}
        # distinct_property charges shared batch-wide by (scope, target) key
        prop_used: Dict[tuple, Dict[str, int]] = {}

        # Replay commits in KERNEL WAVE order when the preemption pass
        # ran: evictions make in-batch usage non-monotone, so an
        # ask-order replay can transiently exceed `avail` on a node
        # whose eviction the kernel sequenced earlier (false fall-
        # through).  Without evictions usage only grows and any prefix
        # of a feasible final state is feasible, so ask order is fine
        # (and commit_wave is None).
        order = list(range(pb.n_place))
        if res.commit_wave is not None:
            cwave = np.asarray(res.commit_wave)
            order.sort(key=lambda p: (int(cwave[p]) if cwave[p] >= 0
                                      else np.iinfo(np.int32).max, p))
        by_p: Dict[int, Placement] = {}
        for p in order:
            g = int(pb.p_ask[p])
            ask = asks[g]
            m = AllocMetric()
            m.nodes_evaluated = pb.n_real
            m.nodes_available = dict(by_dc or {})
            if unfinished[p]:
                # never decided: its per-wave metric slots were never
                # written, so don't fabricate filtered/exhausted counts
                m.nodes_filtered = 0
            else:
                m.nodes_filtered = pb.n_real - int(n_feasible[p])
                for ci, label in enumerate(pb.constraint_labels[g]):
                    cnt = int(cons_filtered[g, ci])
                    if cnt:
                        m.constraint_filtered[label] = cnt
                m.nodes_exhausted = int(n_exhausted[p])
                for d in range(NUM_R):
                    cnt = int(dim_exhausted[p, d])
                    if cnt:
                        m.dimension_exhausted[_DIM_NAMES[d]] = cnt

            placed = None
            ask_vec = pb.ask_res[g]
            if (evict is not None and evict[p].any()
                    and bool(choice_ok[p, 0])):
                # in-kernel preemption pass committed this placement:
                # slot 0 is its single node (no fall-through — the
                # victim set is node-specific); validate the discrete
                # leftovers with the victims removed, then charge
                # host_used with the NET usage (ask minus freed)
                placed = self._evict_commit(
                    int(choice[p, 0]), g, ask, pb, sol_nodes,
                    allocs_by_node, evict[p], host_used,
                    float(score[p, 0]), m)
                if placed is not None:
                    by_p[p] = placed
                    continue
                # discrete fixup failed (ports, stale victim view):
                # fall through as a normal failure — the scheduler's
                # host-side preemption walk remains the safety net
            for k in range(TOP_K):
                if not choice_ok[p, k]:
                    break
                ni = int(choice[p, k])
                node = sol_nodes[ni]
                if not np.all(host_used[ni] + ask_vec <= pb.avail[ni]):
                    continue
                gid = int(pb.distinct[g])
                if gid >= 0 and ni in chosen_by_ask.get(gid, ()):
                    continue
                prop_vals = self._property_fit(node, ask, prop_used)
                if prop_vals is None:
                    continue
                resources = self._host_commit(node, ni, ask, net_cache,
                                              dev_cache, allocs_by_node)
                if resources is None:
                    continue
                host_used[ni] += ask_vec
                if gid >= 0:
                    chosen_by_ask.setdefault(gid, set()).add(ni)
                for key, val in prop_vals:
                    by_val = prop_used.setdefault(key, {})
                    by_val[val] = by_val.get(val, 0) + 1
                m.score_meta = [
                    {"node_id": pb.node_ids[int(choice[p, j])],
                     "normalized_score": float(score[p, j])}
                    for j in range(TOP_K) if choice_ok[p, j]]
                placed = Placement(ask_index=g, node=node,
                                   score=float(score[p, k]), metrics=m,
                                   resources=resources)
                break
            if placed is None:
                if unfinished[p]:
                    # the wave budget ran out before this placement was
                    # decided; the blocked-eval path will retry it
                    reason = "solve wave budget exhausted (retryable)"
                elif n_feasible[p] > 0:
                    reason = "resources exhausted"
                else:
                    reason = "no feasible nodes"
                placed = Placement(ask_index=g, node=None, score=0.0,
                                   metrics=m, failed_reason=reason)
            by_p[p] = placed
        # emit in ask order regardless of replay order: the scheduler
        # maps placements back to its per-ask missing queues by
        # position
        placements: List[Placement] = [by_p[p]
                                       for p in range(pb.n_place)]

        # class eligibility for blocked-eval optimization
        class_elig: List[Dict[str, bool]] = []
        node_class = pb.node_class[:pb.n_real]
        inv_class = {v: k for k, v in pb.class_ids.items()}
        for g in range(pb.n_asks):
            fg = feas[g, :pb.n_real]
            elig: Dict[str, bool] = {}
            for cid, cname in inv_class.items():
                members = node_class == cid
                if members.any():
                    elig[cname] = bool(fg[members].any())
            class_elig.append(elig)

        return SolveOutput(placements=placements,
                           class_eligibility=class_elig,
                           trace=trace_attrs)

    def _evict_commit(self, ni: int, g: int, ask: PlacementAsk,
                      pb: PackedBatch, sol_nodes, allocs_by_node,
                      ev_row: np.ndarray, host_used: np.ndarray,
                      score: float, m: AllocMetric
                      ) -> Optional[Placement]:
        """Host fixup for a kernel-committed (place, evict) pair: map
        the victim-slot mask back to alloc ids through the packed
        `ev_ids` rows, re-check capacity net of the freed usage, and
        run the discrete port/device assignment against the node MINUS
        its victims (fresh accounting — the shared caches still hold
        the victims' reservations).  Returns None when the discrete
        leftovers fail; the caller falls back to the host preemption
        walk."""
        if pb.ev_ids is None or ni >= len(pb.ev_ids):
            return None
        node = sol_nodes[ni]
        victim_ids = [pb.ev_ids[ni][e] for e in np.nonzero(ev_row)[0]
                      if e < len(pb.ev_ids[ni]) and pb.ev_ids[ni][e]]
        if not victim_ids:
            return None
        vset = set(victim_ids)
        proposed = (list(allocs_by_node.get(node.id, ()))
                    if allocs_by_node is not None else [])
        victims = [a for a in proposed if a.id in vset]
        if len(victims) != len(vset):
            # the lazy view and the packed planes disagree (stale
            # world): refuse rather than evict the wrong alloc
            return None
        freed = np.zeros(NUM_R, np.float32)
        for a in victims:
            freed += alloc_usage_vector(a)
        ask_vec = pb.ask_res[g]
        if not np.all(host_used[ni] + ask_vec - freed
                      <= pb.avail[ni]):
            return None
        remaining = [a for a in proposed if a.id not in vset]
        resources = self._host_commit(node, ni, ask, {}, {},
                                      {node.id: remaining})
        if resources is None:
            return None
        host_used[ni] += ask_vec - freed
        m.score_meta = [{"node_id": pb.node_ids[ni],
                         "normalized_score": score}]
        return Placement(ask_index=g, node=node, score=score,
                         metrics=m, resources=resources,
                         evicted=sorted(victim_ids))

    @staticmethod
    def _host_commit(node: Node, node_ix: int, ask: PlacementAsk,
                     net_cache: Dict[int, NetworkIndex],
                     dev_cache: Dict[int, DeviceAccounter],
                     allocs_by_node) -> Optional[AllocatedResources]:
        """Build AllocatedResources with real ports + device instance ids.

        Works on clones and reserves each offer immediately, so multiple
        tasks in one group see each other's ports/instances; the clone is
        only promoted into the cache on success (all-or-nothing).
        Returns None if the discrete assignment fails on this node.
        """
        idx = net_cache.get(node_ix)
        if idx is None:
            idx = NetworkIndex()
            idx.set_node(node)
            if allocs_by_node:
                idx.add_allocs(allocs_by_node.get(node.id, ()))
            net_cache[node_ix] = idx
        acct = dev_cache.get(node_ix)
        if acct is None:
            acct = DeviceAccounter(node)
            if allocs_by_node:
                acct.add_allocs(allocs_by_node.get(node.id, ()))
            dev_cache[node_ix] = acct

        idx = idx.clone()
        acct = acct.clone()

        out = AllocatedResources()
        for t in ask.tg.tasks:
            tr = AllocatedTaskResources(cpu=t.resources.cpu,
                                        memory_mb=t.resources.memory_mb)
            for ask_net in t.resources.networks:
                offer, _err = idx.assign_network(ask_net)
                if offer is None:
                    return None
                idx.add_reserved(offer)
                tr.networks.append(offer)
            for d in t.resources.devices:
                got = Solver._assign_devices(acct, node, d)
                if got is None:
                    return None
                acct.add_reserved(got.vendor, got.type, got.name,
                                  got.device_ids)
                tr.devices.append(got)
            out.tasks[t.name] = tr
        shared_nets = []
        for ask_net in ask.tg.networks:
            offer, _err = idx.assign_network(ask_net)
            if offer is None:
                return None
            idx.add_reserved(offer)
            shared_nets.append(offer)
        out.shared = AllocatedSharedResources(
            disk_mb=ask.tg.ephemeral_disk.size_mb, networks=shared_nets)
        net_cache[node_ix] = idx
        dev_cache[node_ix] = acct
        return out

    @staticmethod
    def _property_fit(node: Node, ask: PlacementAsk,
                      used: Dict[tuple, Dict[str, int]]):
        """Check distinct_property limits against existing + in-batch counts.
        Limits are keyed (scope, attr target); charges under one key are
        shared across all asks carrying it (job-level scope spans the whole
        batch). Returns the (key, value) pairs to charge on acceptance, or
        None if any property is at its limit."""
        if not ask.property_limits:
            return ()
        from ..structs import resolve_node_target
        out = []
        for key, (limit, existing) in ask.property_limits.items():
            target = key[1] if isinstance(key, tuple) else key
            val, ok = resolve_node_target(node, target)
            if not ok:
                # nodes missing the property are infeasible for
                # distinct_property (reference: propertyset.go:240)
                return None
            val = str(val)
            count = existing.get(val, 0) + used.get(key, {}).get(val, 0)
            if count + 1 > limit:
                return None
            out.append((key, val))
        return out

    @staticmethod
    def _assign_devices(acct: DeviceAccounter, node: Node, req
                        ) -> Optional[AllocatedDeviceResource]:
        """Pick free instance ids matching the request pattern
        (reference: scheduler/device.go:32 AssignDevice)."""
        for dev in node.node_resources.devices:
            dv, dt, dm = dev.id_tuple()
            if not req.matches(dv, dt, dm):
                continue
            free = acct.free_instances(dv, dt, dm)
            if len(free) >= req.count:
                return AllocatedDeviceResource(
                    vendor=dv, type=dt, name=dm,
                    device_ids=free[:req.count])
        return None


class PlanSolverView:
    """Read-only facade over a worker's Solver for what-if planning
    (`/v1/job/:id/plan`, ISSUE 7): dry-run schedulers share the
    resident template — plan solves answer at steady-state speed
    instead of re-walking the cluster — but every solve goes through
    the copy-on-read overlay with `overlay_only` pinned, so the world
    is never created, synced, rebuilt, grown, or fed from a plan.
    Carried usage stays bit-identical under any plan/solve
    interleaving (tests/test_plan_overlay.py)."""

    def __init__(self, inner: Solver):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def resident_active(self, snapshot=None) -> bool:
        # only ride a world that already exists; a plan never builds one
        return (self._inner._resident != "off"
                and self._inner._world is not None)

    def note_plan_result(self, plan, result) -> None:
        return None              # dry-run plans never feed the world

    def set_degraded(self, degraded: bool) -> None:
        return None              # brownout belongs to the worker

    def solve(self, *args, **kw) -> SolveOutput:
        kw["_overlay_only"] = True
        return self._inner.solve(*args, **kw)


def solve_trace_attrs(pb: PackedBatch, res,
                      lane_counters: Optional[Dict] = None) -> Dict:
    """Flight-recorder attributes for one kernel run: the device wave/
    rescore/evict counters from the SolveResult plus the ISSUE-4
    two-tier modeled HBM bytes for this solve shape.  Pure read — the
    result arrays were fetched by the caller's unpack anyway.

    `lane_counters` (ISSUE 20): when the solve ran through the chunked
    scan-of-vmap stream (ResidentSolver.lane_counters()), the lane
    width and the cross-lane revalidation's bounce accounting join the
    trace — the explainability surface the bit-identity property test
    pins at L=1."""
    import numpy as _np
    waves = int(_np.asarray(res.n_waves))
    rescore = (int(_np.asarray(res.n_rescore))
               if res.n_rescore is not None else waves)
    evicted = (int(_np.asarray(res.evict).any(axis=1).sum())
               if res.evict is not None else 0)
    backend = ("host" if type(res.choice).__module__
               .startswith("numpy") else "device")
    attrs = {"n_asks": int(pb.n_asks), "n_place": int(pb.n_place),
             "n_nodes": int(pb.n_real), "backend": backend,
             "waves": waves, "rescore_waves": rescore,
             "shortlist_waves": waves - rescore,
             "evict_commits": evicted,
             "unfinished": int(_np.asarray(res.unfinished).sum())}
    if lane_counters is not None:
        attrs["lanes"] = int(lane_counters.get("lanes", 1))
        attrs["lane_chunks"] = int(lane_counters.get("chunks", 0))
        attrs["lane_bounced"] = int(lane_counters.get("bounced", 0))
        attrs["lane_committed"] = int(lane_counters.get("committed", 0))
        attrs["lane_bounce_rate"] = float(
            lane_counters.get("bounce_rate", 0.0))
    try:
        # modeled bytes mirror ResidentSolver.wave_traffic's resolution
        # (best effort: a model failure must never fail a solve)
        from . import pallas_kernel as _pk
        from .kernel import (MERGED_GP_MAX, TOP_K as _TK, WAVE_K,
                             _MERGED_W_CAP, _WIDE_W_CAP,
                             resolve_shortlist_c)
        from .resident import model_wave_bytes
        Np, R = pb.avail.shape
        Gp = pb.ask_res.shape[0]
        K = pb.p_ask.shape[0]
        S = pb.sp_desired.shape[1]
        has_spread = bool((_np.asarray(pb.sp_col[:, 0]) >= 0).any())
        w_cap = (_MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP)
        TKw = min(max(WAVE_K, w_cap) + _TK, Np)
        C = (0 if bool((_np.asarray(pb.distinct) >= 0).any())
             else resolve_shortlist_c(Np, TKw, 0))
        V = pb.sp_desired.shape[2]
        mode = _pk.resolve_mode(Np, Gp, TKw, V, has_spread)
        b1, brw, _passes = model_wave_bytes(Np, Gp, K, S, R,
                                            has_spread, mode, TKw, C)
        attrs["bytes_wave1"] = int(b1)
        attrs["bytes_rewave"] = int(brw)
        attrs["modeled_bytes_total"] = int(
            b1 * rescore + brw * (waves - rescore))
    except Exception:
        pass
    return attrs


def _run_kernel(pb: PackedBatch, host_mode: str = "auto",
                pallas: str = "auto", max_waves: int = 0,
                preempt: bool = False, materialize: bool = True):
    """`materialize=False` is the async-dispatch mode: the device
    kernel is launched but its result is NOT fetched — the caller owns
    the later materialization (PendingSolve.wait).  Ignored on the host
    path (numpy is eager) and forced on under the watchdog (its
    deadline must cover the fetch)."""
    import numpy as _np
    has_spread = bool((_np.asarray(pb.sp_col[:, 0]) >= 0).any())
    # in-kernel preemption (ISSUE 7): only when the batch carries the
    # evictable-alloc planes (resident path, evict_width() > 0) and has
    # no distinct_hosts groups — cross-group blocking is invisible to
    # the eviction pass, so those batches keep the host-side walk.
    # Host twin and device kernel get the SAME decision (bit-identity).
    ev_kw = {}
    if (preempt and pb.ev_prio is not None
            and not bool((_np.asarray(pb.distinct) >= 0).any())):
        ev_kw = dict(has_preempt=True, ev_res=pb.ev_res,
                     ev_prio=pb.ev_prio, ask_prio=pb.ask_prio)
        if max_waves == 0:
            # eviction commits serialize one-per-node-per-wave, so an
            # overcommitted batch needs more waves than the default
            # budget; host twin and device kernel get the same value
            from .kernel import MAX_WAVES
            max_waves = 2 * MAX_WAVES
    if host_mode != "never":
        from .host import host_solve_kernel, prefer_host
        if host_mode == "always" or prefer_host(
                pb.avail.shape[0], pb.n_asks, pb.n_place):
            return host_solve_kernel(*_kernel_args(pb),
                                     has_spread=has_spread,
                                     max_waves=max_waves, **ev_kw)
    # "auto" resolves to the pallas fused wave on TPU backends (or when
    # NOMAD_TPU_PALLAS forces it) and to the unfused kernel otherwise —
    # placement-identical either way (tests/test_pallas_kernel.py)
    host_ev_kw = dict(ev_kw)
    if ev_kw:
        # the eviction pass statically asserts no distinct batches;
        # the check above established it for this batch
        ev_kw["has_distinct"] = False

    def _device():
        from ..chaos.injection import global_injections
        inj = global_injections.get("device_solve")
        if inj is not None:
            inj.fire()
        # lane_axis stays None on the one-shot path: the lane-uniform
        # predicate form (psum over the vmap axis) only exists inside
        # the chunked scan-of-vmap stream — a one-shot solve under a
        # lane axis would trade its carried-window cond for a
        # collective for no reason (ISSUE 20)
        res = solve_kernel(*_kernel_args(pb), has_spread=has_spread,
                           pallas_mode=pallas, max_waves=max_waves,
                           lane_axis=None, **ev_kw)
        # materialize under the watchdog deadline: an async dispatch
        # that only wedges at a later fetch would escape it
        if materialize or global_watchdog.enabled:
            _np.asarray(res.choice)
        return res

    from .watchdog import global_watchdog
    if not global_watchdog.enabled:
        return _device()

    def _host():
        from .host import host_solve_kernel
        return host_solve_kernel(*_kernel_args(pb),
                                 has_spread=has_spread,
                                 max_waves=max_waves, **host_ev_kw)

    res, _backend = global_watchdog.run(
        _device, _host, label=f"solve:{pb.n_asks}x{pb.n_real}")
    return res


def _kernel_args(pb: PackedBatch):
    return (
        pb.avail, pb.reserved, pb.used0, pb.valid, pb.node_dc, pb.attr_rank,
        pb.ask_res, pb.ask_desired, pb.distinct, pb.dc_ok, pb.host_ok,
        pb.coll0,
        pb.penalty, pb.c_op, pb.c_col, pb.c_rank, pb.a_op, pb.a_col,
        pb.a_rank, pb.a_weight, pb.a_host, pb.sp_col, pb.sp_weight,
        pb.sp_targeted,
        pb.sp_desired, pb.sp_implicit, pb.sp_used0, pb.dev_cap, pb.dev_used0,
        pb.dev_ask, pb.p_ask, pb.n_place)
