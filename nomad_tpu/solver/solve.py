"""Solver orchestration: pack -> device solve -> unpack into placements.

The discrete leftovers the tensor solve can't express (exact port picking,
device instance IDs — SURVEY §7.3) are fixed up host-side here, walking the
kernel's top-K candidates per placement so a port/instance conflict falls
through to the next-best node instead of failing the eval.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..structs import (AllocatedDeviceResource, AllocatedResources,
                       AllocatedSharedResources, AllocatedTaskResources,
                       AllocMetric, DeviceAccounter, NetworkIndex, Node)
from .kernel import TOP_K, solve_kernel
from .tensorize import (NUM_R, PackedBatch, PlacementAsk, Tensorizer,
                        R_CPU, R_DISK, R_MEM, R_NET)

_DIM_NAMES = {R_CPU: "cpu", R_MEM: "memory", R_DISK: "disk", R_NET: "network"}


@dataclass
class Placement:
    ask_index: int
    node: Optional[Node]
    score: float
    metrics: AllocMetric
    resources: Optional[AllocatedResources] = None
    failed_reason: str = ""


@dataclass
class SolveOutput:
    placements: List[Placement]
    class_eligibility: List[Dict[str, bool]] = field(default_factory=list)
    # ^ per ask: computed-class -> any feasible node of that class


class Solver:
    """Stateful wrapper owning tensorizer memoization. One per scheduler
    worker (reference analog: the Stack owned by each scheduler).

    `host` picks the compute path: "auto" (default) solves small
    problems with the numpy twin of the kernel (host.py — identical
    placements, no device round trip; SURVEY §7.3's latency fallback),
    "never"/"always" pin a path (tests, benchmarks)."""

    def __init__(self, host: str = "auto") -> None:
        self._tensorizer = Tensorizer()
        self._host = host

    def solve(self, nodes: Sequence[Node], asks: Sequence[PlacementAsk],
              allocs_by_node: Optional[Dict[str, list]] = None,
              by_dc: Optional[Dict[str, int]] = None) -> SolveOutput:
        if not asks:
            return SolveOutput(placements=[])
        pb = self._tensorizer.pack(nodes, asks, allocs_by_node)
        res = _run_kernel(pb, host_mode=self._host)

        choice = np.asarray(res.choice)
        choice_ok = np.asarray(res.choice_ok)
        score = np.asarray(res.score)
        n_feasible = np.asarray(res.n_feasible)
        n_exhausted = np.asarray(res.n_exhausted)
        dim_exhausted = np.asarray(res.dim_exhausted)
        feas = np.asarray(res.feas)
        cons_filtered = np.asarray(res.cons_filtered)
        unfinished = np.asarray(res.unfinished)

        # host fixup state: per-node port/device accounting incl. in-batch.
        # host_used is the AUTHORITATIVE usage: when a placement falls through
        # to a lower-ranked candidate, the kernel's in-batch commit charged
        # the wrong node, so every candidate is re-checked against host_used
        # before acceptance. Likewise distinct_hosts / distinct_property are
        # re-enforced here across in-batch commits.
        net_cache: Dict[int, NetworkIndex] = {}
        dev_cache: Dict[int, DeviceAccounter] = {}
        host_used = pb.used0.copy()
        chosen_by_ask: Dict[int, set] = {}
        # distinct_property charges shared batch-wide by (scope, target) key
        prop_used: Dict[tuple, Dict[str, int]] = {}

        placements: List[Placement] = []
        for p in range(pb.n_place):
            g = int(pb.p_ask[p])
            ask = asks[g]
            m = AllocMetric()
            m.nodes_evaluated = pb.n_real
            m.nodes_available = dict(by_dc or {})
            if unfinished[p]:
                # never decided: its per-wave metric slots were never
                # written, so don't fabricate filtered/exhausted counts
                m.nodes_filtered = 0
            else:
                m.nodes_filtered = pb.n_real - int(n_feasible[p])
                for ci, label in enumerate(pb.constraint_labels[g]):
                    cnt = int(cons_filtered[g, ci])
                    if cnt:
                        m.constraint_filtered[label] = cnt
                m.nodes_exhausted = int(n_exhausted[p])
                for d in range(NUM_R):
                    cnt = int(dim_exhausted[p, d])
                    if cnt:
                        m.dimension_exhausted[_DIM_NAMES[d]] = cnt

            placed = None
            ask_vec = pb.ask_res[g]
            for k in range(TOP_K):
                if not choice_ok[p, k]:
                    break
                ni = int(choice[p, k])
                node = nodes[ni]
                if not np.all(host_used[ni] + ask_vec <= pb.avail[ni]):
                    continue
                gid = int(pb.distinct[g])
                if gid >= 0 and ni in chosen_by_ask.get(gid, ()):
                    continue
                prop_vals = self._property_fit(node, ask, prop_used)
                if prop_vals is None:
                    continue
                resources = self._host_commit(node, ni, ask, net_cache,
                                              dev_cache, allocs_by_node)
                if resources is None:
                    continue
                host_used[ni] += ask_vec
                if gid >= 0:
                    chosen_by_ask.setdefault(gid, set()).add(ni)
                for key, val in prop_vals:
                    by_val = prop_used.setdefault(key, {})
                    by_val[val] = by_val.get(val, 0) + 1
                m.score_meta = [
                    {"node_id": pb.node_ids[int(choice[p, j])],
                     "normalized_score": float(score[p, j])}
                    for j in range(TOP_K) if choice_ok[p, j]]
                placed = Placement(ask_index=g, node=node,
                                   score=float(score[p, k]), metrics=m,
                                   resources=resources)
                break
            if placed is None:
                if unfinished[p]:
                    # the wave budget ran out before this placement was
                    # decided; the blocked-eval path will retry it
                    reason = "solve wave budget exhausted (retryable)"
                elif n_feasible[p] > 0:
                    reason = "resources exhausted"
                else:
                    reason = "no feasible nodes"
                placed = Placement(ask_index=g, node=None, score=0.0,
                                   metrics=m, failed_reason=reason)
            placements.append(placed)

        # class eligibility for blocked-eval optimization
        class_elig: List[Dict[str, bool]] = []
        node_class = pb.node_class[:pb.n_real]
        inv_class = {v: k for k, v in pb.class_ids.items()}
        for g in range(pb.n_asks):
            fg = feas[g, :pb.n_real]
            elig: Dict[str, bool] = {}
            for cid, cname in inv_class.items():
                members = node_class == cid
                if members.any():
                    elig[cname] = bool(fg[members].any())
            class_elig.append(elig)

        return SolveOutput(placements=placements,
                           class_eligibility=class_elig)

    @staticmethod
    def _host_commit(node: Node, node_ix: int, ask: PlacementAsk,
                     net_cache: Dict[int, NetworkIndex],
                     dev_cache: Dict[int, DeviceAccounter],
                     allocs_by_node) -> Optional[AllocatedResources]:
        """Build AllocatedResources with real ports + device instance ids.

        Works on clones and reserves each offer immediately, so multiple
        tasks in one group see each other's ports/instances; the clone is
        only promoted into the cache on success (all-or-nothing).
        Returns None if the discrete assignment fails on this node.
        """
        idx = net_cache.get(node_ix)
        if idx is None:
            idx = NetworkIndex()
            idx.set_node(node)
            if allocs_by_node:
                idx.add_allocs(allocs_by_node.get(node.id, ()))
            net_cache[node_ix] = idx
        acct = dev_cache.get(node_ix)
        if acct is None:
            acct = DeviceAccounter(node)
            if allocs_by_node:
                acct.add_allocs(allocs_by_node.get(node.id, ()))
            dev_cache[node_ix] = acct

        idx = idx.clone()
        acct = acct.clone()

        out = AllocatedResources()
        for t in ask.tg.tasks:
            tr = AllocatedTaskResources(cpu=t.resources.cpu,
                                        memory_mb=t.resources.memory_mb)
            for ask_net in t.resources.networks:
                offer, _err = idx.assign_network(ask_net)
                if offer is None:
                    return None
                idx.add_reserved(offer)
                tr.networks.append(offer)
            for d in t.resources.devices:
                got = Solver._assign_devices(acct, node, d)
                if got is None:
                    return None
                acct.add_reserved(got.vendor, got.type, got.name,
                                  got.device_ids)
                tr.devices.append(got)
            out.tasks[t.name] = tr
        shared_nets = []
        for ask_net in ask.tg.networks:
            offer, _err = idx.assign_network(ask_net)
            if offer is None:
                return None
            idx.add_reserved(offer)
            shared_nets.append(offer)
        out.shared = AllocatedSharedResources(
            disk_mb=ask.tg.ephemeral_disk.size_mb, networks=shared_nets)
        net_cache[node_ix] = idx
        dev_cache[node_ix] = acct
        return out

    @staticmethod
    def _property_fit(node: Node, ask: PlacementAsk,
                      used: Dict[tuple, Dict[str, int]]):
        """Check distinct_property limits against existing + in-batch counts.
        Limits are keyed (scope, attr target); charges under one key are
        shared across all asks carrying it (job-level scope spans the whole
        batch). Returns the (key, value) pairs to charge on acceptance, or
        None if any property is at its limit."""
        if not ask.property_limits:
            return ()
        from ..structs import resolve_node_target
        out = []
        for key, (limit, existing) in ask.property_limits.items():
            target = key[1] if isinstance(key, tuple) else key
            val, ok = resolve_node_target(node, target)
            if not ok:
                # nodes missing the property are infeasible for
                # distinct_property (reference: propertyset.go:240)
                return None
            val = str(val)
            count = existing.get(val, 0) + used.get(key, {}).get(val, 0)
            if count + 1 > limit:
                return None
            out.append((key, val))
        return out

    @staticmethod
    def _assign_devices(acct: DeviceAccounter, node: Node, req
                        ) -> Optional[AllocatedDeviceResource]:
        """Pick free instance ids matching the request pattern
        (reference: scheduler/device.go:32 AssignDevice)."""
        for dev in node.node_resources.devices:
            dv, dt, dm = dev.id_tuple()
            if not req.matches(dv, dt, dm):
                continue
            free = acct.free_instances(dv, dt, dm)
            if len(free) >= req.count:
                return AllocatedDeviceResource(
                    vendor=dv, type=dt, name=dm,
                    device_ids=free[:req.count])
        return None


def _run_kernel(pb: PackedBatch, host_mode: str = "auto",
                pallas: str = "auto"):
    import numpy as _np
    has_spread = bool((_np.asarray(pb.sp_col[:, 0]) >= 0).any())
    if host_mode != "never":
        from .host import host_solve_kernel, prefer_host
        if host_mode == "always" or prefer_host(
                pb.avail.shape[0], pb.n_asks, pb.n_place):
            return host_solve_kernel(*_kernel_args(pb),
                                     has_spread=has_spread)
    # "auto" resolves to the pallas fused wave on TPU backends (or when
    # NOMAD_TPU_PALLAS forces it) and to the unfused kernel otherwise —
    # placement-identical either way (tests/test_pallas_kernel.py)
    return solve_kernel(*_kernel_args(pb), has_spread=has_spread,
                        pallas_mode=pallas)


def _kernel_args(pb: PackedBatch):
    return (
        pb.avail, pb.reserved, pb.used0, pb.valid, pb.node_dc, pb.attr_rank,
        pb.ask_res, pb.ask_desired, pb.distinct, pb.dc_ok, pb.host_ok,
        pb.coll0,
        pb.penalty, pb.c_op, pb.c_col, pb.c_rank, pb.a_op, pb.a_col,
        pb.a_rank, pb.a_weight, pb.a_host, pb.sp_col, pb.sp_weight,
        pb.sp_targeted,
        pb.sp_desired, pb.sp_implicit, pb.sp_used0, pb.dev_cap, pb.dev_used0,
        pb.dev_ask, pb.p_ask, pb.n_place)
