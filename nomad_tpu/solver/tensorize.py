"""Pack a (nodes, asks) scheduling problem into dense tensors.

This is the bridge between the host domain model and the TPU solve
(SURVEY §7.1 plane 2): node fingerprints and task-group asks become
`nodes[N,R]` resource tensors, rank-interned attribute columns, and
per-ask constraint programs. Non-vectorizable checks (regex, version,
semver, set_contains, host volumes, driver health) are evaluated host-side
— memoized by computed class exactly like the reference's
FeasibilityWrapper (scheduler/feasible.go:915) — and folded into a
per-ask boolean `host_ok` mask.

Resource dims (R=4): cpu MHz, memory MB, disk MB, network mbits.

Boolean plane dtype contract: the eligibility masks packed here
(`valid`, `dc_ok`, `host_ok`, `penalty`) stay dense bool on the host —
the interning/memoization layer mutates and compares them row-wise.
BITPACKING into uint32 lanes (1 bit per node column, masks.py
pack_bool_u32) happens at the kernel/transport boundary instead:
resident._stack_args packs `host_ok`/`penalty` before shipping, and
kernel.solve_kernel packs the derived feasibility/penalty planes once
per solve for the pallas fused wave — 8x fewer bytes everywhere the
masks actually move, with zero churn to the host-side packing paths.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from ..scheduler import feasible as hostfeas
from ..structs import (CONSTRAINT_ATTR_IS_NOT_SET, CONSTRAINT_ATTR_IS_SET,
                       CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
                       Constraint, Job, Node, TaskGroup, resolve_node_target)
from .interning import Interner, RankColumn

# Device-side constraint op codes
OP_NONE = 0
OP_EQ = 1
OP_NE = 2
OP_LT = 3
OP_LE = 4
OP_GT = 5
OP_GE = 6
OP_IS_SET = 7
OP_NOT_SET = 8

_VECTOR_OPS = {
    "=": OP_EQ, "==": OP_EQ, "is": OP_EQ,
    "!=": OP_NE, "not": OP_NE,
    "<": OP_LT, "<=": OP_LE, ">": OP_GT, ">=": OP_GE,
    CONSTRAINT_ATTR_IS_SET: OP_IS_SET,
    CONSTRAINT_ATTR_IS_NOT_SET: OP_NOT_SET,
}

R_CPU, R_MEM, R_DISK, R_NET = 0, 1, 2, 3
NUM_R = 4


def evict_width() -> int:
    """Top-E evictable-alloc slots per node for the in-kernel
    preemption planes (ISSUE 7).  NOMAD_TPU_EVICT_E overrides; 0
    disables packing the planes entirely (solves fall back to the
    host-side preemption walk)."""
    import os
    raw = os.environ.get("NOMAD_TPU_EVICT_E", "").strip()
    if not raw:
        return 8
    try:
        return max(0, int(raw))
    except ValueError:
        raise ValueError(
            f"NOMAD_TPU_EVICT_E={raw!r} invalid: use a non-negative "
            "integer slot width (0 disables)") from None


def _evict_sort_key(prio: int, create_index: int, alloc_id: str):
    """Canonical evictable-candidate order: lowest priority first, then
    create_index, then id — the tensorized total order behind
    scheduler/preemption.preemptible_allocs' (priority, create_index)
    sort (the id tail makes ties deterministic across repacks)."""
    return (prio, create_index, alloc_id)


def _evict_candidates(allocs) -> list:
    """Sorted evictable-candidate list for one node:
    [(prio, create_index, id, usage_vec), ...].  Job-less allocs have
    no knowable priority and are never victims (preemptible_allocs)."""
    out = []
    for a in allocs:
        if a.terminal_status() or a.job is None:
            continue
        out.append((int(a.job.priority), int(a.create_index), a.id,
                    alloc_usage_vector(a)))
    out.sort(key=lambda t: _evict_sort_key(t[0], t[1], t[2]))
    return out


def _evict_row(cands, E: int):
    """(prio [E] i16, res [E, R] f32, ids [E]) for one node's top-E
    evictable candidates (-1 / zeros / '' pad the empty slots)."""
    prio = np.full(E, -1, np.int16)
    res = np.zeros((E, NUM_R), np.float32)
    ids = [""] * E
    for e, (p, _ci, aid, vec) in enumerate(cands[:E]):
        prio[e] = min(max(int(p), -1), 32000)
        res[e] = vec
        ids[e] = aid
    return prio, res, ids


@dataclass
class PlacementAsk:
    """One task group needing `count` placements."""
    job: Job
    tg: TaskGroup
    count: int
    penalty_nodes: FrozenSet[str] = frozenset()     # previous-node penalties
    existing_by_node: Dict[str, int] = field(default_factory=dict)
    # ^ count of live allocs of this (job, tg) per node (anti-affinity +
    #   spread seed); computed by the scheduler from proposed state.
    distinct_hosts_blocked: FrozenSet[str] = frozenset()
    # ^ node ids excluded by distinct_hosts / distinct_property semantics
    spread_seed: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # ^ attr target -> value -> existing count (propertyset seed)
    property_limits: Dict[str, Tuple[int, Dict[str, int]]] = field(
        default_factory=dict)
    # ^ distinct_property: attr target -> (limit, existing count by value);
    #   enforced host-side across in-batch placements (solve.py)


def group_resource_vector(tg: TaskGroup) -> np.ndarray:
    """Summed resource ask for one instance of the group."""
    v = np.zeros(NUM_R, dtype=np.float32)
    for t in tg.tasks:
        v[R_CPU] += t.resources.cpu
        v[R_MEM] += t.resources.memory_mb
        for n in t.resources.networks:
            v[R_NET] += n.mbits
    for n in tg.networks:
        v[R_NET] += n.mbits
    v[R_DISK] = tg.ephemeral_disk.size_mb
    return v


def node_capacity_vectors(node: Node) -> Tuple[np.ndarray, np.ndarray]:
    """(capacity, reserved) R-vectors for a node."""
    cap = np.zeros(NUM_R, dtype=np.float32)
    res = np.zeros(NUM_R, dtype=np.float32)
    nr = node.node_resources
    cap[R_CPU], cap[R_MEM], cap[R_DISK] = nr.cpu, nr.memory_mb, nr.disk_mb
    cap[R_NET] = sum(n.mbits for n in nr.networks)
    rr = node.reserved_resources
    res[R_CPU], res[R_MEM], res[R_DISK] = rr.cpu, rr.memory_mb, rr.disk_mb
    return cap, res


def alloc_usage_vector(alloc) -> np.ndarray:
    v = np.zeros(NUM_R, dtype=np.float32)
    c = alloc.comparable_resources()
    v[R_CPU], v[R_MEM], v[R_DISK] = c.cpu, c.memory_mb, c.disk_mb
    v[R_NET] = sum(n.mbits for n in c.networks)
    return v


def alloc_device_usage(dev_pattern_ids, D: int, alloc
                       ) -> Optional[np.ndarray]:
    """[D] device-instance usage row for one alloc against a template's
    interned device patterns, or None when it uses none of them."""
    ar = getattr(alloc, "allocated_resources", None)
    if not dev_pattern_ids or ar is None:
        return None
    row = None
    from ..structs.resources import device_pattern_matches
    for tr in ar.tasks.values():
        for ad in tr.devices:
            for key, dix in dev_pattern_ids.items():
                if device_pattern_matches(key,
                                          (ad.vendor, ad.type, ad.name)):
                    if row is None:
                        row = np.zeros(D, np.float32)
                    row[dix] += len(ad.device_ids)
    return row


def _pad_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def _pad_nodes(n: int) -> int:
    """Node-axis padding.  Small clusters pad to a power of two (few
    distinct compiled shapes across tests/dryruns); large clusters pad
    to a multiple of 1024 — the TPU only needs lane alignment, and
    pow2-padding 10K nodes to 16K would do 1.6x the [G, N] wave work
    for nothing.

    Both regimes are TILE-ALIGNED for the pallas fused wave kernel
    (pallas_kernel.pick_tile): a power of two <= 4096 is divisible by
    every smaller power-of-two tile, and 1024-multiples split into
    lane-aligned 1024/2048 tiles — so the fused path never needs a
    ragged last tile."""
    if n <= 4096:
        return _pad_pow2(max(n, 1))
    return -(-n // 1024) * 1024


def _index_dtype(rank_columns, n_targets: int):
    """int16 when every interned rank (and column index) fits —
    halving the [Np, A] attribute matrix and the constraint/affinity
    program rows that the kernel streams per solve; int32 when a value
    universe is pathologically wide.  Resource tensors deliberately
    STAY float32: cpu-MHz/memory-MB values are integral and < 2^24 so
    f32 compares exactly, while fp16 would round them (a 11000-MHz
    node is not fp16-representable) and int tensors would re-convert
    on every fused multiply-add."""
    if n_targets < 32000 and all(rc.n_values < 32000
                                 for rc in rank_columns):
        return np.int16
    return np.int32


@dataclass
class PackedBatch:
    """Everything the kernel needs, as numpy arrays (device put by solver)."""
    # node axis
    node_ids: List[str]
    n_real: int
    avail: np.ndarray          # [Np, R] cap - reserved
    reserved: np.ndarray       # [Np, R]
    used0: np.ndarray          # [Np, R] live alloc usage (no reserved)
    valid: np.ndarray          # [Np] bool
    node_class: np.ndarray     # [Np] i32 interned computed class
    node_dc: np.ndarray        # [Np] i32 interned datacenter
    attr_rank: np.ndarray      # [Np, A] i32 rank-interned values (-1 missing)
    # ask axis
    n_asks: int
    ask_res: np.ndarray        # [Gp, R]
    ask_desired: np.ndarray    # [Gp] f32 tg.count for anti-affinity denom
    distinct: np.ndarray       # [Gp] i32 distinct_hosts group id (-1 none):
    #   in-batch placements sharing a group id must land on distinct nodes;
    #   a job-level constraint puts all the job's asks in one group
    dc_ok: np.ndarray          # [Gp, NDC] bool over interned dc ids
    host_ok: np.ndarray        # [Gp, Np] bool host-evaluated feasibility
    coll0: np.ndarray          # [Gp, Np] f32 same-(job,tg) live counts
    penalty: np.ndarray        # [Gp, Np] bool reschedule penalty nodes
    # constraint programs
    c_op: np.ndarray           # [Gp, C] i32
    c_col: np.ndarray          # [Gp, C] i32 attr column
    c_rank: np.ndarray         # [Gp, C] i32 operand rank
    # affinities
    a_op: np.ndarray           # [Gp, CA] i32
    a_col: np.ndarray          # [Gp, CA]
    a_rank: np.ndarray         # [Gp, CA]
    a_weight: np.ndarray       # [Gp, CA] f32 (0 = empty slot)
    a_host: np.ndarray         # [Gp, Np] f32 host-evaluated affinity score
    # spreads
    sp_col: np.ndarray         # [Gp, S] i32 attr column (-1 empty)
    sp_weight: np.ndarray      # [Gp, S] f32 weight/sumWeights
    sp_targeted: np.ndarray    # [Gp, S] bool
    sp_desired: np.ndarray     # [Gp, S, V] f32 desired count per value rank
    sp_implicit: np.ndarray    # [Gp, S] f32 implicit-target desired (-1 none)
    sp_used0: np.ndarray       # [Gp, S, V] f32
    # devices
    dev_cap: np.ndarray        # [Np, D] f32 healthy instance counts per pattern
    dev_used0: np.ndarray      # [Np, D]
    dev_ask: np.ndarray        # [Gp, D]
    # placement schedule
    p_ask: np.ndarray          # [K] i32 ask index per placement step
    n_place: int
    # unpack metadata
    rank_columns: List[RankColumn] = field(default_factory=list)
    attr_targets: List[str] = field(default_factory=list)
    constraint_labels: List[List[str]] = field(default_factory=list)
    class_ids: Dict[str, int] = field(default_factory=dict)
    dc_ids: Dict[str, int] = field(default_factory=dict)
    dev_pattern_ids: Dict[Tuple[str, str, str], int] = field(
        default_factory=dict)
    # in-kernel preemption planes (ISSUE 7) — present when the batch
    # was packed with evict_e > 0; delta-maintained on templates like
    # every other node-axis plane (apply_node_delta_host)
    ask_prio: Optional[np.ndarray] = None   # [Gp] i32 job priority
    ev_prio: Optional[np.ndarray] = None    # [Np, E] i16 victim priority
    #   (-1 = empty slot; slots in _evict_sort_key order)
    ev_res: Optional[np.ndarray] = None     # [Np, E, R] f32 victim usage
    ev_ids: Optional[List[List[str]]] = None  # [Np][E] alloc ids
    ev_lists: Optional[List[list]] = None   # per-node candidate lists
    #   (template-only; _evict_candidates order, feeds delta recompute)


@dataclass
class ClusterDelta:
    """Changeset between two cluster states (the plan-apply feedback
    unit): nodes joined/updated, nodes drained/removed, allocs placed,
    allocs stopped.  The incremental tensorize path (delta_pack) turns
    one of these into small scatter arrays instead of a full [N, R]/[A]
    re-tensorization."""
    upsert_nodes: List = field(default_factory=list)   # joined or changed
    remove_node_ids: List[str] = field(default_factory=list)
    place: List[Tuple[str, object]] = field(default_factory=list)
    # ^ (node_id, alloc) usage adds
    stop: List[Tuple[str, object]] = field(default_factory=list)
    # ^ (node_id, alloc) usage subtracts

    def empty(self) -> bool:
        return not (self.upsert_nodes or self.remove_node_ids
                    or self.place or self.stop)

    def size(self) -> int:
        return (len(self.upsert_nodes) + len(self.remove_node_ids)
                + len(self.place) + len(self.stop))


@dataclass
class NodeDelta:
    """Scatter-update arrays produced by Tensorizer.delta_pack: the
    node-side rows a ClusterDelta touches, ready for an `.at[idx].set`
    / `.at[idx].add` device apply (resident.apply_delta) or an in-place
    numpy apply (apply_node_delta_host)."""
    idx: np.ndarray          # [M] i32 touched node slots (upsert+remove)
    avail: np.ndarray        # [M, R]
    reserved: np.ndarray     # [M, R]
    valid: np.ndarray        # [M] bool
    node_class: np.ndarray   # [M] i32
    node_dc: np.ndarray      # [M] i32
    attr_rank: np.ndarray    # [M, A] template dtype
    dev_cap: np.ndarray      # [M, D]
    u_idx: np.ndarray        # [Mu] i32 usage-touched slots (deduped)
    u_res: np.ndarray        # [Mu, R] signed usage adds
    u_dev: np.ndarray        # [Mu, D] signed device-usage adds
    new_nodes: List = field(default_factory=list)  # joins, slot order
    n_real_new: int = 0
    # raw alloc ops (slot, alloc) / (slot, alloc) for templates that
    # carry eviction planes: apply_node_delta_host replays them into
    # ev_lists and recomputes the touched ev rows
    alloc_place: List[Tuple[int, object]] = field(default_factory=list)
    alloc_stop: List[Tuple[int, object]] = field(default_factory=list)

    def nbytes(self) -> int:
        return sum(a.nbytes for a in (
            self.idx, self.avail, self.reserved, self.valid,
            self.node_class, self.node_dc, self.attr_rank, self.dev_cap,
            self.u_idx, self.u_res, self.u_dev))

    def touches_nodes(self) -> bool:
        return self.idx.size > 0

    def ratio(self, n_real: int) -> float:
        """Fraction of real node slots this delta touches — the
        repack-fallback threshold input (scattering most of the array
        is slower than one contiguous re-put)."""
        touched = len(set(self.idx.tolist()) | set(self.u_idx.tolist()))
        return touched / max(n_real, 1)


def apply_node_delta_host(template: PackedBatch, nd: NodeDelta,
                          nodes: List[Node],
                          node_index: Dict[str, int]) -> None:
    """Apply a NodeDelta to the numpy template in place (the host twin
    of the device scatter apply), growing nodes/node_ids/n_real for
    joins.  Removed nodes stay as valid=False tombstones so every
    surviving slot keeps its index (and therefore its tie-break order
    and its carried usage row)."""
    for n in nd.new_nodes:
        node_index[n.id] = len(nodes)
        nodes.append(n)
        template.node_ids.append(n.id)
    template.n_real = nd.n_real_new
    if nd.idx.size:
        template.avail[nd.idx] = nd.avail
        template.reserved[nd.idx] = nd.reserved
        template.valid[nd.idx] = nd.valid
        template.node_class[nd.idx] = nd.node_class
        template.node_dc[nd.idx] = nd.node_dc
        template.attr_rank[nd.idx] = nd.attr_rank
        template.dev_cap[nd.idx] = nd.dev_cap
    if nd.u_idx.size:
        # u_idx rows are pre-aggregated per slot (no duplicate indices)
        template.used0[nd.u_idx] += nd.u_res
        template.dev_used0[nd.u_idx] += nd.u_dev
    if template.ev_lists is not None:
        _apply_evict_delta(template, nd)


def apply_evict_ops(template: PackedBatch, stops, places) -> None:
    """Advance the template's eviction planes by slot-level alloc ops:
    replay (slot, alloc) stops then places into ev_lists (stops BEFORE
    places — an updated alloc arrives as stop+place of the same id)
    and recompute the touched top-E rows.  Shared by the NodeDelta
    path (_apply_evict_delta) and the resident repack carry."""
    import bisect
    lists = template.ev_lists
    while len(lists) < len(template.node_ids):
        lists.append([])            # joined nodes start empty
    touched = set()
    for s, alloc in stops:
        aid = alloc.id
        lists[s] = [t for t in lists[s] if t[2] != aid]
        touched.add(s)
    for s, alloc in places:
        if alloc.terminal_status() or alloc.job is None:
            continue
        ent = (int(alloc.job.priority), int(alloc.create_index),
               alloc.id, alloc_usage_vector(alloc))
        keys = [_evict_sort_key(t[0], t[1], t[2]) for t in lists[s]]
        pos = bisect.bisect_left(keys, _evict_sort_key(*ent[:3]))
        lists[s].insert(pos, ent)
        touched.add(s)
    # invalid (drained/removed) slots keep their candidate lists: the
    # kernel's eviction pass already gates on `feas` (which carries
    # `valid`), and a tombstone that revives keeps exact state
    E = template.ev_prio.shape[1]
    for s in touched:
        if s >= template.ev_prio.shape[0]:
            continue
        prio, res, ids = _evict_row(lists[s], E)
        template.ev_prio[s] = prio
        template.ev_res[s] = res
        template.ev_ids[s] = ids


def _apply_evict_delta(template: PackedBatch, nd: NodeDelta) -> None:
    apply_evict_ops(template, nd.alloc_stop, nd.alloc_place)


# ---------------------------------------------- plane epoch checksums
# ISSUE 14: a cheap, order-stable fingerprint over the node-axis
# planes.  The same function computed on the host template and on the
# arrays fetched back from device must agree at every healthy quiesce
# point — this is the invariant harness's post-recovery check that a
# reshard/rebuild restored EXACTLY the raft-fed state.

def plane_crc(avail, reserved, valid, node_dc, attr_rank, dev_cap,
              ev_prio=None, ev_res=None, meta: bytes = b"") -> int:
    """CRC32 over the node-side planes in a fixed order.  `valid` is
    canonicalized to uint8 so host bools and fetched device bools hash
    identically."""
    import zlib
    crc = zlib.crc32(meta)
    arrs = [np.ascontiguousarray(np.asarray(avail)),
            np.ascontiguousarray(np.asarray(reserved)),
            np.ascontiguousarray(np.asarray(valid).astype(np.uint8)),
            np.ascontiguousarray(np.asarray(node_dc)),
            np.ascontiguousarray(np.asarray(attr_rank)),
            np.ascontiguousarray(np.asarray(dev_cap))]
    if ev_prio is not None:
        arrs.append(np.ascontiguousarray(np.asarray(ev_prio)))
        arrs.append(np.ascontiguousarray(np.asarray(ev_res)))
    for a in arrs:
        crc = zlib.crc32(a.tobytes(), crc)
    return crc


def template_checksum(template: PackedBatch) -> int:
    """Fingerprint of a template's node-side planes (the raft-fed
    source of truth).  Compare with ResidentSolver.plane_checksum()."""
    t = template
    meta = f"{t.n_real}:{','.join(t.node_ids)}".encode()
    return plane_crc(t.avail, t.reserved, t.valid, t.node_dc,
                     t.attr_rank, t.dev_cap, ev_prio=t.ev_prio,
                     ev_res=t.ev_res, meta=meta)


# ------------------------------------------------- elastic tile layout
# ISSUE 8: the elastic mesh owns the node axis in TILES of `tile_np`
# slots routed by an owner remap table instead of contiguous
# axis-index blocks.  A reshard (grow/shrink/rebalance/recover) edits
# the table and moves ONE tile's rows — never the world.

def pick_tile_np(np_pad: int, n_shards: int) -> int:
    """Default shard-tile width: ~4 tiles per shard, power of two so it
    always divides the padded node axis (pow2 <= 4096 or a 1024
    multiple — see _pad_nodes), floor 8, cap 1024.
    NOMAD_TPU_SHARD_TILE overrides."""
    import os
    raw = os.environ.get("NOMAD_TPU_SHARD_TILE", "").strip()
    if raw:
        try:
            t = int(raw)
        except ValueError:
            raise ValueError(
                f"NOMAD_TPU_SHARD_TILE={raw!r} invalid: use a positive "
                "power-of-two slot width") from None
        if t <= 0 or t & (t - 1) or np_pad % t:
            raise ValueError(
                f"NOMAD_TPU_SHARD_TILE={t} invalid: must be a positive "
                f"power of two dividing the padded node axis {np_pad}")
        return t
    target = max(8, np_pad // max(4 * n_shards, 1))
    t = 1 << (target.bit_length() - 1)
    return max(8, min(t, 1024, np_pad))


class TileLayout:
    """Owner remap for the elastic node axis: tile t of `tile_np` slots
    lives on shard owner[t] at local tile position slot[t] (-1 owner =
    unowned: retired, or lost with its shard).  Every shard carries
    `cap_tiles` tile slots (power of two, so the local width stays
    pallas-tileable); unfilled slots are DEAD (valid False, dead global
    ids) and cost slack HBM, which is what makes a grow-by-one-tile
    reshard ship one tile instead of repadding the world."""

    def __init__(self, n_tiles: int, n_shards: int, tile_np: int,
                 cap_tiles: Optional[int] = None, slack_tiles: int = 1):
        self.tile_np = int(tile_np)
        self.n_shards = int(n_shards)
        self.n_tiles = int(n_tiles)
        need = -(-n_tiles // max(n_shards, 1)) + max(slack_tiles, 0)
        if cap_tiles is None:
            cap_tiles = _pad_pow2(max(need, 1), floor=1)
        if cap_tiles * n_shards < n_tiles:
            raise ValueError(
                f"cap_tiles={cap_tiles} x {n_shards} shards cannot hold "
                f"{n_tiles} tiles")
        self.cap_tiles = int(cap_tiles)
        # contiguous initial placement: tile t -> shard t // per, the
        # PR-5 block layout (so an un-resharded elastic solve is the
        # same data arrangement as the static mesh)
        self.owner = np.full(n_tiles, -1, np.int32)
        self.slot = np.zeros(n_tiles, np.int32)
        fill = np.zeros(n_shards, np.int32)
        for t in range(n_tiles):
            s = min(t * n_shards // max(n_tiles, 1), n_shards - 1)
            if fill[s] >= cap_tiles:
                s = int(np.argmin(fill))
            self.owner[t] = s
            self.slot[t] = fill[s]
            fill[s] += 1

    # ---------------- geometry ----------------
    @property
    def npl(self) -> int:
        """Per-shard local node-axis width (slots)."""
        return self.cap_tiles * self.tile_np

    @property
    def n_slots(self) -> int:
        return self.n_shards * self.npl

    def tiles_of(self, shard: int):
        return [t for t in range(self.n_tiles)
                if self.owner[t] == shard]

    def free_slots(self, shard: int) -> int:
        return self.cap_tiles - len(self.tiles_of(shard))

    def least_loaded(self) -> int:
        loads = [len(self.tiles_of(s)) for s in range(self.n_shards)]
        return int(np.argmin(loads))

    # ---------------- table edits ----------------
    def assign(self, t: int, shard: int) -> int:
        """Place tile t on `shard` at its lowest free tile slot."""
        if self.owner[t] >= 0:
            raise ValueError(f"tile {t} already owned by {self.owner[t]}")
        taken = {int(self.slot[u]) for u in self.tiles_of(shard)}
        for sl in range(self.cap_tiles):
            if sl not in taken:
                self.owner[t] = shard
                self.slot[t] = sl
                return sl
        raise ValueError(f"shard {shard} has no free tile slot")

    def release(self, t: int) -> None:
        self.owner[t] = -1
        self.slot[t] = 0

    def grow(self, n: int = 1) -> List[int]:
        """Extend the global axis by n UNOWNED tiles (assign next)."""
        new = list(range(self.n_tiles, self.n_tiles + n))
        self.n_tiles += n
        self.owner = np.concatenate(
            [self.owner, np.full(n, -1, np.int32)])
        self.slot = np.concatenate([self.slot, np.zeros(n, np.int32)])
        return new

    # ---------------- derived device tables ----------------
    def dev_rows(self, t: int) -> np.ndarray:
        """Device-layout row range of tile t (owner's block)."""
        lo = int(self.owner[t]) * self.npl \
            + int(self.slot[t]) * self.tile_np
        return np.arange(lo, lo + self.tile_np)

    def dev_src(self) -> np.ndarray:
        """[n_slots] global row per device row (-1 = dead slot)."""
        src = np.full(self.n_slots, -1, np.int64)
        for t in range(self.n_tiles):
            if self.owner[t] >= 0:
                src[self.dev_rows(t)] = np.arange(
                    t * self.tile_np, (t + 1) * self.tile_np)
        return src

    def node_gid(self, nt_pad: int) -> np.ndarray:
        """[n_slots] global id per device row; dead rows get unique
        ids past the global axis (they hash/merge deterministically
        and can never win or be owned)."""
        src = self.dev_src()
        gid = src.astype(np.int32)
        dead = src < 0
        gid[dead] = nt_pad + np.nonzero(dead)[0].astype(np.int32)
        return gid

    def tables(self):
        """(owner_map, slot_map) [T+1] i32 with the -1 sentinel row the
        kernel clips out-of-range tile indices onto."""
        om = np.full(self.n_tiles + 1, -1, np.int32)
        om[:self.n_tiles] = self.owner
        sm = np.zeros(self.n_tiles + 1, np.int32)
        sm[:self.n_tiles] = self.slot
        return om, sm

    def g2d(self, gids: np.ndarray, unowned: str = "raise"
            ) -> np.ndarray:
        """Global node rows -> device-layout rows.  unowned="raise"
        rejects rows in unowned tiles; "drop" maps them to n_slots —
        out of every shard's local range, so the sharded scatter
        kernels pin and drop them (the degraded-mesh delta path:
        a lost tile's rows stay host-side until recover)."""
        g = np.asarray(gids, np.int64)
        t = g // self.tile_np
        bad = self.owner[t] < 0
        if bad.any():
            if unowned != "drop":
                raise ValueError("global row maps to an unowned tile")
        d = (self.owner[t].astype(np.int64) * self.npl
             + self.slot[t].astype(np.int64) * self.tile_np
             + g % self.tile_np)
        return np.where(bad, np.int64(self.n_slots), d)

    def remap_shards(self, new_ids: Dict[int, int],
                     n_shards: int) -> "TileLayout":
        """A copy on a different shard count: surviving shards keep
        their tiles at their slots under their new ids; tiles of
        shards absent from `new_ids` become unowned (the shard-loss
        transition)."""
        out = TileLayout.__new__(TileLayout)
        out.tile_np = self.tile_np
        out.n_shards = int(n_shards)
        out.n_tiles = self.n_tiles
        out.cap_tiles = self.cap_tiles
        out.owner = np.full(self.n_tiles, -1, np.int32)
        out.slot = self.slot.copy()
        for t in range(self.n_tiles):
            o = int(self.owner[t])
            if o >= 0 and o in new_ids:
                out.owner[t] = new_ids[o]
        return out


#: node-axis template arrays extended by a tile-granular grow, with
#: their dead-row fill values (matching the tensorizer's padding)
_NODE_AXIS_FILLS = (
    ("avail", 0), ("reserved", 0), ("used0", 0), ("valid", False),
    ("node_class", 0), ("node_dc", 0), ("attr_rank", -1),
    ("dev_cap", 0), ("dev_used0", 0), ("ev_prio", -1), ("ev_res", 0),
)


def extend_template_rows(template: PackedBatch, n_rows: int) -> None:
    """Grow the template's global node axis by n_rows dead slots (the
    tile-granular Np growth of ISSUE 8): every node-axis plane is
    extended in place with its pad value — NO repack, no re-interning;
    joining nodes then fill the new slots through the normal delta
    path."""
    for name, fill in _NODE_AXIS_FILLS:
        arr = getattr(template, name, None)
        if arr is None:
            continue
        pad = np.full((n_rows,) + arr.shape[1:], fill, arr.dtype)
        setattr(template, name, np.concatenate([arr, pad]))
    if template.ev_ids is not None:
        E = template.ev_prio.shape[1]
        template.ev_ids.extend([[""] * E for _ in range(n_rows)])


class Tensorizer:
    """Builds PackedBatch from nodes + asks. Stateless across calls except
    for host-op memoization keyed by computed class."""

    def __init__(self) -> None:
        self._class_memo: Dict[Tuple[str, tuple], bool] = {}
        # shared read-only default [gp, Np] planes (see repack_asks)
        self._planes: Dict[Tuple[str, int, int, int], np.ndarray] = {}

    def _shared_plane(self, name: str, gp: int, Np: int,
                      n_real: int) -> np.ndarray:
        """Read-only default plane: all-zero (coll0/penalty/a_host) or
        true-for-real-nodes (host_ok).  One allocation per shape for the
        life of the tensorizer; identity marks it default downstream."""
        key = (name, gp, Np, n_real)
        arr = self._planes.get(key)
        if arr is None:
            if name == "host_ok":
                arr = np.zeros((gp, Np), bool)
                arr[:, :n_real] = True
            elif name == "penalty":
                arr = np.zeros((gp, Np), bool)
            else:
                arr = np.zeros((gp, Np), np.float32)
            arr.flags.writeable = False
            self._planes[key] = arr
        return arr

    def pack(self, nodes: Sequence[Node], asks: Sequence[PlacementAsk],
             allocs_by_node: Optional[Dict[str, list]] = None,
             evict_e: int = 0) -> PackedBatch:
        N = len(nodes)
        Np = _pad_nodes(N)
        G = len(asks)
        Gp = _pad_pow2(max(G, 1), floor=1)

        # ---- node resources ----
        avail = np.zeros((Np, NUM_R), np.float32)
        reserved = np.zeros((Np, NUM_R), np.float32)
        used0 = np.zeros((Np, NUM_R), np.float32)
        valid = np.zeros(Np, bool)
        node_index = {}
        for i, n in enumerate(nodes):
            cap, res = node_capacity_vectors(n)
            avail[i] = cap - res
            reserved[i] = res
            valid[i] = True
            node_index[n.id] = i
        if allocs_by_node:
            for nid, allocs in allocs_by_node.items():
                i = node_index.get(nid)
                if i is None:
                    continue
                for a in allocs:
                    if not a.terminal_status():
                        used0[i] += alloc_usage_vector(a)

        # ---- interned identity columns ----
        dc_interner = Interner()
        class_interner = Interner()
        node_dc = np.zeros(Np, np.int32)
        node_class = np.zeros(Np, np.int32)
        for i, n in enumerate(nodes):
            node_dc[i] = dc_interner.intern(n.datacenter)
            node_class[i] = class_interner.intern(n.computed_class
                                                  or n.compute_class())
        NDC = _pad_pow2(max(len(dc_interner), 1), floor=1)

        # ---- collect referenced attr targets / constraint programs ----
        attr_targets: List[str] = []
        attr_target_ix: Dict[str, int] = {}

        def target_col(t: str) -> int:
            ix = attr_target_ix.get(t)
            if ix is None:
                ix = len(attr_targets)
                attr_target_ix[t] = ix
                attr_targets.append(t)
            return ix

        per_ask_vec_constraints: List[List[Tuple[int, int, str]]] = []
        per_ask_host_constraints: List[List[Constraint]] = []
        per_ask_affinities: List[List[Tuple[int, int, str, float]]] = []
        per_ask_host_affinities: List[List] = []
        constraint_labels: List[List[str]] = []

        for ask in asks:
            vec, host, labels = [], [], []
            for c in hostfeas.merged_constraints(ask.job, ask.tg):
                if c.operand in (CONSTRAINT_DISTINCT_HOSTS,
                                 CONSTRAINT_DISTINCT_PROPERTY):
                    continue  # handled via distinct_hosts_blocked
                op = _VECTOR_OPS.get(c.operand)
                if (op is not None and c.ltarget.startswith("${")
                        and not c.rtarget.startswith("${")):
                    vec.append((op, target_col(c.ltarget), c.rtarget))
                    labels.append(str(c))
                else:
                    host.append(c)
            per_ask_vec_constraints.append(vec)
            per_ask_host_constraints.append(host)
            constraint_labels.append(labels)

            affs, haffs = [], []
            merged_affs = list(ask.job.affinities) + list(ask.tg.affinities)
            for t in ask.tg.tasks:
                merged_affs.extend(t.affinities)
            for a in merged_affs:
                op = _VECTOR_OPS.get(a.operand)
                if (op is not None and a.ltarget.startswith("${")
                        and not a.rtarget.startswith("${")):
                    affs.append((op, target_col(a.ltarget), a.rtarget,
                                 float(a.weight)))
                else:
                    haffs.append(a)
            per_ask_affinities.append(affs)
            per_ask_host_affinities.append(haffs)

            for sp in list(ask.job.spreads) + list(ask.tg.spreads):
                target_col(sp.attribute)

        A = max(len(attr_targets), 1)

        # ---- rank-interned attribute matrix ----
        # value universe per column: node values + operand literals
        node_vals: List[List[Optional[str]]] = [[None] * N for _ in range(A)]
        universes: List[set] = [set() for _ in range(A)]
        for col, t in enumerate(attr_targets):
            for i, n in enumerate(nodes):
                v, ok = resolve_node_target(n, t)
                if ok:
                    node_vals[col][i] = str(v)
                    universes[col].add(str(v))
        for g, vecs in enumerate(per_ask_vec_constraints):
            for op, col, operand in vecs:
                universes[col].add(operand)
        for g, affs in enumerate(per_ask_affinities):
            for op, col, operand, w in affs:
                universes[col].add(operand)
        for ask in asks:
            for sp in list(ask.job.spreads) + list(ask.tg.spreads):
                for st in sp.spread_targets:
                    universes[attr_target_ix[sp.attribute]].add(st.value)

        rank_columns = [RankColumn(u) for u in universes]
        idt = _index_dtype(rank_columns, A)
        attr_rank = np.full((Np, A), -1, idt)
        for col in range(A):
            rc = rank_columns[col]
            for i in range(N):
                v = node_vals[col][i]
                if v is not None:
                    attr_rank[i, col] = rc.rank(v)

        # ---- constraint program arrays ----
        C = _pad_pow2(max((len(v) for v in per_ask_vec_constraints),
                          default=1), floor=4)
        c_op = np.zeros((Gp, C), idt)
        c_col = np.zeros((Gp, C), idt)
        c_rank = np.zeros((Gp, C), idt)
        for g, vecs in enumerate(per_ask_vec_constraints):
            for k, (op, col, operand) in enumerate(vecs):
                c_op[g, k] = op
                c_col[g, k] = col
                c_rank[g, k] = rank_columns[col].rank(operand)

        CA = _pad_pow2(max((len(v) for v in per_ask_affinities), default=1),
                       floor=2)
        a_op = np.zeros((Gp, CA), idt)
        a_col = np.zeros((Gp, CA), idt)
        a_rank = np.zeros((Gp, CA), idt)
        a_weight = np.zeros((Gp, CA), np.float32)
        a_weight_sum = np.zeros(Gp, np.float32)
        for g, affs in enumerate(per_ask_affinities):
            total = sum(abs(w) for _, _, _, w in affs)
            total += sum(abs(a.weight) for a in per_ask_host_affinities[g])
            a_weight_sum[g] = total
            for k, (op, col, operand, w) in enumerate(affs):
                a_op[g, k] = op
                a_col[g, k] = col
                a_rank[g, k] = rank_columns[col].rank(operand)
                a_weight[g, k] = w / total if total else 0.0

        # ---- host-evaluated affinity scores (version/regex/etc. operands) ----
        a_host = np.zeros((Gp, Np), np.float32)
        for g, haffs in enumerate(per_ask_host_affinities):
            total = a_weight_sum[g]
            for aff in haffs:
                c = Constraint(aff.ltarget, aff.rtarget, aff.operand)
                match = self._class_masked(nodes, c)
                a_host[g, :N] += match * (aff.weight / total if total else 0.0)

        # ---- host-evaluated feasibility mask ----
        host_ok = np.zeros((Gp, Np), bool)
        host_ok[:, :N] = True
        drv_masks: Dict[str, np.ndarray] = {}
        for g, ask in enumerate(asks):
            mask = np.ones(N, bool)
            # constraints not expressible on device, memoized by class
            for c in per_ask_host_constraints[g]:
                cmask = self._class_masked(nodes, c)
                mask &= cmask
            # drivers
            for drv in hostfeas.group_drivers(ask.tg):
                dmask = drv_masks.get(drv)
                if dmask is None:
                    dmask = np.fromiter(
                        (hostfeas.driver_feasible(n, drv) for n in nodes),
                        bool, N)
                    drv_masks[drv] = dmask
                mask &= dmask
            # host volumes
            if any(v.type in ("", "host") for v in ask.tg.volumes.values()):
                mask &= np.fromiter(
                    (hostfeas.host_volumes_feasible(n, ask.tg) for n in nodes),
                    bool, N)
            # distinct-hosts / distinct-property exclusions
            for nid in ask.distinct_hosts_blocked:
                i = node_index.get(nid)
                if i is not None:
                    mask[i] = False
            host_ok[g, :N] = mask

        # ---- dc eligibility ----
        dc_ok = np.zeros((Gp, NDC), bool)
        for g, ask in enumerate(asks):
            dcs = set(ask.job.datacenters)
            for dc, did in dc_interner.items():
                if dc in dcs or "*" in dcs:
                    dc_ok[g, did] = True

        # ---- asks ----
        ask_res = np.zeros((Gp, NUM_R), np.float32)
        ask_desired = np.ones(Gp, np.float32)
        distinct = np.full(Gp, -1, np.int32)
        distinct_interner = Interner()
        coll0 = np.zeros((Gp, Np), np.float32)
        penalty = np.zeros((Gp, Np), bool)
        for g, ask in enumerate(asks):
            ask_res[g] = group_resource_vector(ask.tg)
            ask_desired[g] = max(ask.tg.count, 1)
            if any(c.operand == CONSTRAINT_DISTINCT_HOSTS
                   for c in ask.job.constraints):
                # job-level: no two allocs of the job share a node, across
                # all its task groups in this batch
                distinct[g] = distinct_interner.intern("job:" + ask.job.id)
            elif any(c.operand == CONSTRAINT_DISTINCT_HOSTS
                     for c in hostfeas.merged_constraints(ask.job, ask.tg)):
                distinct[g] = distinct_interner.intern(
                    f"tg:{ask.job.id}:{ask.tg.name}")
            for nid, cnt in ask.existing_by_node.items():
                i = node_index.get(nid)
                if i is not None:
                    coll0[g, i] = cnt
            for nid in ask.penalty_nodes:
                i = node_index.get(nid)
                if i is not None:
                    penalty[g, i] = True

        # ---- spreads ----
        all_spreads = [list(ask.job.spreads) + list(ask.tg.spreads)
                       for ask in asks]
        S = _pad_pow2(max((len(s) for s in all_spreads), default=1), floor=1)
        V = _pad_pow2(max((rank_columns[attr_target_ix[sp.attribute]].n_values
                           for sps in all_spreads for sp in sps),
                          default=1), floor=2)
        sp_col = np.full((Gp, S), -1, idt)
        sp_weight = np.zeros((Gp, S), np.float32)
        sp_targeted = np.zeros((Gp, S), bool)
        sp_desired = np.full((Gp, S, V), -1.0, np.float32)
        sp_implicit = np.full((Gp, S), -1.0, np.float32)
        sp_used0 = np.zeros((Gp, S, V), np.float32)
        for g, (ask, sps) in enumerate(zip(asks, all_spreads)):
            sum_w = sum(sp.weight for sp in sps)
            total_count = max(ask.tg.count, 1)
            for s, sp in enumerate(sps):
                col = attr_target_ix[sp.attribute]
                rc = rank_columns[col]
                sp_col[g, s] = col
                sp_weight[g, s] = sp.weight / sum_w if sum_w else 0.0
                if sp.spread_targets:
                    sp_targeted[g, s] = True
                    sum_desired = 0.0
                    for st in sp.spread_targets:
                        d = (st.percent / 100.0) * total_count
                        r = rc.rank(st.value)
                        if r >= 0:
                            sp_desired[g, s, r] = d
                        sum_desired += d
                    if 0 < sum_desired < total_count:
                        sp_implicit[g, s] = total_count - sum_desired
                seed = ask.spread_seed.get(sp.attribute, {})
                for val, cnt in seed.items():
                    r = rc.rank(val)
                    if r >= 0:
                        sp_used0[g, s, r] = cnt

        # ---- devices ----
        dev_patterns: List[Tuple[str, str, str]] = []
        dev_pattern_ix: Dict[Tuple[str, str, str], int] = {}
        for ask in asks:
            for t in ask.tg.tasks:
                for d in t.resources.devices:
                    key = d.id_tuple()
                    if key not in dev_pattern_ix:
                        dev_pattern_ix[key] = len(dev_patterns)
                        dev_patterns.append(key)
        D = _pad_pow2(max(len(dev_patterns), 1), floor=1)
        dev_cap = np.zeros((Np, D), np.float32)
        dev_used0 = np.zeros((Np, D), np.float32)
        dev_ask = np.zeros((Gp, D), np.float32)
        if dev_patterns:
            from ..structs.resources import device_pattern_matches
            for i, n in enumerate(nodes):
                for dev in n.node_resources.devices:
                    healthy = sum(1 for inst in dev.instances if inst.healthy)
                    for key, dix in dev_pattern_ix.items():
                        if device_pattern_matches(key, dev.id_tuple()):
                            dev_cap[i, dix] += healthy
            if allocs_by_node:
                for nid, allocs in allocs_by_node.items():
                    i = node_index.get(nid)
                    if i is None:
                        continue
                    for a in allocs:
                        if a.terminal_status():
                            continue
                        for tr in a.allocated_resources.tasks.values():
                            for ad in tr.devices:
                                for key, dix in dev_pattern_ix.items():
                                    if device_pattern_matches(
                                            key, (ad.vendor, ad.type, ad.name)):
                                        dev_used0[i, dix] += len(ad.device_ids)
            for g, ask in enumerate(asks):
                for t in ask.tg.tasks:
                    for d in t.resources.devices:
                        dev_ask[g, dev_pattern_ix[d.id_tuple()]] += d.count

        # ---- placement schedule ----
        p_ask_list: List[int] = []
        for g, ask in enumerate(asks):
            p_ask_list.extend([g] * ask.count)
        K = _pad_pow2(max(len(p_ask_list), 1), floor=1)
        p_ask = np.zeros(K, np.int32)
        p_ask[:len(p_ask_list)] = p_ask_list

        # ---- ask priorities + evictable-alloc planes (ISSUE 7) ----
        ask_prio = np.zeros(Gp, np.int32)
        for g, ask in enumerate(asks):
            ask_prio[g] = int(getattr(ask.job, "priority", 0) or 0)
        ev_prio = ev_res = ev_ids = ev_lists = None
        if evict_e > 0:
            E = evict_e
            ev_prio = np.full((Np, E), -1, np.int16)
            ev_res = np.zeros((Np, E, NUM_R), np.float32)
            ev_ids = [[""] * E for _ in range(Np)]
            ev_lists = [[] for _ in range(Np)]
            if allocs_by_node:
                for nid, allocs in allocs_by_node.items():
                    i = node_index.get(nid)
                    if i is None:
                        continue
                    cands = _evict_candidates(allocs)
                    ev_lists[i] = cands
                    ev_prio[i], ev_res[i], ev_ids[i] = _evict_row(
                        cands, E)

        return PackedBatch(
            node_ids=[n.id for n in nodes], n_real=N,
            avail=avail, reserved=reserved, used0=used0, valid=valid,
            node_class=node_class, node_dc=node_dc, attr_rank=attr_rank,
            n_asks=G, ask_res=ask_res, ask_desired=ask_desired,
            distinct=distinct, dc_ok=dc_ok, host_ok=host_ok,
            coll0=coll0, penalty=penalty,
            c_op=c_op, c_col=c_col, c_rank=c_rank,
            a_op=a_op, a_col=a_col, a_rank=a_rank, a_weight=a_weight,
            a_host=a_host,
            sp_col=sp_col, sp_weight=sp_weight, sp_targeted=sp_targeted,
            sp_desired=sp_desired, sp_implicit=sp_implicit, sp_used0=sp_used0,
            dev_cap=dev_cap, dev_used0=dev_used0, dev_ask=dev_ask,
            p_ask=p_ask, n_place=len(p_ask_list),
            rank_columns=rank_columns, attr_targets=attr_targets,
            constraint_labels=constraint_labels,
            class_ids=dict(class_interner.items()),
            dc_ids=dict(dc_interner.items()),
            dev_pattern_ids=dict(dev_pattern_ix),
            ask_prio=ask_prio, ev_prio=ev_prio, ev_res=ev_res,
            ev_ids=ev_ids, ev_lists=ev_lists,
        )

    def delta_pack(self, template: PackedBatch,
                   node_index: Dict[str, int],
                   delta: ClusterDelta) -> Optional[NodeDelta]:
        """Incremental tensorize: turn a ClusterDelta into scatter-update
        arrays against `template` instead of a full re-pack.

        Returns None whenever the delta cannot be expressed inside the
        template's interned universe — a joined/changed node carrying an
        attribute value, datacenter or device pattern the rank tables
        have never seen, or more joins than the padded node axis holds —
        in which case the caller must fall back to a full repack (the
        interning-table invalidation path).  Computed classes are the
        one table that CAN grow in place: class ids live in an unbounded
        int column, not a sized axis.

        u_idx/u_res/u_dev are pre-aggregated per node slot so both the
        numpy `+=` apply and the device `.at[].add` see each slot once.
        """
        R = template.avail.shape[1]
        A = template.attr_rank.shape[1]
        D = template.dev_cap.shape[1]
        Np = template.avail.shape[0]
        idt = template.attr_rank.dtype
        n_real = template.n_real

        new_nodes: List[Node] = []
        slot_of: Dict[str, int] = {}

        def slot_for(nid: str) -> Optional[int]:
            s = node_index.get(nid)
            if s is not None:
                return s
            return slot_of.get(nid)

        # ---- node upserts (joins get tail slots in the padding) ----
        rows: List[Tuple[int, Node]] = []
        for n in delta.upsert_nodes:
            s = slot_for(n.id)
            if s is None:
                s = n_real + len(new_nodes)
                if s >= Np:
                    return None                 # node axis overflow
                slot_of[n.id] = s
                new_nodes.append(n)
            rows.append((s, n))

        M = len(rows) + len(delta.remove_node_ids)
        idx = np.zeros(M, np.int32)
        avail = np.zeros((M, R), np.float32)
        reserved = np.zeros((M, R), np.float32)
        valid = np.zeros(M, bool)
        node_class = np.zeros(M, np.int32)
        node_dc = np.zeros(M, np.int32)
        attr_rank = np.full((M, A), -1, idt)
        dev_cap = np.zeros((M, D), np.float32)

        for m, (s, n) in enumerate(rows):
            cap, res = node_capacity_vectors(n)
            idx[m] = s
            avail[m] = cap - res
            reserved[m] = res
            valid[m] = n.ready() if hasattr(n, "ready") else True
            did = template.dc_ids.get(n.datacenter)
            if did is None:
                return None                     # dc axis is sized
            node_dc[m] = did
            cls = n.computed_class or n.compute_class()
            cid = template.class_ids.get(cls)
            if cid is None:                     # class ids are unbounded
                cid = (max(template.class_ids.values()) + 1
                       if template.class_ids else 0)
                template.class_ids[cls] = cid
            node_class[m] = cid
            for col, t in enumerate(template.attr_targets):
                v, ok = resolve_node_target(n, t)
                if not ok:
                    continue
                r = template.rank_columns[col].rank(str(v))
                if r < 0:
                    return None                 # unseen attr value
                attr_rank[m, col] = r
            if template.dev_pattern_ids:
                from ..structs.resources import device_pattern_matches
                for dev in n.node_resources.devices:
                    healthy = sum(1 for i in dev.instances if i.healthy)
                    for key, dix in template.dev_pattern_ids.items():
                        if device_pattern_matches(key, dev.id_tuple()):
                            dev_cap[m, dix] += healthy

        # ---- removes: valid=False tombstones keeping current rows ----
        for k, nid in enumerate(delta.remove_node_ids):
            s = slot_for(nid)
            if s is None:
                return None                     # unknown node id
            m = len(rows) + k
            idx[m] = s
            avail[m] = template.avail[s]
            reserved[m] = template.reserved[s]
            valid[m] = False
            node_class[m] = template.node_class[s]
            node_dc[m] = template.node_dc[s]
            attr_rank[m] = template.attr_rank[s]
            dev_cap[m] = template.dev_cap[s]

        # ---- usage deltas (allocs placed / stopped), per-slot sums ----
        u_res_by: Dict[int, np.ndarray] = {}
        u_dev_by: Dict[int, np.ndarray] = {}
        alloc_place: List[Tuple[int, object]] = []
        alloc_stop: List[Tuple[int, object]] = []

        def charge(nid: str, alloc, sign: float) -> bool:
            s = slot_for(nid)
            if s is None:
                return False
            (alloc_place if sign > 0 else alloc_stop).append((s, alloc))
            vec = u_res_by.get(s)
            if vec is None:
                vec = u_res_by[s] = np.zeros(R, np.float32)
            vec += sign * alloc_usage_vector(alloc)
            drow = alloc_device_usage(template.dev_pattern_ids, D, alloc)
            if drow is not None:
                dv = u_dev_by.get(s)
                if dv is None:
                    dv = u_dev_by[s] = np.zeros(D, np.float32)
                dv += sign * drow
            return True

        for nid, alloc in delta.place:
            if not charge(nid, alloc, 1.0):
                return None
        for nid, alloc in delta.stop:
            if not charge(nid, alloc, -1.0):
                return None

        slots = sorted(set(u_res_by) | set(u_dev_by))
        u_idx = np.asarray(slots, np.int32)
        u_res = np.zeros((len(slots), R), np.float32)
        u_dev = np.zeros((len(slots), D), np.float32)
        for i, s in enumerate(slots):
            if s in u_res_by:
                u_res[i] = u_res_by[s]
            if s in u_dev_by:
                u_dev[i] = u_dev_by[s]

        return NodeDelta(
            idx=idx, avail=avail, reserved=reserved, valid=valid,
            node_class=node_class, node_dc=node_dc, attr_rank=attr_rank,
            dev_cap=dev_cap, u_idx=u_idx, u_res=u_res, u_dev=u_dev,
            new_nodes=new_nodes, n_real_new=n_real + len(new_nodes),
            alloc_place=alloc_place, alloc_stop=alloc_stop)

    @staticmethod
    def ask_signature(ask: PlacementAsk):
        """Hashable semantic signature of an ask's CACHEABLE row - the
        spec-derived program pieces (constraints, affinities, spreads,
        resources, drivers, volumes, datacenters).  Excludes per-eval
        state (existing allocs, penalties, blocked hosts, spread seeds),
        which is pasted onto the cached row per ask, and excludes
        ask.count, which only sizes the placement vector."""
        return (Tensorizer.job_signature(ask.job),
                Tensorizer.tg_signature(ask.tg))

    @staticmethod
    def ask_signer():
        """Per-call signature helper that memoizes the job-level half
        by object identity — a batch's asks usually share few jobs, and
        the job half is ~half the hashing cost.  Scope the returned
        closure to ONE pack/merge call (identity memoization is only
        sound while the caller holds the job objects)."""
        jmemo: dict = {}

        def sig(a):
            js = jmemo.get(id(a.job))
            if js is None:
                js = Tensorizer.job_signature(a.job)
                jmemo[id(a.job)] = js
            return (js, Tensorizer.tg_signature(a.tg))
        return sig

    @staticmethod
    def job_signature(job):
        """Job-level half of ask_signature — callers packing many asks
        of ONE job compute it once."""
        sig: list = []
        add = sig.append
        add("c")
        for c in job.constraints:
            add(c.ltarget); add(c.rtarget); add(c.operand)
        add("a")
        for a in job.affinities:
            add(a.ltarget); add(a.rtarget); add(a.operand); add(a.weight)
        add("s")
        for sp in job.spreads:
            # per-spread marker: targets are variable-arity, and two
            # adjacent spreads must not flatten ambiguously
            add("sp"); add(sp.attribute); add(sp.weight)
            for t in (sp.spread_targets or ()):
                add(t.value); add(t.percent)
        add("d"); sig.extend(job.datacenters)
        return tuple(sig)

    @staticmethod
    def tg_signature(tg):
        """Task-group half of ask_signature (flat append-driven build:
        this runs once per ask on the pack critical path)."""
        sig: list = []
        add = sig.append
        add("c")
        for c in tg.constraints:
            add(c.ltarget); add(c.rtarget); add(c.operand)
        add("a")
        for a in tg.affinities:
            add(a.ltarget); add(a.rtarget); add(a.operand); add(a.weight)
        add("s")
        for sp in tg.spreads:
            add("sp"); add(sp.attribute); add(sp.weight)
            for t in (sp.spread_targets or ()):
                add(t.value); add(t.percent)
        add(tg.count); add(tg.ephemeral_disk.size_mb)
        add(tg.ephemeral_disk.sticky)
        if tg.volumes:
            add("v")
            sig.extend(sorted(
                (k, v.type, v.source, v.read_only)
                for k, v in tg.volumes.items()))
        add("n")
        for n in tg.networks:
            add(n.mbits)
        for t in tg.tasks:
            add("t"); add(t.driver)
            r = t.resources
            add(r.cpu); add(r.memory_mb); add(r.disk_mb)
            for c in t.constraints:
                add(c.ltarget); add(c.rtarget); add(c.operand)
            add("ta")
            for a in t.affinities:
                add(a.ltarget); add(a.rtarget); add(a.operand)
                add(a.weight)
            add("td")
            for d in r.devices:
                add(d.name); add(d.count); add(str(d.constraints))
            add("tn")
            for n in r.networks:
                add(n.mbits)
        return tuple(sig)

    def repack_asks(self, nodes: Sequence[Node], asks: Sequence[PlacementAsk],
                    template: PackedBatch,
                    gp: Optional[int] = None, kp: Optional[int] = None,
                    drv_cache: Optional[Dict[str, np.ndarray]] = None,
                    row_cache: Optional[Dict] = None
                    ) -> Optional[PackedBatch]:
        """Rebuild ONLY the ask-side tensors of `template`, reusing its
        node-side arrays and rank universes untouched.

        This is the resident-solve fast path (solver/resident.py): the node
        tensors stay on device across eval batches, so per batch we only
        have to pack [G, ...] ask programs — no O(N) node walk, no O(N)
        transfer. Returns None when an ask steps outside the template's
        universe (unknown attr column, too many constraint slots, unknown
        device pattern, host volumes), in which case the caller falls back
        to a full `pack`.

        Ordered comparisons against operands the universe has never seen
        stay exact via RankColumn.insertion (a `<` against an unseen
        operand becomes `<` against its insertion rank, etc. — lexical
        order is preserved by construction).
        """
        N = len(nodes)
        Np = template.avail.shape[0]
        if N != template.n_real:
            return None
        G = len(asks)
        gp = gp or template.ask_res.shape[0]
        C = template.c_op.shape[1]
        CA = template.a_op.shape[1]
        S = template.sp_col.shape[1]
        V = template.sp_desired.shape[2]
        D = template.dev_cap.shape[1]
        NDC = template.dc_ok.shape[1]
        if G > gp:
            return None
        # distinct_property limits are enforced host-side by Solver.solve's
        # _property_fit walk, which the resident path skips — fall back
        if any(ask.property_limits for ask in asks):
            return None
        rank_columns = template.rank_columns
        attr_ix = {t: i for i, t in enumerate(template.attr_targets)}

        def ranked(col: int, operand: str, op: int
                   ) -> Optional[Tuple[int, int]]:
            """(op, rank) for an operand vs a fixed universe; exact for
            every op. None = inexpressible (can't happen today)."""
            rc = rank_columns[col]
            r = rc.rank(operand)
            if r >= 0:
                return op, r
            if op in (OP_EQ, OP_NE, OP_IS_SET, OP_NOT_SET):
                return op, -2          # never equals a real rank
            ins = rc.insertion(operand)
            if op in (OP_LT, OP_LE):   # value < unseen  ==  value <= pred
                return OP_LT, ins
            if op in (OP_GT, OP_GE):
                return OP_GE, ins
            return None

        node_index = {n.id: i for i, n in enumerate(nodes)}
        if drv_cache is None:
            drv_cache = {}
        FALLBACK = "fallback"

        def build_row(ask):
            """Spec-derived row pieces for one ask (no per-eval state).
            Returns FALLBACK when the ask is inexpressible in this
            universe (caller returns None -> full pack path)."""
            row = {
                "c_op": np.zeros(C, np.int32),
                "c_col": np.zeros(C, np.int32),
                "c_rank": np.zeros(C, np.int32),
                "a_op": np.zeros(CA, np.int32),
                "a_col": np.zeros(CA, np.int32),
                "a_rank": np.zeros(CA, np.int32),
                "a_weight": np.zeros(CA, np.float32),
                "a_host": np.zeros(N, np.float32),
                "dc_ok": np.zeros(NDC, bool),
                "sp_col": np.full(S, -1, np.int32),
                "sp_weight": np.zeros(S, np.float32),
                "sp_targeted": np.zeros(S, bool),
                "sp_desired": np.full((S, V), -1.0, np.float32),
                "sp_implicit": np.full(S, -1.0, np.float32),
                "dev_ask": np.zeros(D, np.float32),
            }
            vec, labels, host = [], [], []
            for c in hostfeas.merged_constraints(ask.job, ask.tg):
                if c.operand in (CONSTRAINT_DISTINCT_HOSTS,
                                 CONSTRAINT_DISTINCT_PROPERTY):
                    continue
                op = _VECTOR_OPS.get(c.operand)
                if (op is not None and c.ltarget.startswith("${")
                        and not c.rtarget.startswith("${")):
                    col = attr_ix.get(c.ltarget)
                    if col is None:
                        return FALLBACK
                    orank = ranked(col, c.rtarget, op)
                    if orank is None:
                        return FALLBACK
                    vec.append((orank[0], col, orank[1]))
                    labels.append(str(c))
                else:
                    host.append(c)
            if len(vec) > C:
                return FALLBACK
            for k, (op, col, r) in enumerate(vec):
                row["c_op"][k] = op
                row["c_col"][k] = col
                row["c_rank"][k] = r
            row["labels"] = labels

            mask = np.ones(N, bool)
            for c in host:
                mask &= self._class_masked(nodes, c)
            for drv in hostfeas.group_drivers(ask.tg):
                dmask = drv_cache.get(drv)
                if dmask is None:
                    dmask = np.fromiter(
                        (hostfeas.driver_feasible(n, drv) for n in nodes),
                        bool, N)
                    drv_cache[drv] = dmask
                mask &= dmask
            if any(v.type in ("", "host") for v in ask.tg.volumes.values()):
                mask &= np.fromiter(
                    (hostfeas.host_volumes_feasible(n, ask.tg)
                     for n in nodes), bool, N)
            row["host_ok"] = mask
            row["host_ok_all"] = bool(mask.all())

            affs, haffs = [], []
            merged_affs = list(ask.job.affinities) + list(ask.tg.affinities)
            for t in ask.tg.tasks:
                merged_affs.extend(t.affinities)
            for a in merged_affs:
                op = _VECTOR_OPS.get(a.operand)
                if (op is not None and a.ltarget.startswith("${")
                        and not a.rtarget.startswith("${")):
                    col = attr_ix.get(a.ltarget)
                    if col is None:
                        return FALLBACK
                    affs.append((col, a.rtarget, op, float(a.weight)))
                else:
                    haffs.append(a)
            if len(affs) > CA:
                return FALLBACK
            total = (sum(abs(w) for _, _, _, w in affs)
                     + sum(abs(a.weight) for a in haffs))
            for k, (col, operand, op, w) in enumerate(affs):
                orank = ranked(col, operand, op)
                if orank is None:
                    return FALLBACK
                row["a_op"][k] = orank[0]
                row["a_col"][k] = col
                row["a_rank"][k] = orank[1]
                row["a_weight"][k] = w / total if total else 0.0
            for aff in haffs:
                c = Constraint(aff.ltarget, aff.rtarget, aff.operand)
                match = self._class_masked(nodes, c)
                row["a_host"] += match * (aff.weight / total if total
                                          else 0.0)
            row["a_host_zero"] = not haffs or not total

            dcs = set(ask.job.datacenters)
            for dc, did in template.dc_ids.items():
                if dc in dcs or "*" in dcs:
                    row["dc_ok"][did] = True

            row["ask_res"] = group_resource_vector(ask.tg)
            row["ask_desired"] = float(max(ask.tg.count, 1))
            if any(c.operand == CONSTRAINT_DISTINCT_HOSTS
                   for c in ask.job.constraints):
                row["distinct_kind"] = "job"
            elif any(c.operand == CONSTRAINT_DISTINCT_HOSTS
                     for c in hostfeas.merged_constraints(ask.job, ask.tg)):
                row["distinct_kind"] = "tg"
            else:
                row["distinct_kind"] = None

            sps = list(ask.job.spreads) + list(ask.tg.spreads)
            if len(sps) > S:
                return FALLBACK
            sum_w = sum(sp.weight for sp in sps)
            total_count = max(ask.tg.count, 1)
            for si, sp in enumerate(sps):
                col = attr_ix.get(sp.attribute)
                if col is None:
                    return FALLBACK
                rc = rank_columns[col]
                if rc.n_values > V:
                    return FALLBACK
                row["sp_col"][si] = col
                row["sp_weight"][si] = sp.weight / sum_w if sum_w else 0.0
                if sp.spread_targets:
                    row["sp_targeted"][si] = True
                    sum_desired = 0.0
                    for st in sp.spread_targets:
                        d = (st.percent / 100.0) * total_count
                        r = rc.rank(st.value)
                        if r >= 0:
                            row["sp_desired"][si, r] = d
                        sum_desired += d
                    if 0 < sum_desired < total_count:
                        row["sp_implicit"][si] = total_count - sum_desired

            for t in ask.tg.tasks:
                for d in t.resources.devices:
                    dix = template.dev_pattern_ids.get(d.id_tuple())
                    if dix is None:
                        return FALLBACK
                    row["dev_ask"][dix] += d.count
            return row

        # one cached spec row per distinct ask shape; per-eval state is
        # pasted over the copy in the assembly loop below, so cached
        # rows are never mutated
        rows = []
        signer = self.ask_signer()
        for ask in asks:
            sig = signer(ask) if row_cache is not None else None
            row = row_cache.get(sig) if sig is not None else None
            if row is None:
                row = build_row(ask)
                if row is FALLBACK:
                    return None
                if sig is not None:
                    row_cache[sig] = row
            rows.append(row)

        # program rows reuse the TEMPLATE's (possibly int16-minimized)
        # dtypes so repacked batches hit the same compiled kernel
        idt = template.attr_rank.dtype
        c_op = np.zeros((gp, C), idt)
        c_col = np.zeros((gp, C), idt)
        c_rank = np.zeros((gp, C), idt)
        a_op = np.zeros((gp, CA), idt)
        a_col = np.zeros((gp, CA), idt)
        a_rank = np.zeros((gp, CA), idt)
        a_weight = np.zeros((gp, CA), np.float32)
        # The [gp, Np] ask-side planes are DEFAULT for nearly every
        # fresh-job batch (all-true host masks, no penalties, no
        # existing allocs, no host affinities): hand out shared
        # read-only singletons instead of allocating+filling ~MBs per
        # batch — resident._stack_args recognizes them by identity and
        # substitutes device-resident constants, so the default case
        # never touches an O(G*N) byte on the host either.
        need_a_host = any(not row["a_host_zero"] for row in rows)
        need_host_ok = (any(not row["host_ok_all"] for row in rows)
                        or any(a.distinct_hosts_blocked for a in asks))
        need_coll0 = any(a.existing_by_node for a in asks)
        need_penalty = any(a.penalty_nodes for a in asks)
        a_host = (np.zeros((gp, Np), np.float32) if need_a_host
                  else self._shared_plane("a_host", gp, Np, N))
        if need_host_ok:
            host_ok = np.zeros((gp, Np), bool)
            host_ok[:, :N] = True   # padding rows keep the universe
        else:
            host_ok = self._shared_plane("host_ok", gp, Np, N)
        dc_ok = np.zeros((gp, NDC), bool)
        ask_res = np.zeros((gp, NUM_R), np.float32)
        ask_desired = np.ones(gp, np.float32)
        distinct = np.full(gp, -1, np.int32)
        distinct_interner = Interner()
        coll0 = (np.zeros((gp, Np), np.float32) if need_coll0
                 else self._shared_plane("coll0", gp, Np, N))
        penalty = (np.zeros((gp, Np), bool) if need_penalty
                   else self._shared_plane("penalty", gp, Np, N))
        sp_col = np.full((gp, S), -1, idt)
        sp_weight = np.zeros((gp, S), np.float32)
        sp_targeted = np.zeros((gp, S), bool)
        sp_desired = np.full((gp, S, V), -1.0, np.float32)
        sp_implicit = np.full((gp, S), -1.0, np.float32)
        sp_used0 = np.zeros((gp, S, V), np.float32)
        dev_ask = np.zeros((gp, D), np.float32)
        constraint_labels: List[List[str]] = []
        p_ask_list: List[int] = []

        for g, (ask, row) in enumerate(zip(asks, rows)):
            c_op[g], c_col[g], c_rank[g] = \
                row["c_op"], row["c_col"], row["c_rank"]
            constraint_labels.append(row["labels"])
            if need_host_ok:
                host_ok[g, :N] = row["host_ok"]
                for nid in ask.distinct_hosts_blocked:
                    i = node_index.get(nid)
                    if i is not None:
                        host_ok[g, i] = False
            a_op[g], a_col[g], a_rank[g] = \
                row["a_op"], row["a_col"], row["a_rank"]
            a_weight[g] = row["a_weight"]
            if need_a_host:
                a_host[g, :N] = row["a_host"]
            dc_ok[g] = row["dc_ok"]
            ask_res[g] = row["ask_res"]
            ask_desired[g] = row["ask_desired"]
            if row["distinct_kind"] == "job":
                distinct[g] = distinct_interner.intern("job:" + ask.job.id)
            elif row["distinct_kind"] == "tg":
                distinct[g] = distinct_interner.intern(
                    f"tg:{ask.job.id}:{ask.tg.name}")
            if need_coll0:
                for nid, cnt in ask.existing_by_node.items():
                    i = node_index.get(nid)
                    if i is not None:
                        coll0[g, i] = cnt
            if need_penalty:
                for nid in ask.penalty_nodes:
                    i = node_index.get(nid)
                    if i is not None:
                        penalty[g, i] = True
            sp_col[g], sp_weight[g] = row["sp_col"], row["sp_weight"]
            sp_targeted[g] = row["sp_targeted"]
            sp_desired[g] = row["sp_desired"]
            sp_implicit[g] = row["sp_implicit"]
            if ask.spread_seed:
                for si, sp in enumerate(list(ask.job.spreads)
                                        + list(ask.tg.spreads)):
                    seed = ask.spread_seed.get(sp.attribute, {})
                    if seed:
                        rc = rank_columns[sp_col[g, si]]
                        for val, cnt in seed.items():
                            r = rc.rank(val)
                            if r >= 0:
                                sp_used0[g, si, r] = cnt
            dev_ask[g] = row["dev_ask"]
            p_ask_list.extend([g] * ask.count)

        kp = kp or _pad_pow2(max(len(p_ask_list), 1), floor=1)
        if len(p_ask_list) > kp:
            return None
        p_ask = np.zeros(kp, np.int32)
        p_ask[:len(p_ask_list)] = p_ask_list

        ask_prio = np.zeros(gp, np.int32)
        for g, ask in enumerate(asks):
            ask_prio[g] = int(getattr(ask.job, "priority", 0) or 0)

        return PackedBatch(
            node_ids=template.node_ids, n_real=template.n_real,
            avail=template.avail, reserved=template.reserved,
            used0=template.used0, valid=template.valid,
            node_class=template.node_class, node_dc=template.node_dc,
            attr_rank=template.attr_rank,
            n_asks=G, ask_res=ask_res, ask_desired=ask_desired,
            distinct=distinct, dc_ok=dc_ok, host_ok=host_ok,
            coll0=coll0, penalty=penalty,
            c_op=c_op, c_col=c_col, c_rank=c_rank,
            a_op=a_op, a_col=a_col, a_rank=a_rank, a_weight=a_weight,
            a_host=a_host,
            sp_col=sp_col, sp_weight=sp_weight, sp_targeted=sp_targeted,
            sp_desired=sp_desired, sp_implicit=sp_implicit,
            sp_used0=sp_used0,
            dev_cap=template.dev_cap, dev_used0=template.dev_used0,
            dev_ask=dev_ask,
            p_ask=p_ask, n_place=len(p_ask_list),
            rank_columns=rank_columns, attr_targets=template.attr_targets,
            constraint_labels=constraint_labels,
            class_ids=template.class_ids, dc_ids=template.dc_ids,
            dev_pattern_ids=template.dev_pattern_ids,
            ask_prio=ask_prio,
            # node-side eviction planes ride along from the template
            # (delta-maintained there; ev_lists stay template-owned)
            ev_prio=template.ev_prio, ev_res=template.ev_res,
            ev_ids=template.ev_ids,
        )

    def _class_masked(self, nodes: Sequence[Node], c: Constraint) -> np.ndarray:
        """Evaluate a host-op constraint per node, memoized by computed class
        unless the constraint escapes class optimization (unique.* targets)."""
        escapes = ("${node.unique." in c.ltarget or "${attr.unique." in c.ltarget
                   or "${meta.unique." in c.ltarget
                   or "unique." in c.rtarget)
        out = np.zeros(len(nodes), bool)
        if escapes:
            for i, n in enumerate(nodes):
                out[i] = hostfeas.node_meets_constraint(n, c)
            return out
        key_base = (c.ltarget, c.rtarget, c.operand)
        for i, n in enumerate(nodes):
            ck = (n.computed_class, key_base)
            v = self._class_memo.get(ck)
            if v is None:
                v = hostfeas.node_meets_constraint(n, c)
                self._class_memo[ck] = v
            out[i] = v
        return out
