"""ctypes loader for the native host solve kernel.

Builds `native/host_solve.cc` into a shared object on first use (g++,
no external deps) and exposes `native_solve_kernel`, a drop-in for
`host.host_solve_kernel` returning the same SolveResult.  The numpy
twin stays the reference implementation and the fallback — the native
path exists because an interactive eval's wave arithmetic costs tens
of microseconds in C++ vs ~1ms of ufunc overhead in numpy (the
latency-mode p50 budget is sub-millisecond, BASELINE config 1).

tests/test_native_solver.py differential-tests this against the numpy
twin (bitwise-identical placements) across every feature: constraints,
affinities, targeted/even spreads, distinct_hosts, devices, penalties,
collocation counts, seeds, stack_commit.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from .kernel import (MAX_WAVES, MERGED_GP_MAX, TOP_K, _MERGED_W_CAP,
                     _WIDE_W_CAP, SolveResult)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "native", "host_solve.cc")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _lib_path() -> str:
    """Artifact path keyed on a CONTENT hash of the source: a fresh
    checkout (mtimes all equal — git does not preserve them) or a
    committed/foreign .so can never shadow the current source the way
    an mtime comparison could; editing host_solve.cc changes the hash
    and the stale artifact is simply never looked at again."""
    import hashlib
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, "native", f"_host_solve.{digest}.so")


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    try:
        lib_path = _lib_path()
        for attempt in range(2):
            if not os.path.exists(lib_path):
                tmp = lib_path + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, lib_path)  # atomic vs concurrent builders
            try:
                lib = ctypes.CDLL(lib_path)
            except OSError:
                if attempt == 0:
                    # right hash, unloadable object (foreign arch,
                    # truncated write): rebuild once in place
                    os.unlink(lib_path)
                    continue
                raise
            lib.nomad_host_solve.restype = ctypes.c_int
            return lib
        return None
    except (OSError, subprocess.CalledProcessError):
        _build_failed = True
        return None


def available() -> bool:
    return _get_lib() is not None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                _lib = _build()
    return _lib


def _c(a, dtype):
    a = np.ascontiguousarray(a, dtype=dtype)
    return a, a.ctypes.data_as(ctypes.c_void_p)


class PreparedTemplate:
    """Node-side arrays marshaled once per solver (the template is
    fixed for the solver's lifetime), plus reusable output and usage
    buffers.  The per-eval cost of the native path is then one C call
    + a couple of small copies — no per-call ctypes marshaling."""

    def __init__(self, template):
        f32, i32, u8 = np.float32, np.int32, np.uint8
        t = template
        self.avail = np.ascontiguousarray(t.avail, f32)
        self.reserved = np.ascontiguousarray(t.reserved, f32)
        self.valid = np.ascontiguousarray(t.valid, u8)
        self.node_dc = np.ascontiguousarray(t.node_dc, i32)
        self.attr_rank = np.ascontiguousarray(t.attr_rank, i32)
        self.dev_cap = np.ascontiguousarray(t.dev_cap, f32)
        self.Np, self.R = self.avail.shape
        self.A = self.attr_rank.shape[1]
        self.D = self.dev_cap.shape[1]
        # carried usage: the native stream path mutates these in place
        self.used = np.ascontiguousarray(t.used0, f32).copy()
        self.dev_used = np.ascontiguousarray(t.dev_used0, f32).copy()

    def reset_usage(self, used0, dev_used0):
        np.copyto(self.used, np.asarray(used0, np.float32))
        np.copyto(self.dev_used, np.asarray(dev_used0, np.float32))


class PreparedRun:
    """One PackedBatch's fully-marshaled native call.  Build once, run
    many times (seed varies per run); the carried usage lives in the
    PreparedTemplate's buffers and updates in place."""

    def __init__(self, tp: PreparedTemplate, pb, has_spread: bool,
                 hint: int, max_waves: int, stack_commit: bool):
        lib = _get_lib()
        assert lib is not None
        f32, i32, u8 = np.float32, np.int32, np.uint8
        self.tp = tp
        Gp = pb.ask_res.shape[0]
        C = pb.c_op.shape[1]
        CA = pb.a_op.shape[1]
        S = pb.sp_col.shape[1]
        V = pb.sp_desired.shape[2]
        K = pb.p_ask.shape[0]
        NDC = pb.dc_ok.shape[1]
        R, D, Np, A = tp.R, tp.D, tp.Np, tp.A
        assert R <= 8
        w_cap = _MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP
        self.K, self.TOP_K, self.Gp, self.C, self.Np, self.R = \
            K, TOP_K, Gp, C, Np, R

        self.sp_used0 = np.ascontiguousarray(pb.sp_used0, f32)
        self.sp_used = self.sp_used0.copy()
        self.out_idx = np.zeros((K, TOP_K), i32)
        self.out_ok = np.zeros((K, TOP_K), u8)
        self.out_score = np.zeros((K, TOP_K), f32)
        self.out_nfeas = np.zeros(K, i32)
        self.out_nexh = np.zeros(K, i32)
        self.out_dimexh = np.zeros((K, R), i32)
        self.out_unfin = np.zeros(K, u8)
        self.out_waves = np.zeros(1, i32)

        def P(a, dtype):
            a = np.ascontiguousarray(a, dtype)
            self._keep.append(a)
            return ctypes.c_void_p(a.ctypes.data)

        self._keep = []
        args = [
            P(tp.avail, f32), P(tp.reserved, f32),
            P(tp.used, f32), P(tp.valid, u8), P(tp.node_dc, i32),
            P(tp.attr_rank, i32),
            P(pb.ask_res, f32), P(pb.ask_desired, f32),
            P(pb.distinct, i32), P(pb.dc_ok, u8), P(pb.host_ok, u8),
            P(pb.coll0, f32), P(pb.penalty, u8),
            P(pb.c_op, i32), P(pb.c_col, i32), P(pb.c_rank, i32),
            P(pb.a_op, i32), P(pb.a_col, i32), P(pb.a_rank, i32),
            P(pb.a_weight, f32), P(pb.a_host, f32),
            P(pb.sp_col, i32), P(pb.sp_weight, f32),
            P(pb.sp_targeted, u8), P(pb.sp_desired, f32),
            P(pb.sp_implicit, f32), P(self.sp_used, f32),
            P(tp.dev_cap, f32), P(tp.dev_used, f32),
            P(pb.dev_ask, f32), P(pb.p_ask, i32),
            ctypes.c_int(int(pb.n_place)),
            ctypes.c_int(Np), ctypes.c_int(Gp), ctypes.c_int(A),
            ctypes.c_int(C), ctypes.c_int(CA), ctypes.c_int(S),
            ctypes.c_int(V), ctypes.c_int(R), ctypes.c_int(D),
            ctypes.c_int(K), ctypes.c_int(NDC),
            ctypes.c_int(0),                      # seed slot
            ctypes.c_int(1 if has_spread else 0),
            ctypes.c_int(int(hint)),
            ctypes.c_int(int(max_waves or MAX_WAVES)),
            ctypes.c_int(1 if stack_commit else 0),
            ctypes.c_int(w_cap),
            P(self.out_idx, i32), P(self.out_ok, u8),
            P(self.out_score, f32), P(self.out_nfeas, i32),
            P(self.out_nexh, i32), P(self.out_dimexh, i32),
            P(self.out_unfin, u8), P(self.out_waves, i32),
            ctypes.c_void_p(0), ctypes.c_void_p(0),
            # static-program cache: filled on the first run, read-only
            # after (ask programs + template are fixed for this batch)
            ctypes.c_int(0),
            P(np.zeros((Gp, Np), u8), u8),            # feas
            P(np.zeros((Gp, Np), f32), f32),          # aff
            P(np.zeros((Gp, C), i32), i32),           # consf
            P(np.zeros((S, Gp, Np), i32), i32),       # sp_vnode
            P(np.zeros((S, Gp, Np), f32), f32),       # sp_des
        ]
        self._args = args
        self._seed_ix = 43
        self._static_ix = len(args) - 6
        self._lib = lib

    def run(self, seed: int) -> None:
        """Execute; results land in the out_* buffers (overwritten per
        run) and the carried usage updates in place."""
        np.copyto(self.sp_used, self.sp_used0)
        self._args[self._seed_ix] = ctypes.c_int(int(seed))
        rc = self._lib.nomad_host_solve(*self._args)
        assert rc == 0
        if not self._args[self._static_ix].value:
            self._args[self._static_ix] = ctypes.c_int(1)


def native_solve_kernel(avail, reserved, used0, valid, node_dc, attr_rank,
                        ask_res, ask_desired, distinct, dc_ok, host_ok,
                        coll0, penalty,
                        c_op, c_col, c_rank, a_op, a_col, a_rank, a_weight,
                        a_host, sp_col, sp_weight, sp_targeted, sp_desired,
                        sp_implicit, sp_used0, dev_cap, dev_used0, dev_ask,
                        p_ask, n_place, seed=0, *, has_spread=True,
                        group_count_hint=0, max_waves=0,
                        stack_commit=False,
                        static_cache=None) -> SolveResult:
    # static_cache: accepted for drop-in compatibility with the numpy
    # twin; the native kernel recomputes its static program per call
    # (tens of microseconds at latency-path sizes)
    lib = _get_lib()
    assert lib is not None, "native host solve unavailable"
    f32, i32, u8 = np.float32, np.int32, np.uint8
    Np, R = np.asarray(avail).shape
    Gp = np.asarray(ask_res).shape[0]
    A = np.asarray(attr_rank).shape[1]
    C = np.asarray(c_op).shape[1]
    CA = np.asarray(a_op).shape[1]
    S = np.asarray(sp_col).shape[1]
    V = np.asarray(sp_desired).shape[2]
    D = np.asarray(dev_cap).shape[1]
    K = np.asarray(p_ask).shape[0]
    NDC = np.asarray(dc_ok).shape[1]
    assert R <= 8, "native kernel caps R at 8"
    w_cap = _MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP

    used, p_used = _c(np.array(used0, f32), f32)       # in/out copies
    dev_used, p_devu = _c(np.array(dev_used0, f32), f32)
    sp_used, p_spu = _c(np.array(sp_used0, f32), f32)
    ins = [_c(avail, f32), _c(reserved, f32)]
    a_avail, p_avail = ins[0]
    a_res, p_res = ins[1]
    a_valid, p_valid = _c(valid, u8)
    a_ndc, p_ndc = _c(node_dc, i32)
    a_ar, p_ar = _c(attr_rank, i32)
    a_askres, p_askres = _c(ask_res, f32)
    a_askdes, p_askdes = _c(ask_desired, f32)
    a_dist, p_dist = _c(distinct, i32)
    a_dcok, p_dcok = _c(dc_ok, u8)
    a_hostok, p_hostok = _c(host_ok, u8)
    a_coll0, p_coll0 = _c(coll0, f32)
    a_pen, p_pen = _c(penalty, u8)
    a_cop, p_cop = _c(c_op, i32)
    a_ccol, p_ccol = _c(c_col, i32)
    a_crank, p_crank = _c(c_rank, i32)
    a_aop, p_aop = _c(a_op, i32)
    a_acol, p_acol = _c(a_col, i32)
    a_arank, p_arank = _c(a_rank, i32)
    a_aw, p_aw = _c(a_weight, f32)
    a_ah, p_ah = _c(a_host, f32)
    a_spcol, p_spcol = _c(sp_col, i32)
    a_spw, p_spw = _c(sp_weight, f32)
    a_spt, p_spt = _c(sp_targeted, u8)
    a_spd, p_spd = _c(sp_desired, f32)
    a_spi, p_spi = _c(sp_implicit, f32)
    a_devcap, p_devcap = _c(dev_cap, f32)
    a_devask, p_devask = _c(dev_ask, f32)
    a_pask, p_pask = _c(p_ask, i32)

    out_idx = np.zeros((K, TOP_K), i32)
    out_ok = np.zeros((K, TOP_K), u8)
    out_score = np.zeros((K, TOP_K), f32)
    out_nfeas = np.zeros(K, i32)
    out_nexh = np.zeros(K, i32)
    out_dimexh = np.zeros((K, R), i32)
    out_unfin = np.zeros(K, u8)
    out_waves = np.zeros(1, i32)
    out_feas = np.zeros((Gp, Np), u8)
    out_consf = np.zeros((Gp, C), i32)

    def vp(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    rc = lib.nomad_host_solve(
        p_avail, p_res, p_used, p_valid, p_ndc, p_ar,
        p_askres, p_askdes, p_dist, p_dcok, p_hostok, p_coll0, p_pen,
        p_cop, p_ccol, p_crank, p_aop, p_acol, p_arank, p_aw, p_ah,
        p_spcol, p_spw, p_spt, p_spd, p_spi, p_spu,
        p_devcap, p_devu, p_devask, p_pask,
        ctypes.c_int(int(n_place)),
        ctypes.c_int(Np), ctypes.c_int(Gp), ctypes.c_int(A),
        ctypes.c_int(C), ctypes.c_int(CA), ctypes.c_int(S),
        ctypes.c_int(V), ctypes.c_int(R), ctypes.c_int(D),
        ctypes.c_int(K), ctypes.c_int(NDC), ctypes.c_int(int(seed)),
        ctypes.c_int(1 if has_spread else 0),
        ctypes.c_int(int(group_count_hint)),
        ctypes.c_int(int(max_waves or MAX_WAVES)),
        ctypes.c_int(1 if stack_commit else 0), ctypes.c_int(w_cap),
        vp(out_idx), vp(out_ok), vp(out_score), vp(out_nfeas),
        vp(out_nexh), vp(out_dimexh), vp(out_unfin), vp(out_waves),
        vp(out_feas), vp(out_consf),
        ctypes.c_int(0), ctypes.c_void_p(0), ctypes.c_void_p(0),
        ctypes.c_void_p(0), ctypes.c_void_p(0), ctypes.c_void_p(0))
    assert rc == 0
    return SolveResult(
        choice=out_idx, choice_ok=out_ok.astype(bool),
        score=out_score, n_feasible=out_nfeas, n_exhausted=out_nexh,
        dim_exhausted=out_dimexh, feas=out_feas.astype(bool),
        cons_filtered=out_consf, used_final=used,
        dev_used_final=dev_used, n_waves=out_waves[0],
        unfinished=out_unfin.astype(bool))
