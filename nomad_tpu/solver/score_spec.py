"""ONE scoring spec, N verified backends (ROADMAP item 5).

The exact scorer used to live in five hand-replicated float-order-exact
copies (host numpy twin, jit wave kernel, shortlist `_sl_eval`, pallas
tile kernel, native C++), held identical only by nomadlint's
backend-vs-backend drift fingerprints.  This module flips the
relationship: it is the single declarative source of truth for every
scoring term — its exact float-op sequence, constants, dtype/cast
contract, and combine order — and the backends split into two classes:

  * DRIVEN — the host twin (`host.host_solve_kernel.group_scores`) and
    the jit wave scorer (`kernel.solve_kernel.group_scores`) call
    `evaluate_wave` below; they contain NO scoring arithmetic of their
    own.  Backend-specific structure (numpy vs traced jnp, spread
    gather shape, seed-bin control flow) lives in the `NumpyOps` /
    `JaxOps` shims; every float op and constant lives in ONE term
    function here.  Driving both from the same term functions is what
    makes them bit-identical by construction.
  * HAND, SPEC-VERIFIED — the shortlist VMEM twin, the pallas fused
    tile kernel, and the native C++ engine stay hand-written for
    performance; nomadlint SCORE6xx v3 compiles this spec into
    per-term reference fingerprints and statically proves each of them
    implements the spec (SCORE601 = drift vs SPEC, SCORE604 = term
    coverage).

Adding a scoring term = adding ONE term function + ONE `TERMS` entry
here (plus tests).  The driven backends pick it up via the term loop;
SCORE604 then fails until every hand backend named in the entry's
`backends` tuple carries a matching fingerprint.  The reserved
`learned` slot (GDP-style placer head, PAPERS.md) is wired this way:
a precomputed [Gp, Np] plane appended as one more scorer, flowing to
the driven backends only.  The `region` term (ISSUE 13 cross-region
scheduling) follows the same template: a precomputed [Gp, Np]
region-affinity plane — built host-side from each node's region and
the job's home region — appended as one more scorer, driven backends
only.

FINGERPRINT CONTRACT: the assignment-target names inside the term
functions (`free_cpu`, `raw`, `binpack`, `anti`, ...) are the
canonical names nomadlint groups float ops under — they must match the
`groups` tuples declared in `TERMS`, and the bodies must keep the op
structure the hand backends replicate.  `TERMS` itself is a pure
literal: nomadlint parses it with `ast.literal_eval` and never imports
this module.
"""
from __future__ import annotations

import numpy as np

from .tensorize import R_CPU, R_MEM

#: bump on ANY term/combine change; recorded in BENCH_DETAIL by
#: bench.lint_summary and snapshotted by the golden fingerprint test
SPEC_VERSION = "3.1"

#: masked / sentinel score (shared by every backend; the kernel
#: re-exports it)
NEG_INF = -1e30

#: seeded-mode score quantum: seed != 0 bins scores into SCORE_BIN
#: steps and jitters within the bin (see kernel.solve_kernel for why)
SCORE_BIN = 0.05


# ============================================================ ops shims
class NumpyOps:
    """Backend shim for the numpy host twin.  Reproduces host.py's
    pre-refactor structure exactly: constants wrapped `np.float32`,
    gather-based spread `cur`, masked min/max pinned finite (identical
    results to the unpinned kernel form, but RuntimeWarning-clean), and
    python-level seed branching."""

    f32 = np.float32

    @staticmethod
    def asf32(x):
        return np.asarray(x, np.float32)

    where = staticmethod(np.where)
    maximum = staticmethod(np.maximum)
    clip = staticmethod(np.clip)
    floor = staticmethod(np.floor)

    @staticmethod
    def ones_bool(shape):
        return np.ones(shape, bool)

    @staticmethod
    def counts_cast(x):
        # host pins the scorer count to f32 explicitly
        return x.astype(np.float32)

    @staticmethod
    def seed_select(seed, exact, binned):
        # host branches at python level; seed is a host int here
        return binned if seed != 0 else exact

    @staticmethod
    def spread_cur(used_vec, v, V):
        f32 = np.float32
        return np.where(v >= 0, np.take_along_axis(
            used_vec, np.clip(v, 0, V - 1), axis=1), f32(0.0))

    @staticmethod
    def present_minmax(present, used_vec):
        f32 = np.float32
        any_present = present.any(axis=1)[:, None]
        minc = np.min(np.where(present, used_vec, np.inf),
                      axis=1)[:, None].astype(f32)
        maxc = np.max(np.where(present, used_vec, -np.inf),
                      axis=1)[:, None].astype(f32)
        # rows with NO present value carry minc=inf/maxc=-inf; their
        # `even` term is masked to 0 by any_present downstream, but
        # inf/inf through the divides raises RuntimeWarnings across the
        # whole suite — pin the masked rows to finite values first
        # (identical results, clean exact twin)
        minc = np.where(any_present, minc, f32(0.0))
        maxc = np.where(any_present, maxc, f32(0.0))
        return any_present, minc, maxc

    @staticmethod
    def spread_sum(S, fn, shape):
        # sequential accumulation — bitwise equal to the kernel's
        # vmap+sum (tests/test_shortlist.py pinned this equivalence for
        # the shortlist twin long before the spec existed)
        acc = np.zeros(shape, np.float32)
        for s in range(S):
            acc = acc + fn(s)
        return acc


class JaxOps:
    """Backend shim for the jit wave scorer.  Reproduces kernel.py's
    pre-refactor trace exactly: bare (weakly-typed) python float
    constants, select-sum spread `cur` for small vocabularies,
    unpinned masked min/max, vmap'd spread reduction, and traced
    `jnp.where` seed branching."""

    def __init__(self, select_sum_max_v: int = 16):
        import jax
        import jax.numpy as jnp
        self._jax = jax
        self._jnp = jnp
        self.select_sum_max_v = select_sum_max_v

    @staticmethod
    def f32(c):
        # jnp ops promote python floats weakly — bare constants keep
        # the pre-refactor trace byte-identical
        return c

    @staticmethod
    def asf32(x):
        return x

    def where(self, c, a, b):
        return self._jnp.where(c, a, b)

    def maximum(self, a, b):
        return self._jnp.maximum(a, b)

    def clip(self, x, lo, hi):
        return self._jnp.clip(x, lo, hi)

    def floor(self, x):
        return self._jnp.floor(x)

    def ones_bool(self, shape):
        return self._jnp.ones(shape, bool)

    @staticmethod
    def counts_cast(x):
        return x

    def seed_select(self, seed, exact, binned):
        jnp = self._jnp
        return jnp.where(jnp.int32(seed) == 0, exact, binned)

    def spread_cur(self, used_vec, v, V):
        jnp = self._jnp
        if V <= self.select_sum_max_v:
            # gather-free select-sum over the (small) value vocabulary:
            # a per-element gather of [Gp, Np] lowers to a near-scalar
            # loop on TPU
            cur = jnp.zeros_like(v, jnp.float32)
            for val in range(V):
                cur = cur + jnp.where(v == val,
                                      used_vec[:, val][:, None], 0.0)
            return cur
        return jnp.where(v >= 0, jnp.take_along_axis(
            used_vec, jnp.maximum(v, 0), axis=1), 0.0)

    def present_minmax(self, present, used_vec):
        jnp = self._jnp
        any_present = present.any(axis=1)[:, None]
        minc = jnp.min(jnp.where(present, used_vec, jnp.inf),
                       axis=1)[:, None]
        maxc = jnp.max(jnp.where(present, used_vec, -jnp.inf),
                       axis=1)[:, None]
        return any_present, minc, maxc

    def spread_sum(self, S, fn, shape):
        jnp = self._jnp
        return self._jax.vmap(fn)(jnp.arange(S)).sum(axis=0)


# ======================================================= term functions
# Every float op and constant of the exact scorer lives in the bodies
# below; nomadlint fingerprints them per assignment-target group and
# verifies the hand backends against them.  Keep target names in sync
# with the `groups` tuples in TERMS.

def term_feasibility(ops, ctx):
    """Hard placement masks (funcs.go checkers): resource fit per
    dimension, device fit, static feasibility minus per-wave blocking.
    Masks only — no float scoring ops, so this term carries no
    fingerprint groups."""
    after = ctx["used"][None, :, :] + ctx["ask_res"][:, None, :]
    fit_dims = after <= ctx["avail"][None, :, :]
    fit = fit_dims.all(axis=-1)
    if ctx["has_devices"]:
        dev_fit = (ctx["dev_used"][None, :, :] + ctx["dev_ask"][:, None, :]
                   <= ctx["dev_cap"][None, :, :]).all(axis=-1)
    else:
        dev_fit = ops.ones_bool(ctx["shape"])
    feas_b = ctx["feas"] & ~ctx["blocked"]
    placeable = feas_b & fit & dev_fit
    return after, fit_dims, fit, dev_fit, feas_b, placeable


def term_binpack(ops, ctx):
    """Bin-pack (funcs.go:155 ScoreFit, normalized rank.go:441): the
    10**free exponential pressure on cpu+mem, clipped to [0, 18] and
    normalized; 0 where either denominator is empty."""
    f32 = ops.f32
    free_cpu = f32(1.0) - ctx["util_cpu"] / ops.maximum(ctx["denom_cpu"],
                                                        f32(1.0))
    free_mem = f32(1.0) - ctx["util_mem"] / ops.maximum(ctx["denom_mem"],
                                                        f32(1.0))
    raw = f32(20.0) - (f32(10.0) ** free_cpu + f32(10.0) ** free_mem)
    binpack = ops.where(ctx["ok_denoms"],
                        ops.clip(raw, f32(0.0), f32(18.0)) / f32(18.0),
                        f32(0.0))
    return binpack


def term_anti(ops, ctx):
    """Job anti-affinity (rank.go:462): -(collisions+1)/desired on
    nodes already carrying a sibling, appended only when colliding."""
    f32 = ops.f32
    coll = ctx["coll"]
    anti = ops.where(coll > 0,
                     -(coll + f32(1.0)) / ctx["ask_desired"][:, None],
                     f32(0.0))
    anti_counts = coll > 0
    return anti, anti_counts


def term_penalty(ops, ctx):
    """Node penalty (rank.go:532): a flat -1 scorer on penalized
    nodes.  Wave-invariant — evaluated once per solve via
    `static_terms`, not per wave."""
    f32 = ops.f32
    pen_score = ops.where(ctx["penalty"], f32(-1.0), f32(0.0))
    return pen_score


def term_spread(ops, ctx, s):
    """Spread scorer for ONE spread constraint `s` (spread.go):
    targeted boost toward declared desired counts, or the even-spread
    boost against the min/max occupancy band.  The per-backend gather
    shape (take_along_axis vs select-sum) and min/max pinning live in
    the ops shim; every float op is here."""
    f32 = ops.f32
    col = ctx["sp_col"][:, s]
    has = col >= 0
    v = ctx["vnode"][s]
    has_v = v >= 0
    used_vec = ctx["sp_used"][:, s]
    cur = ops.spread_cur(used_vec, v, ctx["V"])
    # targeted scoring (desired counts, +1 for this placement)
    desired = ctx["des"][s]
    boost = ((desired - (cur + f32(1.0)))
             / ops.maximum(desired, f32(1e-9))
             ) * ops.asf32(ctx["sp_weight"][:, s])[:, None]
    targeted = ops.where(~has_v, f32(-1.0),
                         ops.where(desired <= 0, f32(-1.0), boost))
    # even-spread scoring (spread.go evenSpreadScoreBoost)
    present = used_vec > 0
    any_present, minc, maxc = ops.present_minmax(present, used_vec)
    delta_boost = (minc - cur) / ops.maximum(minc, f32(1e-9))
    even = ops.where(cur != minc, delta_boost,
                     ops.where(minc == maxc, f32(-1.0),
                               (maxc - minc) / ops.maximum(minc,
                                                           f32(1e-9))))
    even = ops.where(~has_v, f32(-1.0), even)
    even = ops.where(any_present, even, f32(0.0))
    contrib = ops.where(ctx["sp_targeted"][:, s][:, None], targeted,
                        even)
    return ops.where(has[:, None], contrib, f32(0.0))


def term_learned(ops, ctx):
    """Reserved learned-head slot (GDP-style placer, PAPERS.md): the
    [Gp, Np] score plane arrives PRECOMPUTED in ctx["learned"] (model
    inference happens outside the solve); the spec appends it as one
    more scorer via `combine_learned`.  When no plane is supplied the
    term is statically absent — the combine path and therefore the
    traced program are byte-identical to a spec without it."""
    learned = ctx["learned"]
    return learned


def term_region(ops, ctx):
    """Cross-region placement affinity (ISSUE 13): the [Gp, Np] plane
    arrives PRECOMPUTED in ctx["region_bias"] — built host-side from
    each node's region id and the asking job's home region (home
    region > sibling > remote, scaled by the spillover policy) — and
    the spec appends it as one more scorer via `combine_region`.  When
    no plane is supplied the term is statically absent: the combine
    path and the traced program are byte-identical to a spec without
    it (appending an all-zeros plane would still flip -0.0 to +0.0)."""
    region_bias = ctx["region_bias"]
    return region_bias


def combine(ops, ctx, parts):
    """Append-then-average normalization (rank.go:667): the mean over
    the appended scorers, seed-binned (kernel.solve_kernel documents
    why) and tie-break-jittered.  This body is the canonical `total` /
    `n_scorers` fingerprint every backend must match."""
    f32 = ops.f32
    n_scorers = ops.counts_cast(f32(1.0) + parts["anti_counts"]
                                + parts["pen_counts"]
                                + parts["aff_counts"]
                                + parts["spread_counts"])
    total = (parts["binpack"] + parts["anti"] + parts["pen_score"]
             + parts["aff_score"] + parts["spread_total"]) / n_scorers
    total = ops.seed_select(ctx["seed"], total,
                            ops.floor(total / f32(SCORE_BIN))
                            * f32(SCORE_BIN))
    total = total + ctx["jitter"]
    return total


def combine_learned(ops, ctx, parts):
    """`combine` with the learned plane appended as one more scorer
    (same append semantics as anti/pen/aff/spread: counted when
    nonzero).  A SEPARATE function so the canonical `total` fingerprint
    in `combine` stays exactly what the learned-free hand backends
    implement; nomadlint groups this body under the `learned` term."""
    f32 = ops.f32
    learned = parts["learned"]
    n_scorers = ops.counts_cast(f32(1.0) + parts["anti_counts"]
                                + parts["pen_counts"]
                                + parts["aff_counts"]
                                + parts["spread_counts"]
                                + (learned != 0.0))
    total = (parts["binpack"] + parts["anti"] + parts["pen_score"]
             + parts["aff_score"] + parts["spread_total"]
             + learned) / n_scorers
    total = ops.seed_select(ctx["seed"], total,
                            ops.floor(total / f32(SCORE_BIN))
                            * f32(SCORE_BIN))
    total = total + ctx["jitter"]
    return total


def combine_region(ops, ctx, parts):
    """`combine` with the region-affinity plane appended as one more
    scorer (same append semantics as anti/pen/aff/spread: counted when
    nonzero).  A SEPARATE function, like `combine_learned`, so the
    canonical `total` fingerprint in `combine` stays exactly what the
    region-free hand backends implement; nomadlint groups this body
    under the `region` term."""
    f32 = ops.f32
    region_bias = parts["region"]
    n_scorers = ops.counts_cast(f32(1.0) + parts["anti_counts"]
                                + parts["pen_counts"]
                                + parts["aff_counts"]
                                + parts["spread_counts"]
                                + (region_bias != 0.0))
    total = (parts["binpack"] + parts["anti"] + parts["pen_score"]
             + parts["aff_score"] + parts["spread_total"]
             + region_bias) / n_scorers
    total = ops.seed_select(ctx["seed"], total,
                            ops.floor(total / f32(SCORE_BIN))
                            * f32(SCORE_BIN))
    total = total + ctx["jitter"]
    return total


def combine_learned_region(ops, ctx, parts):
    """Both optional planes active at once (a learned head on a
    federated mesh): learned AND region each append as one more
    scorer.  Grouped under the `region` term like `combine_region`."""
    f32 = ops.f32
    learned = parts["learned"]
    region_bias = parts["region"]
    n_scorers = ops.counts_cast(f32(1.0) + parts["anti_counts"]
                                + parts["pen_counts"]
                                + parts["aff_counts"]
                                + parts["spread_counts"]
                                + (learned != 0.0)
                                + (region_bias != 0.0))
    total = (parts["binpack"] + parts["anti"] + parts["pen_score"]
             + parts["aff_score"] + parts["spread_total"]
             + learned + region_bias) / n_scorers
    total = ops.seed_select(ctx["seed"], total,
                            ops.floor(total / f32(SCORE_BIN))
                            * f32(SCORE_BIN))
    total = total + ctx["jitter"]
    return total


# ====================================================== term registry
#: The declarative spec registry — ONE entry per scoring term.  Pure
#: literal by contract: nomadlint reads it with `ast.literal_eval`
#: (never importing this module) to learn each term's fingerprint
#: groups (group name -> the assignment-target aliases backends may
#: use), which function carries the reference float ops, which
#: backends must implement it, and whether its groups compare as a
#: constant SET only (loop structure genuinely differs per backend).
#:
#: Adding a term: write its term function above, list it here, run the
#: suite — SCORE604 names every hand backend that still misses it, and
#: the golden-fingerprint test surfaces the new reference prints as a
#: reviewed diff.  Backends: "host" and "kernel" are spec-DRIVEN (the
#: term loop picks the entry up automatically); "shortlist", "pallas"
#: and "native" are hand-written and spec-verified.
TERMS = (
    {"name": "feasibility", "fn": "term_feasibility",
     "groups": {}, "const_set": False,
     "backends": ("host", "kernel", "shortlist", "pallas", "native"),
     "doc": "hard placement masks (no float ops; not fingerprinted)"},
    {"name": "binpack", "fn": "term_binpack",
     "groups": {"free": ("free_cpu", "free_mem"),
                "binpack": ("raw", "binpack")},
     "const_set": False,
     "backends": ("host", "kernel", "shortlist", "pallas", "native"),
     "doc": "exponential cpu+mem bin-packing pressure"},
    {"name": "anti", "fn": "term_anti",
     "groups": {"anti": ("anti",)}, "const_set": False,
     "backends": ("host", "kernel", "shortlist", "pallas", "native"),
     "doc": "job anti-affinity collision penalty"},
    {"name": "pen", "fn": "term_penalty",
     "groups": {"pen": ("pen", "pen_score", "pen_sc")},
     "const_set": False,
     "backends": ("host", "kernel", "shortlist", "pallas", "native"),
     "doc": "flat node penalty scorer"},
    {"name": "spread", "fn": "term_spread",
     "groups": {"spread": ("cur", "boost", "targeted", "delta_boost",
                           "even", "contrib", "spread_total",
                           "sp_total", "minc", "maxc", "desired")},
     "const_set": True,
     "backends": ("host", "kernel", "shortlist", "pallas", "native"),
     "doc": "targeted + even spread boosts (const-set compare)"},
    {"name": "learned", "fn": "term_learned",
     "groups": {"learned": ("learned",)}, "const_set": False,
     "backends": ("host", "kernel"),
     "doc": "reserved learned-head plane (driven backends only)"},
    {"name": "region", "fn": "term_region",
     "groups": {"region": ("region_bias",)}, "const_set": False,
     "backends": ("host", "kernel"),
     "doc": "cross-region placement affinity plane (ISSUE 13; "
            "driven backends only)"},
    {"name": "combine", "fn": "combine",
     "groups": {"n_scorers": ("n_scorers",), "total": ("total",)},
     "const_set": False,
     "backends": ("host", "kernel", "shortlist", "pallas", "native"),
     "doc": "append-then-average normalization + binning + jitter"},
)


def term_names():
    """Ordered term names (bench/BENCH_DETAIL provenance)."""
    return tuple(t["name"] for t in TERMS)


# ============================================================= drivers
def static_terms(ops, penalty):
    """Wave-invariant spec terms, evaluated once per solve:
    (pen_score, pen_counts)."""
    pen_score = term_penalty(ops, {"penalty": penalty})
    return pen_score, penalty


def rescore_binpack(ops, after, avail, reserved):
    """Bin-pack for an arbitrary post-delta usage plane `after` —
    shared by the wave scorer and the in-kernel preemption pass (which
    rescores nodes at `used + ask - freed`)."""
    denom_cpu = avail[None, :, R_CPU]
    denom_mem = avail[None, :, R_MEM]
    util_cpu = after[:, :, R_CPU] + reserved[None, :, R_CPU]
    util_mem = after[:, :, R_MEM] + reserved[None, :, R_MEM]
    ok_denoms = (denom_cpu > 0) & (denom_mem > 0)
    return term_binpack(ops, {"util_cpu": util_cpu, "util_mem": util_mem,
                              "denom_cpu": denom_cpu,
                              "denom_mem": denom_mem,
                              "ok_denoms": ok_denoms})


def evaluate_wave(ops, ctx):
    """The term-loop evaluation the driven backends call once per wave:
    masks, every registered term, combine.  Returns the exact
    `group_scores` contract: (score, placeable, feas_b, fit, fit_dims,
    dev_fit).

    ctx keys — wave state: used, dev_used, coll, sp_used, blocked;
    static planes: avail, reserved, ask_res, ask_desired, dev_cap,
    dev_ask, feas; hoisted terms: pen_score, pen_counts, aff_score,
    jitter; spread statics: sp_col, sp_weight, sp_targeted, vnode, des,
    S, V; shape=(Gp, Np), seed, has_devices, has_spread, and the
    optional `learned` / `region_bias` planes (None = term statically
    absent)."""
    f32 = ops.f32
    after, fit_dims, fit, dev_fit, feas_b, placeable = \
        term_feasibility(ops, ctx)

    binpack = rescore_binpack(ops, after, ctx["avail"], ctx["reserved"])
    anti, anti_counts = term_anti(ops, ctx)

    if ctx["has_spread"]:
        spread_total = ops.spread_sum(
            ctx["S"], lambda s: term_spread(ops, ctx, s), ctx["shape"])
        spread_counts = spread_total != 0.0
    else:
        spread_total = f32(0.0)
        spread_counts = False

    aff_score = ctx["aff_score"]
    parts = {"binpack": binpack, "anti": anti,
             "anti_counts": anti_counts,
             "pen_score": ctx["pen_score"],
             "pen_counts": ctx["pen_counts"],
             "aff_score": aff_score, "aff_counts": aff_score != 0.0,
             "spread_total": spread_total,
             "spread_counts": spread_counts}
    # static branches: with no learned/region plane the combine path
    # (and the traced program / float behavior) is byte-identical to a
    # spec without the term — appending an all-zeros plane would still
    # flip -0.0 sums to +0.0
    has_learned = ctx.get("learned") is not None
    has_region = ctx.get("region_bias") is not None
    if has_learned:
        parts["learned"] = term_learned(ops, ctx)
    if has_region:
        parts["region"] = term_region(ops, ctx)
    if has_learned and has_region:
        total = combine_learned_region(ops, ctx, parts)
    elif has_learned:
        total = combine_learned(ops, ctx, parts)
    elif has_region:
        total = combine_region(ops, ctx, parts)
    else:
        total = combine(ops, ctx, parts)
    score = ops.where(placeable, total, f32(NEG_INF))
    return score, placeable, feas_b, fit, fit_dims, dev_fit
