"""The jitted placement solve.

Replaces the reference's per-placement iterator chain
(scheduler/stack.go:107 Select -> feasible.go checks -> rank.go scoring ->
select.go limit/max) with dense tensor math over the full node axis:

  static feasibility mask  [G, N]   (constraints, dc, host-evaluated ops)
  wave loop: batched [G, N] scoring -> per-group top-k -> parallel commit

Wave semantics (the TPU recast of in-plan visibility,
scheduler/context.go:120 ProposedAllocs): instead of committing one
placement per step, every wave

  1. scores all (group, node) pairs against current usage in one batched
     pass — the MXU-friendly shape,
  2. ranks each group's remaining placements and assigns the r-th one to
     the group's r-th best node (top-k), so same-group placements fan out
     across nodes exactly as the reference's job anti-affinity pressure
     (rank.go:462) makes them do one step at a time,
  3. commits every assignment that survives cross-group conflict checks:
     cumulative capacity on shared nodes (segment-sum by node),
     first-per-(node, distinct-group) for distinct_hosts, and a spread
     quota per (group, value) so targeted/even spread cannot be
     overfilled inside a single wave (spread.go semantics),
  4. placements that lose a conflict simply retry next wave against
     refreshed usage.

Every committed placement's capacity is checked against the usage its
wave started from plus all earlier same-wave commits on the node, so no
node ever oversubscribes.  A batch of K placements converges in
O(K / WAVE_K) waves instead of K serial scan steps; each wave is one
fused XLA program over [G, N] tensors.

Scores follow the reference's conditional-append-then-average
normalization (rank.go:667).  Where the reference subsamples nodes
(limit = max(2, log2 N), scheduler/stack.go:80-87), this solve scores
every node — strictly better placements at far higher eval throughput.
"""
from __future__ import annotations

import functools
import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import score_spec as _score_spec
from .tensorize import (OP_EQ, OP_GE, OP_GT, OP_IS_SET, OP_LE, OP_LT, OP_NE,
                        OP_NONE, OP_NOT_SET, R_CPU, R_MEM)

TOP_K = 4
WAVE_K = 32       # min per-group wave width; scales up with batch size
MAX_WAVES = 12    # static wave budget per solve (see scan note below)
NEG_INF = _score_spec.NEG_INF
# victim eligibility gate: ask priority must exceed the victim's by at
# least this (scheduler/preemption.PRIORITY_DELTA — duplicated here so
# the device module stays import-light; pinned equal by a test)
EV_PRIORITY_DELTA = 10
# test hook: force the sort-based conflict path at small K (read at
# trace time; tests clear jit caches after flipping it)
_FORCE_SORT_CONFLICTS = False
# node count from which top-k extraction switches to approx_max_k
_APPROX_MIN_NP = 4096
# value-vocabulary size up to which spread lookups unroll as select-sums
# (gather-free); larger vocabularies fall back to take_along_axis
_SELECT_SUM_MAX_V = 16
# backend shim handing the spec-driven wave scorer its jnp ops (see
# score_spec: this kernel is a DRIVEN backend — no scoring arithmetic
# of its own)
_JAX_OPS = _score_spec.JaxOps(select_sum_max_v=_SELECT_SUM_MAX_V)
# group-count at or below which a batch is treated as "merged few-group"
# (throughput-mode ask dedup): the wave-width cap widens since top-k
# over so few rows is cheap. Shared by resident._group_count_hint and
# merged-mode callers sizing gp.
MERGED_GP_MAX = 16
# per-group candidate-window caps (wave width W <= cap): merged
# few-group batches carry thousands of placements per group, and a
# wider window is more same-wave commit capacity — i.e. fewer waves —
# at near-zero extraction cost with so few rows (read at trace time)
_MERGED_W_CAP = 1024
_WIDE_W_CAP = 256


# ---------------------------------------------------------------- delta
# Scatter-apply kernels for the device-resident cluster state
# (resident.apply_delta): the HBM arrays update in place — the old
# buffer is DONATED where the backend supports it (TPU/GPU), so a delta
# wave moves only the scattered rows, never a full [Np, ...] copy.
# CPU ignores donation; building the jit without it avoids the
# "donated buffers unused" warning storm in host-only runs.
_DELTA_JITS: dict = {}
_DELTA_JITS_LOCK = threading.Lock()


def _delta_scatter(op: str):
    """Lazily-built jit (backend probing at import would pay backend
    init for every package import, including pure-host test runs)."""
    fn = _DELTA_JITS.get(op)
    if fn is None:
        with _DELTA_JITS_LOCK:     # double-checked cache fill
            fn = _DELTA_JITS.get(op)
            if fn is None:
                try:
                    donate = jax.default_backend() != "cpu"
                except Exception:  # backend init can fail in sandboxes
                    donate = False
                if op == "set":
                    def f(arr, idx, rows):
                        return arr.at[idx].set(rows)
                else:
                    def f(arr, idx, rows):
                        return arr.at[idx].add(rows)
                fn = jax.jit(f, donate_argnums=(0,) if donate else ())
                _DELTA_JITS[op] = fn
    return fn


def delta_scatter_set(arr, idx, rows):
    return _delta_scatter("set")(arr, idx, rows)


def delta_scatter_add(arr, idx, rows):
    return _delta_scatter("add")(arr, idx, rows)


def _op_eval(vals: jnp.ndarray, op: jnp.ndarray, rank: jnp.ndarray
             ) -> jnp.ndarray:
    """Evaluate vectorizable constraint ops.

    vals: [N, C] node value ranks (-1 missing); op/rank: [C].
    Semantics mirror scheduler/feasible.go:671 checkConstraint — note `!=`
    passes when the attribute is missing.
    """
    found = vals >= 0
    eq = found & (vals == rank[None, :])
    res = jnp.ones_like(found)
    res = jnp.where(op[None, :] == OP_EQ, eq, res)
    res = jnp.where(op[None, :] == OP_NE, ~eq, res)
    res = jnp.where(op[None, :] == OP_LT, found & (vals < rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_LE, found & (vals <= rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_GT, found & (vals > rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_GE, found & (vals >= rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_IS_SET, found, res)
    res = jnp.where(op[None, :] == OP_NOT_SET, ~found, res)
    return res


class SolveResult(NamedTuple):
    choice: jnp.ndarray        # [K, TOP_K] node indices, best first
    choice_ok: jnp.ndarray     # [K, TOP_K] bool (feasible + fits)
    score: jnp.ndarray         # [K, TOP_K] final normalized scores
    n_feasible: jnp.ndarray    # [K] feasible node count at commit wave
    n_exhausted: jnp.ndarray   # [K] feasible but resource-exhausted
    dim_exhausted: jnp.ndarray  # [K, R] counts per exhausted dimension
    feas: jnp.ndarray          # [G, N] static feasibility mask
    cons_filtered: jnp.ndarray  # [G, C] nodes filtered per constraint slot
    used_final: jnp.ndarray    # [N, R] resource usage after all commits
    dev_used_final: jnp.ndarray  # [N, D] device usage after all commits
    n_waves: jnp.ndarray       # [] wave-loop iterations that did work
    unfinished: jnp.ndarray    # [K] active but undecided after MAX_WAVES
    #  (rare; absorbed by the blocked-eval retry path)
    n_rescore: jnp.ndarray = None  # [] waves that ran the full-N pass
    #  (shortlist-resident waves make up n_waves - n_rescore; None when
    #   a kernel predates / sidesteps the shortlist path)
    evict: jnp.ndarray = None  # [K, E] bool victim-slot mask for
    #  placements committed by the in-kernel preemption pass (ISSUE 7);
    #  slots index the node's ev planes. None when has_preempt is off.
    commit_wave: jnp.ndarray = None  # [K] i32 wave each placement
    #  committed on (-1 = failed/unfinished). Only populated with
    #  has_preempt: evictions make usage non-monotone, so the host
    #  fixup must replay commits in WAVE order — an ask-order replay
    #  can transiently exceed avail on a node whose eviction (by a
    #  later-p placement) the kernel sequenced earlier.


# ------------------------------------------------------- shortlist
# Contention waves (waves >= 2) only ever re-rank nodes that already
# scored on top: the carried per-group top-C shortlist lets them gather
# live usage for <= C nodes and re-rank in VMEM instead of re-reading
# every [Gp, Np] plane from HBM.  Exactness is trigger-guarded — see
# solve_kernel's wave loop.
_SHORTLIST_TILE = 128          # auto width rounds up to this


class _SLState(NamedTuple):
    """Wave-loop carry for the shortlist-resident contention path.

    Per-entry planes are [Gp, C] gathered once per full-N wave; `vn` /
    `de` are the hoisted spread lookups restricted to shortlist nodes.
    `cut_s`/`cut_i` hold the era cutoff key (the C-th best (score,
    node) at the building wave): every non-shortlisted node's key was
    strictly worse and — under the validity triggers — stays frozen,
    so a re-ranked window whose TK-th key still dominates the cutoff
    provably equals the full-N window.  `comp` marks groups whose
    entire placeable set fit inside C (outsiders are permanently
    NEG_INF: every trigger is bypassed).  `win_*`/`nfeas`/`nexh`/
    `ndim`/`gany` are the NEXT wave's pre-computed window and
    explainability counters; `ok` gates using them."""
    idx: jnp.ndarray           # [Gp, C] node ids, ascending
    feas: jnp.ndarray          # [Gp, C] static feasibility
    pen: jnp.ndarray           # [Gp, C] penalty flag
    aff: jnp.ndarray           # [Gp, C] affinity score
    vn: jnp.ndarray            # [S, Gp, C] spread value ranks
    de: jnp.ndarray            # [S, Gp, C] spread desired counts
    coll: jnp.ndarray          # [Gp, C] own-group collocation counts
    cut_s: jnp.ndarray         # [Gp] era cutoff score
    cut_i: jnp.ndarray         # [Gp] era cutoff node id
    comp: jnp.ndarray          # [Gp] shortlist holds ALL placeable
    nfeas: jnp.ndarray         # [Gp] n_feasible for the next wave
    nexh: jnp.ndarray          # [Gp] n_exhausted for the next wave
    ndim: jnp.ndarray          # [Gp, R] dim_exhausted for the next wave
    win_s: jnp.ndarray         # [Gp, TKl] next wave's window scores
    win_i: jnp.ndarray         # [Gp, TKl] next wave's window nodes
    #  (window/table node ids are GLOBAL — in mesh mode they feed the
    #   cross-shard candidate-key merge directly)
    tb_s: jnp.ndarray          # [Gp, V+1, TW] next wave's value tables
    tb_i: jnp.ndarray          # ([Gp, 1, 1] dummies when tables off)
    gany: jnp.ndarray          # [Gp] next wave's grp_any
    ok: jnp.ndarray            # [] next wave may skip the full pass


def resolve_shortlist_c(Np: int, TK: int, requested: int = 0) -> int:
    """Static shortlist width C for a solve (0 = path disabled).

    `requested` 0 auto-sizes: the candidate window TK rounded UP to the
    next _SHORTLIST_TILE multiple (so there is always slack above the
    window for entries that drain), clamped to the node axis.  -1
    disables the path.  Explicit values are validated — never silently
    clamped: they must cover TOP_K fall-through slots, lie within the
    node axis, satisfy lane alignment (multiple of 8), and be at least
    the candidate window TK (narrower could not even fill one wave's
    window).  NOMAD_TPU_SHORTLIST_C feeds this via ResidentSolver."""
    if requested == -1:
        return 0
    if requested in (0, None):
        return min(Np, (TK // _SHORTLIST_TILE + 1) * _SHORTLIST_TILE)
    if not isinstance(requested, int) or requested < TOP_K:
        raise ValueError(
            f"shortlist_c={requested!r} invalid: must be -1 (off), 0 "
            f"(auto) or an int >= TOP_K ({TOP_K})")
    if requested % 8:
        raise ValueError(
            f"shortlist_c={requested} invalid: must be a multiple of 8 "
            "(vector lane alignment)")
    if requested > Np:
        raise ValueError(
            f"shortlist_c={requested} exceeds the padded node axis "
            f"({Np}); pick <= Np — it will not be clamped silently")
    if requested < TK:
        raise ValueError(
            f"shortlist_c={requested} is narrower than the candidate "
            f"window TK={TK} for this problem shape; the shortlist "
            "could not fill a single wave's window. Pass a value >= TK "
            "or 0 for auto sizing")
    return requested


@functools.partial(jax.jit,
                   static_argnames=("has_spread", "group_count_hint",
                                    "max_waves", "wave_mode",
                                    "has_distinct", "has_devices",
                                    "stack_commit", "pallas_mode",
                                    "shortlist_c", "mesh_axis",
                                    "mesh_shards", "has_preempt",
                                    "mesh_hosts", "mesh_nt", "tile_np",
                                    "mesh_regions", "lane_axis"))
def solve_kernel(avail, reserved, used0, valid, node_dc, attr_rank,
                 ask_res, ask_desired, distinct, dc_ok, host_ok, coll0,
                 penalty,
                 c_op, c_col, c_rank, a_op, a_col, a_rank, a_weight, a_host,
                 sp_col, sp_weight, sp_targeted, sp_desired, sp_implicit,
                 sp_used0, dev_cap, dev_used0, dev_ask, p_ask, n_place,
                 seed=0, *, has_spread=True,
                 group_count_hint=0, max_waves=0,
                 wave_mode="scan", has_distinct=True,
                 has_devices=True, stack_commit=False,
                 pallas_mode="off", shortlist_c=0,
                 mesh_axis=None, mesh_shards=0,
                 has_preempt=False, ev_res=None, ev_prio=None,
                 ask_prio=None, mesh_hosts=0, mesh_nt=0, tile_np=0,
                 node_gid=None, owner_map=None, slot_map=None,
                 learned=None, mesh_regions=0,
                 region_bias=None, lane_axis=None) -> SolveResult:
    # has_distinct / has_devices: trace-time guarantees from the packer
    # that NO ask in this batch uses distinct_hosts / requests devices —
    # the per-wave conflict sort, blocking scatter, and device-fit
    # arithmetic those features need then drop out of the program
    # entirely (the common fresh-service-job case)
    max_waves = max_waves or MAX_WAVES
    Np = avail.shape[0]
    Gp = ask_res.shape[0]
    S = sp_col.shape[1]
    R = avail.shape[1]
    K = p_ask.shape[0]
    # ---------- mesh-resident sharding (ISSUE 5 / ISSUE 8) ----------
    # mesh_axis names the shard_map axis the NODE dimension is split
    # over: every [.., Np, ..] arg here is that shard's LOCAL plane.
    # Scoring, extraction, and the shortlist stay shard-local; only
    # per-group candidate KEYS (score, global node id) and K-sized
    # commit/counter vectors cross the interconnect — never a
    # [Gp, Np] plane.
    #
    # ISSUE 8 generalizes the flat "nodes" axis to a TWO-TIER
    # ("hosts", "chips") hierarchy: the candidate-key exchange first
    # all-gathers within a host over fast ICI and lex-merges the host's
    # shards into ONE host window, and only the merged host-winner keys
    # cross the (10-40x slower) DCN between hosts — chip-sliced so each
    # host window traverses DCN once, not once per chip.  Commit psums
    # tier the same way (ICI reduce, then host-level reduce).  Both
    # tiers merge in the exact (score desc, global id asc) lex order of
    # the single-device tournament, so placements stay bit-identical.
    # ISSUE 13 adds a THIRD tier: ("regions", "hosts", "chips").  Each
    # region runs the two-tier exchange above locally (the named-axis
    # collectives over host/chip axes stay within the fixed region
    # coordinate), and only the region-merged top-K window — sliced
    # across the region's shards — crosses the (WAN-modeled) region
    # axis per wave.  mesh_hosts then counts hosts PER REGION.  The
    # final lex merge of the union is tier-structure-independent and
    # the commit psums are integer, so placements and every counter
    # stay bit-identical to the flat and two-tier meshes.
    in_mesh = mesh_axis is not None
    two_tier = in_mesh and isinstance(mesh_axis, tuple)
    three_tier = two_tier and len(mesh_axis) == 3
    if in_mesh:
        assert mesh_shards >= 1, \
            "mesh_axis requires the static mesh_shards axis size"
        if three_tier:
            assert mesh_regions >= 1 \
                and mesh_shards % mesh_regions == 0, (
                    "three-tier mesh_axis needs (region_axis, "
                    "host_axis, chip_axis) and mesh_regions dividing "
                    f"mesh_shards; got {mesh_axis!r} "
                    f"regions={mesh_regions} shards={mesh_shards}")
            region_ax, host_ax, chip_ax = mesh_axis
            SPR = mesh_shards // mesh_regions
            assert mesh_hosts >= 1 and SPR % mesh_hosts == 0, (
                "mesh_hosts (hosts PER REGION) must divide the "
                f"per-region shard count; got hosts={mesh_hosts} "
                f"shards_per_region={SPR}")
            CPH = SPR // mesh_hosts
            my_lin = (lax.axis_index(region_ax).astype(jnp.int32)
                      * jnp.int32(SPR)
                      + lax.axis_index(host_ax).astype(jnp.int32)
                      * jnp.int32(CPH)
                      + lax.axis_index(chip_ax).astype(jnp.int32))
        elif two_tier:
            assert len(mesh_axis) == 2 and mesh_hosts >= 1 \
                and mesh_shards % mesh_hosts == 0, (
                    "two-tier mesh_axis needs (host_axis, chip_axis) "
                    "and mesh_hosts dividing mesh_shards; got "
                    f"{mesh_axis!r} hosts={mesh_hosts} "
                    f"shards={mesh_shards}")
            region_ax = None
            host_ax, chip_ax = mesh_axis
            SPR = mesh_shards
            CPH = mesh_shards // mesh_hosts
            my_lin = (lax.axis_index(host_ax).astype(jnp.int32)
                      * jnp.int32(CPH)
                      + lax.axis_index(chip_ax).astype(jnp.int32))
        else:
            region_ax = host_ax = chip_ax = None
            SPR = CPH = mesh_shards
            my_lin = lax.axis_index(mesh_axis).astype(jnp.int32)
    # elastic tile layout (ISSUE 8): tile_np > 0 means the node axis is
    # owned in TILES of tile_np slots routed by an owner remap table
    # instead of contiguous axis-index blocks — a reshard moves one
    # tile's planes, never the world.  node_gid maps this shard's local
    # slots to stable GLOBAL node ids; owner_map/slot_map (replicated,
    # with a trailing -1 sentinel row) invert a global id to its owning
    # shard and local tile position.
    elastic = in_mesh and tile_np > 0
    if elastic:
        assert node_gid is not None and owner_map is not None \
            and slot_map is not None, \
            "tile_np > 0 needs node_gid/owner_map/slot_map tables"
    # global node axis: the elastic layout carries per-shard slack
    # (dead slots), so the true global width is passed in via mesh_nt —
    # it must match the host twin's padded axis or the TK clamp (and
    # with it the candidate window) would diverge from the twin
    NT = ((mesh_nt or Np * mesh_shards) if in_mesh else Np)
    # shard offset (contiguous layout): NamedSharding splits the node
    # axis into contiguous axis-index-ordered blocks, so global id =
    # axis_index * Np + local
    off = (my_lin * jnp.int32(Np) if (in_mesh and not elastic)
           else None)
    if in_mesh:
        if elastic:
            g_of_local = node_gid.astype(jnp.int32)       # [Np]
            n_tiles_s = owner_map.shape[0] - 1            # sentinel row

            def _l2g(idx):
                return g_of_local[idx]

            def _g2l(gid):
                """global id -> (owned-here, scatter-safe local slot
                (non-owned pinned to the dropped Np slot), clipped
                gather-safe slot).  Dead-slot gids land on the
                sentinel owner row (-1) and are never owned."""
                t = jnp.clip(gid // jnp.int32(tile_np), 0, n_tiles_s)
                own = (owner_map[t] == my_lin) & (gid >= 0)
                loc_ = (slot_map[t] * jnp.int32(tile_np)
                        + gid % jnp.int32(tile_np))
                loc = jnp.where(own, loc_, Np)
                return own, loc, jnp.clip(loc, 0, Np - 1)
        else:
            g_of_local = off + jnp.arange(Np, dtype=jnp.int32)

            def _l2g(idx):
                return idx + off

            def _g2l(gid):
                loc_ = gid - off
                own = (loc_ >= 0) & (loc_ < Np)
                loc = jnp.where(own, loc_, Np)
                return own, loc, jnp.clip(loc, 0, Np - 1)

    def _sliced_psum(x, n_slices, my_slice, over_ax, inner_axes):
        """Reduce x over `over_ax` shipping only a 1/n_slices chunk
        per shard: x is replicated across the `inner_axes` group (whose
        linear index is `my_slice`), so the reduce-scatter degrades to
        a slice (dynamic_slice keeps it collective-free on the inner
        tiers); the reduced chunks reassemble by tiled all-gathers,
        innermost axis first (matching the slice index order)."""
        shp = x.shape
        n = 1
        for d in shp:
            n *= d
        np_ = -(-n // n_slices) * n_slices
        flat = jnp.ravel(x)
        if np_ != n:
            flat = jnp.pad(flat, (0, np_ - n))
        wl = np_ // n_slices
        sl = lax.dynamic_slice_in_dim(flat, my_slice * wl, wl, axis=0)
        sl = lax.psum(sl, over_ax)
        for ax in inner_axes:
            sl = lax.all_gather(sl, ax, axis=0, tiled=True)
        return sl[:n].reshape(shp)

    def _psum_mesh(x):
        """Tiered reduction: ICI (chips) first, then a CHIP-SLICED
        host tier — each chip ships only its 1/CPH slice of the
        host-reduced vector across DCN (reduce-scatter over ICI, host
        psum on the slice, reassembled over ICI), so a commit vector
        crosses DCN once per host, not once per chip — then (three
        tiers) a region tier sliced the same way across ALL of the
        region's shards, so one commit vector crosses the WAN per
        region, not once per host.  Integer operands everywhere, so
        the tiering is order-exact."""
        if not two_tier:
            return lax.psum(x, mesh_axis)
        x = lax.psum(x, chip_ax)
        if mesh_hosts > 1:
            if CPH == 1:
                x = lax.psum(x, host_ax)
            else:
                x = _sliced_psum(x, CPH, lax.axis_index(chip_ax),
                                 host_ax, (chip_ax,))
        if not three_tier or mesh_regions == 1:
            return x
        if SPR == 1:
            return lax.psum(x, region_ax)
        wli = (lax.axis_index(host_ax) * jnp.int32(CPH)
               + lax.axis_index(chip_ax))
        return _sliced_psum(x, SPR, wli, region_ax,
                            (chip_ax, host_ax))

    def _tier_merge(s, i, k, over_ax, n_peers, n_slices, my_slice,
                    inner_axes):
        """One hierarchy level of the candidate-key exchange: merge
        the n_peers windows along `over_ax` into the top-k of their
        union, each transfer SLICED 1/n_slices across the inner-tier
        group (linear index `my_slice`) so one window crosses the
        slow tier once, not once per inner shard.  Power-of-two peer
        counts run a recursive-doubling tournament (every peer ships
        log2(n) windows); other counts fall back to one sliced
        all-gather + single merge (order-free — the lex sort restores
        the tournament order)."""
        ax_last = s.ndim - 1
        pad_c = lambda w: -(-w // n_slices) * n_slices   # noqa: E731

        def _padw(s, i, w):
            d = w - s.shape[ax_last]
            if d <= 0:
                return s, i
            pads = [(0, 0)] * ax_last + [(0, d)]
            return (jnp.pad(s, pads, constant_values=NEG_INF),
                    jnp.pad(i, pads,
                            constant_values=jnp.int32(2 ** 30)))

        def _slice(x):
            wl = x.shape[ax_last] // n_slices
            return lax.dynamic_slice_in_dim(x, my_slice * wl, wl,
                                            axis=ax_last)

        def _reassemble(x):
            for ax in inner_axes:
                x = lax.all_gather(x, ax, axis=ax_last, tiled=True)
            return x

        kp = pad_c(min(k, NT))
        s, i = _padw(s, i, pad_c(s.shape[ax_last]))
        if n_peers & (n_peers - 1) == 0:
            # tournament: round r exchanges with the peer at distance
            # 2^r; widths grow toward kp so no candidate that could
            # reach the global top-k is ever truncated
            for r in range(n_peers.bit_length() - 1):
                d = 1 << r
                perm = [(x, x ^ d) for x in range(n_peers)]
                ps = lax.ppermute(_slice(s), over_ax, perm)
                pi = lax.ppermute(_slice(i), over_ax, perm)
                fs = _reassemble(ps)
                fi = _reassemble(pi)
                w = min(kp, 2 * s.shape[ax_last])
                s, i = _lex_topk(jnp.concatenate([s, fs], axis=ax_last),
                                 jnp.concatenate([i, fi], axis=ax_last),
                                 w)
                s, i = _padw(s, i, pad_c(w))
            return _lex_topk(s, i, k)
        gs_ = lax.all_gather(_slice(s), over_ax, axis=ax_last,
                             tiled=True)
        gi_ = lax.all_gather(_slice(i), over_ax, axis=ax_last,
                             tiled=True)
        return _lex_topk(_reassemble(gs_), _reassemble(gi_), k)

    def _merge_mesh(s, i, k):
        """Hierarchical candidate-key merge: returns the top-k of the
        union of every shard's (score, global id) keys in the exact
        (score desc, id asc) lex order, replicated on all shards.

        Flat mesh: one all-gather + merge (the PR-5 exchange).  Two
        tiers: all-gather + merge within the host over ICI; then a
        chip-SLICED exchange over DCN — each chip ships 1/CPH of its
        host's window to the partner host and the slices reassemble
        over ICI, so one host window crosses DCN once per transfer,
        not once per chip.  Three tiers (ISSUE 13) repeat the same
        move one level up: the region-merged window — sliced across
        ALL of the region's shards — crosses the WAN once per region
        per transfer, never once per host."""
        ax_last = s.ndim - 1
        if not two_tier:
            gs_ = lax.all_gather(s, mesh_axis, axis=ax_last, tiled=True)
            gi_ = lax.all_gather(i, mesh_axis, axis=ax_last, tiled=True)
            return _lex_topk(gs_, gi_, k)
        if CPH > 1:                      # ICI tier: merge the host
            gs_ = lax.all_gather(s, chip_ax, axis=ax_last, tiled=True)
            gi_ = lax.all_gather(i, chip_ax, axis=ax_last, tiled=True)
            s, i = _lex_topk(gs_, gi_, min(k, gs_.shape[ax_last]))
        if mesh_hosts > 1:               # DCN tier: merge the region
            s, i = _tier_merge(s, i, k, host_ax, mesh_hosts, CPH,
                               lax.axis_index(chip_ax), (chip_ax,))
        if not three_tier or mesh_regions == 1:
            return _lex_topk(s, i, k) if mesh_hosts == 1 else (s, i)
        # WAN tier: merge the fleet — slices span the region's full
        # (host, chip) shard grid, reassembled chips-then-hosts to
        # match the within-region linear index
        wli = (lax.axis_index(host_ax) * jnp.int32(CPH)
               + lax.axis_index(chip_ax))
        return _tier_merge(s, i, k, region_ax, mesh_regions, SPR,
                           wli, (chip_ax, host_ax))
    # wider waves for bigger batches: a group may commit up to W
    # placements per wave, so a K-placement batch converges in O(K / W)
    # fused-wave iterations. Size W to ~2x the LARGEST per-group
    # placement count when the caller supplies it (group_count_hint,
    # computed host-side at pack time): per-group candidate demand is
    # what W serves, and oversizing it multiplies every wave's top-k /
    # interleave / candidate costs for no extra commits. Without a hint
    # (direct callers), fall back to the conservative K-based bound so
    # skewed batches still converge.
    per_group = group_count_hint if group_count_hint > 0 else K // 8
    # merged few-group batches (throughput-mode ask dedup) carry far
    # more placements per group; with tiny Gp the top-k cost of a wider
    # window is negligible, so let W grow
    w_cap = _MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP
    TK = min(max(WAVE_K, min(2 * per_group, w_cap)) + TOP_K, NT)
    W = max(TK - TOP_K, 1)          # effective per-group wave width
    # local extraction width: each shard contributes its top-TKl keys
    # to the all-gather merge; TKl = TK off-mesh, so the single-device
    # trace is unchanged.  Correctness of the merge only needs every
    # shard to surface min(TK, Np_local) candidates (a shard can hold
    # at most that many of the global top-TK).
    TKl = min(TK, Np)
    # shortlist width C (0 = disabled): waves >= 2 re-rank the carried
    # top-C instead of re-reading the full node planes, whenever the
    # validity triggers prove the result identical to a full rescore.
    # distinct_hosts blocking mutates feasibility across groups through
    # nodes outside any shortlist — those batches always full-rescore.
    # In mesh mode the shortlist is SHARD-LOCAL (resolved against the
    # local plane): triggers prove each shard's window contribution
    # exact, and escapes rescore only that shard's plane.
    # the learned-head and region-affinity terms flow through the
    # spec-DRIVEN scorers only (host twin + this wave path); the
    # hand-written shortlist twin and pallas tiles don't implement
    # them, so both stay disabled while either plane is active (see
    # score_spec.TERMS backends tuples)
    C = (0 if (has_distinct or learned is not None
               or region_bias is not None)
         else resolve_shortlist_c(Np, TKl, shortlist_c))
    use_sl = C > 0
    NE = C if use_sl else TKl       # full-wave extraction width
    ks = jnp.arange(K)
    gs = jnp.arange(Gp)

    # ---------- in-kernel preemption planes (ISSUE 7) ----------
    # Extra wave passes score the top-E evictable allocs per node as
    # negative-capacity deltas: a group with NOTHING placeable selects,
    # per feasible node, the min-cost victim set (a float-order-exact
    # twin of scheduler/preemption.victim_distance), ranks nodes by the
    # post-eviction bin-pack score, and commits (place, evict) pairs
    # through the same conflict/commit machinery as normal placements.
    if has_preempt:
        if has_distinct:
            raise ValueError(
                "has_preempt does not compose with distinct_hosts "
                "batches (cross-group blocking is invisible to the "
                "eviction pass); callers fall back to host preemption")
        assert ev_res is not None and ev_prio is not None \
            and ask_prio is not None, \
            "has_preempt needs ev_res/ev_prio/ask_prio planes"
        EV = ev_prio.shape[1]
        ev_prio_i = ev_prio.astype(jnp.int32)
        ev_res_f = ev_res.astype(jnp.float32)
        ask_prio_i = ask_prio.astype(jnp.int32)
        # wave-invariant slot eligibility: real slot, priority at least
        # EV_PRIORITY_DELTA below the ask's (preemptible_allocs gate)
        ev_slot_ok = ((ev_prio_i[None, :, :] >= 0)
                      & (ask_prio_i[:, None, None] - ev_prio_i[None, :, :]
                         >= EV_PRIORITY_DELTA))       # [Gp, Np, E]
    else:
        EV = 1

    # ---------- static feasibility [Gp, Np] ----------
    def per_ask_feas(g):
        vals = attr_rank[:, c_col[g]]                      # [Np, C]
        ok = _op_eval(vals, c_op[g], c_rank[g])            # [Np, C]
        base = valid & dc_ok[g][node_dc] & host_ok[g]      # [Np]
        # per-constraint filtered counts with sequential (first-fail) credit
        passed_prev = jnp.cumprod(
            jnp.concatenate([jnp.ones((Np, 1), bool), ok[:, :-1]], axis=1),
            axis=1).astype(bool)
        first_fail = base[:, None] & passed_prev & ~ok
        filtered = first_fail.sum(axis=0)                  # [C]
        return base & ok.all(axis=1), filtered

    # vmap, not lax.map: map would serialize Gp dispatch rounds; the
    # batched [Gp, Np, C] intermediates are small
    feas, cons_filtered = jax.vmap(per_ask_feas)(gs)
    if in_mesh:
        # [Gp, C] explainability sums reduce once per solve; `feas`
        # itself stays a shard-local plane (reassembled by the caller's
        # out_spec when fetched at all)
        cons_filtered = _psum_mesh(cons_filtered)

    # affinity matches are also placement-invariant: [Gp, Np]
    def per_ask_aff(g):
        vals = attr_rank[:, a_col[g]]                      # [Np, CA]
        match = _op_eval(vals, a_op[g], a_rank[g])
        return (match * a_weight[g][None, :]).sum(axis=1)  # [Np]

    aff_score = jax.vmap(per_ask_aff)(gs) + a_host
    pen_score, pen_counts = _score_spec.static_terms(_JAX_OPS, penalty)

    # ---------- hoisted spread lookups (wave-invariant) ----------
    # The per-(group, node) spread value and desired-count are functions
    # of static batch tensors only; gathering them once per solve keeps
    # the wave loop gather-free (per-wave [Gp, Np] gathers dominated the
    # solve cost before this hoist).
    V = sp_desired.shape[2]
    A = attr_rank.shape[1]
    if has_spread:
        def spread_static(s):
            col = sp_col[:, s]                             # [Gp]
            has = col >= 0
            # column lookup as a one-hot matmul: a per-element gather of
            # [Gp, Np] lowers to a near-scalar loop on TPU (~10ns/elem —
            # it was 2/3 of the whole solve); the MXU does it in one pass.
            # attr ranks are small ints, exact in f32.
            onehot = (col[:, None] == jnp.arange(A)[None, :]
                      ).astype(jnp.float32)                # [Gp, A]
            # HIGHEST precision: default TPU matmul is bf16-accumulated,
            # which rounds integer ranks >= 256; f32 keeps ints < 2^24
            # exact, matching the exact gathers in the quota/commit paths
            v = jnp.dot(onehot, attr_rank.T.astype(jnp.float32),
                        precision=lax.Precision.HIGHEST
                        ).astype(jnp.int32)                # [Gp, Np]
            v = jnp.where(has[:, None], v, -1)
            # desired-count lookup: select-sum over small vocabularies
            # (unrolled V ops); gather fallback for high-cardinality
            # attributes where a V-unrolled loop would blow up the trace
            if V <= _SELECT_SUM_MAX_V:
                desired = jnp.zeros(v.shape, jnp.float32)
                for val in range(V):
                    desired = desired + jnp.where(
                        v == val, sp_desired[:, s, val][:, None], 0.0)
            else:
                desired = jnp.take_along_axis(sp_desired[:, s],
                                              jnp.maximum(v, 0), axis=1)
            desired = jnp.where(v >= 0, desired, -1.0)
            desired = jnp.where(desired < 0, sp_implicit[:, s][:, None],
                                desired)
            return v, desired
        sp_vnode, sp_des = jax.vmap(spread_static)(jnp.arange(S))
    else:
        sp_vnode = sp_des = None

    # tie-break jitter: the reference visits nodes in per-worker shuffled
    # order (stack.go NewRandomIterator), so equal-scoring nodes resolve
    # differently per worker. seed=0 keeps exact deterministic scoring;
    # seed != 0 decorrelates both sibling batches (resident.solve_parallel
    # passes distinct seeds) and sibling GROUPS within a batch, fanning
    # same-shaped asks across equal-scoring nodes instead of colliding on
    # one argmax — fewer contention waves for identical placements.
    node_gids = jnp.arange(Np, dtype=jnp.uint32)
    if in_mesh:
        # jitter hashes the GLOBAL node id so seeded scoring is
        # invariant to how the node axis is split (or re-tiled) over
        # the mesh
        node_gids = g_of_local.astype(jnp.uint32)
    h = (node_gids[None, :] * jnp.uint32(2654435761)
         + (gs.astype(jnp.uint32)[:, None] * jnp.uint32(7919)
            + jnp.uint32(seed)) * jnp.uint32(40503))
    h = (h ^ (h >> 16)) * jnp.uint32(2246822519)
    # Seeded mode quantizes scores into coarse bins and jitters within
    # the bin: once cluster usage is heterogeneous, exact scores make
    # every group rank the same few nodes on top and waves stall on
    # conflicts; binning disperses groups across the whole top score
    # band. The reference's limit iterator picks the max of a random
    # max(2, log2 N) node sample (scheduler/stack.go:80-87) — selection
    # within a near-tied band is no further from its semantics than
    # exact argmax, and converges an order of magnitude faster.
    SCORE_BIN = _score_spec.SCORE_BIN
    jitter = jnp.where(jnp.int32(seed) == 0, 0.0,
                       (h & jnp.uint32(1023)).astype(jnp.float32)
                       * (SCORE_BIN / 1023.0))             # [Gp, Np]

    # ---------- pallas fused-wave path (static, trace-time pick) ----
    # "auto" resolves against the problem shape: "topk" fuses scoring
    # AND per-tile top-K extraction (the [G, N] wave never reaches
    # HBM), "score" fuses the scoring chain into one pass and leaves
    # wide-window extraction to approx_max_k/top_k, "off" keeps the
    # unfused jnp path (the host twin's reference shape).
    if learned is not None or region_bias is not None:
        pallas_mode = "off"
    if pallas_mode == "auto":
        from . import pallas_kernel as _pk
        pallas_mode = _pk.resolve_mode(Np, Gp, TK, V, has_spread)
    Vs_i = sp_desired.shape[2]
    want_tables = has_spread and Vs_i <= 8 and not stack_commit
    # per-value candidate-table widths: TKv is the GLOBAL interleave
    # window per value class; TW the shard-local extraction width (the
    # merge only needs each shard's top min(TKv, Np_local) per class).
    TKv = -(-TK // (Vs_i + 1)) if want_tables else 0
    TW = min(TKv, Np) if want_tables else 0
    if in_mesh and pallas_mode == "topk" and want_tables and TW < TKv:
        # the fused kernel derives its table width from TK, which on a
        # shard narrower than TKv would pad tables past the local
        # plane; the "score" pass is the same exact math unfused and
        # lets the jnp extraction use the shard-local width
        pallas_mode = "score"
    if elastic and pallas_mode == "topk":
        # the fused top-K tournament tie-breaks by LOCAL slot order,
        # which under a tile remap is not global-id order; the "score"
        # pass is the same exact math with extraction left to the
        # gid-ordered lex sort below
        pallas_mode = "score"
    use_pk = pallas_mode != "off"
    if use_pk:
        from . import pallas_kernel as _pk
        from .masks import pack_bool_u32
        # bitpacked static planes: 32 node columns per uint32 lane —
        # 1/8th the bytes of the int8 planes on every full wave's
        # HBM re-read (packed ONCE per solve, outside the wave loop)
        pk_feas = pack_bool_u32(feas)
        pk_pen = pack_bool_u32(penalty)
        pk_sp_has = ((sp_col >= 0).astype(jnp.int8) if has_spread
                     else None)
        # int16 value ranks: bounded by the padded vocab (< 2^15
        # always), halving the static plane each wave re-reads; cast
        # ONCE per solve, outside the wave loop
        pk_vnode = (sp_vnode.astype(jnp.int16) if has_spread else None)

    def group_scores(used, dev_used, coll, sp_used, blocked):
        """Batched scoring of every (group, node) pair against current
        usage — one instance of the reference's rank pipeline, [Gp, Np].
        Spec-driven: assembles the plane context and defers every float
        op to score_spec.evaluate_wave (nomadlint SCORE6xx flags
        scoring arithmetic hand-added back here)."""
        ctx = dict(
            used=used, dev_used=dev_used, coll=coll, sp_used=sp_used,
            blocked=blocked, avail=avail, reserved=reserved,
            ask_res=ask_res, ask_desired=ask_desired, dev_cap=dev_cap,
            dev_ask=dev_ask, feas=feas, pen_score=pen_score,
            pen_counts=pen_counts, aff_score=aff_score,
            has_devices=has_devices, has_spread=has_spread,
            sp_col=sp_col, sp_weight=sp_weight, sp_targeted=sp_targeted,
            vnode=sp_vnode, des=sp_des, S=S, V=V, shape=(Gp, Np),
            seed=seed, jitter=jitter, learned=learned,
            region_bias=region_bias)
        return _score_spec.evaluate_wave(_JAX_OPS, ctx)

    # ---------- shortlist scoring twin ----------
    def _lex_topk(score, idx, k):
        """Descending (score, ascending node id) top-k — the exact
        tie order lax.top_k uses over the full node axis, and the
        order the cross-shard candidate-key merge sorts in."""
        neg, six = lax.sort((-score, idx), num_keys=2)
        return -neg[..., :k], six[..., :k]

    if use_sl:
        def _sl_eval(sl, used_x, dev_used_x, sp_used_x):
            """EXACT score/indicator recompute for the <= C shortlist
            entries from gathered live state.  Every float expression
            mirrors group_scores term for term (same op order), so the
            result is bitwise the full rescore restricted to these
            nodes.  Returns (score, placeable, exh_ind, dim_ind)."""
            idx = sl.idx
            u = used_x[idx]                            # [Gp, C, R]
            av = avail[idx]
            rsv = reserved[idx]
            after = u + ask_res[:, None, :]
            fit_dims = after <= av
            fit = fit_dims.all(axis=-1)
            if has_devices:
                dev_fit = (dev_used_x[idx] + dev_ask[:, None, :]
                           <= dev_cap[idx]).all(axis=-1)
            else:
                dev_fit = jnp.ones((Gp, C), bool)
            placeable = sl.feas & fit & dev_fit

            denom_cpu = av[:, :, R_CPU]
            denom_mem = av[:, :, R_MEM]
            util_cpu = after[:, :, R_CPU] + rsv[:, :, R_CPU]
            util_mem = after[:, :, R_MEM] + rsv[:, :, R_MEM]
            ok_denoms = (denom_cpu > 0) & (denom_mem > 0)
            free_cpu = 1.0 - util_cpu / jnp.maximum(denom_cpu, 1.0)
            free_mem = 1.0 - util_mem / jnp.maximum(denom_mem, 1.0)
            raw = 20.0 - (10.0 ** free_cpu + 10.0 ** free_mem)
            binpack = jnp.where(ok_denoms,
                                jnp.clip(raw, 0.0, 18.0) / 18.0, 0.0)

            anti = jnp.where(sl.coll > 0,
                             -(sl.coll + 1.0) / ask_desired[:, None],
                             0.0)
            anti_counts = sl.coll > 0

            if has_spread:
                spread_total = jnp.zeros((Gp, C), jnp.float32)
                for s in range(S):
                    col = sp_col[:, s]
                    has = col >= 0
                    v = sl.vn[s]
                    has_v = v >= 0
                    used_vec = sp_used_x[:, s]
                    cur = jnp.where(v >= 0, jnp.take_along_axis(
                        used_vec, jnp.maximum(v, 0), axis=1), 0.0)
                    desired = sl.de[s]
                    boost = ((desired - (cur + 1.0))
                             / jnp.maximum(desired, 1e-9)
                             ) * sp_weight[:, s][:, None]
                    targeted = jnp.where(~has_v, -1.0,
                                         jnp.where(desired <= 0, -1.0,
                                                   boost))
                    present = used_vec > 0
                    any_present = present.any(axis=1)[:, None]
                    minc = jnp.min(jnp.where(present, used_vec,
                                             jnp.inf), axis=1)[:, None]
                    maxc = jnp.max(jnp.where(present, used_vec,
                                             -jnp.inf), axis=1)[:, None]
                    delta_boost = (minc - cur) / jnp.maximum(minc, 1e-9)
                    even = jnp.where(cur != minc, delta_boost,
                                     jnp.where(minc == maxc, -1.0,
                                               (maxc - minc)
                                               / jnp.maximum(minc,
                                                             1e-9)))
                    even = jnp.where(~has_v, -1.0, even)
                    even = jnp.where(any_present, even, 0.0)
                    contrib = jnp.where(sp_targeted[:, s][:, None],
                                        targeted, even)
                    spread_total = spread_total + jnp.where(
                        has[:, None], contrib, 0.0)
                spread_counts = spread_total != 0.0
            else:
                spread_total = 0.0
                spread_counts = False

            aff_counts = sl.aff != 0.0
            pen_sc = jnp.where(sl.pen, -1.0, 0.0)
            n_scorers = (1.0 + anti_counts + sl.pen + aff_counts
                         + spread_counts)
            total = (binpack + anti + pen_sc + sl.aff
                     + spread_total) / n_scorers
            total = jnp.where(jnp.int32(seed) == 0, total,
                              jnp.floor(total / SCORE_BIN) * SCORE_BIN)
            gid = (_l2g(idx) if in_mesh else idx).astype(jnp.uint32)
            h2 = (gid * jnp.uint32(2654435761)
                  + (gs.astype(jnp.uint32)[:, None] * jnp.uint32(7919)
                     + jnp.uint32(seed)) * jnp.uint32(40503))
            h2 = (h2 ^ (h2 >> 16)) * jnp.uint32(2246822519)
            jit_sl = jnp.where(jnp.int32(seed) == 0, 0.0,
                               (h2 & jnp.uint32(1023)).astype(
                                   jnp.float32) * (SCORE_BIN / 1023.0))
            total = total + jit_sl
            score = jnp.where(placeable, total, NEG_INF)
            exh = sl.feas & ~(fit & dev_fit)
            dim_ind = sl.feas[:, :, None] & ~fit_dims
            return score, placeable, exh, dim_ind

        sl0 = _SLState(
            idx=jnp.zeros((Gp, C), jnp.int32),
            feas=jnp.zeros((Gp, C), bool),
            pen=jnp.zeros((Gp, C), bool),
            aff=jnp.zeros((Gp, C), jnp.float32),
            vn=jnp.zeros((S, Gp, C) if has_spread else (1, 1, 1),
                         jnp.int32),
            de=jnp.zeros((S, Gp, C) if has_spread else (1, 1, 1),
                         jnp.float32),
            coll=jnp.zeros((Gp, C), jnp.float32),
            cut_s=jnp.zeros(Gp, jnp.float32),
            cut_i=jnp.zeros(Gp, jnp.int32),
            comp=jnp.zeros(Gp, bool),
            nfeas=jnp.zeros(Gp, jnp.int32),
            nexh=jnp.zeros(Gp, jnp.int32),
            ndim=jnp.zeros((Gp, R), jnp.int32),
            win_s=jnp.full((Gp, TKl), NEG_INF, jnp.float32),
            win_i=jnp.zeros((Gp, TKl), jnp.int32),
            tb_s=jnp.full((Gp, Vs_i + 1, TW) if want_tables
                          else (Gp, 1, 1), NEG_INF, jnp.float32),
            tb_i=jnp.zeros((Gp, Vs_i + 1, TW) if want_tables
                           else (Gp, 1, 1), jnp.int32),
            gany=jnp.zeros(Gp, bool),
            ok=jnp.bool_(False))
    else:
        sl0 = None

    # ---------- wave loop ----------
    # The carry is kept COMPACT (per-placement vectors, no [Gp, Np]
    # matrices): tunneled transports copy the whole carry every
    # iteration, so collocation counts and distinct-hosts blocking are
    # rebuilt each wave from the committed outputs with one scatter
    # instead of being carried.  The shortlist-resident path
    # additionally carries the [Gp, C] shortlist state (_SLState) and a
    # wave splits statically into:
    #
    #   full wave  — scores all N (pallas fused or jnp), extracts the
    #                top-C shortlist along with the TK window;
    #   shortlist  — uses the window + counters pre-computed at the end
    #                of the previous wave from the carried shortlist
    #                (fresh gathers of live usage, bitwise the full
    #                rescore restricted to those nodes).
    #
    # Validity is decided at the END of each wave, when the post-commit
    # state already equals the next wave's input: the carried window is
    # used only if (a) the group's whole placeable set fits in C
    # (`comp` — outsiders are permanently NEG_INF since usage only
    # grows), or (b) every commit this wave landed inside the group's
    # shortlist (no outsider's bin-pack score moved), the group has no
    # spread (a spread-state change shifts ALL the group's node scores)
    # and the re-ranked window's TK-th key still dominates the era
    # cutoff (no frozen outsider can rank inside the window).  Any
    # other condition falls back to a full-N rescore wave — the escape
    # hatch that keeps placements bit-identical to the host twin.
    def body(st):
        (used, dev_used, sp_used, done,
         out_idx, out_ok, out_score, out_nfeas, out_nexh, out_dimexh,
         wave, n_resc, SL, EVT, out_evict, out_wave) = st
        active = ~done & (ks < n_place)
        g_idx = p_ask
        used_pre, dev_used_pre = used, dev_used

        Vs = Vs_i

        def full_wave(SL):
            """The full-N pass: rebuild coll/blocked from the committed
            outputs, score every (group, node) pair (pallas fused or
            jnp), extract the top-NE, window the first TK (+ spread
            interleave), reduce the explainability counters — and, when
            the shortlist path is on, rebuild the carried shortlist
            from the same extraction."""
            committed = done & out_ok[:, 0]
            # out_idx holds GLOBAL node ids; scatters into the local
            # plane drop rows owned by other shards (mode="drop";
            # negative locals are pinned to Np first — scatter WRAPS
            # python-style negatives before the drop check)
            chosen = jnp.where(committed, out_idx[:, 0], 0)
            if in_mesh:
                _, chosen_l, _ = _g2l(chosen)
            else:
                chosen_l = chosen
            coll = coll0.at[g_idx, chosen_l].add(
                committed.astype(jnp.float32), mode="drop")
            if has_distinct:
                dg_all = distinct[g_idx]
                hit = jnp.zeros((Gp, Np), jnp.int32).at[
                    jnp.maximum(dg_all, 0), chosen_l].add(
                    (committed & (dg_all >= 0)).astype(jnp.int32),
                    mode="drop") > 0
                blocked = hit[jnp.maximum(distinct, 0)] \
                    & (distinct >= 0)[:, None]
            else:
                blocked = jnp.zeros((Gp, Np), bool)

            pk = None
            if use_pk:
                # fused pallas pass: scoring chain + counters (+ top-K
                # and per-value tables in "topk" mode) in ONE walk of
                # each node tile; no [Gp, Np, R] intermediate ever
                # reaches HBM
                if has_spread:
                    pres = sp_used > 0                 # [Gp, S, V]
                    anyp = pres.any(axis=2)
                    minc_w = jnp.min(jnp.where(pres, sp_used, jnp.inf),
                                     axis=2)
                    maxc_w = jnp.max(jnp.where(pres, sp_used, -jnp.inf),
                                     axis=2)
                    # masked rows (nothing present) are pinned finite:
                    # the kernel's contribution for them is masked to 0
                    # either way, and finite inputs keep the VPU out of
                    # inf/nan
                    spread_pack = (
                        pk_vnode, sp_des, sp_used,
                        sp_weight, sp_targeted, pk_sp_has,
                        jnp.where(anyp, minc_w, 0.0).astype(jnp.float32),
                        jnp.where(anyp, maxc_w, 0.0).astype(jnp.float32),
                        anyp.astype(jnp.int8))
                else:
                    spread_pack = None
                from .masks import pack_bool_u32 as _pack
                pk = _pk.fused_wave(
                    mode=pallas_mode, feas=pk_feas,
                    blocked=(_pack(blocked) if has_distinct
                             else None),
                    aff=aff_score, pen=pk_pen, jitter=jitter, coll=coll,
                    used=used, avail=avail, reserved=reserved,
                    ask_res=ask_res, ask_desired=ask_desired,
                    dev=((dev_used, dev_cap, dev_ask) if has_devices
                         else None),
                    spread=spread_pack, seed=jnp.int32(seed), TK=TK,
                    n_extract=NE,
                    tables_v=(Vs_i if (want_tables
                                       and pallas_mode == "topk")
                              else 0))
                n_feas_g, n_exh_g = pk["n_feas"], pk["n_exh"]
                dim_exh_g, grp_any = pk["dim_exh"], pk["grp_any"]
                score = pk.get("score")          # None in "topk" mode
            else:
                score, placeable, feas_b, fit, fit_dims, dev_fit = \
                    group_scores(used, dev_used, coll, sp_used, blocked)
                grp_any = placeable.any(axis=1)            # [Gp]
                # metrics snapshot for placements finishing this wave
                n_feas_g = (feas_b & valid[None, :]).sum(axis=1)
                n_exh_g = (feas_b & valid[None, :]
                           & ~(fit & dev_fit)).sum(axis=1)
                dim_exh_g = (feas_b[:, :, None] & valid[None, :, None]
                             & ~fit_dims).sum(axis=1)      # [Gp, R]

            # full sort-based top_k dominates wave cost at scale; TPU's
            # approx_max_k (recall ~0.95 over near-tied scores) is the
            # hardware-native candidate search — the solve still scores
            # every node, only the top-W *extraction* is approximate, a
            # far smaller perturbation than the reference's 14-node
            # subsample. Small problems (tests, dryruns) keep the exact
            # path.
            if elastic:
                # under a tile remap local slot order is NOT global-id
                # order, so top_k's index tie-break would diverge from
                # the host twin; extract by the explicit (score desc,
                # GLOBAL id asc) lex key, carrying the local slot
                gid_pl = jnp.broadcast_to(g_of_local[None, :],
                                          (Gp, Np))
                slot_pl = jnp.broadcast_to(
                    jnp.arange(Np, dtype=jnp.int32)[None, :], (Gp, Np))
                neg, eg, ei = lax.sort((-score, gid_pl, slot_pl),
                                       num_keys=2)
                ext_s, ext_g, ext_i = -neg[:, :NE], eg[:, :NE], \
                    ei[:, :NE]
            elif use_pk and pallas_mode == "topk":
                ext_s, ext_i = pk["top_score"], pk["top_idx"]
            elif Np >= _APPROX_MIN_NP:
                ext_s, ext_i = lax.approx_max_k(score, NE)
            else:
                ext_s, ext_i = lax.top_k(score, NE)        # [Gp, NE]
            top_score, top_idx = ext_s[:, :TKl], ext_i[:, :TKl]
            if elastic:
                top_idx = ext_g[:, :TKl]       # window keys are GLOBAL

            # per-value candidate tables for the spread interleave
            # (applied to the window AFTER the cross-shard merge — see
            # _interleave in the wave body); extraction is shard-local
            # at width TW (= TKv off-mesh: unchanged single-device
            # trace).  One class per value PLUS a class for nodes
            # MISSING the spread attribute — the reference still places
            # on those with a -1 score penalty (spread.go), so they
            # must stay candidates or feasible nodes would livelock
            # unplaced.
            if want_tables:
                if use_pk and pallas_mode == "topk":
                    # per-value tables came out of the fused pass; the
                    # tile-partial merge is exact-equal to the full-row
                    # top_k below (tournament + node-order tie-break)
                    tab_s, tab_i = pk["tab_s"], pk["tab_i"]
                else:
                    vnode = sp_vnode[0]                    # [Gp, Np]
                    tabs_i, tabs_s = [], []
                    for v in range(Vs + 1):
                        vmask = (vnode == v) if v < Vs else (vnode < 0)
                        sv = jnp.where(vmask, score, NEG_INF)
                        if elastic:
                            # gid-ordered ties, ids leave GLOBAL
                            ts, ti = _lex_topk(sv, gid_pl, TW)
                        elif Np >= _APPROX_MIN_NP:
                            ts, ti = lax.approx_max_k(sv, TW)
                        else:
                            ts, ti = lax.top_k(sv, TW)
                        tabs_i.append(ti)
                        tabs_s.append(ts)
                    tab_i = jnp.stack(tabs_i, axis=1)      # [Gp, V+1, TW]
                    tab_s = jnp.stack(tabs_s, axis=1)
                if in_mesh and not elastic:
                    tab_i = tab_i + off
            else:
                tab_s = jnp.full((Gp, 1, 1), NEG_INF, jnp.float32)
                tab_i = jnp.zeros((Gp, 1, 1), jnp.int32)
            if in_mesh and not elastic:
                # window keys leave the shard with GLOBAL node ids
                top_idx = top_idx + off

            if use_sl:
                # rebuild the carried shortlist from this extraction
                # (stored node-ascending so commit positions resolve
                # with one searchsorted); the cutoff key freezes the
                # best possible outsider for the whole era
                perm = jnp.argsort(ext_i, axis=1)
                sl_i = jnp.take_along_axis(ext_i, perm, axis=1)
                if has_spread:
                    bidx = jnp.broadcast_to(sl_i, (S, Gp, C))
                    vn = jnp.take_along_axis(sp_vnode, bidx, axis=2)
                    de = jnp.take_along_axis(sp_des, bidx, axis=2)
                else:
                    vn, de = SL.vn, SL.de
                SL = _SLState(
                    idx=sl_i,
                    feas=jnp.take_along_axis(feas, sl_i, axis=1),
                    pen=jnp.take_along_axis(penalty, sl_i, axis=1),
                    aff=jnp.take_along_axis(aff_score, sl_i, axis=1),
                    vn=vn, de=de,
                    coll=jnp.take_along_axis(coll, sl_i, axis=1),
                    cut_s=ext_s[:, NE - 1],
                    # era cutoff tie-break key: GLOBAL id under the
                    # elastic remap (the extraction's lex order), local
                    # slot otherwise (identical — the block map is
                    # monotonic)
                    cut_i=(ext_g[:, NE - 1] if elastic
                           else ext_i[:, NE - 1]),
                    comp=(n_feas_g - n_exh_g) <= jnp.int32(C),
                    nfeas=n_feas_g, nexh=n_exh_g, ndim=dim_exh_g,
                    win_s=top_score, win_i=top_idx,
                    tb_s=tab_s, tb_i=tab_i,
                    gany=grp_any, ok=jnp.bool_(False))
            return (top_score, top_idx, tab_s, tab_i, n_feas_g,
                    n_exh_g, dim_exh_g, grp_any, SL, jnp.int32(1))

        if use_sl:
            def carried_wave(SL):
                # shortlist wave: the window and counters were
                # pre-computed at the end of the previous wave from the
                # carried shortlist — no [Gp, Np] plane is touched
                return (SL.win_s, SL.win_i, SL.tb_s, SL.tb_i, SL.nfeas,
                        SL.nexh, SL.ndim, SL.gany, SL, jnp.int32(0))

            if lane_axis is not None:
                # lane-uniform predicate (ISSUE 20): a psum over the
                # lane vmap axis is UNBATCHED, so this cond stays a
                # real branch under `jax.vmap(..., axis_name=lane_axis)`
                # — a per-lane (batched) predicate would lower to
                # select and run the full [Gp, Np] pass every wave for
                # every lane, the PR 4 "pure overhead" that forced
                # shortlists off on vmapped lanes.  Any lane losing its
                # carried window sends ALL lanes through the full pass:
                # conservative (extra rescores, counted in n_resc) and
                # always exact, since the full pass is the escape hatch.
                take_carried = lax.psum(
                    jnp.int32(~SL.ok), lane_axis) == jnp.int32(0)
            else:
                take_carried = SL.ok
            (top_score, top_idx, tab_s, tab_i, n_feas_g, n_exh_g,
             dim_exh_g, grp_any, SL, resc) = lax.cond(
                 take_carried, carried_wave, full_wave, SL)
        else:
            (top_score, top_idx, tab_s, tab_i, n_feas_g, n_exh_g,
             dim_exh_g, grp_any, SL, resc) = full_wave(SL)
        n_resc = n_resc + resc

        # ---- cross-shard candidate-key merge (mesh mode) ----
        # The ONLY per-wave ICI traffic: each shard's [Gp, TKl] window
        # keys (+ [Gp, V+1, TW] value-table keys when the spread
        # interleave is on) are all-gathered and exactly merged by the
        # same lex order the per-shard extraction used — equal to a
        # single device's top-TK over the whole node axis.  Counters
        # reduce with a [Gp]-sized psum; no [Gp, Np] plane ever leaves
        # a shard.  Either branch of the cond above is collective-free,
        # so shards may mix carried/full waves freely — each shard's
        # contribution is trigger-proven exact either way.
        if in_mesh:
            top_score, top_idx = _merge_mesh(top_score, top_idx, TK)
            if want_tables:
                tab_s, tab_i = _merge_mesh(tab_s, tab_i, TKv)
            n_feas_out = _psum_mesh(n_feas_g)
            n_exh_out = _psum_mesh(n_exh_g)
            dim_exh_out = _psum_mesh(dim_exh_g)
            grp_any = _psum_mesh(grp_any.astype(jnp.int32)) > 0
        else:
            n_feas_out, n_exh_out, dim_exh_out = (n_feas_g, n_exh_g,
                                                  dim_exh_g)

        # spread-aware candidate interleaving (slot 0): when node
        # classes correlate with the spread attribute (racks live in
        # one dc, zones in one region — the common cluster layout), a
        # group's global top-W concentrates in ONE value and the spread
        # quota strands all but a few commits per wave. Instead,
        # interleave the per-value tables (slot j -> value j mod V), so
        # a group's candidates arrive pre-balanced across values; holes
        # (exhausted values) compact to the tail to keep the rank-wrap
        # contiguous. Skipped for huge vocabularies where per-value
        # extraction would dominate.
        # (skipped in stack_commit mode: stacking aims every placement
        # at slot 0, and the reference picks the max TOTAL score — the
        # spread term is already inside the score; forcing slot 0 to
        # the spread-preferred value would override the argmax)
        if want_tables:
            has0 = sp_col[:, 0] >= 0                       # [Gp]
            # visit values in each group's preference order (best head
            # candidate first), so the first interleaved slot — where a
            # lone remaining placement always lands — is the value the
            # spread scoring actually favors this wave
            vord = jnp.argsort(-tab_s[:, :, 0], axis=1)    # [Gp, V+1]
            j = jnp.arange(TK)
            vj = vord[:, j % (Vs + 1)]                     # [Gp, TK]
            inter_i = tab_i[gs[:, None], vj, (j // (Vs + 1))[None, :]]
            inter_s = tab_s[gs[:, None], vj, (j // (Vs + 1))[None, :]]
            order = jnp.argsort((inter_s <= NEG_INF / 2)
                                .astype(jnp.int32), axis=1,
                                stable=True)
            inter_i = jnp.take_along_axis(inter_i, order, axis=1)
            inter_s = jnp.take_along_axis(inter_s, order, axis=1)
            top_idx = jnp.where(has0[:, None], inter_i, top_idx)
            top_score = jnp.where(has0[:, None], inter_s, top_score)

        # rank each active placement within its group, then assign the
        # r-th remaining placement the group's (r mod M)-th best node,
        # where M is the group's real candidate count this wave: ranks
        # beyond the candidate list WRAP onto it, so every active
        # placement gets a candidate every wave and per-node cumulative
        # fit commits as many as capacity allows — a count >> W group
        # converges in a couple of waves instead of count/W
        grp_onehot = ((g_idx[None, :] == gs[:, None])
                      & active[None, :]).astype(jnp.int32)  # [Gp, K]
        act_g = grp_onehot.sum(axis=1)                     # [Gp]
        rank = (jnp.cumsum(grp_onehot, axis=1)
                - grp_onehot)[g_idx, ks]                   # exclusive count
        n_cand = (top_score > NEG_INF / 2).sum(axis=1)     # [Gp] real slots
        M = jnp.clip(jnp.minimum(n_cand, W), 1, W)
        # seeded per-group offset into the candidate window: without it,
        # every group's placements sit on slots 0..act-1 and all groups
        # hammer the same few top-scoring (often score-tied) nodes, so
        # per-wave commits are capped by that narrow pool's capacity.
        # Offsetting disperses groups across the whole top-W window —
        # candidates stay within the best W of N nodes (vs the
        # reference's random max(2, log2 N) subsample). seed=0 keeps the
        # exact deterministic mapping.
        g_hash = ((gs.astype(jnp.uint32) * jnp.uint32(2654435761))
                  ^ (jnp.uint32(seed) * jnp.uint32(2246822519)))
        g_off = jnp.where(jnp.int32(seed) == 0, 0,
                          ((g_hash >> 8) % jnp.uint32(W)).astype(
                              jnp.int32))                  # [Gp]
        # rotate the candidate window each wave (seeded mode): a
        # placement bounced by a same-wave conflict probes a DIFFERENT
        # slot next wave instead of re-contending for the node it lost,
        # which otherwise stalls convergence once the cluster fills and
        # scores tie across groups
        # step of 1 is coprime with every window size M (a fixed larger
        # step would be a no-op for groups where M divides it)
        rot = jnp.where(jnp.int32(seed) == 0, 0, wave)
        if stack_commit:
            # serial-fidelity mode (quality/exact path): every active
            # placement of a group aims at the group's CURRENT best
            # node; the cumulative per-node fit below commits as many
            # as actually fit and the rest re-score next wave against
            # updated usage — the reference's per-placement best-fit
            # stacking (rank.go:149 BinPackIterator), wave-batched.
            # Fan-out mode spreads a group across its top-W nodes in
            # one wave (fast), but fragments capacity near the packing
            # limit; stacking trades waves for the reference's quality.
            cr = jnp.zeros_like(rank)
        else:
            cr = (rank + g_off[g_idx] + rot) % M[g_idx]
        cand = top_idx[g_idx, cr]                          # [K]
        cand_score = top_score[g_idx, cr]
        cand_ok = active & (cand_score > NEG_INF / 2)

        # a group with nothing placeable fails all its remaining placements
        fail_now = active & ~grp_any[g_idx]

        # -- same-wave conflict checks over shared nodes --
        # prior_rank(key)[p] = #earlier candidates with the same key;
        # prior_sum(key, v)[p] = sum of v over them. Small K uses [K, K]
        # masks (matmul on the MXU); large K uses sort-based segmented
        # prefix sums, O(K log K) — identical results.
        if K <= 2048 and not _FORCE_SORT_CONFLICTS:
            earlier = ks[None, :] < ks[:, None]            # [K, K]
            both_ok = cand_ok[None, :] & cand_ok[:, None]
            same_node = ((cand[None, :] == cand[:, None])
                         & both_ok & earlier)

            def prior_sum_node(vals):
                return same_node.astype(jnp.float32) @ vals

            def prior_rank_any(key, m):
                # exclusive count of earlier members with equal key,
                # under an arbitrary membership mask (the preemption
                # pass ranks candidates whose cand_ok is False)
                same = ((key[None, :] == key[:, None])
                        & m[None, :] & m[:, None] & earlier)
                return same.sum(axis=1)

            def prior_rank(key, member):
                return prior_rank_any(key, member & cand_ok)
        else:
            def _seg(key, ok):
                """Sort (key, idx) over `ok` members; return per-element
                exclusive segment rank and a segmented exclusive-prefix
                summer."""
                keyc = jnp.where(ok, key, jnp.int32(0x7FFFFFF0))
                s_key, s_ix = lax.sort((keyc, ks), num_keys=2)
                pos = ks
                is_start = jnp.concatenate(
                    [jnp.ones(1, bool), s_key[1:] != s_key[:-1]])
                start_pos = lax.cummax(jnp.where(is_start, pos, 0))

                def summer(vals):
                    v = vals[s_ix]
                    cum = jnp.cumsum(v, axis=0) - v        # exclusive
                    prior_sorted = cum - cum[start_pos]
                    return jnp.zeros_like(vals).at[s_ix].set(prior_sorted)

                rank = jnp.zeros(K, jnp.int32).at[s_ix].set(
                    (pos - start_pos).astype(jnp.int32))
                return rank, summer

            _, prior_sum_node = _seg(cand, cand_ok)

            def prior_rank_any(key, m):
                rank, _ = _seg(key, m)
                return jnp.where(m, rank, 0)

            def prior_rank(key, member):
                # exclusive count of earlier ok members with equal key;
                # non-members get a key outside every real segment
                keyc = jnp.where(member, key, jnp.int32(0x3FFFFFF0))
                rank, _ = _seg(keyc, cand_ok)
                return jnp.where(member, rank, 0)

        res_k = ask_res[g_idx] * cand_ok[:, None]
        prior = prior_sum_node(res_k)                      # [K, R]
        if in_mesh:
            # candidate rows live on their owning shard: each shard
            # evaluates the fit for the <= K candidates it owns and the
            # K-sized bit vectors reduce over the tiered interconnect
            # (candidate-only traffic — the [Np, R] planes stay put).
            # _g2l pins every non-owned candidate to the always-dropped
            # Np slot (scatter WRAPS python-style negatives before
            # mode="drop" checks bounds).
            inb, loc, locc = _g2l(cand)
            fits_l = ((used[locc] + prior + ask_res[g_idx])
                      <= avail[locc]).all(axis=-1) & inb
            fits = _psum_mesh(fits_l.astype(jnp.int32)) > 0
        else:
            loc = locc = cand
            inb = None
            fits = ((used[cand] + prior + ask_res[g_idx])
                    <= avail[cand]).all(axis=-1)
        if has_devices:
            dev_k = dev_ask[g_idx] * cand_ok[:, None]
            prior_dev = prior_sum_node(dev_k)              # [K, D]
            if in_mesh:
                dev_fits_l = ((dev_used[locc] + prior_dev
                               + dev_ask[g_idx])
                              <= dev_cap[locc]).all(axis=-1) & inb
                dev_fits = _psum_mesh(
                    dev_fits_l.astype(jnp.int32)) > 0
            else:
                dev_fits = ((dev_used[cand] + prior_dev + dev_ask[g_idx])
                            <= dev_cap[cand]).all(axis=-1)
        else:
            dev_fits = jnp.ones(K, bool)
        if in_mesh and has_spread:
            # one [K, A] psum-gather of the candidates' attribute-rank
            # rows serves both the spread quota and the commit below
            ar_cand = _psum_mesh(
                jnp.where(inb[:, None],
                          attr_rank[locc].astype(jnp.int32), 0))
        else:
            ar_cand = None

        # distinct_hosts: one commit per (node, distinct group) per wave;
        # cross-wave blocking keeps later waves off the node too
        if has_distinct:
            dg = distinct[g_idx]
            dg_key = cand * jnp.int32(Gp) + jnp.maximum(dg, 0)
            dg_ok = prior_rank(dg_key, dg >= 0) == 0
        else:
            dg_ok = jnp.ones(K, bool)

        # spread quota: cap same-wave commits per (group, slot, value) so
        # a wave cannot blow far past a spread target the serial
        # reference would have steered away from; targeted spreads stop
        # at their desired counts, even spreads at a balanced level
        # (S is a small static pad; unrolled)
        sp_ok = jnp.ones(K, bool)
        for s in (range(S) if has_spread else range(0)):
            cols = sp_col[g_idx, s]
            if in_mesh:
                vs = jnp.take_along_axis(
                    ar_cand, jnp.maximum(cols, 0)[:, None], axis=1)[:, 0]
            else:
                vs = attr_rank[cand, jnp.maximum(cols, 0)]
            has_s = (cols >= 0) & (vs >= 0)
            vsc = jnp.maximum(vs, 0)
            des_s = sp_desired[:, s]                       # [Gp, V]
            use_s = sp_used[:, s]
            des_eff = jnp.where(des_s < 0, sp_implicit[:, s][:, None],
                                des_s)
            present = use_s > 0
            maxc = jnp.max(jnp.where(present, use_s, 0.0),
                           axis=1)[:, None]
            minc = jnp.min(jnp.where(present, use_s,
                                     jnp.where(present.any(axis=1)[:, None],
                                               jnp.inf, 0.0)),
                           axis=1)[:, None]
            minc = jnp.where(jnp.isfinite(minc), minc, 0.0)
            # even spread: every value may grow to a common level L =
            # max(current max, min + fair share of this wave's active
            # placements) — for the FIRST HALF of the wave budget.
            # Near capacity the min value may be almost exhausted yet
            # keep absorbing a node or two per wave; anchored to it,
            # every other value's quota collapses to 1/wave and the
            # batch stalls (config 3's retry storm).  The serial
            # reference only ever steers by SCORE (spread.go penalizes
            # an overfilled value, never hard-blocks), so after the
            # balanced half-budget the quota relaxes and the remaining
            # placements fill whatever capacity exists, score-steered.
            share = jnp.ceil(act_g.astype(jnp.float32) / V)[:, None]
            level = jnp.maximum(maxc, minc + share)
            even_q = jnp.where(wave < jnp.int32(max(max_waves // 2, 1)),
                               jnp.maximum(1.0, level - use_s),
                               jnp.inf)
            quota = jnp.where(
                sp_targeted[:, s][:, None],
                jnp.maximum(1.0, des_eff - use_s),
                even_q)                                    # [Gp, V]
            gv_key = (g_idx * jnp.int32(V) + vsc) * jnp.int32(2) + 1
            gv_rank = prior_rank(gv_key, has_s).astype(jnp.float32)
            sp_ok &= ~has_s | (gv_rank < quota[g_idx, vsc])

        commit = cand_ok & fits & dev_fits & dg_ok & sp_ok
        cm = commit[:, None]

        # -- apply all of this wave's commits at once (coll/blocked are
        # rebuilt from the outputs next wave, not carried); in mesh
        # mode each shard scatters only the rows it owns (mode="drop"
        # discards other shards' candidates) while the replicated
        # sp_used updates identically everywhere --
        used = used.at[loc].add(ask_res[g_idx] * cm, mode="drop")
        if has_devices:
            dev_used = dev_used.at[loc].add(dev_ask[g_idx] * cm,
                                            mode="drop")
        if has_spread:
            if in_mesh:
                svals = jnp.take_along_axis(
                    ar_cand, jnp.maximum(sp_col[g_idx], 0), axis=1)
            else:
                svals = attr_rank[cand[:, None],
                                  jnp.maximum(sp_col[g_idx], 0)]
            okslot = (sp_col[g_idx] >= 0) & (svals >= 0) & cm
            sp_used = sp_used.at[g_idx[:, None], jnp.arange(S)[None, :],
                                 jnp.maximum(svals, 0)].add(
                okslot.astype(jnp.float32))

        # ---------------- preemption wave pass (ISSUE 7) ----------------
        # Runs AFTER the normal commits against post-commit usage, only
        # for groups with nothing placeable this wave.  Greedy min-cost
        # victim selection per (group, node) over the top-E evictable
        # planes — the float-order-exact twin of
        # scheduler/preemption.victim_distance — then node choice by
        # post-eviction bin-pack score (the reference feeds preemption
        # options through the regular rank/max pipeline).  In mesh mode
        # the heavy work is shard-local; only per-group best eviction
        # KEYS (score, global node id) ride the candidate-key ICI
        # exchange, exactly like the placement windows.
        if has_preempt:
            want = active & ~commit & ~grp_any[g_idx]
            want_g = (jnp.zeros(Gp, jnp.int32).at[g_idx]
                      .add(want.astype(jnp.int32)) > 0)

            def do_evict(args):
                used_x, dev_used_x, evt = args
                f32 = jnp.float32
                es = jnp.arange(EV)
                # shortfall base: usage + ask - capacity, per (g, n)
                base_short = (used_x[None, :, :] + ask_res[:, None, :]
                              - avail[None, :, :])     # [Gp, Np, R]
                slot_free = ev_slot_ok & ~evt[None, :, :]
                freed = jnp.zeros((Gp, Np, R), f32)
                picked = jnp.zeros((Gp, Np, EV), bool)
                prank = jnp.full((Gp, Np, EV), EV, jnp.int32)
                for t in range(EV):
                    s = jnp.maximum(base_short - freed, 0.0)
                    covered = (s <= 0.0).all(axis=-1)
                    norm = jnp.maximum(s, 1.0)
                    diff = ((s[:, :, None, :] - ev_res_f[None, :, :, :])
                            / norm[:, :, None, :])     # [Gp, Np, E, R]
                    d2 = diff * diff
                    # explicit association — part of the host-twin
                    # float-order contract (victim_distance)
                    dist = jnp.sqrt(((d2[..., 0] + d2[..., 1])
                                     + d2[..., 2]) + d2[..., 3])
                    cand_e = slot_free & ~picked
                    dist = jnp.where(cand_e, dist, f32(1e30))
                    e_star = jnp.argmin(dist, axis=-1)  # first min wins
                    take = cand_e.any(axis=-1) & ~covered
                    oh = ((es[None, None, :] == e_star[..., None])
                          & take[..., None])
                    picked = picked | oh
                    prank = jnp.where(oh, jnp.int32(t), prank)
                    freed = freed + (ev_res_f[None, :, :, :]
                                     * oh[..., None]).sum(axis=2)
                # redundancy prune (preemption.prune_superset order:
                # highest-priority victims first, pick order on ties)
                key = jnp.where(
                    picked,
                    (jnp.int32(32768) - ev_prio_i[None, :, :])
                    * jnp.int32(EV + 1) + prank,
                    jnp.int32(2 ** 30))
                seq = jnp.argsort(key, axis=-1)
                for t in range(EV):
                    e_t = seq[..., t]
                    oh = es[None, None, :] == e_t[..., None]
                    is_p = (picked & oh).any(axis=-1)
                    vec = (ev_res_f[None, :, :, :]
                           * oh[..., None]).sum(axis=2)
                    trial = freed - vec
                    still = ((base_short - trial) <= 0.0).all(axis=-1)
                    drop = is_p & still
                    picked = picked & ~(oh & drop[..., None])
                    freed = jnp.where(drop[..., None], trial, freed)

                covered_f = ((base_short - freed) <= 0.0).all(axis=-1)
                if has_devices:
                    # device instances are never evicted in-kernel: the
                    # node must fit the device ask as-is (device-dim
                    # shortfalls keep the host preemption fallback)
                    dev_fit_ev = (dev_used_x[None, :, :]
                                  + dev_ask[:, None, :]
                                  <= dev_cap[None, :, :]).all(axis=-1)
                else:
                    dev_fit_ev = jnp.ones((Gp, Np), bool)
                ok_node = (covered_f & picked.any(axis=-1) & feas
                           & dev_fit_ev & want_g[:, None])
                after = (used_x[None, :, :] + ask_res[:, None, :]
                         - freed)
                binpack = _score_spec.rescore_binpack(
                    _JAX_OPS, after, avail, reserved)
                ev_score = jnp.where(ok_node, binpack, f32(NEG_INF))
                ids = (g_of_local if in_mesh
                       else jnp.arange(Np, dtype=jnp.int32))
                ids2 = jnp.broadcast_to(ids[None, :], (Gp, Np))
                slots2 = jnp.broadcast_to(
                    jnp.arange(Np, dtype=jnp.int32)[None, :], (Gp, Np))
                # lex top-1 by (score desc, GLOBAL id asc), carrying
                # the local slot (under the elastic remap slot order is
                # not id order, so the slot cannot be derived back)
                neg_e, nv_i2, nv_l2 = lax.sort(
                    (-ev_score, ids2, slots2), num_keys=2)
                nv_s_l, nv_i_l = -neg_e[:, 0], nv_i2[:, 0]
                # freed/picked at the LOCAL best node: the cross-shard
                # winner is always some shard's local best, so the
                # owner already holds its victim set
                loc_best = nv_l2[:, 0]
                sel_freed = freed[gs, loc_best]             # [Gp, R]
                sel_mask = picked[gs, loc_best]             # [Gp, EV]
                return nv_s_l, nv_i_l, sel_freed, sel_mask

            def skip_evict(args):
                return (jnp.full(Gp, NEG_INF, jnp.float32),
                        jnp.zeros(Gp, jnp.int32),
                        jnp.zeros((Gp, R), jnp.float32),
                        jnp.zeros((Gp, EV), bool))

            # `want` derives from replicated values, so the predicate
            # is mesh-uniform and both branches stay collective-free —
            # the key exchange below runs unconditionally
            nv_s, nv_i, sel_freed, sel_mask = lax.cond(
                want.any(), do_evict, skip_evict,
                (used, dev_used, EVT))

            if in_mesh:
                wv_s2, wv_i2 = _merge_mesh(nv_s[:, None],
                                           nv_i[:, None], 1)
                win_s, win_i = wv_s2[:, 0], wv_i2[:, 0]
            else:
                win_s, win_i = nv_s, nv_i
            ev_any_g = win_s > NEG_INF / 2                  # [Gp]

            e_cand = win_i[g_idx]                           # [K] global
            p_ok = want & ev_any_g[g_idx]
            # one preemption commit per node per wave (across groups):
            # two victim sets computed independently must never both
            # apply to one node
            ev_commit = p_ok & (prior_rank_any(e_cand, p_ok) == 0)
            ecm = ev_commit[:, None]
            if in_mesh:
                e_inb, e_loc, e_locc = _g2l(e_cand)
            else:
                e_loc = e_locc = e_cand
                e_inb = jnp.ones(K, bool)
            own = (e_inb & ev_commit)[:, None]
            # victims leave, the new placement lands — one scatter
            used = used.at[e_loc].add(
                (ask_res[g_idx] - sel_freed[g_idx]) * ecm, mode="drop")
            if has_devices:
                dev_used = dev_used.at[e_loc].add(
                    dev_ask[g_idx] * ecm, mode="drop")
            em_local = sel_mask[g_idx] & own                # [K, EV]
            EVT = EVT | (jnp.zeros((Np, EV), jnp.int32).at[e_loc].add(
                em_local.astype(jnp.int32), mode="drop") > 0)
            if in_mesh:
                em_rep = _psum_mesh(em_local.astype(jnp.int32)) > 0
            else:
                em_rep = em_local
            if has_spread:
                if in_mesh:
                    ar_ev = _psum_mesh(
                        jnp.where(own,
                                  attr_rank[e_locc].astype(jnp.int32),
                                  0))
                    evals_ = jnp.take_along_axis(
                        ar_ev, jnp.maximum(sp_col[g_idx], 0), axis=1)
                else:
                    evals_ = attr_rank[e_cand[:, None],
                                       jnp.maximum(sp_col[g_idx], 0)]
                ok_es = (sp_col[g_idx] >= 0) & (evals_ >= 0) & ecm
                sp_used = sp_used.at[g_idx[:, None],
                                     jnp.arange(S)[None, :],
                                     jnp.maximum(evals_, 0)].add(
                    ok_es.astype(jnp.float32))
            # a group with no placeable node AND no eviction option
            # fails; one with an eviction option keeps retrying
            fail_now = fail_now & ~ev_any_g[g_idx]
        else:
            ev_commit = jnp.zeros(K, bool)

        # -- record results: a committed placement's fall-through top-K is
        # its group's candidate list starting at its own rank --
        offs = cr[:, None] + jnp.arange(TOP_K)[None, :]    # < TK by constr.
        pk_idx = top_idx[g_idx[:, None], offs]
        pk_score = top_score[g_idx[:, None], offs]
        pk_ok = pk_score > NEG_INF / 2
        ok_row = pk_ok & cm
        if has_preempt:
            # an eviction-committed placement records its single chosen
            # node in slot 0 (no fall-through candidates — the victim
            # set is node-specific) with the post-eviction bin-pack
            # score; the evict mask rides in out_evict
            ecol = jnp.arange(TOP_K)[None, :] == 0
            pk_idx = jnp.where(ecm, jnp.where(ecol, e_cand[:, None], 0),
                               pk_idx)
            pk_score = jnp.where(
                ecm, jnp.where(ecol, win_s[g_idx][:, None], NEG_INF),
                pk_score)
            ok_row = jnp.where(ecm, ecol, ok_row)
        newly = commit | ev_commit | fail_now
        upd = newly[:, None]
        out_idx = jnp.where(upd, pk_idx, out_idx)
        out_score = jnp.where(upd, pk_score, out_score)
        out_ok = jnp.where(upd, ok_row, out_ok)
        if has_preempt:
            out_evict = jnp.where(upd, em_rep & ecm, out_evict)
        out_wave = jnp.where(commit | ev_commit, wave, out_wave)
        out_nfeas = jnp.where(newly, n_feas_out[g_idx], out_nfeas)
        out_nexh = jnp.where(newly, n_exh_out[g_idx], out_nexh)
        out_dimexh = jnp.where(newly[:, None], dim_exh_out[g_idx],
                               out_dimexh)
        done = done | newly

        if use_sl:
            # ---- end-of-wave shortlist maintenance ----
            # Post-commit state here IS the next wave's input, so the
            # next window and its validity are decided now: the next
            # wave either reads the carried [Gp, TK] window or runs the
            # full pass — never both.
            active_next = active & ~newly
            act_next_g = jnp.zeros(Gp, jnp.int32).at[g_idx].add(
                active_next.astype(jnp.int32)) > 0
            any_next = active_next.any()
            cf = commit.astype(jnp.float32)
            # TR1: every commit this wave (any group's) landed inside
            # this group's shortlist — otherwise an outsider's bin-pack
            # score moved and the frozen cutoff bound is void.  In mesh
            # mode only commits to THIS shard's nodes can move scores
            # on this shard's plane (binpack/coll are per-node, spread
            # is globally gated below), so the audit is shard-local:
            # owned commits vs the local shortlist.
            if in_mesh:
                tot = (cf * inb.astype(jnp.float32)).sum()
            else:
                tot = cf.sum()
            mark = jnp.zeros(Np, jnp.float32).at[loc].add(cf,
                                                          mode="drop")
            tr1_g = mark[SL.idx].sum(axis=1) == tot
            g_committed = jnp.zeros(Gp, jnp.float32).at[g_idx].add(
                cf) > 0
            if has_spread:
                has_sp_g = (sp_col >= 0).any(axis=1)
            else:
                has_sp_g = jnp.zeros(Gp, bool)
            # spread groups shift ALL their node scores when their OWN
            # sp_used changes (a commit with a spread value); a wave
            # where the group committed nothing leaves its spread state
            # — and so every outsider's score — frozen, and TR1/TR3
            # carry the proof.  Groups riding the per-value interleave
            # (want_tables + slot-0 spread) additionally need FULL
            # class coverage: their window draws from per-class tables
            # whose tails can rank below the global top-C, so only a
            # COMPLETE shortlist (outsiders permanently NEG_INF) makes
            # their re-rank provably exact.
            sp_gate = has_sp_g & g_committed
            if want_tables:
                sp_gate = sp_gate | (sp_col[:, 0] >= 0)
            ok_pre_g = SL.comp | (tr1_g & ~sp_gate)
            pre_ok = any_next & (ok_pre_g | ~act_next_g).all()
            if has_preempt:
                # an eviction REDUCES usage, breaking the monotone-
                # usage argument behind the `comp` bypass and freezing
                # guarantees wholesale: any evict commit this wave
                # forces the next wave back to a full-N rescore (which
                # rebuilds the shortlist and its era state)
                pre_ok = pre_ok & ~ev_commit.any()

            # own-group commit counts fold into the carried coll (the
            # window's shortlist positions resolve by bisection; a
            # full-wave window may hold interleave entries outside the
            # shortlist — those drop here AND fail TR1, forcing the
            # rescore that rebuilds coll from the plane)
            tloc = _g2l(top_idx)[1] if in_mesh else top_idx
            win_pos = jax.vmap(jnp.searchsorted)(SL.idx, tloc)
            pos_hit = jnp.take_along_axis(
                SL.idx, jnp.minimum(win_pos, C - 1), axis=1) == tloc
            win_pos = jnp.where(pos_hit, win_pos, C)       # drop slot
            cand_pos = win_pos[g_idx, cr]
            SL = SL._replace(coll=SL.coll.at[g_idx, cand_pos].add(
                cf, mode="drop"))

            def rerank(sl):
                """Fresh re-rank of the shortlist against post-commit
                state + TR3 cutoff audit + incremental counters."""
                _, _, exh_pre, dim_pre = _sl_eval(
                    sl, used_pre, dev_used_pre, sp_used)
                f_score, f_place, exh_post, dim_post = _sl_eval(
                    sl, used, dev_used, sp_used)
                # only shortlist nodes changed (TR1-guarded), so the
                # full-N counters advance by the shortlist delta
                d_exh = (exh_post.astype(jnp.int32)
                         - exh_pre.astype(jnp.int32)).sum(axis=1)
                d_dim = (dim_post.astype(jnp.int32)
                         - dim_pre.astype(jnp.int32)).sum(axis=1)
                nexh_next = n_exh_g + d_exh
                ndim_next = dim_exh_g + d_dim
                # lex tie-break key: GLOBAL ids under the elastic remap
                # (matching the building extraction and cut_i), local
                # slots otherwise (the block map is monotonic)
                sl_key = _l2g(sl.idx) if elastic else sl.idx
                w_s, w_i = _lex_topk(f_score, sl_key, TKl)
                # TR3: the re-ranked TKl-th key must still dominate the
                # era cutoff — no frozen outsider can rank inside (both
                # sides of the lex compare use the same id space as
                # cut_i)
                ls, li = w_s[:, TKl - 1], w_i[:, TKl - 1]
                tr3_g = (ls > sl.cut_s) | ((ls == sl.cut_s)
                                           & (li <= sl.cut_i))
                if want_tables:
                    # shortlist-local per-value tables for the post-
                    # merge interleave: exact for the groups that reach
                    # here (`comp` guarantees every placeable class
                    # member is present; NEG_INF filler indices differ
                    # from the full pass but are compacted to the tail
                    # and never commit)
                    vnode0 = sl.vn[0]
                    tabs_s, tabs_i = [], []
                    for v in range(Vs + 1):
                        vmask = ((vnode0 == v) if v < Vs
                                 else (vnode0 < 0))
                        sv = jnp.where(vmask, f_score, NEG_INF)
                        ts, ti = _lex_topk(sv, sl_key, TW)
                        tabs_s.append(ts)
                        tabs_i.append(ti)
                    tab_s = jnp.stack(tabs_s, axis=1)   # [Gp, V+1, TW]
                    tab_i = jnp.stack(tabs_i, axis=1)
                    if in_mesh and not elastic:
                        tab_i = tab_i + off
                else:
                    tab_s = jnp.full((Gp, 1, 1), NEG_INF, jnp.float32)
                    tab_i = jnp.zeros((Gp, 1, 1), jnp.int32)
                gany_next = jnp.where(sl.comp, f_place.any(axis=1),
                                      jnp.bool_(True))
                ok_next = ((tr3_g | sl.comp) | ~act_next_g).all()
                if in_mesh and not elastic:
                    w_i = w_i + off
                return (w_s, w_i, tab_s, tab_i, nexh_next, ndim_next,
                        gany_next, ok_next)

            def skip(sl):
                return (jnp.full((Gp, TKl), NEG_INF, jnp.float32),
                        jnp.zeros((Gp, TKl), jnp.int32),
                        jnp.full(sl.tb_s.shape, NEG_INF, jnp.float32),
                        jnp.zeros(sl.tb_i.shape, jnp.int32),
                        sl.nexh, sl.ndim, jnp.zeros(Gp, bool),
                        jnp.bool_(False))

            if lane_axis is not None:
                # same lane-uniform trick as the carried/full dispatch:
                # rerank when ANY lane wants it (the result is gated
                # per-lane by `pre_ok & sl_ok` below — a lane that
                # reranked on a void premise keeps ok=False and its
                # next wave runs the full pass, which rebuilds the
                # window from scratch before anything reads it)
                do_rerank = lax.psum(
                    jnp.int32(pre_ok), lane_axis) > jnp.int32(0)
            else:
                do_rerank = pre_ok
            (nw_s, nw_i, ntb_s, ntb_i, n_nexh, n_ndim, n_gany,
             sl_ok) = lax.cond(do_rerank, rerank, skip, SL)
            SL = SL._replace(win_s=nw_s, win_i=nw_i, tb_s=ntb_s,
                             tb_i=ntb_i, nfeas=n_feas_g,
                             nexh=n_nexh, ndim=n_ndim, gany=n_gany,
                             ok=pre_ok & sl_ok)

        return (used, dev_used, sp_used, done,
                out_idx, out_ok, out_score, out_nfeas, out_nexh, out_dimexh,
                wave + jnp.int32(1), n_resc, SL, EVT, out_evict,
                out_wave)

    # Two loop shapes, chosen statically by the caller:
    #
    # "scan" (default) — fixed-trip scan whose body is skipped through
    # `lax.cond` once every placement is decided.  In unbatched context
    # the cond lowers to a real branch, so drained waves cost only the
    # (compact) carry; the wave budget can be generous.
    #
    # "while" — `lax.while_loop` with the same condition.  Under a vmap
    # (the federated region-stacked solve) `lax.cond` degrades to
    # `select` and BOTH branches execute every wave for every lane, so
    # the scan shape pays the full budget; a while_loop instead runs
    # until every lane drains — the trip count is the max actual
    # convergence depth, evaluated ON DEVICE (no host sync per
    # iteration, the loop is one uninterrupted device program).
    #
    # The rank-wrap commit above converges real batches in a handful of
    # waves either way; anything still unfinished after max_waves is
    # reported in `unfinished` and flows into the system's blocked-eval
    # retry path.
    st0 = (used0, dev_used0, sp_used0,
           jnp.zeros(K, bool),
           jnp.zeros((K, TOP_K), jnp.int32),
           jnp.zeros((K, TOP_K), bool),
           jnp.full((K, TOP_K), NEG_INF, jnp.float32),
           jnp.zeros(K, jnp.int32),
           jnp.zeros(K, jnp.int32),
           jnp.zeros((K, R), jnp.int32),
           jnp.int32(0), jnp.int32(0), sl0,
           (jnp.zeros((Np, EV), bool) if has_preempt
            else jnp.zeros((1, 1), bool)),
           (jnp.zeros((K, EV), bool) if has_preempt
            else jnp.zeros((K, 1), bool)),
           jnp.full(K, -1, jnp.int32))
    if wave_mode == "while":
        def w_cond(st):
            return ((~st[3] & (ks < n_place)).any()
                    & (st[10] < jnp.int32(max_waves)))

        st_final = lax.while_loop(w_cond, body, st0)
    else:
        def body_scan(st, _):
            any_active = (~st[3] & (ks < n_place)).any()
            return lax.cond(any_active, body, lambda s: s, st), None

        (st_final, _) = lax.scan(body_scan, st0, None, length=max_waves)
    (used_final, dev_used_final, _, done, out_idx, out_ok, out_score,
     out_nfeas, out_nexh, out_dimexh, waves, n_resc, _,
     _, out_evict_f, out_wave_f) = st_final
    unfinished = ~done & (ks < n_place)
    if in_mesh:
        # per-shard full-pass count summed over the mesh: the HBM byte
        # model multiplies bytes_wave1 (a PER-SHARD plane walk) by this
        n_resc = (_psum_mesh(n_resc) if use_sl
                  else waves * jnp.int32(mesh_shards))

    return SolveResult(choice=out_idx, choice_ok=out_ok, score=out_score,
                       n_feasible=out_nfeas, n_exhausted=out_nexh,
                       dim_exhausted=out_dimexh, feas=feas,
                       cons_filtered=cons_filtered, used_final=used_final,
                       dev_used_final=dev_used_final, n_waves=waves,
                       unfinished=unfinished,
                       n_rescore=(n_resc if (use_sl or in_mesh)
                                  else waves),
                       evict=(out_evict_f if has_preempt else None),
                       commit_wave=(out_wave_f if has_preempt
                                    else None))
