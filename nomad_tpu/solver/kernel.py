"""The jitted placement solve.

Replaces the reference's per-placement iterator chain
(scheduler/stack.go:107 Select -> feasible.go checks -> rank.go scoring ->
select.go limit/max) with dense tensor math over the full node axis:

  static feasibility mask  [G, N]   (constraints, dc, host-evaluated ops)
  wave loop: batched [G, N] scoring -> per-group top-k -> parallel commit

Wave semantics (the TPU recast of in-plan visibility,
scheduler/context.go:120 ProposedAllocs): instead of committing one
placement per step, every wave

  1. scores all (group, node) pairs against current usage in one batched
     pass — the MXU-friendly shape,
  2. ranks each group's remaining placements and assigns the r-th one to
     the group's r-th best node (top-k), so same-group placements fan out
     across nodes exactly as the reference's job anti-affinity pressure
     (rank.go:462) makes them do one step at a time,
  3. commits every assignment that survives cross-group conflict checks:
     cumulative capacity on shared nodes (segment-sum by node),
     first-per-(node, distinct-group) for distinct_hosts, and a spread
     quota per (group, value) so targeted/even spread cannot be
     overfilled inside a single wave (spread.go semantics),
  4. placements that lose a conflict simply retry next wave against
     refreshed usage.

Every committed placement's capacity is checked against the usage its
wave started from plus all earlier same-wave commits on the node, so no
node ever oversubscribes.  A batch of K placements converges in
O(K / WAVE_K) waves instead of K serial scan steps; each wave is one
fused XLA program over [G, N] tensors.

Scores follow the reference's conditional-append-then-average
normalization (rank.go:667).  Where the reference subsamples nodes
(limit = max(2, log2 N), scheduler/stack.go:80-87), this solve scores
every node — strictly better placements at far higher eval throughput.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .tensorize import (OP_EQ, OP_GE, OP_GT, OP_IS_SET, OP_LE, OP_LT, OP_NE,
                        OP_NONE, OP_NOT_SET, R_CPU, R_MEM)

TOP_K = 4
WAVE_K = 32       # min per-group wave width; scales up with batch size
NEG_INF = -1e30


def _op_eval(vals: jnp.ndarray, op: jnp.ndarray, rank: jnp.ndarray
             ) -> jnp.ndarray:
    """Evaluate vectorizable constraint ops.

    vals: [N, C] node value ranks (-1 missing); op/rank: [C].
    Semantics mirror scheduler/feasible.go:671 checkConstraint — note `!=`
    passes when the attribute is missing.
    """
    found = vals >= 0
    eq = found & (vals == rank[None, :])
    res = jnp.ones_like(found)
    res = jnp.where(op[None, :] == OP_EQ, eq, res)
    res = jnp.where(op[None, :] == OP_NE, ~eq, res)
    res = jnp.where(op[None, :] == OP_LT, found & (vals < rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_LE, found & (vals <= rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_GT, found & (vals > rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_GE, found & (vals >= rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_IS_SET, found, res)
    res = jnp.where(op[None, :] == OP_NOT_SET, ~found, res)
    return res


class SolveResult(NamedTuple):
    choice: jnp.ndarray        # [K, TOP_K] node indices, best first
    choice_ok: jnp.ndarray     # [K, TOP_K] bool (feasible + fits)
    score: jnp.ndarray         # [K, TOP_K] final normalized scores
    n_feasible: jnp.ndarray    # [K] feasible node count at commit wave
    n_exhausted: jnp.ndarray   # [K] feasible but resource-exhausted
    dim_exhausted: jnp.ndarray  # [K, R] counts per exhausted dimension
    feas: jnp.ndarray          # [G, N] static feasibility mask
    cons_filtered: jnp.ndarray  # [G, C] nodes filtered per constraint slot
    used_final: jnp.ndarray    # [N, R] resource usage after all commits
    dev_used_final: jnp.ndarray  # [N, D] device usage after all commits


@functools.partial(jax.jit, static_argnames=())
def solve_kernel(avail, reserved, used0, valid, node_dc, attr_rank,
                 ask_res, ask_desired, distinct, dc_ok, host_ok, coll0,
                 penalty,
                 c_op, c_col, c_rank, a_op, a_col, a_rank, a_weight, a_host,
                 sp_col, sp_weight, sp_targeted, sp_desired, sp_implicit,
                 sp_used0, dev_cap, dev_used0, dev_ask, p_ask, n_place
                 ) -> SolveResult:
    Np = avail.shape[0]
    Gp = ask_res.shape[0]
    S = sp_col.shape[1]
    R = avail.shape[1]
    K = p_ask.shape[0]
    # wider waves for bigger batches: a group may commit up to W
    # placements per wave, so a K-placement batch converges in O(K / W)
    # fused-wave iterations
    TK = min(max(WAVE_K, K // 8) + TOP_K, Np)
    W = max(TK - TOP_K, 1)          # effective per-group wave width
    ks = jnp.arange(K)
    gs = jnp.arange(Gp)

    # ---------- static feasibility [Gp, Np] ----------
    def per_ask_feas(g):
        vals = attr_rank[:, c_col[g]]                      # [Np, C]
        ok = _op_eval(vals, c_op[g], c_rank[g])            # [Np, C]
        base = valid & dc_ok[g][node_dc] & host_ok[g]      # [Np]
        # per-constraint filtered counts with sequential (first-fail) credit
        passed_prev = jnp.cumprod(
            jnp.concatenate([jnp.ones((Np, 1), bool), ok[:, :-1]], axis=1),
            axis=1).astype(bool)
        first_fail = base[:, None] & passed_prev & ~ok
        filtered = first_fail.sum(axis=0)                  # [C]
        return base & ok.all(axis=1), filtered

    feas, cons_filtered = lax.map(per_ask_feas, gs)

    # affinity matches are also placement-invariant: [Gp, Np]
    def per_ask_aff(g):
        vals = attr_rank[:, a_col[g]]                      # [Np, CA]
        match = _op_eval(vals, a_op[g], a_rank[g])
        return (match * a_weight[g][None, :]).sum(axis=1)  # [Np]

    aff_score = lax.map(per_ask_aff, gs) + a_host
    pen_score = jnp.where(penalty, -1.0, 0.0)              # rank.go:532
    pen_counts = penalty

    def group_scores(used, dev_used, coll, sp_used, blocked):
        """Batched scoring of every (group, node) pair against current
        usage — one instance of the reference's rank pipeline, [Gp, Np]."""
        after = used[None, :, :] + ask_res[:, None, :]     # [Gp, Np, R]
        fit_dims = after <= avail[None, :, :]
        fit = fit_dims.all(axis=-1)
        dev_fit = (dev_used[None, :, :] + dev_ask[:, None, :]
                   <= dev_cap[None, :, :]).all(axis=-1)
        feas_b = feas & ~blocked
        placeable = feas_b & fit & dev_fit

        # -- binpack (funcs.go:155 ScoreFit, normalized rank.go:441) --
        denom_cpu = avail[None, :, R_CPU]
        denom_mem = avail[None, :, R_MEM]
        util_cpu = after[:, :, R_CPU] + reserved[None, :, R_CPU]
        util_mem = after[:, :, R_MEM] + reserved[None, :, R_MEM]
        ok_denoms = (denom_cpu > 0) & (denom_mem > 0)
        free_cpu = 1.0 - util_cpu / jnp.maximum(denom_cpu, 1.0)
        free_mem = 1.0 - util_mem / jnp.maximum(denom_mem, 1.0)
        raw = 20.0 - (10.0 ** free_cpu + 10.0 ** free_mem)
        binpack = jnp.where(ok_denoms,
                            jnp.clip(raw, 0.0, 18.0) / 18.0, 0.0)

        # -- job anti-affinity (rank.go:462) --
        anti = jnp.where(coll > 0,
                         -(coll + 1.0) / ask_desired[:, None], 0.0)
        anti_counts = coll > 0

        # -- spread (spread.go; append-if-nonzero) --
        def one_spread(s):
            col = sp_col[:, s]                             # [Gp]
            has = col >= 0
            v = attr_rank[:, jnp.maximum(col, 0)].T        # [Gp, Np]
            has_v = v >= 0
            vc = jnp.maximum(v, 0)
            used_vec = sp_used[:, s]                       # [Gp, V]
            cur = jnp.where(has_v,
                            jnp.take_along_axis(used_vec, vc, axis=1), 0.0)
            # targeted scoring (desired counts, +1 for this placement)
            desired = jnp.where(
                has_v, jnp.take_along_axis(sp_desired[:, s], vc, axis=1),
                -1.0)
            desired = jnp.where(desired < 0, sp_implicit[:, s][:, None],
                                desired)
            boost = ((desired - (cur + 1.0)) / jnp.maximum(desired, 1e-9)
                     ) * sp_weight[:, s][:, None]
            targeted = jnp.where(~has_v, -1.0,
                                 jnp.where(desired <= 0, -1.0, boost))
            # even-spread scoring (spread.go evenSpreadScoreBoost)
            present = used_vec > 0                         # [Gp, V]
            any_present = present.any(axis=1)[:, None]
            minc = jnp.min(jnp.where(present, used_vec, jnp.inf),
                           axis=1)[:, None]
            maxc = jnp.max(jnp.where(present, used_vec, -jnp.inf),
                           axis=1)[:, None]
            delta_boost = (minc - cur) / jnp.maximum(minc, 1e-9)
            even = jnp.where(cur != minc, delta_boost,
                             jnp.where(minc == maxc, -1.0,
                                       (maxc - minc) / jnp.maximum(minc,
                                                                   1e-9)))
            even = jnp.where(~has_v, -1.0, even)
            even = jnp.where(any_present, even, 0.0)
            contrib = jnp.where(sp_targeted[:, s][:, None], targeted, even)
            return jnp.where(has[:, None], contrib, 0.0)

        sp_scores = lax.map(one_spread, jnp.arange(S))     # [S, Gp, Np]
        spread_total = sp_scores.sum(axis=0)
        spread_counts = spread_total != 0.0

        aff_counts = aff_score != 0.0
        # -- normalization: mean over appended scorers (rank.go:667) --
        n_scorers = (1.0 + anti_counts + pen_counts + aff_counts
                     + spread_counts)
        total = (binpack + anti + pen_score + aff_score
                 + spread_total) / n_scorers
        score = jnp.where(placeable, total, NEG_INF)
        return score, placeable, feas_b, fit, fit_dims, dev_fit

    # ---------- wave loop ----------
    def cond(st):
        (_, _, _, _, _, done, _, _, _, _, _, _, wave) = st
        return ((~done & (ks < n_place)).any()) & (wave < K + 1)

    def body(st):
        (used, dev_used, coll, sp_used, blocked, done,
         out_idx, out_ok, out_score, out_nfeas, out_nexh, out_dimexh,
         wave) = st
        active = ~done & (ks < n_place)

        score, placeable, feas_b, fit, fit_dims, dev_fit = group_scores(
            used, dev_used, coll, sp_used, blocked)
        top_score, top_idx = lax.top_k(score, TK)          # [Gp, TK]
        grp_any = placeable.any(axis=1)                    # [Gp]

        # metrics snapshot for placements finishing this wave
        n_feas_g = (feas_b & valid[None, :]).sum(axis=1)
        n_exh_g = (feas_b & valid[None, :] & ~(fit & dev_fit)).sum(axis=1)
        dim_exh_g = (feas_b[:, :, None] & valid[None, :, None]
                     & ~fit_dims).sum(axis=1)              # [Gp, R]

        # rank each active placement within its group; the r-th remaining
        # placement is assigned the group's r-th best node this wave
        g_idx = p_ask
        grp_onehot = ((g_idx[None, :] == gs[:, None])
                      & active[None, :]).astype(jnp.int32)  # [Gp, K]
        rank = (jnp.cumsum(grp_onehot, axis=1)
                - grp_onehot)[g_idx, ks]                   # exclusive count
        in_wave = active & (rank < W)
        cr = jnp.minimum(rank, W - 1)
        cand = top_idx[g_idx, cr]                          # [K]
        cand_score = top_score[g_idx, cr]
        cand_ok = in_wave & (cand_score > NEG_INF / 2)

        # a group with nothing placeable fails all its remaining placements
        fail_now = active & ~grp_any[g_idx]

        # -- cross-group conflict checks over shared nodes --
        earlier = ks[None, :] < ks[:, None]                # [K, K]
        both_ok = cand_ok[None, :] & cand_ok[:, None]
        same_node = (cand[None, :] == cand[:, None]) & both_ok & earlier
        res_k = ask_res[g_idx] * cand_ok[:, None]
        dev_k = dev_ask[g_idx] * cand_ok[:, None]
        prior = same_node.astype(jnp.float32) @ res_k      # [K, R]
        prior_dev = same_node.astype(jnp.float32) @ dev_k  # [K, D]
        fits = ((used[cand] + prior + ask_res[g_idx])
                <= avail[cand]).all(axis=-1)
        dev_fits = ((dev_used[cand] + prior_dev + dev_ask[g_idx])
                    <= dev_cap[cand]).all(axis=-1)

        # distinct_hosts: one commit per (node, distinct group) per wave;
        # cross-wave blocking below keeps later waves off the node too
        dg = distinct[g_idx]
        same_dg = same_node & (dg[None, :] == dg[:, None]) & (dg[:, None] >= 0)
        dg_ok = ~same_dg.any(axis=1)

        # spread quota: cap same-wave commits per (group, slot, value) so a
        # wave cannot overfill a spread target the serial reference would
        # have steered away from (S is a small static pad; unrolled)
        same_g = both_ok & earlier & (g_idx[None, :] == g_idx[:, None])
        sp_ok = jnp.ones(K, bool)
        for s in range(S):
            cols = sp_col[g_idx, s]
            vs = attr_rank[cand, jnp.maximum(cols, 0)]
            has_s = (cols >= 0) & (vs >= 0)
            vsc = jnp.maximum(vs, 0)
            des_s = sp_desired[:, s]                       # [Gp, V]
            use_s = sp_used[:, s]
            des_eff = jnp.where(des_s < 0, sp_implicit[:, s][:, None],
                                des_s)
            present = use_s > 0
            maxc = jnp.max(jnp.where(present, use_s, 0.0),
                           axis=1)[:, None]
            quota = jnp.where(sp_targeted[:, s][:, None],
                              jnp.maximum(1.0, des_eff - use_s),
                              jnp.maximum(1.0, maxc - use_s))  # [Gp, V]
            same_gv = (same_g & (vs[None, :] == vs[:, None])
                       & has_s[:, None] & has_s[None, :])
            gv_rank = same_gv.sum(axis=1).astype(jnp.float32)
            sp_ok &= ~has_s | (gv_rank < quota[g_idx, vsc])

        commit = cand_ok & fits & dev_fits & dg_ok & sp_ok
        cm = commit[:, None]

        # -- apply all of this wave's commits at once --
        used = used.at[cand].add(ask_res[g_idx] * cm)
        dev_used = dev_used.at[cand].add(dev_ask[g_idx] * cm)
        coll = coll.at[g_idx, cand].add(commit.astype(jnp.float32))
        hit = jnp.zeros((Gp, Np), jnp.int32).at[
            jnp.maximum(dg, 0), cand].add(
            (commit & (dg >= 0)).astype(jnp.int32)) > 0
        blocked = blocked | (hit[jnp.maximum(distinct, 0)]
                             & (distinct >= 0)[:, None])
        svals = attr_rank[cand[:, None], jnp.maximum(sp_col[g_idx], 0)]
        okslot = (sp_col[g_idx] >= 0) & (svals >= 0) & cm
        sp_used = sp_used.at[g_idx[:, None], jnp.arange(S)[None, :],
                             jnp.maximum(svals, 0)].add(
            okslot.astype(jnp.float32))

        # -- record results: a committed placement's fall-through top-K is
        # its group's candidate list starting at its own rank --
        offs = cr[:, None] + jnp.arange(TOP_K)[None, :]    # < TK by constr.
        pk_idx = top_idx[g_idx[:, None], offs]
        pk_score = top_score[g_idx[:, None], offs]
        pk_ok = pk_score > NEG_INF / 2
        newly = commit | fail_now
        upd = newly[:, None]
        out_idx = jnp.where(upd, pk_idx, out_idx)
        out_score = jnp.where(upd, pk_score, out_score)
        out_ok = jnp.where(upd, pk_ok & cm, out_ok)
        out_nfeas = jnp.where(newly, n_feas_g[g_idx], out_nfeas)
        out_nexh = jnp.where(newly, n_exh_g[g_idx], out_nexh)
        out_dimexh = jnp.where(newly[:, None], dim_exh_g[g_idx], out_dimexh)
        done = done | newly
        return (used, dev_used, coll, sp_used, blocked, done,
                out_idx, out_ok, out_score, out_nfeas, out_nexh, out_dimexh,
                wave + 1)

    st0 = (used0, dev_used0, coll0, sp_used0,
           jnp.zeros((Gp, Np), bool),
           jnp.zeros(K, bool),
           jnp.zeros((K, TOP_K), jnp.int32),
           jnp.zeros((K, TOP_K), bool),
           jnp.full((K, TOP_K), NEG_INF, jnp.float32),
           jnp.zeros(K, jnp.int32),
           jnp.zeros(K, jnp.int32),
           jnp.zeros((K, R), jnp.int32),
           jnp.int32(0))
    (used_final, dev_used_final, _, _, _, _, out_idx, out_ok, out_score,
     out_nfeas, out_nexh, out_dimexh, _) = lax.while_loop(cond, body, st0)

    return SolveResult(choice=out_idx, choice_ok=out_ok, score=out_score,
                       n_feasible=out_nfeas, n_exhausted=out_nexh,
                       dim_exhausted=out_dimexh, feas=feas,
                       cons_filtered=cons_filtered, used_final=used_final,
                       dev_used_final=dev_used_final)
