"""The jitted placement solve.

Replaces the reference's per-placement iterator chain
(scheduler/stack.go:107 Select -> feasible.go checks -> rank.go scoring ->
select.go limit/max) with dense tensor math over the full node axis:

  static feasibility mask  [G, N]   (constraints, dc, host-evaluated ops)
  `lax.scan` over placements: fit-check + score + masked top-k + commit

The scan is the equivalent of the reference's in-plan visibility
(scheduler/context.go:120 ProposedAllocs): each placement sees all resources
committed by earlier placements in the batch. Scores follow the reference's
conditional-append-then-average normalization (rank.go:667).

Where the reference subsamples nodes (limit = max(2, log2 N),
scheduler/stack.go:80-87), this solve scores every node — strictly better
placements at far higher eval throughput.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .tensorize import (OP_EQ, OP_GE, OP_GT, OP_IS_SET, OP_LE, OP_LT, OP_NE,
                        OP_NONE, OP_NOT_SET, R_CPU, R_MEM)

TOP_K = 4
NEG_INF = -1e30


def _op_eval(vals: jnp.ndarray, op: jnp.ndarray, rank: jnp.ndarray
             ) -> jnp.ndarray:
    """Evaluate vectorizable constraint ops.

    vals: [N, C] node value ranks (-1 missing); op/rank: [C].
    Semantics mirror scheduler/feasible.go:671 checkConstraint — note `!=`
    passes when the attribute is missing.
    """
    found = vals >= 0
    eq = found & (vals == rank[None, :])
    res = jnp.ones_like(found)
    res = jnp.where(op[None, :] == OP_EQ, eq, res)
    res = jnp.where(op[None, :] == OP_NE, ~eq, res)
    res = jnp.where(op[None, :] == OP_LT, found & (vals < rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_LE, found & (vals <= rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_GT, found & (vals > rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_GE, found & (vals >= rank[None, :]), res)
    res = jnp.where(op[None, :] == OP_IS_SET, found, res)
    res = jnp.where(op[None, :] == OP_NOT_SET, ~found, res)
    return res


class SolveResult(NamedTuple):
    choice: jnp.ndarray        # [K, TOP_K] node indices, best first
    choice_ok: jnp.ndarray     # [K, TOP_K] bool (feasible + fits)
    score: jnp.ndarray         # [K, TOP_K] final normalized scores
    n_feasible: jnp.ndarray    # [K] feasible node count at step
    n_exhausted: jnp.ndarray   # [K] feasible but resource-exhausted
    dim_exhausted: jnp.ndarray  # [K, R] counts per exhausted dimension
    feas: jnp.ndarray          # [G, N] static feasibility mask
    cons_filtered: jnp.ndarray  # [G, C] nodes filtered per constraint slot
    used_final: jnp.ndarray    # [N, R] resource usage after all commits


@functools.partial(jax.jit, static_argnames=())
def solve_kernel(avail, reserved, used0, valid, node_dc, attr_rank,
                 ask_res, ask_desired, distinct, dc_ok, host_ok, coll0,
                 penalty,
                 c_op, c_col, c_rank, a_op, a_col, a_rank, a_weight, a_host,
                 sp_col, sp_weight, sp_targeted, sp_desired, sp_implicit,
                 sp_used0, dev_cap, dev_used0, dev_ask, p_ask, n_place
                 ) -> SolveResult:
    Np = avail.shape[0]
    Gp = ask_res.shape[0]
    C = c_op.shape[1]
    K = p_ask.shape[0]

    # ---------- static feasibility [Gp, Np] ----------
    def per_ask_feas(g):
        vals = attr_rank[:, c_col[g]]                      # [Np, C]
        ok = _op_eval(vals, c_op[g], c_rank[g])            # [Np, C]
        base = valid & dc_ok[g][node_dc] & host_ok[g]      # [Np]
        # per-constraint filtered counts with sequential (first-fail) credit
        passed_prev = jnp.cumprod(
            jnp.concatenate([jnp.ones((Np, 1), bool), ok[:, :-1]], axis=1),
            axis=1).astype(bool)
        first_fail = base[:, None] & passed_prev & ~ok
        filtered = first_fail.sum(axis=0)                  # [C]
        return base & ok.all(axis=1), filtered

    feas, cons_filtered = lax.map(per_ask_feas, jnp.arange(Gp))

    # affinity matches are also placement-invariant: [Gp, Np]
    def per_ask_aff(g):
        vals = attr_rank[:, a_col[g]]                      # [Np, CA]
        match = _op_eval(vals, a_op[g], a_rank[g])
        return (match * a_weight[g][None, :]).sum(axis=1)  # [Np]

    aff_score = lax.map(per_ask_aff, jnp.arange(Gp)) + a_host

    # ---------- placement scan ----------
    def step(carry, p):
        used, dev_used, coll, sp_used, blocked = carry
        g = p_ask[p]
        active = p < n_place
        res_g = ask_res[g]

        after = used + res_g[None, :]                      # [Np, R]
        fit_dims = after <= avail                          # [Np, R]
        fit = fit_dims.all(axis=1)
        dev_after = dev_used + dev_ask[g][None, :]
        dev_fit = (dev_after <= dev_cap).all(axis=1)

        feas_g = feas[g] & ~blocked[g]
        placeable = feas_g & fit & dev_fit

        # -- binpack (funcs.go:155 ScoreFit, normalized rank.go:441) --
        denom_cpu = avail[:, R_CPU]
        denom_mem = avail[:, R_MEM]
        util_cpu = after[:, R_CPU] + reserved[:, R_CPU]
        util_mem = after[:, R_MEM] + reserved[:, R_MEM]
        ok_denoms = (denom_cpu > 0) & (denom_mem > 0)
        free_cpu = 1.0 - util_cpu / jnp.maximum(denom_cpu, 1.0)
        free_mem = 1.0 - util_mem / jnp.maximum(denom_mem, 1.0)
        raw = 20.0 - (10.0 ** free_cpu + 10.0 ** free_mem)
        binpack = jnp.where(ok_denoms,
                            jnp.clip(raw, 0.0, 18.0) / 18.0, 0.0)

        # -- job anti-affinity (rank.go:462) --
        collg = coll[g]
        anti = jnp.where(collg > 0, -(collg + 1.0) / ask_desired[g], 0.0)
        anti_counts = collg > 0

        # -- node reschedule penalty (rank.go:532) --
        pen = jnp.where(penalty[g], -1.0, 0.0)
        pen_counts = penalty[g]

        # -- node affinity (rank.go:577; append-if-nonzero) --
        affg = aff_score[g]
        aff_counts = affg != 0.0

        # -- spread (spread.go; append-if-nonzero) --
        def one_spread(s):
            col = sp_col[g, s]
            has = col >= 0
            v = attr_rank[:, jnp.maximum(col, 0)]          # [Np]
            has_v = v >= 0
            vc = jnp.maximum(v, 0)
            used_vec = sp_used[g, s]                       # [V]
            cur = jnp.where(has_v, used_vec[vc], 0.0)
            # targeted scoring (desired counts, +1 for this placement)
            desired = jnp.where(has_v, sp_desired[g, s, vc], -1.0)
            desired = jnp.where(desired < 0, sp_implicit[g, s], desired)
            boost = ((desired - (cur + 1.0)) / jnp.maximum(desired, 1e-9)
                     ) * sp_weight[g, s]
            targeted = jnp.where(~has_v, -1.0,
                                 jnp.where(desired <= 0, -1.0, boost))
            # even-spread scoring (spread.go evenSpreadScoreBoost)
            present = used_vec > 0
            any_present = present.any()
            minc = jnp.min(jnp.where(present, used_vec, jnp.inf))
            maxc = jnp.max(jnp.where(present, used_vec, -jnp.inf))
            delta_boost = (minc - cur) / jnp.maximum(minc, 1e-9)
            even = jnp.where(cur != minc, delta_boost,
                             jnp.where(minc == maxc, -1.0,
                                       (maxc - minc) / jnp.maximum(minc, 1e-9)))
            even = jnp.where(~has_v, -1.0, even)
            even = jnp.where(any_present, even, 0.0)
            contrib = jnp.where(sp_targeted[g, s], targeted, even)
            return jnp.where(has, contrib, 0.0)

        S = sp_col.shape[1]
        sp_scores = lax.map(one_spread, jnp.arange(S))     # [S, Np]
        spread_total = sp_scores.sum(axis=0)
        spread_counts = spread_total != 0.0

        # -- normalization: mean over appended scorers (rank.go:667) --
        n_scorers = (1.0 + anti_counts + pen_counts + aff_counts
                     + spread_counts)
        total = (binpack + anti + pen + affg + spread_total) / n_scorers
        score = jnp.where(placeable, total, NEG_INF)

        top_score, top_idx = lax.top_k(score, TOP_K)
        top_ok = (top_score > NEG_INF / 2) & active
        choice = top_idx[0]
        ok = top_ok[0]

        # -- commit the winner --
        add = jnp.where(ok, 1.0, 0.0)
        used = used.at[choice].add(res_g * add)
        dev_used = dev_used.at[choice].add(dev_ask[g] * add)
        coll = coll.at[g, choice].add(add)
        # distinct_hosts: later placements of any ask sharing this ask's
        # distinct group (same job for job-level constraints) skip the node
        same_grp = (distinct == distinct[g]) & (distinct[g] >= 0)   # [Gp]
        hit = (jnp.arange(Np) == choice) & ok                       # [Np]
        blocked = blocked | (same_grp[:, None] & hit[None, :])
        # spread usage: bump the chosen node's value per spread slot
        ch_vals = attr_rank[choice, jnp.maximum(sp_col[g], 0)]   # [S]
        valid_slot = (sp_col[g] >= 0) & (ch_vals >= 0)
        sp_used = sp_used.at[g, jnp.arange(S),
                             jnp.maximum(ch_vals, 0)].add(
            jnp.where(valid_slot, add, 0.0))

        n_feas = (feas_g & valid).sum()
        n_exh = (feas_g & valid & ~(fit & dev_fit)).sum()
        dim_exh = (feas_g[:, None] & valid[:, None] & ~fit_dims).sum(axis=0)

        return ((used, dev_used, coll, sp_used, blocked),
                (top_idx, top_ok, top_score, n_feas, n_exh, dim_exh))

    init = (used0, dev_used0, coll0, sp_used0,
            jnp.zeros((Gp, Np), bool))
    (used_final, _, _, _, _), outs = lax.scan(init=init, xs=jnp.arange(K),
                                              f=step)
    top_idx, top_ok, top_score, n_feas, n_exh, dim_exh = outs

    return SolveResult(choice=top_idx, choice_ok=top_ok, score=top_score,
                       n_feasible=n_feas, n_exhausted=n_exh,
                       dim_exhausted=dim_exh, feas=feas,
                       cons_filtered=cons_filtered, used_final=used_final)
