"""Pallas-tiled fused wave scoring for the placement solve.

The wave kernel's per-iteration cost is pure HBM traffic: the jnp
implementation (`kernel.group_scores`) walks the [Gp, Np] plane half a
dozen times per wave (fit/after/binpack/anti/spread/normalize/select)
and materializes [Gp, Np, R] broadcast intermediates between passes —
`BENCH_DETAIL.json` device_ceiling puts the measured solve far above
its own bytes/bandwidth floor.  This module fuses the whole scoring
chain into ONE pass per node tile resident in VMEM:

  for each tile of T nodes (grid axis):
      load the tile's static planes (feasibility, affinity+penalty
      score, scorer counts, jitter) and dynamic planes (usage,
      collocation, distinct-blocking) into VMEM once;
      compute feasibility ∧ fit ∧ device-fit, bin-pack, anti-affinity,
      spread (targeted + even), append-then-average normalization,
      seeded binning+jitter — all on VMEM-resident values;
      reduce the per-group explainability counters for the tile;
      EITHER write the tile's score row back (mode "score": one
      [Gp, Np] store total, the only HBM write of the wave)
      OR extract the tile's top-K partial in-kernel (mode "topk":
      nothing but [Gp, tiles*TKt] partials ever reaches HBM — the
      [G, N] wave never materializes at all).

Per-tile top-K partials merge with one small `lax.top_k` over
[Gp, tiles*TKt] outside the kernel; the tournament is EXACT: a row's
global top-K is a subset of the per-tile top-Ks, per-tile extraction
breaks ties low-index-first (same as `lax.top_k`), and tiles
concatenate in node order, so equal scores resolve in global node
order — bitwise the same selection the unfused kernel makes.  The
same-wave conflict commit then runs on the compacted [K] candidate
set exactly as before (kernel.py), so placements are identical by
construction; tests/test_pallas_kernel.py property-tests the full
solve against the `host.py` exact twin in interpreter mode on CPU.

Mode selection is static (trace-time): "topk" when the candidate
window is small enough for iterative in-VMEM extraction, "score"
otherwise (merged throughput batches with 1024-wide windows keep
`approx_max_k` on the fused score), "off" when shapes/features fall
outside the fused universe.  On CPU the kernel runs in pallas
interpreter mode — same semantics, no Mosaic — which is what tier-1
exercises; on TPU `available()` compile-probes a representative kernel
once and disables the path rather than let a Mosaic regression take
the scheduler down.

Two shortlist-era (ISSUE 4) extensions:

  * the boolean planes (feasibility, penalty, distinct-blocking)
    arrive BITPACKED — uint32 words of 32 node columns
    (masks.pack_bool_u32) — and unpack per tile inside the kernel, so
    the static masks cost 1/8th of their int8 bytes on every full
    wave's HBM re-read;
  * `n_extract` decouples the in-kernel extraction width from the
    candidate window TK: the full wave extracts the top-C shortlist
    (C >= TK) in one pass, the caller windows the first TK and carries
    the rest for shortlist-resident contention waves (kernel.py).
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:                               # TPU memory spaces (absent on some
    from jax.experimental.pallas import tpu as pltpu  # cpu-only builds)
except ImportError:                # pragma: no cover
    pltpu = None

NEG_INF = -1e30
#: sentinel strictly below NEG_INF: masks already-extracted slots so
#: the next iterative extraction never re-picks them, while untouched
#: NEG_INF (infeasible) entries still extract in node order like
#: lax.top_k would return them
_EXTRACTED = -2e30
SCORE_BIN = 0.05
#: largest candidate window the in-kernel iterative extraction serves;
#: wider windows (merged throughput batches) use mode "score"
TOPK_MAX = 256
#: per-tile VMEM working-set budget, in [Gp, T] f32-plane elements
_TILE_ELEMS = 1 << 18
#: spread value-vocabulary cap for the unrolled select-sum
_V_MAX = 16

_R_CPU, _R_MEM = 0, 1


def _env_mode() -> str:
    """NOMAD_TPU_PALLAS: '1'/'interpret' force-enables (interpreted on
    CPU), '0' disables, unset = auto (on only for TPU backends)."""
    return os.environ.get("NOMAD_TPU_PALLAS", "").strip().lower()


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=1)
def enabled() -> bool:
    env = _env_mode()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true", "interpret"):
        return True
    return jax.default_backend() == "tpu" and available()


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """Compile-probe a representative fused kernel once: a Mosaic
    lowering failure downgrades the solver to the unfused path instead
    of crashing the scheduler."""
    try:
        import numpy as np
        from .masks import pack_bool_u32
        Gp, Np, R, S, V, D = 2, 256, 4, 1, 4, 2
        out = fused_wave(
            mode="topk",
            feas=pack_bool_u32(jnp.ones((Gp, Np), bool)),
            blocked=pack_bool_u32(jnp.zeros((Gp, Np), bool)),
            aff=jnp.zeros((Gp, Np), jnp.float32),
            pen=pack_bool_u32(jnp.zeros((Gp, Np), bool)),
            jitter=jnp.zeros((Gp, Np), jnp.float32),
            coll=jnp.zeros((Gp, Np), jnp.float32),
            used=jnp.zeros((Np, R), jnp.float32),
            avail=jnp.ones((Np, R), jnp.float32) * 100,
            reserved=jnp.zeros((Np, R), jnp.float32),
            ask_res=jnp.ones((Gp, R), jnp.float32),
            ask_desired=jnp.ones((Gp,), jnp.float32),
            dev=(jnp.zeros((Np, D), jnp.float32),
                 jnp.ones((Np, D), jnp.float32),
                 jnp.zeros((Gp, D), jnp.float32)),
            spread=(jnp.zeros((S, Gp, Np), jnp.int32),
                    jnp.ones((S, Gp, Np), jnp.float32),
                    jnp.zeros((Gp, S, V), jnp.float32),
                    jnp.ones((Gp, S), jnp.float32),
                    jnp.zeros((Gp, S), jnp.bool_),
                    jnp.zeros((Gp, S), jnp.int8),
                    jnp.zeros((Gp, S), jnp.float32),
                    jnp.zeros((Gp, S), jnp.float32),
                    jnp.zeros((Gp, S), jnp.int8)),
            seed=jnp.int32(1), TK=8, tables_v=V)
        np.asarray(out["top_score"])
        return True
    except Exception:               # pragma: no cover - backend specific
        return False


def pick_tile(Np: int, Gp: int) -> int:
    """Node-tile width: largest lane-aligned divisor of Np whose
    [Gp, T] working set fits the VMEM budget.  Padded node counts are
    a power of two (<= 4096) or a multiple of 1024 (tensorize
    _pad_nodes), so a divisor always exists."""
    budget = max(_TILE_ELEMS // max(Gp, 1), 128)
    for t in (2048, 1024, 512, 256, 128):
        if Np % t == 0 and t <= budget:
            return t
    return Np                       # tiny pow2 problems: one tile


def resolve_mode(Np: int, Gp: int, TK: int, V: int,
                 has_spread: bool, enabled_hint: Optional[bool] = None
                 ) -> str:
    """Trace-time mode pick for solve_kernel (all args static)."""
    on = enabled() if enabled_hint is None else enabled_hint
    if not on:
        return "off"
    if has_spread and V > _V_MAX:
        return "off"                # select-sum unroll would explode
    T = pick_tile(Np, Gp)
    if Np % T != 0:
        return "off"
    if TK <= TOPK_MAX:
        return "topk"
    return "score"


def _specs(shape, tile_map, memory_space=None):
    kw = {}
    if pltpu is not None and not _interpret():
        kw["memory_space"] = memory_space or pltpu.VMEM
    return pl.BlockSpec(shape, tile_map, **kw)


def fused_wave(*, mode, feas, blocked, aff, pen, jitter,
               coll, used, avail, reserved, ask_res, ask_desired,
               dev=None, spread=None, seed=0, TK=4, n_extract=0,
               tables_v=0):
    """One fused pass over node tiles producing the wave's scoring
    outputs.  Returns a dict:

      mode "score": score [Gp, Np] f32, counters (see below)
      mode "topk":  top_score/top_idx [Gp, n_extract] (exact, merged
                    from per-tile partials; n_extract defaults to TK —
                    the shortlist path extracts top-C >= TK in the same
                    pass), counters, and when tables_v>0
                    tab_s/tab_i [Gp, tables_v+1, TKv] — the per-value
                    candidate tables for spread-aware interleaving
                    (TKv is derived from the WINDOW width TK, not
                    n_extract, so the interleave matches the unfused
                    kernel exactly).

    counters: n_feas [Gp] i32, n_exh [Gp] i32, grp_any [Gp] bool,
    dim_exh [Gp, R] i32 — the per-wave explainability reductions.

    All tensors use the caller's (kernel.py) layouts.  `feas`, `pen`
    and `blocked` arrive BITPACKED: uint32 words over the node axis
    (masks.pack_bool_u32), unpacked per tile in-kernel.  `spread` packs
    (sp_vnode [S,Gp,Np], sp_des [S,Gp,Np], sp_used [Gp,S,V],
    sp_weight [Gp,S], sp_targeted [Gp,S], sp_has [Gp,S] i8,
    minc [Gp,S], maxc [Gp,S], anyp [Gp,S] i8); `dev` packs
    (dev_used [Np,D], dev_cap [Np,D], dev_ask [Gp,D]).
    """
    Gp = feas.shape[0]
    Np, R = used.shape[0], used.shape[1]
    has_devices = dev is not None
    has_spread = spread is not None
    has_blocked = blocked is not None
    T = pick_tile(Np, Gp)
    n_tiles = Np // T
    # packed boolean planes: words per tile (T is a multiple of 32 for
    # every multi-tile layout; single-tile layouts take the whole —
    # possibly padded — word row)
    Tw = -(-T // 32) if n_tiles == 1 else T // 32
    NE = n_extract or TK
    TKt = min(NE, T)
    want_tables = mode == "topk" and tables_v > 0
    Vs = tables_v
    TKv = -(-TK // (Vs + 1)) if want_tables else 0
    TKvt = min(TKv, T) if want_tables else 0
    CNT = 3 + R

    if has_spread:
        (sp_vnode, sp_des, sp_used, sp_weight, sp_targeted, sp_has,
         minc, maxc, anyp) = spread
        S = sp_vnode.shape[0]
        V = sp_used.shape[2]
    else:
        S = V = 0
    if has_devices:
        dev_used, dev_cap, dev_ask = dev
        D = dev_cap.shape[1]
    else:
        D = 0

    # ---- assemble inputs + block specs (order matters: the kernel
    # unpacks positionally) ----
    gp_t = lambda i: (0, i)              # [Gp, Np] planes  # noqa: E731
    np_r = lambda i: (i, 0)              # [Np, X] planes   # noqa: E731
    full = lambda i: (0, 0)              # whole small arrays # noqa: E731
    inputs = [feas, pen, aff, jitter, coll]
    in_specs = [_specs((Gp, Tw), gp_t)] * 2 \
        + [_specs((Gp, T), gp_t)] * 3
    if has_blocked:
        inputs.append(blocked)
        in_specs.append(_specs((Gp, Tw), gp_t))
    inputs += [used, avail, reserved, ask_res,
               ask_desired.reshape(Gp, 1),
               jnp.asarray(seed, jnp.int32).reshape(1, 1)]
    in_specs += [_specs((T, R), np_r), _specs((T, R), np_r),
                 _specs((T, R), np_r), _specs((Gp, R), full),
                 _specs((Gp, 1), full),
                 _specs((1, 1), full,
                        memory_space=(pltpu.SMEM if pltpu is not None
                                      else None))]
    if has_devices:
        inputs += [dev_used, dev_cap, dev_ask]
        in_specs += [_specs((T, D), np_r), _specs((T, D), np_r),
                     _specs((Gp, D), full)]
    if has_spread:
        s_gp_t = lambda i: (0, 0, i)     # noqa: E731
        inputs += [sp_vnode, sp_des, sp_used, sp_weight,
                   sp_targeted.astype(jnp.int8), sp_has, minc, maxc,
                   anyp]
        in_specs += [_specs((S, Gp, T), s_gp_t),
                     _specs((S, Gp, T), s_gp_t),
                     _specs((Gp, S, V), lambda i: (0, 0, 0)),
                     _specs((Gp, S), full), _specs((Gp, S), full),
                     _specs((Gp, S), full), _specs((Gp, S), full),
                     _specs((Gp, S), full), _specs((Gp, S), full)]

    # ---- outputs ----
    out_shapes = []
    out_specs = []
    if mode == "score":
        out_shapes.append(jax.ShapeDtypeStruct((Gp, Np), jnp.float32))
        out_specs.append(_specs((Gp, T), gp_t))
    else:
        out_shapes += [
            jax.ShapeDtypeStruct((Gp, n_tiles * TKt), jnp.float32),
            jax.ShapeDtypeStruct((Gp, n_tiles * TKt), jnp.int32)]
        out_specs += [_specs((Gp, TKt), gp_t),
                      _specs((Gp, TKt), gp_t)]
        if want_tables:
            out_shapes += [
                jax.ShapeDtypeStruct((Vs + 1, Gp, n_tiles * TKvt),
                                     jnp.float32),
                jax.ShapeDtypeStruct((Vs + 1, Gp, n_tiles * TKvt),
                                     jnp.int32)]
            vmap3 = lambda i: (0, 0, i)  # noqa: E731
            out_specs += [_specs((Vs + 1, Gp, TKvt), vmap3),
                          _specs((Vs + 1, Gp, TKvt), vmap3)]
    out_shapes.append(jax.ShapeDtypeStruct((n_tiles, Gp, CNT),
                                           jnp.float32))
    out_specs.append(_specs((1, Gp, CNT), lambda i: (i, 0, 0)))

    kernel = functools.partial(
        _wave_tile_kernel, mode=mode, Gp=Gp, T=T, R=R, D=D, S=S, V=V,
        TKt=TKt, Vs=Vs, TKvt=TKvt, has_devices=has_devices,
        has_spread=has_spread, has_blocked=has_blocked,
        want_tables=want_tables)

    outs = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_shape=tuple(out_shapes),
        out_specs=tuple(out_specs),
        interpret=_interpret(),
    )(*inputs)

    # ---- merge per-tile partials (the "small reduction") ----
    res = {}
    oi = 0
    if mode == "score":
        res["score"] = outs[oi]
        oi += 1
    else:
        ts_all, ti_all = outs[oi], outs[oi + 1]
        oi += 2
        mTK = min(NE, n_tiles * TKt)
        ms, pos = lax.top_k(ts_all, mTK)
        mi = jnp.take_along_axis(ti_all, pos, axis=1)
        if mTK < NE:                 # tiny problems: pad like top_k of
            pad = NE - mTK           # a row narrower than k never is —
            ms = jnp.concatenate(    # callers clamp TK <= Np upstream
                [ms, jnp.full((Gp, pad), NEG_INF, jnp.float32)], axis=1)
            mi = jnp.concatenate(
                [mi, jnp.zeros((Gp, pad), jnp.int32)], axis=1)
        res["top_score"], res["top_idx"] = ms, mi
        if want_tables:
            vts, vti = outs[oi], outs[oi + 1]
            oi += 2
            mv = min(TKv, n_tiles * TKvt)
            tab_s, vpos = lax.top_k(
                vts.transpose(1, 0, 2), mv)          # [Gp, Vs+1, mv]
            tab_i = jnp.take_along_axis(vti.transpose(1, 0, 2), vpos,
                                        axis=2)
            if mv < TKv:
                padv = TKv - mv
                tab_s = jnp.concatenate(
                    [tab_s, jnp.full((Gp, Vs + 1, padv), NEG_INF,
                                     jnp.float32)], axis=2)
                tab_i = jnp.concatenate(
                    [tab_i, jnp.zeros((Gp, Vs + 1, padv), jnp.int32)],
                    axis=2)
            res["tab_s"], res["tab_i"] = tab_s, tab_i
    cnt = outs[oi].sum(axis=0)                        # [Gp, CNT]
    res["n_feas"] = cnt[:, 0].astype(jnp.int32)
    res["n_exh"] = cnt[:, 1].astype(jnp.int32)
    res["grp_any"] = cnt[:, 2] > 0
    res["dim_exh"] = cnt[:, 3:3 + R].astype(jnp.int32)
    return res


def _extract_topk(sc, col_ids, n_out, write):
    """Iteratively pop the row-wise max `n_out` times, ties broken by
    LOWER column (lax.top_k's order).  `write(j, vals, cols)` stores
    slot j.  Runs entirely on VMEM-resident values."""

    def body(j, sc):
        m = jnp.max(sc, axis=1, keepdims=True)             # [Gp, 1]
        am = jnp.min(jnp.where(sc == m, col_ids, jnp.int32(1 << 30)),
                     axis=1, keepdims=True)                # [Gp, 1]
        write(j, m, am)
        return jnp.where(col_ids == am, jnp.float32(_EXTRACTED), sc)

    lax.fori_loop(0, n_out, body, sc)


def _wave_tile_kernel(*refs, mode, Gp, T, R, D, S, V, TKt, Vs, TKvt,
                      has_devices, has_spread, has_blocked,
                      want_tables):
    """The fused per-tile pass.  Positional refs mirror fused_wave's
    input/output assembly exactly."""
    it = iter(refs)
    feas_ref = next(it)          # packed u32 words
    pen_ref = next(it)           # packed u32 words
    aff_ref = next(it)
    jitter_ref = next(it)
    coll_ref = next(it)
    blocked_ref = next(it) if has_blocked else None   # packed u32
    used_ref = next(it)
    avail_ref = next(it)
    reserved_ref = next(it)
    ask_res_ref = next(it)
    ask_desired_ref = next(it)
    seed_ref = next(it)
    if has_devices:
        dev_used_ref, dev_cap_ref, dev_ask_ref = (next(it), next(it),
                                                  next(it))
    if has_spread:
        (sp_vnode_ref, sp_des_ref, sp_used_ref, sp_w_ref, sp_t_ref,
         sp_has_ref, minc_ref, maxc_ref, anyp_ref) = (
            next(it), next(it), next(it), next(it), next(it), next(it),
            next(it), next(it), next(it))
    if mode == "score":
        score_ref = next(it)
    else:
        ts_ref = next(it)
        ti_ref = next(it)
        if want_tables:
            vts_ref = next(it)
            vti_ref = next(it)
    cnt_ref = next(it)

    i = pl.program_id(0)
    f32 = jnp.float32

    from .masks import unpack_bool_u32
    feas_b = unpack_bool_u32(feas_ref[...], T)         # [Gp, T]
    if has_blocked:
        feas_b &= ~unpack_bool_u32(blocked_ref[...], T)

    # ---- resource fit + bin-pack, one static unroll over R ----
    ask_res = ask_res_ref[...]                         # [Gp, R]
    fit = jnp.ones((Gp, T), bool)
    dim_fail = []
    util_cpu = util_mem = None
    denom_cpu = denom_mem = None
    for r in range(R):
        after_r = (used_ref[:, r][None, :]
                   + ask_res[:, r][:, None])           # [Gp, T]
        fit_r = after_r <= avail_ref[:, r][None, :]
        fit &= fit_r
        dim_fail.append(jnp.sum((feas_b & ~fit_r).astype(f32), axis=1))
        if r == _R_CPU:
            util_cpu = after_r + reserved_ref[:, r][None, :]
            denom_cpu = avail_ref[:, r][None, :]
        elif r == _R_MEM:
            util_mem = after_r + reserved_ref[:, r][None, :]
            denom_mem = avail_ref[:, r][None, :]

    if has_devices:
        dev_fit = jnp.ones((Gp, T), bool)
        dev_ask = dev_ask_ref[...]
        for d in range(D):
            dev_fit &= ((dev_used_ref[:, d][None, :]
                         + dev_ask[:, d][:, None])
                        <= dev_cap_ref[:, d][None, :])
    else:
        dev_fit = jnp.ones((Gp, T), bool)

    placeable = feas_b & fit & dev_fit

    ok_denoms = (denom_cpu > 0) & (denom_mem > 0)
    free_cpu = f32(1.0) - util_cpu / jnp.maximum(denom_cpu, f32(1.0))
    free_mem = f32(1.0) - util_mem / jnp.maximum(denom_mem, f32(1.0))
    raw = f32(20.0) - (f32(10.0) ** free_cpu + f32(10.0) ** free_mem)
    binpack = jnp.where(ok_denoms,
                        jnp.clip(raw, f32(0.0), f32(18.0)) / f32(18.0),
                        f32(0.0))

    # ---- anti-affinity (collocation) ----
    coll = coll_ref[...]
    anti = jnp.where(coll > 0,
                     -(coll + f32(1.0)) / ask_desired_ref[...],
                     f32(0.0))
    anti_counts = (coll > 0).astype(f32)

    # ---- spread (targeted + even), select-sum over the value vocab ----
    if has_spread:
        spread_total = jnp.zeros((Gp, T), f32)
        sp_used = sp_used_ref[...]                     # [Gp, S, V]
        for s in range(S):
            has = sp_has_ref[:, s][:, None] != 0       # [Gp, 1]
            v = sp_vnode_ref[s]                        # [Gp, T]
            has_v = v >= 0
            cur = jnp.zeros((Gp, T), f32)
            for val in range(V):
                cur = cur + jnp.where(v == val,
                                      sp_used[:, s, val][:, None],
                                      f32(0.0))
            desired = sp_des_ref[s]                    # [Gp, T]
            boost = ((desired - (cur + f32(1.0)))
                     / jnp.maximum(desired, f32(1e-9))
                     ) * sp_w_ref[:, s][:, None]
            targeted = jnp.where(~has_v, f32(-1.0),
                                 jnp.where(desired <= 0, f32(-1.0),
                                           boost))
            minc = minc_ref[:, s][:, None]
            maxc = maxc_ref[:, s][:, None]
            anyp = anyp_ref[:, s][:, None] != 0
            delta_boost = (minc - cur) / jnp.maximum(minc, f32(1e-9))
            even = jnp.where(cur != minc, delta_boost,
                             jnp.where(minc == maxc, f32(-1.0),
                                       (maxc - minc)
                                       / jnp.maximum(minc, f32(1e-9))))
            even = jnp.where(~has_v, f32(-1.0), even)
            even = jnp.where(anyp, even, f32(0.0))
            contrib = jnp.where(sp_t_ref[:, s][:, None] != 0, targeted,
                                even)
            spread_total = spread_total + jnp.where(has, contrib,
                                                    f32(0.0))
        spread_counts = (spread_total != 0.0).astype(f32)
    else:
        spread_total = f32(0.0)
        spread_counts = f32(0.0)

    # ---- normalize + seeded binning + jitter + mask ----
    # EXACT float summation order of kernel.group_scores: f32 addition
    # is not associative, and the pallas path must be bitwise the
    # kernel/host twin's score for placement-identity to hold
    pen_counts = unpack_bool_u32(pen_ref[...], T)
    pen_score = jnp.where(pen_counts, f32(-1.0), f32(0.0))
    aff_sc = aff_ref[...]
    aff_counts = aff_sc != 0.0
    n_scorers = (1.0 + anti_counts + pen_counts.astype(f32)
                 + aff_counts.astype(f32) + spread_counts)
    total = (binpack + anti + pen_score + aff_sc
             + spread_total) / n_scorers
    seed = seed_ref[0, 0]
    total = jnp.where(seed == 0, total,
                      jnp.floor(total / f32(SCORE_BIN)) * f32(SCORE_BIN))
    total = total + jitter_ref[...]
    score = jnp.where(placeable, total, f32(NEG_INF))

    # ---- explainability counters for this tile (one 2-D store) ----
    n_feas_t = jnp.sum(feas_b.astype(f32), axis=1)
    n_exh_t = jnp.sum((feas_b & ~(fit & dev_fit)).astype(f32), axis=1)
    any_t = jnp.max(placeable.astype(f32), axis=1)
    cnt_ref[0] = jnp.stack([n_feas_t, n_exh_t, any_t] + dim_fail,
                           axis=1)                     # [Gp, 3 + R]

    if mode == "score":
        score_ref[...] = score
        return

    # ---- in-kernel per-tile top-K extraction ----
    local_cols = lax.broadcasted_iota(jnp.int32, (Gp, T), 1)
    base = i * T

    def write_main(j, vals, cols):
        ts_ref[:, pl.ds(j, 1)] = vals
        ti_ref[:, pl.ds(j, 1)] = cols + base

    _extract_topk(score, local_cols, TKt, write_main)

    if want_tables:
        vnode0 = sp_vnode_ref[0]                       # [Gp, T]
        for vv in range(Vs + 1):
            vmask = (vnode0 == vv) if vv < Vs else (vnode0 < 0)
            sv = jnp.where(vmask, score, f32(NEG_INF))

            def write_v(j, vals, cols, vv=vv):
                vts_ref[vv, :, pl.ds(j, 1)] = vals
                vti_ref[vv, :, pl.ds(j, 1)] = cols + base

            _extract_topk(sv, local_cols, TKvt, write_v)
