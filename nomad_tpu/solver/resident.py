"""Resident solve: node tensors live on device, eval batches stream.

The transport between host and TPU has a large fixed cost per transfer
and per round trip (hundreds of microseconds locally, ~100ms over a
tunnel), while the solve itself is sub-millisecond.  The reference never
faces this — its scheduler runs in-process (nomad/worker.go) — so the
TPU-first design has to restructure the *data flow*, not just the math:

  * pack the node side ONCE (capacity, attributes, device inventory) and
    `device_put` it a single time;
  * per eval batch, pack only the [G, ...] ask programs
    (Tensorizer.repack_asks) — no O(N) host walk, no O(N) transfer;
  * carry `used` / `dev_used` ON DEVICE between batches, so cluster
    usage never bounces through the host;
  * fuse MANY eval batches into one device call with `lax.scan`
    (solve_stream), amortizing the round trip over thousands of
    placements; each batch's placements see every earlier batch's
    RESOURCE commits (cpu/mem/disk/net + devices) through the carried
    usage.  Job-scoped scoring state — distinct_hosts blocking,
    anti-affinity collocation, spread usage — is seeded per batch, which
    is sound because the eval broker serializes evals per job
    (reference: nomad/eval_broker.go job-token dedup): one job can never
    appear in two batches of the same stream, and those dimensions never
    cross jobs.  solve_stream enforces that invariant;
  * fetch ONE packed [B, K, TOP_K, 2] result buffer (node index + score;
    `ok` is derivable because failed slots score NEG_INF).

Falls back to the general Solver path whenever an ask steps outside the
resident universe (repack_asks returns None).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..structs import Node
from .kernel import NEG_INF, TOP_K, solve_kernel
from .tensorize import PackedBatch, PlacementAsk, Tensorizer

# ask-side solve_kernel args stacked per batch (see sharded._ARG_SPECS)
_ASK_ARGS = ("ask_res", "ask_desired", "distinct", "dc_ok", "host_ok",
             "coll0", "penalty", "c_op", "c_col", "c_rank", "a_op", "a_col",
             "a_rank", "a_weight", "a_host", "sp_col", "sp_weight",
             "sp_targeted", "sp_desired", "sp_implicit", "sp_used0",
             "dev_ask", "p_ask")


@functools.partial(jax.jit, static_argnames=())
def _stream_kernel(avail, reserved, valid, node_dc, attr_rank, dev_cap,
                   used0, dev_used0, stacked, n_places):
    """lax.scan solve_kernel over a leading batch axis of ask tensors,
    threading resource usage from batch to batch on device."""

    def step(carry, xs):
        used, dev_used = carry
        batch, n_place = xs
        res = solve_kernel(
            avail, reserved, used, valid, node_dc, attr_rank,
            batch["ask_res"], batch["ask_desired"], batch["distinct"],
            batch["dc_ok"], batch["host_ok"], batch["coll0"],
            batch["penalty"], batch["c_op"], batch["c_col"],
            batch["c_rank"], batch["a_op"], batch["a_col"],
            batch["a_rank"], batch["a_weight"], batch["a_host"],
            batch["sp_col"], batch["sp_weight"], batch["sp_targeted"],
            batch["sp_desired"], batch["sp_implicit"], batch["sp_used0"],
            dev_cap, dev_used, batch["dev_ask"], batch["p_ask"], n_place)
        packed = jnp.stack(
            [res.choice.astype(jnp.float32), res.score], axis=-1)
        return (res.used_final, res.dev_used_final), packed

    (used_f, dev_used_f), out = jax.lax.scan(step, (used0, dev_used0),
                                             (stacked, n_places))
    return used_f, dev_used_f, out


class ResidentSolver:
    """Streaming placement engine for one node snapshot.

    Build once per (node set, attribute/driver universe); then
    `solve_stream` processes eval batches with device-resident state.
    The probe asks passed to the constructor define the tensor universe
    (attr columns, constraint/affinity/spread slot counts, device
    patterns); real batches whose asks fit that universe take the fast
    path.
    """

    def __init__(self, nodes: Sequence[Node],
                 probe_asks: Sequence[PlacementAsk],
                 allocs_by_node: Optional[Dict[str, list]] = None,
                 gp: Optional[int] = None, kp: Optional[int] = None):
        self.nodes = list(nodes)
        self._tz = Tensorizer()
        self.template = self._tz.pack(nodes, probe_asks, allocs_by_node)
        self.gp = gp or self.template.ask_res.shape[0]
        self.kp = kp or self.template.p_ask.shape[0]
        self._drv_cache: Dict[str, np.ndarray] = {}
        t = self.template
        self._dev_node = {
            "avail": jax.device_put(t.avail),
            "reserved": jax.device_put(t.reserved),
            "valid": jax.device_put(t.valid),
            "node_dc": jax.device_put(t.node_dc),
            "attr_rank": jax.device_put(t.attr_rank),
            "dev_cap": jax.device_put(t.dev_cap),
        }
        self._used = jax.device_put(t.used0)
        self._dev_used = jax.device_put(t.dev_used0)

    def pack_batch(self, asks: Sequence[PlacementAsk]
                   ) -> Optional[PackedBatch]:
        """Ask-side-only pack against the resident universe."""
        pb = self._tz.repack_asks(self.nodes, asks, self.template,
                                  gp=self.gp, kp=self.kp,
                                  drv_cache=self._drv_cache)
        if pb is not None:
            pb.job_keys = {(a.job.namespace, a.job.id) for a in asks}
        return pb

    def solve_stream(self, batches: Sequence[PackedBatch]
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Solve B ask batches in ONE device call.

        Returns (choice [B, K, TOP_K] int, ok [B, K, TOP_K] bool,
        score [B, K, TOP_K] float).  Resource usage carries on device: a
        later batch sees every earlier batch's placements, and the
        carried usage persists for the next solve_stream call.

        A job may appear in at most ONE batch per stream (the broker's
        per-job eval serialization): job-scoped scoring state is seeded
        per batch and does not carry.
        """
        seen: set = set()
        for pb in batches:
            keys = getattr(pb, "job_keys", None)
            if keys:
                overlap = seen & keys
                if overlap:
                    raise ValueError(
                        f"job {overlap} appears in multiple batches of "
                        "one stream; job-scoped state (distinct_hosts, "
                        "anti-affinity, spread) would not be visible "
                        "across them")
                seen |= keys
        stacked = {
            name: np.stack([getattr(pb, name) for pb in batches])
            for name in _ASK_ARGS
        }
        n_places = np.asarray([pb.n_place for pb in batches], np.int32)
        self._used, self._dev_used, out = _stream_kernel(
            self._dev_node["avail"], self._dev_node["reserved"],
            self._dev_node["valid"], self._dev_node["node_dc"],
            self._dev_node["attr_rank"], self._dev_node["dev_cap"],
            self._used, self._dev_used, stacked, n_places)
        out = np.asarray(out)                     # ONE fetched buffer
        choice = out[..., 0].astype(np.int32)
        score = out[..., 1]
        ok = score > NEG_INF / 2
        return choice, ok, score

    def usage(self) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch the carried device usage (one sync — call sparingly)."""
        return np.asarray(self._used), np.asarray(self._dev_used)

    def reset_usage(self, used0: Optional[np.ndarray] = None,
                    dev_used0: Optional[np.ndarray] = None) -> None:
        t = self.template
        self._used = jax.device_put(
            t.used0 if used0 is None else used0)
        self._dev_used = jax.device_put(
            t.dev_used0 if dev_used0 is None else dev_used0)
