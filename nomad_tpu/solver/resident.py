"""Resident solve: node tensors live on device, eval batches stream.

The transport between host and TPU has a large fixed cost per transfer
and per round trip (hundreds of microseconds locally, ~100ms over a
tunnel), while the solve itself is sub-millisecond.  The reference never
faces this — its scheduler runs in-process (nomad/worker.go) — so the
TPU-first design has to restructure the *data flow*, not just the math:

  * pack the node side ONCE (capacity, attributes, device inventory) and
    `device_put` it a single time;
  * per eval batch, pack only the [G, ...] ask programs
    (Tensorizer.repack_asks) — no O(N) host walk, no O(N) transfer;
  * carry `used` / `dev_used` ON DEVICE between batches, so cluster
    usage never bounces through the host;
  * fuse MANY eval batches into one device call with `lax.scan`
    (solve_stream), amortizing the round trip over thousands of
    placements; each batch's placements see every earlier batch's
    RESOURCE commits (cpu/mem/disk/net + devices) through the carried
    usage.  Job-scoped scoring state — distinct_hosts blocking,
    anti-affinity collocation, spread usage — is seeded per batch, which
    is sound because the eval broker serializes evals per job
    (reference: nomad/eval_broker.go job-token dedup): one job can never
    appear in two batches of the same stream, and those dimensions never
    cross jobs.  solve_stream enforces that invariant;
  * fetch ONE packed [B, K, 2*TOP_K+1] result buffer (node indices,
    scores, and a per-placement STATUS_* outcome; `ok` is derivable
    because failed slots score NEG_INF).

Falls back to the general Solver path whenever an ask steps outside the
resident universe (repack_asks returns None).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..structs import Node
from .kernel import MERGED_GP_MAX, NEG_INF, TOP_K, solve_kernel
from .tensorize import PackedBatch, PlacementAsk, Tensorizer

from jax import lax


def unpack_stream(out) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]:
    """Decode a fetched stream payload — compact int16 (see
    pack_out_compact) or the f32 layout — into (choice, ok, score,
    status)."""
    out = np.asarray(out)                         # ONE fetched buffer
    if out.dtype == np.int16:
        choice = out[..., :TOP_K].astype(np.int32)
        u16 = np.ascontiguousarray(
            out[..., TOP_K:2 * TOP_K]).view(np.uint16)
        score = (u16.astype(np.uint32) << 16).view(np.float32)
        status = out[..., -1].astype(np.int32)
    else:
        choice = out[..., :TOP_K].astype(np.int32)
        score = out[..., TOP_K:2 * TOP_K]
        status = out[..., -1].astype(np.int32)
    ok = score > NEG_INF / 2
    return choice, ok, score, status


def _env_shortlist_c() -> int:
    """NOMAD_TPU_SHORTLIST_C: unset/'auto' -> 0 (auto), 'off'/-1 ->
    disabled, else an int handed to kernel.resolve_shortlist_c (which
    validates it against the problem shape at trace time)."""
    import os
    raw = os.environ.get("NOMAD_TPU_SHORTLIST_C", "").strip().lower()
    if raw in ("", "auto"):
        return 0
    if raw in ("off", "-1"):
        return -1
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"NOMAD_TPU_SHORTLIST_C={raw!r} invalid: use 'auto', 'off' "
            "or an integer shortlist width") from None


def _env_fused_lanes() -> int:
    """NOMAD_TPU_FUSED_LANES: unset/'1'/'serial' -> 1 (the serial
    scan — the bit-identical legacy fused path); an integer > 1 opts
    solve_stream into the lane-parallel chunked scan-of-vmap
    (ISSUE 20).  Callers that widen per round (the adaptive lane-width
    controller, fleet.LaneWidthController) pass `lanes=` per call
    instead."""
    import os
    raw = os.environ.get("NOMAD_TPU_FUSED_LANES", "").strip().lower()
    if raw in ("", "1", "serial"):
        return 1
    try:
        return max(1, int(raw))
    except ValueError:
        raise ValueError(
            f"NOMAD_TPU_FUSED_LANES={raw!r} invalid: pass an integer "
            "lane width (1 = serial scan)") from None


def pack_out_compact(choice, score, status):
    """Device-side result compaction: node indices as int16, scores
    bitcast through bfloat16, status as int16 — [..., 2*TOP_K+1] int16,
    HALF the fetch bytes of the f32 layout.  Tunneled transports move
    ~0.1 GB/s, so payload bytes are round-trip time; bf16 score
    precision (~3 significant digits) is plenty for explainability
    ranking, and `ok` derives from score > NEG_INF/2 which bf16
    preserves.  Requires Np < 32768 (int16 node indices)."""
    return jnp.concatenate(
        [choice.astype(jnp.int16),
         lax.bitcast_convert_type(score.astype(jnp.bfloat16), jnp.int16),
         status.astype(jnp.int16)[..., None]], axis=-1)

# per-placement outcome in the packed result's last column
STATUS_FAILED = 0      # infeasible / resources exhausted — terminal
STATUS_COMMITTED = 1   # slot-0 choice committed into carried usage
STATUS_RETRY = 2      # bounced by revalidation or wave budget — resubmit


def pack_batch_cached(solver, asks: Sequence[PlacementAsk],
                      job_keys: Optional[set] = None
                      ) -> Optional[PackedBatch]:
    """pack_batch with a whole-batch cache (shared by ResidentSolver
    and HostResidentSolver): asks carrying NO per-eval state (no
    penalties, existing allocs, blocked hosts, spread seeds, property
    limits) reuse the previously packed tensors for the same
    (spec signature, count) sequence — the steady-state stream where
    merge_asks collapses every chunk to the same few rows.  Nothing
    mutates a PackedBatch, so sharing is sound; job_keys (the stream
    guard) is refreshed per call.

    distinct_hosts asks are NEVER cached: their packed `distinct`
    column interns job/group IDENTITY, which the spec signature
    deliberately excludes — a cache hit could alias two different
    jobs' distinctness patterns (same reason merge_asks skips them)."""
    from ..scheduler import feasible as hostfeas
    from ..structs import CONSTRAINT_DISTINCT_HOSTS
    cacheable = all(
        not (a.penalty_nodes or a.existing_by_node
             or a.distinct_hosts_blocked or a.spread_seed
             or a.property_limits)
        and not any(c.operand == CONSTRAINT_DISTINCT_HOSTS
                    for c in hostfeas.merged_constraints(a.job, a.tg))
        for a in asks)
    if not cacheable:
        return solver.pack_batch(asks, job_keys=job_keys)
    sig = solver._tz.ask_signer()
    key = tuple((sig(a), a.count) for a in asks)
    pb = solver._eval_cache.get(key)
    if pb is None:
        pb = solver.pack_batch(asks, job_keys=job_keys)
        if pb is None:
            return None
        if len(solver._eval_cache) > 512:
            solver._eval_cache.clear()
        solver._eval_cache[key] = pb
    else:
        pb.job_keys = (job_keys if job_keys is not None else
                       {(a.job.namespace, a.job.id) for a in asks})
    return pb

def model_wave_bytes(Np: int, Gp: int, K: int, S: int, R: int,
                     has_spread: bool, mode: str, TK: int, C: int
                     ) -> Tuple[int, int, int]:
    """Two-tier per-wave HBM byte model: (bytes_wave1, bytes_rewave,
    fused_pass_count).  bytes_wave1 models a full-N pass (wave 1 and
    every shortlist-escape rescore); bytes_rewave a shortlist-resident
    contention wave over the carried [Gp, C] state.  Pure function of
    the solve shape so tests and the bench roofline share one model
    (ResidentSolver.wave_traffic feeds it the live configuration)."""
    plane = Gp * Np
    spread_planes = (2 * S * plane * 4) if has_spread else 0
    if mode == "off":
        # the unfused chain: ~6 elementwise [Gp, Np] f32 passes plus
        # the [Gp, Np, R] broadcast intermediates and the top-k read
        bytes_wave1 = (plane * 4 * 6 + plane * R * 4 * 2
                       + spread_planes + Np * R * 4 * 2
                       + K * 4 * 6)
        passes = 6
    else:
        # fused single pass: every plane read ONCE (feas/pen as
        # BITPACKED u32 lanes — 1/8th of their former int8 bytes —
        # aff f32, jitter f32, coll f32 + spread statics), node
        # columns once, plus score write+read in "score" mode only
        reads = plane * (4 + 4 + 4) + 2 * (plane // 8) \
            + spread_planes + Np * R * 4 * 3
        extra = (plane * 4 * 2 if mode == "score" else 0)
        bytes_wave1 = reads + extra + K * 4 * 6
        passes = 1
    if C > 0:
        # shortlist wave: carried [Gp, C] state read+written by the
        # loop carry (idx/feas/pen/aff/coll + spread vn/de), live
        # gathers of used/avail/reserved rows, the [Gp, TK] window,
        # the [Np] commit-mark plane and the K-sized commit vectors
        per_entry = (14 + (8 * S if has_spread else 0)) * 2 + 12 * R
        bytes_rewave = (Gp * C * per_entry + Np * 4
                        + Gp * TK * 12 + K * 4 * 2)
    else:
        bytes_rewave = bytes_wave1     # no shortlist: all waves full
    return int(bytes_wave1), int(bytes_rewave), passes


# ask-side solve_kernel args stacked per batch (see sharded._ARG_SPECS)
_ASK_ARGS = ("ask_res", "ask_desired", "distinct", "dc_ok", "host_ok",
             "coll0", "penalty", "c_op", "c_col", "c_rank", "a_op", "a_col",
             "a_rank", "a_weight", "a_host", "sp_col", "sp_weight",
             "sp_targeted", "sp_desired", "sp_implicit", "sp_used0",
             "dev_ask", "p_ask", "ask_prio")


def _solve_one(avail, reserved, valid, node_dc, attr_rank, dev_cap,
               used, dev_used, batch, n_place, seed=0, has_spread=True,
               group_count_hint=0, max_waves=0, wave_mode="scan",
               has_distinct=True, has_devices=True, stack_commit=False,
               pallas_mode="off", shortlist_c=0, mesh_axis=None,
               mesh_shards=0, has_preempt=False, ev_res=None,
               ev_prio=None, mesh_hosts=0, mesh_nt=0, tile_np=0,
               node_gid=None, owner_map=None, slot_map=None,
               mesh_regions=0, lane_axis=None):
    # host_ok / penalty may arrive BITPACKED from _stack_args (uint32
    # lanes, 1/8th the transport bytes of the dense bool planes);
    # unpack on device — dtype is static, so either form compiles once
    from .masks import unpack_bool_u32
    Np = avail.shape[0]
    host_ok = batch["host_ok"]
    if host_ok.dtype == jnp.uint32:
        host_ok = unpack_bool_u32(host_ok, Np)
    penalty = batch["penalty"]
    if penalty.dtype == jnp.uint32:
        penalty = unpack_bool_u32(penalty, Np)
    ev_kw = {}
    if has_preempt:
        # the stream caller gated distinct batches off already (the
        # eviction pass statically refuses distinct_hosts batches)
        has_distinct = False
        ev_kw = dict(has_preempt=True, ev_res=ev_res, ev_prio=ev_prio,
                     ask_prio=batch["ask_prio"])
    return solve_kernel(
        avail, reserved, used, valid, node_dc, attr_rank,
        batch["ask_res"], batch["ask_desired"], batch["distinct"],
        batch["dc_ok"], host_ok, batch["coll0"],
        penalty, batch["c_op"], batch["c_col"],
        batch["c_rank"], batch["a_op"], batch["a_col"],
        batch["a_rank"], batch["a_weight"], batch["a_host"],
        batch["sp_col"], batch["sp_weight"], batch["sp_targeted"],
        batch["sp_desired"], batch["sp_implicit"], batch["sp_used0"],
        dev_cap, dev_used, batch["dev_ask"], batch["p_ask"], n_place,
        seed, has_spread=has_spread, group_count_hint=group_count_hint,
        max_waves=max_waves, wave_mode=wave_mode,
        has_distinct=has_distinct, has_devices=has_devices,
        stack_commit=stack_commit, pallas_mode=pallas_mode,
        shortlist_c=shortlist_c, mesh_axis=mesh_axis,
        mesh_shards=mesh_shards, mesh_hosts=mesh_hosts,
        mesh_nt=mesh_nt, tile_np=tile_np, node_gid=node_gid,
        owner_map=owner_map, slot_map=slot_map,
        mesh_regions=mesh_regions, lane_axis=lane_axis, **ev_kw)


@functools.partial(jax.jit,
                   static_argnames=("has_spread", "group_count_hint",
                                    "max_waves", "wave_mode",
                                    "has_distinct", "has_devices"))
def _parallel_kernel(avail, reserved, valid, node_dc, attr_rank, dev_cap,
                     used0, dev_used0, stacked, n_places, seeds,
                     has_spread=True, group_count_hint=0, max_waves=0,
                     wave_mode="while", has_distinct=True,
                     has_devices=True):
    """The TPU recast of the reference's optimistic worker concurrency
    (nomad/worker.go goroutines + nomad/plan_apply.go serial applier):
    vmap B batch-solves against ONE shared usage snapshot — each with its
    own tie-break seed, the analog of per-worker shuffled node order —
    then revalidate every batch's placements serially against cumulative
    usage, bouncing whatever no longer fits.  All on device; one round
    trip for the whole fleet of batches."""
    res = jax.vmap(
        lambda b, n, s: _solve_one(avail, reserved, valid, node_dc,
                                   attr_rank, dev_cap, used0, dev_used0,
                                   b, n, s, has_spread,
                                   group_count_hint, max_waves,
                                   wave_mode, has_distinct, has_devices,
                                   # vmapped lanes turn the shortlist
                                   # cond into a select (both branches
                                   # run) — pure overhead here
                                   shortlist_c=-1)
    )(stacked, n_places, seeds)
    # res.* have a leading [B] axis; slot-0 choices are the commits
    K = res.choice.shape[1]
    ks = jnp.arange(K)

    def apply_batch(carry, xs):
        used, dev_used = carry
        choice, ok0, score, unfin, res_k, dev_k, n_place = xs
        cand = choice[:, 0]
        ok = ok0[:, 0] & (ks < n_place)
        # cumulative same-node load within this batch, in placement
        # order. Conservative one-round revalidation: a bounced
        # placement's load still counts toward later same-node
        # placements (exact first-fit would need a per-node serial
        # walk), so a bounce can cascade — every bounce is reported
        # STATUS_RETRY, never failed, and clears in the retry stream.
        earlier = ks[None, :] < ks[:, None]
        same = (cand[None, :] == cand[:, None]) & ok[None, :] \
            & ok[:, None] & earlier
        prior = same.astype(jnp.float32) @ (res_k * ok[:, None])
        prior_dev = same.astype(jnp.float32) @ (dev_k * ok[:, None])
        fits = ((used[cand] + prior + res_k) <= avail[cand]).all(-1)
        dev_fits = ((dev_used[cand] + prior_dev + dev_k)
                    <= dev_cap[cand]).all(-1)
        commit = ok & fits & dev_fits
        cm = commit[:, None]
        used = used.at[cand].add(res_k * cm)
        dev_used = dev_used.at[cand].add(dev_k * cm)
        # bounced placements lose ALL slots (their fall-through scores
        # were solved against a stale snapshot and were never charged)
        score = jnp.where(cm, score, NEG_INF)
        status = jnp.where(commit, STATUS_COMMITTED,
                           jnp.where(ok | unfin, STATUS_RETRY,
                                     STATUS_FAILED))
        packed = jnp.concatenate(
            [choice.astype(jnp.float32), score,
             status.astype(jnp.float32)[:, None]], axis=-1)
        return (used, dev_used), packed

    res_per_p = jnp.take_along_axis(
        stacked["ask_res"],
        stacked["p_ask"][:, :, None].astype(jnp.int32), axis=1)  # [B,K,R]
    dev_per_p = jnp.take_along_axis(
        stacked["dev_ask"],
        stacked["p_ask"][:, :, None].astype(jnp.int32), axis=1)  # [B,K,D]
    (used_f, dev_used_f), out = jax.lax.scan(
        apply_batch, (used0, dev_used0),
        (res.choice, res.choice_ok, res.score, res.unfinished,
         res_per_p, dev_per_p, n_places))
    return used_f, dev_used_f, out


@functools.partial(jax.jit,
                   static_argnames=("has_spread", "group_count_hint",
                                    "max_waves", "wave_mode",
                                    "has_distinct", "has_devices",
                                    "stack_commit", "compact",
                                    "pallas_mode", "shortlist_c",
                                    "has_preempt"))
def _stream_kernel(avail, reserved, valid, node_dc, attr_rank, dev_cap,
                   used0, dev_used0, stacked, n_places, seeds,
                   ev_res=None, ev_prio=None,
                   has_spread=True, group_count_hint=0, max_waves=0,
                   wave_mode="scan", has_distinct=True,
                   has_devices=True, stack_commit=False, compact=True,
                   pallas_mode="off", shortlist_c=0,
                   has_preempt=False):
    """lax.scan solve_kernel over a leading batch axis of ask tensors,
    threading resource usage from batch to batch on device.  Also
    returns the per-batch wave and full-rescore counts [B] — the
    instrumentation the two-tier HBM byte model multiplies against
    (bytes_wave1 x rescore + bytes_rewave x shortlist waves) — and the
    per-batch [K, E] eviction-slot masks of the in-kernel preemption
    pass (zeros [K, 1] when has_preempt is off)."""

    def step(carry, xs):
        used, dev_used = carry
        batch, n_place, seed = xs
        res = _solve_one(avail, reserved, valid, node_dc, attr_rank,
                         dev_cap, used, dev_used, batch, n_place, seed,
                         has_spread, group_count_hint, max_waves,
                         wave_mode, has_distinct, has_devices,
                         stack_commit, pallas_mode, shortlist_c,
                         has_preempt=has_preempt, ev_res=ev_res,
                         ev_prio=ev_prio)
        status = jnp.where(res.choice_ok[:, 0], STATUS_COMMITTED,
                           jnp.where(res.unfinished, STATUS_RETRY,
                                     STATUS_FAILED))
        if compact:
            packed = pack_out_compact(res.choice, res.score, status)
        else:
            packed = jnp.concatenate(
                [res.choice.astype(jnp.float32), res.score,
                 status.astype(jnp.float32)[:, None]], axis=-1)
        evict = (res.evict if has_preempt
                 else jnp.zeros((res.choice.shape[0], 1), bool))
        return ((res.used_final, res.dev_used_final),
                (packed, evict, res.n_waves, res.n_rescore))

    (used_f, dev_used_f), (out, evict, waves, rescores) = jax.lax.scan(
        step, (used0, dev_used0), (stacked, n_places, seeds))
    return used_f, dev_used_f, out, evict, waves, rescores


@functools.partial(jax.jit,
                   static_argnames=("lanes", "has_spread",
                                    "group_count_hint", "max_waves",
                                    "wave_mode", "has_distinct",
                                    "has_devices", "stack_commit",
                                    "compact", "pallas_mode",
                                    "shortlist_c"))
def _lane_stream_kernel(avail, reserved, valid, node_dc, attr_rank,
                        dev_cap, used0, dev_used0, stacked, n_places,
                        seeds, lanes=2, has_spread=True,
                        group_count_hint=0, max_waves=0,
                        wave_mode="while", has_distinct=True,
                        has_devices=True, stack_commit=False,
                        compact=True, pallas_mode="off", shortlist_c=0):
    """Chunked scan-of-vmap fused stream (ISSUE 20): the serial scan of
    `_stream_kernel` but L batches per scan step, each step `vmap`ing
    the solve over its L lanes against the CARRIED usage snapshot and
    then revalidating all L lanes' slot-0 commits in one in-kernel pass
    — `_parallel_kernel.apply_batch`'s cumulative same-node credit
    generalized from within-batch to cross-lane placement order (lane-
    major: lane l's placement k revalidates at rank l*K + k).  Serial
    depth drops from B to B/L; placements a sibling lane beat to a node
    bounce to STATUS_RETRY with every score slot nulled — exactly the
    `_parallel_kernel` contract, so the retry stream clears them.

    Unlike `_parallel_kernel`, the lanes keep the caller's shortlist:
    `lane_axis` makes the carried/full wave cond lane-UNIFORM (a psum
    over the vmap axis is unbatched, so the cond stays a real branch —
    see kernel.py), fixing the PR 4 cond→select overhead that forced
    `shortlist_c=-1` and the pinned full-rescore on vmapped lanes.

    B must be a multiple of `lanes` (the host pads with n_place=0 rows).
    Preemption streams stay on the serial kernel: cross-lane
    revalidation of EVICTION credits (usage that goes DOWN) has no
    one-round conservative form.  Returns (used, dev_used, out [B,...],
    waves [B], rescores [B], bounced [B], committed [B])."""
    L = lanes
    B = n_places.shape[0]
    n_chunks = B // L
    st_c = jax.tree_util.tree_map(
        lambda v: v.reshape((n_chunks, L) + v.shape[1:]), dict(stacked))
    np_c = n_places.reshape(n_chunks, L)
    seed_c = seeds.reshape(n_chunks, L)
    K = stacked["p_ask"].shape[1]
    ks = jnp.arange(K)
    lk = jnp.arange(L * K)

    def chunk_step(carry, xs):
        used, dev_used = carry
        batch, n_place, seed = xs
        res = jax.vmap(
            lambda b, n, s: _solve_one(
                avail, reserved, valid, node_dc, attr_rank, dev_cap,
                used, dev_used, b, n, s, has_spread, group_count_hint,
                max_waves, wave_mode, has_distinct, has_devices,
                stack_commit, pallas_mode, shortlist_c,
                lane_axis="lanes"),
            axis_name="lanes")(batch, n_place, seed)
        # ---- cross-lane revalidation (the serial plan applier) ----
        # Flatten lane-major and replay apply_batch's arithmetic over
        # the whole chunk: cumulative same-node credit in (lane,
        # placement) order, conservative one-round semantics (a bounced
        # placement's load still counts toward later same-node rows, so
        # bounces can cascade — every one is STATUS_RETRY, never lost).
        # Intra-lane placements re-earn their own solve's commits: the
        # lane charged them against the same snapshot in the same
        # order, so their cumulative fit re-checks true.
        res_l = jnp.take_along_axis(
            batch["ask_res"],
            batch["p_ask"][:, :, None].astype(jnp.int32), axis=1)
        dev_l = jnp.take_along_axis(
            batch["dev_ask"],
            batch["p_ask"][:, :, None].astype(jnp.int32), axis=1)
        res_k = res_l.reshape(L * K, -1)
        dev_k = dev_l.reshape(L * K, -1)
        choice = res.choice.reshape(L * K, TOP_K)
        score = res.score.reshape(L * K, TOP_K)
        unfin = res.unfinished.reshape(L * K)
        okf = (res.choice_ok[:, :, 0]
               & (ks[None, :] < n_place[:, None])).reshape(L * K)
        cand = choice[:, 0]
        earlier = lk[None, :] < lk[:, None]
        same = ((cand[None, :] == cand[:, None]) & okf[None, :]
                & okf[:, None] & earlier)
        prior = same.astype(jnp.float32) @ (res_k * okf[:, None])
        prior_dev = same.astype(jnp.float32) @ (dev_k * okf[:, None])
        fits = ((used[cand] + prior + res_k) <= avail[cand]).all(-1)
        dev_fits = ((dev_used[cand] + prior_dev + dev_k)
                    <= dev_cap[cand]).all(-1)
        commit = okf & fits & dev_fits
        cm = commit[:, None]
        used = used.at[cand].add(res_k * cm)
        dev_used = dev_used.at[cand].add(dev_k * cm)
        # bounced placements lose ALL slots (their fall-through scores
        # were solved against a stale snapshot and were never charged)
        score = jnp.where(cm, score, NEG_INF)
        status = jnp.where(commit, STATUS_COMMITTED,
                           jnp.where(okf | unfin, STATUS_RETRY,
                                     STATUS_FAILED))
        score_l = score.reshape(L, K, TOP_K)
        status_l = status.reshape(L, K)
        if compact:
            packed = jax.vmap(pack_out_compact)(res.choice, score_l,
                                                status_l)
        else:
            packed = jnp.concatenate(
                [res.choice.astype(jnp.float32), score_l,
                 status_l.astype(jnp.float32)[..., None]], axis=-1)
        bounced = (okf & ~commit).reshape(L, K).sum(axis=1)
        committed = commit.reshape(L, K).astype(jnp.int32).sum(axis=1)
        return ((used, dev_used),
                (packed, res.n_waves, res.n_rescore,
                 bounced.astype(jnp.int32), committed))

    (used_f, dev_used_f), (out, waves, rescores, bounced, committed) = \
        jax.lax.scan(chunk_step, (used0, dev_used0),
                     (st_c, np_c, seed_c))

    def _flat(a):
        return a.reshape((B,) + a.shape[2:])

    return (used_f, dev_used_f, _flat(out), _flat(waves),
            _flat(rescores), _flat(bounced), _flat(committed))


class ResidentSolver:
    """Streaming placement engine for one node snapshot.

    Build once per (node set, attribute/driver universe); then
    `solve_stream` processes eval batches with device-resident state.
    The probe asks passed to the constructor define the tensor universe
    (attr columns, constraint/affinity/spread slot counts, device
    patterns); real batches whose asks fit that universe take the fast
    path.
    """

    def __init__(self, nodes: Sequence[Node],
                 probe_asks: Sequence[PlacementAsk],
                 allocs_by_node: Optional[Dict[str, list]] = None,
                 gp: Optional[int] = None, kp: Optional[int] = None,
                 max_waves: int = 0, wave_mode: str = "scan",
                 stack_commit: bool = False, pallas: str = "auto",
                 delta_threshold: Optional[float] = None,
                 shortlist_c: Optional[int] = None,
                 evict_e: int = 0,
                 fused_lanes: Optional[int] = None):
        import os
        self.nodes = list(nodes)
        #: in-kernel preemption (ISSUE 7): > 0 packs top-E evictable-
        #: alloc planes from `allocs_by_node` and runs the eviction
        #: wave pass for groups with nothing placeable.  Stream-mode
        #: contract: the caller must feed each batch's evictions back
        #: as stop deltas (solve_stream_pipelined deltas=) before the
        #: next batch — usage carries on device, but the candidate
        #: planes only advance through apply_delta.  0 = off (default
        #: for the raw stream engine; the worker Solver enables it via
        #: tensorize.evict_width()).
        self.evict_e = int(evict_e)
        self.max_waves = max_waves        # 0 = kernel default
        self.wave_mode = wave_mode        # see kernel.py loop-shape note
        self.stack_commit = stack_commit  # serial-fidelity commits
        #: "auto" resolves per trace against shape + backend (pallas
        #: fused wave kernel on TPU / forced via NOMAD_TPU_PALLAS);
        #: "off"/"score"/"topk" pin it (tests, benchmarks)
        self.pallas = pallas
        #: shortlist width for contention waves: 0 auto-sizes (the
        #: candidate window rounded up a tile), -1 disables, explicit
        #: values are validated at trace time (kernel.py
        #: resolve_shortlist_c — invalid values RAISE, never clamp).
        #: NOMAD_TPU_SHORTLIST_C overrides when the ctor arg is None
        #: ("auto"/"off" accepted as spellings of 0/-1).
        self.shortlist_c = (
            shortlist_c if shortlist_c is not None
            else _env_shortlist_c())
        #: default lane width for solve_stream (ISSUE 20): 1 = the
        #: serial scan, bit-identical legacy behavior; L > 1 solves L
        #: batches per scan step (chunked scan-of-vmap) and revalidates
        #: their commits cross-lane, bouncing losers to STATUS_RETRY.
        #: NOMAD_TPU_FUSED_LANES overrides when the ctor arg is None;
        #: solve_stream_async(lanes=) overrides per call.
        self.fused_lanes = (int(fused_lanes) if fused_lanes is not None
                            else _env_fused_lanes())
        #: device-side revalidation counters of the last LANE-parallel
        #: stream (None after a serial stream) — fetch via
        #: lane_counters(), which the adaptive width controller feeds on
        self.last_lane_counters = None
        #: per-batch wave counts of the LAST dispatched stream (device
        #: array; fetch syncs — instrumentation consumers only)
        self.last_waves = None
        #: per-batch FULL-rescore wave counts of the last stream (the
        #: remainder up to last_waves ran shortlist-resident)
        self.last_rescore_waves = None
        #: delta waves touching more than this fraction of real node
        #: slots fall back to a full repack (one contiguous re-put beats
        #: a near-total scatter); NOMAD_TPU_DELTA_THRESHOLD overrides
        self.delta_threshold = (
            delta_threshold if delta_threshold is not None
            else float(os.environ.get("NOMAD_TPU_DELTA_THRESHOLD",
                                      "0.25")))
        #: resident-delta observability (ISSUE 2 satellite): consumed by
        #: wave_traffic / BENCH_DETAIL
        self.delta_counters = {
            "delta_applies": 0, "repack_fallbacks": 0,
            "last_delta_ratio": 0.0,
            "bytes_dispatched_delta": 0, "bytes_dispatched_full": 0,
            # cumulative ask-plane bytes the stream dispatches shipped
            # (ISSUE 20 satellite; per-round in last_dispatch_bytes)
            "bytes_dispatched_ask": 0, "ask_dispatches": 0,
        }
        #: pow2-bucketed staging buffers for the B>1 stacked ask planes
        #: (ISSUE 20 satellite — see _staged_stack)
        self._stage_cache: Dict = {}
        #: B>1 repeated-stream device cache (ISSUE 20 satellite): the
        #: stacked+device-put ask dict keyed on the identity tuple of
        #: the stream's batches — see _stack_args
        self._stream_stack_cache: Dict = {}
        #: bumps on every node-shape change; device-side stacked-batch
        #: caches are keyed on it so a stale ask plane is never reused
        self._node_epoch = 0
        #: bumps whenever the EVICTION planes advance (alloc place/stop
        #: deltas replay ev rows WITHOUT touching the node shape, so
        #: the node epoch alone cannot invalidate ev-dependent caches —
        #: ISSUE 8 satellite; see federated._stack_args)
        self._ev_epoch = 0
        #: host bytes the LAST dispatch actually shipped (0 on a
        #: device-cached re-dispatch)
        self.last_dispatch_bytes = 0
        #: wall-clock of the last SYNCHRONOUS stream solve (solve_stream
        #: / solve_stream_pipelined, dispatch through fetch) keyed by
        #: batch count — the serving tier's EWMA solve-time model feeds
        #: from this (server/serving.py EwmaSolveModel.observe)
        self.last_solve_stats = None
        #: [B, K, E] eviction-slot masks of the last dispatched stream
        #: (device array; list when pipelined) — None until a preempt-
        #: enabled stream ran
        self.last_evict = None
        self._probe_asks = list(probe_asks)
        self._tz = Tensorizer()
        self.template = self._tz.pack(nodes, probe_asks, allocs_by_node,
                                      evict_e=self.evict_e)
        self.node_index = {n.id: i for i, n in enumerate(self.nodes)}
        self.gp = gp or self.template.ask_res.shape[0]
        self.kp = kp or self.template.p_ask.shape[0]
        self._drv_cache: Dict[str, np.ndarray] = {}
        self._row_cache: Dict = {}    # ask_signature -> packed spec row
        self._eval_cache: Dict = {}       # see pack_batch_cached
        # device-resident constants for the [G, N] ask-side arrays that
        # are usually all-zero (fresh jobs) or at their universe default
        # (host_ok): shipping them dense per call costs ~100MB/s-class
        # transports far more than the solve itself
        self._const_cache: Dict[Tuple[str, int], object] = {}
        self._put_node_side()

    #: subclass hook (parallel.sharded): bitpacking bool ask planes
    #: would split 32 node columns per uint32 lane, which a node-axis
    #: NamedSharding cannot partition cleanly — the mesh solver ships
    #: them dense instead
    _pack_bool_planes = True

    def _put_node(self, name: str, arr):
        """Device placement for one node-side tensor (subclass hook:
        the mesh-resident solver pins a node-axis NamedSharding).

        Always COPIES first: CPU device_put can alias the numpy buffer
        zero-copy, and apply_delta later mutates the template arrays IN
        PLACE host-side (apply_node_delta_host) — through an alias the
        device carry would see both the host `+=` and the device
        scatter-add, double-charging usage depending on nothing more
        than heap alignment."""
        return jax.device_put(np.array(arr))

    def _put_ask(self, name: str, arr):
        """Device placement for one stacked [B, ...] ask tensor
        (subclass hook, as _put_node)."""
        return jax.device_put(arr)

    def _put_node_side(self) -> None:
        """Ship the full node-side tensors to device (initial build and
        the repack-fallback path) and rebuild everything derived from
        the node axis."""
        t = self.template
        self._dev_node = {
            "avail": self._put_node("avail", t.avail),
            "reserved": self._put_node("reserved", t.reserved),
            "valid": self._put_node("valid", t.valid),
            "node_dc": self._put_node("node_dc", t.node_dc),
            "attr_rank": self._put_node("attr_rank", t.attr_rank),
            "dev_cap": self._put_node("dev_cap", t.dev_cap),
        }
        if t.ev_prio is not None:
            # evictable-alloc planes live in HBM next to the other
            # node-axis planes (delta-maintained through apply_delta)
            self._dev_node["ev_prio"] = self._put_node("ev_prio",
                                                       t.ev_prio)
            self._dev_node["ev_res"] = self._put_node("ev_res", t.ev_res)
        self._used = self._put_node("used", t.used0)
        self._dev_used = self._put_node("dev_used", t.dev_used0)
        # compact int16 result payload needs int16-expressible node ids
        self._compact = t.avail.shape[0] < 32768
        self._default_host_ok = np.zeros((self.gp, t.avail.shape[0]),
                                         bool)
        self._default_host_ok[:, :t.n_real] = True
        self.delta_counters["bytes_dispatched_full"] += int(
            t.avail.nbytes + t.reserved.nbytes + t.valid.nbytes
            + t.node_dc.nbytes + t.attr_rank.nbytes + t.dev_cap.nbytes
            + t.used0.nbytes + t.dev_used0.nbytes
            + (t.ev_prio.nbytes + t.ev_res.nbytes
               if t.ev_prio is not None else 0))

    def _delta_set(self, arr, idx, rows):
        """Row-scatter 'set' into resident node state (subclass hook:
        the mesh solver routes rows to the owning shard — the plain
        jit scatter is only partition-safe on one device)."""
        from .kernel import delta_scatter_set
        return delta_scatter_set(arr, idx, rows)

    def _delta_add(self, arr, idx, rows):
        """Row-scatter 'add' into carried usage (subclass hook, as
        _delta_set)."""
        from .kernel import delta_scatter_add
        return delta_scatter_add(arr, idx, rows)

    # ------------------------------------------------- delta lifecycle
    def apply_delta(self, delta) -> str:
        """Apply a ClusterDelta to the device-resident cluster state.

        The incremental path (returns "delta") scatters only the touched
        rows into the HBM-resident avail/reserved/valid/attr/dev arrays
        and the carried usage, via donate-buffer kernels — no [Np, ...]
        re-tensorization, no full re-put.  Falls back to a full repack
        (returns "repack") when the delta steps outside the interned
        universe (new dc / attr value / device pattern — the
        interning-table invalidation), overflows the padded node axis,
        or touches more than `delta_threshold` of the real node slots.
        """
        from .tensorize import apply_node_delta_host
        if delta.empty():
            return "delta"
        nd = self._tz.delta_pack(self.template, self.node_index, delta)
        if nd is not None:
            ratio = nd.ratio(self.template.n_real)
            self.delta_counters["last_delta_ratio"] = round(ratio, 6)
        if nd is None or nd.ratio(self.template.n_real) \
                > self.delta_threshold:
            self.repack(delta)
            return "repack"
        n_real_before = self.template.n_real
        apply_node_delta_host(self.template, nd, self.nodes,
                              self.node_index)
        # pow2-pad the scatter payloads so steady-state delta waves
        # (whose row counts vary wave to wave) reuse a handful of
        # compiled scatter variants instead of retracing per shape:
        # "set" pads by repeating row 0 (duplicate identical writes),
        # "add" pads with zero rows at slot 0 (no-op adds)
        def _pad(idx, rows, repeat_first):
            M = idx.size
            P = 8
            while P < M:
                P *= 2
            if P == M:
                return idx, rows
            if repeat_first:
                pad_i = np.full(P - M, idx[0], idx.dtype)
                pads = [np.repeat(r[:1], P - M, axis=0) for r in rows]
            else:
                pad_i = np.zeros(P - M, idx.dtype)
                pads = [np.zeros((P - M,) + r.shape[1:], r.dtype)
                        for r in rows]
            return (np.concatenate([idx, pad_i]),
                    [np.concatenate([r, p]) for r, p in zip(rows, pads)])

        if nd.touches_nodes():
            from ..chaos.injection import global_injections
            inj = global_injections.get("delta_row")
            if inj is not None:
                # chaos site "delta_row" (ISSUE 14): corrupt the
                # device-bound scatter rows AFTER the host template took
                # the clean apply — the planes diverge silently until a
                # checksum audit (check_plane_checksums) catches it
                inj.fire()
                k = min(int(inj.args.get("rows", 1)), nd.avail.shape[0])
                nd.avail = nd.avail.copy()
                nd.avail[:k] += 1.0
            dn = self._dev_node
            idx, (r_avail, r_res, r_valid, r_dc, r_attr, r_dev) = _pad(
                nd.idx, [nd.avail, nd.reserved, nd.valid,
                         nd.node_dc.astype(np.asarray(
                             dn["node_dc"]).dtype), nd.attr_rank,
                         nd.dev_cap], repeat_first=True)
            dn["avail"] = self._delta_set(dn["avail"], idx, r_avail)
            dn["reserved"] = self._delta_set(dn["reserved"], idx,
                                             r_res)
            dn["valid"] = self._delta_set(dn["valid"], idx, r_valid)
            dn["node_dc"] = self._delta_set(dn["node_dc"], idx, r_dc)
            dn["attr_rank"] = self._delta_set(dn["attr_rank"], idx,
                                              r_attr)
            dn["dev_cap"] = self._delta_set(dn["dev_cap"], idx, r_dev)
            # node-shape changes invalidate every cached host mask and
            # packed batch (driver/volume feasibility, host_ok widths)
            self._node_epoch += 1
            self._row_cache.clear()
            self._drv_cache.clear()
            self._eval_cache.clear()
            if self.template.n_real != n_real_before:
                self._default_host_ok = np.zeros(
                    (self.gp, self.template.avail.shape[0]), bool)
                self._default_host_ok[:, :self.template.n_real] = True
                self._const_cache = {
                    k: v for k, v in self._const_cache.items()
                    if k[0] != "host_ok"}
        if nd.u_idx.size:
            u_idx, (u_res, u_dev) = _pad(nd.u_idx, [nd.u_res, nd.u_dev],
                                         repeat_first=False)
            self._used = self._delta_add(self._used, u_idx, u_res)
            self._dev_used = self._delta_add(self._dev_used, u_idx,
                                             u_dev)
        if self.template.ev_lists is not None:
            # eviction-plane rows the host apply just recomputed
            # (_apply_evict_delta) scatter like every other node plane
            ev_slots = sorted({s for s, _ in nd.alloc_place}
                              | {s for s, _ in nd.alloc_stop})
            ev_slots = [s for s in ev_slots
                        if s < self.template.ev_prio.shape[0]]
            if ev_slots:
                self._ev_epoch += 1
                t = self.template
                e_idx, (e_prio, e_res) = _pad(
                    np.asarray(ev_slots, np.int32),
                    [t.ev_prio[ev_slots], t.ev_res[ev_slots]],
                    repeat_first=True)
                dn = self._dev_node
                dn["ev_prio"] = self._delta_set(dn["ev_prio"], e_idx,
                                                e_prio)
                dn["ev_res"] = self._delta_set(dn["ev_res"], e_idx,
                                               e_res)
        self.delta_counters["delta_applies"] += 1
        self.delta_counters["bytes_dispatched_delta"] += nd.nbytes()
        return "delta"

    def repack(self, delta=None) -> None:
        """Full-repack fallback: rebuild the node-side template from the
        current node set (delta applied host-side first, removed nodes
        compacted away) and re-put it whole.  Carried usage transfers by
        node id; usage deltas in `delta` are folded in host-side."""
        from .tensorize import alloc_usage_vector
        used, dev_used = self.usage()        # one sync
        old_ids = list(self.template.node_ids)
        by_id = {n.id: n for n in self.nodes}
        removed = set()
        if delta is not None:
            for n in delta.upsert_nodes:
                by_id[n.id] = n
            removed = set(delta.remove_node_ids)
        # keep join order, compact tombstones away; an upsert in the
        # triggering delta revives a previously-removed slot
        upserted = ({n.id for n in delta.upsert_nodes}
                    if delta is not None else set())
        new_nodes = []
        seen = set()
        for i, nid in enumerate(old_ids):
            if nid in removed:
                continue
            if not self.template.valid[i] and nid not in upserted:
                continue              # old tombstone stays dead
            new_nodes.append(by_id[nid])
            seen.add(nid)
        if delta is not None:
            for n in delta.upsert_nodes:
                if n.id not in seen and n.id not in removed:
                    new_nodes.append(n)
                    seen.add(n.id)
        old_ev_lists = (None if self.template.ev_lists is None else
                        {nid: self.template.ev_lists[i]
                         for i, nid in enumerate(old_ids)
                         if i < len(self.template.ev_lists)})
        self.nodes = new_nodes
        self.template = self._tz.pack(self.nodes, self._probe_asks,
                                      evict_e=self.evict_e)
        self.node_index = {n.id: i for i, n in enumerate(self.nodes)}
        # carry usage across by node id (slots moved in the compaction)
        t = self.template
        if t.ev_lists is not None and old_ev_lists is not None:
            # eviction candidates carry by node id too
            from .tensorize import _evict_row
            E = t.ev_prio.shape[1]
            for j, nid in enumerate(t.node_ids):
                cands = old_ev_lists.get(nid)
                if cands:
                    t.ev_lists[j] = list(cands)
                    t.ev_prio[j], t.ev_res[j], t.ev_ids[j] = _evict_row(
                        cands, E)
        for i, nid in enumerate(old_ids):
            j = self.node_index.get(nid)
            if j is not None:
                t.used0[j] = used[i]
                t.dev_used0[j] = dev_used[i]
        if delta is not None:
            for nid, alloc in delta.place:
                j = self.node_index.get(nid)
                if j is not None:
                    t.used0[j] += alloc_usage_vector(alloc)
            for nid, alloc in delta.stop:
                j = self.node_index.get(nid)
                if j is not None:
                    t.used0[j] -= alloc_usage_vector(alloc)
            if t.ev_lists is not None:
                from .tensorize import apply_evict_ops
                slot_ops = lambda grp: [  # noqa: E731
                    (j, a) for nid, a in grp
                    for j in (self.node_index.get(nid),)
                    if j is not None]
                apply_evict_ops(t, slot_ops(delta.stop),
                                slot_ops(delta.place))
        self._node_epoch += 1
        self._ev_epoch += 1
        self._row_cache.clear()
        self._drv_cache.clear()
        self._eval_cache.clear()
        self._const_cache.clear()
        self.delta_counters["repack_fallbacks"] += 1
        self._put_node_side()

    def pack_batch(self, asks: Sequence[PlacementAsk],
                   job_keys: Optional[set] = None
                   ) -> Optional[PackedBatch]:
        """Ask-side-only pack against the resident universe. job_keys
        overrides the same-job stream guard's key set — merge_asks
        callers pass the PRE-merge keys so absorbed jobs still count."""
        pb = self._tz.repack_asks(self.nodes, asks, self.template,
                                  gp=self.gp, kp=self.kp,
                                  drv_cache=self._drv_cache,
                                  row_cache=self._row_cache)
        if pb is not None:
            pb.job_keys = (job_keys if job_keys is not None else
                           {(a.job.namespace, a.job.id) for a in asks})
        return pb

    def pack_batch_cached(self, asks: Sequence[PlacementAsk],
                          job_keys: Optional[set] = None
                          ) -> Optional[PackedBatch]:
        return pack_batch_cached(self, asks, job_keys)

    def merge_asks(self, asks: Sequence[PlacementAsk]
                   ) -> Tuple[List[PlacementAsk], set]:
        """Throughput-mode ask dedup: asks with the SAME spec signature
        and no per-eval state collapse into one group row with the
        summed count, shrinking the [G, N] wave work by the workload's
        duplication factor — the columnar payoff of coalescing evals.
        Job-scoped soft scoring (anti-affinity, spread progress) is then
        computed over the merged population rather than per job; the
        hard commit quotas stay exact, and distinct_hosts (at ANY level,
        incl. per-task) / stateful asks never merge. Returns (merged
        asks, job keys of EVERY original ask — pass to pack_batch so the
        stream guard still sees absorbed jobs). Exact-mode callers
        (tests, quality comparisons) skip this entirely."""
        import dataclasses
        from ..scheduler import feasible as hostfeas
        from ..structs import CONSTRAINT_DISTINCT_HOSTS
        signer = self._tz.ask_signer()
        first: Dict = {}
        counts: Dict = {}
        out: List[PlacementAsk] = []
        order: List = []
        keys = {(a.job.namespace, a.job.id) for a in asks}
        for a in asks:
            stateful = (a.penalty_nodes or a.existing_by_node
                        or a.distinct_hosts_blocked or a.spread_seed
                        or a.property_limits)
            distinct = any(
                c.operand == CONSTRAINT_DISTINCT_HOSTS
                for c in hostfeas.merged_constraints(a.job, a.tg))
            if stateful or distinct:
                out.append(a)
                continue
            sig = signer(a)
            if sig in counts:
                counts[sig] += a.count
            else:
                first[sig] = a
                counts[sig] = a.count
                order.append(sig)
        merged = [
            (first[sig] if counts[sig] == first[sig].count
             else dataclasses.replace(first[sig], count=counts[sig]))
            for sig in order]
        return merged + out, keys

    def solve_stream(self, batches: Sequence[PackedBatch],
                     seeds: Optional[Sequence[int]] = None
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]:
        """Solve B ask batches in ONE device call.

        Returns (choice [B, K, TOP_K] int, ok [B, K, TOP_K] bool,
        score [B, K, TOP_K] float, status [B, K] int — STATUS_*).
        Resource usage carries on device: a later batch sees every
        earlier batch's placements, and the carried usage persists for
        the next solve_stream call.  STATUS_RETRY placements (wave
        budget ran out) should be resubmitted in a later stream.

        A job may appear in at most ONE batch per stream (the broker's
        per-job eval serialization): job-scoped scoring state is seeded
        per batch and does not carry.

        `seeds`: optional per-batch tie-break seeds (see the kernel's
        jitter note). None keeps exact deterministic scoring; passing
        distinct seeds fans identical asks across equal-scoring nodes,
        which converges contended batches in fewer waves.
        """
        import time as _t
        t0 = _t.perf_counter()
        out = self._unpack(self.solve_stream_async(batches, seeds))
        self.last_solve_stats = {"n_batches": len(batches),
                                 "wall_s": _t.perf_counter() - t0}
        return out

    def solve_stream_async(self, batches: Sequence[PackedBatch],
                           seeds: Optional[Sequence[int]] = None,
                           lanes: Optional[int] = None):
        """Dispatch a stream WITHOUT fetching: returns the device-side
        packed result (pass to finish_stream to unpack).  Lets callers
        pipeline independent streams (e.g. one per region/solver) so
        their transport round trips overlap — JAX dispatch is async, and
        the carried usage updates device-side immediately.

        `lanes` overrides the solver's `fused_lanes` width for this
        call: > 1 routes multi-batch streams to the lane-parallel
        chunked scan-of-vmap (ISSUE 20) — L batches solve per scan
        step against the carried snapshot and revalidate cross-lane,
        bouncing conflicts to STATUS_RETRY.  1 (the default) is the
        serial scan, bit-identical to every earlier release.
        Preemption streams always stay serial (the eviction pass has
        no cross-lane revalidation form)."""
        self._check_stream_jobs(batches)
        self._check_batch_axis(batches)
        has_distinct = self._has_distinct(batches)
        preempt = self._preempt_on(has_distinct)
        L = int(self.fused_lanes if lanes is None else lanes)
        if L > 1 and len(batches) > 1 and not preempt:
            return self._solve_lanes(batches, seeds, L, has_distinct)
        self.last_lane_counters = None
        stacked = self._stack_args(batches)
        n_places = np.asarray([pb.n_place for pb in batches], np.int32)
        seed_arr = (np.zeros(len(batches), np.int32) if seeds is None
                    else np.asarray(list(seeds), np.int32))
        (self._used, self._dev_used, out, self.last_evict,
         self.last_waves, self.last_rescore_waves) = _stream_kernel(
            self._dev_node["avail"], self._dev_node["reserved"],
            self._dev_node["valid"], self._dev_node["node_dc"],
            self._dev_node["attr_rank"], self._dev_node["dev_cap"],
            self._used, self._dev_used, stacked, n_places, seed_arr,
            ev_res=self._dev_node.get("ev_res"),
            ev_prio=self._dev_node.get("ev_prio"),
            has_spread=self._has_spread(batches),
            group_count_hint=self._group_count_hint(batches),
            max_waves=self.max_waves, wave_mode=self.wave_mode,
            has_distinct=has_distinct,
            has_devices=self._has_devices(batches),
            stack_commit=self.stack_commit, compact=self._compact,
            pallas_mode=self.pallas, shortlist_c=self.shortlist_c,
            has_preempt=preempt)
        return out

    def _solve_lanes(self, batches: Sequence[PackedBatch], seeds,
                     L: int, has_distinct: bool):
        """Lane-parallel stream dispatch (ISSUE 20): pad B up to a
        multiple of L with zero-place rows (repeating the last batch's
        planes — nothing solves, nothing commits, the padding never
        leaves the device) and run the chunked scan-of-vmap kernel.
        Revalidation counters stay device-side until lane_counters()."""
        B = len(batches)
        pad = (-B) % L
        pbs = list(batches) + [batches[-1]] * pad
        stacked = self._stack_args(pbs)
        n_places = np.asarray(
            [pb.n_place for pb in batches] + [0] * pad, np.int32)
        seed_list = ([0] * B if seeds is None else list(seeds))
        seed_arr = np.asarray(seed_list + [0] * pad, np.int32)
        (self._used, self._dev_used, out, waves, rescores, bounced,
         committed) = _lane_stream_kernel(
            self._dev_node["avail"], self._dev_node["reserved"],
            self._dev_node["valid"], self._dev_node["node_dc"],
            self._dev_node["attr_rank"], self._dev_node["dev_cap"],
            self._used, self._dev_used, stacked, n_places, seed_arr,
            lanes=L, has_spread=self._has_spread(batches),
            group_count_hint=self._group_count_hint(batches),
            max_waves=self.max_waves,
            # "while" drains when EVERY lane converges; the scan
            # shape's per-wave skip cond is per-lane (batched) and
            # would pay the whole wave budget under the vmap
            wave_mode="while",
            has_distinct=has_distinct,
            has_devices=self._has_devices(batches),
            stack_commit=self.stack_commit, compact=self._compact,
            pallas_mode=self.pallas, shortlist_c=self.shortlist_c)
        if pad:
            out, waves, rescores = out[:B], waves[:B], rescores[:B]
            bounced, committed = bounced[:B], committed[:B]
        self.last_evict = None
        self.last_waves = waves
        self.last_rescore_waves = rescores
        self.last_lane_counters = {
            "lanes": L, "chunks": (B + pad) // L,
            "bounced": bounced, "committed": committed}
        return out

    def lane_counters(self) -> Optional[Dict]:
        """Fetch (one sync) the last lane-parallel stream's
        revalidation counters: bounced/committed placement totals and
        the bounce rate the adaptive lane-width controller feeds on
        (fleet.LaneWidthController.note_round).  None after a serial
        stream."""
        lc = self.last_lane_counters
        if lc is None:
            return None
        bounced = int(np.asarray(lc["bounced"]).sum())
        committed = int(np.asarray(lc["committed"]).sum())
        total = bounced + committed
        return {"lanes": int(lc["lanes"]), "chunks": int(lc["chunks"]),
                "bounced": bounced, "committed": committed,
                "bounce_rate": (bounced / total) if total else 0.0}

    def _preempt_on(self, has_distinct: bool) -> bool:
        """Eviction waves run only when the planes are resident and
        the stream has no distinct_hosts groups (the pass statically
        refuses them — those batches keep the host-side walk)."""
        return ("ev_prio" in self._dev_node) and not has_distinct

    def finish_stream(self, out) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]:
        return self._unpack(out)

    def solve_stream_pipelined(self, chunks, seeds=None, pack=None,
                               deltas=None
                               ) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]:
        """True double-buffered wave pipeline.

        Every wave runs three overlapped stages: the DEVICE applies wave
        b's usage-commit delta (scatter into the resident state) and
        solves wave b, while the HOST packs wave b+1 — every dispatch is
        async and the carried usage chains the calls on device, so each
        wave's host-side packing rides entirely under the previous
        wave's delta-apply + solve; ONE concatenated fetch then pays the
        transport round trip once for the whole stream (the fused-call
        schedule pays the same single round trip but serializes ALL
        packing before the first wave can start).

        `chunks`: sequence of PackedBatch, or of ask-lists packed via
        `pack` (default pack_batch_cached).  `deltas`: optional per-wave
        ClusterDelta (or None entries) applied through apply_delta
        BEFORE that wave's solve — the plan-apply feedback path; a delta
        that forces a full repack is still honored, it just pays the
        re-put.  Returns the solve_stream tuple (choice [B,K,TOP_K], ok,
        score, status); per-phase timings land in
        self.last_pipeline_stats (incl. delta_apply_s and the bytes
        each dispatch actually shipped) and per-call wave counts in
        self.last_waves (list of device scalars).
        """
        import time
        chunks = list(chunks)
        if not chunks:
            raise ValueError("solve_stream_pipelined needs >= 1 chunk")
        outs, waves, rescores, evicts = [], [], [], []
        pack_s = dispatch_s = delta_s = 0.0
        bytes_shipped = 0

        def _pack(chunk):
            if isinstance(chunk, PackedBatch):
                return chunk
            pb = (pack or self.pack_batch_cached)(chunk)
            if pb is None:
                raise ValueError(
                    "pipelined chunk fell outside the resident universe")
            return pb

        t0 = time.perf_counter()
        pb_next = _pack(chunks[0])
        pack_s += time.perf_counter() - t0
        for b in range(len(chunks)):
            pb = pb_next
            if deltas is not None and b < len(deltas) \
                    and deltas[b] is not None:
                t0 = time.perf_counter()
                self.apply_delta(deltas[b])
                delta_s += time.perf_counter() - t0
            t0 = time.perf_counter()
            outs.append(self.solve_stream_async(
                [pb], seeds=None if seeds is None else [seeds[b]]))
            waves.append(self.last_waves)
            rescores.append(self.last_rescore_waves)
            evicts.append(self.last_evict)
            bytes_shipped += self.last_dispatch_bytes
            t1 = time.perf_counter()
            dispatch_s += t1 - t0
            if b + 1 < len(chunks):
                # host packs wave b+1 while the device is still applying
                # wave b's delta and solving wave b (async dispatches)
                pb_next = _pack(chunks[b + 1])
                pack_s += time.perf_counter() - t1
        t3 = time.perf_counter()
        packed = np.asarray(outs[0] if len(outs) == 1
                            else self._concat_jit(*outs))
        fetch_s = time.perf_counter() - t3
        self.last_waves = waves
        self.last_rescore_waves = rescores
        self.last_evict = evicts
        self.last_pipeline_stats = {
            "pack_s": pack_s, "dispatch_s": dispatch_s,
            "delta_apply_s": delta_s,
            "fetch_s": fetch_s, "n_dispatches": len(outs),
            "bytes_dispatched": bytes_shipped}
        self.last_solve_stats = {
            "n_batches": len(chunks),
            "wall_s": pack_s + dispatch_s + delta_s + fetch_s}
        return self._unpack(packed)

    @functools.cached_property
    def _concat_jit(self):
        return jax.jit(lambda *xs: jnp.concatenate(xs))

    def wave_traffic(self, batches: Sequence[PackedBatch]) -> Dict:
        """Two-tier per-wave HBM byte model for the CURRENT solve
        configuration (ISSUE 4).

        `bytes_wave1` models a FULL-N pass (the first wave, and every
        rescore-escape wave); `bytes_rewave` models a shortlist-
        resident contention wave — the carried [Gp, C] state plus the
        <= C live gathers, typically 10-100x below the full pass.
        Combined with the measured per-batch counters (last_waves /
        last_rescore_waves) the total is
        ``bytes_wave1 x rescore_waves + bytes_rewave x shortlist
        waves`` — the achieved-GB/s numerator of the roofline report.
        `bytes_per_wave` stays as the full-pass alias for older
        consumers.  Measured counters ride along under "measured" when
        a stream has been dispatched."""
        from . import pallas_kernel as _pk
        from .kernel import (TOP_K as _TOP_K, WAVE_K, _MERGED_W_CAP,
                             _WIDE_W_CAP, resolve_shortlist_c)
        t = self.template
        Np, R = t.avail.shape
        Gp = max(pb.ask_res.shape[0] for pb in batches)
        K = max(pb.p_ask.shape[0] for pb in batches)
        S = t.sp_desired.shape[1]
        has_spread = self._has_spread(batches)
        hint = self._group_count_hint(batches)
        w_cap = (_MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP)
        TK = min(max(WAVE_K, min(2 * hint, w_cap)) + _TOP_K, Np)
        C = (0 if self._has_distinct(batches)
             else resolve_shortlist_c(Np, TK, self.shortlist_c))
        mode = self.pallas
        if mode == "auto":
            V = t.sp_desired.shape[2]
            mode = _pk.resolve_mode(Np, Gp, TK, V, has_spread)
        bytes_wave1, bytes_rewave, passes = model_wave_bytes(
            Np, Gp, K, S, R, has_spread, mode, TK, C)
        out = {"mode": mode, "tile": _pk.pick_tile(Np, Gp),
               "bytes_per_wave": int(bytes_wave1),
               "bytes_wave1": int(bytes_wave1),
               "bytes_rewave": int(bytes_rewave),
               "shortlist_c": int(C),
               "fused_pass_count": passes,
               # resident-delta traffic counters (ISSUE 2): how much
               # node-state each lifecycle path actually dispatched
               "delta": dict(self.delta_counters)}
        m = self.measured_wave_counters()
        if m is not None:
            m["modeled_bytes_total"] = int(
                bytes_wave1 * m["rescore_waves"]
                + bytes_rewave * m["shortlist_waves"])
            out["measured"] = m
        return out

    def trace_attrs(self, batches: Optional[Sequence[PackedBatch]] = None
                    ) -> Dict:
        """Flight-recorder attributes for the last dispatched stream
        (ISSUE 10): the measured wave/rescore/shortlist counters, the
        eviction-commit count, the resident-delta counters and — when
        the solved batches are passed — the full two-tier byte model
        (ICI/DCN tiers included on the mesh solvers, which override
        wave_traffic).  This is the structured form the solve span
        carries instead of the bench-only JSON."""
        attrs: Dict = {"delta": dict(self.delta_counters)}
        m = self.measured_wave_counters()
        if m is not None:
            attrs.update(m)
        ev = self.last_evict
        if ev is not None:
            evs = ev if isinstance(ev, list) else [ev]
            attrs["evict_commits"] = int(sum(
                int(np.asarray(e).any(axis=-1).sum())
                for e in evs if e is not None))
        if self.last_solve_stats is not None:
            attrs["solve"] = dict(self.last_solve_stats)
        if batches:
            try:
                wt = self.wave_traffic(batches)
            except Exception:   # the model must never fail a trace
                wt = None
            if wt is not None:
                attrs["wave_traffic"] = {
                    k: v for k, v in wt.items() if k != "delta"}
        return attrs

    def measured_wave_counters(self) -> Optional[Dict]:
        """Waves / full-rescore waves of the LAST dispatched stream(s)
        (fetch syncs).  shortlist_waves is the remainder — the waves
        that ran shortlist-resident."""
        if self.last_waves is None:
            return None
        def _tot(x):
            if isinstance(x, list):
                return int(sum(int(np.asarray(w).sum()) for w in x))
            return int(np.asarray(x).sum())
        waves = _tot(self.last_waves)
        resc = (_tot(self.last_rescore_waves)
                if self.last_rescore_waves is not None else waves)
        return {"waves_total": waves, "rescore_waves": resc,
                "shortlist_waves": waves - resc}

    def health_counters(self):
        """Fleet health reduction over the RESIDENT planes (ISSUE 15):
        one kernel dispatch + one fetch, no repack, no host walk.
        Returns a telemetry.HealthCounters bit-identical to the numpy
        twin over the same template/usage mirrors."""
        from ..telemetry.health import device_health_counters
        return device_health_counters(self)

    @staticmethod
    def _has_spread(batches: Sequence[PackedBatch]) -> bool:
        return bool(any((pb.sp_col[:, 0] >= 0).any() for pb in batches))

    @staticmethod
    def _has_distinct(batches: Sequence[PackedBatch]) -> bool:
        return bool(any((pb.distinct >= 0).any() for pb in batches))

    @staticmethod
    def _has_devices(batches: Sequence[PackedBatch]) -> bool:
        return bool(any(pb.dev_ask.any() for pb in batches))

    @staticmethod
    def _group_count_hint(batches: Sequence[PackedBatch],
                          floor: int = 6) -> int:
        """Pow2-rounded largest per-group placement count across the
        stream (sizes the kernel's wave width; pow2 rounding bounds the
        number of distinct compiled variants).  `floor` is the pow2
        exponent floor: 6 (=64) for the device path so drain/retry
        batches share one compiled bucket; the host path passes 3 —
        no compile, so the window can track real demand."""
        m = 1
        for pb in batches:
            if pb.n_place:
                cm = pb.__dict__.get("_count_max")
                if cm is None:
                    cm = int(np.bincount(pb.p_ask[:pb.n_place]).max())
                    pb.__dict__["_count_max"] = cm
                m = max(m, cm)
        # floor at 64: one compiled variant covers all small counts
        # (reduced drain/retry batches would otherwise each compile
        # their own bucket). The ceiling mirrors the kernel's wave-width
        # clamp (W = min(2*hint, w_cap)) — larger hints would compile
        # byte-identical programs.
        from .kernel import _MERGED_W_CAP, _WIDE_W_CAP
        gp = max((pb.ask_res.shape[0] for pb in batches), default=0)
        cap = (_MERGED_W_CAP if gp <= MERGED_GP_MAX else _WIDE_W_CAP) // 2
        return min(1 << max(floor, (m - 1).bit_length()), cap)

    @staticmethod
    def _unpack(out) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray]:
        return unpack_stream(out)

    def _stack_args(self, batches: Sequence[PackedBatch]):
        """Stack ask tensors on a leading batch axis, substituting
        cached device-resident constants for the big [G, N] arrays when
        every batch carries the default value (all-zero coll0 / penalty
        / a_host, universe-default host_ok) — the common fresh-job case.
        A host-side compare costs milliseconds; shipping the dense zeros
        costs hundreds on tunneled transports.

        Single-batch dispatches (the pipelined steady-state schedule)
        additionally cache the fully device-put stacked dict ON the
        PackedBatch, keyed by the node epoch: a re-dispatched batch —
        the blocked-eval retry / drain re-eval / same-jobs steady state
        — ships ZERO ask bytes.  last_dispatch_bytes records what each
        call actually moved (the delta-vs-full traffic counters)."""
        B = len(batches)
        if B == 1:
            cached = batches[0].__dict__.get("_dev_stacked")
            if cached is not None and cached[0] == self._node_epoch:
                self.last_dispatch_bytes = 0
                return cached[1]
        else:
            # B>1 twin of the single-batch step cache (ISSUE 20
            # satellite): a steady-state stream re-dispatching the SAME
            # batch objects — the lane sweep's per-family packed memo,
            # the retry drain — ships zero ask bytes.  Keyed on batch
            # identity; the entry holds strong refs to the batches so
            # the ids cannot be recycled while cached.
            skey = tuple(id(pb) for pb in batches)
            cached = self._stream_stack_cache.get(skey)
            if cached is not None and cached[0] == self._node_epoch:
                self.last_dispatch_bytes = 0
                return cached[2]
        stacked = {}
        shipped = 0
        t = self.template
        # identity fast path: repack_asks hands out one shared read-only
        # plane per default [G, N] argument — recognizing it skips both
        # the O(G*N) .any()/array_equal scans and the host stack
        def _all_shared(mats, name):
            shared = self._tz._planes.get(
                (name, self.gp, t.avail.shape[0], t.n_real))
            return shared is not None and all(m is shared for m in mats)
        for name in _ASK_ARGS:
            mats = [getattr(pb, name) for pb in batches]
            if name in ("coll0", "penalty", "a_host") and (
                    _all_shared(mats, name)
                    or not any(m.any() for m in mats)):
                key = (name, B)
                if key not in self._const_cache:
                    self._const_cache[key] = self._put_ask(
                        name,
                        np.zeros((B,) + mats[0].shape, mats[0].dtype))
                stacked[name] = self._const_cache[key]
                continue
            if name == "host_ok" and (
                    _all_shared(mats, name)
                    or all(np.array_equal(m, self._default_host_ok)
                           for m in mats)):
                key = (name, B)
                if key not in self._const_cache:
                    self._const_cache[key] = self._put_ask(
                        name, np.broadcast_to(
                            self._default_host_ok,
                            (B,) + self._default_host_ok.shape).copy())
                stacked[name] = self._const_cache[key]
                continue
            arr = (self._staged_stack(name, mats) if B > 1
                   else np.stack(mats))
            if name in ("host_ok", "penalty") and self._pack_bool_planes:
                # ship the bool planes bitpacked (uint32 lanes, 8x
                # fewer transport bytes); _solve_one unpacks on device
                from .masks import np_pack_bool_u32
                arr = np_pack_bool_u32(arr)
            shipped += arr.nbytes
            stacked[name] = arr
        self.last_dispatch_bytes = shipped
        self.delta_counters["bytes_dispatched_ask"] += shipped
        self.delta_counters["ask_dispatches"] += 1
        if B == 1:
            dev = {k: (self._put_ask(k, v) if isinstance(v, np.ndarray)
                       else v) for k, v in stacked.items()}
            batches[0].__dict__["_dev_stacked"] = (self._node_epoch, dev)
            return dev
        # device-put through a COPY: the staged planes are views into
        # the rotating staging ring, and CPU device_put may alias that
        # memory zero-copy — a later round refilling the ring would
        # corrupt the cached device arrays through the alias
        dev = {k: (self._put_ask(k, np.array(v))
                   if isinstance(v, np.ndarray) else v)
               for k, v in stacked.items()}
        if len(self._stream_stack_cache) >= 4:
            self._stream_stack_cache.pop(
                next(iter(self._stream_stack_cache)))
        self._stream_stack_cache[skey] = (self._node_epoch,
                                          tuple(batches), dev)
        return dev

    def _check_batch_axis(self, batches: Sequence[PackedBatch]) -> None:
        """A full repack can change the padded node axis; batches packed
        before it carry [G, Np_old] planes and must be re-packed."""
        Np = self.template.avail.shape[0]
        for pb in batches:
            if pb.host_ok.shape[1] != Np:
                raise ValueError(
                    "PackedBatch predates a full repack (node axis "
                    f"{pb.host_ok.shape[1]} != {Np}); re-pack its asks")

    @staticmethod
    def _check_stream_jobs(batches: Sequence[PackedBatch]) -> None:
        seen: set = set()
        for pb in batches:
            keys = getattr(pb, "job_keys", None)
            if keys:
                overlap = seen & keys
                if overlap:
                    raise ValueError(
                        f"job {overlap} appears in multiple batches of "
                        "one stream; job-scoped state (distinct_hosts, "
                        "anti-affinity, spread) would not be visible "
                        "across them")
                seen |= keys

    def solve_parallel(self, batches: Sequence[PackedBatch]
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray]:
        """Optimistic-parallel variant of solve_stream: all B batches
        solve concurrently against the CURRENT usage snapshot (each with
        a distinct tie-break seed), then a serial on-device revalidation
        pass commits them in order and bounces placements that no longer
        fit — the reference's worker/plan-applier split, fused into one
        device call.  Bounced placements come back STATUS_RETRY with all
        score slots nulled; the caller resubmits them in a later stream.
        Higher throughput than solve_stream, weaker in-batch visibility
        (batches don't see each other's scoring state at all, only the
        revalidation)."""
        self._check_stream_jobs(batches)
        self._check_batch_axis(batches)
        stacked = self._stack_args(batches)
        n_places = np.asarray([pb.n_place for pb in batches], np.int32)
        seeds = np.arange(1, len(batches) + 1, dtype=np.int32)
        self._used, self._dev_used, out = _parallel_kernel(
            self._dev_node["avail"], self._dev_node["reserved"],
            self._dev_node["valid"], self._dev_node["node_dc"],
            self._dev_node["attr_rank"], self._dev_node["dev_cap"],
            self._used, self._dev_used, stacked, n_places, seeds,
            has_spread=self._has_spread(batches),
            group_count_hint=self._group_count_hint(batches),
            max_waves=self.max_waves,
            has_distinct=self._has_distinct(batches),
            has_devices=self._has_devices(batches))  # wave_mode: the parallel
        # kernel's vmap over sibling batches always wants "while" (its
        # default) — a cond would run every budget wave for every lane
        return self._unpack(out)

    def _staged_stack(self, name: str, mats) -> np.ndarray:
        """Pow2-bucketed preallocated staging for the fused path's
        B>1 stacked ask planes (ISSUE 20 satellite): `np.stack`
        allocates a fresh [B, ...] block per arg per round, which at
        128-member rounds is the dispatch stage's single biggest host
        cost — these buffers are keyed (arg, pow2(B), row shape) and
        reused round over round, copying rows in place.  TWO buffers
        rotate per key: CPU `device_put` may alias the host memory
        zero-copy and the coordinator keeps exactly one round in
        flight, so the previous round's dispatch can still be reading
        buffer A while this round fills buffer B."""
        B = len(mats)
        bucket = 1 << max(0, (B - 1).bit_length())
        key = (name, bucket, mats[0].shape, mats[0].dtype.str)
        ring = self._stage_cache.get(key)
        if ring is None:
            ring = [np.empty((bucket,) + mats[0].shape, mats[0].dtype),
                    np.empty((bucket,) + mats[0].shape, mats[0].dtype),
                    0]
            self._stage_cache[key] = ring
        buf = ring[ring[2]]
        ring[2] ^= 1
        for i, m in enumerate(mats):
            buf[i] = m
        return buf[:B]

    # ------------------------------------------------ retrace guard
    @staticmethod
    def compile_count() -> int:
        """Total compiled variants across the resident dispatch
        kernels (the jit compile-cache probe behind the retrace-count
        regression guard, nomadlint JIT203's runtime twin): steady-state
        streams over a fixed node/ask universe must not grow this —
        every new entry is a silent recompile eating the PR 1/2 wins.
        Returns -1 when the probe is unavailable (jax version without
        _cache_size)."""
        total = 0
        for fn in (_stream_kernel, _parallel_kernel,
                   _lane_stream_kernel):
            try:
                total += fn._cache_size()
            except (AttributeError, TypeError):
                # jax version without the _cache_size probe
                return -1
        return total

    def usage(self) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch the carried device usage (one sync — call sparingly)."""
        return np.asarray(self._used), np.asarray(self._dev_used)

    def plane_checksum(self) -> int:
        """Fingerprint the DEVICE-resident node planes (one fetch —
        call at quiesce points only).  Must equal
        tensorize.template_checksum(self.template) whenever the mesh
        is healthy: the delta-scatter path, a repack, and an elastic
        recover all have to land the device planes bit-identical to
        the raft-fed host template (ISSUE 14 invariant harness)."""
        from .tensorize import plane_crc
        t = self.template
        dn = self._dev_node
        meta = f"{t.n_real}:{','.join(t.node_ids)}".encode()
        return plane_crc(dn["avail"], dn["reserved"], dn["valid"],
                         dn["node_dc"], dn["attr_rank"], dn["dev_cap"],
                         ev_prio=dn.get("ev_prio"),
                         ev_res=dn.get("ev_res"), meta=meta)

    def reset_usage(self, used0: Optional[np.ndarray] = None,
                    dev_used0: Optional[np.ndarray] = None) -> None:
        t = self.template
        self._used = self._put_node(
            "used", t.used0 if used0 is None else used0)
        self._dev_used = self._put_node(
            "dev_used", t.dev_used0 if dev_used0 is None else dev_used0)
