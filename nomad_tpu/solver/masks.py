"""Standalone feasibility-mask kernel + bitpacked boolean planes.

Computes only the static [G, N] feasibility mask (constraints + dc +
host-evaluated ops) without the placement scan — used by the system
scheduler, which forces placements onto specific nodes and only needs
the mask (reference analog: feasible.go checks without rank/limit).

Bitpacking: the solve's boolean planes (feasibility, penalty,
distinct-blocking) are one int8 lane per (group, node) cell when they
ride along the fused wave kernel, and one full bool per cell on the
host/device fetch path.  `pack_bool_u32` folds 32 node columns into one
uint32 lane — 8x fewer HBM bytes per wave re-read of the static planes
(kernel.py feeds the pallas pass packed words) and 8x fewer transport
bytes when a mask is fetched whole (`static_feasibility` below fetches
words and unpacks host-side).  Bit j of word w is node column
``w * 32 + j``; the node axis must be a multiple of 32, which every
tensorize padding (pow2 >= 32, or 1024-multiples) guarantees.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .kernel import _op_eval

#: node columns folded per packed word
PACK_LANES = 32


def pack_bool_u32(mask: jnp.ndarray) -> jnp.ndarray:
    """[..., N] bool/int mask -> [..., ceil(N/32)] uint32 words (jnp;
    traceable inside jit).  Node axes below a 32-multiple (tiny test
    pads) zero-fill the trailing bits."""
    n = mask.shape[-1]
    if n % PACK_LANES:
        pad = PACK_LANES - n % PACK_LANES
        mask = jnp.concatenate(
            [mask, jnp.zeros(mask.shape[:-1] + (pad,), mask.dtype)],
            axis=-1)
        n += pad
    bits = mask.astype(jnp.uint32).reshape(
        mask.shape[:-1] + (n // PACK_LANES, PACK_LANES))
    shifts = jnp.arange(PACK_LANES, dtype=jnp.uint32)
    return (bits << shifts).sum(axis=-1, dtype=jnp.uint32)


def unpack_bool_u32(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """[..., N // 32] uint32 -> [..., n] bool (jnp; traceable inside
    jit and inside a pallas kernel body)."""
    shifts = jnp.arange(PACK_LANES, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(words.shape[:-1]
                        + (words.shape[-1] * PACK_LANES,))[..., :n] != 0


def np_pack_bool_u32(mask: np.ndarray) -> np.ndarray:
    """Host-side (numpy) twin of pack_bool_u32."""
    n = mask.shape[-1]
    if n % PACK_LANES:
        pad = PACK_LANES - n % PACK_LANES
        mask = np.concatenate(
            [mask, np.zeros(mask.shape[:-1] + (pad,), mask.dtype)],
            axis=-1)
        n += pad
    bits = np.asarray(mask, bool).reshape(
        mask.shape[:-1] + (n // PACK_LANES, PACK_LANES))
    weights = (np.uint32(1) << np.arange(PACK_LANES, dtype=np.uint32))
    return (bits * weights).sum(axis=-1, dtype=np.uint64).astype(np.uint32)


def np_unpack_bool_u32(words: np.ndarray, n: int) -> np.ndarray:
    """Host-side (numpy) twin of unpack_bool_u32."""
    shifts = np.arange(PACK_LANES, dtype=np.uint32)
    bits = (words[..., None] >> shifts) & np.uint32(1)
    return bits.reshape(words.shape[:-1]
                        + (words.shape[-1] * PACK_LANES,))[..., :n] != 0


@jax.jit
def _feas_kernel(valid, node_dc, attr_rank, dc_ok, host_ok, c_op, c_col,
                 c_rank):
    import jax.numpy as jnp
    from jax import lax

    def per_ask(g):
        vals = attr_rank[:, c_col[g]]
        ok = _op_eval(vals, c_op[g], c_rank[g])
        base = valid & dc_ok[g][node_dc] & host_ok[g]
        return base & ok.all(axis=1)

    Gp = c_op.shape[0]
    feas = lax.map(per_ask, jnp.arange(Gp))
    # fetch bitpacked words, not bools: the [G, N] plane crosses the
    # transport 8x smaller (the system scheduler fetches this whole)
    return pack_bool_u32(feas)


def static_feasibility(pb) -> np.ndarray:
    """[G, N] bool mask for a PackedBatch (fetched as packed uint32
    words, unpacked host-side)."""
    words = _feas_kernel(pb.valid, pb.node_dc, pb.attr_rank, pb.dc_ok,
                         pb.host_ok, pb.c_op, pb.c_col, pb.c_rank)
    return np_unpack_bool_u32(np.asarray(words), pb.valid.shape[0])
