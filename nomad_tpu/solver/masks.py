"""Standalone feasibility-mask kernel.

Computes only the static [G, N] feasibility mask (constraints + dc +
host-evaluated ops) without the placement scan — used by the system
scheduler, which forces placements onto specific nodes and only needs
the mask (reference analog: feasible.go checks without rank/limit).
"""
from __future__ import annotations

import jax
import numpy as np

from .kernel import _op_eval


@jax.jit
def _feas_kernel(valid, node_dc, attr_rank, dc_ok, host_ok, c_op, c_col,
                 c_rank):
    import jax.numpy as jnp
    from jax import lax

    def per_ask(g):
        vals = attr_rank[:, c_col[g]]
        ok = _op_eval(vals, c_op[g], c_rank[g])
        base = valid & dc_ok[g][node_dc] & host_ok[g]
        return base & ok.all(axis=1)

    Gp = c_op.shape[0]
    return lax.map(per_ask, jnp.arange(Gp))


def static_feasibility(pb) -> np.ndarray:
    """[G, N] bool mask for a PackedBatch."""
    out = _feas_kernel(pb.valid, pb.node_dc, pb.attr_rank, pb.dc_ok,
                       pb.host_ok, pb.c_op, pb.c_col, pb.c_rank)
    return np.asarray(out)
