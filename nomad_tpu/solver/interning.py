"""String interning for tensorization.

The key trick (SURVEY §7.3): Go's constraint comparisons <,<=,>,>= are
*lexical* string comparisons (reference: scheduler/feasible.go
checkLexicalOrder). We intern each attribute column's observed values —
node values plus constraint operands — with ORDER-PRESERVING ranks, so a
lexical comparison becomes an integer comparison on device, exactly.
"""
from __future__ import annotations

from typing import Dict, Iterable, List


class Interner:
    """Plain string -> dense int id (no ordering guarantees)."""

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self._strs: List[str] = []

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
        return i

    def lookup(self, i: int) -> str:
        return self._strs[i]

    def items(self):
        return self._ids.items()

    def __len__(self) -> int:
        return len(self._strs)


class RankColumn:
    """Order-preserving interning for one attribute column.

    Build with the full value universe (node values + operand literals),
    then `rank(value)` is monotone in lexical order: a < b (strings)
    iff rank(a) < rank(b) (ints).
    """

    MISSING = -1

    def __init__(self, values: Iterable[str]):
        uniq = sorted(set(values))
        self._rank = {v: i for i, v in enumerate(uniq)}
        self._values = uniq

    def rank(self, value: str) -> int:
        return self._rank.get(value, self.MISSING)

    def insertion(self, value: str) -> int:
        """bisect_left of `value` in the universe: every rank < insertion
        sorts strictly before `value`, every rank >= insertion sorts at or
        after it. Lets ordered comparisons against operands OUTSIDE the
        built universe stay exact (used by Tensorizer.repack_asks)."""
        import bisect
        return bisect.bisect_left(self._values, value)

    @property
    def n_values(self) -> int:
        return len(self._values)

    def value(self, rank: int) -> str:
        return self._values[rank]
