// Native host solve: the interactive-latency twin of the wave kernel.
//
// A singleton eval on a small cluster finishes its arithmetic in tens
// of microseconds; the numpy twin (solver/host.py host_solve_kernel)
// pays ~1ms of interpreter/ufunc overhead for the same math.  This
// translation unit is a line-for-line port of that numpy kernel — same
// wave loop, same f32 formulas, same tie-breaks, same XLA gather/
// scatter edge semantics — compiled once and driven through ctypes
// (solver/native.py).  tests/test_native_solver.py asserts bitwise-
// identical placements against the numpy twin, which is itself
// differential-tested against the device kernel.
//
// Reference analog: the in-process Go solve (scheduler/generic_sched.go
// :427 SetJob → stack.Select); this file is the TPU framework's answer
// to "an eval must not pay a device round trip when the cluster is
// small" (SURVEY §7.3).
//
// Everything is plain C++17 + libm; no external dependencies.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {

constexpr int TOP_K = 4;
constexpr float NEG_INF = -1e30f;
constexpr float SCORE_BIN = 0.05f;

// op codes (solver/tensorize.py)
enum { OP_NONE = 0, OP_EQ, OP_NE, OP_LT, OP_LE, OP_GT, OP_GE,
       OP_IS_SET, OP_NOT_SET };

struct Shape {
  int Np, Gp, A, C, CA, S, V, R, D, K;
};

inline bool op_eval(int32_t val, int32_t op, int32_t rank) {
  const bool found = val >= 0;
  switch (op) {
    case OP_EQ: return found && val == rank;
    case OP_NE: return !(found && val == rank);
    case OP_LT: return found && val < rank;
    case OP_LE: return found && val <= rank;
    case OP_GT: return found && val > rank;
    case OP_GE: return found && val >= rank;
    case OP_IS_SET: return found;
    case OP_NOT_SET: return !found;
    default: return true;
  }
}

// exact descending top-k per row; ties -> lower index first (the
// numpy twin's stable argsort of -score)
void top_k_row(const float* score, int n, int k, float* out_s,
               int32_t* out_i, std::vector<int>& scratch) {
  scratch.resize(n);
  for (int i = 0; i < n; ++i) scratch[i] = i;
  const int kk = std::min(k, n);
  std::partial_sort(scratch.begin(), scratch.begin() + kk, scratch.end(),
                    [&](int a, int b) {
                      if (score[a] != score[b]) return score[a] > score[b];
                      return a < b;
                    });
  for (int i = 0; i < kk; ++i) {
    out_i[i] = static_cast<int32_t>(scratch[i]);
    out_s[i] = score[scratch[i]];
  }
  for (int i = kk; i < k; ++i) {   // n < k pad (cannot happen: TK<=Np)
    out_i[i] = 0;
    out_s[i] = NEG_INF;
  }
}

}  // namespace

extern "C" int nomad_host_solve(
    // node template
    const float* avail, const float* reserved, float* used,
    const uint8_t* valid, const int32_t* node_dc, const int32_t* attr_rank,
    // ask programs
    const float* ask_res, const float* ask_desired, const int32_t* distinct,
    const uint8_t* dc_ok, const uint8_t* host_ok, const float* coll0,
    const uint8_t* penalty, const int32_t* c_op, const int32_t* c_col,
    const int32_t* c_rank, const int32_t* a_op, const int32_t* a_col,
    const int32_t* a_rank, const float* a_weight, const float* a_host,
    const int32_t* sp_col, const float* sp_weight, const uint8_t* sp_targeted,
    const float* sp_desired, const float* sp_implicit, float* sp_used,
    const float* dev_cap, float* dev_used, const float* dev_ask,
    const int32_t* p_ask, int n_place,
    // shape + mode
    int Np, int Gp, int A, int C, int CA, int S, int V, int R, int D, int K,
    int NDC, int seed, int has_spread, int group_count_hint, int max_waves,
    int stack_commit, int w_cap,
    // outputs
    int32_t* out_idx, uint8_t* out_ok, float* out_score,
    int32_t* out_nfeas, int32_t* out_nexh, int32_t* out_dimexh,
    uint8_t* out_unfinished, int32_t* out_waves,
    uint8_t* out_feas, int32_t* out_consf,
    // optional static-program cache (PreparedRun): when static_ready
    // is nonzero, feas/aff/spread hoists are READ from these buffers
    // instead of recomputed; on a 0->1 first run they are filled.
    // Null buffers = compute locally every call (the generic path).
    int static_ready, uint8_t* feas_buf, float* aff_buf,
    int32_t* consf_buf, int32_t* spv_buf, float* spd_buf) {
  const int per_group = group_count_hint > 0 ? group_count_hint : K / 8;
  const int WAVE_K = 32;
  const int TK = std::min(std::max(WAVE_K, std::min(2 * per_group, w_cap))
                          + TOP_K, Np);
  const int W = std::max(TK - TOP_K, 1);

  // ---------- wave-invariant program ----------
  std::vector<uint8_t> feas_loc;
  std::vector<float> aff_loc;
  std::vector<int32_t> consf_loc;
  std::vector<int32_t> spv_loc;
  std::vector<float> spd_loc;
  const bool cached = feas_buf != nullptr;
  if (!cached) {
    feas_loc.resize(static_cast<size_t>(Gp) * Np);
    aff_loc.resize(static_cast<size_t>(Gp) * Np);
    consf_loc.assign(static_cast<size_t>(Gp) * C, 0);
  }
  uint8_t* feas = cached ? feas_buf : feas_loc.data();
  float* aff = cached ? aff_buf : aff_loc.data();
  int32_t* consf = cached ? consf_buf : consf_loc.data();
  if (!(cached && static_ready)) {
  if (cached) std::fill(consf, consf + static_cast<size_t>(Gp) * C, 0);
  for (int g = 0; g < Gp; ++g) {
    for (int n = 0; n < Np; ++n) {
      const bool base = valid[n] && dc_ok[g * NDC + node_dc[n]]
                        && host_ok[g * Np + n];
      bool all_ok = true;
      bool failed_already = false;
      for (int c = 0; c < C; ++c) {
        const int32_t col = c_col[g * C + c];
        const int32_t v = attr_rank[n * A + col];
        const bool ok = op_eval(v, c_op[g * C + c], c_rank[g * C + c]);
        if (!ok) {
          if (base && !failed_already) consf[g * C + c] += 1;
          failed_already = true;
          all_ok = false;
        }
      }
      feas[g * Np + n] = base && all_ok;
      // f32 accumulation order matches the numpy twin: sum the
      // affinity weights first, then add a_host
      float a = 0.0f;
      for (int c = 0; c < CA; ++c) {
        const int32_t col = a_col[g * CA + c];
        const int32_t v = attr_rank[n * A + col];
        if (op_eval(v, a_op[g * CA + c], a_rank[g * CA + c]))
          a += a_weight[g * CA + c];
      }
      aff[g * Np + n] = a + a_host[g * Np + n];
    }
  }
  }  // end !(cached && static_ready)
  // hoisted spread lookups
  if (!cached && has_spread) {
    spv_loc.resize(static_cast<size_t>(S) * Gp * Np);
    spd_loc.resize(static_cast<size_t>(S) * Gp * Np);
  }
  int32_t* sp_vnode = cached ? spv_buf : spv_loc.data();
  float* sp_des = cached ? spd_buf : spd_loc.data();
  if (has_spread && !(cached && static_ready)) {
    for (int s = 0; s < S; ++s) {
      for (int g = 0; g < Gp; ++g) {
        const int32_t col = sp_col[g * S + s];
        for (int n = 0; n < Np; ++n) {
          int32_t v = attr_rank[n * A + std::max(col, 0)];
          if (col < 0) v = -1;
          // XLA gather: clamp OOB
          float desired = sp_desired[(g * S + s) * V
                                     + std::min(std::max(v, 0), V - 1)];
          if (v < 0) desired = -1.0f;
          if (desired < 0) desired = sp_implicit[g * S + s];
          sp_vnode[(static_cast<size_t>(s) * Gp + g) * Np + n] = v;
          sp_des[(static_cast<size_t>(s) * Gp + g) * Np + n] = desired;
        }
      }
    }
  }

  // tie-break jitter (bit-exact uint32 hash of the jitted kernel)
  std::vector<float> jitter(static_cast<size_t>(Gp) * Np, 0.0f);
  if (seed != 0) {
    for (int g = 0; g < Gp; ++g) {
      const uint32_t gh = static_cast<uint32_t>(g) * 7919u
                          + static_cast<uint32_t>(seed);
      for (int n = 0; n < Np; ++n) {
        uint32_t h = static_cast<uint32_t>(n) * 2654435761u
                     + gh * 40503u;
        h = (h ^ (h >> 16)) * 2246822519u;
        jitter[g * Np + n] = static_cast<float>(h & 1023u)
                             * (SCORE_BIN / 1023.0f);
      }
    }
  }
  std::vector<int32_t> g_off(Gp, 0);
  if (seed != 0) {
    for (int g = 0; g < Gp; ++g) {
      const uint32_t gh = (static_cast<uint32_t>(g) * 2654435761u)
                          ^ (static_cast<uint32_t>(seed) * 2246822519u);
      g_off[g] = static_cast<int32_t>((gh >> 8) % static_cast<uint32_t>(W));
    }
  }

  // ---------- resource-row dedup ----------
  // binpack and raw fit depend on (g, n) only through ask_res[g] /
  // dev_ask[g]; most batches carry few distinct rows (config-1's ten
  // groups share four).  Computing the expensive pieces once per
  // DISTINCT row per wave cuts the powf count by the duplication
  // factor with bit-identical results.
  std::vector<int> row_id(Gp, 0);
  std::vector<int> row_rep;                   // first g of each row
  for (int g = 0; g < Gp; ++g) {
    int found = -1;
    for (size_t r = 0; r < row_rep.size(); ++r) {
      const int g2 = row_rep[r];
      if (std::memcmp(ask_res + g * R, ask_res + g2 * R,
                      sizeof(float) * R) == 0
          && std::memcmp(dev_ask + g * D, dev_ask + g2 * D,
                         sizeof(float) * D) == 0) {
        found = static_cast<int>(r);
        break;
      }
    }
    if (found < 0) {
      found = static_cast<int>(row_rep.size());
      row_rep.push_back(g);
    }
    row_id[g] = found;
  }
  const int NR = static_cast<int>(row_rep.size());

  // ---------- wave state ----------
  std::vector<uint8_t> done(K, 0);
  std::fill(out_idx, out_idx + static_cast<size_t>(K) * TOP_K, 0);
  std::fill(out_ok, out_ok + static_cast<size_t>(K) * TOP_K, 0);
  std::fill(out_score, out_score + static_cast<size_t>(K) * TOP_K, NEG_INF);
  std::fill(out_nfeas, out_nfeas + K, 0);
  std::fill(out_nexh, out_nexh + K, 0);
  std::fill(out_dimexh, out_dimexh + static_cast<size_t>(K) * R, 0);

  std::vector<float> score(static_cast<size_t>(Gp) * Np);
  std::vector<uint8_t> placeable(static_cast<size_t>(Gp) * Np);
  std::vector<uint8_t> feas_b(static_cast<size_t>(Gp) * Np);
  // per distinct resource row (not per group): raw fit, per-dim fit,
  // device fit, binpack score
  std::vector<uint8_t> row_fit(static_cast<size_t>(NR) * Np);
  std::vector<uint8_t> row_fitd(static_cast<size_t>(NR) * Np * R);
  std::vector<uint8_t> row_devfit(static_cast<size_t>(NR) * Np);
  std::vector<float> row_binpack(static_cast<size_t>(NR) * Np);
  std::vector<float> coll(static_cast<size_t>(Gp) * Np);
  std::vector<uint8_t> blocked(static_cast<size_t>(Gp) * Np);
  std::vector<int32_t> hit(static_cast<size_t>(Gp) * Np);
  std::vector<float> top_s(static_cast<size_t>(Gp) * TK);
  std::vector<int32_t> top_i(static_cast<size_t>(Gp) * TK);
  std::vector<int> scratch;
  std::vector<float> sv_row(Np);
  std::vector<int32_t> rank(K), cand(K), Mg(Gp), n_cand(Gp), act_g(Gp);
  std::vector<uint8_t> cand_okv(K), commitv(K), fail_nowv(K);
  std::vector<float> cand_s(K);
  std::vector<int32_t> nfeas_g(Gp), nexh_g(Gp);
  std::vector<int32_t> dimexh_g(static_cast<size_t>(Gp) * R);
  std::vector<uint8_t> grp_any(Gp);
  // interleave scratch
  const int Vs = V;
  const bool interleave = has_spread && Vs <= 8 && !stack_commit;
  const int TKv = interleave ? (TK + Vs) / (Vs + 1) : 0;
  std::vector<float> tab_s;
  std::vector<int32_t> tab_i;
  std::vector<int> vord(Vs + 1);
  std::vector<float> int_s(TK);
  std::vector<int32_t> int_i(TK);

  int wave = 0;
  for (; wave < max_waves; ++wave) {
    bool any_active = false;
    for (int p = 0; p < n_place && p < K; ++p)
      if (!done[p]) { any_active = true; break; }
    if (!any_active) break;

    // rebuild coll / distinct blocking from committed outputs
    std::memcpy(coll.data(), coll0,
                sizeof(float) * static_cast<size_t>(Gp) * Np);
    std::fill(hit.begin(), hit.end(), 0);
    for (int p = 0; p < K; ++p) {
      if (done[p] && out_ok[p * TOP_K]) {
        const int g = p_ask[p];
        const int ch = out_idx[p * TOP_K];
        coll[g * Np + ch] += 1.0f;
        const int32_t dg = distinct[g];
        if (dg >= 0) hit[dg * Np + ch] += 1;
      }
    }
    for (int g = 0; g < Gp; ++g) {
      const int32_t dg = distinct[g];
      for (int n = 0; n < Np; ++n)
        blocked[g * Np + n] =
            dg >= 0 && hit[std::max(dg, 0) * Np + n] > 0;
    }

    // ---------- batched scoring ----------
    // per-row pass: fit, per-dim fit, device fit, binpack (the powf
    // pair) computed once per DISTINCT resource row
    for (int rr = 0; rr < NR; ++rr) {
      const int g0 = row_rep[rr];
      for (int n = 0; n < Np; ++n) {
        bool fit = true;
        for (int r = 0; r < R; ++r) {
          const float after = used[n * R + r] + ask_res[g0 * R + r];
          const bool fd = after <= avail[n * R + r];
          row_fitd[(rr * Np + n) * R + r] = fd;
          fit = fit && fd;
        }
        bool dfit = true;
        for (int d = 0; d < D; ++d)
          dfit = dfit && (dev_used[n * D + d] + dev_ask[g0 * D + d]
                          <= dev_cap[n * D + d]);
        row_fit[rr * Np + n] = fit;
        row_devfit[rr * Np + n] = dfit;
        const float denom_cpu = avail[n * R + 0];
        const float denom_mem = avail[n * R + 1];
        float binpack = 0.0f;
        if (fit && dfit && denom_cpu > 0 && denom_mem > 0) {
          const float util_cpu = used[n * R + 0] + ask_res[g0 * R + 0]
                                 + reserved[n * R + 0];
          const float util_mem = used[n * R + 1] + ask_res[g0 * R + 1]
                                 + reserved[n * R + 1];
          const float free_cpu =
              1.0f - util_cpu / std::max(denom_cpu, 1.0f);
          const float free_mem =
              1.0f - util_mem / std::max(denom_mem, 1.0f);
          float raw = 20.0f - (std::pow(10.0f, free_cpu)
                               + std::pow(10.0f, free_mem));
          raw = std::min(std::max(raw, 0.0f), 18.0f);
          binpack = raw / 18.0f;
        }
        row_binpack[rr * Np + n] = binpack;
      }
    }
    for (int g = 0; g < Gp; ++g) {
      const float adesired = ask_desired[g];
      const int rr = row_id[g];
      int nf = 0, ne = 0;
      int de[8] = {0};
      bool ga = false;
      for (int n = 0; n < Np; ++n) {
        const bool fit = row_fit[rr * Np + n];
        const bool dfit = row_devfit[rr * Np + n];
        const bool fb = feas[g * Np + n] && !blocked[g * Np + n];
        feas_b[g * Np + n] = fb;
        const bool pl = fb && fit && dfit;
        placeable[g * Np + n] = pl;
        ga = ga || pl;
        if (fb && valid[n]) {
          ++nf;
          if (!(fit && dfit)) ++ne;
          for (int r = 0; r < R && r < 8; ++r)
            if (!row_fitd[(rr * Np + n) * R + r]) ++de[r];
        }
        if (!pl) {
          // unplaceable: the numpy twin computes-then-discards; the
          // score is NEG_INF either way and nothing below reads more
          score[g * Np + n] = NEG_INF;
          continue;
        }
        const float binpack = row_binpack[rr * Np + n];
        const float cl = coll[g * Np + n];
        const float anti = cl > 0 ? -(cl + 1.0f) / adesired : 0.0f;
        const float pen = penalty[g * Np + n] ? -1.0f : 0.0f;
        const float af = aff[g * Np + n];
        float sp_total = 0.0f;
        if (has_spread) {
          for (int s = 0; s < S; ++s) {
            const int32_t col = sp_col[g * S + s];
            const int32_t v =
                sp_vnode[(static_cast<size_t>(s) * Gp + g) * Np + n];
            const float* uv = sp_used + (g * S + s) * V;
            float cur = 0.0f;
            if (v >= 0)
              cur = uv[std::min(std::max(v, 0), V - 1)];
            float minc = std::numeric_limits<float>::infinity();
            float maxc = -std::numeric_limits<float>::infinity();
            bool anyp = false;
            for (int vv = 0; vv < V; ++vv) {
              if (uv[vv] > 0) {
                anyp = true;
                minc = std::min(minc, uv[vv]);
                maxc = std::max(maxc, uv[vv]);
              }
            }
            float contrib;
            if (sp_targeted[g * S + s]) {
              const float desired =
                  sp_des[(static_cast<size_t>(s) * Gp + g) * Np + n];
              const float boost = (desired - (cur + 1.0f))
                                  / std::max(desired, 1e-9f)
                                  * sp_weight[g * S + s];
              contrib = (v < 0) ? -1.0f : (desired <= 0 ? -1.0f : boost);
            } else {
              float even;
              if (!anyp) {
                even = (v < 0) ? -1.0f : 0.0f;
              } else if (cur != minc) {
                even = (minc - cur) / std::max(minc, 1e-9f);
              } else if (minc == maxc) {
                even = -1.0f;
              } else {
                even = (maxc - minc) / std::max(minc, 1e-9f);
              }
              if (v < 0) even = -1.0f;
              if (!anyp) even = 0.0f;
              contrib = even;
            }
            if (col >= 0) sp_total += contrib;
          }
        }
        const bool sp_cnt = sp_total != 0.0f;
        const bool anti_cnt = cl > 0;
        const bool pen_cnt = penalty[g * Np + n];
        const bool aff_cnt = af != 0.0f;
        const float n_scorers = 1.0f + (anti_cnt ? 1.0f : 0.0f)
                                + (pen_cnt ? 1.0f : 0.0f)
                                + (aff_cnt ? 1.0f : 0.0f)
                                + (sp_cnt ? 1.0f : 0.0f);
        float total = (binpack + anti + pen + af + sp_total) / n_scorers;
        if (seed != 0)
          total = std::floor(total / SCORE_BIN) * SCORE_BIN;
        total += jitter[g * Np + n];
        score[g * Np + n] = pl ? total : NEG_INF;
      }
      grp_any[g] = ga;
      nfeas_g[g] = nf;
      nexh_g[g] = ne;
      for (int r = 0; r < R && r < 8; ++r) dimexh_g[g * R + r] = de[r];
    }

    // ---------- per-group top-k (+ optional spread interleave) ----------
    for (int g = 0; g < Gp; ++g)
      top_k_row(score.data() + static_cast<size_t>(g) * Np, Np, TK,
                top_s.data() + static_cast<size_t>(g) * TK,
                top_i.data() + static_cast<size_t>(g) * TK, scratch);

    if (interleave) {
      tab_s.assign(static_cast<size_t>(Vs + 1) * TKv, NEG_INF);
      tab_i.assign(static_cast<size_t>(Vs + 1) * TKv, 0);
      for (int g = 0; g < Gp; ++g) {
        if (!(sp_col[g * S + 0] >= 0)) continue;
        const int32_t* vnode =
            sp_vnode + static_cast<size_t>(0) * Gp * Np + g * Np;
        for (int v = 0; v <= Vs; ++v) {
          for (int n = 0; n < Np; ++n) {
            const bool vm = (v < Vs) ? (vnode[n] == v) : (vnode[n] < 0);
            sv_row[n] = vm ? score[g * Np + n] : NEG_INF;
          }
          top_k_row(sv_row.data(), Np, TKv,
                    tab_s.data() + static_cast<size_t>(v) * TKv,
                    tab_i.data() + static_cast<size_t>(v) * TKv, scratch);
        }
        // value visit order: best head candidate first (stable)
        for (int v = 0; v <= Vs; ++v) vord[v] = v;
        std::stable_sort(vord.begin(), vord.end(), [&](int a, int b) {
          return tab_s[static_cast<size_t>(a) * TKv]
                 > tab_s[static_cast<size_t>(b) * TKv];
        });
        for (int j = 0; j < TK; ++j) {
          const int vj = vord[j % (Vs + 1)];
          const int row = j / (Vs + 1);
          int_i[j] = tab_i[static_cast<size_t>(vj) * TKv + row];
          int_s[j] = tab_s[static_cast<size_t>(vj) * TKv + row];
        }
        // compact holes to the tail (stable partition by finiteness)
        int w = 0;
        for (int j = 0; j < TK; ++j)
          if (int_s[j] > NEG_INF / 2) {
            top_i[static_cast<size_t>(g) * TK + w] = int_i[j];
            top_s[static_cast<size_t>(g) * TK + w] = int_s[j];
            ++w;
          }
        for (int j = 0; j < TK; ++j)
          if (!(int_s[j] > NEG_INF / 2)) {
            top_i[static_cast<size_t>(g) * TK + w] = int_i[j];
            top_s[static_cast<size_t>(g) * TK + w] = int_s[j];
            ++w;
          }
      }
    }

    // ---------- candidate assignment ----------
    std::fill(act_g.begin(), act_g.end(), 0);
    for (int p = 0; p < K; ++p) {
      const bool active = !done[p] && p < n_place;
      rank[p] = active ? act_g[p_ask[p]]++ : 0;
    }
    for (int g = 0; g < Gp; ++g) {
      int nc = 0;
      for (int j = 0; j < TK; ++j)
        if (top_s[static_cast<size_t>(g) * TK + j] > NEG_INF / 2) ++nc;
      n_cand[g] = nc;
      Mg[g] = std::min(std::max(std::min(nc, W), 1), W);
    }
    const int rot = (seed == 0) ? 0 : wave;
    for (int p = 0; p < K; ++p) {
      const bool active = !done[p] && p < n_place;
      const int g = p_ask[p];
      const int cr = stack_commit
          ? 0 : (rank[p] + g_off[g] + rot) % Mg[g];
      cand[p] = top_i[static_cast<size_t>(g) * TK + cr];
      cand_s[p] = top_s[static_cast<size_t>(g) * TK + cr];
      cand_okv[p] = active && cand_s[p] > NEG_INF / 2;
      fail_nowv[p] = active && !grp_any[g];
      rank[p] = cr;  // keep the slot for the fall-through record below
    }

    // ---------- same-wave conflict checks (serial, index order) ----------
    // per-node cumulative resource fit
    {
      std::vector<std::pair<int, std::vector<float>>> dummy;  // unused
      // prior resource sums per node via flat maps (K is small here)
      std::vector<float> prior(static_cast<size_t>(K) * R, 0.0f);
      std::vector<float> prior_dev(static_cast<size_t>(K) * D, 0.0f);
      {
        // node -> accumulated vec; use a dense [Np, R] accumulator
        std::vector<float> accR(static_cast<size_t>(Np) * R, 0.0f);
        std::vector<float> accD(static_cast<size_t>(Np) * D, 0.0f);
        for (int p = 0; p < K; ++p) {
          if (!cand_okv[p]) continue;
          const int n = cand[p];
          const int g = p_ask[p];
          for (int r = 0; r < R; ++r) {
            prior[p * R + r] = accR[n * R + r];
            accR[n * R + r] += ask_res[g * R + r];
          }
          for (int d = 0; d < D; ++d) {
            prior_dev[p * D + d] = accD[n * D + d];
            accD[n * D + d] += dev_ask[g * D + d];
          }
        }
      }
      // distinct rank + spread quota ranks
      std::vector<int32_t> dg_rank(K, 0);
      if (true) {
        std::vector<int32_t> cnt(static_cast<size_t>(Np) * Gp, 0);
        for (int p = 0; p < K; ++p) {
          const int g = p_ask[p];
          const int32_t dg = distinct[g];
          if (!(cand_okv[p] && dg >= 0)) continue;
          dg_rank[p] = cnt[cand[p] * Gp + dg]++;
        }
      }
      std::vector<uint8_t> sp_okv(K, 1);
      if (has_spread) {
        std::vector<int32_t> gv_cnt;
        for (int s = 0; s < S; ++s) {
          gv_cnt.assign(static_cast<size_t>(Gp) * V, 0);
          for (int p = 0; p < K; ++p) {
            if (!cand_okv[p]) continue;
            const int g = p_ask[p];
            const int32_t col = sp_col[g * S + s];
            const int32_t v = attr_rank[cand[p] * A + std::max(col, 0)];
            const bool has_s = col >= 0 && v >= 0;
            if (!has_s) continue;
            const int vc = std::max(v, 0);
            const int rank_gv = gv_cnt[g * V + std::min(vc, V - 1)]++;
            // quota
            const float* uv = sp_used + (g * S + s) * V;
            float quota;
            if (sp_targeted[g * S + s]) {
              float des = sp_desired[(g * S + s) * V
                                     + std::min(vc, V - 1)];
              if (des < 0) des = sp_implicit[g * S + s];
              quota = std::max(
                  1.0f, des - uv[std::min(vc, V - 1)]);
            } else if (wave < std::max(max_waves / 2, 1)) {
              float minc = std::numeric_limits<float>::infinity();
              float maxc = 0.0f;
              bool anyp = false;
              for (int vv = 0; vv < V; ++vv)
                if (uv[vv] > 0) {
                  anyp = true;
                  minc = std::min(minc, uv[vv]);
                  maxc = std::max(maxc, uv[vv]);
                }
              if (!anyp) minc = 0.0f;
              if (!std::isfinite(minc)) minc = 0.0f;
              const float share =
                  std::ceil(static_cast<float>(act_g[g])
                            / static_cast<float>(V));
              const float level = std::max(maxc, minc + share);
              quota = std::max(1.0f, level - uv[std::min(vc, V - 1)]);
            } else {
              quota = std::numeric_limits<float>::infinity();
            }
            if (!(static_cast<float>(rank_gv) < quota)) sp_okv[p] = 0;
          }
        }
      }

      // ---------- commit ----------
      for (int p = 0; p < K; ++p) {
        const int g = p_ask[p];
        bool fits = true;
        if (cand_okv[p]) {
          for (int r = 0; r < R; ++r)
            fits = fits && (used[cand[p] * R + r] + prior[p * R + r]
                            + ask_res[g * R + r]
                            <= avail[cand[p] * R + r]);
          for (int d = 0; d < D && fits; ++d)
            fits = fits && (dev_used[cand[p] * D + d]
                            + prior_dev[p * D + d] + dev_ask[g * D + d]
                            <= dev_cap[cand[p] * D + d]);
        }
        const int32_t dgv = distinct[g];
        const bool dg_ok = dgv < 0 || dg_rank[p] == 0;
        commitv[p] = cand_okv[p] && fits && dg_ok && sp_okv[p];
      }
    }

    // apply commits + record results
    for (int p = 0; p < K; ++p) {
      const int g = p_ask[p];
      if (commitv[p]) {
        for (int r = 0; r < R; ++r)
          used[cand[p] * R + r] += ask_res[g * R + r];
        for (int d = 0; d < D; ++d)
          dev_used[cand[p] * D + d] += dev_ask[g * D + d];
        if (has_spread) {
          for (int s = 0; s < S; ++s) {
            const int32_t col = sp_col[g * S + s];
            const int32_t v = attr_rank[cand[p] * A + std::max(col, 0)];
            // XLA scatter: OOB updates dropped
            if (col >= 0 && v >= 0 && v < V)
              sp_used[(g * S + s) * V + v] += 1.0f;
          }
        }
      }
      const bool newly = commitv[p] || fail_nowv[p];
      if (newly) {
        const int cr = rank[p];
        for (int t = 0; t < TOP_K; ++t) {
          const int off = cr + t;
          const float s = (off < TK)
              ? top_s[static_cast<size_t>(g) * TK + off] : NEG_INF;
          const int32_t i = (off < TK)
              ? top_i[static_cast<size_t>(g) * TK + off] : 0;
          out_idx[p * TOP_K + t] = i;
          out_score[p * TOP_K + t] = s;
          out_ok[p * TOP_K + t] = (s > NEG_INF / 2) && commitv[p];
        }
        out_nfeas[p] = nfeas_g[g];
        out_nexh[p] = nexh_g[g];
        for (int r = 0; r < R; ++r)
          out_dimexh[p * R + r] = dimexh_g[g * R + r];
        done[p] = 1;
      }
    }
  }

  for (int p = 0; p < K; ++p)
    out_unfinished[p] = !done[p] && p < n_place;
  *out_waves = wave;
  if (out_feas)
    std::memcpy(out_feas, feas, static_cast<size_t>(Gp) * Np);
  if (out_consf)
    std::memcpy(out_consf, consf,
                static_cast<size_t>(Gp) * C * sizeof(int32_t));
  return 0;
}
