"""Host (numpy) mirror of the wave-solve kernel, for latency mode.

The tunneled TPU transport costs ~100ms per device round trip; an
interactive singleton eval (one job, a small cluster) finishes its
entire solve in well under a millisecond of arithmetic.  SURVEY §7.3
prescribes a host fallback for exactly this regime (reference analog:
the in-process Go solve, scheduler/generic_sched.go:427) — the worker
picks the path by batch/cluster size, and the semantics MUST be the
kernel's: this module is a line-for-line numpy port of
`kernel.solve_kernel` (same wave loop, same scoring formulas, same
tie-breaks), differential-tested to produce identical placements.

Scope: exact only where the device kernel is exact — the dispatch
gate (`prefer_host`) excludes padded node counts that would take the
device's `approx_max_k` path, so host argsort and device top_k agree.

Shortlist note (ISSUE 4): the device kernel's contention waves may
re-rank a carried top-C shortlist instead of re-scoring all N
(kernel.py `shortlist_c`).  This twin deliberately stays FULL-RESCORE
on every wave: it is the semantic reference the shortlist path must
equal bit-for-bit — the kernel only takes a shortlist wave when its
validity triggers PROVE the result identical to this full rescore,
and escapes back to a full-N wave otherwise.  tests/test_shortlist.py
pins that contract; `n_rescore == n_waves` here by construction.
"""
from __future__ import annotations

import numpy as np

from . import score_spec as _score_spec
from .kernel import (EV_PRIORITY_DELTA, MAX_WAVES, MERGED_GP_MAX, NEG_INF,
                     TOP_K, WAVE_K, _APPROX_MIN_NP, _MERGED_W_CAP,
                     _SELECT_SUM_MAX_V, _WIDE_W_CAP, SolveResult)
from .tensorize import (OP_EQ, OP_GE, OP_GT, OP_IS_SET, OP_LE, OP_LT,
                        OP_NE, OP_NOT_SET, R_CPU, R_MEM)

#: spec-driver shim: every scoring float op this twin executes comes
#: from solver/score_spec.py through these numpy ops
_NP_OPS = _score_spec.NumpyOps()

# dispatch gate defaults: the host path wins whenever the numpy wave
# loop (microseconds per wave at these sizes) beats one transport
# round trip.  Above these sizes the device's fused throughput takes
# over; at/above _APPROX_MIN_NP the device kernel switches to
# approx_max_k and exactness would be lost anyway.
HOST_MAX_PLACE = 1024
HOST_MAX_CELLS = 1 << 18         # Gp * Np budget per wave


def prefer_host(n_nodes_padded: int, n_asks: int, n_place: int) -> bool:
    """Should this problem solve on host?  (The worker's path pick —
    reference: the always-in-process scheduler, nomad/worker.go.)"""
    return (n_nodes_padded < _APPROX_MIN_NP
            and n_place <= HOST_MAX_PLACE
            and n_nodes_padded * max(n_asks, 1) <= HOST_MAX_CELLS)


def _top_k(score: np.ndarray, k: int):
    """Exact descending top-k per row, ties broken by LOWER index first
    — lax.top_k's documented order."""
    order = np.argsort(-score, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(score, order, axis=1), order.astype(np.int32)


def _static_program(avail, valid, node_dc, attr_rank, dc_ok,
                    host_ok, c_op, c_col, c_rank, a_op, a_col, a_rank,
                    a_weight, a_host, sp_col, sp_desired, sp_implicit,
                    has_spread, cache=None):
    """The wave-invariant tensors: static feasibility + per-constraint
    filtered counts, affinity scores, hoisted spread lookups.  These
    depend only on the ask programs and the node template, so repeated
    evals with identical programs (the steady-state service workload)
    hit `cache` instead of recomputing — the host path's analog of the
    kernel's one-compile-many-calls amortization."""
    f32 = np.float32
    key = None
    if cache is not None:
        # the bytes themselves key the dict (equality-checked) — a
        # 64-bit pre-hash could silently collide two programs
        key = (c_op.tobytes(), c_col.tobytes(), c_rank.tobytes(),
               a_op.tobytes(), a_col.tobytes(), a_rank.tobytes(),
               a_weight.tobytes(), a_host.tobytes(),
               dc_ok.tobytes(), host_ok.tobytes(),
               sp_col.tobytes(), sp_desired.tobytes(),
               sp_implicit.tobytes(), bool(has_spread))
        hit = cache.get(key)
        if hit is not None:
            return hit
    Np = avail.shape[0]
    Gp = c_op.shape[0]
    S = sp_col.shape[1]
    V = sp_desired.shape[2]

    # vals3[g, n, c] = attr_rank[n, c_col[g, c]] — one gather for all
    # groups (the per-group loop dominated the solve cost)
    vals3 = attr_rank[:, c_col].transpose(1, 0, 2)       # [Gp, Np, C]
    ok3 = _op_eval3(vals3, c_op, c_rank)
    base = valid[None, :] & dc_ok[:, node_dc] & host_ok
    passed_prev = np.cumprod(
        np.concatenate([np.ones((Gp, Np, 1), bool), ok3[:, :, :-1]],
                       axis=2), axis=2).astype(bool)
    first_fail = base[:, :, None] & passed_prev & ~ok3
    cons_filtered = first_fail.sum(axis=1).astype(np.int32)  # [Gp, C]
    feas = base & ok3.all(axis=2)

    avals3 = attr_rank[:, a_col].transpose(1, 0, 2)
    match3 = _op_eval3(avals3, a_op, a_rank)
    aff_score = ((match3 * a_weight[:, None, :]).sum(axis=2)
                 + np.asarray(a_host, f32)).astype(f32)

    if has_spread:
        sp_vnode = np.full((S, Gp, Np), -1, np.int32)
        sp_des = np.zeros((S, Gp, Np), f32)
        for s in range(S):
            col = sp_col[:, s]
            has = col >= 0
            v = attr_rank[:, np.maximum(col, 0)].T.astype(np.int32)
            v = np.where(has[:, None], v, -1)
            # XLA gather semantics: out-of-range indices CLAMP
            desired = np.take_along_axis(
                np.asarray(sp_desired[:, s], f32),
                np.clip(v, 0, V - 1), axis=1)
            desired = np.where(v >= 0, desired, f32(-1.0))
            desired = np.where(desired < 0,
                               np.asarray(sp_implicit[:, s],
                                          f32)[:, None], desired)
            sp_vnode[s] = v
            sp_des[s] = desired
    else:
        sp_vnode = sp_des = None

    out = (feas, cons_filtered, aff_score, sp_vnode, sp_des)
    if cache is not None:
        if len(cache) > 256:
            cache.clear()
        cache[key] = out
    return out


def _op_eval3(vals: np.ndarray, op: np.ndarray, rank: np.ndarray
              ) -> np.ndarray:
    """[Gp, Np, C] variant of _op_eval (same semantics, one pass)."""
    found = vals >= 0
    rk = rank[:, None, :]
    eq = found & (vals == rk)
    res = np.ones_like(found)
    opb = op[:, None, :]
    res = np.where(opb == OP_EQ, eq, res)
    res = np.where(opb == OP_NE, ~eq, res)
    res = np.where(opb == OP_LT, found & (vals < rk), res)
    res = np.where(opb == OP_LE, found & (vals <= rk), res)
    res = np.where(opb == OP_GT, found & (vals > rk), res)
    res = np.where(opb == OP_GE, found & (vals >= rk), res)
    res = np.where(opb == OP_IS_SET, found, res)
    res = np.where(opb == OP_NOT_SET, ~found, res)
    return res


def host_solve_kernel(avail, reserved, used0, valid, node_dc, attr_rank,
                      ask_res, ask_desired, distinct, dc_ok, host_ok,
                      coll0, penalty,
                      c_op, c_col, c_rank, a_op, a_col, a_rank, a_weight,
                      a_host, sp_col, sp_weight, sp_targeted, sp_desired,
                      sp_implicit, sp_used0, dev_cap, dev_used0, dev_ask,
                      p_ask, n_place, seed=0, *, has_spread=True,
                      group_count_hint=0, max_waves=0,
                      stack_commit=False,
                      static_cache=None, has_preempt=False,
                      ev_res=None, ev_prio=None,
                      ask_prio=None, learned=None,
                      region_bias=None) -> SolveResult:
    """Numpy port of kernel.solve_kernel — see that docstring for the
    wave semantics.  Every formula, window size, and tie-break matches;
    tests/test_host_solver.py asserts bitwise-equal placements.

    Scoring is spec-DRIVEN: this twin assembles the plane context and
    calls score_spec.evaluate_wave — the float ops live in ONE place
    (solver/score_spec.py) shared with the jit kernel.  `learned` is
    the optional precomputed [Gp, Np] learned-head plane (score_spec's
    reserved slot) and `region_bias` the cross-region placement
    affinity plane (ISSUE 13); None leaves the scorer byte-identical
    to a spec without the term."""
    f32 = np.float32
    avail = np.asarray(avail, f32)
    reserved = np.asarray(reserved, f32)
    used = np.array(used0, f32)
    ask_res = np.asarray(ask_res, f32)
    dev_cap = np.asarray(dev_cap, f32)
    dev_used = np.array(dev_used0, f32)
    dev_ask = np.asarray(dev_ask, f32)
    sp_used = np.array(sp_used0, f32)
    max_waves = max_waves or MAX_WAVES

    Np = avail.shape[0]
    Gp = ask_res.shape[0]
    S = sp_col.shape[1]
    R = avail.shape[1]
    K = p_ask.shape[0]
    per_group = group_count_hint if group_count_hint > 0 else K // 8
    w_cap = _MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP
    TK = min(max(WAVE_K, min(2 * per_group, w_cap)) + TOP_K, Np)
    W = max(TK - TOP_K, 1)
    ks = np.arange(K)
    gs = np.arange(Gp)
    g_idx = np.asarray(p_ask, np.int64)

    # ---------- wave-invariant program (cached across evals) ----------
    V = sp_desired.shape[2]
    feas, cons_filtered, aff_score, sp_vnode, sp_des = _static_program(
        avail, valid, node_dc, attr_rank, dc_ok, host_ok,
        c_op, c_col, c_rank, a_op, a_col, a_rank, a_weight, a_host,
        sp_col, sp_desired, sp_implicit, has_spread, cache=static_cache)
    pen_score, pen_counts = _score_spec.static_terms(_NP_OPS, penalty)

    # tie-break jitter (kernel's uint32 hash, bit-exact)
    u32 = np.uint32
    with np.errstate(over="ignore"):
        h = (np.arange(Np, dtype=u32)[None, :] * u32(2654435761)
             + (gs.astype(u32)[:, None] * u32(7919)
                + u32(seed)) * u32(40503))
        h = (h ^ (h >> u32(16))) * u32(2246822519)
    SCORE_BIN = _score_spec.SCORE_BIN
    jitter = (np.zeros((Gp, Np), f32) if seed == 0 else
              (h & u32(1023)).astype(f32) * f32(SCORE_BIN / 1023.0))

    def group_scores(used, dev_used, coll, sp_used, blocked):
        """Spec-driven scoring: assembles the plane context and defers
        every float op to score_spec.evaluate_wave (nomadlint SCORE6xx
        flags scoring arithmetic hand-added back here)."""
        ctx = dict(
            used=used, dev_used=dev_used, coll=coll, sp_used=sp_used,
            blocked=blocked, avail=avail, reserved=reserved,
            ask_res=ask_res, ask_desired=ask_desired, dev_cap=dev_cap,
            dev_ask=dev_ask, feas=feas, pen_score=pen_score,
            pen_counts=pen_counts, aff_score=aff_score,
            has_devices=True, has_spread=has_spread, sp_col=sp_col,
            sp_weight=sp_weight, sp_targeted=sp_targeted,
            vnode=sp_vnode, des=sp_des, S=S, V=V, shape=(Gp, Np),
            seed=seed, jitter=jitter, learned=learned,
            region_bias=region_bias)
        return _score_spec.evaluate_wave(_NP_OPS, ctx)

    # ---------- in-kernel preemption planes (kernel.py twin) ----------
    if has_preempt:
        EVW = ev_prio.shape[1]
        ev_prio_i = np.asarray(ev_prio, np.int32)
        ev_res_f = np.asarray(ev_res, f32)
        ask_prio_i = np.asarray(ask_prio, np.int32)
        ev_slot_ok = ((ev_prio_i[None, :, :] >= 0)
                      & (ask_prio_i[:, None, None] - ev_prio_i[None, :, :]
                         >= EV_PRIORITY_DELTA))       # [Gp, Np, E]
        EVT = np.zeros((Np, EVW), bool)
        out_evict = np.zeros((K, EVW), bool)
    else:
        out_evict = None

    # ---------- wave loop state ----------
    done = np.zeros(K, bool)
    out_idx = np.zeros((K, TOP_K), np.int32)
    out_ok = np.zeros((K, TOP_K), bool)
    out_score = np.full((K, TOP_K), NEG_INF, f32)
    out_nfeas = np.zeros(K, np.int32)
    out_nexh = np.zeros(K, np.int32)
    out_dimexh = np.zeros((K, R), np.int32)
    out_wave = np.full(K, -1, np.int32)
    wave = 0
    Vs = sp_desired.shape[2]

    while wave < max_waves:
        active = ~done & (ks < n_place)
        if not active.any():
            break

        committed = done & out_ok[:, 0]
        chosen = np.where(committed, out_idx[:, 0], 0).astype(np.int64)
        coll = coll0.astype(f32).copy()
        np.add.at(coll, (g_idx, chosen), committed.astype(f32))
        dg_all = np.asarray(distinct)[g_idx]
        hit = np.zeros((Gp, Np), np.int32)
        np.add.at(hit, (np.maximum(dg_all, 0), chosen),
                  (committed & (dg_all >= 0)).astype(np.int32))
        hit = hit > 0
        blocked = (hit[np.maximum(distinct, 0)]
                   & (distinct >= 0)[:, None])

        score, placeable, feas_b, fit, fit_dims, dev_fit = group_scores(
            used, dev_used, coll, sp_used, blocked)
        top_score, top_idx = _top_k(score, TK)

        # spread-aware candidate interleaving (kernel's slot-0 path;
        # bypassed in stack mode — see kernel.py)
        if has_spread and Vs <= 8 and not stack_commit:
            has0 = sp_col[:, 0] >= 0
            vnode = sp_vnode[0]
            TKv = -(-TK // (Vs + 1))
            tabs_i, tabs_s = [], []
            for v in range(Vs + 1):
                vmask = (vnode == v) if v < Vs else (vnode < 0)
                sv = np.where(vmask, score, f32(NEG_INF))
                ts, ti = _top_k(sv, TKv)
                tabs_i.append(ti)
                tabs_s.append(ts)
            tab_i = np.stack(tabs_i, axis=1)
            tab_s = np.stack(tabs_s, axis=1)
            vord = np.argsort(-tab_s[:, :, 0], axis=1,
                              kind="stable").astype(np.int64)
            j = np.arange(TK)
            vj = vord[:, j % (Vs + 1)]
            inter_i = tab_i[gs[:, None], vj, (j // (Vs + 1))[None, :]]
            inter_s = tab_s[gs[:, None], vj, (j // (Vs + 1))[None, :]]
            order = np.argsort((inter_s <= NEG_INF / 2).astype(np.int32),
                               axis=1, kind="stable")
            inter_i = np.take_along_axis(inter_i, order, axis=1)
            inter_s = np.take_along_axis(inter_s, order, axis=1)
            top_idx = np.where(has0[:, None], inter_i, top_idx)
            top_score = np.where(has0[:, None], inter_s, top_score)

        grp_any = placeable.any(axis=1)

        n_feas_g = (feas_b & valid[None, :]).sum(axis=1)
        n_exh_g = (feas_b & valid[None, :] & ~(fit & dev_fit)).sum(axis=1)
        dim_exh_g = (feas_b[:, :, None] & valid[None, :, None]
                     & ~fit_dims).sum(axis=1)

        grp_onehot = ((g_idx[None, :] == gs[:, None])
                      & active[None, :]).astype(np.int32)
        act_g = grp_onehot.sum(axis=1)
        rank = (np.cumsum(grp_onehot, axis=1) - grp_onehot)[g_idx, ks]
        n_cand = (top_score > NEG_INF / 2).sum(axis=1)
        M = np.clip(np.minimum(n_cand, W), 1, W)
        with np.errstate(over="ignore"):
            g_hash = ((gs.astype(u32) * u32(2654435761))
                      ^ (u32(seed) * u32(2246822519)))
        g_off = (np.zeros(Gp, np.int32) if seed == 0 else
                 ((g_hash >> u32(8)) % u32(W)).astype(np.int32))
        rot = 0 if seed == 0 else wave
        if stack_commit:
            # serial-fidelity commits (kernel.py stack_commit note)
            cr = np.zeros_like(rank)
        else:
            cr = (rank + g_off[g_idx] + rot) % M[g_idx]
        cand = top_idx[g_idx, cr].astype(np.int64)
        cand_score = top_score[g_idx, cr]
        cand_ok = active & (cand_score > NEG_INF / 2)

        fail_now = active & ~grp_any[g_idx]

        # -- same-wave conflict checks (exact serial accumulation) --
        def prior_sum_node(vals):
            out = np.zeros_like(vals)
            acc = {}
            for p in range(K):
                if not cand_ok[p]:
                    continue
                key = int(cand[p])
                prev = acc.get(key)
                if prev is not None:
                    out[p] = prev
                acc[key] = (prev if prev is not None
                            else np.zeros(vals.shape[1], vals.dtype)
                            ) + vals[p]
            return out

        def prior_rank(key, member):
            out = np.zeros(K, np.int32)
            counts = {}
            m = member & cand_ok
            for p in range(K):
                if not m[p]:
                    continue
                kk = int(key[p])
                out[p] = counts.get(kk, 0)
                counts[kk] = out[p] + 1
            return out

        res_k = ask_res[g_idx] * cand_ok[:, None]
        prior = prior_sum_node(res_k)
        fits = ((used[cand] + prior + ask_res[g_idx])
                <= avail[cand]).all(axis=-1)
        dev_k = dev_ask[g_idx] * cand_ok[:, None]
        prior_dev = prior_sum_node(dev_k)
        dev_fits = ((dev_used[cand] + prior_dev + dev_ask[g_idx])
                    <= dev_cap[cand]).all(axis=-1)

        dg = np.asarray(distinct)[g_idx]
        dg_key = cand * np.int64(Gp) + np.maximum(dg, 0)
        dg_ok = prior_rank(dg_key, dg >= 0) == 0

        sp_ok = np.ones(K, bool)
        for s in (range(S) if has_spread else range(0)):
            cols = sp_col[g_idx, s]
            vs = attr_rank[cand, np.maximum(cols, 0)]
            has_s = (cols >= 0) & (vs >= 0)
            vsc = np.maximum(vs, 0).astype(np.int64)
            des_s = np.asarray(sp_desired[:, s], f32)
            use_s = sp_used[:, s]
            des_eff = np.where(
                des_s < 0, np.asarray(sp_implicit[:, s], f32)[:, None],
                des_s)
            present = use_s > 0
            # hi_cnt/lo_cnt: the occupancy band the quota levels
            # against (NOT the spread scorer's minc/maxc — those live
            # in score_spec.term_spread; alias-distinct names keep the
            # driven-backend fingerprint empty)
            hi_cnt = np.max(np.where(present, use_s, f32(0.0)),
                            axis=1)[:, None]
            lo_cnt = np.min(np.where(present, use_s,
                                     np.where(present.any(axis=1)[:, None],
                                              np.inf, 0.0)),
                            axis=1)[:, None]
            lo_cnt = np.where(np.isfinite(lo_cnt), lo_cnt,
                              0.0).astype(f32)
            # even-spread quota for the first half of the wave budget
            # only (kernel.py quota block note)
            share = np.ceil(act_g.astype(f32) / V)[:, None]
            level = np.maximum(hi_cnt, lo_cnt + share)
            even_q = (np.maximum(f32(1.0), level - use_s)
                      if wave < max(max_waves // 2, 1)
                      else np.full_like(use_s, np.inf))
            quota = np.where(
                np.asarray(sp_targeted[:, s])[:, None],
                np.maximum(f32(1.0), des_eff - use_s),
                even_q)
            gv_key = (g_idx * np.int64(V) + vsc) * np.int64(2) + 1
            gv_rank = prior_rank(gv_key, has_s).astype(f32)
            # gather clamps (XLA OOB semantics) — the key stays exact
            sp_ok &= ~has_s | (gv_rank
                               < quota[g_idx, np.minimum(vsc, V - 1)])

        commit = cand_ok & fits & dev_fits & dg_ok & sp_ok
        cm = commit[:, None]

        np.add.at(used, cand, ask_res[g_idx] * cm)
        np.add.at(dev_used, cand, dev_ask[g_idx] * cm)
        if has_spread:
            svals = attr_rank[cand[:, None],
                              np.maximum(sp_col[g_idx], 0)]
            # XLA scatter semantics: out-of-range updates are DROPPED
            okslot = ((sp_col[g_idx] >= 0) & (svals >= 0)
                      & (svals < V) & cm)
            np.add.at(sp_used,
                      (g_idx[:, None], np.arange(S)[None, :],
                       np.clip(svals, 0, V - 1)),
                      okslot.astype(f32))

        # ---------- preemption wave pass (kernel.py twin) ----------
        ev_commit = np.zeros(K, bool)
        if has_preempt:
            want = active & ~commit & ~grp_any[g_idx]
            want_g = np.zeros(Gp, bool)
            np.logical_or.at(want_g, g_idx, want)
            win_s = np.full(Gp, NEG_INF, f32)
            win_i = np.zeros(Gp, np.int32)
            sel_freed = np.zeros((Gp, R), f32)
            sel_mask = np.zeros((Gp, EVW), bool)
            if want.any():
                es = np.arange(EVW)
                base_short = (used[None, :, :] + ask_res[:, None, :]
                              - avail[None, :, :])     # [Gp, Np, R]
                slot_free = ev_slot_ok & ~EVT[None, :, :]
                freed = np.zeros((Gp, Np, R), f32)
                picked = np.zeros((Gp, Np, EVW), bool)
                prank = np.full((Gp, Np, EVW), EVW, np.int32)
                for t in range(EVW):
                    s = np.maximum(base_short - freed, f32(0.0))
                    covered = (s <= 0.0).all(axis=-1)
                    norm = np.maximum(s, f32(1.0))
                    diff = ((s[:, :, None, :] - ev_res_f[None, :, :, :])
                            / norm[:, :, None, :])
                    d2 = diff * diff
                    dist = np.sqrt(((d2[..., 0] + d2[..., 1])
                                    + d2[..., 2]) + d2[..., 3])
                    cand_e = slot_free & ~picked
                    dist = np.where(cand_e, dist, f32(1e30))
                    e_star = np.argmin(dist, axis=-1)  # first min wins
                    take = cand_e.any(axis=-1) & ~covered
                    oh = ((es[None, None, :] == e_star[..., None])
                          & take[..., None])
                    picked = picked | oh
                    prank = np.where(oh, np.int32(t), prank)
                    freed = freed + (ev_res_f[None, :, :, :]
                                     * oh[..., None]).sum(axis=2,
                                                          dtype=f32)
                key = np.where(
                    picked,
                    (np.int32(32768) - ev_prio_i[None, :, :])
                    * np.int32(EVW + 1) + prank,
                    np.int32(2 ** 30))
                seq = np.argsort(key, axis=-1, kind="stable")
                for t in range(EVW):
                    e_t = seq[..., t]
                    oh = es[None, None, :] == e_t[..., None]
                    is_p = (picked & oh).any(axis=-1)
                    vec = (ev_res_f[None, :, :, :]
                           * oh[..., None]).sum(axis=2, dtype=f32)
                    trial = freed - vec
                    still = ((base_short - trial) <= 0.0).all(axis=-1)
                    drop = is_p & still
                    picked = picked & ~(oh & drop[..., None])
                    freed = np.where(drop[..., None], trial, freed)

                covered_f = ((base_short - freed) <= 0.0).all(axis=-1)
                dev_fit_ev = (dev_used[None, :, :] + dev_ask[:, None, :]
                              <= dev_cap[None, :, :]).all(axis=-1)
                ok_node = (covered_f & picked.any(axis=-1) & feas
                           & dev_fit_ev & want_g[:, None])
                after = (used[None, :, :] + ask_res[:, None, :]
                         - freed)
                binpack = _score_spec.rescore_binpack(
                    _NP_OPS, after, avail, reserved)
                ev_score = np.where(ok_node, binpack, f32(NEG_INF))
                wv_s, wv_i = _top_k(ev_score, 1)
                win_s, win_i = wv_s[:, 0], wv_i[:, 0].astype(np.int32)
                sel_freed = freed[gs, win_i]
                sel_mask = picked[gs, win_i]
            ev_any_g = win_s > NEG_INF / 2

            e_cand = win_i[g_idx].astype(np.int64)
            p_ok = want & ev_any_g[g_idx]
            # first member per node wins (prior_rank_any == 0 twin)
            seen_nodes: set = set()
            for p in range(K):
                if not p_ok[p]:
                    continue
                n = int(e_cand[p])
                if n not in seen_nodes:
                    ev_commit[p] = True
                    seen_nodes.add(n)
            ecm = ev_commit[:, None]
            np.add.at(used, e_cand,
                      (ask_res[g_idx] - sel_freed[g_idx]) * ecm)
            np.add.at(dev_used, e_cand, dev_ask[g_idx] * ecm)
            em = sel_mask[g_idx] & ecm
            np.logical_or.at(EVT, e_cand, em)
            if has_spread:
                evals_ = attr_rank[e_cand[:, None],
                                   np.maximum(sp_col[g_idx], 0)]
                ok_es = ((sp_col[g_idx] >= 0) & (evals_ >= 0)
                         & (evals_ < V) & ecm)
                np.add.at(sp_used,
                          (g_idx[:, None], np.arange(S)[None, :],
                           np.clip(evals_, 0, V - 1)),
                          ok_es.astype(f32))
            fail_now = fail_now & ~ev_any_g[g_idx]

        offs = cr[:, None] + np.arange(TOP_K)[None, :]
        pk_idx = top_idx[g_idx[:, None], offs]
        pk_score = top_score[g_idx[:, None], offs]
        pk_ok = pk_score > NEG_INF / 2
        ok_row = pk_ok & cm
        if has_preempt:
            ecol = np.arange(TOP_K)[None, :] == 0
            pk_idx = np.where(ecm, np.where(ecol, e_cand[:, None], 0),
                              pk_idx).astype(np.int32)
            pk_score = np.where(
                ecm, np.where(ecol, win_s[g_idx][:, None], f32(NEG_INF)),
                pk_score)
            ok_row = np.where(ecm, ecol, ok_row)
        newly = commit | ev_commit | fail_now
        upd = newly[:, None]
        out_idx = np.where(upd, pk_idx, out_idx)
        out_score = np.where(upd, pk_score, out_score)
        out_ok = np.where(upd, ok_row, out_ok)
        if has_preempt:
            out_evict = np.where(upd, em & ecm, out_evict)
        out_wave = np.where(commit | ev_commit, wave, out_wave)
        out_nfeas = np.where(newly, n_feas_g[g_idx], out_nfeas)
        out_nexh = np.where(newly, n_exh_g[g_idx], out_nexh)
        out_dimexh = np.where(newly[:, None], dim_exh_g[g_idx],
                              out_dimexh)
        done = done | newly
        wave += 1

    unfinished = ~done & (ks < n_place)
    return SolveResult(
        choice=out_idx, choice_ok=out_ok, score=out_score,
        n_feasible=out_nfeas, n_exhausted=out_nexh,
        dim_exhausted=out_dimexh, feas=feas,
        cons_filtered=cons_filtered, used_final=used,
        dev_used_final=dev_used, n_waves=np.int32(wave),
        unfinished=unfinished, n_rescore=np.int32(wave),
        evict=out_evict,
        commit_wave=(out_wave if has_preempt else None))


class HostResidentSolver:
    """Host twin of resident.ResidentSolver for the interactive path:
    same pack-once / stream-asks surface and the same carried-usage
    semantics, but every solve runs the numpy kernel in-process — one
    singleton eval costs microseconds of arithmetic instead of a
    transport round trip.  Differential-tested batch-for-batch against
    the device stream (tests/test_host_solver.py)."""

    def __init__(self, nodes, probe_asks, allocs_by_node=None,
                 gp=None, kp=None, max_waves: int = 0,
                 stack_commit: bool = False, use_native: bool = True,
                 device_parity: bool = False):
        #: device_parity pins the wave-width hint to the device
        #: kernel's (compile-variant-floored) sizing so a stream solved
        #: here is BITWISE identical to the device stream.  The default
        #: sizes the window to the real per-group demand instead —
        #: ~2x faster per eval; placements remain a valid wave solve
        #: (the width is a scheduling parameter, like the reference's
        #: per-worker shuffled node order), just not bit-matched.
        self.device_parity = device_parity
        from .tensorize import Tensorizer
        self.nodes = list(nodes)
        self.max_waves = max_waves
        self.stack_commit = stack_commit
        self._tz = Tensorizer()
        self.template = self._tz.pack(nodes, probe_asks, allocs_by_node)
        self.gp = gp or self.template.ask_res.shape[0]
        self.kp = kp or self.template.p_ask.shape[0]
        self._drv_cache = {}
        self._row_cache = {}
        # program cache for _static_program: sound because the node
        # template is fixed for this solver's lifetime
        self._static_cache = {}
        # whole-eval PackedBatch cache (stateless asks only): repeated
        # evals with the same job shape — the steady-state service
        # workload — skip repack entirely
        self._eval_cache = {}
        # native (C++) wave kernel: bitwise-same placements as the
        # numpy twin (tests/test_native_solver.py), ~20x less per-eval
        # overhead — the production interactive path (solve_stream's
        # PreparedRun branch; the numpy kernel is the fallback)
        from . import native as native_mod
        self._native = use_native and native_mod.available()
        self._kernel = host_solve_kernel
        t = self.template
        if self._native:
            # carried usage lives in the prepared template's buffers so
            # the C kernel can update it in place (no per-call copies);
            # self._used ALIASES them for the whole solver lifetime
            self._tp = native_mod.PreparedTemplate(t)
            self._preps = {}
            self._used = self._tp.used
            self._dev_used = self._tp.dev_used
        else:
            self._used = np.array(t.used0, np.float32)
            self._dev_used = np.array(t.dev_used0, np.float32)

    def pack_batch(self, asks, job_keys=None):
        pb = self._tz.repack_asks(self.nodes, asks, self.template,
                                  gp=self.gp, kp=self.kp,
                                  drv_cache=self._drv_cache,
                                  row_cache=self._row_cache)
        if pb is not None:
            pb.job_keys = (job_keys if job_keys is not None else
                           {(a.job.namespace, a.job.id) for a in asks})
        return pb

    def pack_batch_cached(self, asks, job_keys=None):
        from .resident import pack_batch_cached
        return pack_batch_cached(self, asks, job_keys)

    def reset_usage(self, used0=None, dev_used0=None) -> None:
        t = self.template
        if self._native:
            self._tp.reset_usage(
                t.used0 if used0 is None else used0,
                t.dev_used0 if dev_used0 is None else dev_used0)
            return
        self._used = np.array(
            t.used0 if used0 is None else used0, np.float32)
        self._dev_used = np.array(
            t.dev_used0 if dev_used0 is None else dev_used0, np.float32)

    def usage(self):
        return self._used.copy(), self._dev_used.copy()

    @staticmethod
    def _host_hint(batches) -> int:
        """Wave-width hint for the in-process path.  The device hint
        floors at 64 purely to bound COMPILED variants; host solves
        have no compile, so the window tracks the real per-group
        demand — a 10-count group sorts ~36 candidates per wave, not
        132."""
        from .resident import ResidentSolver
        return ResidentSolver._group_count_hint(batches, floor=3)

    def solve_stream(self, batches, seeds=None):
        """Same contract as ResidentSolver.solve_stream: returns
        (choice [B, K, TOP_K], ok, score, status [B, K]); usage carries
        batch to batch and across calls."""
        # STATUS_* live in resident.py; import here to avoid a cycle
        from .resident import (STATUS_COMMITTED, STATUS_FAILED,
                               STATUS_RETRY, ResidentSolver)
        hint = (ResidentSolver._group_count_hint(batches)
                if self.device_parity else self._host_hint(batches))
        t = self.template
        B = len(batches)
        K = self.kp
        choice = np.zeros((B, K, TOP_K), np.int32)
        ok = np.zeros((B, K, TOP_K), bool)
        score = np.full((B, K, TOP_K), NEG_INF, np.float32)
        status = np.zeros((B, K), np.int32)
        has_spread = bool(any((pb.sp_col[:, 0] >= 0).any()
                              for pb in batches))
        for b, pb in enumerate(batches):
            seed = 0 if seeds is None else int(seeds[b])
            if self._native:
                # prepared-run fast path: args marshaled once per
                # batch, usage mutates in place in the tp buffers
                from . import native as native_mod
                pkey = (id(pb), hint, has_spread)
                ent = self._preps.get(pkey)
                if ent is None or ent[0] is not pb:
                    if len(self._preps) > 1024:
                        self._preps.clear()
                    pr = native_mod.PreparedRun(
                        self._tp, pb, has_spread, hint,
                        self.max_waves, self.stack_commit)
                    self._preps[pkey] = (pb, pr)
                else:
                    pr = ent[1]
                pr.run(seed)
                choice[b] = pr.out_idx
                score[b] = pr.out_score
                ok[b] = pr.out_score > NEG_INF / 2
                status[b] = np.where(
                    pr.out_ok[:, 0].astype(bool), STATUS_COMMITTED,
                    np.where(pr.out_unfin.astype(bool), STATUS_RETRY,
                             STATUS_FAILED))
                continue
            res = self._kernel(
                t.avail, t.reserved, self._used, t.valid, t.node_dc,
                t.attr_rank, pb.ask_res, pb.ask_desired, pb.distinct,
                pb.dc_ok, pb.host_ok, pb.coll0, pb.penalty, pb.c_op,
                pb.c_col, pb.c_rank, pb.a_op, pb.a_col, pb.a_rank,
                pb.a_weight, pb.a_host, pb.sp_col, pb.sp_weight,
                pb.sp_targeted, pb.sp_desired, pb.sp_implicit,
                pb.sp_used0, t.dev_cap, self._dev_used, pb.dev_ask,
                pb.p_ask, pb.n_place, seed, has_spread=has_spread,
                group_count_hint=hint, max_waves=self.max_waves,
                stack_commit=self.stack_commit,
                static_cache=self._static_cache)
            self._used = res.used_final
            self._dev_used = res.dev_used_final
            choice[b] = res.choice
            score[b] = res.score
            ok[b] = res.score > NEG_INF / 2
            status[b] = np.where(
                res.choice_ok[:, 0], STATUS_COMMITTED,
                np.where(res.unfinished, STATUS_RETRY, STATUS_FAILED))
        return choice, ok, score, status
