"""Solve watchdog: a deadline on device dispatch with a bit-identical
host-twin failover (ISSUE 14).

The device kernel and the numpy host twin produce placement-identical
results (tests/test_host_solver.py), which makes a stuck or wedged
device dispatch recoverable WITHOUT changing any answer: run the
device call on a worker thread with a deadline; on expiry abandon it,
answer from the host twin, quarantine the device path, and re-probe
it with capped jittered exponential backoff.  Every transition lands
in the mesh event log and the flight recorder, and counters surface
through MetricsRegistry.

Disabled by default (deadline None -> the device call runs inline,
zero overhead).  Enable per-instance or fleet-wide via
``NOMAD_TPU_SOLVE_DEADLINE_S``.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Optional

_ENV_DEADLINE = "NOMAD_TPU_SOLVE_DEADLINE_S"


def _env_deadline() -> Optional[float]:
    raw = os.environ.get(_ENV_DEADLINE, "")
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


class SolveWatchdog:
    """Wraps one device dispatch site.  Thread-safe: concurrent solves
    share the quarantine state under a lock; the device probe after
    backoff is claimed by exactly one caller."""

    def __init__(self, deadline_s: Optional[float] = None,
                 base_backoff_s: float = 0.1,
                 max_backoff_s: float = 5.0,
                 seed: int = 0x5EED,
                 event_log=None, tracer=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_deadline())
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._rng = random.Random(seed)
        self._clock = clock
        self._lock = threading.Lock()
        self.quarantined = False
        self._failures = 0            # consecutive deadline expiries
        self._probe_at = 0.0          # next device re-probe time
        self._probing = False         # a caller holds the probe claim
        if event_log is None:
            from ..utils.tracing import global_mesh_events
            event_log = global_mesh_events
        if tracer is None:
            from ..utils.tracing import global_tracer
            tracer = global_tracer
        if metrics is None:
            from ..utils.metrics import global_metrics
            metrics = global_metrics
        self.event_log = event_log
        self.tracer = tracer
        self.metrics = metrics

    # ------------------------------------------------------------ state
    @property
    def enabled(self) -> bool:
        return self.deadline_s is not None

    def _claim_probe(self) -> bool:
        """True when this caller should try the device again: either
        healthy, or quarantined with the backoff elapsed (one caller
        wins the probe; the rest stay on the host twin)."""
        with self._lock:
            if not self.quarantined:
                return True
            if self._probing or self._clock() < self._probe_at:
                return False
            self._probing = True
            return True

    def _note_success(self) -> None:
        with self._lock:
            was = self.quarantined
            self.quarantined = False
            self._failures = 0
            self._probing = False
        if was:
            self.metrics.incr_counter("watchdog.recovered")
            self.event_log.record("watchdog.recovered")

    def _note_expiry(self, label: str, waited_s: float) -> None:
        with self._lock:
            self._failures += 1
            fails = self._failures
            self.quarantined = True
            self._probing = False
            # capped jittered exponential backoff before the next
            # device probe; jitter decorrelates a fleet of workers
            # re-probing a shared device
            delay = min(self.max_backoff_s,
                        self.base_backoff_s * (2 ** (fails - 1)))
            delay *= 0.5 + self._rng.random() / 2.0
            self._probe_at = self._clock() + delay
        self.metrics.incr_counter("watchdog.expired")
        self.metrics.set_gauge("watchdog.consecutive_failures",
                               float(fails))
        self.event_log.record("watchdog.failover", label=label,
                              waited_s=round(waited_s, 4),
                              failures=fails,
                              retry_in_s=round(delay, 4))
        self.tracer.event(label or "solve", "watchdog.failover",
                          waited_s=round(waited_s, 4), failures=fails)

    # -------------------------------------------------------------- run
    def run(self, device_fn: Callable[[], object],
            host_fn: Callable[[], object], label: str = ""):
        """Answer from `device_fn` under the deadline, falling back to
        the bit-identical `host_fn`.  Returns (result, backend) where
        backend is "device", "host_failover" (this call expired) or
        "host_quarantine" (an earlier expiry, backoff not elapsed).

        `device_fn` must BLOCK until its result is materialized
        (dispatch + fetch) — an async handle that only hangs at a
        later fetch would escape the deadline."""
        if not self.enabled:
            return device_fn(), "device"
        if not self._claim_probe():
            self.metrics.incr_counter("watchdog.host_quarantine")
            return host_fn(), "host_quarantine"

        box: dict = {}
        done = threading.Event()

        def _runner():
            try:
                box["result"] = device_fn()
            except BaseException as e:       # noqa: BLE001 — relayed
                box["error"] = e
            done.set()

        t0 = self._clock()
        t = threading.Thread(target=_runner, daemon=True,
                             name="solve-watchdog")
        t.start()
        if done.wait(self.deadline_s) and "result" in box:
            self._note_success()
            return box["result"], "device"
        waited = self._clock() - t0
        if "error" in box:
            # the device path died rather than hung: same failover
            # (quarantine + host answer), but record the cause
            self.event_log.record("watchdog.device_error",
                                  label=label,
                                  error=repr(box["error"]))
        self._note_expiry(label, waited)
        self.metrics.incr_counter("watchdog.host_failover")
        return host_fn(), "host_failover"

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "deadline_s": self.deadline_s,
                    "quarantined": self.quarantined,
                    "consecutive_failures": self._failures}


#: process-wide watchdog consulted by solve.py's _run_kernel; disabled
#: unless NOMAD_TPU_SOLVE_DEADLINE_S is set or a harness configures it
global_watchdog = SolveWatchdog()
