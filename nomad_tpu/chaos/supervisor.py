"""ChaosSupervisor: replays a FaultPlan through the system's own
recovery hooks, one logical step at a time.

The supervisor owns NO clock and NO thread — the driving loop (a test,
or bench's --chaos phase) calls ``advance(step)`` at its own cadence
and the supervisor applies every event due at that step.  Faults that
the target's state machine refuses (a second shard kill while
degraded) are recorded as ``chaos.skipped`` instead of raising, so a
generated plan survives contact with guarded transitions.

Targets are all optional; an event whose target surface is absent is
skipped-and-recorded, which lets one plan drive differently shaped
harnesses (single-region tests vs the federated bench storm).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .injection import InjectionRegistry, global_injections
from .plan import FaultEvent, FaultPlan


class ChaosSupervisor:
    def __init__(self, plan: FaultPlan,
                 elastic=None,            # ElasticShardedResidentSolver
                 federated=None,          # CrossRegionResidentSolver
                 mesh_supervisor=None,    # ElasticMeshSupervisor
                 raft=None,               # RaftNode (leader step-down)
                 injections: Optional[InjectionRegistry] = None,
                 event_log=None,
                 watchdog_deadline_s: float = 0.5):
        if event_log is None:
            from ..utils.tracing import global_mesh_events
            event_log = global_mesh_events
        self.plan = plan
        self.elastic = elastic
        self.federated = federated
        self.mesh_supervisor = mesh_supervisor
        self.raft = raft
        self.injections = (global_injections if injections is None
                           else injections)
        self.event_log = event_log
        self.watchdog_deadline_s = watchdog_deadline_s
        self.applied: List[FaultEvent] = []
        self.skipped: List[FaultEvent] = []
        self.counters: Dict[str, int] = {}
        self._step = -1

    # ---------------------------------------------------------- drive
    def advance(self, step: int) -> List[FaultEvent]:
        """Apply every plan event due at `step` (steps must advance
        monotonically); returns the events actually applied."""
        if step <= self._step:
            return []
        applied = []
        for ev in self.plan.due(step):
            if self._apply(ev):
                applied.append(ev)
                self.applied.append(ev)
                self.counters[ev.kind] = \
                    self.counters.get(ev.kind, 0) + 1
            else:
                self.skipped.append(ev)
                self.event_log.record("chaos.skipped", fault=ev.kind,
                                      step=step, target=str(ev.target))
        self._step = step
        return applied

    def run_to(self, step: int) -> List[FaultEvent]:
        """Advance through every intermediate step (catch-up after a
        driving loop that batches several logical steps per tick)."""
        out = []
        for s in range(self._step + 1, step + 1):
            out.extend(self.advance(s))
        return out

    @property
    def done(self) -> bool:
        return self._step >= self.plan.horizon - 1

    # ---------------------------------------------------------- apply
    def _apply(self, ev: FaultEvent) -> bool:
        fn = getattr(self, f"_ev_{ev.kind}", None)
        if fn is None:
            return False
        ok = fn(ev)
        if ok:
            self.event_log.record(f"chaos.{ev.kind}", step=ev.step,
                                  target=str(ev.target), **ev.args)
        return ok

    def _ev_shard_kill(self, ev: FaultEvent) -> bool:
        sol = self.elastic or (self.federated.solver
                               if self.federated else None)
        if sol is None or sol.mesh_state != "healthy":
            return False
        shard = int(ev.target or 0) % sol.n_shards
        sol.fail_shard(shard)
        return True

    def _ev_shard_recover(self, ev: FaultEvent) -> bool:
        sol = self.elastic or (self.federated.solver
                               if self.federated else None)
        if sol is None or sol.mesh_state != "degraded":
            return False
        sol.recover()
        return True

    def _ev_region_kill(self, ev: FaultEvent) -> bool:
        fed = self.federated
        if fed is None or fed.mesh_state != "healthy":
            return False
        region = ev.target if ev.target is not None \
            else fed.region_names[0]
        fed.fail_region_shard(region,
                              int(ev.args.get("shard_in_region", 0)))
        return True

    def _ev_region_recover(self, ev: FaultEvent) -> bool:
        fed = self.federated
        if fed is None or fed.mesh_state != "degraded":
            return False
        fed.recover_region()
        return True

    def _ev_gossip_flap(self, ev: FaultEvent) -> bool:
        sup = self.mesh_supervisor
        if sup is None or ev.target is None:
            return False
        # a flap is the serf fail->rejoin pair delivered back to back:
        # the supervisor state machine fails the member's shard and
        # immediately rebuilds on the rejoin — the recovery path the
        # real gossip plane would drive over suspicion_timeout
        sup.on_fail(ev.target)
        sup.on_join(ev.target)
        return True

    def _ev_leader_stepdown(self, ev: FaultEvent) -> bool:
        if self.raft is None:
            return False
        return bool(self.raft.step_down())

    def _ev_stuck_solve(self, ev: FaultEvent) -> bool:
        # a sleep comfortably past the watchdog deadline: the device
        # dispatch wedges, the watchdog fails over to the host twin
        stall = float(ev.args.get("sleep_s",
                                  4.0 * self.watchdog_deadline_s))
        self.injections.arm("device_solve", "sleep",
                            budget=int(ev.args.get("budget", 1)),
                            sleep_s=stall)
        return True

    def _ev_slow_solve(self, ev: FaultEvent) -> bool:
        self.injections.arm("device_solve", "sleep",
                            budget=int(ev.args.get("budget", 1)),
                            sleep_s=float(ev.args.get("sleep_s", 0.05)))
        return True

    def _ev_poison_solve(self, ev: FaultEvent) -> bool:
        self.injections.arm("device_solve", "raise",
                            budget=int(ev.args.get("budget", 1)))
        return True

    def _ev_corrupt_delta(self, ev: FaultEvent) -> bool:
        self.injections.arm("delta_row", "mutate",
                            budget=int(ev.args.get("budget", 1)),
                            rows=int(ev.args.get("rows", 1)))
        return True

    # ---------------------------------------------------------- report
    def report(self) -> dict:
        return {"seed": self.plan.seed, "horizon": self.plan.horizon,
                "planned": len(self.plan),
                "applied": len(self.applied),
                "skipped": len(self.skipped),
                "by_kind": dict(sorted(self.counters.items()))}
