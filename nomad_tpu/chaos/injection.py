"""Named injection sites: how scripted faults reach code that has no
natural external hook.

Product code consults a site by name on its hot path; the common case
(nothing armed) is one dict lookup returning None, so sites are safe
to leave in production paths.  A site is armed with a *budget* (how
many times it fires before disarming itself) so a one-shot "stuck
solve" does not wedge every subsequent solve.

Kinds understood by `Injection.fire`:
  * "sleep"  — block for args["sleep_s"] (slow/stuck solves; a stuck
               solve is a sleep longer than the watchdog deadline)
  * "raise"  — raise ChaosInjected (poisoned solve / poisoned eval)
  * "mutate" — no built-in effect; the consulting site reads
               `inj.args` and applies its own corruption (delta-row
               corruption in tests/bench reads args["rows"])

Sites currently consulted:
  * "device_solve"    — inside the device branch of the solve path
                        (solver/solve.py _run_kernel), under the
                        watchdog deadline
  * "delta_row"       — resident delta apply (consulted by the chaos
                        harness around apply_delta)
  * "rpc_transport"   — rpc client attempt loop (transient transport
                        failures for retry/backoff tests)
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class ChaosInjected(Exception):
    """Raised by a "raise"-kind injection — distinguishable from real
    faults so harnesses can assert the failure path they triggered."""


class Injection:
    __slots__ = ("site", "kind", "args", "remaining", "fired")

    def __init__(self, site: str, kind: str, budget: int = 1,
                 **args):
        self.site = site
        self.kind = kind
        self.args = args
        self.remaining = int(budget)
        self.fired = 0

    def fire(self) -> None:
        """Apply the effect (called by the consulting site)."""
        self.fired += 1
        if self.kind == "sleep":
            time.sleep(float(self.args.get("sleep_s", 0.0)))
        elif self.kind == "raise":
            raise ChaosInjected(f"injected fault at {self.site}")
        # "mutate": effect applied by the consulting site via .args


class InjectionRegistry:
    """Thread-safe site table.  `get` pops one firing off the armed
    injection's budget and returns it (None when the site is idle) —
    consult-then-fire is a single atomic claim so concurrent solvers
    cannot double-spend a one-shot fault."""

    def __init__(self):
        self._sites: Dict[str, Injection] = {}
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {}

    def arm(self, site: str, kind: str, budget: int = 1,
            **args) -> Injection:
        inj = Injection(site, kind, budget, **args)
        with self._lock:
            self._sites[site] = inj
        return inj

    def disarm(self, site: str) -> None:
        with self._lock:
            self._sites.pop(site, None)

    def get(self, site: str) -> Optional[Injection]:
        with self._lock:
            inj = self._sites.get(site)
            if inj is None or inj.remaining <= 0:
                return None
            inj.remaining -= 1
            if inj.remaining <= 0:
                self._sites.pop(site, None)
            self.counters[site] = self.counters.get(site, 0) + 1
        return inj

    def armed(self, site: str) -> bool:
        with self._lock:
            inj = self._sites.get(site)
            return inj is not None and inj.remaining > 0

    def reset(self) -> None:
        with self._lock:
            self._sites.clear()
            self.counters.clear()


#: process-wide registry (idle unless a chaos harness arms a site)
global_injections = InjectionRegistry()
