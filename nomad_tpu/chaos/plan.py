"""Fault schedules: scripted or seeded, always deterministic.

A `FaultPlan` is an ordered list of `FaultEvent`s keyed on LOGICAL
steps — the driving loop's iteration counter, never wall time — so the
same plan replays the same storm bit-for-bit regardless of host speed.
`FaultPlan.generate` derives a schedule from (seed, horizon, rates)
with every fault paired to its recovery inside the horizon, and
non-overlapping per fault family (the shard/region state machines
refuse a second kill while degraded, so overlap would just be skipped
noise).
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# fault kinds and their paired recovery kinds (None = self-clearing)
KIND_RECOVERY: Dict[str, Optional[str]] = {
    "shard_kill": "shard_recover",
    "region_kill": "region_recover",
    "gossip_flap": None,          # fail+join pair applied as one event
    "leader_stepdown": None,
    "stuck_solve": None,          # one-shot injection, watchdog clears
    "slow_solve": None,
    "poison_solve": None,
    "corrupt_delta": None,
}

FAULT_KINDS = tuple(KIND_RECOVERY)
RECOVERY_KINDS = tuple(k for k in KIND_RECOVERY.values() if k)


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault (or recovery) at a logical step.

    `target` names the victim where the hook needs one (shard id,
    region name, member id); `args` carries kind-specific knobs
    (e.g. ``{"sleep_s": 2.0}`` for slow_solve, ``{"rows": 3}`` for
    corrupt_delta)."""
    step: int
    kind: str
    target: Optional[object] = None
    args: Dict = field(default_factory=dict)

    def wire(self) -> dict:
        return {"step": self.step, "kind": self.kind,
                "target": self.target, "args": dict(self.args)}

    @staticmethod
    def from_wire(d: dict) -> "FaultEvent":
        return FaultEvent(step=int(d["step"]), kind=d["kind"],
                          target=d.get("target"),
                          args=dict(d.get("args", {})))


class FaultPlan:
    """An immutable, step-ordered fault schedule."""

    def __init__(self, events: Sequence[FaultEvent],
                 seed: Optional[int] = None, horizon: int = 0):
        for ev in events:
            if ev.kind not in KIND_RECOVERY \
                    and ev.kind not in RECOVERY_KINDS:
                raise ValueError(f"unknown fault kind {ev.kind!r}")
        self.events: Tuple[FaultEvent, ...] = tuple(
            sorted(events, key=lambda e: (e.step, e.kind,
                                          str(e.target))))
        self.seed = seed
        self.horizon = int(horizon) if horizon else (
            max((e.step for e in self.events), default=0) + 1)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def due(self, step: int) -> List[FaultEvent]:
        """Events scheduled exactly at `step` (the supervisor's tick
        granularity — callers advance step monotonically)."""
        return [e for e in self.events if e.step == step]

    def wire(self) -> dict:
        return {"seed": self.seed, "horizon": self.horizon,
                "events": [e.wire() for e in self.events]}

    @staticmethod
    def from_wire(d: dict) -> "FaultPlan":
        return FaultPlan([FaultEvent.from_wire(e)
                          for e in d.get("events", [])],
                         seed=d.get("seed"),
                         horizon=int(d.get("horizon", 0)))

    # ------------------------------------------------------- generator
    @staticmethod
    def generate(seed: int, horizon: int,
                 rates: Dict[str, float],
                 shards: Sequence[int] = (),
                 regions: Sequence[str] = (),
                 members: Sequence[str] = (),
                 min_dwell: int = 2,
                 max_dwell: int = 8) -> "FaultPlan":
        """Seeded schedule: for each kind in `rates`, expected
        ``rates[kind] * horizon`` occurrences uniformly over the
        horizon.  Paired kinds (shard/region kills) get a recovery
        after a dwell of [min_dwell, max_dwell] steps, clamped inside
        the horizon, and never overlap another kill of the same family
        (the degraded state machines are single-fault).  Identical
        (seed, horizon, rates, targets) inputs produce the identical
        plan."""
        if isinstance(shards, int):   # count → shard-id range
            shards = range(shards)
        rng = random.Random(seed)
        events: List[FaultEvent] = []
        for kind in sorted(rates):
            if kind not in KIND_RECOVERY:
                raise ValueError(f"unknown fault kind {kind!r}")
            n = max(0, round(rates[kind] * horizon))
            recovery = KIND_RECOVERY[kind]
            busy_until = -1      # same-family non-overlap watermark
            for _ in range(n):
                step = rng.randrange(max(1, horizon - max_dwell - 1))
                if recovery is not None and step <= busy_until:
                    step = busy_until + 1
                    if step >= horizon - min_dwell - 1:
                        break
                target: Optional[object] = None
                if kind == "shard_kill" and shards:
                    target = rng.choice(list(shards))
                elif kind == "region_kill" and regions:
                    target = rng.choice(list(regions))
                elif kind == "gossip_flap" and members:
                    target = rng.choice(list(members))
                args: Dict = {}
                if kind == "slow_solve":
                    args["sleep_s"] = round(rng.uniform(0.05, 0.3), 3)
                if kind == "corrupt_delta":
                    args["rows"] = rng.randrange(1, 4)
                events.append(FaultEvent(step, kind, target, args))
                if recovery is not None:
                    dwell = rng.randrange(min_dwell, max_dwell + 1)
                    rstep = min(step + dwell, horizon - 1)
                    events.append(FaultEvent(rstep, recovery, target))
                    busy_until = rstep
        return FaultPlan(events, seed=seed, horizon=horizon)
