"""Chaos plane: seeded, fully deterministic fault injection plus a
continuously-running invariant harness (ISSUE 14).

Nomad's core promise is surviving failure; this package makes failure
a first-class, replayable *input*.  A `FaultPlan` is a schedule of
`FaultEvent`s keyed on LOGICAL steps (never wall time) — scripted
explicitly or generated from (seed, horizon, rates) — and a
`ChaosSupervisor` replays it through the recovery hooks the system
already owns:

  * shard kill / recover        (ElasticShardedResidentSolver)
  * region kill / recover       (CrossRegionResidentSolver)
  * gossip membership flaps     (ElasticMeshSupervisor / GossipAgent
                                 on_fail / on_join)
  * leader step-down            (RaftNode)
  * slow / stuck / poisoned device solves and delta-row corruption
                                (the `global_injections` site registry,
                                 consulted by solver code)

While a storm runs, an `InvariantHarness` checks end-to-end properties
continuously: no eval lost through broker/shed lanes, no
double-placement, per-node usage conservation bit-identical to a
from-scratch repack at quiesce points, shed/admission accounting
balanced, and device-resident planes checksum-verified against the
raft-fed template after every recovery.

Every applied event lands in the mesh event log (`chaos.*` kinds) so a
storm is auditable after the fact; the same seed replays the same
storm bit-for-bit.
"""
from .plan import FaultEvent, FaultPlan
from .injection import Injection, InjectionRegistry, global_injections
from .supervisor import ChaosSupervisor
from .invariants import InvariantHarness, InvariantViolation

__all__ = ["FaultEvent", "FaultPlan", "Injection", "InjectionRegistry",
           "global_injections", "ChaosSupervisor", "InvariantHarness",
           "InvariantViolation"]
