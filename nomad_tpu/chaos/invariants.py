"""The invariant harness: end-to-end properties checked CONTINUOUSLY
while a fault storm runs, not just at the end of a test.

The harness is a ledger plus a set of check methods.  The driving loop
feeds it ground truth as it happens (evals enqueued, outcomes reached,
allocs placed, usage committed) and calls the checks at every quiesce
point; each failed check appends a structured violation (and records a
``chaos.invariant_violation`` mesh event) instead of raising, so one
broken invariant never masks the others — `raise_if_violated` turns
the accumulated list into an exception at the end.

Checks:
  * eval conservation — every eval the harness saw enter is accounted
    for across terminal outcomes + broker-resident states + shed lane
    (at-least-once, nothing dropped)
  * no double placement — an alloc id placed on two nodes, or the
    same (eval, placement slot) decided twice, trips immediately
  * usage conservation — per-node device-carried usage equals a
    from-scratch host recompute of the ledger, bit-identical
  * shed/admission balance — offered == admitted + shed, and the
    router's shed lane drains only into readmissions
  * plane checksums — device-resident node planes hash-identical to
    the host template (the raft-fed source of truth) at quiesce
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np


class InvariantViolation(AssertionError):
    """One or more invariants failed during a storm."""


class InvariantHarness:
    def __init__(self, event_log=None):
        if event_log is None:
            from ..utils.tracing import global_mesh_events
            event_log = global_mesh_events
        self.event_log = event_log
        self._lock = threading.Lock()
        self.violations: List[dict] = []
        self.checks_run = 0
        # eval ledger: id -> terminal outcome ("" while in flight)
        self._evals: Dict[str, str] = {}
        # alloc ledger: alloc id -> node id
        self._alloc_nodes: Dict[str, str] = {}
        # usage ledger: node id -> summed usage vector (host recompute)
        self._usage: Dict[str, np.ndarray] = {}

    # ------------------------------------------------------------ feed
    def note_enqueued(self, eval_id: str) -> None:
        with self._lock:
            self._evals.setdefault(eval_id, "")

    def note_outcome(self, eval_id: str, outcome: str) -> None:
        """Terminal outcome: "acked", "failed", "shed"... — an eval
        reaching two different terminal outcomes is itself a
        violation (a shed eval later acked is fine: readmission
        overwrites "shed")."""
        with self._lock:
            prev = self._evals.get(eval_id)
            if prev is None:
                self._evals[eval_id] = outcome
                return
            if prev and prev != outcome and prev != "shed":
                self._violate_locked(
                    "eval_conservation",
                    f"eval {eval_id} reached {outcome!r} after {prev!r}")
            self._evals[eval_id] = outcome

    def note_placement(self, alloc_id: str, node_id: str) -> None:
        with self._lock:
            prev = self._alloc_nodes.get(alloc_id)
            if prev is not None and prev != node_id:
                self._violate_locked(
                    "double_placement",
                    f"alloc {alloc_id} placed on {node_id} and {prev}")
            self._alloc_nodes[alloc_id] = node_id

    def note_usage(self, node_id: str, vec) -> None:
        vec = np.asarray(vec, np.float32)
        with self._lock:
            cur = self._usage.get(node_id)
            if cur is None:
                self._usage[node_id] = vec.copy()
            else:
                cur += vec

    # ---------------------------------------------------------- checks
    def check_eval_conservation(self, broker=None,
                                shed_pending: int = 0) -> bool:
        """Everything that entered is terminal, in the broker, or in
        the shed lane.  `shed_pending`: evals currently parked in the
        BlockedEvals shed lane (in flight, not lost)."""
        with self._lock:
            total = len(self._evals)
            terminal = sum(1 for o in self._evals.values() if o)
        in_broker = 0
        if broker is not None:
            st = broker.stats()
            in_broker = (st["total_ready"] + st["total_unacked"]
                         + st["total_blocked"] + st["total_waiting"])
        lost = total - terminal - in_broker - int(shed_pending)
        ok = lost == 0
        if not ok:
            self._violate(
                "eval_conservation",
                f"{lost} eval(s) unaccounted for "
                f"(saw {total}, terminal {terminal}, broker "
                f"{in_broker}, shed {shed_pending})")
        self.checks_run += 1
        return ok

    def check_no_double_placement(self) -> bool:
        # dupes trip inline in note_placement; this quiesce-point call
        # exists so the check shows up in checks_run accounting
        self.checks_run += 1
        return not any(v["check"] == "double_placement"
                       for v in self.violations)

    def check_usage_conservation(self, solver,
                                 baseline: Optional[Dict] = None
                                 ) -> bool:
        """Device-carried per-node usage == from-scratch host recompute
        of the ledger, bit-identical.  `solver` is any resident solver
        exposing `usage()` and `template.node_ids`; `baseline` maps
        node id -> usage vector present before the ledger started
        (template used0 at harness start)."""
        used, _dev_used = solver.usage()
        node_ids = solver.template.node_ids
        ok = True
        with self._lock:
            ledger = {k: v.copy() for k, v in self._usage.items()}
        for i, nid in enumerate(node_ids):
            if i >= solver.template.n_real or \
                    not solver.template.valid[i]:
                continue
            expect = np.zeros(used.shape[1], np.float32)
            if baseline is not None and nid in baseline:
                expect = np.asarray(baseline[nid], np.float32).copy()
            if nid in ledger:
                expect = expect + ledger[nid]
            if not np.array_equal(used[i], expect):
                ok = False
                self._violate(
                    "usage_conservation",
                    f"node {nid} carried usage {used[i].tolist()} != "
                    f"recomputed {expect.tolist()}")
        self.checks_run += 1
        return ok

    def check_shed_accounting(self, admission=None, router=None,
                              shed_pending: int = 0) -> bool:
        """offered == admitted + shed on the admission tier; on the
        router, lifetime sheds == readmitted + still parked."""
        ok = True
        if admission is not None:
            st = admission.stats()
            offered = st.get("offered",
                             st["admitted"] + st["shed"])
            if offered != st["admitted"] + st["shed"]:
                ok = False
                self._violate(
                    "shed_accounting",
                    f"admission offered {offered} != admitted "
                    f"{st['admitted']} + shed {st['shed']}")
        if router is not None:
            st = router.stats()
            counts = st.get("counts", st)
            shed = counts.get("shed", 0)
            readmitted = counts.get("readmitted", 0)
            parked = router.shed_depth()
            if shed != readmitted + parked:
                ok = False
                self._violate(
                    "shed_accounting",
                    f"router shed {shed} != readmitted {readmitted} "
                    f"+ parked {parked}")
        if shed_pending < 0:
            ok = False
            self._violate("shed_accounting",
                          f"negative shed lane depth {shed_pending}")
        self.checks_run += 1
        return ok

    def check_plane_checksums(self, solver) -> bool:
        """Device-resident node planes hash-identical to the host
        template (only meaningful at healthy quiesce points — a
        degraded mesh deliberately zeroes lost tiles)."""
        from ..solver.tensorize import template_checksum
        state = getattr(solver, "mesh_state", "healthy")
        if state != "healthy":
            self.checks_run += 1
            return True
        dev = solver.plane_checksum()
        host = template_checksum(solver.template)
        ok = dev == host
        if not ok:
            self._violate(
                "plane_checksum",
                f"device planes {dev:#010x} != template {host:#010x}")
        self.checks_run += 1
        return ok

    # --------------------------------------------------------- results
    def _violate(self, check: str, message: str) -> None:
        with self._lock:
            self._violate_locked(check, message)

    def _violate_locked(self, check: str, message: str) -> None:
        self.violations.append({"check": check, "message": message})
        if self.event_log is not None:
            self.event_log.record("chaos.invariant_violation",
                                  check=check, message=message)

    @property
    def ok(self) -> bool:
        return not self.violations

    def report(self) -> dict:
        with self._lock:
            by_check: Dict[str, int] = {}
            for v in self.violations:
                by_check[v["check"]] = by_check.get(v["check"], 0) + 1
            return {"ok": not self.violations,
                    "checks_run": self.checks_run,
                    "violations": list(self.violations),
                    "violations_by_check": by_check,
                    "evals_seen": len(self._evals),
                    "allocs_seen": len(self._alloc_nodes)}

    def raise_if_violated(self) -> None:
        if self.violations:
            lines = [f"[{v['check']}] {v['message']}"
                     for v in self.violations]
            raise InvariantViolation(
                f"{len(lines)} invariant violation(s):\n"
                + "\n".join(lines))
