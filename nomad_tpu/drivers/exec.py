"""exec driver: subprocesses jailed in namespaces + chroot
(reference: drivers/exec/driver.go — libcontainer isolation via the
shared executor, task config `command` + `args`).

Same supervision model as raw_exec (detached executor, durable state,
RecoverTask re-attach); the executor additionally enters fresh
mount+pid namespaces, builds a read-only allowlist chroot around the
task's writable /local, /alloc (and /secrets) dirs, and applies cgroup
cpu/memory limits (drivers/isolation.py).  The task sees itself as
pid 1 with only the chroot view of the filesystem.

Fingerprints only where the kernel supports it: on hosts without
namespace privileges the driver reports itself undetected rather than
running tasks with a silently weakened sandbox (the reference exec
driver likewise requires root + cgroups: drivers/exec capabilities).
"""
from __future__ import annotations

import os
from typing import Dict

from ..plugins.drivers import (DriverCapabilities, DriverFingerprint,
                               HEALTH_HEALTHY, HEALTH_UNDETECTED,
                               TaskConfig)
from . import isolation
from .rawexec import RawExecDriver


class ExecDriver(RawExecDriver):
    name = "exec"
    capabilities = DriverCapabilities(send_signals=True, exec=True,
                                      fs_isolation="chroot")

    task_config_keys = ("command", "args", "extra_chroot_paths")

    def __init__(self):
        super().__init__()
        self._probe = isolation.probe()

    def fingerprint(self) -> DriverFingerprint:
        if not self._probe["namespaces"]:
            return DriverFingerprint(
                attributes={}, health=HEALTH_UNDETECTED,
                health_description="kernel denies mount/pid namespaces")
        return DriverFingerprint(attributes={
            f"driver.{self.name}": "1",
            f"driver.{self.name}.version": "0.1.0",
            f"driver.{self.name}.userns":
                "1" if self._probe["userns"] or os.getuid() == 0 else "0",
            f"driver.{self.name}.cgroups":
                "1" if self._probe["cgroups"] else "0",
        })

    def _isolation_spec(self, cfg: TaskConfig) -> Dict:
        rootfs = os.path.join(cfg.task_dir, ".rootfs")
        return {
            "rootfs": rootfs,
            # in-jail /local == <task_dir>/local and /secrets ==
            # <task_dir>/secrets — the same dirs NOMAD_TASK_DIR points
            # at under raw_exec (allocdir layout), so volume binds and
            # artifacts land identically under both drivers
            "task_dir": os.path.join(cfg.task_dir, "local"),
            "alloc_dir": cfg.alloc_dir,
            "secrets_dir": os.path.join(cfg.task_dir, "secrets"),
            "extra_paths": list(
                (cfg.config or {}).get("extra_chroot_paths") or []),
            "cpu_shares": cfg.cpu_mhz,
            "memory_mb": cfg.memory_mb,
            "cgroup_name": cfg.id.replace("/", "_"),
        }

    def _task_env(self, cfg: TaskConfig) -> Dict[str, str]:
        # inside the chroot the task dir IS /local (reference:
        # client/taskenv NewBuilder chroot-relative NOMAD_* paths)
        env = dict(cfg.env or {})
        env["NOMAD_TASK_DIR"] = "/local"
        env["NOMAD_ALLOC_DIR"] = "/alloc"
        return env
