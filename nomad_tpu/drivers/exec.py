"""exec driver: subprocesses jailed in namespaces + chroot
(reference: drivers/exec/driver.go — libcontainer isolation via the
shared executor, task config `command` + `args`).

Same supervision model as raw_exec (detached executor, durable state,
RecoverTask re-attach); the executor additionally enters fresh
mount+pid namespaces, builds a read-only allowlist chroot around the
task's writable /local, /alloc (and /secrets) dirs, and applies cgroup
cpu/memory limits (drivers/isolation.py).  The task sees itself as
pid 1 with only the chroot view of the filesystem.

Fingerprints only where the kernel supports it: on hosts without
namespace privileges the driver reports itself undetected rather than
running tasks with a silently weakened sandbox (the reference exec
driver likewise requires root + cgroups: drivers/exec capabilities).
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict

# Resolved at import time: the post-fork child must not run `import` or
# dlopen (either can deadlock on locks another agent thread held at
# fork); it only CALLS this already-bound function.  prctl is
# Linux-only; elsewhere the driver fingerprints as undetected anyway.
_PR_SET_PDEATHSIG = 1
try:
    _libc_prctl = ctypes.CDLL(None, use_errno=True).prctl
except (OSError, AttributeError):
    _libc_prctl = None

from ..plugins.drivers import (DriverCapabilities, DriverFingerprint,
                               HEALTH_HEALTHY, HEALTH_UNDETECTED,
                               TaskConfig)
from . import isolation
from .rawexec import RawExecDriver


class ExecDriver(RawExecDriver):
    name = "exec"
    capabilities = DriverCapabilities(send_signals=True, exec=True,
                                      fs_isolation="chroot")

    task_config_keys = ("command", "args", "extra_chroot_paths")

    def __init__(self):
        super().__init__()
        self._probe = isolation.probe()

    def fingerprint(self) -> DriverFingerprint:
        if not self._probe["namespaces"]:
            return DriverFingerprint(
                attributes={}, health=HEALTH_UNDETECTED,
                health_description="kernel denies mount/pid namespaces")
        return DriverFingerprint(attributes={
            f"driver.{self.name}": "1",
            f"driver.{self.name}.version": "0.1.0",
            f"driver.{self.name}.userns":
                "1" if self._probe["userns"] or os.getuid() == 0 else "0",
            f"driver.{self.name}.cgroups":
                "1" if self._probe["cgroups"] else "0",
        })

    def _isolation_spec(self, cfg: TaskConfig) -> Dict:
        rootfs = os.path.join(cfg.task_dir, ".rootfs")
        return {
            "rootfs": rootfs,
            # in-jail /local == <task_dir>/local and /secrets ==
            # <task_dir>/secrets — the same dirs NOMAD_TASK_DIR points
            # at under raw_exec (allocdir layout), so volume binds and
            # artifacts land identically under both drivers
            "task_dir": os.path.join(cfg.task_dir, "local"),
            "alloc_dir": cfg.alloc_dir,
            "secrets_dir": os.path.join(cfg.task_dir, "secrets"),
            "extra_paths": list(
                (cfg.config or {}).get("extra_chroot_paths") or []),
            "cpu_shares": cfg.cpu_mhz,
            "memory_mb": cfg.memory_mb,
            "cgroup_name": cfg.id.replace("/", "_"),
        }

    def _task_env(self, cfg: TaskConfig) -> Dict[str, str]:
        # inside the chroot the task dir IS /local (reference:
        # client/taskenv NewBuilder chroot-relative NOMAD_* paths)
        env = dict(cfg.env or {})
        env["NOMAD_TASK_DIR"] = "/local"
        env["NOMAD_ALLOC_DIR"] = "/alloc"
        return env

    # ------------------------------------------------------ jailed exec
    def _exec_env(self, cfg) -> Dict[str, str]:
        # ONLY the task's env inside the jail — agent env vars must not
        # leak through `alloc exec` (reference: drivers/exec runs
        # ExecTaskStreaming inside the container with the task env)
        env = self._task_env(cfg) if cfg else {}
        env.setdefault("PATH", "/usr/local/bin:/usr/bin:/bin")
        return env

    def _exec_jail(self, t):
        """Enter the running task's user/mount/pid namespaces and its
        chroot before exec'ing the command, so `alloc exec` sees
        exactly the task's view of the world (reference:
        drivers/exec/driver.go ExecTaskStreaming -> shared executor in
        the task's namespaces)."""
        from .executor import pid_alive
        from .rawexec import DriverError

        ds = t.handle.driver_state or {}
        pid = ds.get("pid")
        cfg = t.handle.config
        if not pid or cfg is None:
            raise DriverError("exec: no live task process to enter")
        # start_ticks defeats pid reuse: never setns into an unrelated
        # process that inherited a dead task's pid
        if not pid_alive(pid, ds.get("start_ticks", 0)):
            raise DriverError("exec: task process is not running")
        rootfs = os.path.join(cfg.task_dir, ".rootfs")
        fds = []

        def ns_fd(name: str) -> int:
            fd = os.open(f"/proc/{pid}/ns/{name}", os.O_RDONLY)
            fds.append(fd)
            return fd

        try:
            # joining one's own user ns is EINVAL — only join when the
            # executor created a root-mapped user ns (unprivileged run)
            user_fd = None
            if (os.stat(f"/proc/{pid}/ns/user").st_ino
                    != os.stat("/proc/self/ns/user").st_ino):
                user_fd = ns_fd("user")
            mnt_fd = ns_fd("mnt")
            pid_fd = ns_fd("pid")
        except OSError as e:
            for fd in fds:
                os.close(fd)
            raise DriverError(f"exec: cannot enter task namespaces: {e}")

        def enter():
            import signal as _sig
            from .isolation import (CLONE_NEWNS, CLONE_NEWPID,
                                    CLONE_NEWUSER, setns)

            if user_fd is not None:
                setns(user_fd, CLONE_NEWUSER)
            setns(mnt_fd, CLONE_NEWNS)
            setns(pid_fd, CLONE_NEWPID)
            os.chroot(rootfs)
            os.chdir("/local")
            # setns(CLONE_NEWPID) applies only to CHILDREN: fork once
            # more so the exec'd command itself is a member of the task
            # pid namespace (its /proc view, `kill`, and lifetime are
            # the jail's — it dies with the task's pid 1).  The
            # intermediate stays outside, forwarding signals and exit
            # status.
            pid = os.fork()
            if pid == 0:
                # Die with the intermediate: subprocess timeouts SIGKILL
                # the intermediate (uncatchable, unforwardable), which
                # would otherwise leave this command running inside the
                # task's pid namespace until the task exits.
                if _libc_prctl is not None:
                    _libc_prctl(_PR_SET_PDEATHSIG, _sig.SIGKILL, 0, 0, 0)
                return                 # grandchild: execs the command
            # drop every inherited fd: the intermediate never execs,
            # so subprocess's CLOEXEC error pipe (and the pty master /
            # sockets) would otherwise stay open here and the parent's
            # Popen() would block until the command EXITS — a deadlock
            # for interactive exec
            try:
                hi = os.sysconf("SC_OPEN_MAX")
            except (ValueError, OSError):
                hi = 65536
            os.closerange(3, min(max(hi, 4096), 1 << 20))
            for s in (_sig.SIGTERM, _sig.SIGINT, _sig.SIGHUP,
                      _sig.SIGQUIT):
                _sig.signal(s, lambda n, f, p=pid: os.kill(p, n))
            while True:
                try:
                    _, st = os.waitpid(pid, 0)
                except InterruptedError:
                    continue
                except ChildProcessError:
                    os._exit(127)
                if os.WIFEXITED(st):
                    os._exit(os.WEXITSTATUS(st))
                if os.WIFSIGNALED(st):
                    os._exit(128 + os.WTERMSIG(st))

        def cleanup():
            for fd in fds:
                try:
                    os.close(fd)
                except OSError:
                    pass

        # no pass_fds: preexec_fn runs before subprocess closes fds, so
        # enter() can setns on them; marking them inheritable would hand
        # the jailed command open /proc/<pid>/ns/* fds
        return enter, (), None, cleanup
