"""Scriptable mock driver for tests (reference: drivers/mock).

Task config drives the lifecycle:
  start_error      -> start_task raises DriverError(msg)
  run_for          -> seconds to run before exiting (absent = run forever)
  exit_code        -> exit code when run_for elapses (default 0)
  exit_signal      -> signal number instead of exit code
  exit_err_msg     -> driver-level error on exit

Mock tasks are in-memory threads: they do NOT survive the driver
instance, so recover_task raises TaskNotFoundError — exactly the
"workload lost on restart" path the task runner must handle.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, Optional

from ..plugins.drivers import (TASK_STATE_EXITED, TASK_STATE_RUNNING,
                               DriverCapabilities, DriverError,
                               DriverFingerprint, DriverPlugin, ExitResult,
                               TaskConfig, TaskHandle, TaskNotFoundError,
                               TaskStatus)


class _MockTask:
    def __init__(self, cfg: TaskConfig):
        self.cfg = cfg
        self.started_at = _time.time()
        self.completed_at = 0.0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self.stop = threading.Event()

    def run(self):
        conf = self.cfg.config or {}
        run_for = conf.get("run_for")
        if run_for is None:
            self.stop.wait()
            result = ExitResult()
        else:
            try:
                wait_s = float(run_for)          # unitless = seconds
            except (TypeError, ValueError):
                from ..jobspec.parse import parse_duration_s
                try:
                    wait_s = parse_duration_s(run_for)
                except Exception:
                    # a bad duration fails the task, never wedges it
                    self.exit_result = ExitResult(
                        exit_code=1, err=f"bad run_for: {run_for!r}")
                    self.completed_at = _time.time()
                    self.done.set()
                    return
            finished = self.stop.wait(wait_s)
            if finished:
                result = ExitResult()
            else:
                result = ExitResult(exit_code=int(conf.get("exit_code", 0)),
                                    signal=int(conf.get("exit_signal", 0)),
                                    err=str(conf.get("exit_err_msg", "")))
        self.exit_result = result
        self.completed_at = _time.time()
        self.done.set()


class MockDriver(DriverPlugin):
    name = "mock_driver"
    capabilities = DriverCapabilities(send_signals=True)

    def __init__(self):
        self._tasks: Dict[str, _MockTask] = {}
        self._lock = threading.Lock()

    def fingerprint(self) -> DriverFingerprint:
        return DriverFingerprint(attributes={f"driver.{self.name}": "1"})

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        conf = cfg.config or {}
        if conf.get("start_error"):
            raise DriverError(str(conf["start_error"]))
        task = _MockTask(cfg)
        with self._lock:
            if cfg.id in self._tasks:
                raise DriverError(f"task {cfg.id} already started")
            self._tasks[cfg.id] = task
        threading.Thread(target=task.run, daemon=True).start()
        return TaskHandle(driver=self.name, task_id=cfg.id, config=cfg,
                          state=TASK_STATE_RUNNING,
                          driver_state={"started_at": task.started_at})

    def _get(self, task_id: str) -> _MockTask:
        with self._lock:
            t = self._tasks.get(task_id)
        if t is None:
            raise TaskNotFoundError(f"task {task_id} not found")
        return t

    def wait_task(self, task_id: str,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        t = self._get(task_id)
        if not t.done.wait(timeout):
            return None
        return t.exit_result

    def stop_task(self, task_id: str, timeout_s: float,
                  signal: str = "") -> None:
        t = self._get(task_id)
        t.stop.set()
        t.done.wait(timeout_s + 1.0)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        t = self._get(task_id)
        if not t.done.is_set():
            if not force:
                raise DriverError(f"task {task_id} still running")
            t.stop.set()
            t.done.wait(1.0)
        with self._lock:
            self._tasks.pop(task_id, None)

    def recover_task(self, handle: TaskHandle) -> None:
        with self._lock:
            if handle.task_id in self._tasks:
                return
        raise TaskNotFoundError(
            "mock tasks do not survive driver restarts")

    def inspect_task(self, task_id: str) -> TaskStatus:
        t = self._get(task_id)
        return TaskStatus(
            id=task_id, name=t.cfg.name,
            state=TASK_STATE_EXITED if t.done.is_set() else TASK_STATE_RUNNING,
            started_at=t.started_at, completed_at=t.completed_at,
            exit_result=t.exit_result)

    def signal_task(self, task_id: str, signal: str) -> None:
        self._get(task_id)             # existence check only
