"""raw_exec driver: real subprocesses with no isolation
(reference: drivers/rawexec/driver.go, task config `command` + `args`).

Each task runs under a detached executor process
(nomad_tpu/drivers/executor.py) so the workload survives agent restarts;
RecoverTask re-attaches from the persisted TaskHandle by verifying
{pid, start_ticks} and resuming the exit-file watch.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from ..plugins.drivers import (TASK_STATE_EXITED, TASK_STATE_RUNNING,
                               DriverCapabilities, DriverError,
                               DriverFingerprint, DriverPlugin, ExitResult,
                               TaskConfig, TaskHandle, TaskNotFoundError,
                               TaskStatus)
from .executor import pid_alive

_START_TIMEOUT_S = 10.0


def _signum(name: str, default: int = signal.SIGTERM) -> int:
    if not name:
        return default
    name = name.upper()
    if not name.startswith("SIG"):
        name = "SIG" + name
    try:
        return int(getattr(signal, name))
    except AttributeError:
        raise DriverError(f"unknown signal {name!r}")


class _Task:
    def __init__(self, handle: TaskHandle,
                 popen: Optional[subprocess.Popen] = None):
        self.handle = handle
        self.popen = popen            # executor process, when we spawned it
        self.exit_result: Optional[ExitResult] = None
        self.completed_at = 0.0
        self.lock = threading.Lock()


class RawExecDriver(DriverPlugin):
    name = "raw_exec"
    capabilities = DriverCapabilities(send_signals=True, exec=True,
                                      fs_isolation="none")

    #: jobspec task-config keys (reference: rawexec taskConfigSpec)
    task_config_keys = ("command", "args")

    def __init__(self):
        self._tasks: Dict[str, _Task] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------- fingerprint
    def fingerprint(self) -> DriverFingerprint:
        return DriverFingerprint(attributes={
            f"driver.{self.name}": "1",
            f"driver.{self.name}.version": "0.1.0",
        })

    # -------------------------------------------------------------- start
    def _validate(self, cfg: TaskConfig) -> Tuple[str, List[str]]:
        conf = cfg.config or {}
        for key in conf:
            if key not in self.task_config_keys:
                raise DriverError(
                    f"raw_exec: unknown task config key {key!r}")
        command = conf.get("command")
        if not command or not isinstance(command, str):
            raise DriverError("raw_exec: task config requires 'command'")
        args = conf.get("args") or []
        if not isinstance(args, list):
            raise DriverError("raw_exec: 'args' must be a list")
        return command, [str(a) for a in args]

    def _task_env(self, cfg: TaskConfig) -> Dict[str, str]:
        """Hook: the env the workload sees (exec rewrites NOMAD_* paths
        to their in-chroot locations)."""
        return dict(cfg.env)

    def _isolation_spec(self, cfg: TaskConfig):
        """Hook: executor isolation block; None = no sandbox
        (raw_exec's contract — reference: drivers/rawexec has no
        isolation)."""
        return None

    def _paths(self, cfg: TaskConfig) -> Dict[str, str]:
        base = os.path.join(cfg.task_dir, ".executor")
        os.makedirs(base, exist_ok=True)
        return {
            "spec": os.path.join(base, "spec.json"),
            "state": os.path.join(base, "state.json"),
            "exit": os.path.join(base, "exit.json"),
            "log": os.path.join(base, "executor.log"),
        }

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        with self._lock:
            if cfg.id in self._tasks:
                raise DriverError(f"task {cfg.id} already started")
        command, args = self._validate(cfg)
        paths = self._paths(cfg)
        for stale in (paths["state"], paths["exit"]):
            if os.path.exists(stale):
                os.unlink(stale)
        spec = {
            "argv": [command] + args,
            "env": self._task_env(cfg),
            "cwd": cfg.task_dir,
            "stdout_path": cfg.stdout_path,
            "stderr_path": cfg.stderr_path,
            "log_max_bytes": cfg.log_max_file_size_mb * 1024 * 1024,
            "log_max_files": cfg.log_max_files,
            "state_file": paths["state"],
            "exit_file": paths["exit"],
        }
        iso = self._isolation_spec(cfg)
        if iso:
            spec["isolation"] = iso
        with open(paths["spec"], "w") as f:
            json.dump(spec, f)
        with open(paths["log"], "ab") as elog:
            popen = subprocess.Popen(
                [sys.executable, "-m", "nomad_tpu.drivers.executor",
                 paths["spec"]],
                stdout=elog, stderr=elog, stdin=subprocess.DEVNULL,
                start_new_session=True,      # survives this agent's death
                cwd="/",
                # absolutize: the executor runs with cwd=/ — relative
                # sys.path entries (script dirs, '') would dangle
                env={"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
                     "PYTHONPATH": os.pathsep.join(
                         os.path.abspath(p) for p in sys.path)},
            )
        state = self._await_state(paths, popen)
        handle = TaskHandle(
            driver=self.name, task_id=cfg.id, config=cfg,
            state=TASK_STATE_RUNNING,
            driver_state={
                "pid": state["pid"],
                "start_ticks": state["start_ticks"],
                "executor_pid": state["executor_pid"],
                "started_at": state["started_at"],
                "state_file": paths["state"],
                "exit_file": paths["exit"],
            })
        with self._lock:
            self._tasks[cfg.id] = _Task(handle, popen)
        return handle

    def _await_state(self, paths: Dict[str, str],
                     popen: subprocess.Popen) -> Dict[str, Any]:
        deadline = _time.monotonic() + _START_TIMEOUT_S
        while _time.monotonic() < deadline:
            if os.path.exists(paths["state"]):
                try:
                    with open(paths["state"]) as f:
                        return json.load(f)
                except (OSError, json.JSONDecodeError):
                    pass               # mid-write; retry
            if os.path.exists(paths["exit"]):
                # spawn failed: the executor wrote the error exit record
                with open(paths["exit"]) as f:
                    rec = json.load(f)
                raise DriverError(
                    f"raw_exec: failed to start task: "
                    f"{rec.get('err') or rec}")
            if popen.poll() is not None and not os.path.exists(paths["exit"]):
                tail = ""
                try:
                    with open(paths["log"]) as f:
                        tail = f.read()[-500:]
                except OSError:
                    pass
                raise DriverError(f"raw_exec: executor died at startup: "
                                  f"{tail}")
            _time.sleep(0.01)
        raise DriverError("raw_exec: timed out waiting for executor")

    # --------------------------------------------------------------- wait
    def _get(self, task_id: str) -> _Task:
        with self._lock:
            t = self._tasks.get(task_id)
        if t is None:
            raise TaskNotFoundError(f"task {task_id} not found")
        return t

    def wait_task(self, task_id: str,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        t = self._get(task_id)
        deadline = None if timeout is None else _time.monotonic() + timeout
        ds = t.handle.driver_state
        while True:
            with t.lock:
                if t.exit_result is not None:
                    return t.exit_result
            result = self._poll_exit(t)
            if result is not None:
                return result
            if deadline is not None and _time.monotonic() >= deadline:
                return None
            _time.sleep(0.02)

    def _poll_exit(self, t: _Task) -> Optional[ExitResult]:
        ds = t.handle.driver_state
        if t.popen is not None:
            t.popen.poll()              # reap the executor if it finished
        if os.path.exists(ds["exit_file"]):
            try:
                with open(ds["exit_file"]) as f:
                    rec = json.load(f)
            except (OSError, json.JSONDecodeError):
                return None            # mid-write
            result = ExitResult(exit_code=int(rec.get("exit_code", 0)),
                                signal=int(rec.get("signal", 0)),
                                err=rec.get("err", ""))
            with t.lock:
                t.exit_result = result
                t.completed_at = float(rec.get("finished_at", _time.time()))
                t.handle.state = TASK_STATE_EXITED
            return result
        if (not pid_alive(ds["pid"], ds.get("start_ticks", 0))
                and not pid_alive(ds.get("executor_pid", 0))):
            # both task and its supervisor vanished without an exit record
            result = ExitResult(exit_code=-1,
                                err="task lost: executor died")
            with t.lock:
                t.exit_result = result
                t.completed_at = _time.time()
                t.handle.state = TASK_STATE_EXITED
            return result
        return None

    # --------------------------------------------------------------- stop
    def stop_task(self, task_id: str, timeout_s: float,
                  signal_name: str = "") -> None:
        t = self._get(task_id)
        ds = t.handle.driver_state
        sig = _signum(signal_name)
        self._kill_group(ds["pid"], sig)
        if self.wait_task(task_id, timeout=max(timeout_s, 0.0)) is None:
            self._kill_group(ds["pid"], signal.SIGKILL)
            self.wait_task(task_id, timeout=5.0)

    @staticmethod
    def _kill_group(pid: int, sig: int) -> None:
        try:
            os.killpg(pid, sig)        # executor starts the task setsid
        except ProcessLookupError:
            pass
        except PermissionError:
            try:
                os.kill(pid, sig)
            except OSError:
                pass

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        t = self._get(task_id)
        with t.lock:
            running = t.exit_result is None
        if running:
            if not force:
                raise DriverError(f"task {task_id} still running")
            self.stop_task(task_id, timeout_s=1.0)
        with self._lock:
            self._tasks.pop(task_id, None)

    # ------------------------------------------------------------ recover
    def recover_task(self, handle: TaskHandle) -> None:
        ds = handle.driver_state or {}
        if not ds.get("pid") or not ds.get("exit_file"):
            raise TaskNotFoundError("handle has no executor state")
        with self._lock:
            if handle.task_id in self._tasks:
                return
            self._tasks[handle.task_id] = _Task(handle, popen=None)
        t = self._get(handle.task_id)
        # settle the state immediately: exited (exit file), running
        # (pid+ticks match), or lost
        self._poll_exit(t)

    # ------------------------------------------------------------ inspect
    def inspect_task(self, task_id: str) -> TaskStatus:
        t = self._get(task_id)
        ds = t.handle.driver_state
        with t.lock:
            result = t.exit_result
            completed = t.completed_at
        return TaskStatus(
            id=task_id,
            name=t.handle.config.name if t.handle.config else "",
            state=TASK_STATE_EXITED if result else TASK_STATE_RUNNING,
            started_at=ds.get("started_at", 0.0),
            completed_at=completed,
            exit_result=result,
            driver_attributes={"pid": str(ds.get("pid", ""))})

    def signal_task(self, task_id: str, signal_name: str) -> None:
        t = self._get(task_id)
        self._kill_group(t.handle.driver_state["pid"], _signum(signal_name))

    def _exec_env(self, cfg: Optional[TaskConfig]) -> Dict[str, str]:
        """Hook: env an `alloc exec` command sees.  raw_exec tasks run
        unisolated in the agent's environment; exec overrides this to
        hand out ONLY the task's env (the jail must not leak agent
        variables)."""
        env = dict(os.environ)
        if cfg:
            env.update(cfg.env or {})
        return env

    def _exec_jail(self, t: _Task):
        """Hook: (preexec, pass_fds, cwd, cleanup) placing an exec'd
        command next to the task.  raw_exec: no jail, run in the task
        dir.  exec overrides this to enter the task's namespaces and
        chroot (reference: drivers/exec runs ExecTaskStreaming inside
        the container via the shared executor)."""
        cfg = t.handle.config
        cwd = cfg.task_dir if cfg and cfg.task_dir else None
        return None, (), cwd, (lambda: None)

    def exec_task(self, task_id: str, cmd: List[str],
                  timeout_s: float = 30.0) -> Tuple[bytes, int]:
        t = self._get(task_id)
        cfg = t.handle.config
        jail_preexec, pass_fds, cwd, cleanup = self._exec_jail(t)

        def preexec():
            # Own process group so a timeout can kill the command AND
            # anything it spawned, not just the direct child.
            os.setpgid(0, 0)
            if jail_preexec:
                jail_preexec()

        try:
            with subprocess.Popen(
                    cmd, cwd=cwd, env=self._exec_env(cfg),
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    preexec_fn=preexec, pass_fds=pass_fds) as proc:
                try:
                    out, _ = proc.communicate(timeout=timeout_s)
                    return out, proc.returncode
                except subprocess.TimeoutExpired:
                    # Kill the whole group; in the jailed case the
                    # intermediate's death also SIGKILLs the in-namespace
                    # command via its PR_SET_PDEATHSIG.
                    try:
                        os.killpg(proc.pid, 9)
                    except (ProcessLookupError, PermissionError):
                        proc.kill()
                    # a descendant that escaped the group (setsid) can
                    # hold the pipe open; don't let it wedge this thread
                    try:
                        out, _ = proc.communicate(timeout=2.0)
                    except subprocess.TimeoutExpired:
                        out = b""
                    return (out or b"") + b"\n(timed out)", 124
        finally:
            cleanup()

    def exec_task_streaming(self, task_id: str, cmd: List[str],
                            tty: bool = True, width: int = 80,
                            height: int = 24):
        """Interactive exec in the task's dir/env (reference:
        drivers/rawexec + drivers/shared/executor ExecStreaming,
        executor/pty_unix.go).  tty=True runs the command on a fresh
        pty (its own session + controlling terminal, so shells get job
        control); tty=False uses a socketpair for clean EOF
        semantics."""
        import fcntl
        import socket as _socket
        import struct as _struct
        import termios
        from ..plugins.drivers import ExecStream

        t = self._get(task_id)
        cfg = t.handle.config
        jail_preexec, pass_fds, cwd, cleanup = self._exec_jail(t)
        env = self._exec_env(cfg)
        env.setdefault("TERM", "xterm")

        try:
            if tty:
                import pty
                master, slave = pty.openpty()
                fcntl.ioctl(slave, termios.TIOCSWINSZ,
                            _struct.pack("HHHH", height, width, 0, 0))

                def preexec():
                    # jail first: the exec jail forks an intermediate
                    # and only the final command process returns here,
                    # so it — not the intermediate — becomes the
                    # session leader owning the pty
                    if jail_preexec is not None:
                        jail_preexec()
                    os.setsid()
                    fcntl.ioctl(0, termios.TIOCSCTTY, 0)

                try:
                    proc = subprocess.Popen(
                        cmd, cwd=cwd, env=env, stdin=slave, stdout=slave,
                        stderr=slave, preexec_fn=preexec, close_fds=True,
                        pass_fds=pass_fds)
                except BaseException:
                    # a failing preexec (e.g. jail entry) re-raises in
                    # the parent; the raw pty ints have no finalizer
                    os.close(master)
                    os.close(slave)
                    raise
                os.close(slave)
                return ExecStream(fd=master, pid=proc.pid, tty=True,
                                  popen=proc)

            parent, child = _socket.socketpair()
            proc = subprocess.Popen(
                cmd, cwd=cwd, env=env, stdin=child.fileno(),
                stdout=child.fileno(), stderr=child.fileno(),
                start_new_session=True, close_fds=True,
                pass_fds=pass_fds, preexec_fn=jail_preexec)
            child.close()
            return ExecStream(fd=parent.detach(), pid=proc.pid, tty=False,
                              popen=proc)
        finally:
            cleanup()
