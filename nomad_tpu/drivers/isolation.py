"""Linux isolation primitives for the exec driver.

Reference: drivers/shared/executor/executor_linux.go — the reference
jails exec-driver tasks with libcontainer (runc): mount+pid namespaces,
a chroot built from an allowlist of system paths, cgroup resource
limits.  This is the same sandbox built directly on the syscalls
(no container runtime dependency): `enter_namespaces` +
`build_chroot_binds` run in the detached executor process, and
`child_preexec_steps` finish the jail (fresh /proc, chroot) in the
forked task between fork and exec.

Degrades explicitly: `probe()` reports which pieces this kernel/user
can do; the driver refuses to start (rather than silently weakening
the sandbox) unless the caller opts into best-effort.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import os
from typing import Dict, List, Optional

_libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                    use_errno=True)

MS_RDONLY = 0x1
MS_NOSUID = 0x2
MS_NODEV = 0x4
MS_NOEXEC = 0x8
MS_REMOUNT = 0x20
MS_NOATIME = 0x400
MS_NODIRATIME = 0x800
MS_BIND = 0x1000
MS_REC = 0x4000
MS_PRIVATE = 0x40000
MS_RELATIME = 0x200000

#: statvfs f_flag bit -> mount flag, for re-asserting a submount's
#: EXISTING flags during remount (a user-ns locked flag that the
#: remount drops is an EPERM; preserving them lets the remount succeed)
_STATVFS_TO_MS = [
    (getattr(os, "ST_NOSUID", 0x2), MS_NOSUID),
    (getattr(os, "ST_NODEV", 0x4), MS_NODEV),
    (getattr(os, "ST_NOEXEC", 0x8), MS_NOEXEC),
    (getattr(os, "ST_NOATIME", 0x400), MS_NOATIME),
    (getattr(os, "ST_NODIRATIME", 0x800), MS_NODIRATIME),
    (getattr(os, "ST_RELATIME", 0x1000000), MS_RELATIME),
]

#: reference: drivers/exec chroot_env default allowlist
#: (website docs chroot_env; executor_linux chroot build)
DEFAULT_CHROOT_PATHS = ["/bin", "/etc", "/lib", "/lib64", "/sbin",
                        "/usr", "/dev", "/run/resolvconf",
                        "/run/systemd/resolve"]


class IsolationError(OSError):
    pass


# os.unshare/os.CLONE_* only exist on python >= 3.12; the jail speaks
# to libc directly everywhere else (same syscall, same semantics)
CLONE_NEWNS = getattr(os, "CLONE_NEWNS", 0x00020000)
CLONE_NEWUSER = getattr(os, "CLONE_NEWUSER", 0x10000000)
CLONE_NEWPID = getattr(os, "CLONE_NEWPID", 0x20000000)


def _unshare(flags: int) -> None:
    if hasattr(os, "unshare"):
        os.unshare(flags)
        return
    if _libc.unshare(flags) != 0:
        e = ctypes.get_errno()
        raise OSError(e, f"unshare({flags:#x}): {os.strerror(e)}")


def setns(fd: int, nstype: int = 0) -> None:
    """os.setns (3.12+) or the raw syscall on older pythons."""
    if hasattr(os, "setns"):
        os.setns(fd, nstype)
        return
    if _libc.setns(fd, nstype) != 0:
        e = ctypes.get_errno()
        raise OSError(e, f"setns({fd}, {nstype:#x}): {os.strerror(e)}")


def _mount(src: Optional[str], target: str, fstype: Optional[str],
           flags: int, data: Optional[str] = None) -> None:
    rc = _libc.mount(os.fsencode(src) if src else None,
                     os.fsencode(target),
                     fstype.encode() if fstype else None, flags,
                     data.encode() if data else None)
    if rc != 0:
        e = ctypes.get_errno()
        raise IsolationError(
            e, f"mount({src!r}, {target!r}, {fstype!r}, {flags:#x}): "
               f"{os.strerror(e)}")


_PROBE_SCRIPT = """
import ctypes, ctypes.util, os, sys
NEWNS, NEWUSER, NEWPID = 0x00020000, 0x10000000, 0x20000000
libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                   use_errno=True)
def unshare(flags):
    if hasattr(os, "unshare"):
        os.unshare(flags)
    elif libc.unshare(flags) != 0:
        raise OSError(ctypes.get_errno(), "unshare")
code = 0
try:
    unshare(NEWNS | NEWPID)
    code |= 1
except OSError:
    try:
        unshare(NEWUSER | NEWNS | NEWPID)
        code |= 1 | 2
    except OSError:
        pass
sys.exit(code)
"""
_probe_cache: Optional[Dict[str, bool]] = None


def probe() -> Dict[str, bool]:
    """What this kernel/uid supports.  Checked once per process in a
    throwaway subprocess (fork+exec — a bare fork from a threaded
    process is a deadlock hazard)."""
    global _probe_cache
    if _probe_cache is not None:
        return dict(_probe_cache)
    import subprocess
    import sys
    try:
        code = subprocess.run(
            [sys.executable, "-c", _PROBE_SCRIPT],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=15).returncode
    except (OSError, subprocess.TimeoutExpired):
        code = 0
    _probe_cache = {
        "namespaces": bool(code & 1),
        "userns": bool(code & 2),
        "cgroups": os.access("/sys/fs/cgroup/cpu", os.W_OK),
    }
    return dict(_probe_cache)


def enter_namespaces() -> None:
    """Called in the EXECUTOR before forking the task: new mount + pid
    namespaces (the next fork lands as pid 1), root-mapped user ns
    first when not privileged."""
    if os.getuid() != 0:
        _unshare(CLONE_NEWUSER)
        # self-mapping is allowed for a single entry + setgroups deny
        with open("/proc/self/setgroups", "w") as f:
            f.write("deny")
        with open("/proc/self/uid_map", "w") as f:
            f.write(f"0 {os.getuid()} 1")
        with open("/proc/self/gid_map", "w") as f:
            f.write(f"0 {os.getgid()} 1")
    _unshare(CLONE_NEWNS | CLONE_NEWPID)
    # stop mount events from leaking back to the host namespace
    _mount(None, "/", None, MS_REC | MS_PRIVATE)


def _unescape_mount_path(raw: bytes) -> str:
    """Decode one /proc/self/mounts path field: octal escapes
    (\\040 etc per fstab(5)) applied on the raw bytes, then fs-decoded
    so non-ASCII mount points survive the round trip."""
    out = bytearray()
    i = 0
    while i < len(raw):
        if raw[i:i + 1] == b"\\" and raw[i + 1:i + 4].isdigit():
            out.append(int(raw[i + 1:i + 4], 8))
            i += 4
        else:
            out.append(raw[i])
            i += 1
    return os.fsdecode(bytes(out))


def _mounts_under(prefix: str) -> List[str]:
    """Mount points strictly below `prefix` in this mount namespace,
    deepest first."""
    out = []
    try:
        with open("/proc/self/mounts", "rb") as f:
            for line in f:
                fields = line.split()
                if len(fields) < 2:
                    continue
                mp = _unescape_mount_path(fields[1])
                if mp.startswith(prefix + "/"):
                    out.append(mp)
    except OSError:
        return []
    return sorted(set(out), key=len, reverse=True)


def _remount_ro_tree(tgt: str) -> None:
    """Remount-ro `tgt` and every submount below it (a recursive bind
    keeps each submount's own writability until told otherwise).

    The top-level remount must succeed — a writable system bind is a
    jail break, and the driver's contract is to refuse to start rather
    than weaken the sandbox.  A submount remount failure is retried
    with the mount's existing flags preserved (a userns-locked flag the
    remount drops is an EPERM) and then tolerated ONLY if the submount
    is verifiably already read-only; a submount left writable fails
    task start."""
    for mp in _mounts_under(tgt):
        flags = MS_REMOUNT | MS_BIND | MS_RDONLY | MS_NOSUID
        try:
            _mount(None, mp, None, flags)
            continue
        except IsolationError:
            pass
        # A locked flag (inherited through a user namespace) that the
        # remount DROPS is an EPERM: retry preserving the submount's
        # existing flags, then verify.  Tolerate failure only if the
        # mount is in fact read-only — a submount left writable for any
        # other reason is a jail break and the task refuses to start.
        try:
            st_flag = os.statvfs(mp).f_flag
        except OSError:
            st_flag = 0
        for st_bit, ms_bit in _STATVFS_TO_MS:
            if st_flag & st_bit:
                flags |= ms_bit
        try:
            _mount(None, mp, None, flags)
            continue
        except IsolationError:
            pass
        try:
            ro = bool(os.statvfs(mp).f_flag & os.ST_RDONLY)
        except OSError:
            ro = False
        if not ro:
            raise IsolationError(
                f"cannot pin submount {mp!r} read-only and it is "
                "writable inside the chroot")
    _mount(None, tgt, None,
           MS_REMOUNT | MS_BIND | MS_RDONLY | MS_NOSUID)


def build_chroot_binds(rootfs: str, task_dir: str, alloc_dir: str,
                       secrets_dir: str = "",
                       extra_paths: Optional[List[str]] = None) -> None:
    """Assemble the task's root: allowlisted system paths bound
    read-only, task/alloc/secrets dirs bound writable at the
    reference's in-chroot locations (/local, /alloc, /secrets —
    client/allocdir layout), an empty /proc mountpoint for the child,
    /tmp as a fresh tmpfs."""
    os.makedirs(rootfs, exist_ok=True)
    paths = list(DEFAULT_CHROOT_PATHS) + list(extra_paths or [])
    for p in paths:
        if not os.path.exists(p):
            continue
        tgt = rootfs + p
        os.makedirs(tgt, exist_ok=True)
        _mount(p, tgt, None, MS_BIND | MS_REC)
        if p != "/dev":
            # remount the bind read-only (two-step per mount(2));
            # MS_REMOUNT applies only to the top mount, so walk every
            # submount the recursive bind dragged in (e.g. a host
            # mount under /usr) and pin each read-only too
            _remount_ro_tree(tgt)
    rw = [("/local", task_dir), ("/alloc", alloc_dir)]
    if secrets_dir:
        rw.append(("/secrets", secrets_dir))
    for inpath, host in rw:
        if not host:
            continue
        tgt = rootfs + inpath
        os.makedirs(tgt, exist_ok=True)
        # recursive: nested mounts under the task dir (CSI volume
        # targets bound in by the alloc runner) must follow into the
        # jail
        _mount(host, tgt, None, MS_BIND | MS_REC)
    os.makedirs(rootfs + "/proc", exist_ok=True)
    os.makedirs(rootfs + "/tmp", exist_ok=True)
    _mount("tmpfs", rootfs + "/tmp", "tmpfs", MS_NOSUID | MS_NODEV,
           "size=64m")


def child_preexec_steps(rootfs: str) -> None:
    """Called in the forked TASK between fork and exec: it is pid 1 of
    the new pid namespace here, so mount its own /proc, then jail."""
    _mount("proc", rootfs + "/proc", "proc", MS_NOSUID | MS_NODEV)
    os.chroot(rootfs)
    os.chdir("/local")


# ------------------------------------------------------------- cgroups
_CG_ROOT = "/sys/fs/cgroup"


def cgroup_create(name: str, cpu_shares: int = 0,
                  memory_mb: int = 0) -> List[str]:
    """Best-effort cgroup v1 limits (reference: libcontainer cgroup
    manager driven by Resources.LinuxResources).  Returns the created
    dirs (for cleanup)."""
    created = []
    subs = []
    if cpu_shares and os.path.isdir(f"{_CG_ROOT}/cpu"):
        subs.append(("cpu", "cpu.shares", str(max(2, cpu_shares))))
    if memory_mb and os.path.isdir(f"{_CG_ROOT}/memory"):
        subs.append(("memory", "memory.limit_in_bytes",
                     str(memory_mb * 1024 * 1024)))
    for sub, knob, value in subs:
        d = f"{_CG_ROOT}/{sub}/nomad_tpu/{name}"
        try:
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, knob), "w") as f:
                f.write(value)
            created.append(d)
        except OSError:
            continue
    return created


def cgroup_add_pid(dirs: List[str], pid: int) -> None:
    for d in dirs:
        try:
            with open(os.path.join(d, "tasks"), "w") as f:
                f.write(str(pid))
        except OSError:
            pass


def cgroup_remove(dirs: List[str]) -> None:
    for d in dirs:
        try:
            os.rmdir(d)
        except OSError:
            pass
