"""Per-task executor process (reference: drivers/shared/executor).

The reference launches every task under an out-of-process executor so the
workload survives agent restarts/upgrades, and the restarted agent
re-attaches to the executor to recover the exit code. This is the same
design: `python -m nomad_tpu.drivers.executor <spec.json>` detaches into
its own session, spawns the task, records {pid, start_ticks} to the state
file (start_ticks defeats pid reuse on re-attach), waits, and writes the
exit result file that a (possibly different) agent process polls.

Spec file (JSON): argv, env, cwd, stdout_path, stderr_path,
state_file, exit_file; optionally `isolation` (exec driver —
reference: drivers/shared/executor/executor_linux.go): {rootfs,
task_dir, alloc_dir, secrets_dir, extra_paths, cpu_shares, memory_mb,
cgroup_name} — the executor enters fresh mount+pid namespaces, builds
the chroot from bind mounts, applies cgroup limits, and the forked
task finishes the jail (own /proc, chroot) before exec.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time


def proc_start_ticks(pid: int) -> int:
    """Kernel start time of `pid` in clock ticks (field 22 of
    /proc/<pid>/stat, after the comm field which may contain spaces)."""
    with open(f"/proc/{pid}/stat", "rb") as f:
        data = f.read().decode("ascii", "replace")
    rest = data[data.rfind(")") + 2:].split()
    return int(rest[19])           # field 22 overall; 20th after state


def pid_alive(pid: int, start_ticks: int = 0) -> bool:
    """Liveness with pid-reuse protection."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass
    if start_ticks:
        try:
            return proc_start_ticks(pid) == start_ticks
        except (OSError, ValueError):
            return False
    return True


def _atomic_write_json(path: str, obj) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


class _RotatingWriter:
    """Size-rotated log sink (reference: client/logmon/logging — the
    out-of-proc rotating writer; this executor IS the out-of-proc
    supervisor, so logs both survive agent restarts and stay bounded).
    Current file keeps the task path; older generations shift to
    .1 .. .N and the oldest is dropped."""

    def __init__(self, path: str, max_bytes: int, max_files: int):
        self.path = path
        self.max_bytes = max(max_bytes, 1)
        self.max_files = max(max_files, 1)
        self._fh = open(path, "ab", buffering=0)
        self._size = self._fh.tell()

    def write(self, data: bytes) -> None:
        if self._size + len(data) > self.max_bytes and self._size > 0:
            self._rotate()
        self._fh.write(data)
        self._size += len(data)

    def _rotate(self) -> None:
        self._fh.close()
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            try:
                os.replace(src, f"{self.path}.{i}")
            except FileNotFoundError:
                pass
        if self.max_files == 1:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass
        self._fh = open(self.path, "ab", buffering=0)
        self._size = 0

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


def _pump_until_eof(writers: dict, poll=None, grace: float = 5.0,
                    timeout: float = 1.0) -> None:
    """Thread-free select/os.read pump: drain every readable fd into its
    rotating writer until ALL pipes hit EOF (the task tree closed them).
    A detached grandchild can inherit the pipe and never close it, so
    once `poll()` reports the direct child gone the pump lingers at most
    `grace` seconds — the old daemon-thread join bound.

    Runs on the executor's main thread — no `threading.Thread`, which the
    exec jail may forbid (thread creation inside a fresh user+pid
    namespace is blocked on some kernels) and whose failure used to kill
    the task outright.  `os.read` on a select-ready pipe fd returns
    whatever is buffered immediately, so output reaches the log file
    while the task is still running (a BufferedReader `.read(n)` blocks
    for the full n bytes and stalled live log streaming until exit).

    A writer error (disk full, rotation race) must never stall the
    child: the failing sink is downgraded to drain-and-discard so the
    pipe keeps flowing.
    """
    import select
    fds = dict(writers)            # fd -> writer (or None: discard)
    exit_deadline = None
    while fds:
        if poll is not None and exit_deadline is None \
                and poll() is not None:
            exit_deadline = time.monotonic() + grace
        if exit_deadline is not None and time.monotonic() > exit_deadline:
            break
        try:
            ready, _, _ = select.select(list(fds), [], [], timeout)
        except OSError:
            ready = list(fds)      # EBADF etc: probe each fd directly
        for fd in ready:
            try:
                chunk = os.read(fd, 65536)
            except BlockingIOError:
                continue
            except OSError:
                chunk = b""
            if not chunk:          # EOF (or dead fd): retire it
                w = fds.pop(fd, None)
                if w is not None:
                    w.close()
                continue
            w = fds.get(fd)
            if w is not None:
                try:
                    w.write(chunk)
                except OSError:
                    try:
                        w.close()
                    except OSError:
                        pass
                    fds[fd] = None      # keep draining, drop the bytes
    for w in fds.values():              # grace-break: flush what's left
        if w is not None:
            w.close()


def main(spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)

    # log rotation: when the spec carries limits, task output flows
    # through this supervisor into rotating files; otherwise the child
    # inherits the raw file descriptors (legacy specs)
    log_max_bytes = int(spec.get("log_max_bytes") or 0)
    log_max_files = int(spec.get("log_max_files") or 0)
    rotate = log_max_bytes > 0 and log_max_files > 0
    if rotate:
        stdout = subprocess.PIPE
        stderr = subprocess.PIPE
    else:
        stdout = open(spec["stdout_path"], "ab", buffering=0)
        stderr = open(spec["stderr_path"], "ab", buffering=0)
    iso = spec.get("isolation")
    cg_dirs = []
    preexec = None
    cwd = spec.get("cwd") or None
    try:
        if iso:
            from . import isolation
            isolation.enter_namespaces()
            isolation.build_chroot_binds(
                iso["rootfs"], iso.get("task_dir", ""),
                iso.get("alloc_dir", ""), iso.get("secrets_dir", ""),
                iso.get("extra_paths"))
            cg_dirs = isolation.cgroup_create(
                iso.get("cgroup_name") or f"task-{os.getpid()}",
                cpu_shares=int(iso.get("cpu_shares") or 0),
                memory_mb=int(iso.get("memory_mb") or 0))
            rootfs = iso["rootfs"]
            cwd = None                # chroot sets its own cwd

            def preexec():
                isolation.child_preexec_steps(rootfs)

        child = subprocess.Popen(
            spec["argv"],
            env=spec.get("env") or None,
            cwd=cwd,
            stdout=stdout, stderr=stderr,
            stdin=subprocess.DEVNULL,
            start_new_session=True,   # own pgid: killpg targets the task tree
            preexec_fn=preexec,
        )
    except (OSError, KeyError) as e:
        _atomic_write_json(spec["exit_file"], {
            "exit_code": 127, "signal": 0, "err": str(e),
            "finished_at": time.time()})
        return 1

    writers = {}
    if rotate:
        for src, path in ((child.stdout, spec["stdout_path"]),
                          (child.stderr, spec["stderr_path"])):
            try:
                writers[src.fileno()] = _RotatingWriter(
                    path, log_max_bytes, log_max_files)
            except OSError:
                # sink unavailable: drain-and-discard keeps the child
                # unblocked; the task itself must survive
                writers[src.fileno()] = None

    if cg_dirs:
        from . import isolation
        isolation.cgroup_add_pid(cg_dirs, child.pid)

    _atomic_write_json(spec["state_file"], {
        "executor_pid": os.getpid(),
        "pid": child.pid,
        "start_ticks": proc_start_ticks(child.pid),
        "started_at": time.time(),
    })

    # the driver signals the task's process group directly; the executor
    # itself ignores SIGINT/SIGTERM so an agent shutdown can't take the
    # workload's supervisor down with it
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    signal.signal(signal.SIGTERM, signal.SIG_IGN)

    if writers:
        _pump_until_eof(writers, poll=child.poll)
    code = child.wait()
    result = {"exit_code": code if code >= 0 else 0,
              "signal": -code if code < 0 else 0,
              "err": "",
              "finished_at": time.time()}
    _atomic_write_json(spec["exit_file"], result)
    if cg_dirs:
        from . import isolation
        isolation.cgroup_remove(cg_dirs)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
