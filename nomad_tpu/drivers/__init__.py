"""Builtin task drivers (reference: drivers/ — rawexec, exec, mock, …).

The compute path of this framework is JAX/XLA on TPU; drivers are the
host-side task runtime that the client's task runners drive through the
plugin boundary (nomad_tpu/plugins/drivers.py). Builtins:

- rawexec: real subprocesses under a detached per-task executor
  (reference: drivers/rawexec + drivers/shared/executor)
- exec: rawexec semantics plus best-effort isolation knobs
  (reference: drivers/exec; chroot/libcontainer isolation is replaced
  by setsid + rlimits — containers are out of scope for this build)
- mock: scriptable lifecycle for tests (reference: drivers/mock)
"""
from .mock import MockDriver
from .rawexec import RawExecDriver


def register_builtins(registry) -> None:
    """reference: helper/pluginutils/catalog/register.go:15-19."""
    registry.register(RawExecDriver())
    registry.register(MockDriver())


__all__ = ["RawExecDriver", "MockDriver", "register_builtins"]
