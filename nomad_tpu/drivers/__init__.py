"""Builtin task drivers (reference: drivers/ — rawexec, exec, mock, …).

The compute path of this framework is JAX/XLA on TPU; drivers are the
host-side task runtime that the client's task runners drive through the
plugin boundary (nomad_tpu/plugins/drivers.py). Builtins:

- rawexec: real subprocesses under a detached per-task executor
  (reference: drivers/rawexec + drivers/shared/executor)
- exec: rawexec supervision plus a real jail — mount+pid namespaces,
  read-only allowlist chroot, cgroup cpu/memory limits (reference:
  drivers/exec + executor_linux.go libcontainer isolation, rebuilt on
  raw syscalls in drivers/isolation.py)
- mock: scriptable lifecycle for tests (reference: drivers/mock)
"""
from .exec import ExecDriver
from .mock import MockDriver
from .rawexec import RawExecDriver


def register_builtins(registry) -> None:
    """reference: helper/pluginutils/catalog/register.go:15-19."""
    registry.register(RawExecDriver())
    registry.register(ExecDriver())
    registry.register(MockDriver())


__all__ = ["RawExecDriver", "ExecDriver", "MockDriver",
           "register_builtins"]
