"""Federated resident solve: R regions fused into ONE device call.

The reference federates by running an independent server cluster per
region and forwarding RPCs between them (nomad/serf.go WAN gossip,
nomad/rpc.go `forward`); each region's scheduler is oblivious to the
others.  The TPU recast keeps that isolation — each region owns its own
node universe, usage tensors, and eval stream — but fuses the *solves*:
every stream step carries one batch per region, vmapped over a leading
region axis inside a single `lax.scan` device program.  One dispatch and
one result fetch cover every region's whole workload, where R separate
streams would pay R transport round trips (ruinous on tunneled
transports, see solver/resident.py).

On a multi-chip mesh the region axis is the natural sharding axis: the
same program with the vmap replaced by a `shard_map` over a
`Mesh(('region',))` places one region's universe per chip and needs no
cross-chip collectives at all — regions never share state (see
parallel/sharded.federated_solve for the mesh variant used by the
multi-chip dryrun).

Semantics per region are identical to ResidentSolver.solve_stream:
resource usage carries batch-to-batch on device, job-scoped state is
seeded per batch, and the per-job stream guard applies within a region's
stream.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..structs import Node
from ..solver.kernel import NEG_INF, TOP_K
from ..solver.resident import (ResidentSolver, STATUS_COMMITTED,
                               STATUS_FAILED, STATUS_RETRY, _ASK_ARGS,
                               _solve_one)
from ..solver.tensorize import PackedBatch, PlacementAsk


@functools.partial(jax.jit,
                   static_argnames=("has_spread", "group_count_hint",
                                    "max_waves", "has_distinct",
                                    "has_devices", "compact"))
def _federated_stream_kernel(avail, reserved, valid, node_dc, attr_rank,
                             dev_cap, used0, dev_used0, stacked, n_places,
                             seeds, has_spread=True, group_count_hint=0,
                             max_waves=0, has_distinct=True,
                             has_devices=True, compact=True):
    """Node args carry a leading [R] region axis; `stacked` ask tensors
    carry [B, R, ...]; scan over B steps, vmap over R regions."""

    def step(carry, xs):
        used, dev_used = carry                       # [R, ...]
        batch, n_place, seed = xs                    # [R, ...] each

        def one_region(av, rs_, vl, ndc, ar, dcp, u, du, b, n, s):
            # "while" wave mode: under this vmap a cond-skipped scan
            # would execute every budget wave for every region lane
            # (cond lowers to select when batched); the while_loop runs
            # exactly as many waves as the slowest region needs
            return _solve_one(av, rs_, vl, ndc, ar, dcp, u, du, b, n, s,
                              has_spread, group_count_hint, max_waves,
                              "while", has_distinct, has_devices,
                              # under the region vmap the shortlist
                              # cond lowers to select (both branches
                              # run every wave) — keep it off
                              shortlist_c=-1)

        res = jax.vmap(one_region)(avail, reserved, valid, node_dc,
                                   attr_rank, dev_cap, used, dev_used,
                                   batch, n_place, seed)
        status = jnp.where(res.choice_ok[:, :, 0], STATUS_COMMITTED,
                           jnp.where(res.unfinished, STATUS_RETRY,
                                     STATUS_FAILED))
        if compact:
            from ..solver.resident import pack_out_compact
            packed = pack_out_compact(res.choice, res.score, status)
        else:
            packed = jnp.concatenate(
                [res.choice.astype(jnp.float32), res.score,
                 status.astype(jnp.float32)[:, :, None]], axis=-1)
        return (res.used_final, res.dev_used_final), packed

    (used_f, dev_used_f), out = jax.lax.scan(
        step, (used0, dev_used0), (stacked, n_places, seeds))
    return used_f, dev_used_f, out                   # out [B, R, K, .]


class FederatedResidentSolver:
    """R regional node universes solved in one fused device stream.

    Every region gets its own ResidentSolver for packing (merge_asks /
    pack_batch run against that region's rank universe); the node-side
    tensors are stacked [R, ...] once at construction.  All regions'
    templates must agree on padded shapes — build them from the same
    probe asks over same-sized clusters (pass `gp`/`kp` explicitly to
    pin the ask-side padding).
    """

    def __init__(self, region_nodes: Sequence[Sequence[Node]],
                 probe_asks: Sequence[PlacementAsk],
                 gp: Optional[int] = None, kp: Optional[int] = None,
                 max_waves: int = 0, evict_e: int = 0):
        if not region_nodes:
            raise ValueError("need at least one region")
        # regions passed the SAME node-list object share one packed
        # template and tensorizer (packing a 10K-node universe costs
        # ~1s; usage stays per-region in the fed-level stacks, so
        # sharing is purely a pack-once optimization)
        # keep the keyed list object alive alongside its solver: a
        # freed list's id could be reused by a different region's list
        # and silently alias their universes
        shared: Dict[int, Tuple[object, ResidentSolver]] = {}
        self.solvers = []
        for nodes in region_nodes:
            entry = shared.get(id(nodes))
            if entry is None or entry[0] is not nodes:
                entry = (nodes, ResidentSolver(nodes, probe_asks,
                                               gp=gp, kp=kp,
                                               max_waves=max_waves,
                                               evict_e=evict_e))
                shared[id(nodes)] = entry
            self.solvers.append(entry[1])
        self.R = len(self.solvers)
        self.gp = self.solvers[0].gp
        self.kp = self.solvers[0].kp
        self.max_waves = max_waves
        # ragged regions (ISSUE 13): unequal universes pad to the max
        # padded node axis with DEAD rows (the same tile-granular row
        # extension the elastic grow path uses) instead of rejecting —
        # dead slots are invalid, score nothing, and never win, so a
        # padded region solves bit-identically to its unpadded self
        np_max = max(s.template.avail.shape[0] for s in self.solvers)
        for s in {id(s): s for s in self.solvers}.values():
            Np = s.template.avail.shape[0]
            if Np < np_max:
                from ..solver.tensorize import extend_template_rows
                extend_template_rows(s.template, np_max - Np)
                s._compact = np_max < 32768
                s._default_host_ok = np.zeros((s.gp, np_max), bool)
                s._default_host_ok[:, :s.template.n_real] = True
        # non-node dims cannot be padded away — name the region so a
        # mis-built federation fails loudly, not at trace time
        for name in ("attr_rank", "dc_ok", "dev_cap"):
            ref_dim = tuple(getattr(self.solvers[0].template,
                                    name).shape)
            for r, s in enumerate(self.solvers):
                dim = tuple(getattr(s.template, name).shape)
                if dim != ref_dim:
                    raise ValueError(
                        f"region {r} disagrees on {name} shape: "
                        f"{dim} vs region 0's {ref_dim}; regions "
                        "must share attribute/datacenter/device "
                        "universes (node counts may differ)")
        t0 = self.solvers[0].template
        self._node_stack = {
            "avail": jax.device_put(np.stack(
                [s.template.avail for s in self.solvers])),
            "reserved": jax.device_put(np.stack(
                [s.template.reserved for s in self.solvers])),
            "valid": jax.device_put(np.stack(
                [s.template.valid for s in self.solvers])),
            "node_dc": jax.device_put(np.stack(
                [s.template.node_dc for s in self.solvers])),
            "attr_rank": jax.device_put(np.stack(
                [s.template.attr_rank for s in self.solvers])),
            "dev_cap": jax.device_put(np.stack(
                [s.template.dev_cap for s in self.solvers])),
        }
        self._used = jax.device_put(np.stack(
            [s.template.used0 for s in self.solvers]))
        self._dev_used = jax.device_put(np.stack(
            [s.template.dev_used0 for s in self.solvers]))
        self._const_cache: Dict = {}
        self._default_host_ok = np.stack(
            [s._default_host_ok for s in self.solvers])  # [R, gp, Np]

    # ---------------- packing (delegates per region) ----------------
    def merge_asks(self, region: int, asks: Sequence[PlacementAsk]):
        return self.solvers[region].merge_asks(asks)

    def pack_batch(self, region: int, asks: Sequence[PlacementAsk],
                   job_keys: Optional[set] = None
                   ) -> Optional[PackedBatch]:
        return self.solvers[region].pack_batch(asks, job_keys=job_keys)

    def pack_batch_cached(self, region: int,
                          asks: Sequence[PlacementAsk],
                          job_keys: Optional[set] = None
                          ) -> Optional[PackedBatch]:
        return self.solvers[region].pack_batch_cached(asks,
                                                      job_keys=job_keys)

    # ---------------- solving ----------------
    def solve_stream(self, batches: Sequence[Sequence[PackedBatch]],
                     seeds: Optional[Sequence[Sequence[int]]] = None):
        """batches[r][b]: region r's b-th batch; every region must carry
        the same number of steps (pad with an empty repeat batch if a
        region's workload is shorter).  Returns (choice, ok, score,
        status) each with leading [R, B] axes."""
        return self.finish_stream(self.solve_stream_async(batches, seeds))

    def solve_stream_async(self,
                           batches: Sequence[Sequence[PackedBatch]],
                           seeds=None):
        NBs = {len(rb) for rb in batches}
        if len(batches) != self.R or len(NBs) != 1:
            raise ValueError(
                f"need {self.R} regions with equal step counts, got "
                f"{[len(rb) for rb in batches]}")
        NB = NBs.pop()
        for r, rb in enumerate(batches):
            self.solvers[r]._check_stream_jobs(rb)
        stacked = self._stack_args(batches, NB)
        n_places = np.asarray(
            [[batches[r][b].n_place for r in range(self.R)]
             for b in range(NB)], np.int32)               # [B, R]
        if seeds is None:
            seed_arr = np.zeros((NB, self.R), np.int32)
        else:
            seed_arr = np.asarray(
                [[seeds[r][b] for r in range(self.R)]
                 for b in range(NB)], np.int32)
        flat = [pb for rb in batches for pb in rb]
        self._used, self._dev_used, out = _federated_stream_kernel(
            self._node_stack["avail"], self._node_stack["reserved"],
            self._node_stack["valid"], self._node_stack["node_dc"],
            self._node_stack["attr_rank"], self._node_stack["dev_cap"],
            self._used, self._dev_used, stacked, n_places, seed_arr,
            has_spread=ResidentSolver._has_spread(flat),
            group_count_hint=ResidentSolver._group_count_hint(flat),
            max_waves=self.max_waves,
            has_distinct=ResidentSolver._has_distinct(flat),
            has_devices=ResidentSolver._has_devices(flat),
            compact=self.solvers[0]._compact)
        return out

    def finish_stream(self, out) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]:
        from ..solver.resident import unpack_stream
        out = np.asarray(out)                        # [B, R, K, .]
        out = np.swapaxes(out, 0, 1)                 # [R, B, K, .]
        return unpack_stream(out)

    def _stack_args(self, batches, NB):
        """[B, R, ...] host stack with the device-resident zero-constant
        shortcut for the big [G, N] tensors (see ResidentSolver).  A
        re-dispatched step (same PackedBatch objects — the steady-state
        delta-wave schedule) returns its fully device-put dict from
        cache and ships nothing.

        The cache key includes every region solver's resident NODE
        EPOCH (bumped by apply_delta/repack): a delta applied to a
        region between steps invalidates that step's cached stack, so a
        re-dispatch can never serve ask planes packed against the old
        node universe.  It ALSO keys on each solver's EVICT-PLANE epoch
        (ISSUE 8 satellite): PR 7's ev rows advance on pure alloc
        place/stop deltas that never move the node epoch — today the
        stacked dict carries no ev operand (the federated kernel solves
        preemption-free), but any future ev plumbing through this stack
        would otherwise serve rows from before the replay, so the key
        is pinned conservatively now and the regression test holds it."""
        step_key = (tuple(s._node_epoch for s in self.solvers),
                    tuple(s._ev_epoch for s in self.solvers),
                    tuple(id(pb) for rb in batches for pb in rb))
        cached = getattr(self, "_step_cache", None)
        if cached is None:
            cached = self._step_cache = {}
        flat_pbs = [pb for rb in batches for pb in rb]
        hit = cached.get(step_key)
        if hit is not None and len(hit[0]) == len(flat_pbs) \
                and all(a is b for a, b in zip(hit[0], flat_pbs)):
            return hit[1]
        stacked = {}
        for name in _ASK_ARGS:
            mats = [[getattr(batches[r][b], name) for r in range(self.R)]
                    for b in range(NB)]
            if name in ("coll0", "penalty", "a_host") and not any(
                    m.any() for row in mats for m in row):
                ckey = (name, NB)
                if ckey not in self._const_cache:
                    self._const_cache[ckey] = jax.device_put(np.zeros(
                        (NB, self.R) + mats[0][0].shape,
                        mats[0][0].dtype))
                stacked[name] = self._const_cache[ckey]
                continue
            if name == "host_ok" and all(
                    np.array_equal(m, self._default_host_ok[r])
                    for row in mats for r, m in enumerate(row)):
                ckey = (name, NB)
                if ckey not in self._const_cache:
                    self._const_cache[ckey] = jax.device_put(
                        np.broadcast_to(
                            self._default_host_ok[None],
                            (NB,) + self._default_host_ok.shape).copy())
                stacked[name] = self._const_cache[ckey]
                continue
            stacked[name] = np.stack(
                [np.stack(row) for row in mats])
        dev = {k: (jax.device_put(v) if isinstance(v, np.ndarray)
                   else v) for k, v in stacked.items()}
        if len(cached) > 64:
            cached.clear()
        cached[step_key] = (flat_pbs, dev)
        return dev

    # ---------------- compile-cache surface ----------------
    @staticmethod
    def compile_count() -> int:
        """Traced-computation count of the federated stream kernel.
        The jit keys on the stacked operand shapes — which carry the
        region count R and every padded dim — plus the static config,
        so adding a region (new [B, R, ...] shapes) costs exactly one
        new entry and leaves every existing entry warm.  -1 when the
        runtime doesn't expose the cache."""
        try:
            return int(_federated_stream_kernel._cache_size())
        except (AttributeError, TypeError):
            # jax version without the _cache_size probe
            return -1

    # ---------------- usage ----------------
    def usage(self) -> Tuple[np.ndarray, np.ndarray]:
        return np.asarray(self._used), np.asarray(self._dev_used)

    def reset_usage(self, used0: Optional[np.ndarray] = None,
                    dev_used0: Optional[np.ndarray] = None) -> None:
        if used0 is None:
            used0 = np.stack([s.template.used0 for s in self.solvers])
        if dev_used0 is None:
            dev_used0 = np.stack(
                [s.template.dev_used0 for s in self.solvers])
        # copy before placing: CPU device_put can alias a caller-owned
        # numpy buffer zero-copy, and a later in-place edit on the
        # caller's side would leak into the resident usage carry (the
        # PR-5 double-charge class; nomadlint ALIAS503)
        self._used = jax.device_put(np.array(used0))
        self._dev_used = jax.device_put(np.array(dev_used0))

    # ---------------- health (ISSUE 15) ----------------
    def health_counters(self):
        """Union-fleet health in ONE kernel call: the [R, Np, ...]
        region stacks flatten to a single [R*Np, ...] node axis (the
        health reduction is a sum over nodes, so region boundaries
        are irrelevant — regions already share the attr/dc/device
        universes by construction).  Bit-identical to merging the
        per-region host twins."""
        from ..telemetry.health import (HealthCounters, MAX_NODES,
                                        _health_kernel)
        ns = self._node_stack
        R, Np = ns["valid"].shape
        if R * Np > MAX_NODES:
            raise ValueError(
                f"health kernel split accumulators are i32-safe up "
                f"to {MAX_NODES} stacked node rows; got {R * Np}")
        key = ("health_ask_res", 0)
        ask = self._const_cache.get(key)
        if ask is None:
            ask = self._const_cache[key] = jax.device_put(
                np.asarray(self.solvers[0].template.ask_res,
                           np.float32))
        nres = ns["avail"].shape[-1]
        raw = _health_kernel(
            ns["avail"].reshape(-1, nres),
            ns["valid"].reshape(-1),
            ns["node_dc"].reshape(-1),
            ns["dev_cap"].reshape(R * Np, -1),
            self._used.reshape(-1, nres),
            self._dev_used.reshape(R * Np, -1),
            ask, None, None, None)
        return HealthCounters.from_raw(jax.device_get(raw))

    def health_host_twin(self):
        """Per-region numpy twins, integer-merged — the reference the
        property tests hold `health_counters` to."""
        from ..telemetry.health import HealthCounters, health_host
        used, dev_used = self.usage()
        out: Optional[HealthCounters] = None
        for r, s in enumerate(self.solvers):
            hc = _twin_no_ev(s.template, used[r], dev_used[r])
            out = hc if out is None else out.merge(hc)
        return out


def _twin_no_ev(template, used, dev_used):
    """Host twin over a template whose DEVICE stack carries no ev
    planes (the federated node stack) — mask them off so the twin
    mirrors what the kernel saw."""
    from ..telemetry.health import health_host
    if getattr(template, "ev_prio", None) is None:
        return health_host(template, used, dev_used)
    import copy
    t = copy.copy(template)
    t.ev_prio = None
    t.ev_res = None
    return health_host(t, used, dev_used)


# ===================================================================
# Cross-region scheduling (ISSUE 13)
# ===================================================================

class RegionDirectory:
    """Federation membership table: region -> live gossip members,
    driven by serf WAN-gossip join/fail events (the TPU recast of
    nomad/serf.go's WAN pool — plug ``on_join``/``on_fail`` straight
    into ``membership.gossip.GossipAgent``).  Every transition lands
    in the mesh event log as a ``region.*`` event, so the agent event
    surface (and ``MeshEventLog.region_table()``) can replay the
    federation state after the fact."""

    def __init__(self, event_log=None):
        from ..utils.tracing import global_mesh_events
        self.event_log = (global_mesh_events if event_log is None
                          else event_log)
        self._members: Dict[str, set] = {}

    @staticmethod
    def _region_member(member) -> Tuple[str, str]:
        region = getattr(member, "region", None) or "global"
        mid = getattr(member, "id", None) or str(member)
        return str(region), str(mid)

    def on_join(self, member) -> None:
        region, mid = self._region_member(member)
        new_region = not self._members.get(region)
        self._members.setdefault(region, set()).add(mid)
        self.event_log.record(
            "region.join", region=region, member=mid,
            n_members=len(self._members[region]),
            new_region=bool(new_region))

    def on_fail(self, member) -> None:
        region, mid = self._region_member(member)
        self._members.get(region, set()).discard(mid)
        left = not self._members.get(region)
        self.event_log.record(
            "region.fail", region=region, member=mid,
            n_members=len(self._members.get(region, ())))
        if left:
            # last member gone: the whole region leaves the federation
            self.event_log.record("region.leave", region=region)

    def regions(self) -> List[str]:
        return sorted(r for r, m in self._members.items() if m)

    def members_of(self, region: str) -> List[str]:
        return sorted(self._members.get(region, ()))


class CrossRegionResidentSolver:
    """Cross-region SCHEDULING over one three-tier elastic mesh (the
    ISSUE 13 tentpole).

    Where FederatedResidentSolver keeps stock Nomad's isolation (each
    region's scheduler sees only its own universe; nomad/rpc.go only
    ever FORWARDS whole evals between regions), this solver places
    every eval against the UNION of all regions' nodes — the
    intentional extension stock never does.  The interconnect stays
    honest about region boundaries: the union node axis shards over a
    ``("regions", "hosts", "chips")`` mesh, each region's shards run
    the wave loop locally, candidate keys merge per host over ICI and
    per region over DCN, and only region-winner top-K key windows
    ``(score f32, global node id i32)`` cross the modeled WAN tier
    per wave — in the same ``(score desc, id asc)`` lex-merge order
    as every inner tier, so placements and ALL explainability
    counters are bit-identical to a single flat mesh (equivalently,
    the single-device host twin over the union).  Commit psums tier
    the same way: ONE commit vector crosses the WAN per region per
    wave, not one per host (see solver/kernel.py ``_psum_mesh`` /
    ``_tier_merge`` and sharded.model_ici_dcn_wan_bytes).

    Built on ElasticShardedResidentSolver, so shard loss inside a
    region degrades gracefully (the lost tiles' nodes drop out
    fleet-wide; every surviving shard keeps the device fast path) and
    ``recover()`` rejoins at the original three-tier topology."""

    def __init__(self, region_nodes: Sequence[Sequence[Node]],
                 probe_asks: Sequence[PlacementAsk], *,
                 region_names: Optional[Sequence[str]] = None,
                 n_hosts_per_region: int = 1,
                 n_devices: Optional[int] = None,
                 directory: Optional[RegionDirectory] = None,
                 **kw):
        from .sharded import (ElasticShardedResidentSolver,
                              make_three_tier_mesh)
        if not region_nodes:
            raise ValueError("need at least one region")
        self.R = len(region_nodes)
        self.region_names = (list(region_names) if region_names
                             else [f"region{r}"
                                   for r in range(self.R)])
        if len(self.region_names) != self.R:
            raise ValueError(
                f"{len(self.region_names)} region names for "
                f"{self.R} regions")
        union: List[Node] = []
        #: node id -> owning region name (the placement attribution
        #: surface: which region a cross-region placement landed in)
        self.region_of: Dict[str, str] = {}
        self._region_slices: Dict[str, Tuple[int, int]] = {}
        for name, nodes in zip(self.region_names, region_nodes):
            lo = len(union)
            union.extend(nodes)
            self._region_slices[name] = (lo, len(union))
            for n in nodes:
                self.region_of[n.id] = name
        mesh = make_three_tier_mesh(self.R, n_hosts_per_region,
                                    n_devices)
        self.solver = ElasticShardedResidentSolver(
            union, probe_asks, mesh=mesh, **kw)
        self.directory = directory
        self.event_log = self.solver.event_log
        for name, (lo, hi) in self._region_slices.items():
            self.event_log.record(
                "region.join", region=name, n_nodes=hi - lo,
                shards_per_region=self.solver.shards_per_region)

    # ---------------- delegation to the union solver ----------------
    def pack_batch(self, asks, job_keys=None):
        return self.solver.pack_batch(asks, job_keys=job_keys)

    def pack_batch_cached(self, asks, job_keys=None):
        return self.solver.pack_batch_cached(asks, job_keys=job_keys)

    def merge_asks(self, asks):
        return self.solver.merge_asks(asks)

    def solve_stream(self, batches, seeds=None):
        return self.solver.solve_stream(batches, seeds)

    def solve_stream_async(self, batches, seeds=None):
        return self.solver.solve_stream_async(batches, seeds)

    def apply_delta(self, delta):
        return self.solver.apply_delta(delta)

    def reset_usage(self, used0=None, dev_used0=None):
        return self.solver.reset_usage(used0=used0,
                                       dev_used0=dev_used0)

    def usage(self):
        return self.solver.usage()

    def health_counters(self):
        """Fleet health over the UNION mesh — the inner elastic
        solver's kernel runs with its tile-liveness mask, so a
        region-degraded mesh reports only the device-resident fleet
        (lost regions' rows drop out, exactly like the solve path)."""
        return self.solver.health_counters()

    def health_row_mask(self):
        return self.solver.health_row_mask()

    def wave_traffic(self, batches) -> Dict:
        """The full tier stack: HBM + ICI + per-region DCN + the WAN
        block (``wan_cut_vs_flat`` and the measured-counter totals —
        see ShardedResidentSolver.wave_traffic)."""
        return self.solver.wave_traffic(batches)

    @property
    def template(self):
        return self.solver.template

    @property
    def mesh_state(self) -> str:
        return self.solver.mesh_state

    # ---------------- region surfaces ----------------
    def _region_index(self, region) -> int:
        if isinstance(region, str):
            return self.region_names.index(region)
        return int(region)

    def region_shards(self, region) -> List[int]:
        """Linear shard ids owned by one region of the healthy mesh."""
        ix = self._region_index(region)
        spr = self.solver.shards_per_region
        return list(range(ix * spr, (ix + 1) * spr))

    def region_bias_plane(self, gp: int, home,
                          weight: float = 1.0) -> np.ndarray:
        """[gp, Np] region-affinity plane for the score_spec `region`
        term (solve_kernel/host_solve_kernel ``region_bias=``):
        +weight on the home region's rows, 0 elsewhere.  Driven
        backends only — see solver/score_spec.py term_region."""
        Np = self.solver.template.avail.shape[0]
        plane = np.zeros((gp, Np), np.float32)
        lo, hi = self._region_slices[self.region_names[
            self._region_index(home)]]
        plane[:, lo:hi] = np.float32(weight)
        return plane

    def fail_region_shard(self, region,
                          shard_in_region: int = 0) -> List[int]:
        """Shard loss INSIDE a region (the region-degraded state):
        the lost tiles' nodes drop out of every solve fleet-wide
        while all surviving shards — the region's remaining ones
        included — keep solving on the device fast path.  Returns
        the lost tile ids."""
        ix = self._region_index(region)
        shard = self.region_shards(ix)[shard_in_region]
        lost = self.solver.fail_shard(shard)
        self.event_log.record(
            "region.degraded", region=self.region_names[ix],
            shard=int(shard), lost_tiles=len(lost))
        return lost

    def recover_region(self) -> int:
        """Rejoin the failed shard at the original three-tier
        topology (see ElasticShardedResidentSolver.recover)."""
        recovered = self.solver.recover()
        self.event_log.record("region.recovered",
                              bytes=int(recovered))
        return recovered
