"""Multi-chip solve: shard the node axis over a TPU mesh.

The scaling-book recipe (SURVEY §2.6): pick a mesh, annotate input
shardings, and let XLA/GSPMD insert the collectives. The node axis is our
"long sequence" (SURVEY §5.7) — feasibility masking and scoring partition
cleanly along it; the per-step masked top-k and the winner-commit scatter
become cross-shard collectives (reduce over ICI) that XLA derives from
the shardings, replacing hand-written NCCL/MPI in the reference's world.

Two levels:
  * `sharded_solve_args`  — one region's solve, node axis sharded.
  * `federated_solve_args` — BASELINE config 5: a leading region axis
    (independent solves, the federation analog of nomad/serf.go regions)
    vmapped and sharded over the mesh's "region" axis; node axis sharded
    within each region's device row.
"""
from __future__ import annotations

import functools
import inspect
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..solver.kernel import solve_kernel
from ..solver.resident import (ResidentSolver, STATUS_COMMITTED,
                               STATUS_FAILED, STATUS_RETRY, _solve_one,
                               model_wave_bytes, pack_out_compact)
from ..solver.tensorize import PackedBatch

# PartitionSpec per solve_kernel positional arg (node axis = "nodes").
_ARG_SPECS: List[P] = [
    P("nodes", None),        # avail [Np, R]
    P("nodes", None),        # reserved
    P("nodes", None),        # used0
    P("nodes"),              # valid [Np]
    P("nodes"),              # node_dc [Np]
    P("nodes", None),        # attr_rank [Np, A]
    P(),                     # ask_res [Gp, R]
    P(),                     # ask_desired [Gp]
    P(),                     # distinct [Gp]
    P(),                     # dc_ok [Gp, NDC]
    P(None, "nodes"),        # host_ok [Gp, Np]
    P(None, "nodes"),        # coll0 [Gp, Np]
    P(None, "nodes"),        # penalty [Gp, Np]
    P(), P(), P(),           # c_op / c_col / c_rank [Gp, C]
    P(), P(), P(), P(),      # a_op / a_col / a_rank / a_weight [Gp, CA]
    P(None, "nodes"),        # a_host [Gp, Np]
    P(), P(), P(),           # sp_col / sp_weight / sp_targeted [Gp, S]
    P(), P(), P(),           # sp_desired / sp_implicit / sp_used0
    P("nodes", None),        # dev_cap [Np, D]
    P("nodes", None),        # dev_used0 [Np, D]
    P(),                     # dev_ask [Gp, D]
    P(),                     # p_ask [K]
    P(),                     # n_place (scalar)
]


def _kernel_positional_count() -> int:
    """Required positional parameters of solve_kernel (everything
    before the defaulted `seed`)."""
    sig = inspect.signature(inspect.unwrap(solve_kernel))
    return sum(1 for p in sig.parameters.values()
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
               and p.default is p.empty)


# _ARG_SPECS is maintained BY HAND parallel to solve_kernel's
# positional signature: a kernel arg added without a spec would be
# silently replicated (or worse, the specs would shift and misshard an
# unrelated arg).  Fail at import time instead.
_N_KERNEL_POSITIONAL = _kernel_positional_count()
assert len(_ARG_SPECS) == _N_KERNEL_POSITIONAL, (
    f"sharded._ARG_SPECS lists {len(_ARG_SPECS)} specs but solve_kernel "
    f"takes {_N_KERNEL_POSITIONAL} positional args — update _ARG_SPECS "
    "for the new/removed kernel argument")


def kernel_args(pb: PackedBatch) -> Tuple:
    """PackedBatch -> solve_kernel positional args."""
    return (pb.avail, pb.reserved, pb.used0, pb.valid, pb.node_dc,
            pb.attr_rank, pb.ask_res, pb.ask_desired, pb.distinct, pb.dc_ok,
            pb.host_ok, pb.coll0, pb.penalty, pb.c_op, pb.c_col, pb.c_rank,
            pb.a_op, pb.a_col, pb.a_rank, pb.a_weight, pb.a_host, pb.sp_col,
            pb.sp_weight, pb.sp_targeted, pb.sp_desired, pb.sp_implicit,
            pb.sp_used0, pb.dev_cap, pb.dev_used0, pb.dev_ask, pb.p_ask,
            np.int32(pb.n_place))


def make_mesh(n_devices: Optional[int] = None,
              n_regions: int = 1) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    assert n % n_regions == 0, "devices must divide evenly into regions"
    grid = np.array(devices).reshape(n_regions, n // n_regions)
    return Mesh(grid, ("region", "nodes"))


def _shard_args(args: Tuple, mesh: Mesh, region_axis: bool) -> Tuple:
    out = []
    for arg, spec in zip(args, _ARG_SPECS):
        if region_axis:
            spec = P("region", *spec)
        out.append(jax.device_put(arg, NamedSharding(mesh, spec)))
    return tuple(out)


def sharded_solve_args(args: Tuple, mesh: Mesh):
    """Run one solve with the node axis sharded over mesh axis "nodes".
    XLA partitions the kernel and inserts the cross-shard reductions for
    the masked top-k and commit scatter."""
    return solve_kernel(*_shard_args(args, mesh, region_axis=False))


def sharded_solve(pb: PackedBatch, mesh: Mesh):
    return sharded_solve_args(kernel_args(pb), mesh)


# vmap over a leading region axis: each region is an independent solve
# (regions don't share nodes), mapping onto disjoint device rows.
# wave_mode="while": under vmap the scan shape's cond-skip lowers to
# select and pays the full wave budget per lane (see kernel.py loop-
# shape note); the while_loop runs only as deep as the slowest region.
_federated_kernel = jax.jit(jax.vmap(
    # shortlist off: under vmap its cond degrades to select and both
    # branches would execute every wave for every lane
    functools.partial(solve_kernel, wave_mode="while", shortlist_c=-1)))


def federated_solve(pbs: Sequence[PackedBatch], mesh: Mesh):
    """Solve R regions at once: inputs stacked on a leading region axis,
    sharded over the mesh "region" axis (all batches must share shapes —
    use one Tensorizer per region with identical padding)."""
    per_region = [kernel_args(pb) for pb in pbs]
    shapes = {tuple(np.shape(a) for a in args) for args in per_region}
    assert len(shapes) == 1, "region batches must be shape-aligned"
    stacked = tuple(np.stack([args[i] for args in per_region])
                    for i in range(len(per_region[0])))
    return _federated_kernel(*_shard_args(stacked, mesh, region_axis=True))


# ===================================================================
# Mesh-resident sharded solve (ISSUE 5)
# ===================================================================
# The GSPMD wrapper above is STATELESS: every solve re-ships the whole
# packed batch and lets XLA guess the collectives, so each wave re-reads
# (and re-gathers) full [G, N] planes.  The mesh-resident path below
# keeps each shard's node planes in its own HBM under a "nodes"-axis
# NamedSharding and runs the wave loop under shard_map with explicit
# candidate-only ICI traffic: per-shard [G, TK_local] (score, global
# node id) keys all-gathered and exactly lex-merged, K-sized commit/
# counter psums — never a [G, N] plane (see solver/kernel.py mesh_axis).

#: ask-side args whose TRAILING axis is the node axis
_PLANE_ASK_ARGS = ("host_ok", "coll0", "penalty", "a_host")

MESH_NODE_AXIS = "nodes"


def make_node_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the node axis (the mesh-resident solver's
    layout; make_mesh keeps the region x nodes grid for the stateless
    wrapper and the federated vmap)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (MESH_NODE_AXIS,))


def _sharded_stream_body(avail, reserved, valid, node_dc, attr_rank,
                         dev_cap, used0, dev_used0, stacked, n_places,
                         seeds, ev_res, ev_prio, *, n_shards,
                         has_spread, group_count_hint, max_waves,
                         wave_mode, has_distinct, has_devices,
                         stack_commit, compact, pallas_mode,
                         shortlist_c, has_preempt):
    """shard_map body: the resident stream scan with every solve run in
    mesh mode.  All node args are this shard's LOCAL planes; ask
    tensors are replicated except the [B, G, N] planes (node-sharded on
    their last axis).  The eviction planes (ISSUE 7) are node-sharded
    like every other node plane — the kernel's preemption pass is
    shard-local and only per-group eviction KEYS (score, global node
    id) ride the candidate-key ICI exchange.  Outputs: local
    used/dev_used blocks, replicated packed results, psum-replicated
    evict masks, wave counters."""
    def step(carry, xs):
        used, dev_used = carry
        batch, n_place, seed = xs
        res = _solve_one(avail, reserved, valid, node_dc, attr_rank,
                         dev_cap, used, dev_used, batch, n_place, seed,
                         has_spread, group_count_hint, max_waves,
                         wave_mode, has_distinct, has_devices,
                         stack_commit, pallas_mode, shortlist_c,
                         mesh_axis=MESH_NODE_AXIS, mesh_shards=n_shards,
                         has_preempt=has_preempt, ev_res=ev_res,
                         ev_prio=ev_prio)
        status = jnp.where(res.choice_ok[:, 0], STATUS_COMMITTED,
                           jnp.where(res.unfinished, STATUS_RETRY,
                                     STATUS_FAILED))
        if compact:
            packed = pack_out_compact(res.choice, res.score, status)
        else:
            packed = jnp.concatenate(
                [res.choice.astype(jnp.float32), res.score,
                 status.astype(jnp.float32)[:, None]], axis=-1)
        evict = (res.evict if has_preempt
                 else jnp.zeros((res.choice.shape[0], 1), bool))
        return ((res.used_final, res.dev_used_final),
                (packed, evict, res.n_waves, res.n_rescore))

    (used_f, dev_used_f), (out, evict, waves, rescores) = jax.lax.scan(
        step, (used0, dev_used0), (stacked, n_places, seeds))
    return used_f, dev_used_f, out, evict, waves, rescores


def _build_sharded_stream_kernel(mesh: Mesh):
    """jit(shard_map(stream)) closed over one mesh: node tensors stay
    sharded in HBM across calls, results and counters come back
    replicated."""
    axis = MESH_NODE_AXIS
    n_shards = int(mesh.shape[axis])
    node2 = P(axis, None)
    node1 = P(axis)
    plane = P(None, None, axis)

    @functools.partial(jax.jit, static_argnames=(
        "has_spread", "group_count_hint", "max_waves", "wave_mode",
        "has_distinct", "has_devices", "stack_commit", "compact",
        "pallas_mode", "shortlist_c", "has_preempt"))
    def kern(avail, reserved, valid, node_dc, attr_rank, dev_cap,
             used0, dev_used0, stacked, n_places, seeds,
             ev_res=None, ev_prio=None, *,
             has_spread=True, group_count_hint=0, max_waves=0,
             wave_mode="scan", has_distinct=True, has_devices=True,
             stack_commit=False, compact=True, pallas_mode="off",
             shortlist_c=0, has_preempt=False):
        stacked_specs = {k: (plane if k in _PLANE_ASK_ARGS else P())
                         for k in stacked}
        # eviction planes shard on the node axis with the rest of the
        # node-side state; without preemption the (None) placeholders
        # are replicated empties
        ev3 = P(axis, None, None) if has_preempt else P()
        ev2 = P(axis, None) if has_preempt else P()
        body = functools.partial(
            _sharded_stream_body, n_shards=n_shards,
            has_spread=has_spread, group_count_hint=group_count_hint,
            max_waves=max_waves, wave_mode=wave_mode,
            has_distinct=has_distinct, has_devices=has_devices,
            stack_commit=stack_commit, compact=compact,
            pallas_mode=pallas_mode, shortlist_c=shortlist_c,
            has_preempt=has_preempt)
        return shard_map(
            body, mesh=mesh,
            in_specs=(node2, node2, node1, node1, node2, node2,
                      node2, node2, stacked_specs, P(), P(),
                      ev3, ev2),
            out_specs=(node2, node2, P(), P(), P(), P()),
            check_rep=False)(
            avail, reserved, valid, node_dc, attr_rank, dev_cap,
            used0, dev_used0, stacked, n_places, seeds,
            ev_res, ev_prio)

    return kern


def model_ici_bytes(Gp: int, K: int, A: int, R: int, TKl: int,
                    n_shards: int, want_tables: bool, V: int, TW: int,
                    has_spread: bool) -> Dict:
    """Per-wave ICI byte model for the mesh-resident solve (the third
    tier next to resident.model_wave_bytes' two HBM tiers).

    `bytes_ici_per_wave` is the candidate-KEY traffic: each shard's
    [Gp, tk_local] (f32 score, i32 global id) window+table keys
    all-gathered across `n_shards` — by construction it equals
    tk_local x Gp x n_shards x key_bytes, the ISSUE-5 acceptance
    bound; no [Gp, Np] plane term appears anywhere.
    `bytes_ici_commit_per_wave` adds the K-sized commit-phase psums
    (fit votes, candidate attr rows, explainability counters)."""
    key_bytes = 8                       # f32 score + i32 node id
    tk_local = TKl + ((V + 1) * TW if want_tables else 0)
    window = Gp * tk_local * key_bytes * n_shards
    commit = (2 * K * 4                          # fit / dev-fit votes
              + (K * A * 4 if has_spread else 0)  # candidate attr rows
              + (3 * Gp + Gp * R) * 4             # counters + grp_any
              ) * n_shards
    return {"key_bytes": key_bytes, "tk_local": int(tk_local),
            "devices": int(n_shards),
            "bytes_ici_per_wave": int(window),
            "bytes_ici_commit_per_wave": int(commit),
            "bytes_ici_total_per_wave": int(window + commit),
            "bound_candidate_keys": int(
                tk_local * Gp * n_shards * key_bytes)}


class ShardedResidentSolver(ResidentSolver):
    """ResidentSolver whose node planes live SHARDED across a TPU mesh.

    Same surface as ResidentSolver (pack_batch / merge_asks /
    solve_stream / apply_delta / wave_traffic), but:

      * avail/reserved/valid/attr_rank/dev_cap and the carried
        used/dev_used live in each chip's HBM under a "nodes"-axis
        NamedSharding — packed and placed ONCE;
      * apply_delta scatters delta rows through the same donate-buffer
        kernels; GSPMD routes each row to its owning shard and the
        result is re-pinned to the node sharding (no full re-put);
      * solve_stream runs the wave loop under shard_map: full-N scoring
        and the PR 4 shortlist contention waves are shard-local, and
        only per-shard top-K candidate keys cross ICI (see
        solver/kernel.py `mesh_axis`) — placements and explainability
        counters stay bit-identical to the single-device host twin;
      * wave_traffic grows the ICI tier (`bytes_ici_per_wave`).

    Bool ask planes ship dense (not bitpacked): a uint32 lane packs 32
    node columns and cannot be split on the node axis.
    """

    _pack_bool_planes = False

    def __init__(self, nodes, probe_asks, *args,
                 mesh: Optional[Mesh] = None,
                 n_devices: Optional[int] = None, **kw):
        self._mesh = mesh if mesh is not None else make_node_mesh(
            n_devices)
        if MESH_NODE_AXIS not in self._mesh.axis_names:
            raise ValueError(
                f"mesh must carry a '{MESH_NODE_AXIS}' axis, got "
                f"{self._mesh.axis_names}")
        self.n_shards = int(self._mesh.shape[MESH_NODE_AXIS])
        self._kern = _build_sharded_stream_kernel(self._mesh)
        self._scatter_kerns: Dict = {}
        super().__init__(nodes, probe_asks, *args, **kw)
        Np = self.template.avail.shape[0]
        if Np % self.n_shards:
            raise ValueError(
                f"padded node axis {Np} does not divide over "
                f"{self.n_shards} shards")

    # ---------------- sharded placement hooks ----------------
    def _put_node(self, name, arr):
        # leading node axis sharded, trailing axes replicated (covers
        # the 3-D ev_res eviction plane alongside the 1/2-D planes)
        spec = P(MESH_NODE_AXIS, *([None] * (np.ndim(arr) - 1)))
        # copy before placing — see ResidentSolver._put_node (host-side
        # in-place template updates must never alias device buffers)
        return jax.device_put(np.array(arr),
                              NamedSharding(self._mesh, spec))

    def _put_ask(self, name, arr):
        if name in _PLANE_ASK_ARGS:
            spec = P(*([None] * (np.ndim(arr) - 1)), MESH_NODE_AXIS)
        else:
            spec = P()
        return jax.device_put(arr, NamedSharding(self._mesh, spec))

    # ---------------- delta lifecycle ----------------
    # Incremental tensorize across the mesh: the inherited apply_delta
    # drives these hooks, which route each pow2-padded row bundle to
    # the shard OWNING its node slot under shard_map — every shard
    # scatters only its own rows (non-owned indices pin to the dropped
    # Np slot), so a delta wave moves only the scattered rows and the
    # arrays never leave their node-axis sharding.  (A plain jit
    # scatter on a sharded operand is NOT partition-safe: GSPMD may
    # replicate the update and apply it once per shard.)
    def _sharded_scatter(self, op: str, arr, idx, rows):
        key = (op, np.ndim(arr))
        fn = self._scatter_kerns.get(key)
        if fn is None:
            spec = P(MESH_NODE_AXIS, *([None] * (np.ndim(arr) - 1)))

            def body(a_l, idx_, rows_, _op=op):
                Npl = a_l.shape[0]
                off = jax.lax.axis_index(MESH_NODE_AXIS) * Npl
                loc = idx_.astype(jnp.int32) - off
                # negative locals WRAP before mode="drop" bounds-checks;
                # pin non-owned rows to the always-dropped Npl slot
                loc = jnp.where((loc >= 0) & (loc < Npl), loc, Npl)
                if _op == "set":
                    return a_l.at[loc].set(rows_, mode="drop")
                return a_l.at[loc].add(rows_, mode="drop")

            fn = jax.jit(shard_map(body, mesh=self._mesh,
                                   in_specs=(spec, P(), P()),
                                   out_specs=spec, check_rep=False))
            self._scatter_kerns[key] = fn
        return fn(arr, idx, rows)

    def _delta_set(self, arr, idx, rows):
        return self._sharded_scatter("set", arr, idx, rows)

    def _delta_add(self, arr, idx, rows):
        return self._sharded_scatter("add", arr, idx, rows)

    # ---------------- solving ----------------
    def solve_stream_async(self, batches: Sequence[PackedBatch],
                           seeds: Optional[Sequence[int]] = None):
        self._check_stream_jobs(batches)
        self._check_batch_axis(batches)
        stacked = self._stack_args(batches)
        n_places = np.asarray([pb.n_place for pb in batches], np.int32)
        seed_arr = (np.zeros(len(batches), np.int32) if seeds is None
                    else np.asarray(list(seeds), np.int32))
        has_distinct = self._has_distinct(batches)
        preempt = self._preempt_on(has_distinct)
        (self._used, self._dev_used, out, self.last_evict,
         self.last_waves, self.last_rescore_waves) = self._kern(
            self._dev_node["avail"], self._dev_node["reserved"],
            self._dev_node["valid"], self._dev_node["node_dc"],
            self._dev_node["attr_rank"], self._dev_node["dev_cap"],
            self._used, self._dev_used, stacked, n_places, seed_arr,
            self._dev_node.get("ev_res"), self._dev_node.get("ev_prio"),
            has_spread=self._has_spread(batches),
            group_count_hint=self._group_count_hint(batches),
            max_waves=self.max_waves, wave_mode=self.wave_mode,
            has_distinct=has_distinct,
            has_devices=self._has_devices(batches),
            stack_commit=self.stack_commit, compact=self._compact,
            pallas_mode=self.pallas, shortlist_c=self.shortlist_c,
            has_preempt=preempt)
        return out

    # ---------------- byte model ----------------
    def measured_wave_counters(self) -> Optional[Dict]:
        """Mesh units: rescore_waves counts per-SHARD full passes (the
        kernel psums its per-shard escape counter), so the shortlist
        remainder is taken against waves x shards."""
        m = super().measured_wave_counters()
        if m is not None:
            m["shard_waves_total"] = m["waves_total"] * self.n_shards
            m["shortlist_waves"] = max(
                m["shard_waves_total"] - m["rescore_waves"], 0)
        return m

    def wave_traffic(self, batches: Sequence[PackedBatch]) -> Dict:
        """Three-tier model: the inherited two HBM tiers plus the ICI
        tier.  HBM tiers are restated PER SHARD (each chip walks only
        its Np/devices slice of every plane); `measured` gains
        `modeled_bytes_ici_total` (per-wave ICI model x measured wave
        counters).  `rescore_waves` counts per-SHARD full passes (a
        mixed wave where 3 of 8 shards escape costs 3 shard-plane
        walks, not 8)."""
        from ..solver import pallas_kernel as _pk
        from ..solver.kernel import (TOP_K as _TOP_K, WAVE_K,
                                     _MERGED_W_CAP, _WIDE_W_CAP,
                                     MERGED_GP_MAX, resolve_shortlist_c)
        out = super().wave_traffic(batches)
        t = self.template
        Np, R = t.avail.shape
        Npl = Np // self.n_shards
        Gp = max(pb.ask_res.shape[0] for pb in batches)
        K = max(pb.p_ask.shape[0] for pb in batches)
        A = t.attr_rank.shape[1]
        S = t.sp_desired.shape[1]
        V = t.sp_desired.shape[2]
        has_spread = self._has_spread(batches)
        hint = self._group_count_hint(batches)
        w_cap = (_MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP)
        TK = min(max(WAVE_K, min(2 * hint, w_cap)) + _TOP_K, Np)
        TKl = min(TK, Npl)
        C = (0 if self._has_distinct(batches)
             else resolve_shortlist_c(Npl, TKl, self.shortlist_c))
        mode = self.pallas
        if mode == "auto":
            mode = _pk.resolve_mode(Npl, Gp, TKl, V, has_spread)
        want_tables = has_spread and V <= 8 and not self.stack_commit
        TKv = -(-TK // (V + 1)) if want_tables else 0
        TW = min(TKv, Npl) if want_tables else 0
        out["ici"] = model_ici_bytes(Gp, K, A, R, TKl, self.n_shards,
                                     want_tables, V, TW, has_spread)
        out["bytes_ici_per_wave"] = out["ici"]["bytes_ici_per_wave"]
        b1, brw, passes = model_wave_bytes(
            Npl, Gp, K, S, R, has_spread, mode, TKl, C)
        out["per_shard"] = {"np_local": int(Npl),
                            "bytes_wave1": int(b1),
                            "bytes_rewave": int(brw),
                            "shortlist_c": int(C),
                            "fused_pass_count": passes}
        m = out.get("measured")
        if m is not None:
            # rescore_waves counts PER-SHARD full passes in mesh mode
            shortlist_shard_waves = (m["waves_total"] * self.n_shards
                                     - m["rescore_waves"])
            m["modeled_bytes_total"] = int(
                b1 * m["rescore_waves"]
                + brw * max(shortlist_shard_waves, 0))
            m["modeled_bytes_ici_total"] = int(
                out["ici"]["bytes_ici_total_per_wave"]
                * m["waves_total"])
        return out
