"""Multi-chip solve: shard the node axis over a TPU mesh.

The scaling-book recipe (SURVEY §2.6): pick a mesh, annotate input
shardings, and let XLA/GSPMD insert the collectives. The node axis is our
"long sequence" (SURVEY §5.7) — feasibility masking and scoring partition
cleanly along it; the per-step masked top-k and the winner-commit scatter
become cross-shard collectives (reduce over ICI) that XLA derives from
the shardings, replacing hand-written NCCL/MPI in the reference's world.

Two levels:
  * `sharded_solve_args`  — one region's solve, node axis sharded.
  * `federated_solve_args` — BASELINE config 5: a leading region axis
    (independent solves, the federation analog of nomad/serf.go regions)
    vmapped and sharded over the mesh's "region" axis; node axis sharded
    within each region's device row.
"""
from __future__ import annotations

import functools
import inspect
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..solver.kernel import solve_kernel
from ..solver.resident import (ResidentSolver, STATUS_COMMITTED,
                               STATUS_FAILED, STATUS_RETRY, _solve_one,
                               model_wave_bytes, pack_out_compact)
from ..solver.tensorize import PackedBatch

# PartitionSpec per solve_kernel positional arg (node axis = "nodes").
_ARG_SPECS: List[P] = [
    P("nodes", None),        # avail [Np, R]
    P("nodes", None),        # reserved
    P("nodes", None),        # used0
    P("nodes"),              # valid [Np]
    P("nodes"),              # node_dc [Np]
    P("nodes", None),        # attr_rank [Np, A]
    P(),                     # ask_res [Gp, R]
    P(),                     # ask_desired [Gp]
    P(),                     # distinct [Gp]
    P(),                     # dc_ok [Gp, NDC]
    P(None, "nodes"),        # host_ok [Gp, Np]
    P(None, "nodes"),        # coll0 [Gp, Np]
    P(None, "nodes"),        # penalty [Gp, Np]
    P(), P(), P(),           # c_op / c_col / c_rank [Gp, C]
    P(), P(), P(), P(),      # a_op / a_col / a_rank / a_weight [Gp, CA]
    P(None, "nodes"),        # a_host [Gp, Np]
    P(), P(), P(),           # sp_col / sp_weight / sp_targeted [Gp, S]
    P(), P(), P(),           # sp_desired / sp_implicit / sp_used0
    P("nodes", None),        # dev_cap [Np, D]
    P("nodes", None),        # dev_used0 [Np, D]
    P(),                     # dev_ask [Gp, D]
    P(),                     # p_ask [K]
    P(),                     # n_place (scalar)
]


def _kernel_positional_count() -> int:
    """Required positional parameters of solve_kernel (everything
    before the defaulted `seed`)."""
    sig = inspect.signature(inspect.unwrap(solve_kernel))
    return sum(1 for p in sig.parameters.values()
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
               and p.default is p.empty)


# _ARG_SPECS is maintained BY HAND parallel to solve_kernel's
# positional signature: a kernel arg added without a spec would be
# silently replicated (or worse, the specs would shift and misshard an
# unrelated arg).  Fail at import time instead.
_N_KERNEL_POSITIONAL = _kernel_positional_count()
assert len(_ARG_SPECS) == _N_KERNEL_POSITIONAL, (
    f"sharded._ARG_SPECS lists {len(_ARG_SPECS)} specs but solve_kernel "
    f"takes {_N_KERNEL_POSITIONAL} positional args — update _ARG_SPECS "
    "for the new/removed kernel argument")


def kernel_args(pb: PackedBatch) -> Tuple:
    """PackedBatch -> solve_kernel positional args."""
    return (pb.avail, pb.reserved, pb.used0, pb.valid, pb.node_dc,
            pb.attr_rank, pb.ask_res, pb.ask_desired, pb.distinct, pb.dc_ok,
            pb.host_ok, pb.coll0, pb.penalty, pb.c_op, pb.c_col, pb.c_rank,
            pb.a_op, pb.a_col, pb.a_rank, pb.a_weight, pb.a_host, pb.sp_col,
            pb.sp_weight, pb.sp_targeted, pb.sp_desired, pb.sp_implicit,
            pb.sp_used0, pb.dev_cap, pb.dev_used0, pb.dev_ask, pb.p_ask,
            np.int32(pb.n_place))


def make_mesh(n_devices: Optional[int] = None,
              n_regions: int = 1) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    assert n % n_regions == 0, "devices must divide evenly into regions"
    grid = np.array(devices).reshape(n_regions, n // n_regions)
    return Mesh(grid, ("region", "nodes"))


def _shard_args(args: Tuple, mesh: Mesh, region_axis: bool) -> Tuple:
    out = []
    for arg, spec in zip(args, _ARG_SPECS):
        if region_axis:
            spec = P("region", *spec)
        out.append(jax.device_put(arg, NamedSharding(mesh, spec)))
    return tuple(out)


def sharded_solve_args(args: Tuple, mesh: Mesh):
    """Run one solve with the node axis sharded over mesh axis "nodes".
    XLA partitions the kernel and inserts the cross-shard reductions for
    the masked top-k and commit scatter."""
    return solve_kernel(*_shard_args(args, mesh, region_axis=False))


def sharded_solve(pb: PackedBatch, mesh: Mesh):
    return sharded_solve_args(kernel_args(pb), mesh)


# vmap over a leading region axis: each region is an independent solve
# (regions don't share nodes), mapping onto disjoint device rows.
# wave_mode="while": under vmap the scan shape's cond-skip lowers to
# select and pays the full wave budget per lane (see kernel.py loop-
# shape note); the while_loop runs only as deep as the slowest region.
_federated_kernel = jax.jit(jax.vmap(
    # shortlist off: under vmap its cond degrades to select and both
    # branches would execute every wave for every lane
    functools.partial(solve_kernel, wave_mode="while", shortlist_c=-1)))


def federated_solve(pbs: Sequence[PackedBatch], mesh: Mesh):
    """Solve R regions at once: inputs stacked on a leading region axis,
    sharded over the mesh "region" axis (all batches must share shapes —
    use one Tensorizer per region with identical padding)."""
    per_region = [kernel_args(pb) for pb in pbs]
    shapes = {tuple(np.shape(a) for a in args) for args in per_region}
    assert len(shapes) == 1, "region batches must be shape-aligned"
    stacked = tuple(np.stack([args[i] for args in per_region])
                    for i in range(len(per_region[0])))
    return _federated_kernel(*_shard_args(stacked, mesh, region_axis=True))


# ===================================================================
# Mesh-resident sharded solve (ISSUE 5)
# ===================================================================
# The GSPMD wrapper above is STATELESS: every solve re-ships the whole
# packed batch and lets XLA guess the collectives, so each wave re-reads
# (and re-gathers) full [G, N] planes.  The mesh-resident path below
# keeps each shard's node planes in its own HBM under a "nodes"-axis
# NamedSharding and runs the wave loop under shard_map with explicit
# candidate-only ICI traffic: per-shard [G, TK_local] (score, global
# node id) keys all-gathered and exactly lex-merged, K-sized commit/
# counter psums — never a [G, N] plane (see solver/kernel.py mesh_axis).

#: ask-side args whose TRAILING axis is the node axis
_PLANE_ASK_ARGS = ("host_ok", "coll0", "penalty", "a_host")

MESH_NODE_AXIS = "nodes"
#: two-tier hierarchy axes (ISSUE 8): the node axis splits over
#: ("hosts", "chips") — candidate keys merge per host over ICI, only
#: host-winner keys cross the DCN between hosts
MESH_HOST_AXIS = "hosts"
MESH_CHIP_AXIS = "chips"
#: three-tier hierarchy axis (ISSUE 13): the node axis splits over
#: ("regions", "hosts", "chips") — candidate keys merge per host over
#: ICI and per region over DCN; only region-winner keys cross the WAN
MESH_REGION_AXIS = "regions"


def make_node_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the node axis (the mesh-resident solver's
    layout; make_mesh keeps the region x nodes grid for the stateless
    wrapper and the federated vmap)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (MESH_NODE_AXIS,))


def env_mesh_hosts() -> Optional[int]:
    """NOMAD_TPU_MESH_HOSTS: host-group count for the two-tier mesh
    (unset/empty/0 -> None: flat single-tier)."""
    import os
    raw = os.environ.get("NOMAD_TPU_MESH_HOSTS", "").strip()
    if not raw or raw == "0":
        return None
    try:
        h = int(raw)
    except ValueError:
        raise ValueError(
            f"NOMAD_TPU_MESH_HOSTS={raw!r} invalid: use a positive "
            "host-group count (0/unset = flat mesh)") from None
    if h <= 0:
        raise ValueError(
            f"NOMAD_TPU_MESH_HOSTS={h} invalid: must be positive")
    return h


def env_mesh_regions() -> Optional[int]:
    """NOMAD_TPU_MESH_REGIONS: region count for the three-tier mesh
    (unset/empty/0 -> None: no WAN tier)."""
    import os
    raw = os.environ.get("NOMAD_TPU_MESH_REGIONS", "").strip()
    if not raw or raw == "0":
        return None
    try:
        r = int(raw)
    except ValueError:
        raise ValueError(
            f"NOMAD_TPU_MESH_REGIONS={raw!r} invalid: use a positive "
            "region count (0/unset = no WAN tier)") from None
    if r <= 0:
        raise ValueError(
            f"NOMAD_TPU_MESH_REGIONS={r} invalid: must be positive")
    return r


def make_two_tier_mesh(n_hosts: Optional[int] = None,
                       n_devices: Optional[int] = None) -> Mesh:
    """A ("hosts", "chips") mesh: the device list factored into
    n_hosts contiguous groups (real fleets would group by actual host
    topology; the CPU simulation groups by enumeration order).
    n_hosts defaults to NOMAD_TPU_MESH_HOSTS."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if n_hosts is None:
        n_hosts = env_mesh_hosts() or 1
    if n_hosts <= 0 or n % n_hosts:
        raise ValueError(
            f"{n} devices do not factor into {n_hosts} hosts x "
            f"{n / max(n_hosts, 1):g} chips; pick a host count that "
            "divides the device count")
    grid = np.array(devices).reshape(n_hosts, n // n_hosts)
    return Mesh(grid, (MESH_HOST_AXIS, MESH_CHIP_AXIS))


def make_three_tier_mesh(n_regions: Optional[int] = None,
                         n_hosts: Optional[int] = None,
                         n_devices: Optional[int] = None) -> Mesh:
    """A ("regions", "hosts", "chips") mesh (ISSUE 13): the device
    list factored into n_regions contiguous region groups of n_hosts
    hosts each (n_hosts is hosts PER REGION).  Defaults come from
    NOMAD_TPU_MESH_REGIONS / NOMAD_TPU_MESH_HOSTS."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if n_regions is None:
        n_regions = env_mesh_regions() or 1
    if n_hosts is None:
        n_hosts = env_mesh_hosts() or 1
    if (n_regions <= 0 or n_hosts <= 0 or n % n_regions
            or (n // n_regions) % n_hosts):
        raise ValueError(
            f"{n} devices do not factor into {n_regions} regions x "
            f"{n_hosts} hosts x chips; pick counts whose product "
            "divides the device count")
    grid = np.array(devices).reshape(
        n_regions, n_hosts, n // (n_regions * n_hosts))
    return Mesh(grid, (MESH_REGION_AXIS, MESH_HOST_AXIS,
                       MESH_CHIP_AXIS))


def _sharded_stream_body(avail, reserved, valid, node_dc, attr_rank,
                         dev_cap, used0, dev_used0, stacked, n_places,
                         seeds, ev_res, ev_prio, node_gid, owner_map,
                         slot_map, *, n_shards, mesh_axes, mesh_hosts,
                         mesh_regions, mesh_nt, tile_np,
                         has_spread, group_count_hint, max_waves,
                         wave_mode, has_distinct, has_devices,
                         stack_commit, compact, pallas_mode,
                         shortlist_c, has_preempt):
    """shard_map body: the resident stream scan with every solve run in
    mesh mode.  All node args are this shard's LOCAL planes; ask
    tensors are replicated except the [B, G, N] planes (node-sharded on
    their last axis).  The eviction planes (ISSUE 7) are node-sharded
    like every other node plane — the kernel's preemption pass is
    shard-local and only per-group eviction KEYS (score, global node
    id) ride the candidate-key ICI exchange.  Outputs: local
    used/dev_used blocks, replicated packed results, psum-replicated
    evict masks, wave counters."""
    def step(carry, xs):
        used, dev_used = carry
        batch, n_place, seed = xs
        res = _solve_one(avail, reserved, valid, node_dc, attr_rank,
                         dev_cap, used, dev_used, batch, n_place, seed,
                         has_spread, group_count_hint, max_waves,
                         wave_mode, has_distinct, has_devices,
                         stack_commit, pallas_mode, shortlist_c,
                         mesh_axis=mesh_axes, mesh_shards=n_shards,
                         has_preempt=has_preempt, ev_res=ev_res,
                         ev_prio=ev_prio, mesh_hosts=mesh_hosts,
                         mesh_regions=mesh_regions,
                         mesh_nt=mesh_nt, tile_np=tile_np,
                         node_gid=node_gid, owner_map=owner_map,
                         slot_map=slot_map)
        status = jnp.where(res.choice_ok[:, 0], STATUS_COMMITTED,
                           jnp.where(res.unfinished, STATUS_RETRY,
                                     STATUS_FAILED))
        if compact:
            packed = pack_out_compact(res.choice, res.score, status)
        else:
            packed = jnp.concatenate(
                [res.choice.astype(jnp.float32), res.score,
                 status.astype(jnp.float32)[:, None]], axis=-1)
        evict = (res.evict if has_preempt
                 else jnp.zeros((res.choice.shape[0], 1), bool))
        return ((res.used_final, res.dev_used_final),
                (packed, evict, res.n_waves, res.n_rescore))

    (used_f, dev_used_f), (out, evict, waves, rescores) = jax.lax.scan(
        step, (used0, dev_used0), (stacked, n_places, seeds))
    return used_f, dev_used_f, out, evict, waves, rescores


def mesh_node_axes(mesh: Mesh):
    """The node-axis split of a solver mesh: the flat "nodes" axis
    (PR 5), the two-tier ("hosts", "chips") hierarchy (ISSUE 8), or
    the three-tier ("regions", "hosts", "chips") hierarchy (ISSUE 13).
    Returns (axes, n_hosts) where axes is the solve_kernel mesh_axis
    value AND the PartitionSpec element splitting the node dim;
    n_hosts is hosts PER REGION in the three-tier case (use
    mesh_region_count for the region fan-out)."""
    names = mesh.axis_names
    if MESH_HOST_AXIS in names and MESH_CHIP_AXIS in names:
        if MESH_REGION_AXIS in names:
            return ((MESH_REGION_AXIS, MESH_HOST_AXIS,
                     MESH_CHIP_AXIS), int(mesh.shape[MESH_HOST_AXIS]))
        return ((MESH_HOST_AXIS, MESH_CHIP_AXIS),
                int(mesh.shape[MESH_HOST_AXIS]))
    if MESH_NODE_AXIS in names:
        return MESH_NODE_AXIS, 1
    raise ValueError(
        f"mesh must carry a '{MESH_NODE_AXIS}' axis or the "
        f"('{MESH_HOST_AXIS}', '{MESH_CHIP_AXIS}') pair "
        f"(optionally under '{MESH_REGION_AXIS}'), got {names}")


def mesh_region_count(mesh: Mesh) -> int:
    """Region fan-out of a solver mesh (1 when no WAN tier)."""
    return (int(mesh.shape[MESH_REGION_AXIS])
            if MESH_REGION_AXIS in mesh.axis_names else 1)


def _build_sharded_stream_kernel(mesh: Mesh):
    """jit(shard_map(stream)) closed over one mesh: node tensors stay
    sharded in HBM across calls, results and counters come back
    replicated.  The node dimension splits over the flat "nodes" axis
    or the two-tier ("hosts", "chips") pair — the kernel's merge and
    psum tiering follows the axis structure."""
    axis, n_hosts = mesh_node_axes(mesh)
    n_regions = mesh_region_count(mesh)
    n_shards = int(np.prod([mesh.shape[a] for a in
                            (axis if isinstance(axis, tuple)
                             else (axis,))]))
    node2 = P(axis, None)
    node1 = P(axis)
    plane = P(None, None, axis)

    @functools.partial(jax.jit, static_argnames=(
        "has_spread", "group_count_hint", "max_waves", "wave_mode",
        "has_distinct", "has_devices", "stack_commit", "compact",
        "pallas_mode", "shortlist_c", "has_preempt", "mesh_nt",
        "tile_np"))
    def kern(avail, reserved, valid, node_dc, attr_rank, dev_cap,
             used0, dev_used0, stacked, n_places, seeds,
             ev_res=None, ev_prio=None, node_gid=None, owner_map=None,
             slot_map=None, *,
             has_spread=True, group_count_hint=0, max_waves=0,
             wave_mode="scan", has_distinct=True, has_devices=True,
             stack_commit=False, compact=True, pallas_mode="off",
             shortlist_c=0, has_preempt=False, mesh_nt=0, tile_np=0):
        stacked_specs = {k: (plane if k in _PLANE_ASK_ARGS else P())
                         for k in stacked}
        # eviction planes shard on the node axis with the rest of the
        # node-side state; without preemption the (None) placeholders
        # are replicated empties
        ev3 = P(axis, None, None) if has_preempt else P()
        ev2 = P(axis, None) if has_preempt else P()
        gid1 = P(axis) if tile_np else P()
        body = functools.partial(
            _sharded_stream_body, n_shards=n_shards,
            mesh_axes=axis, mesh_hosts=n_hosts,
            mesh_regions=n_regions, mesh_nt=mesh_nt,
            tile_np=tile_np,
            has_spread=has_spread, group_count_hint=group_count_hint,
            max_waves=max_waves, wave_mode=wave_mode,
            has_distinct=has_distinct, has_devices=has_devices,
            stack_commit=stack_commit, compact=compact,
            pallas_mode=pallas_mode, shortlist_c=shortlist_c,
            has_preempt=has_preempt)
        return shard_map(
            body, mesh=mesh,
            in_specs=(node2, node2, node1, node1, node2, node2,
                      node2, node2, stacked_specs, P(), P(),
                      ev3, ev2, gid1, P(), P()),
            out_specs=(node2, node2, P(), P(), P(), P()),
            check_rep=False)(
            avail, reserved, valid, node_dc, attr_rank, dev_cap,
            used0, dev_used0, stacked, n_places, seeds,
            ev_res, ev_prio, node_gid, owner_map, slot_map)

    return kern


def model_ici_bytes(Gp: int, K: int, A: int, R: int, TKl: int,
                    n_shards: int, want_tables: bool, V: int, TW: int,
                    has_spread: bool) -> Dict:
    """Per-wave ICI byte model for the mesh-resident solve (the third
    tier next to resident.model_wave_bytes' two HBM tiers).

    `bytes_ici_per_wave` is the candidate-KEY traffic: each shard's
    [Gp, tk_local] (f32 score, i32 global id) window+table keys
    all-gathered across `n_shards` — by construction it equals
    tk_local x Gp x n_shards x key_bytes, the ISSUE-5 acceptance
    bound; no [Gp, Np] plane term appears anywhere.
    `bytes_ici_commit_per_wave` adds the K-sized commit-phase psums
    (fit votes, candidate attr rows, explainability counters)."""
    key_bytes = 8                       # f32 score + i32 node id
    tk_local = TKl + ((V + 1) * TW if want_tables else 0)
    window = Gp * tk_local * key_bytes * n_shards
    commit = (2 * K * 4                          # fit / dev-fit votes
              + (K * A * 4 if has_spread else 0)  # candidate attr rows
              + (3 * Gp + Gp * R) * 4             # counters + grp_any
              ) * n_shards
    return {"key_bytes": key_bytes, "tk_local": int(tk_local),
            "devices": int(n_shards),
            "bytes_ici_per_wave": int(window),
            "bytes_ici_commit_per_wave": int(commit),
            "bytes_ici_total_per_wave": int(window + commit),
            "bound_candidate_keys": int(
                tk_local * Gp * n_shards * key_bytes)}


def model_ici_dcn_bytes(Gp: int, K: int, A: int, R: int, TK: int,
                        TKl: int, n_shards: int, n_hosts: int,
                        want_tables: bool, V: int, TKv: int, TW: int,
                        has_spread: bool) -> Dict:
    """Two-tier per-wave interconnect byte model (ISSUE 8), the DCN
    generalization of model_ici_bytes.

    Convention: a tier's bytes/wave counts the bytes ENTERING devices
    across that tier's links (import volume), fleet-wide.  The flat
    single-tier exchange is host-OBLIVIOUS — its all-gather
    materializes every remote shard's window on every chip, so each
    chip imports (S - CPH) remote chunks over DCN.  The tiered
    exchange merges each host over ICI first and ships only
    chip-SLICED host-winner windows across DCN — one host window per
    DCN traversal, in log2(H) recursive-doubling rounds (pow2 H; one
    sliced all-gather otherwise).  Commit psums tier the same way:
    the host-level reduction moves host partials, not shard partials.

    `dcn_cut_vs_flat` is the acceptance figure: modeled DCN bytes/wave
    of the tiered exchange over the flat exchange's cross-host bytes.
    """
    key_bytes = 8                       # f32 score + i32 node id
    H = max(n_hosts, 1)
    CPH = n_shards // H
    # per-shard window chunk (keys + per-value table keys)
    tk_local = TKl + ((V + 1) * TW if want_tables else 0)
    ck = Gp * tk_local * key_bytes
    # host-merged window chunk after the ICI tier
    tk_host = (min(TK, TKl * CPH)
               + ((V + 1) * min(TKv, TW * CPH) if want_tables else 0))
    ch = Gp * tk_host * key_bytes
    # commit-phase vector (fit votes, candidate attr rows, counters)
    cc = (2 * K * 4
          + (K * A * 4 if has_spread else 0)
          + (3 * Gp + Gp * R) * 4)
    # ---- flat single-tier exchange, charged per-chip import ----
    flat_dcn_window = H * CPH * (n_shards - CPH) * ck
    flat_ici_window = H * CPH * (CPH - 1) * ck
    # psum ~ reduce-scatter + all-gather: 2(S-1)/S chunk imports per
    # chip, (S-CPH)/(S-1) of them crossing hosts
    flat_dcn_commit = (2 * H * CPH * (n_shards - CPH) * cc
                       // max(n_shards, 1))
    # ---- tiered exchange ----
    # ICI tier: within-host window gather + the sliced DCN rounds'
    # reassembly gathers
    if H > 1 and H & (H - 1) == 0:
        rounds = H.bit_length() - 1
        dcn_window = H * rounds * ch
    elif H > 1:
        rounds = 1
        dcn_window = H * (H - 1) * ch
    else:
        rounds = 0
        dcn_window = 0
    ici_window = (H * CPH * (CPH - 1) * ck
                  + H * CPH * rounds * ch * (CPH - 1) // max(CPH, 1))
    # commit psums: ICI reduce, then the CHIP-SLICED host tier — each
    # chip ships its 1/CPH slice of the host-reduced vector across
    # DCN (reduce-scatter + host psum + ICI reassembly gather), so a
    # commit vector crosses DCN ~2(H-1)/H times per host, not per chip
    ici_commit = 2 * H * CPH * (CPH - 1) * cc // max(CPH, 1)
    dcn_commit = (2 * (H - 1) * cc) if H > 1 else 0
    dcn_total = dcn_window + dcn_commit
    flat_dcn_total = flat_dcn_window + flat_dcn_commit
    return {
        "key_bytes": key_bytes, "n_hosts": int(H),
        "chips_per_host": int(CPH),
        "tk_local": int(tk_local), "tk_host": int(tk_host),
        "bytes_ici_per_wave": int(ici_window + ici_commit),
        "bytes_dcn_window_per_wave": int(dcn_window),
        "bytes_dcn_commit_per_wave": int(dcn_commit),
        "bytes_dcn_total_per_wave": int(dcn_total),
        "flat_dcn_window_per_wave": int(flat_dcn_window),
        "flat_dcn_total_per_wave": int(flat_dcn_total),
        "dcn_cut_vs_flat": (float(dcn_total) / float(flat_dcn_total)
                            if flat_dcn_total else 0.0),
    }


def model_ici_dcn_wan_bytes(Gp: int, K: int, A: int, R: int, TK: int,
                            TKl: int, n_shards: int, n_regions: int,
                            n_hosts: int, want_tables: bool, V: int,
                            TKv: int, TW: int,
                            has_spread: bool) -> Dict:
    """Three-tier per-wave interconnect byte model (ISSUE 13): the WAN
    generalization of model_ici_dcn_bytes.  `n_hosts` is hosts PER
    REGION; shards split n_regions x n_hosts x chips.

    Same import-volume convention as the DCN model.  Within a region
    the two-tier ICI/DCN exchange runs unchanged (restated here per
    region); across regions only region-winner candidate-key windows
    travel — one region window per WAN traversal, in log2(Rg)
    recursive-doubling rounds (pow2 region counts; one sliced
    all-gather otherwise) — and ONE commit vector crosses the WAN per
    region per psum (reduce-scatter over the chip x host slice, WAN
    psum, in-region reassembly), not one per host or chip.

    `wan_cut_vs_flat` is the acceptance figure: modeled WAN bytes/wave
    of the tiered exchange over the flat single-tier exchange's
    cross-REGION bytes."""
    key_bytes = 8
    Rg = max(n_regions, 1)
    SPR = n_shards // Rg                # shards per region
    base = model_ici_dcn_bytes(Gp, K, A, R, TK, TKl, SPR, n_hosts,
                               want_tables, V, TKv, TW, has_spread)
    H = max(n_hosts, 1)
    CPH = SPR // H
    tk_local = base["tk_local"]
    ck = Gp * tk_local * key_bytes
    # region-merged window chunk after the ICI + DCN tiers
    tk_region = (min(TK, TKl * SPR)
                 + ((V + 1) * min(TKv, TW * SPR) if want_tables
                    else 0))
    cr = Gp * tk_region * key_bytes
    cc = (2 * K * 4
          + (K * A * 4 if has_spread else 0)
          + (3 * Gp + Gp * R) * 4)
    # ---- flat single-tier exchange, charged per-chip import ----
    # every chip imports every chunk outside its own region
    flat_wan_window = n_shards * (n_shards - SPR) * ck
    flat_wan_commit = (2 * n_shards * (n_shards - SPR) * cc
                       // max(n_shards, 1))
    # ---- tiered exchange ----
    if Rg > 1 and Rg & (Rg - 1) == 0:
        rounds = Rg.bit_length() - 1
        wan_window = Rg * rounds * cr
    elif Rg > 1:
        rounds = 1
        wan_window = Rg * (Rg - 1) * cr
    else:
        rounds = 0
        wan_window = 0
    # the WAN rounds' chip-sliced reassembly gathers ride the
    # in-region links: (SPR-1)/SPR of each round's region window
    # re-gathers over ICI+DCN inside every region
    intra_reassembly = (Rg * SPR * rounds * cr * (SPR - 1)
                        // max(SPR, 1))
    wan_commit = (2 * (Rg - 1) * cc) if Rg > 1 else 0
    wan_total = wan_window + wan_commit
    flat_wan_total = flat_wan_window + flat_wan_commit
    out = {
        "key_bytes": key_bytes, "n_regions": int(Rg),
        "shards_per_region": int(SPR), "n_hosts": int(H),
        "chips_per_host": int(CPH),
        "tk_local": int(tk_local), "tk_host": base["tk_host"],
        "tk_region": int(tk_region),
        # per-region two-tier exchange restated fleet-wide, plus the
        # WAN reassembly riding the in-region links
        "bytes_ici_per_wave": int(
            Rg * base["bytes_ici_per_wave"] + intra_reassembly),
        "bytes_dcn_total_per_wave": int(
            Rg * base["bytes_dcn_total_per_wave"]),
        "bytes_wan_window_per_wave": int(wan_window),
        "bytes_wan_commit_per_wave": int(wan_commit),
        "bytes_wan_total_per_wave": int(wan_total),
        "flat_wan_window_per_wave": int(flat_wan_window),
        "flat_wan_total_per_wave": int(flat_wan_total),
        "wan_cut_vs_flat": (float(wan_total) / float(flat_wan_total)
                            if flat_wan_total else 0.0),
    }
    return out


class ShardedResidentSolver(ResidentSolver):
    """ResidentSolver whose node planes live SHARDED across a TPU mesh.

    Same surface as ResidentSolver (pack_batch / merge_asks /
    solve_stream / apply_delta / wave_traffic), but:

      * avail/reserved/valid/attr_rank/dev_cap and the carried
        used/dev_used live in each chip's HBM under a "nodes"-axis
        NamedSharding — packed and placed ONCE;
      * apply_delta scatters delta rows through the same donate-buffer
        kernels; GSPMD routes each row to its owning shard and the
        result is re-pinned to the node sharding (no full re-put);
      * solve_stream runs the wave loop under shard_map: full-N scoring
        and the PR 4 shortlist contention waves are shard-local, and
        only per-shard top-K candidate keys cross ICI (see
        solver/kernel.py `mesh_axis`) — placements and explainability
        counters stay bit-identical to the single-device host twin;
      * wave_traffic grows the ICI tier (`bytes_ici_per_wave`).

    Bool ask planes ship dense (not bitpacked): a uint32 lane packs 32
    node columns and cannot be split on the node axis.
    """

    _pack_bool_planes = False

    def __init__(self, nodes, probe_asks, *args,
                 mesh: Optional[Mesh] = None,
                 n_devices: Optional[int] = None, **kw):
        if mesh is None:
            # NOMAD_TPU_MESH_REGIONS > 1 defaults new solvers onto the
            # three-tier hierarchy, NOMAD_TPU_MESH_HOSTS > 1 onto the
            # two-tier one; unset keeps the flat PR-5 mesh
            regions = env_mesh_regions()
            hosts = env_mesh_hosts()
            if regions and regions > 1:
                mesh = make_three_tier_mesh(regions, hosts or 1,
                                            n_devices)
            elif hosts and hosts > 1:
                mesh = make_two_tier_mesh(hosts, n_devices)
            else:
                mesh = make_node_mesh(n_devices)
        self._set_mesh(mesh)
        super().__init__(nodes, probe_asks, *args, **kw)
        Np = self.template.avail.shape[0]
        if not self._elastic and Np % self.n_shards:
            raise ValueError(
                f"padded node axis {Np} does not divide over "
                f"{self.n_shards} shards")

    #: subclass flag: the elastic solver owns the node axis by tile
    #: remap instead of contiguous blocks
    _elastic = False

    def _set_mesh(self, mesh: Mesh) -> None:
        """Bind a mesh: resolves the node-axis split (flat or
        two-tier), rebuilds the stream kernel and the scatter-kernel
        cache.  The elastic reshard/recovery path re-binds meshes as
        shards leave and rejoin."""
        self._mesh = mesh
        axes, n_hosts = mesh_node_axes(mesh)
        self._axis = axes            # P element splitting the node dim
        self.n_hosts = n_hosts       # hosts PER REGION (three-tier)
        self.n_regions = mesh_region_count(mesh)
        self.n_shards = int(np.prod(
            [mesh.shape[a] for a in (axes if isinstance(axes, tuple)
                                     else (axes,))]))
        self.shards_per_region = self.n_shards // max(self.n_regions, 1)
        self.chips_per_host = self.shards_per_region // max(n_hosts, 1)
        self.two_tier = isinstance(axes, tuple)
        self.three_tier = self.two_tier and len(axes) == 3
        self._kern = _build_sharded_stream_kernel(mesh)
        self._scatter_kerns: Dict = {}

    # ---------------- sharded placement hooks ----------------
    def _put_node(self, name, arr):
        # leading node axis sharded, trailing axes replicated (covers
        # the 3-D ev_res eviction plane alongside the 1/2-D planes)
        spec = P(self._axis, *([None] * (np.ndim(arr) - 1)))
        # copy before placing — see ResidentSolver._put_node (host-side
        # in-place template updates must never alias device buffers)
        return jax.device_put(np.array(arr),
                              NamedSharding(self._mesh, spec))

    def _put_ask(self, name, arr):
        if name in _PLANE_ASK_ARGS:
            spec = P(*([None] * (np.ndim(arr) - 1)), self._axis)
        else:
            spec = P()
        return jax.device_put(arr, NamedSharding(self._mesh, spec))

    # ---------------- delta lifecycle ----------------
    # Incremental tensorize across the mesh: the inherited apply_delta
    # drives these hooks, which route each pow2-padded row bundle to
    # the shard OWNING its node slot under shard_map — every shard
    # scatters only its own rows (non-owned indices pin to the dropped
    # Np slot), so a delta wave moves only the scattered rows and the
    # arrays never leave their node-axis sharding.  (A plain jit
    # scatter on a sharded operand is NOT partition-safe: GSPMD may
    # replicate the update and apply it once per shard.)
    def _sharded_scatter(self, op: str, arr, idx, rows):
        """idx are DEVICE-LAYOUT rows (== global rows for the
        contiguous block layout; the elastic solver translates global
        rows through its tile tables before calling)."""
        key = (op, np.ndim(arr))
        fn = self._scatter_kerns.get(key)
        if fn is None:
            spec = P(self._axis, *([None] * (np.ndim(arr) - 1)))
            axes = self._axis
            cph = self.chips_per_host
            spr = self.shards_per_region

            def body(a_l, idx_, rows_, _op=op):
                Npl = a_l.shape[0]
                if isinstance(axes, tuple) and len(axes) == 3:
                    lin = (jax.lax.axis_index(axes[0]) * spr
                           + jax.lax.axis_index(axes[1]) * cph
                           + jax.lax.axis_index(axes[2]))
                elif isinstance(axes, tuple):
                    lin = (jax.lax.axis_index(axes[0]) * cph
                           + jax.lax.axis_index(axes[1]))
                else:
                    lin = jax.lax.axis_index(axes)
                off = lin * Npl
                loc = idx_.astype(jnp.int32) - off
                # negative locals WRAP before mode="drop" bounds-checks;
                # pin non-owned rows to the always-dropped Npl slot
                loc = jnp.where((loc >= 0) & (loc < Npl), loc, Npl)
                if _op == "set":
                    return a_l.at[loc].set(rows_, mode="drop")
                return a_l.at[loc].add(rows_, mode="drop")

            fn = jax.jit(shard_map(body, mesh=self._mesh,
                                   in_specs=(spec, P(), P()),
                                   out_specs=spec, check_rep=False))
            self._scatter_kerns[key] = fn
        return fn(arr, idx, rows)

    def _delta_set(self, arr, idx, rows):
        return self._sharded_scatter("set", arr, idx, rows)

    def _delta_add(self, arr, idx, rows):
        return self._sharded_scatter("add", arr, idx, rows)

    # ---------------- solving ----------------
    def solve_stream_async(self, batches: Sequence[PackedBatch],
                           seeds: Optional[Sequence[int]] = None):
        self._check_stream_jobs(batches)
        self._check_batch_axis(batches)
        stacked = self._stack_args(batches)
        n_places = np.asarray([pb.n_place for pb in batches], np.int32)
        seed_arr = (np.zeros(len(batches), np.int32) if seeds is None
                    else np.asarray(list(seeds), np.int32))
        has_distinct = self._has_distinct(batches)
        preempt = self._preempt_on(has_distinct)
        node_gid, owner_map, slot_map, tile_np, mesh_nt = \
            self._elastic_operands()
        (self._used, self._dev_used, out, self.last_evict,
         self.last_waves, self.last_rescore_waves) = self._kern(
            self._dev_node["avail"], self._dev_node["reserved"],
            self._dev_node["valid"], self._dev_node["node_dc"],
            self._dev_node["attr_rank"], self._dev_node["dev_cap"],
            self._used, self._dev_used, stacked, n_places, seed_arr,
            self._dev_node.get("ev_res"), self._dev_node.get("ev_prio"),
            node_gid, owner_map, slot_map,
            has_spread=self._has_spread(batches),
            group_count_hint=self._group_count_hint(batches),
            max_waves=self.max_waves, wave_mode=self.wave_mode,
            has_distinct=has_distinct,
            has_devices=self._has_devices(batches),
            stack_commit=self.stack_commit, compact=self._compact,
            pallas_mode=self.pallas, shortlist_c=self.shortlist_c,
            has_preempt=preempt, mesh_nt=mesh_nt, tile_np=tile_np)
        return out

    def _elastic_operands(self):
        """(node_gid, owner_map, slot_map, tile_np, mesh_nt) — the
        contiguous block layout needs none of them (tile_np 0 keeps
        the kernel on the axis-offset arithmetic)."""
        return None, None, None, 0, 0

    # ---------------- byte model ----------------
    def measured_wave_counters(self) -> Optional[Dict]:
        """Mesh units: rescore_waves counts per-SHARD full passes (the
        kernel psums its per-shard escape counter), so the shortlist
        remainder is taken against waves x shards."""
        m = super().measured_wave_counters()
        if m is not None:
            m["shard_waves_total"] = m["waves_total"] * self.n_shards
            m["shortlist_waves"] = max(
                m["shard_waves_total"] - m["rescore_waves"], 0)
        return m

    def wave_traffic(self, batches: Sequence[PackedBatch]) -> Dict:
        """Three-tier model: the inherited two HBM tiers plus the ICI
        tier.  HBM tiers are restated PER SHARD (each chip walks only
        its Np/devices slice of every plane); `measured` gains
        `modeled_bytes_ici_total` (per-wave ICI model x measured wave
        counters).  `rescore_waves` counts per-SHARD full passes (a
        mixed wave where 3 of 8 shards escape costs 3 shard-plane
        walks, not 8)."""
        from ..solver import pallas_kernel as _pk
        from ..solver.kernel import (TOP_K as _TOP_K, WAVE_K,
                                     _MERGED_W_CAP, _WIDE_W_CAP,
                                     MERGED_GP_MAX, resolve_shortlist_c)
        out = super().wave_traffic(batches)
        t = self.template
        Np, R = t.avail.shape
        Npl = self._np_local()
        Gp = max(pb.ask_res.shape[0] for pb in batches)
        K = max(pb.p_ask.shape[0] for pb in batches)
        A = t.attr_rank.shape[1]
        S = t.sp_desired.shape[1]
        V = t.sp_desired.shape[2]
        has_spread = self._has_spread(batches)
        hint = self._group_count_hint(batches)
        w_cap = (_MERGED_W_CAP if Gp <= MERGED_GP_MAX else _WIDE_W_CAP)
        TK = min(max(WAVE_K, min(2 * hint, w_cap)) + _TOP_K, Np)
        TKl = min(TK, Npl)
        C = (0 if self._has_distinct(batches)
             else resolve_shortlist_c(Npl, TKl, self.shortlist_c))
        mode = self.pallas
        if mode == "auto":
            mode = _pk.resolve_mode(Npl, Gp, TKl, V, has_spread)
        want_tables = has_spread and V <= 8 and not self.stack_commit
        TKv = -(-TK // (V + 1)) if want_tables else 0
        TW = min(TKv, Npl) if want_tables else 0
        out["ici"] = model_ici_bytes(Gp, K, A, R, TKl, self.n_shards,
                                     want_tables, V, TW, has_spread)
        out["bytes_ici_per_wave"] = out["ici"]["bytes_ici_per_wave"]
        n_reg = getattr(self, "n_regions", 1)
        if self.two_tier or self._elastic:
            # ISSUE 8: the DCN tier next to ICI — and the flat
            # exchange's cross-host exposure it is measured against.
            # Per REGION on a three-tier mesh (the WAN block below
            # restates the fleet-wide totals).
            out["dcn"] = model_ici_dcn_bytes(
                Gp, K, A, R, TK, TKl, self.n_shards // max(n_reg, 1),
                self.n_hosts if self.two_tier else 1,
                want_tables, V, TKv, TW, has_spread)
            out["bytes_dcn_per_wave"] = \
                out["dcn"]["bytes_dcn_total_per_wave"]
        if getattr(self, "three_tier", False) and n_reg > 1:
            # ISSUE 13: the WAN tier — and the flat exchange's
            # cross-region exposure it is measured against
            out["wan"] = model_ici_dcn_wan_bytes(
                Gp, K, A, R, TK, TKl, self.n_shards, n_reg,
                self.n_hosts, want_tables, V, TKv, TW, has_spread)
            out["bytes_wan_per_wave"] = \
                out["wan"]["bytes_wan_total_per_wave"]
        b1, brw, passes = model_wave_bytes(
            Npl, Gp, K, S, R, has_spread, mode, TKl, C)
        out["per_shard"] = {"np_local": int(Npl),
                            "bytes_wave1": int(b1),
                            "bytes_rewave": int(brw),
                            "shortlist_c": int(C),
                            "fused_pass_count": passes}
        m = out.get("measured")
        if m is not None:
            # rescore_waves counts PER-SHARD full passes in mesh mode
            shortlist_shard_waves = (m["waves_total"] * self.n_shards
                                     - m["rescore_waves"])
            m["modeled_bytes_total"] = int(
                b1 * m["rescore_waves"]
                + brw * max(shortlist_shard_waves, 0))
            m["modeled_bytes_ici_total"] = int(
                out["ici"]["bytes_ici_total_per_wave"]
                * m["waves_total"])
            if "dcn" in out:
                m["modeled_bytes_dcn_total"] = int(
                    out["dcn"]["bytes_dcn_total_per_wave"]
                    * m["waves_total"])
                m["modeled_bytes_dcn_flat_total"] = int(
                    out["dcn"]["flat_dcn_total_per_wave"]
                    * m["waves_total"])
            if "wan" in out:
                m["modeled_bytes_wan_total"] = int(
                    out["wan"]["bytes_wan_total_per_wave"]
                    * m["waves_total"])
                m["modeled_bytes_wan_flat_total"] = int(
                    out["wan"]["flat_wan_total_per_wave"]
                    * m["waves_total"])
        return out

    def _np_local(self) -> int:
        """Per-shard node-axis width (the elastic layout carries
        capacity slack beyond Np // n_shards)."""
        return self.template.avail.shape[0] // self.n_shards


# ===================================================================
# Elastic mesh (ISSUE 8): tile-granular reshard + shard-loss recovery
# ===================================================================

#: dead-slot fill per node plane (matching the tensorizer's padding)
_LAYOUT_FILLS = {"valid": False, "attr_rank": -1, "ev_prio": -1}


class ElasticShardedResidentSolver(ShardedResidentSolver):
    """ShardedResidentSolver whose node axis is owned in SHARD-TILES
    routed by an owner remap table (tensorize.TileLayout) instead of
    contiguous axis-index blocks.

    What that buys (ISSUE 8):

      * ``grow_tiles`` extends the global node axis by whole tiles and
        ships ONLY the new tiles' plane rows (measured, not modeled) —
        no world repack, no re-put of resident state;
      * ``move_tile`` rebalances one tile between shards, carrying its
        delta-carried usage: the moved tile's rows are the only bytes
        that travel;
      * ``fail_shard`` / ``recover`` is the shard-loss state machine:
        on loss the surviving shards keep solving at DEGRADED width
        (the lost tiles' nodes drop out of the solve; every surviving
        solve stays on the device fast path), while the lost planes
        are rebuilt from the host-side template — the raft-backed
        store's view of the world — and ``recover`` rejoins them,
        restoring usage to the last plan-fed state.

    Placements and explainability counters stay bit-identical to the
    host twin through ANY reshard/fail/rejoin interleaving: candidate
    keys carry stable GLOBAL node ids and the kernel's extraction and
    merge order them by (score desc, global id asc) regardless of
    where a tile physically lives (solve_kernel `tile_np`).
    """

    _elastic = True
    _fresh_layout = True

    def __init__(self, nodes, probe_asks, *args,
                 mesh: Optional[Mesh] = None,
                 n_devices: Optional[int] = None,
                 tile_np: Optional[int] = None,
                 slack_tiles: Optional[int] = None, **kw):
        import os
        self._tile_np_req = tile_np
        self._slack_tiles = (
            slack_tiles if slack_tiles is not None
            else int(os.environ.get("NOMAD_TPU_RESHARD_SLACK", "1")))
        #: reshard/recovery observability (bench + acceptance tests)
        self.reshard_counters = {
            "tiles_grown": 0, "tiles_moved": 0, "tiles_shrunk": 0,
            "tiles_reclaimed": 0,
            "last_reshard_bytes": 0, "reshard_bytes_total": 0,
            "recoveries": 0, "last_recovery_bytes": 0,
            "last_recovery_s": 0.0, "degraded_solves": 0,
        }
        #: mesh event log (ISSUE 10): every grow/shrink/move/fail/
        #: recover transition lands here with its measured bytes and
        #: duration — the /v1/agent/events surface.  The process-global
        #: log by default so one HTTP endpoint sees every mesh.
        from ..utils.tracing import global_mesh_events
        _log = kw.pop("event_log", None)
        # explicit None test: an EMPTY MeshEventLog is falsy (__len__)
        self.event_log = global_mesh_events if _log is None else _log
        super().__init__(nodes, probe_asks, *args, mesh=mesh,
                         n_devices=n_devices, **kw)

    # ---------------- layout lifecycle ----------------
    def _put_node_side(self) -> None:
        from ..solver.tensorize import TileLayout, pick_tile_np
        if self._fresh_layout:
            NT = self.template.avail.shape[0]
            tile = self._tile_np_req or pick_tile_np(NT, self.n_shards)
            if tile <= 0 or NT % tile:
                raise ValueError(
                    f"tile_np={tile} does not divide the padded node "
                    f"axis {NT}")
            self._layout = TileLayout(NT // tile, self.n_shards, tile,
                                      slack_tiles=self._slack_tiles)
            self.mesh_state = "healthy"
            self._lost_tiles: List[int] = []
            self._orig_mesh = self._mesh
        self._src_cache = self._layout.dev_src()
        super()._put_node_side()
        self._refresh_tables()

    @property
    def tile_np(self) -> int:
        return self._layout.tile_np

    def _np_local(self) -> int:
        return self._layout.npl

    def _elastic_operands(self):
        # mesh_nt caps the kernel's candidate-window width (TK).  Use
        # the FROM-SCRATCH pad of the real universe, not the tile-
        # grown template axis: a grow adds dead slack tiles, and a
        # window cap that tracked them would diverge from the host
        # twin / a fresh pack at the same node set (the dead slots can
        # never hold candidates, so the narrower cap is exact).
        from ..solver.tensorize import _pad_nodes
        return (self._dev_gid, self._dev_owner, self._dev_slot,
                self._layout.tile_np,
                _pad_nodes(max(self.template.n_real, 1)))

    def _refresh_tables(self, gid_rows=None) -> int:
        """(Re)place the device-side layout tables.  gid_rows
        incremental: (dev_rows, gids) scatters only the touched rows
        of the [n_slots] gid vector.  Returns bytes shipped."""
        om, sm = self._layout.tables()
        self._dev_owner = jax.device_put(
            om, NamedSharding(self._mesh, P()))
        self._dev_slot = jax.device_put(
            sm, NamedSharding(self._mesh, P()))
        shipped = int(om.nbytes + sm.nbytes)
        if gid_rows is not None and getattr(self, "_dev_gid",
                                            None) is not None:
            rows, gids = gid_rows
            self._dev_gid = self._sharded_scatter(
                "set", self._dev_gid, np.asarray(rows, np.int32),
                np.asarray(gids, np.int32))
            shipped += int(np.asarray(rows).nbytes
                           + np.asarray(gids).nbytes)
        else:
            gid = self._layout.node_gid(self.template.avail.shape[0])
            self._dev_gid = jax.device_put(
                gid, NamedSharding(self._mesh, P(self._axis)))
            shipped += int(gid.nbytes)
        return shipped

    # ---------------- layout-aware placement hooks ----------------
    def _to_layout(self, name, arr, axis):
        src = self._src_cache
        take = np.clip(src, 0, np.asarray(arr).shape[axis] - 1)
        fill = _LAYOUT_FILLS.get(name, 0)
        if axis == 0:
            out = np.ascontiguousarray(np.asarray(arr)[take])
            out[src < 0] = fill
        else:
            out = np.ascontiguousarray(np.asarray(arr)[..., take])
            out[..., src < 0] = fill
        return out

    def _put_node(self, name, arr):
        lay = self._to_layout(
            "used0" if name in ("used", "dev_used") else name, arr, 0)
        spec = P(self._axis, *([None] * (np.ndim(lay) - 1)))
        return jax.device_put(lay, NamedSharding(self._mesh, spec))

    def _put_ask(self, name, arr):
        if name in _PLANE_ASK_ARGS:
            lay = self._to_layout(name, arr, -1)
            spec = P(*([None] * (np.ndim(lay) - 1)), self._axis)
            return jax.device_put(lay,
                                  NamedSharding(self._mesh, spec))
        return jax.device_put(arr, NamedSharding(self._mesh, P()))

    def plane_checksum(self) -> int:
        """Layout-inverting override: the elastic planes live in
        tile-routed device order, so fetch and route rows back to
        template (global) order before hashing — healthy meshes cover
        every global row with exactly one live tile, making the result
        directly comparable to template_checksum (ISSUE 14)."""
        from ..solver.tensorize import plane_crc
        t = self.template
        dn = self._dev_node
        src = self._src_cache
        live = src >= 0
        Np = t.avail.shape[0]

        def back(arr):
            a = np.asarray(arr)
            out = np.zeros((Np,) + a.shape[1:], a.dtype)
            out[src[live]] = a[live]
            return out

        meta = f"{t.n_real}:{','.join(t.node_ids)}".encode()
        return plane_crc(
            back(dn["avail"]), back(dn["reserved"]),
            back(dn["valid"]), back(dn["node_dc"]),
            back(dn["attr_rank"]), back(dn["dev_cap"]),
            ev_prio=(back(dn["ev_prio"]) if "ev_prio" in dn
                     else None),
            ev_res=(back(dn["ev_res"]) if "ev_res" in dn else None),
            meta=meta)

    # delta scatters arrive with GLOBAL rows; route through the tile
    # tables to device-layout rows (the base scatter kernel's space).
    # Rows landing in a RETIRED tile (shrunk away, then handed to a
    # joining node by the host-side slot allocator) re-own that tile on
    # demand; rows in a LOST tile (shard down) drop device-side — the
    # template keeps the truth and recover() replays it.
    def _reclaim_tiles(self, idx) -> None:
        lay = self._layout
        tiles = np.unique(np.asarray(idx, np.int64) // lay.tile_np)
        lost = set(self._lost_tiles)
        for t in tiles:
            t = int(t)
            if (0 <= t < lay.n_tiles and lay.owner[t] < 0
                    and t not in lost):
                lay.assign(t, lay.least_loaded())
                self._src_cache = lay.dev_src()
                shipped = self._ship_tile(t)
                self._fresh_tiles.add(t)
                self.reshard_counters["tiles_reclaimed"] += 1
                self.reshard_counters["reshard_bytes_total"] += shipped

    def apply_delta(self, delta) -> str:
        # tiles reclaimed while THIS delta applies ship template rows
        # that already include the delta's host-applied usage; the
        # usage-add scatter below must not re-add it (see _delta_add)
        self._fresh_tiles: set = set()
        return super().apply_delta(delta)

    def _delta_set(self, arr, idx, rows):
        # only `set` scatters can reclaim: their rows are genuinely
        # touched node slots (add-side pow2 padding zero-fills idx,
        # and row 0's tile must not be resurrected by a pad artifact)
        self._reclaim_tiles(idx)
        return super()._delta_set(
            arr, self._layout.g2d(idx, unowned="drop").astype(np.int32),
            rows)

    def _delta_add(self, arr, idx, rows):
        fresh = getattr(self, "_fresh_tiles", None)
        if fresh:
            t = np.asarray(idx, np.int64) // self._layout.tile_np
            hit = np.isin(t, list(fresh))
            if hit.any():
                rows = np.where(
                    hit.reshape((-1,) + (1,) * (rows.ndim - 1)),
                    0, rows)
        return super()._delta_add(
            arr, self._layout.g2d(idx, unowned="drop").astype(np.int32),
            rows)

    def usage(self):
        """Carried usage in GLOBAL row order (dead/unowned rows 0)."""
        src = self._src_cache
        real = src >= 0
        u_dev = np.asarray(self._used)
        du_dev = np.asarray(self._dev_used)
        u = np.zeros((self.template.avail.shape[0], u_dev.shape[1]),
                     u_dev.dtype)
        du = np.zeros((self.template.avail.shape[0], du_dev.shape[1]),
                      du_dev.dtype)
        u[src[real]] = u_dev[real]
        du[src[real]] = du_dev[real]
        return u, du

    def _health_live_mask(self):
        """Device-row liveness for the health kernel (ISSUE 15):
        retired / lost tile rows keep STALE plane values (including
        valid=True) because layout fills apply only at put time, so
        the kernel must mask on tile residency, not the valid plane.
        Cached per layout epoch — `_src_cache` is replaced (never
        mutated) on every grow/shrink/move/fail/recover."""
        src = self._src_cache
        cache = self.__dict__.get("_health_live_dev")
        if cache is None or cache[0] is not src:
            dev = jax.device_put(
                np.ascontiguousarray(src >= 0),
                NamedSharding(self._mesh, P(self._axis)))
            self.__dict__["_health_live_dev"] = cache = (src, dev)
        return cache[1]

    def health_row_mask(self) -> np.ndarray:
        """GLOBAL-order row mask of device-resident rows — the host
        twin's view of what `_health_live_mask` keeps (lost tiles drop
        out of both)."""
        src = self._src_cache
        mask = np.zeros(self.template.avail.shape[0], bool)
        mask[src[src >= 0]] = True
        return mask

    def solve_stream_async(self, batches, seeds=None):
        if self.mesh_state == "degraded":
            self.reshard_counters["degraded_solves"] += 1
        return super().solve_stream_async(batches, seeds)

    def repack(self, delta=None) -> None:
        """A full repack rebuilds the whole world from the raft-fed
        template — on a degraded mesh that SUBSUMES recovery, so
        rejoin first: the lost tiles' planes and usage restore from
        the template before the repack re-reads device usage (going
        straight to repack would fold the lost tiles' zeroed device
        rows into the rebuilt used0, losing their plan-fed state)."""
        if getattr(self, "mesh_state", "healthy") == "degraded":
            self.recover()
        super().repack(delta)

    # ---------------- tile-granular reshard ----------------
    def _bump_layout_epoch(self) -> None:
        self._node_epoch += 1
        self._ev_epoch += 1
        self._row_cache.clear()
        self._drv_cache.clear()
        self._eval_cache.clear()
        self._const_cache.clear()

    def _ship_tile(self, t: int, usage=None) -> int:
        """Scatter one tile's plane rows (from the host template — the
        raft-fed source of truth) into its device location.  Returns
        the bytes shipped — THE grow/move measurement."""
        tile = self._layout.tile_np
        tmpl = self.template
        g_lo = t * tile
        rows = np.arange(g_lo, g_lo + tile)
        dev = self._layout.dev_rows(t).astype(np.int32)
        shipped = 0
        dn = self._dev_node
        plane_srcs = {
            "avail": tmpl.avail, "reserved": tmpl.reserved,
            "valid": tmpl.valid, "node_dc": tmpl.node_dc,
            "attr_rank": tmpl.attr_rank, "dev_cap": tmpl.dev_cap}
        if "ev_prio" in dn:
            plane_srcs["ev_prio"] = tmpl.ev_prio
            plane_srcs["ev_res"] = tmpl.ev_res
        for name, srca in plane_srcs.items():
            payload = np.ascontiguousarray(srca[rows])
            dn[name] = self._sharded_scatter("set", dn[name], dev,
                                             payload)
            shipped += payload.nbytes
        if usage is None:
            u_rows = np.ascontiguousarray(tmpl.used0[rows])
            du_rows = np.ascontiguousarray(tmpl.dev_used0[rows])
        else:
            u_rows, du_rows = usage
        self._used = self._sharded_scatter("set", self._used, dev,
                                           u_rows)
        self._dev_used = self._sharded_scatter("set", self._dev_used,
                                               dev, du_rows)
        shipped += int(u_rows.nbytes + du_rows.nbytes)
        shipped += self._refresh_tables(
            gid_rows=(dev, rows.astype(np.int32)))
        return shipped

    def grow_tiles(self, n: int = 1, shard: Optional[int] = None
                   ) -> List[int]:
        """Grow the global node axis by n whole shard-tiles: extends
        the host template with dead rows, assigns the tiles to the
        least-loaded shards (or `shard`), and ships ONLY those tiles'
        rows.  Joining nodes then fill the new slots through the
        normal delta path.  Raises if the per-shard capacity slack is
        exhausted — grow the slack (NOMAD_TPU_RESHARD_SLACK) or take
        a full repack."""
        import time as _t
        from ..solver.tensorize import extend_template_rows
        _t0 = _t.perf_counter()
        tile = self._layout.tile_np
        new = self._layout.grow(n)
        try:
            for t in new:
                self._layout.assign(
                    t, shard if shard is not None
                    else self._layout.least_loaded())
        except ValueError:
            raise ValueError(
                "no free tile slots left on any shard; increase "
                "slack_tiles/NOMAD_TPU_RESHARD_SLACK or repack")
        extend_template_rows(self.template, n * tile)
        NT = self.template.avail.shape[0]
        self._src_cache = self._layout.dev_src()
        self._compact = NT < 32768
        self._default_host_ok = np.zeros((self.gp, NT), bool)
        self._default_host_ok[:, :self.template.n_real] = True
        shipped = 0
        for t in new:
            shipped += self._ship_tile(t)
        self._bump_layout_epoch()
        self.reshard_counters["tiles_grown"] += n
        self.reshard_counters["last_reshard_bytes"] = shipped
        self.reshard_counters["reshard_bytes_total"] += shipped
        self.event_log.record(
            "grow", tiles=[int(t) for t in new], n_tiles=n,
            tile_np=tile, bytes=shipped,
            duration_s=round(_t.perf_counter() - _t0, 6),
            n_shards=self.n_shards)
        return new

    def move_tile(self, t: int, dst: int) -> int:
        """Rebalance one tile to shard `dst`, carrying its live usage.
        Only the tile's rows (planes + usage + gid marks) travel.
        Returns the measured bytes."""
        import time as _t
        _t0 = _t.perf_counter()
        lay = self._layout
        if lay.owner[t] < 0:
            raise ValueError(f"tile {t} is not owned")
        if lay.owner[t] == dst:
            return 0
        src_shard = int(lay.owner[t])
        tile = lay.tile_np
        old_rows = lay.dev_rows(t).astype(np.int32)
        # live usage rides along (small device gather)
        u_rows = np.ascontiguousarray(np.asarray(self._used)[old_rows])
        du_rows = np.ascontiguousarray(
            np.asarray(self._dev_used)[old_rows])
        # kill the old location: dead gids + valid False + zero usage
        NT = self.template.avail.shape[0]
        dead = (NT + old_rows).astype(np.int32)
        dn = self._dev_node
        dn["valid"] = self._sharded_scatter(
            "set", dn["valid"], old_rows, np.zeros(tile, bool))
        self._used = self._sharded_scatter(
            "set", self._used, old_rows, np.zeros_like(u_rows))
        self._dev_used = self._sharded_scatter(
            "set", self._dev_used, old_rows, np.zeros_like(du_rows))
        self._refresh_tables(gid_rows=(old_rows, dead))
        lay.release(t)
        lay.assign(t, dst)
        self._src_cache = lay.dev_src()
        shipped = self._ship_tile(t, usage=(u_rows, du_rows))
        self._bump_layout_epoch()
        self.reshard_counters["tiles_moved"] += 1
        self.reshard_counters["last_reshard_bytes"] = shipped
        self.reshard_counters["reshard_bytes_total"] += shipped
        self.event_log.record(
            "move", tile=int(t), src_shard=src_shard, dst_shard=int(dst),
            bytes=shipped,
            duration_s=round(_t.perf_counter() - _t0, 6))
        return shipped

    def shrink_tiles(self, n: int = 1) -> List[int]:
        """Shrink Np by whole shard-tiles: retire up to n EMPTY owned
        tiles (every template row invalid — the nodes were drained
        through the normal delta path first).  The retired tiles'
        device rows die (dead gids, zero usage) and their tile slots
        free up; only those rows' dead marks travel, never the world.
        A joining node later handed a retired tile's rows re-owns the
        tile on demand (see _reclaim_tiles).  Returns the retired tile
        ids ([] if nothing is empty)."""
        lay = self._layout
        tile = lay.tile_np
        v = self.template.valid
        u_dev = np.asarray(self._used)
        du_dev = np.asarray(self._dev_used)
        out: List[int] = []
        for t in range(lay.n_tiles):
            if len(out) >= n:
                break
            if lay.owner[t] < 0:
                continue
            if v[t * tile:(t + 1) * tile].any():
                continue                       # live nodes: not empty
            dr = lay.dev_rows(t)
            if u_dev[dr].any() or du_dev[dr].any():
                # a tombstone keeps its carried usage row so a revived
                # node resumes exactly; retiring it would zero that
                continue
            dev = lay.dev_rows(t).astype(np.int32)
            NT = self.template.avail.shape[0]
            dead = (NT + dev).astype(np.int32)
            dn = self._dev_node
            dn["valid"] = self._sharded_scatter(
                "set", dn["valid"], dev, np.zeros(tile, bool))
            self._used = self._sharded_scatter(
                "set", self._used, dev,
                np.zeros((tile,) + np.asarray(self._used).shape[1:],
                         np.asarray(self._used).dtype))
            self._dev_used = self._sharded_scatter(
                "set", self._dev_used, dev,
                np.zeros((tile,)
                         + np.asarray(self._dev_used).shape[1:],
                         np.asarray(self._dev_used).dtype))
            self._refresh_tables(gid_rows=(dev, dead))
            lay.release(t)
            out.append(t)
        if out:
            self._src_cache = lay.dev_src()
            self._bump_layout_epoch()
            self.reshard_counters["tiles_shrunk"] += len(out)
            self.event_log.record("shrink",
                                  tiles=[int(t) for t in out],
                                  n_tiles=len(out))
        return out

    # ---------------- shard-loss recovery ----------------
    def _shard_devices(self):
        return list(np.asarray(self._mesh.devices).reshape(-1))

    def _rebind(self, mesh: Mesh, layout, u, du) -> None:
        """Re-place resident state under a new mesh/layout with the
        given GLOBAL usage (the fail/recover transitions; surviving
        tiles' planes re-marshal device-side — simulation fetches
        through the host, a real fleet would move them over ICI)."""
        self._layout = layout
        self._set_mesh(mesh)
        self._fresh_layout = False
        try:
            self._put_node_side()
        finally:
            self._fresh_layout = True
        self._used = self._put_node("used", u)
        self._dev_used = self._put_node("dev_used", du)
        self._bump_layout_epoch()

    def fail_shard(self, shard: int) -> List[int]:
        """Declare one shard (device) lost.  Its tiles become unowned
        — their nodes drop out of every solve — while the surviving
        shards re-bind to a flat mesh over the remaining devices and
        KEEP SOLVING with their carried usage (degraded width, still
        the device fast path).  Returns the lost tile ids."""
        if self.mesh_state != "healthy":
            raise ValueError(f"mesh is {self.mesh_state}; recover "
                             "before failing another shard")
        if not 0 <= shard < self.n_shards:
            raise ValueError(f"no shard {shard}")
        if self.n_shards < 2:
            raise ValueError("cannot lose the only shard")
        u, du = self.usage()
        lost = self._layout.tiles_of(shard)
        tile = self._layout.tile_np
        for t in lost:
            u[t * tile:(t + 1) * tile] = 0      # HBM state is GONE
            du[t * tile:(t + 1) * tile] = 0
        devices = self._shard_devices()
        self._failed_device = devices[shard]
        self._failed_shard = shard
        survivors = [d for i, d in enumerate(devices) if i != shard]
        remap = {}
        j = 0
        for i in range(self.n_shards):
            if i != shard:
                remap[i] = j
                j += 1
        self._recover_remap = {v: k for k, v in remap.items()}
        new_layout = self._layout.remap_shards(remap, len(survivors))
        self._lost_tiles = lost
        self._rebind(Mesh(np.array(survivors), (MESH_NODE_AXIS,)),
                     new_layout, u, du)
        self.mesh_state = "degraded"
        self.event_log.record(
            "fail", shard=int(shard),
            tiles=[int(t) for t in lost],
            surviving_shards=len(survivors))
        return lost

    def recover(self) -> int:
        """Rebuild the lost shard's planes from the host template (the
        raft-backed store's view) and rejoin it: lost tiles return to
        the restored shard with usage as of the last plan-fed state;
        surviving tiles keep their live carried usage untouched.
        Returns the measured recovery bytes (the lost tiles' rows)."""
        import time
        if self.mesh_state != "degraded":
            raise ValueError("mesh is not degraded")
        t0 = time.perf_counter()
        u, du = self.usage()                    # survivors' live state
        tmpl = self.template
        tile = self._layout.tile_np
        recovered_bytes = 0
        for t in self._lost_tiles:
            rows = slice(t * tile, (t + 1) * tile)
            u[rows] = tmpl.used0[rows]
            du[rows] = tmpl.dev_used0[rows]
            recovered_bytes += int(
                tmpl.avail[rows].nbytes + tmpl.reserved[rows].nbytes
                + tmpl.valid[rows].nbytes + tmpl.node_dc[rows].nbytes
                + tmpl.attr_rank[rows].nbytes
                + tmpl.dev_cap[rows].nbytes + tmpl.used0[rows].nbytes
                + tmpl.dev_used0[rows].nbytes)
        mesh = self._orig_mesh
        axes, n_hosts = mesh_node_axes(mesh)
        S = int(np.prod([mesh.shape[a] for a in
                         (axes if isinstance(axes, tuple)
                          else (axes,))]))
        layout = self._layout.remap_shards(self._recover_remap, S)
        for t in self._lost_tiles:
            layout.assign(t, self._failed_shard)
        self._lost_tiles = []
        self._rebind(mesh, layout, u, du)
        self.mesh_state = "healthy"
        self.reshard_counters["recoveries"] += 1
        self.reshard_counters["last_recovery_bytes"] = recovered_bytes
        self.reshard_counters["last_recovery_s"] = (
            time.perf_counter() - t0)
        self.event_log.record(
            "recover", shard=int(self._failed_shard),
            bytes=recovered_bytes,
            duration_s=round(self.reshard_counters["last_recovery_s"],
                             6),
            n_shards=self.n_shards)
        return recovered_bytes


class ElasticMeshSupervisor:
    """The recovery trigger: maps fleet membership / node events onto
    the elastic solver's fail/recover state machine.

    Two event planes feed it (ISSUE 8):

      * serf-plane — plug ``on_fail`` / ``on_join`` straight into
        ``membership.gossip.GossipAgent(on_fail=..., on_join=...)``;
        a registered mesh host transitioning to dead fails its shard
        (survivors keep solving at degraded width), and its rejoin
        triggers the rebuild-and-rejoin recovery;
      * scheduler-plane — ``note_node_event`` from the worker's
        node-update eval path (EVAL_TRIGGER_NODE_UPDATE), for fleets
        whose mesh hosts are registered workload nodes rather than
        gossip members.

    Callbacks fire on gossip/worker threads while the solver is
    driven elsewhere, so transitions serialize under one lock; the
    solver's own solve calls are NOT held by it — fail/recover
    re-bind between solves, exactly like the direct API."""

    def __init__(self, solver: "ElasticShardedResidentSolver"):
        import threading
        self.solver = solver
        self._hosts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.events: List[Tuple[str, str]] = []

    def register_host(self, member_id: str, shard: int) -> None:
        """Declare that `member_id` (a gossip member or node id) hosts
        mesh shard `shard`."""
        with self._lock:
            self._hosts[member_id] = int(shard)

    def _member_id(self, member) -> str:
        return getattr(member, "id", member)

    def on_fail(self, member) -> None:
        mid = self._member_id(member)
        with self._lock:
            shard = self._hosts.get(mid)
            if shard is None or self.solver.mesh_state != "healthy":
                return
            self.solver.fail_shard(shard)
            self.events.append(("fail", mid))
            self.solver.event_log.record("supervisor.fail",
                                         member=mid, shard=int(shard))

    def on_join(self, member) -> None:
        mid = self._member_id(member)
        with self._lock:
            if mid not in self._hosts \
                    or self.solver.mesh_state != "degraded":
                return
            self.solver.recover()
            self.events.append(("recover", mid))
            self.solver.event_log.record("supervisor.recover",
                                         member=mid)

    def note_node_event(self, node_id: str, status: str) -> None:
        """Scheduler-plane trigger: a node-update eval observed
        `node_id` at `status` (structs NODE_STATUS_*)."""
        from ..structs.consts import NODE_STATUS_DOWN
        if status == NODE_STATUS_DOWN:
            self.on_fail(node_id)
        else:
            self.on_join(node_id)
