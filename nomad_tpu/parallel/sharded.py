"""Multi-chip solve: shard the node axis over a TPU mesh.

The scaling-book recipe (SURVEY §2.6): pick a mesh, annotate input
shardings, and let XLA/GSPMD insert the collectives. The node axis is our
"long sequence" (SURVEY §5.7) — feasibility masking and scoring partition
cleanly along it; the per-step masked top-k and the winner-commit scatter
become cross-shard collectives (reduce over ICI) that XLA derives from
the shardings, replacing hand-written NCCL/MPI in the reference's world.

Two levels:
  * `sharded_solve_args`  — one region's solve, node axis sharded.
  * `federated_solve_args` — BASELINE config 5: a leading region axis
    (independent solves, the federation analog of nomad/serf.go regions)
    vmapped and sharded over the mesh's "region" axis; node axis sharded
    within each region's device row.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..solver.kernel import solve_kernel
from ..solver.tensorize import PackedBatch

# PartitionSpec per solve_kernel positional arg (node axis = "nodes").
_ARG_SPECS: List[P] = [
    P("nodes", None),        # avail [Np, R]
    P("nodes", None),        # reserved
    P("nodes", None),        # used0
    P("nodes"),              # valid [Np]
    P("nodes"),              # node_dc [Np]
    P("nodes", None),        # attr_rank [Np, A]
    P(),                     # ask_res [Gp, R]
    P(),                     # ask_desired [Gp]
    P(),                     # distinct [Gp]
    P(),                     # dc_ok [Gp, NDC]
    P(None, "nodes"),        # host_ok [Gp, Np]
    P(None, "nodes"),        # coll0 [Gp, Np]
    P(None, "nodes"),        # penalty [Gp, Np]
    P(), P(), P(),           # c_op / c_col / c_rank [Gp, C]
    P(), P(), P(), P(),      # a_op / a_col / a_rank / a_weight [Gp, CA]
    P(None, "nodes"),        # a_host [Gp, Np]
    P(), P(), P(),           # sp_col / sp_weight / sp_targeted [Gp, S]
    P(), P(), P(),           # sp_desired / sp_implicit / sp_used0
    P("nodes", None),        # dev_cap [Np, D]
    P("nodes", None),        # dev_used0 [Np, D]
    P(),                     # dev_ask [Gp, D]
    P(),                     # p_ask [K]
    P(),                     # n_place (scalar)
]


def kernel_args(pb: PackedBatch) -> Tuple:
    """PackedBatch -> solve_kernel positional args."""
    return (pb.avail, pb.reserved, pb.used0, pb.valid, pb.node_dc,
            pb.attr_rank, pb.ask_res, pb.ask_desired, pb.distinct, pb.dc_ok,
            pb.host_ok, pb.coll0, pb.penalty, pb.c_op, pb.c_col, pb.c_rank,
            pb.a_op, pb.a_col, pb.a_rank, pb.a_weight, pb.a_host, pb.sp_col,
            pb.sp_weight, pb.sp_targeted, pb.sp_desired, pb.sp_implicit,
            pb.sp_used0, pb.dev_cap, pb.dev_used0, pb.dev_ask, pb.p_ask,
            np.int32(pb.n_place))


def make_mesh(n_devices: Optional[int] = None,
              n_regions: int = 1) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    assert n % n_regions == 0, "devices must divide evenly into regions"
    grid = np.array(devices).reshape(n_regions, n // n_regions)
    return Mesh(grid, ("region", "nodes"))


def _shard_args(args: Tuple, mesh: Mesh, region_axis: bool) -> Tuple:
    out = []
    for arg, spec in zip(args, _ARG_SPECS):
        if region_axis:
            spec = P("region", *spec)
        out.append(jax.device_put(arg, NamedSharding(mesh, spec)))
    return tuple(out)


def sharded_solve_args(args: Tuple, mesh: Mesh):
    """Run one solve with the node axis sharded over mesh axis "nodes".
    XLA partitions the kernel and inserts the cross-shard reductions for
    the masked top-k and commit scatter."""
    return solve_kernel(*_shard_args(args, mesh, region_axis=False))


def sharded_solve(pb: PackedBatch, mesh: Mesh):
    return sharded_solve_args(kernel_args(pb), mesh)


# vmap over a leading region axis: each region is an independent solve
# (regions don't share nodes), mapping onto disjoint device rows.
# wave_mode="while": under vmap the scan shape's cond-skip lowers to
# select and pays the full wave budget per lane (see kernel.py loop-
# shape note); the while_loop runs only as deep as the slowest region.
_federated_kernel = jax.jit(jax.vmap(
    # shortlist off: under vmap its cond degrades to select and both
    # branches would execute every wave for every lane
    functools.partial(solve_kernel, wave_mode="while", shortlist_c=-1)))


def federated_solve(pbs: Sequence[PackedBatch], mesh: Mesh):
    """Solve R regions at once: inputs stacked on a leading region axis,
    sharded over the mesh "region" axis (all batches must share shapes —
    use one Tensorizer per region with identical padding)."""
    per_region = [kernel_args(pb) for pb in pbs]
    shapes = {tuple(np.shape(a) for a in args) for args in per_region}
    assert len(shapes) == 1, "region batches must be shape-aligned"
    stacked = tuple(np.stack([args[i] for args in per_region])
                    for i in range(len(per_region[0])))
    return _federated_kernel(*_shard_args(stacked, mesh, region_axis=True))
