"""HCL jobspec parsing (reference: jobspec/ — Parse at parse.go:27)."""
from .hcl import Body, HCLParseError, parse_hcl
from .parse import (JobspecParseError, parse_duration_s, parse_file,
                    parse_job)

__all__ = ["parse_job", "parse_file", "parse_hcl", "parse_duration_s",
           "JobspecParseError", "HCLParseError", "Body"]
