"""HCL jobspec -> structs.Job (reference: jobspec/parse.go:27 Parse,
parse_job.go, parse_group.go, parse_task.go — HCL1 with strict key
validation per block).

Durations accept Go syntax ("30s", "5m", "1h30m"); the mapped fields are
the *_s float fields of the structs.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ..structs import (Affinity, Artifact, Constraint, DispatchPayloadConfig,
                       EphemeralDisk, Job, LogConfig, MigrateStrategy,
                       NetworkResource, ParameterizedJobConfig,
                       PeriodicConfig, Port, RequestedDevice,
                       ReschedulePolicy, Resources, RestartPolicy, Service,
                       ServiceCheck, Spread, SpreadTarget, Task, TaskGroup,
                       Template, UpdateStrategy, VolumeMount, VolumeRequest)
from .hcl import Body, HCLParseError, parse_hcl


class JobspecParseError(ValueError):
    pass


_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h|d)")
_DUR_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
              "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration_s(v: Any) -> float:
    """Go-style duration string -> seconds ("1h30m", "15s", "500ms")."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if not s:
        return 0.0
    total, pos = 0.0, 0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise JobspecParseError(f"bad duration {v!r}")
        total += float(m.group(1)) * _DUR_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise JobspecParseError(f"bad duration {v!r}")
    return total


def _check_keys(body: Body, allowed, where: str) -> None:
    """Strict key validation (reference: helper checkHCLKeys)."""
    extra = body.keys() - set(allowed)
    if extra:
        raise JobspecParseError(
            f"invalid key(s) in {where}: {', '.join(sorted(extra))}")


def _str_map(v: Any, where: str) -> Dict[str, str]:
    if v is None:
        return {}
    if not isinstance(v, dict):
        raise JobspecParseError(f"{where} must be a map")
    return {str(k): str(val) for k, val in v.items()}


# ---------------------------------------------------------------- shared
def _parse_constraints(body: Body) -> List[Constraint]:
    out = []
    for labels, b in body.blocks_named("constraint"):
        _check_keys(b, {"attribute", "operator", "value", "distinct_hosts",
                        "distinct_property", "regexp", "version", "semver",
                        "set_contains"}, "constraint")
        operand = str(b.attrs.get("operator", "="))
        lt = str(b.attrs.get("attribute", ""))
        rt = str(b.attrs.get("value", ""))
        for sugar in ("regexp", "version", "semver", "set_contains"):
            if sugar in b.attrs:
                operand, rt = sugar, str(b.attrs[sugar])
        if b.attrs.get("distinct_hosts"):
            operand = "distinct_hosts"
        if "distinct_property" in b.attrs:
            operand = "distinct_property"
            lt = str(b.attrs["distinct_property"])
        out.append(Constraint(ltarget=lt, rtarget=rt, operand=operand))
    return out


def _parse_affinities(body: Body) -> List[Affinity]:
    out = []
    for labels, b in body.blocks_named("affinity"):
        _check_keys(b, {"attribute", "operator", "value", "weight",
                        "regexp", "version", "semver", "set_contains",
                        "set_contains_any"}, "affinity")
        operand = str(b.attrs.get("operator", "="))
        rt = str(b.attrs.get("value", ""))
        for sugar in ("regexp", "version", "semver", "set_contains",
                      "set_contains_any"):
            if sugar in b.attrs:
                operand, rt = sugar, str(b.attrs[sugar])
        out.append(Affinity(ltarget=str(b.attrs.get("attribute", "")),
                            rtarget=rt, operand=operand,
                            weight=float(b.attrs.get("weight", 50))))
    return out


def _parse_spreads(body: Body) -> List[Spread]:
    out = []
    for labels, b in body.blocks_named("spread"):
        _check_keys(b, {"attribute", "weight", "target"}, "spread")
        targets = []
        for tlabels, tb in b.blocks_named("target"):
            _check_keys(tb, {"value", "percent"}, "spread target")
            targets.append(SpreadTarget(
                value=str(tb.attrs.get("value", tlabels[0] if tlabels
                                       else "")),
                percent=int(tb.attrs.get("percent", 0))))
        out.append(Spread(attribute=str(b.attrs.get("attribute", "")),
                          weight=float(b.attrs.get("weight", 50)),
                          spread_targets=targets))
    return out


def _parse_network(b: Body) -> NetworkResource:
    _check_keys(b, {"mbits", "port", "mode"}, "network")
    net = NetworkResource(mbits=int(b.attrs.get("mbits", 0)),
                          mode=str(b.attrs.get("mode", "host")))
    for labels, pb in b.blocks_named("port"):
        _check_keys(pb, {"static", "to", "host_network"}, "port")
        label = labels[0] if labels else ""
        port = Port(label=label, value=int(pb.attrs.get("static", 0)),
                    to=int(pb.attrs.get("to", 0)),
                    host_network=str(pb.attrs.get("host_network", "")))
        (net.reserved_ports if port.value else net.dynamic_ports).append(port)
    return net


def _parse_resources(b: Body) -> Resources:
    _check_keys(b, {"cpu", "memory", "disk", "iops", "network", "device"},
                "resources")
    res = Resources(cpu=int(b.attrs.get("cpu", 100)),
                    memory_mb=int(b.attrs.get("memory", 300)),
                    disk_mb=int(b.attrs.get("disk", 0)))
    for labels, nb in b.blocks_named("network"):
        res.networks.append(_parse_network(nb))
    for labels, db in b.blocks_named("device"):
        _check_keys(db, {"count", "constraint", "affinity"}, "device")
        res.devices.append(RequestedDevice(
            name=labels[0] if labels else "",
            count=int(db.attrs.get("count", 1)),
            constraints=_parse_constraints(db),
            affinities=_parse_affinities(db)))
    return res


def _parse_update(b: Body) -> UpdateStrategy:
    _check_keys(b, {"stagger", "max_parallel", "health_check",
                    "min_healthy_time", "healthy_deadline",
                    "progress_deadline", "auto_revert", "auto_promote",
                    "canary"}, "update")
    u = UpdateStrategy()
    if "stagger" in b.attrs:
        u.stagger_s = parse_duration_s(b.attrs["stagger"])
    u.max_parallel = int(b.attrs.get("max_parallel", u.max_parallel))
    u.health_check = str(b.attrs.get("health_check", u.health_check))
    if "min_healthy_time" in b.attrs:
        u.min_healthy_time_s = parse_duration_s(b.attrs["min_healthy_time"])
    if "healthy_deadline" in b.attrs:
        u.healthy_deadline_s = parse_duration_s(b.attrs["healthy_deadline"])
    if "progress_deadline" in b.attrs:
        u.progress_deadline_s = parse_duration_s(
            b.attrs["progress_deadline"])
    u.auto_revert = bool(b.attrs.get("auto_revert", False))
    u.auto_promote = bool(b.attrs.get("auto_promote", False))
    u.canary = int(b.attrs.get("canary", 0))
    return u


def _parse_service(b: Body) -> Service:
    _check_keys(b, {"name", "port", "tags", "canary_tags", "address_mode",
                    "check"}, "service")
    svc = Service(name=str(b.attrs.get("name", "")),
                  port_label=str(b.attrs.get("port", "")),
                  tags=[str(t) for t in b.attrs.get("tags", [])],
                  canary_tags=[str(t) for t in
                               b.attrs.get("canary_tags", [])],
                  address_mode=str(b.attrs.get("address_mode", "auto")))
    for labels, cb in b.blocks_named("check"):
        _check_keys(cb, {"name", "type", "path", "command", "args",
                         "interval", "timeout", "port"}, "check")
        svc.checks.append(ServiceCheck(
            name=str(cb.attrs.get("name", "")),
            type=str(cb.attrs.get("type", "")),
            path=str(cb.attrs.get("path", "")),
            command=str(cb.attrs.get("command", "")),
            args=[str(a) for a in cb.attrs.get("args", [])],
            interval_s=parse_duration_s(cb.attrs.get("interval", "10s")),
            timeout_s=parse_duration_s(cb.attrs.get("timeout", "2s")),
            port_label=str(cb.attrs.get("port", ""))))
    return svc


# ------------------------------------------------------------------ task
def _parse_task(name: str, b: Body) -> Task:
    _check_keys(b, {"driver", "user", "config", "env", "service",
                    "resources", "constraint", "affinity", "meta",
                    "kill_timeout", "kill_signal", "leader",
                    "shutdown_delay", "volume_mount", "template",
                    "artifact", "dispatch_payload", "logs", "lifecycle"},
                f"task {name!r}")
    task = Task(name=name, driver=str(b.attrs.get("driver", "")),
                user=str(b.attrs.get("user", "")),
                leader=bool(b.attrs.get("leader", False)),
                kill_signal=str(b.attrs.get("kill_signal", "")))
    if "kill_timeout" in b.attrs:
        task.kill_timeout_s = parse_duration_s(b.attrs["kill_timeout"])
    if "shutdown_delay" in b.attrs:
        task.shutdown_delay_s = parse_duration_s(b.attrs["shutdown_delay"])
    cfg = b.one_block("config")
    if cfg is not None:
        task.config = dict(cfg.attrs)
        for cname, _, cb in cfg.blocks:
            task.config.setdefault(cname, dict(cb.attrs))
    env = b.one_block("env")
    if env is not None:
        task.env = _str_map(env.attrs, "env")
    elif "env" in b.attrs:
        task.env = _str_map(b.attrs["env"], "env")
    meta = b.one_block("meta")
    if meta is not None:
        task.meta = _str_map(meta.attrs, "meta")
    res = b.one_block("resources")
    if res is not None:
        task.resources = _parse_resources(res)
    task.constraints = _parse_constraints(b)
    task.affinities = _parse_affinities(b)
    for _, sb in b.blocks_named("service"):
        task.services.append(_parse_service(sb))
    for _, vb in b.blocks_named("volume_mount"):
        _check_keys(vb, {"volume", "destination", "read_only"},
                    "volume_mount")
        task.volume_mounts.append(VolumeMount(
            volume=str(vb.attrs.get("volume", "")),
            destination=str(vb.attrs.get("destination", "")),
            read_only=bool(vb.attrs.get("read_only", False))))
    for _, tb in b.blocks_named("template"):
        _check_keys(tb, {"source", "destination", "data", "change_mode",
                         "change_signal"}, "template")
        task.templates.append(Template(
            source_path=str(tb.attrs.get("source", "")),
            dest_path=str(tb.attrs.get("destination", "")),
            embedded_tmpl=str(tb.attrs.get("data", "")),
            change_mode=str(tb.attrs.get("change_mode", "restart")),
            change_signal=str(tb.attrs.get("change_signal", ""))))
    for _, ab in b.blocks_named("artifact"):
        _check_keys(ab, {"source", "destination", "options"}, "artifact")
        opts = ab.one_block("options")
        task.artifacts.append(Artifact(
            getter_source=str(ab.attrs.get("source", "")),
            relative_dest=str(ab.attrs.get("destination", "")),
            getter_options=_str_map(opts.attrs if opts else
                                    ab.attrs.get("options"), "options")))
    dp = b.one_block("dispatch_payload")
    if dp is not None:
        _check_keys(dp, {"file"}, "dispatch_payload")
        task.dispatch_payload = DispatchPayloadConfig(
            file=str(dp.attrs.get("file", "")))
    logs = b.one_block("logs")
    if logs is not None:
        _check_keys(logs, {"max_files", "max_file_size"}, "logs")
        task.log_config = LogConfig(
            max_files=int(logs.attrs.get("max_files", 10)),
            max_file_size_mb=int(logs.attrs.get("max_file_size", 10)))
    return task


# ----------------------------------------------------------------- group
def _parse_group(name: str, b: Body) -> TaskGroup:
    _check_keys(b, {"count", "constraint", "affinity", "spread", "task",
                    "restart", "reschedule", "ephemeral_disk", "update",
                    "migrate", "network", "meta", "volume",
                    "stop_after_client_disconnect"}, f"group {name!r}")
    tg = TaskGroup(name=name, count=int(b.attrs.get("count", 1)))
    tg.constraints = _parse_constraints(b)
    tg.affinities = _parse_affinities(b)
    tg.spreads = _parse_spreads(b)
    meta = b.one_block("meta")
    if meta is not None:
        tg.meta = _str_map(meta.attrs, "meta")
    restart = b.one_block("restart")
    if restart is not None:
        _check_keys(restart, {"attempts", "interval", "delay", "mode"},
                    "restart")
        tg.restart_policy = RestartPolicy(
            attempts=int(restart.attrs.get("attempts", 2)),
            interval_s=parse_duration_s(
                restart.attrs.get("interval", "30m")),
            delay_s=parse_duration_s(restart.attrs.get("delay", "15s")),
            mode=str(restart.attrs.get("mode", "fail")))
    resched = b.one_block("reschedule")
    if resched is not None:
        _check_keys(resched, {"attempts", "interval", "delay",
                              "delay_function", "max_delay", "unlimited"},
                    "reschedule")
        tg.reschedule_policy = ReschedulePolicy(
            attempts=int(resched.attrs.get("attempts", 0)),
            interval_s=parse_duration_s(resched.attrs.get("interval", 0)),
            delay_s=parse_duration_s(resched.attrs.get("delay", "30s")),
            delay_function=str(resched.attrs.get("delay_function",
                                                 "exponential")),
            max_delay_s=parse_duration_s(resched.attrs.get("max_delay",
                                                           "1h")),
            unlimited=bool(resched.attrs.get("unlimited", False)))
    disk = b.one_block("ephemeral_disk")
    if disk is not None:
        _check_keys(disk, {"sticky", "size", "migrate"}, "ephemeral_disk")
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(disk.attrs.get("sticky", False)),
            size_mb=int(disk.attrs.get("size", 300)),
            migrate=bool(disk.attrs.get("migrate", False)))
    upd = b.one_block("update")
    if upd is not None:
        tg.update = _parse_update(upd)
    mig = b.one_block("migrate")
    if mig is not None:
        _check_keys(mig, {"max_parallel", "health_check",
                          "min_healthy_time", "healthy_deadline"},
                    "migrate")
        tg.migrate = MigrateStrategy(
            max_parallel=int(mig.attrs.get("max_parallel", 1)),
            health_check=str(mig.attrs.get("health_check", "checks")),
            min_healthy_time_s=parse_duration_s(
                mig.attrs.get("min_healthy_time", "10s")),
            healthy_deadline_s=parse_duration_s(
                mig.attrs.get("healthy_deadline", "5m")))
    for labels, nb in b.blocks_named("network"):
        tg.networks.append(_parse_network(nb))
    for labels, vb in b.blocks_named("volume"):
        _check_keys(vb, {"type", "source", "read_only"}, "volume")
        vname = labels[0] if labels else ""
        tg.volumes[vname] = VolumeRequest(
            name=vname, type=str(vb.attrs.get("type", "host")),
            source=str(vb.attrs.get("source", "")),
            read_only=bool(vb.attrs.get("read_only", False)))
    if "stop_after_client_disconnect" in b.attrs:
        tg.stop_after_client_disconnect_s = parse_duration_s(
            b.attrs["stop_after_client_disconnect"])
    for labels, taskb in b.blocks_named("task"):
        if not labels:
            raise JobspecParseError(f"task in group {name!r} needs a name")
        tg.tasks.append(_parse_task(labels[0], taskb))
    return tg


# ------------------------------------------------------------------- job
def parse_job(text: str) -> Job:
    """Parse an HCL jobspec into a structs.Job
    (reference: jobspec.Parse, jobspec/parse.go:27)."""
    try:
        root = parse_hcl(text)
    except HCLParseError as e:
        raise JobspecParseError(str(e))
    jobs = root.blocks_named("job")
    if len(jobs) != 1:
        raise JobspecParseError("jobspec must contain exactly one "
                                f"'job' block, found {len(jobs)}")
    labels, b = jobs[0]
    if not labels:
        raise JobspecParseError("'job' block requires a name label")
    _check_keys(b, {"id", "name", "region", "namespace", "all_at_once",
                    "priority", "datacenters", "type", "constraint",
                    "affinity", "spread", "group", "task", "update",
                    "periodic", "parameterized", "meta", "vault_token"},
                "job")
    job = Job(id=str(b.attrs.get("id", labels[0])),
              name=str(b.attrs.get("name", labels[0])))
    job.region = str(b.attrs.get("region", "global"))
    job.namespace = str(b.attrs.get("namespace", "default"))
    job.type = str(b.attrs.get("type", "service"))
    job.priority = int(b.attrs.get("priority", 50))
    job.all_at_once = bool(b.attrs.get("all_at_once", False))
    job.datacenters = [str(d) for d in b.attrs.get("datacenters", ["dc1"])]
    job.vault_token = str(b.attrs.get("vault_token", ""))
    job.constraints = _parse_constraints(b)
    job.affinities = _parse_affinities(b)
    job.spreads = _parse_spreads(b)
    meta = b.one_block("meta")
    if meta is not None:
        job.meta = _str_map(meta.attrs, "meta")
    upd = b.one_block("update")
    if upd is not None:
        job.update = _parse_update(upd)
    per = b.one_block("periodic")
    if per is not None:
        _check_keys(per, {"cron", "prohibit_overlap", "time_zone",
                          "enabled"}, "periodic")
        job.periodic = PeriodicConfig(
            enabled=bool(per.attrs.get("enabled", True)),
            spec=str(per.attrs.get("cron", "")),
            prohibit_overlap=bool(per.attrs.get("prohibit_overlap", False)),
            timezone=str(per.attrs.get("time_zone", "UTC")))
    par = b.one_block("parameterized")
    if par is not None:
        _check_keys(par, {"payload", "meta_required", "meta_optional"},
                    "parameterized")
        job.parameterized = ParameterizedJobConfig(
            payload=str(par.attrs.get("payload", "optional")),
            meta_required=[str(m) for m in
                           par.attrs.get("meta_required", [])],
            meta_optional=[str(m) for m in
                           par.attrs.get("meta_optional", [])])
    for glabels, gb in b.blocks_named("group"):
        if not glabels:
            raise JobspecParseError("'group' block requires a name label")
        job.task_groups.append(_parse_group(glabels[0], gb))
    # a bare task at job level becomes a single-task group of the same
    # name (reference: jobspec/parse.go job-level task sugar)
    for tlabels, tb in b.blocks_named("task"):
        if not tlabels:
            raise JobspecParseError("'task' block requires a name label")
        task = _parse_task(tlabels[0], tb)
        job.task_groups.append(TaskGroup(name=task.name, count=1,
                                         tasks=[task]))
    job.canonicalize()
    errs = job.validate()
    if errs:
        raise JobspecParseError("; ".join(errs))
    return job


def parse_file(path: str) -> Job:
    with open(path) as f:
        return parse_job(f.read())
