"""Minimal HCL1 parser (reference: jobspec/parse.go consumes
hashicorp/hcl). Covers the subset Nomad jobspecs use:

  attribute   key = value
  block       name "label" ... { body }
  values      string, number, bool, list, object, heredoc (<<EOF, <<-EOF)
  comments    #, //, /* */

The parse result is a Body tree: attrs {key: value} plus an ordered list
of (name, labels, Body) blocks. ${...} interpolations inside strings are
preserved verbatim (they are resolved later, at task-env build time).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class HCLParseError(ValueError):
    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


# ------------------------------------------------------------------ lexer
_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t\r]+)
  | (?P<nl>\n)
  | (?P<comment>\#[^\n]*|//[^\n]*)
  | (?P<mcomment>/\*.*?\*/)
  | (?P<heredoc><<-?(?P<hdtag>[A-Za-z_][A-Za-z0-9_]*)\n)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.-]*)
  | (?P<punct>[{}\[\],=])
""", re.VERBOSE | re.DOTALL)


@dataclass
class _Tok:
    kind: str
    value: Any
    line: int


def _lex(text: str) -> List[_Tok]:
    toks: List[_Tok] = []
    pos, line = 0, 1
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise HCLParseError(f"unexpected character {text[pos]!r}", line)
        kind = m.lastgroup
        raw = m.group(0)
        if kind == "heredoc":
            tag = m.group("hdtag")
            strip_indent = raw.startswith("<<-")
            line += 1
            end_re = re.compile(
                rf"^[ \t]*{re.escape(tag)}[ \t]*$", re.MULTILINE)
            em = end_re.search(text, m.end())
            if em is None:
                raise HCLParseError(f"unterminated heredoc <<{tag}", line)
            body = text[m.end():em.start()]
            if body.endswith("\n"):
                body = body[:-1]      # the newline before the EOF marker
            if strip_indent:
                body = "\n".join(l.lstrip("\t ") for l in body.split("\n"))
            toks.append(_Tok("string", body, line))
            line += body.count("\n") + 1
            pos = em.end()
            continue
        if kind == "nl":
            line += 1
        elif kind == "mcomment":
            line += raw.count("\n")
        elif kind == "string":
            s = raw[1:-1]
            s = (s.replace(r"\\", "\x00")
                  .replace(r"\"", '"')
                  .replace(r"\n", "\n")
                  .replace(r"\t", "\t")
                  .replace("\x00", "\\"))
            toks.append(_Tok("string", s, line))
        elif kind == "number":
            toks.append(_Tok("number",
                             float(raw) if "." in raw else int(raw), line))
        elif kind == "ident":
            toks.append(_Tok("ident", raw, line))
        elif kind == "punct":
            toks.append(_Tok(raw, raw, line))
        pos = m.end()
    toks.append(_Tok("eof", None, line))
    return toks


# ----------------------------------------------------------------- parser
@dataclass
class Body:
    attrs: Dict[str, Any] = field(default_factory=dict)
    blocks: List[Tuple[str, List[str], "Body"]] = field(default_factory=list)

    def blocks_named(self, name: str) -> List[Tuple[List[str], "Body"]]:
        return [(labels, body) for n, labels, body in self.blocks
                if n == name]

    def one_block(self, name: str) -> Optional["Body"]:
        found = self.blocks_named(name)
        return found[0][1] if found else None

    def keys(self):
        return set(self.attrs) | {n for n, _, _ in self.blocks}


class _Parser:
    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        tok = self.toks[self.i]
        self.i += 1
        return tok

    def expect(self, kind: str) -> _Tok:
        tok = self.next()
        if tok.kind != kind:
            raise HCLParseError(
                f"expected {kind}, got {tok.kind} ({tok.value!r})", tok.line)
        return tok

    def parse_body(self, until: str) -> Body:
        body = Body()
        while True:
            tok = self.peek()
            if tok.kind == until:
                self.next()
                return body
            if tok.kind not in ("ident", "string"):
                raise HCLParseError(
                    f"expected identifier, got {tok.kind} ({tok.value!r})",
                    tok.line)
            name = self.next().value
            tok = self.peek()
            if tok.kind == "=":
                self.next()
                if name in body.attrs:
                    raise HCLParseError(f"duplicate key {name!r}", tok.line)
                body.attrs[name] = self.parse_value()
                continue
            # block: zero or more labels then '{'
            labels: List[str] = []
            while self.peek().kind in ("string", "ident"):
                labels.append(self.next().value)
            open_tok = self.expect("{")
            body.blocks.append((name, labels, self.parse_body("}")))

    def parse_value(self) -> Any:
        tok = self.next()
        if tok.kind in ("string", "number"):
            return tok.value
        if tok.kind == "ident":
            if tok.value == "true":
                return True
            if tok.value == "false":
                return False
            raise HCLParseError(f"unexpected identifier {tok.value!r} "
                                "as value", tok.line)
        if tok.kind == "[":
            items = []
            while True:
                if self.peek().kind == "]":
                    self.next()
                    return items
                items.append(self.parse_value())
                if self.peek().kind == ",":
                    self.next()
        if tok.kind == "{":
            obj: Dict[str, Any] = {}
            while True:
                t = self.peek()
                if t.kind == "}":
                    self.next()
                    return obj
                if t.kind not in ("ident", "string"):
                    raise HCLParseError(
                        f"expected key, got {t.kind}", t.line)
                key = self.next().value
                self.expect("=")
                obj[key] = self.parse_value()
                if self.peek().kind == ",":
                    self.next()
        raise HCLParseError(f"unexpected token {tok.kind}", tok.line)


def parse_hcl(text: str) -> Body:
    return _Parser(_lex(text)).parse_body("eof")
