"""SWIM-style membership over the RPC substrate.

Reference: vendored hashicorp/memberlist + serf as wired in
nomad/serf.go — gossip disseminates the member list, a probe cycle
detects failures (direct ping, then indirect ping through k peers),
suspicion protects against false positives, and incarnation numbers
let a live member refute its own death.

This implementation keeps the protocol but rides the framed-TCP RPC
layer instead of UDP packets: each round gossips full state to a
random peer (anti-entropy push-pull) and probes one member. Clusters
here are server quorums (3-5 per region plus federation peers), so
full-state sync per round is well within frame budget.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..rpc.client import ClientPool, RpcError
from ..rpc.server import RpcServer

_log = logging.getLogger(__name__)

STATUS_ALIVE = "alive"
STATUS_SUSPECT = "suspect"
STATUS_DEAD = "dead"
STATUS_LEFT = "left"

_RANK = {STATUS_ALIVE: 0, STATUS_SUSPECT: 1, STATUS_DEAD: 2,
         STATUS_LEFT: 3}


@dataclass
class Member:
    id: str
    addr: Tuple[str, int]
    region: str = "global"
    status: str = STATUS_ALIVE
    incarnation: int = 0
    tags: Dict[str, str] = field(default_factory=dict)

    def wire(self) -> dict:
        return {"id": self.id, "addr": list(self.addr),
                "region": self.region, "status": self.status,
                "incarnation": self.incarnation, "tags": self.tags}

    @staticmethod
    def from_wire(d: dict) -> "Member":
        return Member(id=d["id"], addr=(d["addr"][0], int(d["addr"][1])),
                      region=d.get("region", "global"),
                      status=d.get("status", STATUS_ALIVE),
                      incarnation=int(d.get("incarnation", 0)),
                      tags=d.get("tags", {}))


class GossipAgent:
    """One server's membership view + the gossip/probe loops.

    Callbacks (reference: serf.go:34-40 event handler):
      on_join(member)  — a member newly seen alive
      on_fail(member)  — a member transitioned to suspect->dead
    """

    def __init__(self, member: Member, rpc_server: RpcServer,
                 gossip_interval_s: float = 0.2,
                 probe_interval_s: float = 0.3,
                 probe_timeout_s: float = 0.5,
                 suspicion_timeout_s: float = 1.5,
                 indirect_probes: int = 2,
                 on_join: Optional[Callable[[Member], None]] = None,
                 on_fail: Optional[Callable[[Member], None]] = None):
        self.me = member
        self.rpc = rpc_server
        self._members: Dict[str, Member] = {member.id: member}
        self._suspect_since: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._pool = ClientPool()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self.gossip_interval_s = gossip_interval_s
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.suspicion_timeout_s = suspicion_timeout_s
        self.indirect_probes = indirect_probes
        self.on_join = on_join
        self.on_fail = on_fail
        rpc_server.register("Gossip.Sync", self._rpc_sync)
        rpc_server.register("Gossip.Ping", self._rpc_ping)
        rpc_server.register("Gossip.PingReq", self._rpc_ping_req)

    # ------------------------------------------------------------ API
    def join(self, addr: Tuple[str, int]) -> None:
        """Push-pull with a seed member (serf join)."""
        remote = self._sync_with(addr)
        if remote is None:
            raise ConnectionError(f"join {addr} failed")

    def members(self, alive_only: bool = False) -> List[Member]:
        with self._lock:
            out = [m for m in self._members.values()
                   if not alive_only or m.status == STATUS_ALIVE]
            return sorted(out, key=lambda m: m.id)

    def member(self, member_id: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(member_id)

    def regions(self) -> List[str]:
        with self._lock:
            return sorted({m.region for m in self._members.values()
                           if m.status == STATUS_ALIVE})

    def members_of_region(self, region: str) -> List[Member]:
        with self._lock:
            return sorted((m for m in self._members.values()
                           if m.region == region
                           and m.status == STATUS_ALIVE),
                          key=lambda m: m.id)

    def start(self) -> None:
        for fn, name in ((self._gossip_loop, "gossip"),
                         (self._probe_loop, "probe")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"{name}-{self.me.id}")
            t.start()
            self._threads.append(t)

    def leave(self) -> None:
        """Graceful exit: mark self left and push once (serf Leave)."""
        with self._lock:
            self.me.incarnation += 1
            self.me.status = STATUS_LEFT
            self._members[self.me.id] = self.me
            peers = self._gossip_targets_locked()
        for m in peers:
            self._sync_with(m.addr)
        self.stop()

    def stop(self) -> None:
        self._shutdown.set()
        self._pool.close()

    # ------------------------------------------------------ rpc verbs
    def _check_running(self) -> None:
        # a stopped agent must be unreachable even while its shared
        # RpcServer keeps serving other subsystems — peers probe
        # liveness through these verbs
        if self._shutdown.is_set():
            from ..rpc.server import RpcHandlerError
            raise RpcHandlerError("unreachable",
                                  f"gossip agent {self.me.id} stopped")

    def _rpc_sync(self, params):
        """Anti-entropy push-pull: merge the caller's view, reply with
        ours."""
        self._check_running()
        for d in params[0]:
            self._merge(Member.from_wire(d))
        with self._lock:
            return [m.wire() for m in self._members.values()]

    def _rpc_ping(self, params):
        self._check_running()
        return self.me.id

    def _rpc_ping_req(self, params):
        """Indirect probe on behalf of a suspicious peer."""
        self._check_running()
        target_id = params[0]
        with self._lock:
            target = self._members.get(target_id)
        if target is None:
            return False
        return self._direct_ping(target)

    # ---------------------------------------------------------- loops
    def _gossip_loop(self) -> None:
        while not self._shutdown.wait(self.gossip_interval_s):
            try:
                with self._lock:
                    peers = self._gossip_targets_locked()
                if peers:
                    self._sync_with(random.choice(peers).addr)
            except Exception:                   # noqa: BLE001
                _log.exception("%s: gossip round failed", self.me.id)

    def _probe_loop(self) -> None:
        while not self._shutdown.wait(self.probe_interval_s):
            try:
                self._probe_round()
            except Exception:                   # noqa: BLE001
                _log.exception("%s: probe round failed", self.me.id)

    def _probe_round(self) -> None:
        with self._lock:
            candidates = [m for m in self._members.values()
                          if m.id != self.me.id
                          and m.status in (STATUS_ALIVE,
                                           STATUS_SUSPECT)]
        if candidates:
            target = random.choice(candidates)
            if self._direct_ping(target) or self._indirect_ping(target):
                self._set_alive(target.id, target.incarnation)
            else:
                self._suspect(target)
        self._expire_suspects()

    # ------------------------------------------------------- plumbing
    def _gossip_targets_locked(self) -> List[Member]:
        return [m for m in self._members.values()
                if m.id != self.me.id and m.status != STATUS_LEFT]

    def _sync_with(self, addr) -> Optional[List[Member]]:
        try:
            with self._lock:
                state = [m.wire() for m in self._members.values()]
            out = self._pool.get(f"{addr[0]}:{addr[1]}", addr).call(
                "Gossip.Sync", [state], timeout=self.probe_timeout_s)
        except (ConnectionError, RpcError):
            return None
        members = [Member.from_wire(d) for d in out]
        for m in members:
            self._merge(m)
        return members

    def _direct_ping(self, target: Member) -> bool:
        try:
            key = f"{target.addr[0]}:{target.addr[1]}"
            out = self._pool.get(key, target.addr).call(
                "Gossip.Ping", [], timeout=self.probe_timeout_s)
            return out == target.id
        except (ConnectionError, RpcError):
            return False

    def _indirect_ping(self, target: Member) -> bool:
        with self._lock:
            helpers = [m for m in self._members.values()
                       if m.status == STATUS_ALIVE
                       and m.id not in (self.me.id, target.id)]
        random.shuffle(helpers)
        for helper in helpers[:self.indirect_probes]:
            try:
                key = f"{helper.addr[0]}:{helper.addr[1]}"
                from ..rpc.client import DIAL_TIMEOUT_S
                ok = self._pool.get(key, helper.addr).call(
                    "Gossip.PingReq", [target.id],
                    timeout=DIAL_TIMEOUT_S + 2 * self.probe_timeout_s)
                if ok:
                    return True
            except (ConnectionError, RpcError):
                continue
        return False

    def _merge(self, incoming: Member) -> None:
        """Incarnation-ordered merge (memberlist aliveness rules):
        higher incarnation wins; at equal incarnation the worse status
        wins. News about OURSELVES that isn't alive is refuted by
        bumping our incarnation (memberlist refute)."""
        fire_join = fire_fail = None
        with self._lock:
            if incoming.id == self.me.id:
                if (incoming.status != STATUS_ALIVE
                        and incoming.incarnation >= self.me.incarnation
                        and self.me.status == STATUS_ALIVE):
                    self.me.incarnation = incoming.incarnation + 1
                return
            cur = self._members.get(incoming.id)
            applied = False
            if cur is None:
                self._members[incoming.id] = incoming
                applied = True
                if incoming.status == STATUS_ALIVE:
                    fire_join = incoming
            else:
                newer = (incoming.incarnation, _RANK[incoming.status]) \
                    > (cur.incarnation, _RANK[cur.status])
                if newer:
                    was = cur.status
                    self._members[incoming.id] = incoming
                    applied = True
                    if (was != STATUS_ALIVE
                            and incoming.status == STATUS_ALIVE):
                        fire_join = incoming
                    if (was in (STATUS_ALIVE, STATUS_SUSPECT)
                            and incoming.status == STATUS_DEAD):
                        fire_fail = incoming
            # suspicion-clock bookkeeping only follows records that WON
            # the merge: a stale alive claim (rank-losing) must not
            # clear an armed suspicion timer
            if applied and incoming.status == STATUS_ALIVE:
                self._suspect_since.pop(incoming.id, None)
            elif applied and incoming.status == STATUS_SUSPECT:
                # a suspicion learned via gossip expires here too —
                # every observer runs its own suspicion clock
                # (memberlist's suspicion timeout), otherwise a member
                # that only ever HEARD the suspicion keeps it forever
                self._suspect_since.setdefault(incoming.id,
                                               time.monotonic())
        if fire_join and self.on_join:
            self.on_join(fire_join)
        if fire_fail and self.on_fail:
            self.on_fail(fire_fail)

    def _set_alive(self, member_id: str, incarnation: int) -> None:
        with self._lock:
            m = self._members.get(member_id)
            if m and m.status == STATUS_SUSPECT \
                    and m.incarnation <= incarnation:
                m.status = STATUS_ALIVE
                self._suspect_since.pop(member_id, None)

    def _suspect(self, target: Member) -> None:
        with self._lock:
            m = self._members.get(target.id)
            if m and m.status == STATUS_ALIVE:
                m.status = STATUS_SUSPECT
                self._suspect_since[m.id] = time.monotonic()
                _log.info("%s: member %s suspect", self.me.id, m.id)

    def _expire_suspects(self) -> None:
        now = time.monotonic()
        fire: List[Member] = []
        with self._lock:
            for mid, since in list(self._suspect_since.items()):
                if now - since < self.suspicion_timeout_s:
                    continue
                m = self._members.get(mid)
                if m and m.status == STATUS_SUSPECT:
                    m.status = STATUS_DEAD
                    fire.append(m)
                self._suspect_since.pop(mid, None)
        for m in fire:
            _log.warning("%s: member %s failed", self.me.id, m.id)
            if self.on_fail:
                self.on_fail(m)
