"""Multi-region request routing over gossip membership.

Reference: nomad/rpc.go forward() — a request naming another region is
proxied to a live server of that region discovered via the WAN gossip
pool (nomad/server.go:1498 Regions / serf member tags).
"""
from __future__ import annotations

import random
from typing import Any, List, Optional

from ..rpc.client import ClientPool, RpcError
from .gossip import GossipAgent


class RegionRouter:
    """Routes RPC verbs to a region's servers using the member list."""

    def __init__(self, gossip: GossipAgent):
        self.gossip = gossip
        self._pool = ClientPool()

    def regions(self) -> List[str]:
        return self.gossip.regions()

    def close(self) -> None:
        self._pool.close()

    def call_region(self, region: str, method: str, params: List[Any],
                    timeout: float = 30.0) -> Any:
        """Invoke an RPC verb on some live server of `region`; tries
        members in random order, following in-region leader forwarding
        server-side."""
        members = self.gossip.members_of_region(region)
        if not members:
            raise ConnectionError(f"no live servers in region {region!r}")
        random.shuffle(members)
        last: Optional[Exception] = None
        for m in members:
            try:
                return self._pool.get(m.id, m.addr).call(
                    method, params, timeout=timeout)
            except (ConnectionError, RpcError) as e:
                if isinstance(e, RpcError) and e.kind not in (
                        "not_leader", "forward_failed"):
                    raise
                last = e
        raise last if last is not None else \
            ConnectionError(f"region {region!r} unreachable")
