"""Cluster membership: SWIM-style gossip + region routing.

Reference: nomad/serf.go (serf/memberlist gossip joins the servers,
fires nodeJoin/nodeFailed events) and the region forwarding that rides
on it (nomad/server.go:1498 Regions, nomad/rpc.go forward to a remote
region by name).
"""
from .gossip import GossipAgent, Member
from .regions import RegionRouter

__all__ = ["GossipAgent", "Member", "RegionRouter"]
