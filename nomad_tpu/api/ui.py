"""Web UI: hash-routed single-page app, no build step, no dependencies.

Reference: ui/ — a full Ember app consuming /v1/* with live updates
(routes/adapters per resource, ui/app/router.js).  This build serves
one HTML page at /ui with the same route structure in miniature:

  #/            dashboard (jobs / deployments / nodes / services)
  #/job/<id>    job detail: groups, allocations, evals, deployments,
                versions
  #/node/<id>   node detail: attributes, drivers, allocations on node
  #/alloc/<id>  alloc detail: task states + events, log tail (when the
                alloc runs on this agent's node)

Everything renders from the same /v1 endpoints the CLI and SDK use,
auto-refreshing every 2s; all interpolated values are HTML-escaped.
"""

UI_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  body { font: 13px/1.5 system-ui, sans-serif; margin: 0; color: #222; }
  header { background: #1f2d3d; color: #fff; padding: 10px 20px; }
  header h1 { font-size: 16px; margin: 0; display: inline-block; }
  header h1 a { color: #fff; text-decoration: none; }
  header span { opacity: .7; margin-left: 12px; font-size: 12px; }
  main { padding: 16px 20px; max-width: 1100px; }
  h2 { font-size: 14px; border-bottom: 1px solid #ddd;
       padding-bottom: 4px; margin: 22px 0 8px; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: 4px 10px 4px 0;
           border-bottom: 1px solid #f0f0f0; font-size: 12.5px; }
  th { color: #888; font-weight: 600; }
  .ok { color: #1a7f37; } .bad { color: #c62828; }
  .dim { color: #999; }
  code { background: #f5f5f5; padding: 1px 4px; border-radius: 3px; }
  a { color: #14508c; text-decoration: none; }
  a:hover { text-decoration: underline; }
  pre.logs { background: #111; color: #ddd; padding: 10px;
             max-height: 320px; overflow: auto; font-size: 12px; }
  .crumb { margin: 0 0 10px; font-size: 12.5px; }
</style>
</head>
<body>
<header><h1><a href="#/">nomad-tpu</a></h1><span id="stamp"></span>
</header>
<main id="view"></main>
<script>
async function j(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path + ": " + r.status);
  return r.json();
}
function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;"}[c]));
}
function row(cells, header) {
  const tag = header ? "th" : "td";
  return "<tr>" + cells.map(c => `<${tag}>${c}</${tag}>`).join("") +
         "</tr>";
}
function table(header, rows) {
  return "<table>" + row(header, true) +
    (rows.length ? rows.map(r => row(r)).join("")
                 : row(["<span class=dim>none</span>"])) + "</table>";
}
function statusCell(s, goodSet) {
  const cls = goodSet.includes(s) ? "ok" : "bad";
  return `<span class="${cls}">${esc(s)}</span>`;
}
function idLink(kind, id, len) {
  return `<a href="#/${kind}/${encodeURIComponent(id)}"><code>` +
         esc(len ? id.slice(0, len) : id) + "</code></a>";
}

async function viewDashboard() {
  const [jobs, nodes, deps, services] = await Promise.all([
    j("/v1/jobs"), j("/v1/nodes"), j("/v1/deployments"),
    j("/v1/services")]);
  return "<h2>Jobs</h2>" +
    table(["ID", "Type", "Priority", "Status", "Summary"],
      jobs.map(x => [
        idLink("job", x.id), esc(x.type), esc(x.priority),
        statusCell(x.status, ["running"]), esc(x.summary || "")])) +
    "<h2>Deployments</h2>" +
    table(["ID", "Job", "Status", "Description"],
      deps.map(d => [
        `<code>${esc(d.id.slice(0, 8))}</code>`,
        idLink("job", d.job_id),
        statusCell(d.status, ["successful", "running"]),
        esc(d.status_description || "")])) +
    "<h2>Nodes</h2>" +
    table(["ID", "Name", "DC", "Class", "Eligibility", "Status"],
      nodes.map(n => [
        idLink("node", n.id, 8), esc(n.name), esc(n.datacenter),
        n.node_class ? esc(n.node_class) : "<span class=dim>-</span>",
        esc(n.scheduling_eligibility),
        statusCell(n.status, ["ready"])])) +
    "<h2>Services</h2>" +
    table(["Service", "Tags"],
      services.map(s => [
        `<code>${esc(s.ServiceName)}</code>`,
        esc((s.Tags || []).join(", "))]));
}

function allocRows(allocs) {
  // alloc LIST endpoints return CamelCase stubs (the reference's
  // AllocListStub JSON); detail endpoints are snake_case
  return allocs.map(a => [
    idLink("alloc", a.ID, 8), esc(a.TaskGroup), esc(a.Name),
    a.NodeID ? idLink("node", a.NodeID, 8)
             : "<span class=dim>-</span>",
    esc(a.DesiredStatus),
    statusCell(a.ClientStatus, ["running", "complete"])]);
}
const ALLOC_HDR = ["ID", "Group", "Name", "Node", "Desired", "Client"];

async function viewJob(id) {
  const q = encodeURIComponent(id);   // dispatched child ids embed '/'
  const [job, allocs, evals, deps, versions] = await Promise.all([
    j(`/v1/job/${q}`), j(`/v1/job/${q}/allocations`),
    j(`/v1/job/${q}/evaluations`), j(`/v1/job/${q}/deployments`),
    j(`/v1/job/${q}/versions`).catch(() => [])]);
  const groups = (job.task_groups || []).map(g => [
    esc(g.name), esc(g.count),
    esc((g.tasks || []).map(t => t.name + " (" + t.driver + ")")
        .join(", "))]);
  return `<p class=crumb><a href="#/">jobs</a> /
            <code>${esc(id)}</code></p>` +
    `<h2>Job ${esc(id)} <span class=dim>type=${esc(job.type)}
       priority=${esc(job.priority)}
       status=${esc(job.status)}</span></h2>` +
    "<h2>Task groups</h2>" +
    table(["Group", "Count", "Tasks"], groups) +
    "<h2>Allocations</h2>" + table(ALLOC_HDR, allocRows(allocs)) +
    "<h2>Evaluations</h2>" +
    table(["ID", "Trigger", "Status"],
      evals.map(e => [`<code>${esc(e.id.slice(0, 8))}</code>`,
                      esc(e.triggered_by),
                      statusCell(e.status, ["complete"])])) +
    "<h2>Deployments</h2>" +
    table(["ID", "Status", "Description"],
      deps.map(d => [`<code>${esc(d.id.slice(0, 8))}</code>`,
                     statusCell(d.status, ["successful", "running"]),
                     esc(d.status_description || "")])) +
    "<h2>Versions</h2>" +
    table(["Version", "Stable"],
      versions.map(v => [esc(v.version), esc(v.stable)]));
}

async function viewNode(id) {
  const [node, allocs] = await Promise.all([
    j(`/v1/node/${id}`), j(`/v1/node/${id}/allocations`)]);
  const attrs = Object.entries(node.attributes || {}).sort()
    .map(([k, v]) => [`<code>${esc(k)}</code>`, esc(v)]);
  return `<p class=crumb><a href="#/">nodes</a> /
            <code>${esc(node.name)}</code></p>` +
    `<h2>Node ${esc(node.name)}
       <span class=dim>${esc(node.id)} dc=${esc(node.datacenter)}
       status=${esc(node.status)}
       eligibility=${esc(node.scheduling_eligibility)}</span></h2>` +
    "<h2>Allocations on node</h2>" +
    table(ALLOC_HDR, allocRows(allocs)) +
    "<h2>Attributes</h2>" + table(["Attribute", "Value"], attrs);
}

async function viewAlloc(id) {
  const a = await j(`/v1/allocation/${id}`);
  const states = Object.entries(a.task_states || {}).map(([t, st]) => [
    esc(t), statusCell(st.state, ["running", "dead"]),
    esc(st.failed ? "failed" : ""),
    esc((st.events || []).map(e => e.type).join(" \\u2192 "))]);
  const events = [];
  for (const [t, st] of Object.entries(a.task_states || {}))
    for (const e of (st.events || []))
      events.push([esc(t), esc(e.type),
                   esc(e.display_message || e.message || "")]);
  let logs = "";
  const tasks = Object.keys(a.task_states || {});
  if (tasks.length) {
    try {
      const lg = await j(`/v1/client/fs/logs/${id}` +
                         `?task=${encodeURIComponent(tasks[0])}` +
                         `&type=stdout&tail_lines=40`);
      logs = `<h2>Logs <span class=dim>${esc(tasks[0])}
              stdout (tail)</span></h2>` +
             `<pre class=logs>${esc(lg.data || "")}</pre>`;
    } catch (e) { /* alloc not on this agent's node */ }
  }
  return `<p class=crumb><a href="#/">allocs</a> /
            <a href="#/job/${encodeURIComponent(a.job_id)}">` +
            `${esc(a.job_id)}</a> /
            <code>${esc(a.id.slice(0, 8))}</code></p>` +
    `<h2>Allocation ${esc(a.name)}
       <span class=dim>${esc(a.id)}
       desired=${esc(a.desired_status)}
       client=${esc(a.client_status)}</span></h2>` +
    "<h2>Task states</h2>" +
    table(["Task", "State", "Failed", "Events"], states) +
    "<h2>Events</h2>" +
    table(["Task", "Type", "Message"], events) + logs;
}

async function render() {
  const h = location.hash || "#/";
  const parts = h.slice(2).split("/");
  let html;
  try {
    if (parts[0] === "job" && parts[1])
      html = await viewJob(decodeURIComponent(parts[1]));
    else if (parts[0] === "node" && parts[1])
      html = await viewNode(decodeURIComponent(parts[1]));
    else if (parts[0] === "alloc" && parts[1])
      html = await viewAlloc(decodeURIComponent(parts[1]));
    else
      html = await viewDashboard();
    document.getElementById("view").innerHTML = html;
    document.getElementById("stamp").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("stamp").textContent = "error: " + e;
  }
}
window.addEventListener("hashchange", render);
render();
setInterval(render, 2000);
</script>
</body>
</html>
"""
