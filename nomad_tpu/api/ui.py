"""Minimal web UI.

Reference: ui/ — a full Ember app consuming /v1/* with live updates.
This build ships a deliberately small single-page dashboard (no build
step, no dependencies) served at /ui: jobs with summary counts, nodes,
deployments and the service catalog, auto-refreshing against the same
/v1 endpoints the CLI and SDK use.
"""

UI_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  body { font: 13px/1.5 system-ui, sans-serif; margin: 0; color: #222; }
  header { background: #1f2d3d; color: #fff; padding: 10px 20px; }
  header h1 { font-size: 16px; margin: 0; display: inline-block; }
  header span { opacity: .7; margin-left: 12px; font-size: 12px; }
  main { padding: 16px 20px; max-width: 1100px; }
  h2 { font-size: 14px; border-bottom: 1px solid #ddd;
       padding-bottom: 4px; margin: 22px 0 8px; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: 4px 10px 4px 0;
           border-bottom: 1px solid #f0f0f0; font-size: 12.5px; }
  th { color: #888; font-weight: 600; }
  .ok { color: #1a7f37; } .bad { color: #c62828; }
  .dim { color: #999; }
  code { background: #f5f5f5; padding: 1px 4px; border-radius: 3px; }
</style>
</head>
<body>
<header><h1>nomad-tpu</h1><span id="stamp"></span></header>
<main>
  <h2>Jobs</h2><table id="jobs"></table>
  <h2>Deployments</h2><table id="deps"></table>
  <h2>Nodes</h2><table id="nodes"></table>
  <h2>Services</h2><table id="services"></table>
</main>
<script>
async function j(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path + ": " + r.status);
  return r.json();
}
function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;"}[c]));
}
function row(cells, header) {
  const tag = header ? "th" : "td";
  return "<tr>" + cells.map(c => `<${tag}>${c}</${tag}>`).join("") +
         "</tr>";
}
function setTable(id, header, rows) {
  document.getElementById(id).innerHTML =
    row(header, true) +
    (rows.length ? rows.map(r => row(r)).join("")
                 : row(["<span class=dim>none</span>"]));
}
function statusCell(s, goodSet) {
  const cls = goodSet.includes(s) ? "ok" : "bad";
  return `<span class="${cls}">${esc(s)}</span>`;
}
async function refresh() {
  try {
    const [jobs, nodes, deps, services] = await Promise.all([
      j("/v1/jobs"), j("/v1/nodes"), j("/v1/deployments"),
      j("/v1/services")]);
    setTable("jobs", ["ID", "Type", "Priority", "Status", "Summary"],
      jobs.map(x => [
        `<code>${esc(x.id)}</code>`, esc(x.type), esc(x.priority),
        statusCell(x.status, ["running"]),
        esc(x.summary || "")]));
    setTable("nodes", ["ID", "Name", "DC", "Class", "Eligibility",
                       "Status"],
      nodes.map(n => [
        `<code>${esc(n.id.slice(0, 8))}</code>`, esc(n.name),
        esc(n.datacenter),
        n.node_class ? esc(n.node_class) : "<span class=dim>-</span>",
        esc(n.scheduling_eligibility),
        statusCell(n.status, ["ready"])]));
    setTable("deps", ["ID", "Job", "Status", "Description"],
      deps.map(d => [
        `<code>${esc(d.id.slice(0, 8))}</code>`, esc(d.job_id),
        statusCell(d.status, ["successful", "running"]),
        esc(d.status_description || "")]));
    setTable("services", ["Service", "Tags"],
      services.map(s => [
        `<code>${esc(s.ServiceName)}</code>`,
        esc((s.Tags || []).join(", "))]));
    document.getElementById("stamp").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("stamp").textContent = "error: " + e;
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
