"""HTTP API layer + client SDK (reference: command/agent/http.go and
the api/ Go SDK)."""
from .client import ApiClient, APIError
from .http_server import HTTPAgentServer, HTTPError

__all__ = ["ApiClient", "APIError", "HTTPAgentServer", "HTTPError"]
