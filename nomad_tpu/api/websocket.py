"""Minimal dependency-free WebSocket (RFC 6455) — server and client.

The reference streams interactive `alloc exec` sessions over a
websocket between the CLI and the agent HTTP API
(command/alloc_exec.go -> api/allocations.go Exec -> websocket ->
command/agent/alloc_endpoint.go), then over gRPC to the driver
(plugins/drivers/execstreaming.go).  This module is the wire layer for
the same path here: JSON text frames, close/ping/pong control frames,
client-side masking per the RFC.  Only what the exec path needs — no
extensions, no fragmentation (frames up to 2^63 are written whole;
fragmented incoming messages are reassembled).
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct
from typing import Optional, Tuple
from urllib.parse import urlsplit

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

# the exec protocol's frames are b64 chunks of <=64KiB reads plus JSON
# overhead; anything larger is a hostile or broken peer.  The cap
# bounds what one connection can park in this process's memory.
MAX_MESSAGE_BYTES = 1 << 20


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


class WebSocketClosed(Exception):
    pass


class WebSocketConn:
    """A connected websocket endpoint over a plain socket.

    `mask` must be True for client-originated frames (RFC 6455 §5.3);
    servers send unmasked.
    """

    def __init__(self, sock: socket.socket, mask: bool):
        self._sock = sock
        self._mask = mask
        self._buf = b""
        self.closed = False

    # ------------------------------------------------------------ send
    def _send_frame(self, opcode: int, payload: bytes) -> None:
        if self.closed:
            raise WebSocketClosed("send on closed websocket")
        head = bytes([0x80 | opcode])
        mask_bit = 0x80 if self._mask else 0
        n = len(payload)
        if n < 126:
            head += bytes([mask_bit | n])
        elif n < (1 << 16):
            head += bytes([mask_bit | 126]) + struct.pack(">H", n)
        else:
            head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
        if self._mask:
            key = os.urandom(4)
            masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
            data = head + key + masked
        else:
            data = head + payload
        try:
            self._sock.sendall(data)
        except OSError as e:
            self.closed = True
            raise WebSocketClosed(str(e))

    def send_json(self, obj) -> None:
        self._send_frame(OP_TEXT, json.dumps(obj).encode())

    def send_close(self, code: int = 1000) -> None:
        if not self.closed:
            try:
                self._send_frame(OP_CLOSE, struct.pack(">H", code))
            except WebSocketClosed:
                pass
            self.closed = True

    # ------------------------------------------------------------ recv
    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self._sock.recv(65536)
            except OSError as e:
                raise WebSocketClosed(str(e))
            if not chunk:
                raise WebSocketClosed("peer closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_frame(self) -> Tuple[int, bytes, bool]:
        h = self._read_exact(2)
        fin = bool(h[0] & 0x80)
        opcode = h[0] & 0x0F
        masked = bool(h[1] & 0x80)
        n = h[1] & 0x7F
        if n == 126:
            n = struct.unpack(">H", self._read_exact(2))[0]
        elif n == 127:
            n = struct.unpack(">Q", self._read_exact(8))[0]
        if n > MAX_MESSAGE_BYTES:
            self.send_close(1009)          # message too big
            raise WebSocketClosed(f"frame of {n} bytes exceeds cap")
        key = self._read_exact(4) if masked else None
        payload = self._read_exact(n)
        if key:
            payload = bytes(b ^ key[i % 4]
                            for i, b in enumerate(payload))
        return opcode, payload, fin

    def recv_message(self) -> Optional[bytes]:
        """Next complete data message (reassembling continuations), or
        None once the peer closes."""
        if self.closed:
            return None
        parts = []
        total = 0
        while True:
            try:
                opcode, payload, fin = self._recv_frame()
            except WebSocketClosed:
                self.closed = True
                return None
            if opcode == OP_CLOSE:
                self.send_close()
                return None
            if opcode == OP_PING:
                try:
                    self._send_frame(OP_PONG, payload)
                except WebSocketClosed:
                    return None
                continue
            if opcode == OP_PONG:
                continue
            parts.append(payload)
            total += len(payload)
            if total > MAX_MESSAGE_BYTES:   # endless continuations
                self.send_close(1009)
                self.closed = True
                return None
            if fin:
                return b"".join(parts)

    def recv_json(self):
        msg = self.recv_message()
        return None if msg is None else json.loads(msg)

    def close(self) -> None:
        self.send_close()
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------- server
def server_handshake(handler) -> WebSocketConn:
    """Upgrade a BaseHTTPRequestHandler's connection; returns the
    websocket (server side, unmasked sends)."""
    key = handler.headers.get("Sec-WebSocket-Key", "")
    if not key:
        raise ValueError("missing Sec-WebSocket-Key")
    resp = ("HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept_key(key)}\r\n\r\n")
    handler.connection.sendall(resp.encode())
    return WebSocketConn(handler.connection, mask=False)


# ---------------------------------------------------------------- client
def client_connect(url: str, token: str = "",
                   timeout: float = 30.0) -> WebSocketConn:
    """Dial an http(s)/ws(s) URL and perform the client handshake;
    returns the websocket (client side, masked sends)."""
    parts = urlsplit(url)
    host = parts.hostname or "127.0.0.1"
    tls = parts.scheme in ("https", "wss")
    port = parts.port or (443 if tls else 80)
    path = parts.path + (f"?{parts.query}" if parts.query else "")
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(timeout)
    if tls:
        import ssl
        sock = ssl.create_default_context().wrap_socket(
            sock, server_hostname=host)
    key = base64.b64encode(os.urandom(16)).decode()
    req = (f"GET {path} HTTP/1.1\r\n"
           f"Host: {host}:{port}\r\n"
           "Upgrade: websocket\r\n"
           "Connection: Upgrade\r\n"
           f"Sec-WebSocket-Key: {key}\r\n"
           "Sec-WebSocket-Version: 13\r\n")
    if token:
        req += f"X-Nomad-Token: {token}\r\n"
    req += "\r\n"
    sock.sendall(req.encode())
    # read response head
    head = b""
    while b"\r\n\r\n" not in head:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("websocket handshake: peer closed")
        head += chunk
        if len(head) > 65536:
            raise ConnectionError("websocket handshake: oversized reply")
    head_s, _, rest = head.partition(b"\r\n\r\n")
    lines = head_s.decode("latin-1").split("\r\n")
    status = lines[0].split(" ", 2)
    if len(status) < 2 or status[1] != "101":
        raise ConnectionError(f"websocket handshake refused: {lines[0]}")
    hdrs = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        hdrs[k.strip().lower()] = v.strip()
    if hdrs.get("sec-websocket-accept") != accept_key(key):
        raise ConnectionError("websocket handshake: bad accept key")
    ws = WebSocketConn(sock, mask=True)
    ws._buf = rest
    return ws
