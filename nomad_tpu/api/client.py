"""API client SDK (reference: api/ — api.NewClient api.go:400, per-
resource files jobs.go, nodes.go, allocations.go, evaluations.go,
deployments.go, operator.go).

Talks to the agent's HTTP /v1 surface; no imports from the server
packages — this is the external-consumer boundary the CLI uses.
"""
from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple


def _q(path_param: str) -> str:
    """Percent-encode a path parameter; dispatched child job ids embed a
    '/' (<parent>/dispatch-<...>) and must travel as one path segment."""
    return urllib.parse.quote(path_param, safe="")


class APIError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(f"HTTP {code}: {msg}")
        self.code = code
        self.msg = msg


class ApiClient:
    def __init__(self, address: Optional[str] = None,
                 timeout: float = 330.0, token: Optional[str] = None,
                 tls=None):
        """`tls`: a utils.tlsutil.TLSConfig (or env NOMAD_CACERT /
        NOMAD_CLIENT_CERT / NOMAD_CLIENT_KEY, like the reference api
        client) — mutual TLS to an https agent address."""
        self.address = (address or os.environ.get("NOMAD_ADDR")
                        or "http://127.0.0.1:4646").rstrip("/")
        # reference: api.Config.SecretID / NOMAD_TOKEN (api/api.go)
        self.token = token or os.environ.get("NOMAD_TOKEN", "")
        self.timeout = timeout
        if tls is None and os.environ.get("NOMAD_CACERT"):
            from ..utils.tlsutil import TLSConfig
            tls = TLSConfig(
                ca_file=os.environ.get("NOMAD_CACERT", ""),
                cert_file=os.environ.get("NOMAD_CLIENT_CERT", ""),
                key_file=os.environ.get("NOMAD_CLIENT_KEY", ""))
        self.ssl_context = None
        if tls is not None and getattr(tls, "enabled", lambda: False)():
            from ..utils.tlsutil import client_context
            self.ssl_context = client_context(tls)
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.allocations = Allocations(self)
        self.evaluations = Evaluations(self)
        self.deployments = Deployments(self)
        self.system = System(self)
        self.agent = Agent(self)
        self.operator = Operator(self)

    # ------------------------------------------------------------ plumbing
    def request(self, method: str, path: str,
                params: Optional[Dict[str, Any]] = None,
                body: Any = None) -> Tuple[Any, int]:
        url = f"{self.address}{path}"
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v not in (None, "")})
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self.ssl_context) as resp:
                payload = json.loads(resp.read() or b"null")
                index = int(resp.headers.get("X-Nomad-Index") or 0)
                return payload, index
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:
                msg = str(e)
            raise APIError(e.code, msg)
        except urllib.error.URLError as e:
            raise APIError(0, f"cannot reach agent at {self.address}: "
                              f"{e.reason}")
        except OSError as e:
            # e.g. a plaintext dial against a TLS listener resets mid-
            # response; surface it as the same unreachable-agent error
            raise APIError(0, f"cannot reach agent at {self.address}: "
                              f"{e}")

    def get(self, path, **params):
        return self.request("GET", path, params=params)

    def post(self, path, body=None, **params):
        return self.request("POST", path, params=params, body=body)

    def delete(self, path, **params):
        return self.request("DELETE", path, params=params)


class _Sub:
    def __init__(self, client: ApiClient):
        self.c = client


class Jobs(_Sub):
    def list(self, prefix: str = "", index: int = 0, wait: str = ""):
        return self.c.get("/v1/jobs", prefix=prefix, index=index or None,
                          wait=wait)

    def register(self, job_wire: dict) -> dict:
        return self.c.post("/v1/jobs", {"job": job_wire})[0]

    def register_with_check(self, job_wire: dict,
                            check_index: int) -> dict:
        return self.c.post("/v1/jobs", {
            "job": job_wire, "enforce_index": True,
            "job_modify_index": check_index})[0]

    def parse(self, hcl: str) -> dict:
        return self.c.post("/v1/jobs/parse", {"job_hcl": hcl})[0]

    def info(self, job_id: str, index: int = 0, wait: str = ""):
        return self.c.get(f"/v1/job/{_q(job_id)}", index=index or None,
                          wait=wait)

    def deregister(self, job_id: str, purge: bool = False) -> dict:
        return self.c.delete(f"/v1/job/{_q(job_id)}",
                             purge="true" if purge else None)[0]

    def allocations(self, job_id: str) -> List[dict]:
        return self.c.get(f"/v1/job/{_q(job_id)}/allocations")[0]

    def evaluations(self, job_id: str) -> List[dict]:
        return self.c.get(f"/v1/job/{_q(job_id)}/evaluations")[0]

    def deployments(self, job_id: str) -> List[dict]:
        return self.c.get(f"/v1/job/{_q(job_id)}/deployments")[0]

    def summary(self, job_id: str) -> dict:
        return self.c.get(f"/v1/job/{_q(job_id)}/summary")[0]

    def versions(self, job_id: str) -> List[dict]:
        return self.c.get(f"/v1/job/{_q(job_id)}/versions")[0]

    def plan(self, job_id: str, job_wire: dict) -> dict:
        return self.c.post(f"/v1/job/{_q(job_id)}/plan",
                           {"job": job_wire})[0]

    def periodic_force(self, job_id: str) -> dict:
        return self.c.post(f"/v1/job/{_q(job_id)}/periodic/force")[0]

    def dispatch(self, job_id: str, payload: bytes = b"",
                 meta: Optional[Dict[str, str]] = None) -> dict:
        """Instantiate a parameterized job (reference: api/jobs.go
        Dispatch); returns {dispatched_job_id, eval_id}."""
        import base64
        body: Dict[str, Any] = {}
        if payload:
            body["payload"] = base64.b64encode(payload).decode()
        if meta:
            body["meta"] = dict(meta)
        return self.c.post(f"/v1/job/{_q(job_id)}/dispatch", body)[0]

    def revert(self, job_id: str, version: int,
               enforce_prior_version: Optional[int] = None) -> dict:
        body: Dict[str, Any] = {"job_version": version}
        if enforce_prior_version is not None:
            body["enforce_prior_version"] = enforce_prior_version
        return self.c.post(f"/v1/job/{_q(job_id)}/revert", body)[0]

    def stable(self, job_id: str, version: int,
               stable: bool = True) -> dict:
        return self.c.post(f"/v1/job/{_q(job_id)}/stable",
                           {"job_version": version, "stable": stable})[0]

    def scale(self, job_id: str, group: str, count: int) -> dict:
        return self.c.post(f"/v1/job/{_q(job_id)}/scale",
                           {"group": group, "count": count})[0]


class Nodes(_Sub):
    def list(self, prefix: str = "", index: int = 0, wait: str = ""):
        return self.c.get("/v1/nodes", prefix=prefix, index=index or None,
                          wait=wait)

    def info(self, node_id: str) -> dict:
        return self.c.get(f"/v1/node/{node_id}")[0]

    def allocations(self, node_id: str) -> List[dict]:
        return self.c.get(f"/v1/node/{node_id}/allocations")[0]

    def drain(self, node_id: str, deadline_s: float = 3600.0,
              ignore_system_jobs: bool = False,
              disable: bool = False) -> dict:
        body = {"drain_spec": None if disable else
                {"deadline_s": deadline_s,
                 "ignore_system_jobs": ignore_system_jobs},
                "mark_eligible": disable}
        return self.c.post(f"/v1/node/{node_id}/drain", body)[0]

    def eligibility(self, node_id: str, eligible: bool) -> dict:
        return self.c.post(
            f"/v1/node/{node_id}/eligibility",
            {"eligibility": "eligible" if eligible else "ineligible"})[0]

    def stats(self, node_id: str = "") -> dict:
        """Host resource gauges from a node's agent (reference:
        /v1/client/stats; ?node_id routes to that node)."""
        params = {"node_id": node_id} if node_id else {}
        return self.c.get("/v1/client/stats", **params)[0]


class Allocations(_Sub):
    def list(self, prefix: str = "", index: int = 0, wait: str = ""):
        return self.c.get("/v1/allocations", prefix=prefix,
                          index=index or None, wait=wait)

    def info(self, alloc_id: str) -> dict:
        return self.c.get(f"/v1/allocation/{alloc_id}")[0]

    def stop(self, alloc_id: str) -> dict:
        return self.c.post(f"/v1/allocation/{alloc_id}/stop")[0]

    def logs(self, alloc_id: str, task: str = "",
             type: str = "stdout", tail_lines: int = 0) -> str:
        """Task log contents (routed to the owning agent by the server
        — reference: api/fs.go Logs)."""
        params = {"type": type}
        if task:
            params["task"] = task
        if tail_lines:
            params["tail_lines"] = tail_lines
        out, _ix = self.c.get(f"/v1/client/fs/logs/{alloc_id}", **params)
        return out.get("data", "")

    def exec(self, alloc_id: str, cmd, task: str = "",
             timeout_s: float = 30.0) -> dict:
        """One-shot exec in the task's context; returns
        {"output", "exit_code"} (routed to the owning agent)."""
        body = {"cmd": [str(c) for c in cmd], "timeout_s": timeout_s}
        if task:
            body["task"] = task
        return self.c.post(
            f"/v1/client/allocation/{alloc_id}/exec", body)[0]

    # alloc filesystem (reference: api/fs.go — routed by the server)
    def _fs_get(self, verb: str, alloc_id: str, fs_path: str,
                **extra):
        # request() directly: the kwarg-based get() collides with a
        # file param literally named "path"
        return self.c.request(
            "GET", f"/v1/client/fs/{verb}/{alloc_id}",
            params=dict(extra, path=fs_path))[0]

    def fs_ls(self, alloc_id: str, path: str = "/") -> List[dict]:
        return self._fs_get("ls", alloc_id, path)["files"]

    def fs_stat(self, alloc_id: str, path: str) -> dict:
        return self._fs_get("stat", alloc_id, path)["file"]

    def fs_cat(self, alloc_id: str, path: str) -> bytes:
        """Full file contents; pages past the server's single-response
        cap with readat so large files come back complete."""
        import base64
        out = self._fs_get("cat", alloc_id, path)
        data = base64.b64decode(out.get("data", ""))
        total = out.get("size", len(data))
        while out.get("truncated") and len(data) < total:
            chunk = self.fs_readat(alloc_id, path, offset=len(data),
                                   limit=1 << 20)
            if not chunk:
                break
            data += chunk
        return data

    def fs_readat(self, alloc_id: str, path: str, offset: int = 0,
                  limit: int = 1 << 20) -> bytes:
        import base64
        out = self._fs_get("readat", alloc_id, path, offset=offset,
                           limit=limit)
        return base64.b64decode(out.get("data", ""))

    def fs_stream(self, alloc_id: str, path: str, offset: int = 0,
                  wait: float = 2.0) -> dict:
        """One long-poll step of a file follow; returns
        {"data": bytes, "offset": next_offset, "size": file_size}."""
        import base64
        out = self._fs_get("stream", alloc_id, path, offset=offset,
                           wait=wait)
        out["data"] = base64.b64decode(out.get("data", ""))
        return out

    def stats(self, alloc_id: str) -> dict:
        """Per-task resource usage (routed to the owning agent)."""
        return self.c.get(
            f"/v1/client/allocation/{alloc_id}/stats")[0]

    def exec_stream(self, alloc_id: str, command, task: str = "",
                    tty: bool = True, stdin_fd=None, stdout_fd=1,
                    tty_size=None, timeout: float = 3600.0) -> int:
        """Interactive exec (reference: api/allocations.go Exec —
        websocket to the agent, bridged to the driver's streaming
        exec).  Pumps local file descriptors: stdin_fd -> task stdin
        (None = output-only), task output -> stdout_fd.  Returns the
        remote exit code."""
        import base64
        import json as _json
        import select
        import threading
        from urllib.parse import quote

        from .websocket import WebSocketClosed, client_connect

        qs = (f"command={quote(_json.dumps([str(c) for c in command]))}"
              f"&tty={'true' if tty else 'false'}")
        if task:
            qs += f"&task={quote(task)}"
        url = (f"{self.c.address}/v1/client/allocation/{alloc_id}"
               f"/exec?{qs}")
        ws = client_connect(url, token=self.c.token, timeout=timeout)
        if tty_size:
            ws.send_json({"tty_size": {"width": tty_size[0],
                                       "height": tty_size[1]}})
        done = threading.Event()

        def pump_stdin():
            if stdin_fd is None:
                return
            try:
                while not done.is_set():
                    r, _, _ = select.select([stdin_fd], [], [], 0.2)
                    if not r:
                        continue
                    data = os.read(stdin_fd, 65536)
                    if not data:
                        ws.send_json({"stdin": {"close": True}})
                        return
                    ws.send_json({"stdin": {
                        "data": base64.b64encode(data).decode()}})
            except (OSError, WebSocketClosed):
                pass

        in_t = threading.Thread(target=pump_stdin, daemon=True)
        in_t.start()
        code = -1
        try:
            while True:
                msg = ws.recv_json()
                if msg is None:
                    break
                if "stdout" in msg and msg["stdout"].get("data"):
                    os.write(stdout_fd,
                             base64.b64decode(msg["stdout"]["data"]))
                elif "exit" in msg:
                    code = int(msg["exit"].get("code", -1))
                    break
        finally:
            done.set()
            ws.close()
            in_t.join(timeout=1.0)
        return code


class Evaluations(_Sub):
    def list(self) -> List[dict]:
        return self.c.get("/v1/evaluations")[0]

    def info(self, eval_id: str) -> dict:
        return self.c.get(f"/v1/evaluation/{eval_id}")[0]

    def allocations(self, eval_id: str) -> List[dict]:
        return self.c.get(f"/v1/evaluation/{eval_id}/allocations")[0]


class Deployments(_Sub):
    def list(self, index: int = 0, wait: str = ""):
        return self.c.get("/v1/deployments", index=index or None, wait=wait)

    def info(self, dep_id: str) -> dict:
        return self.c.get(f"/v1/deployment/{dep_id}")[0]

    def promote(self, dep_id: str) -> dict:
        return self.c.post(f"/v1/deployment/promote/{dep_id}")[0]

    def fail(self, dep_id: str) -> dict:
        return self.c.post(f"/v1/deployment/fail/{dep_id}")[0]

    def allocations(self, dep_id: str) -> List[dict]:
        return self.c.get(f"/v1/deployment/allocations/{dep_id}")[0]


class System(_Sub):
    def gc(self) -> None:
        self.c.post("/v1/system/gc")


class Agent(_Sub):
    def self_(self) -> dict:
        return self.c.get("/v1/agent/self")[0]

    def members(self) -> dict:
        return self.c.get("/v1/agent/members")[0]

    def metrics(self) -> dict:
        return self.c.get("/v1/metrics")[0]


class Operator(_Sub):
    def scheduler_config(self) -> dict:
        return self.c.get("/v1/operator/scheduler/configuration")[0]
