"""HTTP /v1 API (reference: command/agent/http.go:251-370 route table +
the command/agent/*_endpoint.go adapters).

Serves the server's verbs and the store's blocking queries over JSON.
Wire format is the codec's snake_case encoding of the domain structs
(this framework's own API; the shape parity with the reference is
per-route, not per-field). Blocking queries take ?index=N&wait=5s and
answer with the X-Nomad-Index header, exactly like the reference.
"""
from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from ..jobspec import JobspecParseError, parse_duration_s, parse_job
from ..server.server import JobValidationError
from ..structs import Evaluation, Job, Plan, PlanResult
from ..utils.codec import from_wire, to_wire
from ..utils.metrics import global_metrics

import logging

_log = logging.getLogger(__name__)

MAX_BLOCK_S = 300.0     # reference: nomad/rpc.go:35 maxQueryTime


class HTTPError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code
        self.msg = msg


class _DryRunPlanner:
    """Planner that records instead of committing (the Job.Plan path —
    reference: nomad/job_endpoint.go Job.Plan runs the scheduler against
    a snapshot with a no-op raft)."""

    def __init__(self, store):
        self.store = store
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []

    def submit_plan(self, plan: Plan):
        self.plans.append(plan)
        return PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=self.store.latest_index()), None

    def update_eval(self, ev): self.evals.append(ev)

    def create_eval(self, ev): self.evals.append(ev)

    def reblock_eval(self, ev): self.evals.append(ev)


class HTTPAgentServer:
    """The agent's HTTP listener. `server` is the in-proc control plane;
    `client` (optional) the local node agent for agent-local routes."""

    def __init__(self, server, client=None, host: str = "127.0.0.1",
                 port: int = 0, acl_enabled: bool = False, tls=None):
        """`tls`: utils.tlsutil.TLSConfig — serve /v1 over mutual TLS;
        a client without a CA-signed cert is rejected at handshake
        (reference: command/agent/http.go wraps the listener via
        tlsutil.NewTLSConfiguration when tls.http is set)."""
        self.server = server
        self.client = client
        self.acl_enabled = acl_enabled
        self.tls = tls
        # every agent exposes /v1/agent/monitor: capture the package's
        # logs from the moment the HTTP surface exists
        from ..utils.monitor import global_monitor
        global_monitor.install()
        self._routes = _build_routes(self)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def setup(self):
                import ssl as _ssl
                # self.request is the raw accepted socket (setup() has
                # not assigned self.connection yet)
                if isinstance(self.request, _ssl.SSLSocket):
                    self.request.settimeout(10.0)
                    self.request.do_handshake()
                    self.request.settimeout(None)
                super().setup()

            def log_message(self, *args):   # quiet
                pass

            def _handle(self, method: str):
                upgrade = (self.headers.get("Upgrade") or "").lower()
                if (method == "GET" and upgrade == "websocket"
                        and "/exec" in self.path
                        and self.path.startswith("/v1/client/allocation/")):
                    outer.handle_exec_ws(self)
                    self.close_connection = True
                    return
                if (method == "GET"
                        and self.path.split("?")[0]
                        == "/v1/agent/monitor"):
                    outer.handle_monitor(self)
                    self.close_connection = True
                    return
                if (method == "GET"
                        and self.path.split("?")[0] == "/v1/metrics"
                        and "format=prometheus" in (self.path.split("?")
                                                    + [""])[1]):
                    # text exposition needs its own content type; the
                    # JSON dispatch below would re-encode it
                    outer.handle_prometheus(self)
                    return
                if method == "GET" and (self.path == "/ui"
                                        or self.path.startswith("/ui/")
                                        or self.path == "/"):
                    from .ui import UI_HTML
                    data = UI_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                try:
                    token = self.headers.get("X-Nomad-Token", "")
                    code, body, index = outer.dispatch(
                        method, self.path, self._read_body(), token)
                except HTTPError as e:
                    code, body, index = e.code, {"error": e.msg}, None
                except Exception as e:
                    import traceback
                    traceback.print_exc()
                    code, body, index = 500, {"error": str(e)}, None
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if index is not None:
                    self.send_header("X-Nomad-Index", str(index))
                self.end_headers()
                self.wfile.write(data)

            def _read_body(self):
                length = int(self.headers.get("Content-Length") or 0)
                if not length:
                    return None
                raw = self.rfile.read(length)
                if not raw:
                    return None
                try:
                    return json.loads(raw)
                except json.JSONDecodeError as e:
                    raise HTTPError(400, f"invalid JSON body: {e}")

            def do_GET(self): self._handle("GET")

            def do_POST(self): self._handle("POST")

            def do_PUT(self): self._handle("PUT")

            def do_DELETE(self): self._handle("DELETE")

        self._tl = threading.local()     # per-request token (for proxying)
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        if tls is not None and tls.enabled():
            from ..utils.tlsutil import server_context
            # do_handshake_on_connect=False: the handshake runs in the
            # per-connection handler thread (with a deadline, below) —
            # on-connect it would run inside accept() on the single
            # serve_forever thread, letting one stalled client hang the
            # whole API (the RPC server takes the same care)
            self._httpd.socket = server_context(tls).wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if (self.tls is not None
                             and self.tls.enabled()) else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        # advertise this agent's HTTP address on its node so any server
        # can route client endpoints (logs/exec/fs/stats) to the owning
        # agent (reference: servers reach clients over persistent
        # nodeConns, nomad/server.go:151-153 + nomad/client_rpc.go; the
        # TPU build routes over the agent HTTP surface instead — unique.
        # prefix keeps it out of the computed class)
        if self.client is not None:
            host, port = self._httpd.server_address[:2]
            if host in ("0.0.0.0", "::", ""):
                # wildcard bind: advertise the node's fingerprinted
                # address so cross-host routing reaches THIS machine
                nets = self.client.node.node_resources.networks
                host = (nets[0].ip if nets and nets[0].ip
                        else "127.0.0.1")
            self.client.node.attributes["unique.advertise.http"] = \
                f"{host}:{port}"
            try:
                self.client.servers.register_node(self.client.node)
            except Exception:
                _log.warning("could not re-register node with advertise "
                             "address", exc_info=True)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)

    # ----------------------------------------------------------- dispatch
    def dispatch(self, method: str, path: str, body, token: str = ""):
        url = urlparse(path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        for pattern, methods in self._routes:
            m = pattern.match(url.path)
            if not m:
                continue
            fn = methods.get(method)
            if fn is None:
                raise HTTPError(405, f"method {method} not allowed")
            # Path params arrive percent-encoded; decode AFTER matching
            # so an encoded '/' (dispatched child ids embed one:
            # <parent>/dispatch-<...>) routes as one segment but reaches
            # the handler as the real id (reference: the agent mux
            # handles these ids the same way).  The ACL check receives
            # each segment decoded the same way so it authorizes the
            # exact id the handler will act on.
            segs = [unquote(s) for s in url.path.split("/")]
            self._enforce_acl(method, url.path, q, body, token, segs)
            self._tl.token = token
            return fn(q, body, *(unquote(g) for g in m.groups()))
        raise HTTPError(404, f"no handler for {url.path}")

    def _valid_migrate_token(self, alloc_prefix: str, token: str) -> bool:
        """Does `token` authorize disk-migration reads of this alloc?
        HMAC under the OWNING node's secret (structs.funcs
        generate_migrate_token)."""
        if not token:
            return False
        from ..structs.funcs import compare_migrate_token
        alloc = self.server.store.alloc_by_id(alloc_prefix)
        if alloc is None:
            return False
        node = self.server.store.node_by_id(alloc.node_id)
        if node is None or not node.secret_id:
            # never verify under a missing/empty secret — an empty HMAC
            # key would make the token forgeable from the alloc id
            return False
        return compare_migrate_token(alloc.id, node.secret_id, token)

    def _alloc_namespace(self, prefix: str) -> str:
        """Namespace of the alloc a client endpoint will act on; an
        AMBIGUOUS prefix is rejected here so the capability check can
        never authorize against a different alloc than the handler
        resolves (both layers demand uniqueness)."""
        exact = self.server.store.alloc_by_id(prefix)
        if exact is not None:
            return exact.namespace
        matches = {al.namespace for al in self.server.store.allocs()
                   if al.id.startswith(prefix)}
        if len(matches) > 1:
            raise HTTPError(400, f"ambiguous alloc prefix {prefix!r}")
        return next(iter(matches), "default")

    def _enforce_acl(self, method: str, path: str, q, body,
                     token: str, segs=None) -> None:
        """Route-class capability checks (reference: each agent endpoint
        resolves the token and asserts one capability — e.g.
        job_endpoint.go requires submit-job to register, read-job to
        get). Disabled servers skip enforcement entirely."""
        if not self.acl_enabled or path == "/v1/acl/bootstrap":
            return
        if segs is None:
            segs = path.split("/")
        # a migrate token is not an ACL token: it authorizes exactly
        # one alloc's fs reads for disk migration (reference:
        # fs_endpoint.go CompareMigrateToken) and is checked before
        # token resolution
        if (path.startswith("/v1/client/fs/")
                and self._valid_migrate_token(segs[-1], token)):
            return
        from ..acl import acl as aclmod
        a = self.server.resolve_token(token) if token else None
        if a is None:
            raise HTTPError(403, "token required" if not token
                            else "invalid token")
        # the namespace the request ACTUALLY operates on must match
        # what the handler will use: job handlers take the submitted
        # job's body namespace (otherwise ?namespace=dev would launder a
        # prod-namespace body past the check); every other handler reads
        # the query parameter, so the check does too
        ns = q.get("namespace", "default")
        if path.startswith(("/v1/jobs", "/v1/job/")) \
                and isinstance(body, dict):
            job_body = body.get("job") if isinstance(body.get("job"),
                                                     dict) else None
            if job_body and job_body.get("namespace"):
                ns = job_body["namespace"]
        if path.startswith("/v1/acl"):
            # token/policy management is management-only (reference:
            # acl_endpoint.go IsManagement checks) — operator scope
            # must NOT mint tokens or read secrets
            if not a.management:
                raise HTTPError(403, "management token required")
            return
        write = (method in ("POST", "PUT", "DELETE")
                 and path != "/v1/search")
        if "/exec" in path and path.startswith("/v1/client/allocation/"):
            target_ns = self._alloc_namespace(segs[4])
            if not a.allow_namespace_op(target_ns,
                                        aclmod.CAP_ALLOC_EXEC):
                raise HTTPError(403, "missing capability alloc-exec")
            return
        if path.startswith("/v1/client/fs/logs/"):
            # task logs often carry secrets: require read-logs in the
            # ALLOC's namespace (resolved server-side, not caller-said)
            target_ns = self._alloc_namespace(segs[-1])
            if not a.allow_namespace_op(target_ns,
                                        aclmod.CAP_READ_LOGS):
                raise HTTPError(403, "missing capability read-logs")
            return
        if path.startswith("/v1/client/fs/"):
            # ls/stat/cat/readat/stream over the alloc dir: read-fs in
            # the alloc's namespace (reference: fs_endpoint.go ACL), OR
            # a migrate token scoped to exactly this alloc — the
            # replacement alloc's disk-migration read authority
            # (reference: fs_endpoint.go checks CompareMigrateToken)
            target_ns = self._alloc_namespace(segs[-1])
            if not a.allow_namespace_op(target_ns, aclmod.CAP_READ_FS):
                raise HTTPError(403, "missing capability read-fs")
            return
        if (path == "/v1/client/stats"
                or path.endswith("/stats")
                and path.startswith("/v1/client/allocation/")):
            # host stats = node:read; alloc stats = read-job in ns
            if path == "/v1/client/stats":
                if not a.allow_node_read():
                    raise HTTPError(403, "node permission denied")
            else:
                target_ns = self._alloc_namespace(segs[4])
                if not a.allow_namespace_op(target_ns,
                                            aclmod.CAP_READ_JOB):
                    raise HTTPError(403, "missing capability read-job")
            return
        if path.startswith("/v1/secret"):
            # secrets are write-class EVEN TO READ: a read-only job
            # token must not exfiltrate raw secret values
            if not a.allow_namespace_op(ns, aclmod.CAP_SUBMIT_JOB):
                raise HTTPError(403, "secrets require namespace write")
            return
        if path.startswith("/v1/job/") and path.endswith("/dispatch"):
            # dispatching is its own capability (reference:
            # job_endpoint.go Dispatch requires dispatch-job)
            if not a.allow_namespace_op(ns, aclmod.CAP_DISPATCH_JOB):
                raise HTTPError(403, "missing capability dispatch-job")
            return
        if path.startswith(("/v1/jobs", "/v1/job/", "/v1/allocation",
                            "/v1/evaluation", "/v1/deployment",
                            "/v1/search", "/v1/volume", "/v1/service")):
            cap = (aclmod.CAP_SUBMIT_JOB if write
                   else aclmod.CAP_READ_JOB)
            if not a.allow_namespace_op(ns, cap):
                raise HTTPError(403, f"missing capability {cap}")
            return
        if path.startswith("/v1/node"):
            ok = a.allow_node_write() if write else a.allow_node_read()
            if not ok:
                raise HTTPError(403, "node permission denied")
            return
        if path.startswith("/v1/agent/pprof"):
            # runtime profiles expose internals: agent WRITE, like the
            # reference's ACL-gated pprof (pprof.go:58 AgentWrite)
            if not a.allow_agent_write():
                raise HTTPError(403, "agent write permission required")
            return
        if path.startswith("/v1/agent") or path == "/v1/metrics" \
                or path.startswith(("/v1/trace", "/v1/traces",
                                    "/v1/telemetry")):
            # traces expose job/placement internals cluster-wide, the
            # same blast radius as /v1/metrics + /v1/agent/monitor:
            # agent read to look, agent write to export to disk
            ok = a.allow_agent_write() if write else a.allow_agent_read()
            if not ok:
                raise HTTPError(403, "agent permission denied")
            return
        if path.startswith(("/v1/operator", "/v1/system")):
            ok = (a.allow_operator_write() if write
                  else a.allow_operator_read())
            if not ok:
                raise HTTPError(403, "operator permission denied")
            return

    # ------------------------------------------------------- blocking wait
    def _block(self, q: Dict[str, str], table: str) -> int:
        """Run the blocking-query wait; returns the index to report."""
        store = self.server.store
        try:
            min_index = int(q.get("index", 0) or 0)
            wait_s = min(parse_duration_s(q.get("wait", "5m")),
                         MAX_BLOCK_S)
        except (ValueError, JobspecParseError) as e:
            raise HTTPError(400, f"invalid blocking-query params: {e}")
        if min_index <= 0:
            return store.latest_index()
        import time as _t
        deadline = _t.monotonic() + wait_s
        while True:
            # capture the head BEFORE the table check so a write landing
            # between the reads wakes the wait immediately (same pattern
            # as Server.get_client_allocs)
            head = store.latest_index()
            if store.table_index(table) > min_index:
                break
            remain = deadline - _t.monotonic()
            if remain <= 0:
                break
            store.wait_for_change(head, remain)
        return max(store.table_index(table), min_index)

    # -------------------------------------------------------------- jobs
    def jobs_list(self, q, body):
        index = self._block(q, "jobs")
        prefix = q.get("prefix", "")
        jobs = [j for j in self.server.store.jobs()
                if j.id.startswith(prefix)]
        out = []
        for j in sorted(jobs, key=lambda j: j.id):
            summary = self.server.store.job_summary(j.namespace, j.id)
            out.append({
                "id": j.id, "name": j.name, "namespace": j.namespace,
                "type": j.type, "priority": j.priority, "status": j.status,
                "stop": j.stop, "version": j.version,
                "create_index": j.create_index,
                "modify_index": j.modify_index,
                "summary": to_wire(summary) if summary else None})
        return 200, out, index

    def jobs_register(self, q, body):
        if not body or "job" not in body:
            raise HTTPError(400, "body must carry a 'job' object")
        job = from_wire(Job, body["job"])
        errs = job.validate()
        if errs:
            raise HTTPError(400, "; ".join(errs))
        try:
            ev = self.server.register_job(
                job, enforce_index=bool(body.get("enforce_index")),
                check_index=int(body.get("job_modify_index", 0)))
        except JobValidationError as e:
            raise HTTPError(400, str(e))
        except ValueError as e:
            raise HTTPError(409, str(e))
        return 200, {"eval_id": ev.id if ev else "",
                     "job_modify_index": job.modify_index}, None

    def jobs_parse(self, q, body):
        if not body or "job_hcl" not in body:
            raise HTTPError(400, "body must carry 'job_hcl'")
        try:
            job = parse_job(body["job_hcl"])
        except JobspecParseError as e:
            raise HTTPError(400, str(e))
        return 200, to_wire(job), None

    def _get_job(self, job_id: str) -> Job:
        job = self.server.store.job_by_id("default", job_id)
        if job is None:
            raise HTTPError(404, f"job {job_id!r} not found")
        return job

    def job_get(self, q, body, job_id):
        index = self._block(q, "jobs")
        return 200, to_wire(self._get_job(job_id)), index

    def job_update(self, q, body, job_id):
        return self.jobs_register(q, body)

    def job_delete(self, q, body, job_id):
        purge = q.get("purge", "").lower() == "true"
        ev = self.server.deregister_job("default", job_id, purge=purge)
        return 200, {"eval_id": ev.id if ev else ""}, None

    def job_allocations(self, q, body, job_id):
        index = self._block(q, "allocs")
        allocs = self.server.store.allocs_by_job("default", job_id)
        return 200, [a.stub() for a in allocs], index

    def job_evaluations(self, q, body, job_id):
        index = self._block(q, "evals")
        evals = self.server.store.evals_by_job("default", job_id)
        return 200, [to_wire(e) for e in evals], index

    def job_deployments(self, q, body, job_id):
        index = self._block(q, "deployments")
        deps = self.server.store.deployments_by_job("default", job_id)
        return 200, [to_wire(d) for d in deps], index

    def job_summary(self, q, body, job_id):
        index = self._block(q, "jobs")
        s = self.server.store.job_summary("default", job_id)
        if s is None:
            raise HTTPError(404, f"no summary for {job_id!r}")
        return 200, to_wire(s), index

    def job_versions(self, q, body, job_id):
        versions = self.server.store.job_versions("default", job_id)
        return 200, [to_wire(j) for j in versions], None

    def job_periodic_force(self, q, body, job_id):
        child = self.server.periodic.force_launch("default", job_id)
        if child is None:
            raise HTTPError(404,
                            f"{job_id!r} is not a tracked periodic job")
        return 200, {"child_job_id": child.id}, None

    def job_plan(self, q, body, job_id):
        """Dry-run the scheduler (reference: Job.Plan)."""
        if not body or "job" not in body:
            raise HTTPError(400, "body must carry a 'job' object")
        from ..scheduler.base import new_scheduler
        from ..structs import EVAL_STATUS_PENDING, EVAL_TRIGGER_JOB_REGISTER
        job = from_wire(Job, body["job"])
        job.canonicalize()
        planner = _DryRunPlanner(self.server.store)
        ev = Evaluation(namespace=job.namespace, job_id=job.id,
                        type=job.type, priority=job.priority,
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        status=EVAL_STATUS_PENDING, annotate_plan=True)
        # plan against a snapshot with the SUBMITTED job overlaid, so the
        # dry-run sees the proposed version without writing state
        snap = self.server.store.snapshot()
        snap_index = snap.index
        current = snap.job_by_id(job.namespace, job.id)
        job.version = (current.version + 1) if current else 0
        snap._t["jobs"] = dict(snap._t["jobs"])
        snap._t["jobs"][(job.namespace, job.id)] = job
        # what-if overlay solve (ISSUE 7): ride the first worker's
        # resident solver through its read-only plan view — the dry run
        # answers from the delta-maintained template at steady-state
        # speed, against a copy-on-read usage overlay, and never
        # touches the carried world state
        solver = None
        workers = getattr(self.server, "workers", None)
        if workers:
            solver = workers[0].fleet_solver().plan_view()
        sched = new_scheduler(job.type, snap, planner, solver=solver)
        planner_err = sched.process(ev)
        ann = None
        if planner.plans and planner.plans[-1].annotations is not None:
            ann = to_wire(planner.plans[-1].annotations)
        diff = None
        if body.get("diff", True):
            from ..structs.diff import job_diff
            diff = job_diff(current, job)
        return 200, {
            "annotations": ann,
            "diff": diff,
            "created_evals": [to_wire(e) for e in planner.evals],
            "diff_seen_index": snap_index,
            "error": str(planner_err) if planner_err else "",
        }, None

    # ------------------------------------------------------------- evals
    def evals_list(self, q, body):
        index = self._block(q, "evals")
        evals = sorted(self.server.store.evals(), key=lambda e: e.id)
        return 200, [to_wire(e) for e in evals], index

    def eval_get(self, q, body, eval_id):
        index = self._block(q, "evals")    # wait BEFORE reading
        ev = self.server.store.eval_by_id(eval_id)
        if ev is None:
            raise HTTPError(404, f"eval {eval_id!r} not found")
        return 200, to_wire(ev), index

    def eval_allocations(self, q, body, eval_id):
        allocs = self.server.store.allocs_by_eval(eval_id)
        return 200, [a.stub() for a in allocs], None

    # ------------------------------------------------------------ allocs
    def allocs_list(self, q, body):
        index = self._block(q, "allocs")
        prefix = q.get("prefix", "")
        allocs = [a for a in self.server.store.allocs()
                  if a.id.startswith(prefix)]
        return 200, [a.stub() for a in sorted(allocs, key=lambda a: a.id)], \
            index

    def alloc_get(self, q, body, alloc_id):
        index = self._block(q, "allocs")
        a = self.server.store.alloc_by_id(alloc_id)
        if a is None:
            raise HTTPError(404, f"alloc {alloc_id!r} not found")
        return 200, to_wire(a), index

    def alloc_stop(self, q, body, alloc_id):
        ev = self.server.stop_alloc(alloc_id)
        if ev is None:
            raise HTTPError(404, f"alloc {alloc_id!r} not found")
        return 200, {"eval_id": ev.id}, None

    # ------------------------------------------------------------- nodes
    def nodes_list(self, q, body):
        index = self._block(q, "nodes")
        prefix = q.get("prefix", "")
        nodes = [n for n in self.server.store.nodes()
                 if n.id.startswith(prefix)]
        out = [{"id": n.id, "name": n.name, "datacenter": n.datacenter,
                "node_class": n.node_class, "status": n.status,
                "scheduling_eligibility": n.scheduling_eligibility,
                "drain": n.drain_strategy is not None,
                "modify_index": n.modify_index}
               for n in sorted(nodes, key=lambda n: n.id)]
        return 200, out, index

    def _resolve_node(self, node_id: str) -> str:
        node = self.server.store.node_by_id(node_id)
        if node is not None:
            return node.id
        matches = [n.id for n in self.server.store.nodes()
                   if n.id.startswith(node_id)]
        if len(matches) == 1:
            return matches[0]
        raise HTTPError(404, f"node {node_id!r} not found")

    def node_get(self, q, body, node_id):
        index = self._block(q, "nodes")
        node = self.server.store.node_by_id(self._resolve_node(node_id))
        return 200, to_wire(node), index

    def node_allocations(self, q, body, node_id):
        index = self._block(q, "allocs")
        allocs = self.server.store.allocs_by_node(
            self._resolve_node(node_id))
        return 200, [a.stub() for a in allocs], index

    def node_drain(self, q, body, node_id):
        from ..structs import DrainStrategy
        node_id = self._resolve_node(node_id)
        spec = (body or {}).get("drain_spec")
        strategy = None
        if spec is not None:
            strategy = DrainStrategy(
                deadline_s=float(spec.get("deadline_s", 3600.0)),
                ignore_system_jobs=bool(spec.get("ignore_system_jobs",
                                                 False)))
        index = self.server.update_node_drain(
            node_id, strategy,
            mark_eligible=bool((body or {}).get("mark_eligible", False)))
        return 200, {"node_modify_index": index}, None

    def node_eligibility(self, q, body, node_id):
        node_id = self._resolve_node(node_id)
        elig = (body or {}).get("eligibility", "")
        if elig not in ("eligible", "ineligible"):
            raise HTTPError(400, "eligibility must be eligible|ineligible")
        index = self.server.update_node_eligibility(node_id, elig)
        return 200, {"node_modify_index": index}, None

    def node_evaluate(self, q, body, node_id):
        node = self.server.store.node_by_id(self._resolve_node(node_id))
        self.server._create_node_evals(node, self.server.store.latest_index())
        return 200, {}, None

    # -------------------------------------------------------- deployments
    def deployments_list(self, q, body):
        index = self._block(q, "deployments")
        deps = sorted(self.server.store.deployments(), key=lambda d: d.id)
        return 200, [to_wire(d) for d in deps], index

    def _resolve_deployment(self, dep_id: str):
        d = self.server.store.deployment_by_id(dep_id)
        if d is not None:
            return d
        matches = [d for d in self.server.store.deployments()
                   if d.id.startswith(dep_id)]
        if len(matches) == 1:
            return matches[0]
        raise HTTPError(404, f"deployment {dep_id!r} not found")

    def deployment_get(self, q, body, dep_id):
        index = self._block(q, "deployments")
        return 200, to_wire(self._resolve_deployment(dep_id)), index

    def deployment_promote(self, q, body, dep_id):
        dep = self._resolve_deployment(dep_id)
        try:
            ev = self.server.promote_deployment(dep.id, all_groups=True)
        except ValueError as e:
            raise HTTPError(409, str(e))
        return 200, {"eval_id": ev.id if ev else ""}, None

    def deployment_fail(self, q, body, dep_id):
        dep = self._resolve_deployment(dep_id)
        ev = self.server.fail_deployment(dep.id)
        return 200, {"eval_id": ev.id if ev else ""}, None

    def deployment_allocations(self, q, body, dep_id):
        dep = self._resolve_deployment(dep_id)
        allocs = self.server.store.allocs_by_deployment(dep.id)
        return 200, [a.stub() for a in allocs], None

    # ------------------------------------------------------ agent / misc
    def agent_self(self, q, body):
        out = {"server": {"enabled": True,
                          "workers": len(self.server.workers)},
               "client": None, "version": "0.1.0"}
        if self.client is not None:
            out["client"] = {"enabled": True,
                            "node_id": self.client.node.id,
                            "allocs": self.client.num_allocs()}
        return 200, out, None

    def agent_members(self, q, body):
        """Server membership (reference: /v1/agent/members from serf).
        With gossip attached the real member list is served; a
        standalone dev server reports itself."""
        gossip = getattr(self.server, "gossip", None)
        if gossip is not None:
            leader_id = self.server.raft.leader_id
            return 200, {"members": [
                {"name": m.id, "addr": list(m.addr),
                 "region": m.region, "status": m.status,
                 "leader": m.id == leader_id}
                for m in gossip.members()]}, None
        return 200, {"members": [{
            "name": self.server.raft.id, "status": "alive",
            "leader": self.server.is_leader()}]}, None

    def regions_list(self, q, body):
        """Known federation regions, sorted (reference:
        nomad/regions_endpoint.go Regions.List from the WAN serf pool;
        a standalone server reports its own region)."""
        gossip = getattr(self.server, "gossip", None)
        if gossip is not None:
            try:
                return 200, sorted(set(gossip.regions())), None
            except Exception:
                pass
        region = getattr(self.server, "region", "") or "global"
        return 200, [region], None

    def status_leader(self, q, body):
        return 200, "127.0.0.1:4647", None

    def status_peers(self, q, body):
        return 200, ["127.0.0.1:4647"], None

    def metrics(self, q, body):
        return 200, global_metrics.dump(), None

    def handle_prometheus(self, handler) -> None:
        """/v1/metrics?format=prometheus — text exposition 0.0.4
        (served outside the JSON dispatch for the content type)."""
        from urllib.parse import parse_qs, urlparse
        url = urlparse(handler.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        token = handler.headers.get("X-Nomad-Token", "")
        try:
            self._enforce_acl("GET", "/v1/metrics", q, None, token)
            data = global_metrics.prometheus().encode()
            code, ctype = 200, ("text/plain; version=0.0.4; "
                                "charset=utf-8")
        except HTTPError as e:
            data = json.dumps({"error": e.msg}).encode()
            code, ctype = e.code, "application/json"
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    # -------------------------------------------------- flight recorder
    def trace_get(self, q, body, trace_id):
        """/v1/trace/:id — one eval's full recorded timeline (the
        trace id IS the eval id)."""
        from ..utils.tracing import global_tracer
        spans = global_tracer.get(trace_id)
        if spans is None:
            raise HTTPError(404, f"no trace {trace_id!r}")
        return 200, {"trace_id": trace_id, "spans": spans}, None

    def traces_list(self, q, body):
        """/v1/traces — newest-first summaries plus recorder stats."""
        from ..utils.tracing import global_tracer
        try:
            limit = int(q.get("limit", 50))
        except ValueError:
            raise HTTPError(400, "limit must be an integer")
        return 200, {"stats": global_tracer.stats(),
                     "traces": global_tracer.traces(limit)}, None

    def trace_corpus(self, q, body):
        """/v1/trace/corpus — the recorded per-eval placement corpus
        (ROADMAP item 1's training substrate).  GET returns the rows;
        POST with {"path": ...} exports them as JSONL to that path on
        the agent host and returns the row count."""
        from ..utils.tracing import global_tracer
        if body is not None and isinstance(body, dict) \
                and body.get("path"):
            try:
                n = global_tracer.write_corpus(body["path"])
            except OSError as e:
                raise HTTPError(400, f"cannot write corpus: {e}")
            return 200, {"path": body["path"], "rows": n}, None
        return 200, {"rows": global_tracer.corpus_rows()}, None

    def agent_events(self, q, body):
        """/v1/agent/events — the mesh event log (elastic grow/shrink/
        move/fail/recover transitions with measured bytes/durations).
        `?since_seq=N` pages by cursor: only events with seq > N, plus
        the log's `last_seq` so pollers resume without overlap."""
        from ..utils.tracing import global_mesh_events
        try:
            limit = int(q.get("limit", 256))
            since_seq = int(q.get("since_seq", 0))
        except ValueError:
            raise HTTPError(400, "limit/since_seq must be integers")
        return 200, {
            "events": global_mesh_events.events(
                limit, kind=q.get("kind") or None,
                since_seq=since_seq),
            "last_seq": global_mesh_events.last_seq}, None

    # ------------------------------------------------- telemetry plane
    def telemetry_health(self, q, body):
        """/v1/telemetry/health — the latest fleet health report
        (server telemetry tick) plus the serving-tier SLO status and
        the recorder/series bookkeeping."""
        from ..telemetry.series import global_series
        from ..utils.tracing import global_tracer
        serving = getattr(self.server, "serving", None)
        health_fn = getattr(self.server, "last_health", None)
        return 200, {
            "health": health_fn() if callable(health_fn) else None,
            "serving": serving.stats() if serving is not None else None,
            "tracer": global_tracer.stats(),
            "series": global_series.stats(),
        }, None

    def telemetry_series(self, q, body):
        """/v1/telemetry/series?name=&res=&since= — one named series
        from the multi-resolution ring (bucket starts > since)."""
        from ..telemetry.series import global_series
        name = q.get("name", "")
        if not name:
            return 200, {"names": global_series.names()}, None
        try:
            res = int(q.get("res", 1))
            since = float(q.get("since", 0))
        except ValueError:
            raise HTTPError(400, "res/since must be numeric")
        try:
            points = global_series.points(name, res=res, since=since)
        except KeyError:
            raise HTTPError(
                400, f"unknown resolution {res}s (configured: "
                     f"{[r for r, _ in global_series.resolutions]})")
        return 200, {"name": name, "res": res, "points": points}, None

    # ----------------------------------------------- agent monitor/pprof
    def handle_monitor(self, handler) -> None:
        """/v1/agent/monitor — live log streaming (reference:
        command/agent/monitor/monitor.go:14 + agent_endpoint.go
        AgentMonitor): replay the ring of recent lines, then follow new
        ones until the client disconnects.  ?log_level= filters;
        ?node_id= routes to that node's agent and relays its stream."""
        import queue as _q
        from urllib.parse import parse_qs, urlparse
        from ..utils.monitor import global_monitor, parse_level

        url = urlparse(handler.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        token = handler.headers.get("X-Nomad-Token", "")
        try:
            self._enforce_acl("GET", "/v1/agent/monitor", q, None, token)
        except HTTPError as e:
            data = json.dumps({"error": e.msg}).encode()
            handler.send_response(e.code)
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
            return

        node_id = q.get("node_id", "")
        if node_id and not (self.client is not None
                            and self.client.node.id.startswith(node_id)):
            self._relay_monitor(handler, node_id, q, token)
            return

        min_level = parse_level(q.get("log_level", "debug"))
        # bounded follow for polling clients/tests; 0 = until disconnect
        try:
            deadline_s = float(q.get("duration_s", 0) or 0)
        except ValueError:
            deadline_s = 0.0
        sub = global_monitor.subscribe(min_level=min_level)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type",
                                "text/plain; charset=utf-8")
            handler.send_header("X-Accel-Buffering", "no")
            handler.end_headers()
            end = (time.monotonic() + deadline_s) if deadline_s else None
            while True:
                timeout = 1.0
                if end is not None:
                    timeout = min(timeout, end - time.monotonic())
                    if timeout <= 0:
                        return
                try:
                    levelno, line = sub.get(timeout=max(timeout, 0.01))
                except _q.Empty:
                    continue
                if levelno < min_level:
                    continue
                handler.wfile.write(line.encode() + b"\n")
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            return
        finally:
            global_monitor.unsubscribe(sub)

    def _peer_conn(self, addr: str, timeout: float):
        """HTTP(S) connection to a peer agent: when this cluster runs
        TLS, every agent listener is HTTPS and relays must present this
        agent's certificate too."""
        import http.client as hc
        if self.tls is not None and self.tls.enabled():
            from ..utils.tlsutil import client_context
            if getattr(self, "_relay_ctx", None) is None:
                self._relay_ctx = client_context(self.tls)
            return hc.HTTPSConnection(addr, timeout=timeout,
                                      context=self._relay_ctx)
        return hc.HTTPConnection(addr, timeout=timeout)

    def _relay_monitor(self, handler, node_id: str, q, token) -> None:
        """Stream another agent's monitor through this one (the
        server-side hop of the reference's remote monitor)."""
        import http.client as hc
        from urllib.parse import urlencode
        matches = [n for n in self.server.store.nodes()
                   if n.id.startswith(node_id)]
        if len(matches) != 1:
            code = 400 if matches else 404
            data = json.dumps({"error": f"node {node_id!r} "
                               + ("ambiguous" if matches
                                  else "not found")}).encode()
            handler.send_response(code)
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
            return
        addr = matches[0].attributes.get("unique.advertise.http", "")
        if not addr:
            handler.send_response(502)
            handler.end_headers()
            return
        qs = urlencode(dict(q, _routed="1"))
        conn = self._peer_conn(addr, timeout=330.0)
        try:
            conn.request("GET", f"/v1/agent/monitor?{qs}",
                         headers={"X-Nomad-Token": token or ""})
            resp = conn.getresponse()
            handler.send_response(resp.status)
            handler.send_header("Content-Type",
                                "text/plain; charset=utf-8")
            handler.end_headers()
            while True:
                chunk = resp.read(4096)
                if not chunk:
                    return
                handler.wfile.write(chunk)
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            return
        finally:
            conn.close()

    def agent_pprof(self, q, body, profile):
        """/v1/agent/pprof/* (reference: command/agent/pprof/pprof.go:58
        — ACL-gated runtime profiles).  Profiles: `profile` (sampled
        CPU stacks, ?seconds=), `goroutine` (all-thread dump),
        `cmdline`."""
        from ..utils import monitor as monmod
        if profile == "profile":
            try:
                seconds = min(float(q.get("seconds", 1.0)), 30.0)
            except ValueError:
                raise HTTPError(400, "seconds must be a number")
            if q.get("mode") == "solver":
                return self._solver_profile(q, seconds)
            hz = 100
            text = monmod.sample_profile(seconds=seconds, hz=hz)
            return 200, {"profile": text, "seconds": seconds,
                         "hz": hz}, None
        if profile == "goroutine":
            return 200, {"stacks": monmod.thread_dump(),
                         "threads": threading.active_count()}, None
        if profile == "cmdline":
            return 200, {"cmdline": " ".join(sys.argv)}, None
        raise HTTPError(404, f"unknown profile {profile!r} "
                             "(have: profile, goroutine, cmdline)")

    def _solver_profile(self, q, seconds: float):
        """/v1/agent/pprof/profile?mode=solver — wrap a steady-state
        solve window in `jax.profiler.trace` and return the trace
        artifact path (TensorBoard/XPlane format).  With ?job_id= the
        window is driven by repeated what-if plan solves of that job
        through the worker's read-only plan view (zero writes);
        without, the window passively captures whatever the live
        workers solve.  501 when the installed jax has no profiler."""
        try:
            import jax
            tracer = jax.profiler.trace
        except (ImportError, AttributeError):
            raise HTTPError(501, "jax.profiler is not available in "
                                 "this build")
        import tempfile
        import time as _t
        logdir = tempfile.mkdtemp(prefix="nomad-tpu-solver-profile-")
        job_id = q.get("job_id", "")
        namespace = q.get("namespace", "default")
        solves = 0
        deadline = None
        try:
            with tracer(logdir):
                deadline = _t.monotonic() + seconds
                if job_id:
                    solves = self._drive_plan_solves(
                        namespace, job_id, deadline)
                else:
                    _t.sleep(seconds)
        except Exception as e:
            raise HTTPError(500, f"profiler trace failed: {e}")
        return 200, {"artifact": logdir, "seconds": seconds,
                     "mode": "solver", "solves": solves}, None

    def _drive_plan_solves(self, namespace: str, job_id: str,
                           deadline: float) -> int:
        """Steady-state solve load for the profiler window: repeated
        dry-run (what-if overlay) solves of an existing job."""
        import time as _t
        from ..scheduler.base import new_scheduler
        from ..structs import (EVAL_STATUS_PENDING,
                               EVAL_TRIGGER_JOB_REGISTER)
        job = self.server.store.job_by_id(namespace, job_id)
        if job is None:
            raise HTTPError(404, f"unknown job {job_id!r}")
        workers = getattr(self.server, "workers", None)
        solver = workers[0].fleet_solver().plan_view() if workers \
            else None
        solves = 0
        while _t.monotonic() < deadline:
            planner = _DryRunPlanner(self.server.store)
            ev = Evaluation(namespace=namespace, job_id=job_id,
                            type=job.type, priority=job.priority,
                            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                            status=EVAL_STATUS_PENDING,
                            annotate_plan=True)
            sched = new_scheduler(job.type, self.server.store.snapshot(),
                                  planner, solver=solver)
            sched.process(ev)
            solves += 1
        return solves

    def system_gc(self, q, body):
        self.server.force_gc()
        return 200, {}, None

    def search(self, q, body, *groups):
        """Prefix search over the ID spaces (reference:
        nomad/search_endpoint.go)."""
        if not body or "prefix" not in body:
            raise HTTPError(400, "body must carry 'prefix'")
        from ..server.search import search as do_search
        try:
            matches, truncations = do_search(
                self.server.store, body["prefix"],
                body.get("context", "all") or "all",
                namespace=body.get("namespace", "default"))
        except ValueError as e:
            raise HTTPError(400, str(e))
        return 200, {"matches": matches,
                     "truncations": truncations}, \
            self.server.store.latest_index()

    def client_logs(self, q, body, alloc_id):
        """Task log contents (reference: client/fs_endpoint.go logs;
        plain read of the alloc dir's rotated log files, ?task= and
        ?type=stdout|stderr, tail via ?offset/?limit or ?tail_lines).
        Routed to the owning agent when the alloc is not local."""
        remote = self._client_route(alloc_id, q)
        if remote is not None:
            return self._proxy_client_http(
                remote, "GET", f"/v1/client/fs/logs/{alloc_id}", q, None)
        runner = self._local_runner(alloc_id)
        names = [t.name for t in
                 (runner.alloc.job.lookup_task_group(
                     runner.alloc.task_group).tasks
                  if runner.alloc.job else [])]
        task = q.get("task")
        if not task:
            if len(names) != 1:
                raise HTTPError(400, "specify ?task= (multiple tasks)")
            task = names[0]
        elif task not in names:
            # also forecloses path traversal through the task name
            raise HTTPError(404, f"unknown task {task!r}")
        kind = q.get("type", "stdout")
        if kind not in ("stdout", "stderr"):
            raise HTTPError(400, "type must be stdout|stderr")
        path = (runner.alloc_dir.stdout_path(task) if kind == "stdout"
                else runner.alloc_dir.stderr_path(task))
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            data = b""
        tail = q.get("tail_lines")
        if tail:
            try:
                n = int(tail)
                if n <= 0:
                    raise ValueError
            except ValueError:
                raise HTTPError(400, "tail_lines must be a positive int")
            data = b"\n".join(data.splitlines()[-n:])
        text = data.decode("utf-8", errors="replace")
        return 200, {"task": task, "type": kind, "data": text,
                     "size": len(data)}, None

    # ---------------------------------------------- fs + stats surface
    def _local_runner(self, alloc_id: str):
        """The local alloc runner for an id/prefix, or 404."""
        if self.client is None:
            raise HTTPError(400, "no client agent on this node")
        runner = self.client.get_alloc_runner(alloc_id)
        if runner is None:
            matches = [r for aid, r in list(self.client.runners.items())
                       if aid.startswith(alloc_id)]
            if len(matches) != 1:
                raise HTTPError(404, f"alloc {alloc_id} not on node")
            runner = matches[0]
        return runner

    def _fs_call(self, q, alloc_id: str, verb: str, fn):
        """Route-or-serve shared shell for the fs verbs (reference:
        command/agent/fs_endpoint.go dispatching to the owning client
        via server RPC)."""
        from ..client import fs as fsmod
        remote = self._client_route(alloc_id, q)
        if remote is not None:
            return self._proxy_client_http(
                remote, "GET", f"/v1/client/fs/{verb}/{alloc_id}",
                q, None)
        runner = self._local_runner(alloc_id)
        try:
            return fn(fsmod, runner)
        except fsmod.FSError as e:
            raise HTTPError(e.code, e.msg)

    def client_fs_ls(self, q, body, alloc_id):
        """Directory listing inside the alloc dir (reference:
        client/fs_endpoint.go List)."""
        return self._fs_call(q, alloc_id, "ls", lambda fsmod, r: (
            200, {"files": fsmod.list_dir(r.alloc_dir.root,
                                          q.get("path", "/"))}, None))

    def client_fs_stat(self, q, body, alloc_id):
        """Stat one path (reference: client/fs_endpoint.go Stat)."""
        return self._fs_call(q, alloc_id, "stat", lambda fsmod, r: (
            200, {"file": fsmod.stat_path(r.alloc_dir.root,
                                          q.get("path", "/"))}, None))

    def client_fs_cat(self, q, body, alloc_id):
        """Whole-file read (reference: fs_endpoint.go Cat) — base64 in
        JSON so it survives the routing proxy byte-exact.  `size` is
        the FILE's size and `truncated` is explicit so callers can
        page the remainder with readat (the SDK does)."""
        import base64

        def run(fsmod, r):
            st = fsmod.stat_path(r.alloc_dir.root, q.get("path", "/"))
            data = fsmod.read_at(r.alloc_dir.root, q.get("path", "/"),
                                 0, 1 << 24)
            return 200, {"data": base64.b64encode(data).decode(),
                         "encoding": "base64", "size": st["size"],
                         "truncated": len(data) < st["size"]}, None
        return self._fs_call(q, alloc_id, "cat", run)

    def client_fs_readat(self, q, body, alloc_id):
        """Bounded range read (reference: fs_endpoint.go ReadAt)."""
        import base64

        def run(fsmod, r):
            try:
                offset = int(q.get("offset", 0))
                limit = int(q.get("limit", 1 << 20))
            except ValueError:
                raise HTTPError(400, "offset/limit must be integers")
            data = fsmod.read_at(r.alloc_dir.root, q.get("path", "/"),
                                 offset, limit)
            return 200, {"data": base64.b64encode(data).decode(),
                         "encoding": "base64", "offset": offset,
                         "size": len(data)}, None
        return self._fs_call(q, alloc_id, "readat", run)

    def client_fs_stream(self, q, body, alloc_id):
        """Follow a growing file: long-poll returning bytes past
        ?offset (reference: fs_endpoint.go Stream's follow frames,
        JSON-framed so it routes like everything else)."""
        import base64

        def run(fsmod, r):
            try:
                offset = int(q.get("offset", 0))
                wait_s = float(q.get("wait", 2.0))
            except ValueError:
                raise HTTPError(400, "offset/wait must be numeric")
            res = fsmod.stream_from(r.alloc_dir.root,
                                    q.get("path", "/"), offset, wait_s)
            return 200, {"data": base64.b64encode(res["data"]).decode(),
                         "encoding": "base64",
                         "offset": res["offset"],
                         "size": res["size"]}, None
        return self._fs_call(q, alloc_id, "stream", run)

    def client_host_stats(self, q, body):
        """Host resource gauges (reference: /v1/client/stats,
        client/stats/host.go); ?node_id= routes to that node's agent."""
        from ..client import fs as fsmod
        node_prefix = q.get("node_id", "")
        if (node_prefix and not q.get("_routed")
                and (self.client is None
                     or not self.client.node.id.startswith(node_prefix))):
            nodes = [n for n in self.server.store.nodes()
                     if n.id.startswith(node_prefix)]
            if len(nodes) != 1:
                raise HTTPError(404 if not nodes else 400,
                                f"node {node_prefix!r} "
                                + ("not found" if not nodes
                                   else "is ambiguous"))
            addr = nodes[0].attributes.get("unique.advertise.http", "")
            if not addr:
                raise HTTPError(502, "node has no advertised agent "
                                     "address")
            return self._proxy_client_http(addr, "GET",
                                           "/v1/client/stats", q, None)
        if self.client is None:
            raise HTTPError(400, "no client agent on this node")
        return 200, fsmod.host_stats(self.client.data_dir), None

    def client_alloc_stats(self, q, body, alloc_id):
        """Per-task resource usage for one alloc (reference:
        client/allocrunner stats hooks + pid_collector)."""
        from ..client import fs as fsmod
        remote = self._client_route(alloc_id, q)
        if remote is not None:
            return self._proxy_client_http(
                remote, "GET",
                f"/v1/client/allocation/{alloc_id}/stats", q, None)
        runner = self._local_runner(alloc_id)
        tasks = {}
        for tr in runner.task_runners:
            ds = (tr.handle.driver_state if tr.handle else None) or {}
            pid = ds.get("pid")
            tasks[tr.task.name] = (fsmod.task_stats(pid) if pid
                                   else None)
        return 200, {"alloc_id": runner.alloc.id, "tasks": tasks}, None

    def handle_exec_ws(self, handler) -> None:
        """Interactive exec over a websocket (reference: the alloc-exec
        stream — api/allocations.go Exec websocket frames bridged to
        plugins/drivers/execstreaming.go ExecTaskStreaming).

        Frames: client sends {"stdin": {"data": b64}} |
        {"stdin": {"close": true}} | {"tty_size": {"width", "height"}};
        server sends {"stdout": {"data": b64}} | {"exit": {"code": N}}.
        """
        import base64
        import select
        from urllib.parse import parse_qs, urlsplit

        from .websocket import WebSocketClosed, server_handshake

        parts = urlsplit(handler.path)
        q = {k: v[-1] for k, v in parse_qs(parts.query).items()}
        token = handler.headers.get("X-Nomad-Token", "")
        if handler.headers.get("X-Nomad-Routed"):
            q["_routed"] = "1"      # never bounce a forwarded upgrade

        def refuse(code: int, msg: str) -> None:
            data = json.dumps({"error": msg}).encode()
            resp = (f"HTTP/1.1 {code} Error\r\n"
                    "Content-Type: application/json\r\n"
                    f"Content-Length: {len(data)}\r\n\r\n")
            handler.connection.sendall(resp.encode() + data)

        remote = None
        try:
            self._enforce_acl("POST", parts.path, q, None, token)
            alloc_id = parts.path.split("/")[4]
            remote = self._client_route(alloc_id, q)
            if remote is None:
                tr = self._resolve_task_runner(alloc_id, q.get("task"))
            cmd = json.loads(q.get("command") or "[]")
            if not isinstance(cmd, list) or not cmd:
                raise HTTPError(400, "query param 'command' must be a "
                                     "non-empty JSON array")
            tty = q.get("tty", "true") != "false"
            if not handler.headers.get("Sec-WebSocket-Key"):
                raise HTTPError(400, "missing Sec-WebSocket-Key")
        except HTTPError as e:
            refuse(e.code, e.msg)
            return
        except Exception as e:
            refuse(500, str(e))
            return

        if remote is not None:
            # splice the upgrade through to the owning agent
            # (reference: the alloc-exec stream forwarded over
            # nodeConns — nomad/client_alloc_endpoint.go)
            try:
                self._tunnel_ws(handler, remote)
            except OSError as e:
                refuse(502, f"routing to {remote} failed: {e}")
            return

        # spawn only after the request is fully validated; if the
        # upgrade still fails mid-handshake, reap the process instead
        # of leaking it
        try:
            stream = tr.driver.exec_task_streaming(
                tr.task_id, [str(c) for c in cmd], tty=tty)
        except Exception as e:
            refuse(500, str(e))
            return
        try:
            ws = server_handshake(handler)
        except Exception:
            stream.terminate()
            stream.close()
            raise
        stop = threading.Event()

        def pump_output():
            try:
                while not stop.is_set():
                    r, _, _ = select.select([stream.fd], [], [], 0.2)
                    if not r:
                        if stream.poll() is not None:
                            break
                        continue
                    try:
                        data = os.read(stream.fd, 65536)
                    except OSError:      # pty closed on child exit
                        break
                    if not data:
                        break
                    ws.send_json({"stdout": {
                        "data": base64.b64encode(data).decode()}})
            except WebSocketClosed:
                pass
            finally:
                # drain the exit code (bounded — the child may have
                # been killed by close)
                code = stream.poll()
                for _ in range(50):
                    if code is not None:
                        break
                    time.sleep(0.1)
                    code = stream.poll()
                try:
                    ws.send_json({"exit": {
                        "code": -1 if code is None else code}})
                except WebSocketClosed:
                    pass
                ws.send_close()

        out_t = threading.Thread(target=pump_output, daemon=True)
        out_t.start()
        try:
            while True:
                msg = ws.recv_json()
                if msg is None:
                    break
                if "stdin" in msg:
                    st = msg["stdin"]
                    if st.get("close"):
                        stream.close_stdin()
                    elif st.get("data"):
                        try:
                            os.write(stream.fd,
                                     base64.b64decode(st["data"]))
                        except OSError:
                            break
                elif "tty_size" in msg:
                    sz = msg["tty_size"]
                    stream.resize(int(sz.get("width", 80)),
                                  int(sz.get("height", 24)))
        finally:
            stop.set()
            if stream.poll() is None:
                stream.terminate()
            out_t.join(timeout=6.0)
            stream.close()

    # -------------------------------------------- server->client routing
    def _client_route(self, alloc_prefix: str,
                      q: Optional[Dict[str, str]] = None
                      ) -> Optional[str]:
        """Which agent owns this alloc?  None = this one (serve
        locally); otherwise the owning node's advertised HTTP address
        to route to (reference: nomad/client_rpc.go — any server
        forwards client RPCs to the node over a persistent connection;
        here the agent's advertised HTTP surface is the conduit)."""
        if q and q.get("_routed"):
            # already forwarded once: answer locally or fail — never
            # bounce a request around the cluster
            return None
        if self.client is not None:
            if (self.client.get_alloc_runner(alloc_prefix) is not None
                or any(aid.startswith(alloc_prefix)
                       for aid in list(self.client.runners))):
                return None
        exact = self.server.store.alloc_by_id(alloc_prefix)
        matches = [exact] if exact is not None else [
            al for al in self.server.store.allocs()
            if al.id.startswith(alloc_prefix)]
        # prefer live allocs, but still route terminal ones — the
        # owning agent keeps terminal runners (and their logs) around
        live = [al for al in matches if not al.terminal_status()]
        pool = live or matches
        if len(pool) > 1:
            raise HTTPError(400, f"ambiguous alloc prefix "
                                 f"{alloc_prefix!r}")
        if not pool:
            raise HTTPError(404, f"alloc {alloc_prefix} not found")
        alloc = pool[0]
        if (self.client is not None
                and alloc.node_id == self.client.node.id):
            return None
        node = self.server.store.node_by_id(alloc.node_id)
        addr = (node.attributes.get("unique.advertise.http", "")
                if node else "")
        if not addr:
            raise HTTPError(
                502, f"node {alloc.node_id[:8]} has no advertised "
                     "agent address to route to")
        return addr

    def _proxy_client_http(self, remote: str, method: str, path: str,
                           q: Dict[str, str], body):
        """Forward one client-endpoint request to the owning agent and
        relay its JSON reply."""
        from urllib.parse import urlencode
        qs = urlencode(dict(q, _routed="1"))
        # the forwarded request may itself run a command with a
        # caller-chosen timeout; allow it to finish plus slack
        try:
            budget = float((body or {}).get("timeout_s", 0)) + 30.0
        except (TypeError, ValueError):
            budget = 30.0
        conn = self._peer_conn(remote, timeout=max(60.0, budget))
        try:
            conn.request(
                method, f"{path}?{qs}",
                body=(json.dumps(body) if body is not None else None),
                headers={"X-Nomad-Token":
                         getattr(self._tl, "token", "") or "",
                         "Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        except OSError as e:
            raise HTTPError(502, f"routing to {remote} failed: {e}")
        finally:
            conn.close()
        try:
            payload = json.loads(data) if data else {}
        except json.JSONDecodeError:
            payload = {"error": data.decode("utf-8", "replace")}
        if resp.status != 200:
            raise HTTPError(resp.status,
                            payload.get("error", f"agent {remote} "
                                                 f"replied {resp.status}"))
        idx = resp.getheader("X-Nomad-Index")
        return 200, payload, (int(idx) if idx else None)

    def _tunnel_ws(self, handler, remote: str) -> None:
        """Splice a websocket upgrade through to the owning agent:
        replay the request bytes, then pump both directions until
        either side closes (the exec stream's routed form)."""
        import socket as _socket
        host, _, port = remote.rpartition(":")
        rsock = _socket.create_connection((host, int(port)), timeout=60)
        rsock.settimeout(None)   # connect-only timeout: an idle
        # interactive session must not be torn down after 60s of quiet
        lines = [f"{handler.command} {handler.path} HTTP/1.1",
                 f"Host: {remote}", "X-Nomad-Routed: 1"]
        for k, v in handler.headers.items():
            if k.lower() in ("host", "x-nomad-routed"):
                continue
            lines.append(f"{k}: {v}")
        rsock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        csock = handler.connection

        def pump(src, dst):
            try:
                while True:
                    chunk = src.recv(65536)
                    if not chunk:
                        break
                    dst.sendall(chunk)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass

        t = threading.Thread(target=pump, args=(rsock, csock),
                             daemon=True)
        t.start()
        pump(csock, rsock)
        t.join(timeout=10.0)
        rsock.close()

    def _resolve_task_runner(self, alloc_id: str, task):
        """Find the local task runner for (alloc prefix, task name)."""
        if self.client is None:
            raise HTTPError(400, "no client agent on this node")
        runner = self.client.get_alloc_runner(alloc_id)
        if runner is None:
            matches = [r for aid, r in list(self.client.runners.items())
                       if aid.startswith(alloc_id)]
            if len(matches) != 1:
                raise HTTPError(404, f"alloc {alloc_id} not on node")
            runner = matches[0]
        trs = runner.task_runners
        if task:
            trs = [tr for tr in trs if tr.task.name == task]
        if len(trs) != 1:
            raise HTTPError(400, "specify 'task' (multiple tasks)"
                            if not task else f"unknown task {task!r}")
        tr = trs[0]
        if tr.handle is None:
            raise HTTPError(409, "task is not running")
        return tr

    def client_exec(self, q, body, alloc_id):
        """One-shot command execution inside a task's context
        (reference: alloc exec, plugins/drivers ExecTask — the one-shot
        form; see handle_exec_ws for the interactive pty stream).
        Routed to the owning agent when the alloc is not local."""
        if not body or not body.get("cmd"):
            raise HTTPError(400, "body must carry 'cmd' (list)")
        remote = self._client_route(alloc_id, q)
        if remote is not None:
            return self._proxy_client_http(
                remote, "POST", f"/v1/client/allocation/{alloc_id}/exec",
                q, body)
        tr = self._resolve_task_runner(alloc_id, body.get("task"))
        try:
            timeout_s = float(body.get("timeout_s", 30.0))
        except (TypeError, ValueError):
            raise HTTPError(400, "timeout_s must be a number")
        out, code = tr.driver.exec_task(
            tr.task_id, list(body["cmd"]), timeout_s=timeout_s)
        return 200, {"output": out.decode("utf-8", errors="replace"),
                     "exit_code": code}, None

    def client_csi_plugin_register(self, q, body, name):
        """Register an external CSI plugin endpoint with this agent's
        node (reference: dynamic plugin registration; the reference
        does this via plugin-supervisor task hooks, here it is also a
        first-class agent API)."""
        if self.client is None:
            raise HTTPError(400, "no client agent on this node")
        if not body or "addr" not in body:
            raise HTTPError(400, "body must carry 'addr' [host, port]")
        try:
            self.client.register_csi_plugin(name, tuple(body["addr"]))
        except Exception as e:
            raise HTTPError(502, f"plugin registration failed: {e}")
        return 200, {"registered": name}, None

    def job_dispatch(self, q, body, job_id):
        """Instantiate a parameterized job with a payload + meta
        (reference: command/agent/job_endpoint.go Dispatch →
        nomad/job_endpoint.go Job.Dispatch)."""
        import base64
        body = body or {}
        payload = b""
        if body.get("payload"):
            try:
                payload = base64.b64decode(body["payload"])
            except Exception:
                raise HTTPError(400, "payload must be base64")
        meta = body.get("meta") or {}
        if not isinstance(meta, dict):
            raise HTTPError(400, "meta must be an object")
        ns = q.get("namespace", "default")
        try:
            child, ev = self.server.dispatch_job(ns, job_id,
                                                 payload=payload,
                                                 meta=meta)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return 200, {"dispatched_job_id": child.id,
                     "eval_id": ev.id if ev else "",
                     "job_create_index": child.create_index}, \
            self.server.store.latest_index()

    def job_revert(self, q, body, job_id):
        """Manual revert to a retained job version (reference:
        Job.Revert — /v1/job/:id/revert)."""
        body = body or {}
        if "job_version" not in body:
            raise HTTPError(400, "body must carry 'job_version'")
        ns = q.get("namespace", "default")
        try:
            new_version, ev = self.server.revert_job_version(
                ns, job_id, int(body["job_version"]),
                enforce_prior_version=body.get("enforce_prior_version"))
        except (ValueError, TypeError) as e:
            raise HTTPError(400, str(e))
        return 200, {"job_version": new_version,
                     "eval_id": ev.id if ev else ""}, \
            self.server.store.latest_index()

    def job_stable(self, q, body, job_id):
        """Mark a job version (un)stable (reference: Job.Stable —
        /v1/job/:id/stable)."""
        body = body or {}
        if "job_version" not in body:
            raise HTTPError(400, "body must carry 'job_version'")
        ns = q.get("namespace", "default")
        try:
            self.server.set_job_stability(
                ns, job_id, int(body["job_version"]),
                bool(body.get("stable", True)))
        except (ValueError, TypeError) as e:
            raise HTTPError(400, str(e))
        return 200, {"job_version": int(body["job_version"]),
                     "stable": bool(body.get("stable", True))}, \
            self.server.store.latest_index()

    def job_scale(self, q, body, job_id):
        """Adjust a task group's count (reference: Job.Scale,
        nomad/job_endpoint.go ScaleStatus/Scale — registers the updated
        job and evaluates it with the scaling trigger)."""
        if not body or "group" not in body or "count" not in body:
            raise HTTPError(400, "body must carry 'group' and 'count'")
        ns = q.get("namespace", "default")
        job = self.server.store.job_by_id(ns, job_id)
        if job is None:
            raise HTTPError(404, f"job {job_id} not found")
        try:
            count = int(body["count"])
        except (TypeError, ValueError):
            raise HTTPError(400, "count must be an integer")
        if count < 0:
            raise HTTPError(400, "count must be >= 0")
        import copy as _copy
        j2 = _copy.deepcopy(job)
        tg = j2.lookup_task_group(body["group"])
        if tg is None:
            raise HTTPError(400, f"unknown group {body['group']!r}")
        tg.count = count
        try:
            ev = self.server.register_job(j2)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return 200, {"eval_id": ev.id if ev else "",
                     "index": self.server.store.latest_index()}, None

    def services_list(self, q, body):
        ns = q.get("namespace", "default")
        index = self._block(q, "services")
        return 200, self.server.store.service_names(ns), index

    def service_get(self, q, body, name):
        ns = q.get("namespace", "default")
        index = self._block(q, "services")
        regs = self.server.store.services_by_name(ns, name)
        return 200, [to_wire(r) for r in regs], index

    def secrets_list(self, q, body):
        ns = q.get("namespace", "default")
        return 200, self.server.store.secret_paths(ns), \
            self.server.store.table_index("secrets")

    def secret_get(self, q, body, path):
        ns = q.get("namespace", "default")
        d = self.server.store.secret_by_path(ns, path)
        if d is None:
            raise HTTPError(404, f"secret {path} not found")
        return 200, {"path": path, "data": d}, \
            self.server.store.table_index("secrets")

    def secret_put(self, q, body, path):
        ns = q.get("namespace", "default")
        if (not body or "data" not in body
                or not isinstance(body["data"], dict)):
            raise HTTPError(400, "body must carry a 'data' object")
        index = self.server.upsert_secret(ns, path, body["data"])
        return 200, {"index": index}, index

    def secret_delete(self, q, body, path):
        ns = q.get("namespace", "default")
        index = self.server.delete_secret(ns, path)
        return 200, {"index": index}, index

    def acl_bootstrap(self, q, body):
        try:
            token = self.server.bootstrap_acl()
        except ValueError as e:
            raise HTTPError(400, str(e))
        return 200, to_wire(token), self.server.store.latest_index()

    def acl_policies_list(self, q, body):
        return 200, [to_wire(p) for p in self.server.store.acl_policies()], \
            self.server.store.latest_index()

    def acl_policy_get(self, q, body, name):
        p = self.server.store.acl_policy_by_name(name)
        if p is None:
            raise HTTPError(404, f"policy {name} not found")
        return 200, to_wire(p), self.server.store.latest_index()

    def acl_policy_upsert(self, q, body, name):
        from ..acl import ACLPolicy
        if not body:
            raise HTTPError(400, "body must carry the policy")
        policy = from_wire(ACLPolicy, body)
        policy.name = name
        index = self.server.upsert_acl_policy(policy)
        return 200, {"index": index}, index

    def acl_policy_delete(self, q, body, name):
        index = self.server.delete_acl_policy(name)
        return 200, {"index": index}, index

    def acl_tokens_list(self, q, body):
        out = []
        for t in self.server.store.acl_tokens():
            w = to_wire(t)
            w.pop("secret_id", None)       # listings never leak secrets
            out.append(w)
        return 200, out, self.server.store.latest_index()

    def acl_token_upsert(self, q, body):
        from ..acl import ACLToken
        if not body:
            raise HTTPError(400, "body must carry the token")
        token = from_wire(ACLToken, body)
        index = self.server.upsert_acl_token(token)
        return 200, to_wire(token), index

    def acl_token_get(self, q, body, accessor):
        t = self.server.store.acl_token_by_accessor(accessor)
        if t is None:
            raise HTTPError(404, f"token {accessor} not found")
        return 200, to_wire(t), self.server.store.latest_index()

    def acl_token_delete(self, q, body, accessor):
        index = self.server.delete_acl_token(accessor)
        return 200, {"index": index}, index

    def volumes_list(self, q, body):
        ns = q.get("namespace", "default")
        vols = self.server.store.csi_volumes(ns)
        return 200, [to_wire(v) for v in vols], \
            self.server.store.latest_index()

    def volume_get(self, q, body, vol_id):
        ns = q.get("namespace", "default")
        v = self.server.store.csi_volume_by_id(ns, vol_id)
        if v is None:
            raise HTTPError(404, f"volume {vol_id} not found")
        return 200, to_wire(v), self.server.store.latest_index()

    def volume_register(self, q, body, vol_id):
        from ..structs import CSIVolume
        if not body:
            raise HTTPError(400, "body must carry the volume")
        vol = from_wire(CSIVolume, body.get("volume", body))
        vol.id = vol_id
        if "namespace" in q:
            vol.namespace = q["namespace"]
        index = self.server.register_csi_volume(vol)
        return 200, {"index": index}, index

    def volume_delete(self, q, body, vol_id):
        ns = q.get("namespace", "default")
        try:
            index = self.server.deregister_csi_volume(ns, vol_id)
        except ValueError as e:
            raise HTTPError(409, str(e))
        return 200, {"index": index}, index

    def operator_scheduler_config(self, q, body):
        cfg = self.server.store.scheduler_config()
        return 200, to_wire(cfg), None


def _build_routes(s: HTTPAgentServer):
    R = re.compile
    return [
        (R(r"^/v1/jobs$"), {"GET": s.jobs_list, "POST": s.jobs_register,
                            "PUT": s.jobs_register}),
        (R(r"^/v1/jobs/parse$"), {"POST": s.jobs_parse,
                                  "PUT": s.jobs_parse}),
        (R(r"^/v1/job/([^/]+)$"), {"GET": s.job_get, "POST": s.job_update,
                                   "PUT": s.job_update,
                                   "DELETE": s.job_delete}),
        (R(r"^/v1/job/([^/]+)/allocations$"), {"GET": s.job_allocations}),
        (R(r"^/v1/job/([^/]+)/evaluations$"), {"GET": s.job_evaluations}),
        (R(r"^/v1/job/([^/]+)/deployments$"), {"GET": s.job_deployments}),
        (R(r"^/v1/job/([^/]+)/summary$"), {"GET": s.job_summary}),
        (R(r"^/v1/job/([^/]+)/versions$"), {"GET": s.job_versions}),
        (R(r"^/v1/job/([^/]+)/plan$"), {"POST": s.job_plan,
                                        "PUT": s.job_plan}),
        (R(r"^/v1/job/([^/]+)/periodic/force$"),
         {"POST": s.job_periodic_force}),
        (R(r"^/v1/evaluations$"), {"GET": s.evals_list}),
        (R(r"^/v1/evaluation/([^/]+)$"), {"GET": s.eval_get}),
        (R(r"^/v1/evaluation/([^/]+)/allocations$"),
         {"GET": s.eval_allocations}),
        (R(r"^/v1/allocations$"), {"GET": s.allocs_list}),
        (R(r"^/v1/allocation/([^/]+)$"), {"GET": s.alloc_get}),
        (R(r"^/v1/allocation/([^/]+)/stop$"), {"POST": s.alloc_stop,
                                               "PUT": s.alloc_stop}),
        (R(r"^/v1/nodes$"), {"GET": s.nodes_list}),
        (R(r"^/v1/node/([^/]+)$"), {"GET": s.node_get}),
        (R(r"^/v1/node/([^/]+)/allocations$"), {"GET": s.node_allocations}),
        (R(r"^/v1/node/([^/]+)/drain$"), {"POST": s.node_drain,
                                          "PUT": s.node_drain}),
        (R(r"^/v1/node/([^/]+)/eligibility$"), {"POST": s.node_eligibility,
                                                "PUT": s.node_eligibility}),
        (R(r"^/v1/node/([^/]+)/evaluate$"), {"POST": s.node_evaluate,
                                             "PUT": s.node_evaluate}),
        (R(r"^/v1/deployments$"), {"GET": s.deployments_list}),
        (R(r"^/v1/deployment/promote/([^/]+)$"),
         {"POST": s.deployment_promote, "PUT": s.deployment_promote}),
        (R(r"^/v1/deployment/fail/([^/]+)$"),
         {"POST": s.deployment_fail, "PUT": s.deployment_fail}),
        (R(r"^/v1/deployment/allocations/([^/]+)$"),
         {"GET": s.deployment_allocations}),
        (R(r"^/v1/deployment/([^/]+)$"), {"GET": s.deployment_get}),
        (R(r"^/v1/regions$"), {"GET": s.regions_list}),
        (R(r"^/v1/agent/self$"), {"GET": s.agent_self}),
        (R(r"^/v1/agent/pprof/([^/]+)$"), {"GET": s.agent_pprof}),
        (R(r"^/v1/agent/members$"), {"GET": s.agent_members}),
        (R(r"^/v1/status/leader$"), {"GET": s.status_leader}),
        (R(r"^/v1/status/peers$"), {"GET": s.status_peers}),
        (R(r"^/v1/metrics$"), {"GET": s.metrics}),
        (R(r"^/v1/traces$"), {"GET": s.traces_list}),
        # literal /v1/trace/corpus must outrank the :id capture
        (R(r"^/v1/trace/corpus$"), {"GET": s.trace_corpus,
                                    "POST": s.trace_corpus,
                                    "PUT": s.trace_corpus}),
        (R(r"^/v1/trace/([^/]+)$"), {"GET": s.trace_get}),
        (R(r"^/v1/agent/events$"), {"GET": s.agent_events}),
        (R(r"^/v1/telemetry/health$"), {"GET": s.telemetry_health}),
        (R(r"^/v1/telemetry/series$"), {"GET": s.telemetry_series}),
        (R(r"^/v1/system/gc$"), {"PUT": s.system_gc,
                                 "POST": s.system_gc}),
        (R(r"^/v1/operator/scheduler/configuration$"),
         {"GET": s.operator_scheduler_config}),
        (R(r"^/v1/search$"), {"POST": s.search, "PUT": s.search}),
        (R(r"^/v1/volumes$"), {"GET": s.volumes_list}),
        (R(r"^/v1/volume/csi/([^/]+)$"), {"GET": s.volume_get,
                                          "PUT": s.volume_register,
                                          "POST": s.volume_register,
                                          "DELETE": s.volume_delete}),
        (R(r"^/v1/acl/bootstrap$"), {"POST": s.acl_bootstrap,
                                     "PUT": s.acl_bootstrap}),
        (R(r"^/v1/acl/policies$"), {"GET": s.acl_policies_list}),
        (R(r"^/v1/acl/policy/([^/]+)$"), {"GET": s.acl_policy_get,
                                          "POST": s.acl_policy_upsert,
                                          "PUT": s.acl_policy_upsert,
                                          "DELETE": s.acl_policy_delete}),
        (R(r"^/v1/acl/tokens$"), {"GET": s.acl_tokens_list,
                                  "POST": s.acl_token_upsert,
                                  "PUT": s.acl_token_upsert}),
        (R(r"^/v1/acl/token/([^/]+)$"), {"GET": s.acl_token_get,
                                         "DELETE": s.acl_token_delete}),
        (R(r"^/v1/client/fs/logs/([^/]+)$"), {"GET": s.client_logs}),
        (R(r"^/v1/client/fs/ls/([^/]+)$"), {"GET": s.client_fs_ls}),
        (R(r"^/v1/client/fs/stat/([^/]+)$"), {"GET": s.client_fs_stat}),
        (R(r"^/v1/client/fs/cat/([^/]+)$"), {"GET": s.client_fs_cat}),
        (R(r"^/v1/client/fs/readat/([^/]+)$"),
         {"GET": s.client_fs_readat}),
        (R(r"^/v1/client/fs/stream/([^/]+)$"),
         {"GET": s.client_fs_stream}),
        (R(r"^/v1/client/stats$"), {"GET": s.client_host_stats}),
        (R(r"^/v1/client/allocation/([^/]+)/stats$"),
         {"GET": s.client_alloc_stats}),
        (R(r"^/v1/client/allocation/([^/]+)/exec$"),
         {"POST": s.client_exec, "PUT": s.client_exec}),
        (R(r"^/v1/client/csi/plugin/([^/]+)$"),
         {"POST": s.client_csi_plugin_register,
          "PUT": s.client_csi_plugin_register}),
        (R(r"^/v1/job/([^/]+)/scale$"), {"POST": s.job_scale,
                                         "PUT": s.job_scale}),
        (R(r"^/v1/job/([^/]+)/dispatch$"), {"POST": s.job_dispatch,
                                            "PUT": s.job_dispatch}),
        (R(r"^/v1/job/([^/]+)/revert$"), {"POST": s.job_revert,
                                          "PUT": s.job_revert}),
        (R(r"^/v1/job/([^/]+)/stable$"), {"POST": s.job_stable,
                                          "PUT": s.job_stable}),
        (R(r"^/v1/services$"), {"GET": s.services_list}),
        (R(r"^/v1/service/([^/]+)$"), {"GET": s.service_get}),
        (R(r"^/v1/secrets$"), {"GET": s.secrets_list}),
        (R(r"^/v1/secret/(.+)$"), {"GET": s.secret_get,
                                   "PUT": s.secret_put,
                                   "POST": s.secret_put,
                                   "DELETE": s.secret_delete}),
    ]
