"""In-memory replicated state store with snapshots and blocking watches.

Reference: nomad/state/state_store.go (go-memdb MVCC tables) + schema.go.
Rebuild notes: instead of radix-tree MVCC we keep plain dict tables plus
secondary indexes, and give schedulers immutable *snapshots* (shallow table
copies). Entries are treated as immutable once inserted — writers replace
objects, never mutate in place — which is what makes the shallow snapshot
sound (same discipline the reference enforces via memdb).

Every write carries a raft-style log index; per-table indexes power blocking
queries (reference: rpc.go blocking-query min-index machinery).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

from ..structs import (ALLOC_CLIENT_LOST, ALLOC_DESIRED_STOP, Allocation,
                       Deployment, Evaluation, Job, JOB_STATUS_DEAD,
                       JOB_STATUS_PENDING, JOB_STATUS_RUNNING, Node,
                       NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE, Plan,
                       PlanResult)
from ..structs.consts import (EVAL_STATUS_BLOCKED, EVAL_STATUS_COMPLETE,
                              EVAL_STATUS_PENDING, JOB_TYPE_SYSTEM)

TABLES = ("nodes", "jobs", "job_versions", "job_summaries", "evals", "allocs",
          "deployments", "periodic_launches", "scheduler_config", "indexes",
          "acl_policies", "acl_tokens", "scaling_policies", "scaling_events",
          "vault_accessors", "csi_volumes", "csi_plugins", "cluster_meta",
          "services", "secrets")


class JobSummary:
    """Per-task-group alloc status counts (reference: structs.JobSummary)."""

    def __init__(self, job_id: str, namespace: str):
        self.job_id = job_id
        self.namespace = namespace
        # tg -> {"queued":n,"complete":n,"failed":n,"running":n,"starting":n,"lost":n}
        self.summary: Dict[str, Dict[str, int]] = {}
        self.children_pending = 0
        self.children_running = 0
        self.children_dead = 0
        self.create_index = 0
        self.modify_index = 0

    def copy(self) -> "JobSummary":
        s = JobSummary(self.job_id, self.namespace)
        s.summary = {k: dict(v) for k, v in self.summary.items()}
        s.children_pending = self.children_pending
        s.children_running = self.children_running
        s.children_dead = self.children_dead
        s.create_index = self.create_index
        s.modify_index = self.modify_index
        return s


class SchedulerConfiguration:
    """Runtime-tunable knobs (reference: structs.SchedulerConfiguration).

    `solver_backend` is the switch SURVEY §5.6 calls out: "host" runs the
    scalar reference-semantics path, "tpu" the batched JAX solve.
    """

    def __init__(self, preemption_system=True, preemption_service=False,
                 preemption_batch=False, solver_backend="tpu"):
        self.preemption_system_enabled = preemption_system
        self.preemption_service_enabled = preemption_service
        self.preemption_batch_enabled = preemption_batch
        self.solver_backend = solver_backend
        self.create_index = 0
        self.modify_index = 0


class StateSnapshot:
    """Immutable point-in-time view handed to schedulers.

    Exposes the same read API as the live store (reference:
    scheduler.State interface, scheduler/scheduler.go:65).
    """

    def __init__(self, tables: Dict[str, dict], indexes: Dict[str, int],
                 index: int):
        self._t = tables
        self._ix = dict(indexes)
        self.index = index

    # -- nodes --
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._t["nodes"].get(node_id)

    def nodes(self) -> Iterable[Node]:
        return self._t["nodes"].values()

    def ready_nodes_in_dcs(self, datacenters: List[str]
                           ) -> Tuple[List[Node], Dict[str, int]]:
        """Reference: scheduler/util.go:233 readyNodesInDCs."""
        dcs = set(datacenters)
        out, by_dc = [], {}
        for n in self._t["nodes"].values():
            if not n.ready():
                continue
            if n.datacenter not in dcs and "*" not in dcs:
                continue
            out.append(n)
            by_dc[n.datacenter] = by_dc.get(n.datacenter, 0) + 1
        return out, by_dc

    # -- csi volumes --
    def csi_volume_by_id(self, namespace: str, vol_id: str):
        return self._t["csi_volumes"].get((namespace, vol_id))

    # -- jobs --
    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self._t["jobs"].get((namespace, job_id))

    def jobs(self) -> Iterable[Job]:
        return self._t["jobs"].values()

    def jobs_by_namespace(self, namespace: str) -> List[Job]:
        return [j for (ns, _), j in self._t["jobs"].items() if ns == namespace]

    def job_versions(self, namespace: str, job_id: str) -> List[Job]:
        return list(self._t["job_versions"].get((namespace, job_id), ()))

    def job_by_id_and_version(self, namespace: str, job_id: str,
                              version: int) -> Optional[Job]:
        for j in self._t["job_versions"].get((namespace, job_id), ()):
            if j.version == version:
                return j
        return None

    def job_summary(self, namespace: str, job_id: str) -> Optional[JobSummary]:
        return self._t["job_summaries"].get((namespace, job_id))

    # -- evals --
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._t["evals"].get(eval_id)

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return [e for e in self._t["evals"].values()
                if e.job_id == job_id and e.namespace == namespace]

    def evals(self) -> Iterable[Evaluation]:
        return self._t["evals"].values()

    # -- allocs --
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._t["allocs"].get(alloc_id)

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        ids = self._t.get("_allocs_by_node", {}).get(node_id, ())
        return [self._t["allocs"][i] for i in ids if i in self._t["allocs"]]

    def allocs_by_node_terminal(self, node_id: str,
                                terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id)
                if a.terminal_status() == terminal]

    def allocs_by_job(self, namespace: str, job_id: str,
                      anyCreateIndex: bool = True) -> List[Allocation]:
        ids = self._t.get("_allocs_by_job", {}).get((namespace, job_id), ())
        return [self._t["allocs"][i] for i in ids if i in self._t["allocs"]]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        return [a for a in self._t["allocs"].values() if a.eval_id == eval_id]

    def allocs(self) -> Iterable[Allocation]:
        return self._t["allocs"].values()

    def allocs_by_deployment(self, dep_id: str) -> List[Allocation]:
        return [a for a in self._t["allocs"].values()
                if a.deployment_id == dep_id]

    # -- deployments --
    def deployment_by_id(self, dep_id: str) -> Optional[Deployment]:
        return self._t["deployments"].get(dep_id)

    def deployments(self) -> Iterable[Deployment]:
        return self._t["deployments"].values()

    def deployments_by_job(self, namespace: str, job_id: str) -> List[Deployment]:
        return [d for d in self._t["deployments"].values()
                if d.job_id == job_id and d.namespace == namespace]

    def latest_deployment_by_job(self, namespace: str,
                                 job_id: str) -> Optional[Deployment]:
        deps = self.deployments_by_job(namespace, job_id)
        if not deps:
            return None
        return max(deps, key=lambda d: d.create_index)

    # -- config / meta --
    def scheduler_config(self) -> SchedulerConfiguration:
        return self._t["scheduler_config"].get("config") or SchedulerConfiguration()

    def table_index(self, table: str) -> int:
        return self._ix.get(table, 0)


class ChangeLog:
    """Bounded append-only log of cluster-state-relevant writes (node
    and alloc table mutations), keyed by raft index.  The solver's
    device-resident cluster state (solver/solve.py ResidentWorld) pulls
    `since(last, snapshot_index)` to build exact incremental deltas
    instead of re-walking the whole world per eval; a consumer that
    fell behind the ring gets None and must full-repack.

    Appends are monotonically non-decreasing in index (raft apply
    order), so `since` is a pair of bisects, not a scan."""

    __slots__ = ("cap", "_entries", "_indexes", "floor")

    def __init__(self, cap: int = 131072):
        self.cap = cap
        self._entries: List[tuple] = []     # (index, kind, key)
        self._indexes: List[int] = []       # parallel, for bisect
        self.floor = 0              # highest index ever evicted

    def append(self, index: int, kind: str, key) -> None:
        self._entries.append((index, kind, key))
        self._indexes.append(index)
        if len(self._entries) > 2 * self.cap:
            cut = len(self._entries) - self.cap
            self.floor = max(self.floor, self._indexes[cut - 1])
            del self._entries[:cut]
            del self._indexes[:cut]

    def since(self, min_index: int, max_index: int):
        """Entries with min_index < index <= max_index, or None when the
        window reaches below the ring's floor (consumer must rebuild)."""
        import bisect
        if min_index < self.floor:
            return None
        lo = bisect.bisect_right(self._indexes, min_index)
        hi = bisect.bisect_right(self._indexes, max_index)
        return self._entries[lo:hi]


class StateStore(StateSnapshot):
    """The live, writable store. Reads are inherited from StateSnapshot."""

    def __init__(self) -> None:
        tables: Dict[str, dict] = {name: {} for name in TABLES}
        tables["_allocs_by_node"] = {}
        tables["_allocs_by_job"] = {}
        super().__init__(tables, {}, 0)
        self._lock = threading.RLock()
        self._watch = threading.Condition(self._lock)
        self.changelog = ChangeLog()

    def changes_since(self, min_index: int, max_index: int):
        """Node/alloc change entries in (min_index, max_index], or None
        if the log was truncated past min_index (see ChangeLog)."""
        with self._lock:
            return self.changelog.since(min_index, max_index)

    # -- snapshot & watch --
    def snapshot(self) -> StateSnapshot:
        with self._lock:
            copied = {}
            for name, table in self._t.items():
                if name in ("_allocs_by_node", "_allocs_by_job"):
                    copied[name] = {k: set(v) for k, v in table.items()}
                else:
                    copied[name] = dict(table)
            return StateSnapshot(copied, self._ix, self.index)

    def latest_index(self) -> int:
        with self._lock:
            return self.index

    def wait_for_index(self, index: int, timeout: float = 5.0) -> int:
        """Block until the store reaches `index` (reference: worker.go:228
        snapshotMinIndex). Returns the current index."""
        deadline = None
        with self._watch:
            while self.index < index:
                import time
                if deadline is None:
                    deadline = time.monotonic() + timeout
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                self._watch.wait(remain)
            return self.index

    def wait_for_change(self, min_index: int, timeout: float) -> int:
        """Blocking-query primitive: wait until store index > min_index."""
        import time
        deadline = time.monotonic() + timeout
        with self._watch:
            while self.index <= min_index:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                self._watch.wait(remain)
            return self.index

    def _bump_locked(self, table: str, index: int) -> None:
        self.index = max(self.index, index)
        self._ix[table] = max(self._ix.get(table, 0), index)
        self._watch.notify_all()

    # -- nodes --
    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            existing = self._t["nodes"].get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
            else:
                node.create_index = index
            node.modify_index = index
            if not node.computed_class:
                node.compute_class()
            self._t["nodes"][node.id] = node
            self.changelog.append(index, "node", node.id)
            self._bump_locked("nodes", index)

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            self._t["nodes"].pop(node_id, None)
            self.changelog.append(index, "node", node_id)
            self._bump_locked("nodes", index)

    def update_node_status(self, index: int, node_id: str, status: str,
                           updated_at: float = 0.0) -> None:
        with self._lock:
            n = self._t["nodes"].get(node_id)
            if n is None:
                raise KeyError(f"node {node_id} not found")
            import copy as _copy
            n2 = _copy.copy(n)
            n2.status = status
            n2.status_updated_at = updated_at
            n2.modify_index = index
            self._t["nodes"][node_id] = n2
            self.changelog.append(index, "node", node_id)
            self._bump_locked("nodes", index)

    def update_node_eligibility(self, index: int, node_id: str,
                                eligibility: str) -> None:
        with self._lock:
            n = self._t["nodes"].get(node_id)
            if n is None:
                raise KeyError(f"node {node_id} not found")
            import copy as _copy
            n2 = _copy.copy(n)
            n2.scheduling_eligibility = eligibility
            n2.modify_index = index
            self._t["nodes"][node_id] = n2
            self.changelog.append(index, "node", node_id)
            self._bump_locked("nodes", index)

    def update_node_drain(self, index: int, node_id: str, drain_strategy,
                          mark_eligible: bool = False) -> None:
        with self._lock:
            n = self._t["nodes"].get(node_id)
            if n is None:
                raise KeyError(f"node {node_id} not found")
            import copy as _copy
            n2 = _copy.copy(n)
            n2.drain_strategy = drain_strategy
            n2.drain = drain_strategy is not None
            if drain_strategy is not None:
                n2.scheduling_eligibility = NODE_SCHED_INELIGIBLE
            elif mark_eligible:
                n2.scheduling_eligibility = NODE_SCHED_ELIGIBLE
            n2.modify_index = index
            self._t["nodes"][node_id] = n2
            self.changelog.append(index, "node", node_id)
            self._bump_locked("nodes", index)

    # -- jobs --
    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            key = (job.namespace, job.id)
            existing = self._t["jobs"].get(key)
            if existing is not None:
                job.create_index = existing.create_index
                job.job_modify_index = index
                if self._job_spec_changed(existing, job):
                    job.version = existing.version + 1
                else:
                    job.version = existing.version
            else:
                job.create_index = index
                job.job_modify_index = index
                job.version = 0
            job.modify_index = index
            self._t["jobs"][key] = job
            versions = list(self._t["job_versions"].get(key, ()))
            if not versions or versions[0].version != job.version:
                versions.insert(0, job)
                from ..structs.consts import MAX_RETAINED_JOB_VERSIONS
                del versions[MAX_RETAINED_JOB_VERSIONS:]
            else:
                versions[0] = job
            self._t["job_versions"][key] = versions
            self._ensure_summary_locked(index, job)
            self._bump_locked("jobs", index)

    @staticmethod
    def _job_spec_changed(old: Job, new: Job) -> bool:
        """Did the user-facing spec change? (reference: Job.SpecChanged)"""
        import copy as _copy
        a, b = _copy.copy(old), _copy.copy(new)
        for j in (a, b):
            j.version = 0
            j.status = ""
            j.status_description = ""
            j.stable = False
            j.create_index = j.modify_index = j.job_modify_index = 0
            j.submit_time = 0.0
        return a != b

    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            key = (namespace, job_id)
            self._t["jobs"].pop(key, None)
            self._t["job_versions"].pop(key, None)
            self._t["job_summaries"].pop(key, None)
            self._t["periodic_launches"].pop(key, None)
            self._bump_locked("jobs", index)

    def update_job_stability(self, index: int, namespace: str, job_id: str,
                             version: int, stable: bool) -> None:
        with self._lock:
            self._update_job_stability_locked(index, namespace, job_id,
                                              version, stable)

    def _update_job_stability_locked(self, index: int, namespace: str,
                                     job_id: str, version: int,
                                     stable: bool) -> None:
        key = (namespace, job_id)
        for tbl in ("jobs",):
            j = self._t[tbl].get(key)
            if j is not None and j.version == version:
                import copy as _copy
                j2 = _copy.copy(j)
                j2.stable = stable
                j2.modify_index = index
                self._t[tbl][key] = j2
        versions = list(self._t["job_versions"].get(key, ()))
        for i, jv in enumerate(versions):
            if jv.version == version:
                import copy as _copy
                j2 = _copy.copy(jv)
                j2.stable = stable
                versions[i] = j2
        self._t["job_versions"][key] = versions
        self._bump_locked("jobs", index)

    def _mark_stable_locked(self, index: int, namespace: str,
                            job_id: str, version: int) -> None:
        self._update_job_stability_locked(index, namespace, job_id,
                                          version, True)

    def _ensure_summary_locked(self, index: int, job: Job) -> None:
        key = (job.namespace, job.id)
        summary = self._t["job_summaries"].get(key)
        if summary is None:
            summary = JobSummary(job.id, job.namespace)
            summary.create_index = index
        else:
            summary = summary.copy()
        for tg in job.task_groups:
            summary.summary.setdefault(tg.name, {
                "queued": 0, "complete": 0, "failed": 0,
                "running": 0, "starting": 0, "lost": 0})
        summary.modify_index = index
        self._t["job_summaries"][key] = summary

    def update_job_summary_queued(self, index: int, namespace: str,
                                  job_id: str, queued: Dict[str, int]) -> None:
        with self._lock:
            key = (namespace, job_id)
            summary = self._t["job_summaries"].get(key)
            if summary is None:
                return
            summary = summary.copy()
            for tg, n in queued.items():
                summary.summary.setdefault(tg, {
                    "queued": 0, "complete": 0, "failed": 0,
                    "running": 0, "starting": 0, "lost": 0})["queued"] = n
            summary.modify_index = index
            self._t["job_summaries"][key] = summary
            self._bump_locked("job_summaries", index)

    # -- evals --
    def upsert_evals(self, index: int, evals: List[Evaluation]) -> None:
        with self._lock:
            for e in evals:
                existing = self._t["evals"].get(e.id)
                if existing is not None:
                    e.create_index = existing.create_index
                else:
                    e.create_index = index
                e.modify_index = index
                self._t["evals"][e.id] = e
                self._refresh_job_status_locked(index, e.namespace, e.job_id)
            self._bump_locked("evals", index)

    def delete_eval(self, index: int, eval_ids: List[str],
                    alloc_ids: List[str] = ()) -> None:
        with self._lock:
            for eid in eval_ids:
                self._t["evals"].pop(eid, None)
            for aid in alloc_ids:
                self._remove_alloc_locked(aid, index)
            self._bump_locked("evals", index)
            if alloc_ids:
                self._bump_locked("allocs", index)

    def _refresh_job_status_locked(self, index: int, namespace: str,
                            job_id: str) -> None:
        """Keep Job.status in sync as evals/allocs flow (simplified
        reference: state_store.go setJobStatus/getJobStatus — called from
        eval upserts, plan application and client alloc updates)."""
        key = (namespace, job_id)
        job = self._t["jobs"].get(key)
        if job is None:
            return
        has_live_alloc = any(
            not self._t["allocs"][a].terminal_status()
            for a in self._t["_allocs_by_job"].get(key, ())
            if a in self._t["allocs"])
        has_open_eval = any(
            e.job_id == job_id and e.namespace == namespace
            and e.status in (EVAL_STATUS_PENDING, EVAL_STATUS_BLOCKED)
            for e in self._t["evals"].values())
        new_status = JOB_STATUS_DEAD
        if job.stopped():
            new_status = JOB_STATUS_DEAD
        elif has_live_alloc:
            new_status = JOB_STATUS_RUNNING
        elif has_open_eval or job.is_periodic() or job.is_parameterized():
            new_status = JOB_STATUS_PENDING
        if new_status != job.status:
            import copy as _copy
            j2 = _copy.copy(job)
            j2.status = new_status
            j2.modify_index = index
            self._t["jobs"][key] = j2

    # -- allocs --
    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        with self._lock:
            for a in allocs:
                self._upsert_alloc_locked(index, a)
            # sorted: set order varies with PYTHONHASHSEED across
            # replica processes (nomadlint FSM103)
            for key in sorted({(a.namespace, a.job_id) for a in allocs}):
                self._refresh_job_status_locked(index, *key)
            self._bump_locked("allocs", index)

    def _upsert_alloc_locked(self, index: int, a: Allocation) -> None:
        existing = self._t["allocs"].get(a.id)
        if existing is not None:
            a.create_index = existing.create_index
            # server-side upserts keep client-reported state unless newer
            if not a.task_states and existing.task_states:
                a.task_states = existing.task_states
            if a.client_status == "" and existing.client_status:
                a.client_status = existing.client_status
        else:
            a.create_index = index
        a.modify_index = index
        self._update_deployment_with_alloc_locked(index, a, existing)
        self._update_summary_with_alloc_locked(index, a, existing)
        self._t["allocs"][a.id] = a
        self.changelog.append(index, "alloc", a.id)
        self._t["_allocs_by_node"].setdefault(a.node_id, set()).add(a.id)
        self._t["_allocs_by_job"].setdefault(
            (a.namespace, a.job_id), set()).add(a.id)
        # server-side terminal transitions (lost nodes, evictions) must
        # drop the alloc's service registrations too — the dead client
        # will never send the update that would
        self._sync_services_locked(index, a)

    _SUMMARY_BUCKETS = {"pending": "starting", "running": "running",
                        "complete": "complete", "failed": "failed",
                        "lost": "lost"}

    def _update_summary_with_alloc_locked(self, index: int, a: Allocation,
                                          existing) -> None:
        """Move the alloc between its job summary's status buckets
        (reference: state_store.go updateSummaryWithAlloc)."""
        key = (a.namespace, a.job_id)
        summary = self._t["job_summaries"].get(key)
        if summary is None:
            return
        old = (self._SUMMARY_BUCKETS.get(existing.client_status)
               if existing is not None else None)
        new = self._SUMMARY_BUCKETS.get(a.client_status)
        if old == new:
            return
        s2 = summary.copy()
        tg = s2.summary.setdefault(a.task_group, {
            "queued": 0, "complete": 0, "failed": 0, "running": 0,
            "starting": 0, "lost": 0})
        if old is not None and tg.get(old, 0) > 0:
            tg[old] -= 1
        if new is not None:
            tg[new] = tg.get(new, 0) + 1
        s2.modify_index = index
        self._t["job_summaries"][key] = s2
        self._bump_locked("job_summaries", index)

    def _update_deployment_with_alloc_locked(self, index: int, a: Allocation,
                                             existing) -> None:
        """Track per-task-group deployment progress as allocs are written
        (reference: state_store.go:4317 updateDeploymentWithAlloc) —
        placements bump placed_allocs/placed_canaries; health transitions
        move healthy/unhealthy counters."""
        if not a.deployment_id:
            return
        dep = self._t["deployments"].get(a.deployment_id)
        if dep is None or a.task_group not in dep.task_groups:
            return
        placed = healthy = unhealthy = 0
        ex_set = (existing is not None and existing.deployment_status is not None
                  and existing.deployment_status.healthy is not None)
        new_set = (a.deployment_status is not None
                   and a.deployment_status.healthy is not None)
        if existing is None or existing.deployment_id != a.deployment_id:
            placed += 1
        elif not ex_set and new_set:
            if a.deployment_status.healthy:
                healthy += 1
            else:
                unhealthy += 1
        elif ex_set and new_set:
            if (existing.deployment_status.healthy
                    and not a.deployment_status.healthy):
                healthy -= 1
                unhealthy += 1
        is_canary = (a.deployment_status is not None
                     and a.deployment_status.canary)
        if placed == 0 and healthy == 0 and unhealthy == 0 and not is_canary:
            return
        if a.deployment_status is not None and (healthy != 0
                                                or unhealthy != 0):
            a.deployment_status.modify_index = index
        d2 = dep.copy()
        d2.modify_index = index
        state = d2.task_groups[a.task_group]
        state.placed_allocs += placed
        state.healthy_allocs += healthy
        state.unhealthy_allocs += unhealthy
        if is_canary and a.id not in state.placed_canaries:
            state.placed_canaries.append(a.id)
        self._t["deployments"][d2.id] = d2

    def _remove_alloc_locked(self, alloc_id: str, index: int = 0) -> None:
        a = self._t["allocs"].pop(alloc_id, None)
        if a is None:
            return
        self.changelog.append(index or self.index, "alloc", alloc_id)
        s = self._t["_allocs_by_node"].get(a.node_id)
        if s:
            s.discard(alloc_id)
        s = self._t["_allocs_by_job"].get((a.namespace, a.job_id))
        if s:
            s.discard(alloc_id)
        # a reaped alloc releases its CSI claims even if it never
        # reported client-terminal (lost node, forced GC) — otherwise
        # the volume is stuck in-use forever
        self._release_csi_claims_locked(index or self.index, alloc_id)
        self._drop_services_locked(index or self.index, alloc_id)

    def update_allocs_from_client(self, index: int,
                                  updates: List[Allocation]) -> None:
        """Apply client status updates (reference: fsm.go:749
        applyAllocClientUpdate — merges client fields into stored alloc)."""
        with self._lock:
            for upd in updates:
                existing = self._t["allocs"].get(upd.id)
                if existing is None:
                    continue
                import copy as _copy
                a = _copy.copy(existing)
                a.client_status = upd.client_status
                a.client_description = upd.client_description
                a.task_states = dict(upd.task_states)
                a.deployment_status = upd.deployment_status
                a.modify_index = index
                a.modify_time = upd.modify_time or a.modify_time
                self._update_deployment_with_alloc_locked(index, a, existing)
                self._update_summary_with_alloc_locked(index, a, existing)
                if (a.client_terminal_status()
                        and not existing.client_terminal_status()):
                    # terminal allocs release their CSI volume claims
                    # (reference: csi_hook postrun -> Volume.Unpublish)
                    self._release_csi_claims_locked(index, a.id)
                self._t["allocs"][a.id] = a
                self.changelog.append(index, "alloc", a.id)
                self._sync_services_locked(index, a)
            # sorted for replica determinism (nomadlint FSM103)
            for key in sorted({(u.namespace, u.job_id) for u in updates}):
                self._refresh_job_status_locked(index, *key)
            self._bump_locked("allocs", index)

    # -- native service discovery (derived from task liveness) --
    def _sync_services_locked(self, index: int, alloc) -> None:
        """Recompute the alloc's registrations from its task states
        (reference: the consul service hook register/deregister on task
        start/stop; here the catalog is native, FSM-deterministic).
        Idempotent: the table index only bumps when the registration set
        actually changes, so blocking-query watchers don't wake on
        unrelated alloc updates."""
        from ..structs.services import ServiceRegistration
        from ..structs import TASK_STATE_RUNNING
        job = alloc.job or self._t["jobs"].get(
            (alloc.namespace, alloc.job_id))
        current = {k: r for k, r in self._t["services"].items()
                   if r.alloc_id == alloc.id}
        desired = {}
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if (tg is not None and not alloc.client_terminal_status()
                and not alloc.server_terminal_status()):
            node = self._t["nodes"].get(alloc.node_id)
            address = ""
            if node is not None and node.node_resources.networks:
                address = node.node_resources.networks[0].ip
            for task in tg.tasks:
                st = alloc.task_states.get(task.name)
                if st is None or st.state != TASK_STATE_RUNNING:
                    continue
                tr = alloc.allocated_resources.tasks.get(task.name)
                for svc in task.services:
                    port = 0
                    if tr is not None and svc.port_label:
                        for net in tr.networks:
                            for p in (list(net.reserved_ports)
                                      + list(net.dynamic_ports)):
                                if p.label == svc.port_label:
                                    port = p.value
                    rid = f"{alloc.id}-{task.name}-{svc.name}"
                    healthy = all(
                        st.checks.get(
                            f"{svc.name}/{c.name or c.type}", False)
                        for c in svc.checks) if svc.checks else True
                    desired[rid] = ServiceRegistration(
                        id=rid, service_name=svc.name,
                        namespace=alloc.namespace,
                        job_id=alloc.job_id, alloc_id=alloc.id,
                        node_id=alloc.node_id, task=task.name,
                        address=address, port=port,
                        tags=list(svc.tags), healthy=healthy,
                        create_index=index, modify_index=index)
        same = (current.keys() == desired.keys() and all(
            (current[k].address, current[k].port, current[k].tags,
             current[k].healthy)
            == (desired[k].address, desired[k].port, desired[k].tags,
                desired[k].healthy)
            for k in desired))
        if same:
            return
        # sorted: the table dict's residual insertion order must not
        # depend on set-difference order (nomadlint FSM103)
        for k in sorted(current.keys() - desired.keys()):
            del self._t["services"][k]
        for k, reg in desired.items():
            old = current.get(k)
            if old is not None:
                reg.create_index = old.create_index
            self._t["services"][k] = reg
        self._bump_locked("services", index)

    def _drop_services_locked(self, index: int, alloc_id: str,
                              bump: bool = True) -> bool:
        doomed = [k for k, r in self._t["services"].items()
                  if r.alloc_id == alloc_id]
        for k in doomed:
            del self._t["services"][k]
        if doomed and bump:
            self._bump_locked("services", index)
        return bool(doomed)

    def service_names(self, namespace: str = "default"):
        with self._lock:
            out = {}
            for r in self._t["services"].values():
                if r.namespace != namespace:
                    continue
                out.setdefault(r.service_name, set()).update(r.tags)
            return [{"ServiceName": name, "Tags": sorted(tags)}
                    for name, tags in sorted(out.items())]

    def services_by_name(self, namespace: str, name: str):
        with self._lock:
            return sorted((r for r in self._t["services"].values()
                           if r.namespace == namespace
                           and r.service_name == name),
                          key=lambda r: r.id)

    # -- secrets (native KV; the Vault-analog secret store) --
    def upsert_secret(self, index: int, namespace: str, path: str,
                      data: Dict[str, str]) -> None:
        with self._lock:
            self._t["secrets"][(namespace, path)] = dict(data)
            self._bump_locked("secrets", index)

    def delete_secret(self, index: int, namespace: str,
                      path: str) -> None:
        with self._lock:
            self._t["secrets"].pop((namespace, path), None)
            self._bump_locked("secrets", index)

    def secret_by_path(self, namespace: str, path: str):
        with self._lock:
            d = self._t["secrets"].get((namespace, path))
            return dict(d) if d is not None else None

    def secret_paths(self, namespace: str = "default"):
        with self._lock:
            return sorted(p for (ns, p) in self._t["secrets"]
                          if ns == namespace)

    # -- ACL (reference: state_store.go ACLPolicy/ACLToken tables) --
    def set_acl_bootstrapped(self, index: int) -> None:
        with self._lock:
            self._t["cluster_meta"]["acl_bootstrapped"] = True
            self._bump_locked("cluster_meta", index)

    def acl_bootstrapped(self) -> bool:
        with self._lock:
            return bool(self._t["cluster_meta"].get("acl_bootstrapped"))

    def upsert_acl_policy(self, index: int, policy) -> None:
        with self._lock:
            import copy as _copy
            p = _copy.copy(policy)
            existing = self._t["acl_policies"].get(p.name)
            p.create_index = existing.create_index if existing else index
            p.modify_index = index
            self._t["acl_policies"][p.name] = p
            self._bump_locked("acl_policies", index)

    def delete_acl_policy(self, index: int, name: str) -> None:
        with self._lock:
            self._t["acl_policies"].pop(name, None)
            self._bump_locked("acl_policies", index)

    def acl_policy_by_name(self, name: str):
        with self._lock:
            return self._t["acl_policies"].get(name)

    def acl_policies(self):
        with self._lock:
            return sorted(self._t["acl_policies"].values(),
                          key=lambda p: p.name)

    def upsert_acl_token(self, index: int, token) -> None:
        with self._lock:
            import copy as _copy
            t = _copy.copy(token)
            existing = self._t["acl_tokens"].get(t.accessor_id)
            t.create_index = existing.create_index if existing else index
            t.modify_index = index
            self._t["acl_tokens"][t.accessor_id] = t
            self._bump_locked("acl_tokens", index)

    def delete_acl_token(self, index: int, accessor_id: str) -> None:
        with self._lock:
            self._t["acl_tokens"].pop(accessor_id, None)
            self._bump_locked("acl_tokens", index)

    def acl_token_by_accessor(self, accessor_id: str):
        with self._lock:
            return self._t["acl_tokens"].get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        with self._lock:
            for t in self._t["acl_tokens"].values():
                if t.secret_id == secret_id:
                    return t
            return None

    def acl_tokens(self):
        with self._lock:
            return sorted(self._t["acl_tokens"].values(),
                          key=lambda t: t.accessor_id)

    # -- CSI volumes (reference: state_store.go CSIVolumeRegister/Claim) --
    def upsert_csi_volume(self, index: int, vol) -> None:
        with self._lock:
            import copy as _copy
            v = _copy.copy(vol)
            existing = self._t["csi_volumes"].get((v.namespace, v.id))
            if existing is not None:
                # re-registration must not wipe live claims (a cleared
                # write_claims would re-admit a second writer on a
                # single-writer volume)
                v.read_claims = dict(existing.read_claims)
                v.write_claims = dict(existing.write_claims)
                v.create_index = existing.create_index
            v.modify_index = index
            self._t["csi_volumes"][(v.namespace, v.id)] = v
            self._bump_locked("csi_volumes", index)

    def delete_csi_volume(self, index: int, namespace: str,
                          vol_id: str) -> None:
        with self._lock:
            v = self._t["csi_volumes"].get((namespace, vol_id))
            if v is not None and v.in_use():
                raise ValueError(f"volume {vol_id} is in use")
            self._t["csi_volumes"].pop((namespace, vol_id), None)
            self._bump_locked("csi_volumes", index)

    def csi_volume_by_id(self, namespace: str, vol_id: str):
        with self._lock:
            return self._t["csi_volumes"].get((namespace, vol_id))

    def csi_volumes(self, namespace: Optional[str] = None):
        with self._lock:
            return [v for (ns, _vid), v in
                    sorted(self._t["csi_volumes"].items())
                    if namespace is None or ns == namespace]

    def claim_csi_volume(self, index: int, namespace: str, vol_id: str,
                         mode: str, alloc_id: str, node_id: str) -> None:
        with self._lock:
            v = self._t["csi_volumes"].get((namespace, vol_id))
            if v is None:
                raise KeyError(f"volume {vol_id} not found")
            import copy as _copy
            v2 = _copy.copy(v)
            v2.read_claims = dict(v.read_claims)
            v2.write_claims = dict(v.write_claims)
            v2.claim(mode, alloc_id, node_id)
            v2.modify_index = index
            self._t["csi_volumes"][(namespace, vol_id)] = v2
            self._bump_locked("csi_volumes", index)

    def release_csi_claims(self, index: int, alloc_id: str) -> None:
        with self._lock:
            self._release_csi_claims_locked(index, alloc_id)

    def _release_csi_claims_locked(self, index: int,
                                   alloc_id: str) -> None:
        changed = False
        import copy as _copy
        for key, v in list(self._t["csi_volumes"].items()):
            if alloc_id in v.read_claims or alloc_id in v.write_claims:
                v2 = _copy.copy(v)
                v2.read_claims = dict(v.read_claims)
                v2.write_claims = dict(v.write_claims)
                v2.release(alloc_id)
                v2.modify_index = index
                self._t["csi_volumes"][key] = v2
                changed = True
        if changed:
            self._bump_locked("csi_volumes", index)

    def update_alloc_desired_transition(self, index: int, alloc_ids: List[str],
                                        transition) -> None:
        with self._lock:
            for aid in alloc_ids:
                existing = self._t["allocs"].get(aid)
                if existing is None:
                    continue
                import copy as _copy
                a = _copy.copy(existing)
                a.desired_transition = transition
                a.modify_index = index
                self._t["allocs"][aid] = a
            self._bump_locked("allocs", index)

    # -- plan results (the single commit path; reference fsm.go:918) --
    def upsert_plan_results(self, index: int, result: PlanResult,
                            job: Optional[Job] = None) -> None:
        with self._lock:
            # deployment first so _update_deployment_with_alloc_locked sees
            # it when the plan's own placements land (reference order,
            # state_store.go:253-263)
            if result.deployment is not None:
                self._upsert_deployment_locked(index, result.deployment)
            for du in result.deployment_updates:
                self._apply_deployment_update_locked(index, du)
            for allocs in result.node_update.values():
                for a in allocs:
                    existing = self._t["allocs"].get(a.id)
                    if existing is not None and a.job is None:
                        a.job = existing.job
                    self._upsert_alloc_locked(index, a)
            for allocs in result.node_allocation.values():
                for a in allocs:
                    if a.job is None:
                        a.job = job
                    self._upsert_alloc_locked(index, a)
            for allocs in result.node_preemptions.values():
                for a in allocs:
                    existing = self._t["allocs"].get(a.id)
                    if existing is not None and a.job is None:
                        a.job = existing.job
                    self._upsert_alloc_locked(index, a)
            touched = set()
            for m in (result.node_update, result.node_allocation,
                      result.node_preemptions):
                for allocs in m.values():
                    touched.update((a.namespace, a.job_id) for a in allocs)
            # sorted for replica determinism (nomadlint FSM103)
            for key in sorted(touched):
                self._refresh_job_status_locked(index, *key)
            self._bump_locked("allocs", index)

    # -- deployments --
    def upsert_deployment(self, index: int, dep: Deployment) -> None:
        with self._lock:
            self._upsert_deployment_locked(index, dep)
            self._bump_locked("deployments", index)

    def _upsert_deployment_locked(self, index: int, dep: Deployment) -> None:
        existing = self._t["deployments"].get(dep.id)
        if existing is not None:
            dep.create_index = existing.create_index
        else:
            dep.create_index = index
        dep.modify_index = index
        self._t["deployments"][dep.id] = dep

    def _apply_deployment_update_locked(self, index: int, du) -> None:
        dep = self._t["deployments"].get(du.deployment_id)
        if dep is None:
            return
        d2 = dep.copy()
        d2.status = du.status
        d2.status_description = du.status_description
        d2.modify_index = index
        self._t["deployments"][du.deployment_id] = d2
        # a deployment going SUCCESSFUL marks its job version stable in
        # the SAME apply, no matter which path flipped it — the watcher
        # or a reconciler plan (reference: state_store.go
        # updateDeploymentStatusImpl -> updateJobStabilityImpl; the
        # watcher racing the plan applier must not lose the stability
        # bit)
        from ..structs import DEPLOYMENT_STATUS_SUCCESSFUL
        if (du.status == DEPLOYMENT_STATUS_SUCCESSFUL
                and dep.status != DEPLOYMENT_STATUS_SUCCESSFUL):
            self._mark_stable_locked(index, dep.namespace, dep.job_id,
                                     dep.job_version)

    def upsert_deployment_updates(self, index: int, updates) -> None:
        """Standalone deployment status updates (reference:
        fsm.go applyDeploymentStatusUpdate)."""
        with self._lock:
            for du in updates:
                self._apply_deployment_update_locked(index, du)
            self._bump_locked("deployments", index)

    def update_deployment_promotion(self, index: int, dep_id: str,
                                    groups=None) -> None:
        """Flip promoted for canary groups (reference:
        state_store.go UpdateDeploymentPromotion). groups=None promotes
        every canary group."""
        with self._lock:
            dep = self._t["deployments"].get(dep_id)
            if dep is None:
                raise KeyError(f"deployment {dep_id} not found")
            d2 = dep.copy()
            for name, state in d2.task_groups.items():
                if state.desired_canaries <= 0:
                    continue
                if groups is not None and name not in groups:
                    continue
                state.promoted = True
            d2.status_description = "Deployment is running"
            d2.modify_index = index
            self._t["deployments"][dep_id] = d2
            self._bump_locked("deployments", index)

    def delete_deployment(self, index: int, dep_ids: List[str]) -> None:
        with self._lock:
            for did in dep_ids:
                self._t["deployments"].pop(did, None)
            self._bump_locked("deployments", index)

    # -- scheduler config --
    def set_scheduler_config(self, index: int,
                             cfg: SchedulerConfiguration) -> None:
        with self._lock:
            cfg.modify_index = index
            self._t["scheduler_config"]["config"] = cfg
            self._bump_locked("scheduler_config", index)

    # -- periodic launches --
    def upsert_periodic_launch(self, index: int, namespace: str, job_id: str,
                               launch_time: float) -> None:
        with self._lock:
            self._t["periodic_launches"][(namespace, job_id)] = launch_time
            self._bump_locked("periodic_launches", index)

    def periodic_launch(self, namespace: str, job_id: str) -> Optional[float]:
        with self._lock:    # guarded table; lockless read is racy
            return self._t["periodic_launches"].get((namespace, job_id))
