"""Device-side fleet health kernel + bit-identical numpy host twin.

One jit reduction over the resident node planes (avail / valid /
node_dc / dev_cap) and the carried usage planes turns the whole fleet
into a handful of integers per wave: per-resource utilization
ge-counts (the histogram), stranded-capacity fragmentation inputs,
busy / per-DC counts for spread-violation accounting, evictable
pressure and device totals.  The kernel runs unchanged on the plain
resident solver, the NamedSharding'd mesh solvers (GSPMD inserts the
cross-shard psums) and the federated region stack (rows flattened).

Bit-identity with the numpy twin is by construction, not luck:

  * every reduced quantity is an INTEGER.  Per-node scalars are
    clamped to [0, 2^24) (f32-exact), split into hi = v >> 14 /
    lo = v & 16383 and summed in i32 — order-independent, overflow-
    free for up to 2^17 nodes (`MAX_NODES`, guarded at the call
    site) — then recombined host-side as Python ints.
  * histogram membership uses MULTIPLICATION against exact-f32
    threshold edges (`used >= avail * edge`), never division: float
    multiply is correctly rounded everywhere, while TPU division may
    lower to a reciprocal approximation.
  * the host twin applies the SAME clamps in the same order, so both
    sides saturate identically (a per-node value above 2^24-1 is
    reported as 2^24-1 on both sides — semantic saturation, not
    drift).

The per-tier (ICI/DCN/WAN) byte totals ride along in the REPORT, not
the kernel: they come from the mesh solvers' wave_traffic byte model
at the sampling site (see `tier_bytes`), which already owns the
topology.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

#: static DC-universe bound for the segment-sum planes; node_dc ids are
#: clamped into it (interned ids are small in practice).
MAX_DC = 64

#: utilization ge-thresholds: 0 and 1 - 2^-k for k = 1..6, then 1.0.
#: All exactly representable in f32, so `avail * edge` is a single
#: correctly-rounded multiply on every backend.
UTIL_EDGES: Tuple[float, ...] = (
    0.0, 0.5, 0.75, 0.875, 0.9375, 0.96875, 0.984375, 1.0)
N_EDGES = len(UTIL_EDGES)

#: a node is "busy" when any resource sits at >= 3/4 of its allocatable
#: capacity (the classic bin-packing pressure watermark).
BUSY_EDGE = 0.75

#: per-node integer ceiling: clamped to [0, 2^24) so every value is
#: f32-exact and the hi/lo split sums cannot overflow i32.
_CAP_I = (1 << 24) - 1
_CAP_F = np.float32(_CAP_I)
_SPLIT = 1 << 14

#: hi/lo split sums stay inside i32 for up to this many nodes
#: (2^17 * max(hi) = 2^17 * 2^10 < 2^31); health_counters guards it.
MAX_NODES = 1 << 17


def _split_sum(v_i):
    """Order-independent i32 split sum over the node axis (axis 0)."""
    return ((v_i >> 14).sum(axis=0),
            (v_i & (_SPLIT - 1)).sum(axis=0))


@jax.jit
def _health_kernel(avail, valid, node_dc, dev_cap, used, dev_used,
                   ask_res, live, ev_prio, ev_res):
    """One-pass fleet reduction; returns a dict of small i32 arrays.

    `live` masks device rows whose tile is still resident (elastic
    layouts keep STALE plane rows for retired/lost tiles — `valid`
    alone is not enough); None means every row is live.  `ev_prio` /
    `ev_res` are None when the world has no preemption planes.
    """
    if live is not None:
        valid = jnp.logical_and(valid, live)
    edges = jnp.asarray(UTIL_EDGES, dtype=jnp.float32)
    av = jnp.where(valid[:, None], jnp.clip(avail, 0.0, _CAP_F), 0.0)
    us = jnp.where(valid[:, None], jnp.clip(used, 0.0, _CAP_F), 0.0)
    free = jnp.clip(av - us, 0.0, _CAP_F)
    av_i = av.astype(jnp.int32)
    us_i = us.astype(jnp.int32)
    free_i = free.astype(jnp.int32)

    # ge-counts per (resource, edge): in-bucket histogram derived
    # host-side as ge[k] - ge[k+1].  av is zeroed for invalid rows, so
    # the av > 0 gate doubles as the validity gate.
    cap_pos = (av > 0.0)
    ge = jnp.logical_and(
        us[:, :, None] >= av[:, :, None] * edges,
        cap_pos[:, :, None]).astype(jnp.int32).sum(axis=0)   # [R, E]

    busy = jnp.logical_and(
        cap_pos, us >= av * jnp.float32(BUSY_EDGE)).any(axis=1)

    # stranded capacity: free somewhere, but no nonzero probe ask fits
    # whole on the node — the numerator of the fragmentation index.
    ask_mask = (ask_res > 0.0).any(axis=1)                   # [Gp]
    fits = (ask_res[None, :, :] <= free[:, None, :]).all(axis=2)
    placeable = jnp.logical_and(fits, ask_mask[None, :]).any(axis=1)
    stranded = jnp.logical_and(
        jnp.logical_and(valid, free_i.sum(axis=1) > 0),
        jnp.logical_not(placeable))

    dcc = jnp.clip(node_dc, 0, MAX_DC - 1)

    # device planes: few device types per node, so sum over the device
    # axis first, then clamp (saturation rule shared with the twin).
    dcap = jnp.minimum(
        jnp.where(valid[:, None],
                  jnp.clip(dev_cap, 0.0, _CAP_F), 0.0)
        .astype(jnp.int32).sum(axis=1), _CAP_I)
    dusd = jnp.minimum(
        jnp.where(valid[:, None],
                  jnp.clip(dev_used, 0.0, _CAP_F), 0.0)
        .astype(jnp.int32).sum(axis=1), _CAP_I)

    # outputs STACKED into a handful of buffers: dispatch + fetch cost
    # on the sampling beat scales with output-buffer count, not bytes
    # (order mirrors _SCALAR_KEYS / _SUM_KEYS in the host unpack)
    scalars = [valid.astype(jnp.int32).sum(),
               busy.astype(jnp.int32).sum(),
               stranded.astype(jnp.int32).sum(),
               (dcap >> 14).sum(), (dcap & (_SPLIT - 1)).sum(),
               (dusd >> 14).sum(), (dusd & (_SPLIT - 1)).sum()]
    sums = [jnp.stack(_split_sum(v_i))
            for v_i in (free_i, us_i, av_i,
                        jnp.where(stranded[:, None], free_i, 0))]

    if ev_prio is not None:
        slots = jnp.logical_and(ev_prio >= 0, valid[:, None])
        scalars.append(slots.astype(jnp.int32).sum())
        ev_i = jnp.minimum(
            jnp.where(slots[:, :, None],
                      jnp.clip(ev_res, 0.0, _CAP_F), 0.0)
            .astype(jnp.int32).sum(axis=1), _CAP_I)       # [Np, R]
        sums.append(jnp.stack(_split_sum(ev_i)))
    return {
        "scalars": jnp.stack(scalars),
        "sums": jnp.stack(sums),
        "util_ge": ge,
        "dc_nodes": jax.ops.segment_sum(
            valid.astype(jnp.int32), dcc, num_segments=MAX_DC),
        "dc_busy": jax.ops.segment_sum(
            busy.astype(jnp.int32), dcc, num_segments=MAX_DC),
    }


#: unpack order for the kernel's stacked outputs (ev entries ride at
#: the end only when the world packs preemption planes)
_SCALAR_KEYS = ("nodes_valid", "nodes_busy", "nodes_stranded",
                "dev_cap_hi", "dev_cap_lo", "dev_used_hi",
                "dev_used_lo")
_SUM_KEYS = ("free", "used", "avail", "stranded_free")


def _unpack_raw(got: Dict) -> Dict:
    """Fan the kernel's stacked buffers back out to the flat raw-dict
    key space `HealthCounters.from_raw` and the host twin share."""
    raw = {"util_ge": got["util_ge"], "dc_nodes": got["dc_nodes"],
           "dc_busy": got["dc_busy"]}
    sc = np.asarray(got["scalars"])
    for i, k in enumerate(_SCALAR_KEYS):
        raw[k] = sc[i]
    if sc.shape[0] > len(_SCALAR_KEYS):
        raw["ev_slots"] = sc[len(_SCALAR_KEYS)]
    sums = np.asarray(got["sums"])
    for i, k in enumerate(_SUM_KEYS):
        raw[k + "_hi"], raw[k + "_lo"] = sums[i, 0], sums[i, 1]
    if sums.shape[0] > len(_SUM_KEYS):
        raw["ev_hi"], raw["ev_lo"] = sums[-1, 0], sums[-1, 1]
    return raw


def _recombine(hi, lo) -> Tuple[int, ...]:
    hi = np.atleast_1d(np.asarray(hi))
    lo = np.atleast_1d(np.asarray(lo))
    return tuple(int(h) * _SPLIT + int(l) for h, l in zip(hi, lo))


@dataclasses.dataclass(frozen=True)
class HealthCounters:
    """Exact integer fleet counters for one sampling wave.

    Tuple-typed fields (never arrays) so `==` between the device and
    host-twin products is structural — the property tests compare
    whole dataclasses.
    """
    n_resources: int
    nodes_valid: int
    nodes_busy: int
    nodes_stranded: int
    util_ge: Tuple[Tuple[int, ...], ...]   # [R][N_EDGES] ge-counts
    free: Tuple[int, ...]                  # per-resource exact sums
    used: Tuple[int, ...]
    avail: Tuple[int, ...]
    stranded_free: Tuple[int, ...]
    dc_nodes: Tuple[int, ...]              # [MAX_DC]
    dc_busy: Tuple[int, ...]
    dev_cap: int
    dev_used: int
    ev_slots: int = 0
    ev_pressure: Tuple[int, ...] = ()      # per-resource evictable sums

    @classmethod
    def from_raw(cls, raw: Dict) -> "HealthCounters":
        ge = np.asarray(raw["util_ge"])
        kw = {}
        if "ev_slots" in raw:
            kw = {"ev_slots": int(raw["ev_slots"]),
                  "ev_pressure": _recombine(raw["ev_hi"],
                                            raw["ev_lo"])}
        return cls(
            n_resources=int(ge.shape[0]),
            nodes_valid=int(raw["nodes_valid"]),
            nodes_busy=int(raw["nodes_busy"]),
            nodes_stranded=int(raw["nodes_stranded"]),
            util_ge=tuple(tuple(int(x) for x in row) for row in ge),
            free=_recombine(raw["free_hi"], raw["free_lo"]),
            used=_recombine(raw["used_hi"], raw["used_lo"]),
            avail=_recombine(raw["avail_hi"], raw["avail_lo"]),
            stranded_free=_recombine(raw["stranded_free_hi"],
                                     raw["stranded_free_lo"]),
            dc_nodes=tuple(int(x) for x in np.asarray(raw["dc_nodes"])),
            dc_busy=tuple(int(x) for x in np.asarray(raw["dc_busy"])),
            dev_cap=_recombine(raw["dev_cap_hi"],
                               raw["dev_cap_lo"])[0],
            dev_used=_recombine(raw["dev_used_hi"],
                                raw["dev_used_lo"])[0],
            **kw)

    def merge(self, other: "HealthCounters") -> "HealthCounters":
        """Counter-wise sum — every field is a sum over nodes, so
        merging regions == computing over the union fleet."""
        if self.n_resources != other.n_resources:
            raise ValueError("resource-dim mismatch in health merge")
        add = lambda a, b: tuple(x + y for x, y in zip(a, b))
        ep = (add(self.ev_pressure, other.ev_pressure)
              if self.ev_pressure and other.ev_pressure
              else self.ev_pressure or other.ev_pressure)
        return HealthCounters(
            n_resources=self.n_resources,
            nodes_valid=self.nodes_valid + other.nodes_valid,
            nodes_busy=self.nodes_busy + other.nodes_busy,
            nodes_stranded=self.nodes_stranded + other.nodes_stranded,
            util_ge=tuple(add(a, b) for a, b in
                          zip(self.util_ge, other.util_ge)),
            free=add(self.free, other.free),
            used=add(self.used, other.used),
            avail=add(self.avail, other.avail),
            stranded_free=add(self.stranded_free, other.stranded_free),
            dc_nodes=add(self.dc_nodes, other.dc_nodes),
            dc_busy=add(self.dc_busy, other.dc_busy),
            dev_cap=self.dev_cap + other.dev_cap,
            dev_used=self.dev_used + other.dev_used,
            ev_slots=self.ev_slots + other.ev_slots,
            ev_pressure=ep)

    # ------------------------------------------------- derived report
    def spread_violations(self) -> int:
        """DCs whose busy share exceeds 1.5x their node share —
        exact integer cross-multiply, no float ratios."""
        if self.nodes_busy <= 0 or self.nodes_valid <= 0:
            return 0
        out = 0
        for nodes_d, busy_d in zip(self.dc_nodes, self.dc_busy):
            if busy_d > 0 and \
                    2 * busy_d * self.nodes_valid > \
                    3 * nodes_d * self.nodes_busy:
                out += 1
        return out

    def util_hist(self) -> Tuple[Tuple[int, ...], ...]:
        """In-bucket counts per resource: bucket k = [edge_k,
        edge_{k+1}), last bucket = full/overcommitted (u >= 1)."""
        out = []
        for ge in self.util_ge:
            row = [ge[k] - ge[k + 1] for k in range(N_EDGES - 1)]
            row.append(ge[N_EDGES - 1])
            out.append(tuple(row))
        return tuple(out)

    def fragmentation_index(self) -> float:
        """Stranded fraction of free capacity across all resources:
        1.0 = every free unit is on a node nothing placeable fits."""
        total_free = sum(self.free)
        if total_free <= 0:
            return 0.0
        return sum(self.stranded_free) / total_free

    def _dc_report(self) -> Dict:
        """Per-DC counts trimmed to the populated id range."""
        n_dc = max((i + 1 for i, n in enumerate(self.dc_nodes) if n),
                   default=0)
        return {"nodes": list(self.dc_nodes[:n_dc]),
                "busy": list(self.dc_busy[:n_dc])}

    def report(self, tiers: Optional[Dict] = None) -> Dict:
        total_avail = sum(self.avail)
        out = {
            "nodes": {"valid": self.nodes_valid,
                      "busy": self.nodes_busy,
                      "stranded": self.nodes_stranded},
            "utilization": (sum(self.used) / total_avail
                            if total_avail > 0 else 0.0),
            "util_edges": list(UTIL_EDGES),
            "util_hist": [list(r) for r in self.util_hist()],
            "fragmentation_index": self.fragmentation_index(),
            "stranded_free": list(self.stranded_free),
            "free": list(self.free),
            "used": list(self.used),
            "avail": list(self.avail),
            "spread_violations": self.spread_violations(),
            "dc": self._dc_report(),
            "evictable": {"slots": self.ev_slots,
                          "pressure": list(self.ev_pressure)},
            "devices": {"cap": self.dev_cap, "used": self.dev_used},
        }
        if tiers:
            out["tier_bytes"] = dict(tiers)
        return out


# ---------------------------------------------------------- host twin
def health_host(template, used, dev_used,
                row_mask: Optional[np.ndarray] = None
                ) -> HealthCounters:
    """Numpy twin of `_health_kernel` over a host-side PackedBatch
    mirror: same clamps, same multiply-threshold compares, same split
    accumulators, identical saturation.  `row_mask` selects the rows
    the device world actually holds (elastic layouts drop lost tiles).
    """
    f32 = np.float32
    valid = np.asarray(template.valid, bool).copy()
    if row_mask is not None:
        valid &= np.asarray(row_mask, bool)
    edges = np.asarray(UTIL_EDGES, dtype=f32)
    av = np.where(valid[:, None],
                  np.clip(np.asarray(template.avail, f32),
                          f32(0), _CAP_F), f32(0))
    us = np.where(valid[:, None],
                  np.clip(np.asarray(used, f32), f32(0), _CAP_F),
                  f32(0))
    free = np.clip(av - us, f32(0), _CAP_F)
    av_i = av.astype(np.int32)
    us_i = us.astype(np.int32)
    free_i = free.astype(np.int32)

    cap_pos = av > 0
    ge = np.logical_and(
        us[:, :, None] >= av[:, :, None] * edges,
        cap_pos[:, :, None]).astype(np.int32).sum(axis=0)

    busy = np.logical_and(cap_pos, us >= av * f32(BUSY_EDGE)).any(axis=1)

    ask_res = np.asarray(template.ask_res, f32)
    ask_mask = (ask_res > 0).any(axis=1)
    fits = (ask_res[None, :, :] <= free[:, None, :]).all(axis=2)
    placeable = np.logical_and(fits, ask_mask[None, :]).any(axis=1)
    stranded = valid & (free_i.sum(axis=1) > 0) & ~placeable

    dcc = np.clip(np.asarray(template.node_dc), 0, MAX_DC - 1)
    dc_nodes = np.zeros(MAX_DC, np.int32)
    np.add.at(dc_nodes, dcc, valid.astype(np.int32))
    dc_busy = np.zeros(MAX_DC, np.int32)
    np.add.at(dc_busy, dcc, busy.astype(np.int32))

    raw: Dict = {
        "nodes_valid": valid.astype(np.int32).sum(),
        "nodes_busy": busy.astype(np.int32).sum(),
        "nodes_stranded": stranded.astype(np.int32).sum(),
        "util_ge": ge, "dc_nodes": dc_nodes, "dc_busy": dc_busy,
    }
    for name, v_i in (("free", free_i), ("used", us_i),
                      ("avail", av_i),
                      ("stranded_free",
                       np.where(stranded[:, None], free_i, 0))):
        raw[name + "_hi"], raw[name + "_lo"] = _split_sum(v_i)

    for name, plane in (("dev_cap", template.dev_cap),
                        ("dev_used", dev_used)):
        v = np.minimum(
            np.where(valid[:, None],
                     np.clip(np.asarray(plane, f32), f32(0), _CAP_F),
                     f32(0)).astype(np.int32).sum(axis=1),
            np.int32(_CAP_I))
        raw[name + "_hi"] = (v >> 14).sum()
        raw[name + "_lo"] = (v & (_SPLIT - 1)).sum()

    if getattr(template, "ev_prio", None) is not None:
        slots = np.logical_and(
            np.asarray(template.ev_prio) >= 0, valid[:, None])
        raw["ev_slots"] = slots.astype(np.int32).sum()
        ev_i = np.minimum(
            np.where(slots[:, :, None],
                     np.clip(np.asarray(template.ev_res, f32),
                             f32(0), _CAP_F), f32(0))
            .astype(np.int32).sum(axis=1),
            np.int32(_CAP_I))
        raw["ev_hi"], raw["ev_lo"] = _split_sum(ev_i)
    return HealthCounters.from_raw(raw)


# ------------------------------------------------------ solver driver
def device_health_raw(solver) -> Dict:
    """Dispatch the health kernel over a resident solver's device
    planes and return the UNFETCHED raw output dict — the async half
    of `device_health_counters`, for samplers that must not stall the
    dispatch stream: dispatch now, materialize a beat later with
    `HealthCounters.from_raw(jax.device_get(raw))` once the stream
    has moved on (the arrays snapshot the planes at dispatch time).

    Reuses the solver's plane caches (the probe ask_res is re-put
    only when the template changes, via `_put_ask` so mesh solvers
    replicate it).
    """
    dn = solver._dev_node
    np_rows = int(solver.template.avail.shape[0])
    if np_rows > MAX_NODES:
        raise ValueError(
            f"health kernel split accumulators are i32-safe up to "
            f"{MAX_NODES} nodes; got {np_rows}")
    # keyed on (template, mesh): a repack swaps the template, and a
    # shard-loss/recover swaps the mesh the replica must live on
    mesh = getattr(solver, "_mesh", None)
    cache = solver.__dict__.get("_health_ask_dev")
    if cache is None or cache[0] is not solver.template \
            or cache[1] is not mesh:
        dev = solver._put_ask(
            "health_ask_res",
            np.asarray(solver.template.ask_res, np.float32))
        solver.__dict__["_health_ask_dev"] = cache = (
            solver.template, mesh, dev)
    live = None
    live_fn = getattr(solver, "_health_live_mask", None)
    if live_fn is not None:
        live = live_fn()
    return _health_kernel(
        dn["avail"], dn["valid"], dn["node_dc"], dn["dev_cap"],
        solver._used, solver._dev_used, cache[2], live,
        dn.get("ev_prio"), dn.get("ev_res"))


def fetch_health(raw) -> HealthCounters:
    """Materialize a `device_health_raw` dispatch (blocking)."""
    return HealthCounters.from_raw(_unpack_raw(jax.device_get(raw)))


def device_health_counters(solver) -> HealthCounters:
    """Run the health kernel over a resident solver's device planes:
    one kernel dispatch + one blocking fetch."""
    return fetch_health(device_health_raw(solver))


def tier_bytes(solver, batches: Optional[Sequence] = None
               ) -> Dict[str, int]:
    """Per-tier modeled byte totals for the last dispatched stream —
    HBM always, ICI/DCN/WAN when the solver's wave_traffic models
    those tiers (mesh / federated solvers).  Advisory: returns {} when
    no stream has been dispatched or the model fails."""
    if not batches:
        return {}
    try:
        wt = solver.wave_traffic(list(batches))
    except Exception:
        return {}   # the byte model must never fail a health sample
    m = wt.get("measured") or {}
    waves = int(m.get("waves_total", 1)) or 1
    out: Dict[str, int] = {}
    if "modeled_bytes_total" in m:
        out["hbm"] = int(m["modeled_bytes_total"])
    else:
        out["hbm"] = int(wt.get("bytes_per_wave", 0)) * waves
    for tier, key in (("ici", "bytes_ici_per_wave"),
                      ("dcn", "bytes_dcn_per_wave"),
                      ("wan", "bytes_wan_per_wave")):
        if key in wt:
            out[tier] = int(wt[key]) * waves
    return out
