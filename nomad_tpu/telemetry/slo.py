"""Multi-window SLO error-budget (burn-rate) accounting.

Classic SRE-workbook alerting shape: an SLO of `objective` (e.g.
99.9% of evals under the p99 latency target) defines an error budget
of `1 - objective`.  The burn rate over a window is

    burn(w) = (bad_fraction over w) / budget

so burn 1.0 consumes exactly the budget over the SLO period, 14.4
exhausts a 30-day budget in ~2 days.  Two windows are tracked:

  * FAST (default 60s, threshold 14): page-grade — a sudden cliff.
  * SLOW (default 600s, threshold 2): ticket-grade — a slow leak.

Alerts flip with hysteresis (clear at half the trip threshold) and
surface both ways the rest of this repo reports: a `slo.burn` mesh
event on trip/clear, and `slo.*` gauges every observation.

The ring holds per-second (good, bad) pairs bounded by the slow
window, so memory is O(slow_window_s).  Clock injected for tests.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple


class SloBurnTracker:
    FAST = "fast"
    SLOW = "slow"

    def __init__(self, objective: float = 0.999,
                 fast_window_s: int = 60, fast_burn: float = 14.0,
                 slow_window_s: int = 600, slow_burn: float = 2.0,
                 clock=time.monotonic,
                 events=None, metrics=None, prefix: str = "slo"):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1): {objective}")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError("windows must satisfy 0 < fast <= slow")
        self.objective = objective
        self.budget = 1.0 - objective
        self.windows: Tuple[Tuple[str, int, float], ...] = (
            (self.FAST, int(fast_window_s), float(fast_burn)),
            (self.SLOW, int(slow_window_s), float(slow_burn)))
        self._clock = clock
        self._events = events
        self._metrics = metrics
        self._prefix = prefix
        self._lock = threading.Lock()
        # ring of (second, good, bad) triples, newest last, spanning
        # at most slow_window_s distinct seconds
        self._ring: List[List[int]] = []
        self._alerting: Dict[str, bool] = {
            self.FAST: False, self.SLOW: False}

    # ------------------------------------------------------- feeding
    def observe(self, good: int = 0, bad: int = 0,
                now: Optional[float] = None) -> None:
        """Fold a batch of SLO verdicts into the current second and
        re-evaluate both windows."""
        t = int(self._clock() if now is None else now)
        fired: List[Tuple[str, bool, float]] = []
        with self._lock:
            if self._ring and self._ring[-1][0] == t:
                self._ring[-1][1] += int(good)
                self._ring[-1][2] += int(bad)
            else:
                self._ring.append([t, int(good), int(bad)])
            horizon = t - self.windows[-1][1]
            while self._ring and self._ring[0][0] <= horizon:
                self._ring.pop(0)
            for name, w, threshold in self.windows:
                burn = self._burn_locked(t, w)
                on = self._alerting[name]
                if not on and burn >= threshold:
                    self._alerting[name] = True
                    fired.append((name, True, burn))
                elif on and burn < threshold / 2.0:
                    self._alerting[name] = False
                    fired.append((name, False, burn))
                if self._metrics is not None:
                    self._metrics.set_gauge(
                        f"{self._prefix}.burn_{name}", burn)
        if self._metrics is not None:
            self._metrics.set_gauge(
                f"{self._prefix}.alerting",
                1.0 if any(self._alerting.values()) else 0.0)
        for name, on, burn in fired:
            if self._events is not None:
                self._events.record(
                    "slo.burn", window=name,
                    state="trip" if on else "clear",
                    burn_rate=round(burn, 4),
                    objective=self.objective)

    # ------------------------------------------------------- reading
    def _burn_locked(self, t: int, window_s: int) -> float:
        lo = t - window_s
        good = bad = 0
        for sec, g, b in self._ring:
            if sec > lo:
                good += g
                bad += b
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / self.budget

    def burn_rate(self, window_s: int,
                  now: Optional[float] = None) -> float:
        t = int(self._clock() if now is None else now)
        with self._lock:
            return self._burn_locked(t, window_s)

    def status(self, now: Optional[float] = None) -> Dict:
        t = int(self._clock() if now is None else now)
        with self._lock:
            out = {"objective": self.objective,
                   "budget": self.budget,
                   "windows": {}, "alerting": dict(self._alerting)}
            for name, w, threshold in self.windows:
                out["windows"][name] = {
                    "window_s": w, "threshold": threshold,
                    "burn_rate": self._burn_locked(t, w)}
            return out
