"""Multi-resolution time-series rings with downsampling rollover.

`TimeSeriesStore` keeps, per series name, one fixed-size ring per
resolution (1s / 10s / 60s by default).  A `record(name, value)` lands
in the current 1s bucket; when the wall clock crosses a bucket
boundary the finalized point (min / max / sum / count over the bucket)
is pushed into the 1s ring AND merged into the current 10s bucket,
which rolls over into the 60s ring the same way.  Memory is bounded:
ring lengths are fixed at construction, the name universe is capped
(overflow recorded in a counter, mirroring MetricsRegistry's
admission cap), and a point is a 5-tuple — no per-sample retention.

An optional JSONL sink receives every FINALIZED 1s point (one line
per point), so a scrape-less deployment still gets a durable,
greppable trail at bounded rate.

Clock is injected (`clock=time.monotonic` default) so the rollover
tests drive time explicitly, like every other timed component here.
"""
from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: (resolution_seconds, ring_length) — 2h of 1s, ~5.5h of 10s, 24h of
#: 60s; ~7200 + 2000 + 1440 points * 5 floats per name, worst case.
DEFAULT_RESOLUTIONS: Tuple[Tuple[int, int], ...] = (
    (1, 7200), (10, 2000), (60, 1440))

#: series-name admission cap (same spirit as MetricsRegistry's
#: per-namespace cap): past it, records land in the overflow counter
#: instead of growing memory.
DEFAULT_MAX_NAMES = 256

OVERFLOW_NAME = "telemetry.series_overflow"


class _Bucket:
    __slots__ = ("start", "mn", "mx", "sum", "count")

    def __init__(self, start: int):
        self.start = start
        self.mn = float("inf")
        self.mx = float("-inf")
        self.sum = 0.0
        self.count = 0

    def add(self, v: float) -> None:
        if v < self.mn:
            self.mn = v
        if v > self.mx:
            self.mx = v
        self.sum += v
        self.count += 1

    def merge(self, p: Tuple) -> None:
        # p = (t, mn, mx, sum, count) — a finalized finer-grain point
        if p[1] < self.mn:
            self.mn = p[1]
        if p[2] > self.mx:
            self.mx = p[2]
        self.sum += p[3]
        self.count += p[4]

    def point(self) -> Tuple[int, float, float, float, int]:
        return (self.start, self.mn, self.mx, self.sum, self.count)


class _Ring:
    """Fixed-capacity append ring of finalized points."""
    __slots__ = ("cap", "buf", "head", "n")

    def __init__(self, cap: int):
        self.cap = cap
        self.buf: List = [None] * cap
        self.head = 0
        self.n = 0

    def push(self, p) -> None:
        self.buf[self.head] = p
        self.head = (self.head + 1) % self.cap
        if self.n < self.cap:
            self.n += 1

    def points(self) -> List:
        if self.n < self.cap:
            return [p for p in self.buf[:self.n]]
        return self.buf[self.head:] + self.buf[:self.head]


class _Series:
    __slots__ = ("rings", "cur")

    def __init__(self, resolutions):
        self.rings = [_Ring(cap) for _, cap in resolutions]
        self.cur: List[Optional[_Bucket]] = [None] * len(resolutions)


class TimeSeriesStore:
    """Thread-safe multi-resolution ring store (tentpole b)."""

    def __init__(self,
                 resolutions: Sequence[Tuple[int, int]] =
                 DEFAULT_RESOLUTIONS,
                 max_names: int = DEFAULT_MAX_NAMES,
                 sink: Optional[io.TextIOBase] = None,
                 clock=time.monotonic):
        res = sorted(resolutions)
        if not res or any(r <= 0 or cap <= 0 for r, cap in res):
            raise ValueError(f"bad resolutions: {resolutions}")
        for (ra, _), (rb, _) in zip(res, res[1:]):
            if rb % ra != 0:
                raise ValueError(
                    f"resolutions must nest (each a multiple of the "
                    f"previous): {resolutions}")
        self.resolutions = tuple(res)
        self.max_names = max_names
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        self._overflow = 0
        self._sink = sink
        self._sink_lock = threading.Lock()

    # ------------------------------------------------------ recording
    def record(self, name: str, value: float,
               now: Optional[float] = None) -> None:
        t = self._clock() if now is None else now
        lines = None
        with self._lock:
            s = self._series.get(name)
            if s is None:
                if len(self._series) >= self.max_names and \
                        name != OVERFLOW_NAME:
                    self._overflow += 1
                    return
                s = self._series[name] = _Series(self.resolutions)
            lines = self._roll_locked(name, s, t)
            b = s.cur[0]
            if b is None:
                b = s.cur[0] = _Bucket(
                    int(t) // self.resolutions[0][0]
                    * self.resolutions[0][0])
            b.add(float(value))
        if lines:
            self._emit(lines)

    def _roll_locked(self, name: str, s: _Series, t: float) -> List:
        """Finalize any current buckets the clock has moved past,
        cascading each finalized point into the next resolution.
        Returns sink lines to emit outside the lock."""
        lines: List[str] = []
        carry = None
        for i, (res, _cap) in enumerate(self.resolutions):
            b = s.cur[i]
            if carry is not None:
                if b is None:
                    b = s.cur[i] = _Bucket(
                        carry[0] // res * res)
                b.merge(carry)
            carry = None
            if b is not None and int(t) // res * res > b.start:
                p = b.point()
                s.rings[i].push(p)
                s.cur[i] = None
                carry = p
                if i == 0 and self._sink is not None:
                    lines.append(json.dumps(
                        {"name": name, "t": p[0], "min": p[1],
                         "max": p[2], "sum": p[3], "count": p[4]},
                        separators=(",", ":")))
        return lines

    def _emit(self, lines: List[str]) -> None:
        sink = self._sink
        if sink is None:
            return
        with self._sink_lock:
            for ln in lines:
                sink.write(ln + "\n")

    def flush(self, now: Optional[float] = None) -> None:
        """Finalize every in-progress bucket (shutdown / test hook)."""
        t = self._clock() if now is None else now
        out: List[str] = []
        with self._lock:
            for name, s in self._series.items():
                # nudge past every resolution's bucket end
                out += self._roll_locked(
                    name, s, t + self.resolutions[-1][0])
        if out:
            self._emit(out)
        if self._sink is not None:
            with self._sink_lock:
                self._sink.flush()

    # -------------------------------------------------------- reading
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, name: str, res: int = 1,
               since: float = 0.0) -> List[Dict]:
        """Finalized points for one series at one resolution, oldest
        first, bucket start > `since` (the HTTP cursor)."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            for i, (r, _cap) in enumerate(self.resolutions):
                if r == int(res):
                    pts = s.rings[i].points()
                    break
            else:
                raise KeyError(f"no ring at resolution {res}s "
                               f"(have {[r for r, _ in self.resolutions]})")
        return [{"t": p[0], "min": p[1], "max": p[2], "sum": p[3],
                 "count": p[4],
                 "mean": (p[3] / p[4] if p[4] else 0.0)}
                for p in pts if p is not None and p[0] > since]

    def stats(self) -> Dict:
        with self._lock:
            return {"names": len(self._series),
                    "overflow": self._overflow,
                    "resolutions": [list(rc)
                                    for rc in self.resolutions]}


def open_sink(path: str):
    """Line-buffered append JSONL sink for a TimeSeriesStore."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    return open(path, "a", buffering=1, encoding="utf-8")


#: process-wide store, mirroring global_metrics / global_tracer.
global_series = TimeSeriesStore()
