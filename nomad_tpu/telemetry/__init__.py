"""Cluster health plane (ISSUE 15).

Three layers, one substrate for the rebalancing / autoscaling tiers
the roadmap has queued behind it:

  * `health` — the device-side fleet reduction (utilization
    histograms, stranded-capacity fragmentation, busy / per-DC spread
    accounting, evictable pressure) with its bit-identical numpy twin.
  * `series` — bounded multi-resolution time-series rings (1s/10s/60s
    with min/max/sum/count downsampling, JSONL sink).
  * `slo` — multi-window error-budget burn-rate alerting for the
    serving tier.

Served over `/v1/telemetry/health` and `/v1/telemetry/series`, and
merged into the Prometheus exposition via the shared registry.
"""
from .health import (HealthCounters, MAX_DC, MAX_NODES, N_EDGES,
                     UTIL_EDGES, device_health_counters, health_host,
                     tier_bytes)
from .series import (DEFAULT_RESOLUTIONS, TimeSeriesStore, global_series,
                     open_sink)
from .slo import SloBurnTracker

__all__ = [
    "DEFAULT_RESOLUTIONS", "HealthCounters", "MAX_DC", "MAX_NODES",
    "N_EDGES", "SloBurnTracker", "TimeSeriesStore", "UTIL_EDGES",
    "device_health_counters", "global_series", "health_host",
    "open_sink", "tier_bytes",
]
