"""Client-side CSI volume mount lifecycle.

Reference: client/pluginmanager/csimanager/ — the reference's manager
owns per-plugin gRPC clients and drives NodeStageVolume /
NodePublishVolume around alloc setup (volume.go MountVolume /
UnmountVolume), refcounting the staging mount across allocs.  Same
shape here over the framed-RPC CSI protocol (plugins/csi.py):

  mount(plugin, vol, alloc)    -> stage once per (plugin, vol), then
                                  publish a per-alloc target path
  unmount(plugin, vol, alloc)  -> unpublish; unstage on last ref

Paths follow the reference's layout under the client data dir:
<data_dir>/csi/staging/<plugin>/<vol> and
<data_dir>/csi/per-alloc/<alloc>/<vol>.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ..plugins.csi import CSIError, CSIPluginClient


class CSIManager:
    def __init__(self, data_dir: str):
        self.base = os.path.join(data_dir, "csi")
        self._plugins: Dict[str, CSIPluginClient] = {}
        self._stage_refs: Dict[Tuple[str, str], int] = {}
        # serializes the whole stage/publish/refcount sequence per
        # volume (reference: csimanager's volume usage tracker) — a
        # bare refcount read outside the lock lets two concurrent
        # mounts both see refs==0 and double-stage
        self._vol_locks: Dict[Tuple[str, str], threading.Lock] = {}
        self._lock = threading.Lock()

    def _acquire_vol(self, key: Tuple[str, str]) -> threading.Lock:
        """Acquire the per-volume lock.  Entries are dropped when the
        last reference unstages, so re-check identity after acquiring:
        a waiter that won a deleted lock must retry against the fresh
        one or two mounts could interleave."""
        while True:
            with self._lock:
                lock = self._vol_locks.get(key)
                if lock is None:
                    lock = self._vol_locks[key] = threading.Lock()
            lock.acquire()
            with self._lock:
                if self._vol_locks.get(key) is lock:
                    return lock
            lock.release()

    def _release_vol(self, key: Tuple[str, str], lock: threading.Lock,
                     drop: bool) -> None:
        if drop:
            with self._lock:
                if self._vol_locks.get(key) is lock:
                    del self._vol_locks[key]
        lock.release()

    # ------------------------------------------------------- plugins
    def register_plugin(self, name: str, addr) -> CSIPluginClient:
        """Register an external plugin endpoint (reference: dynamic
        plugin registry fed by plugin-supervisor task hooks)."""
        client = CSIPluginClient(tuple(addr))
        if not client.probe():
            raise CSIError(f"plugin {name!r} failed probe")
        with self._lock:
            self._plugins[name] = client
        return client

    def plugin(self, name: str) -> Optional[CSIPluginClient]:
        with self._lock:
            return self._plugins.get(name)

    def plugin_names(self):
        with self._lock:
            return sorted(self._plugins)

    # -------------------------------------------------------- mounts
    def _staging_path(self, plugin: str, vol: str) -> str:
        return os.path.join(self.base, "staging", plugin,
                            vol.replace("/", "_"))

    def _target_path(self, alloc_id: str, vol: str) -> str:
        return os.path.join(self.base, "per-alloc", alloc_id,
                            vol.replace("/", "_"))

    def mount(self, plugin_name: str, volume_id: str, alloc_id: str,
              read_only: bool = False) -> str:
        client = self.plugin(plugin_name)
        if client is None:
            raise CSIError(f"no CSI plugin {plugin_name!r} registered")
        staging = self._staging_path(plugin_name, volume_id)
        target = self._target_path(alloc_id, volume_id)
        key = (plugin_name, volume_id)
        lock = self._acquire_vol(key)
        try:
            refs = self._stage_refs.get(key, 0)
            if refs == 0:
                client.node_stage(volume_id, staging)
            try:
                client.node_publish(volume_id, staging, target,
                                    read_only=read_only)
            except BaseException:
                # a first-reference stage with no publish would leak:
                # nothing records it, so nothing would ever unstage it
                if refs == 0:
                    try:
                        client.node_unstage(volume_id, staging)
                    except CSIError:
                        pass
                raise
            self._stage_refs[key] = refs + 1
        finally:
            self._release_vol(key, lock,
                              drop=self._stage_refs.get(key, 0) == 0)
        return target

    def unmount(self, plugin_name: str, volume_id: str,
                alloc_id: str) -> None:
        client = self.plugin(plugin_name)
        if client is None:
            return
        target = self._target_path(alloc_id, volume_id)
        key = (plugin_name, volume_id)
        lock = self._acquire_vol(key)
        refs = 1
        try:
            try:
                client.node_unpublish(volume_id, target)
            except CSIError:
                pass
            refs = max(0, self._stage_refs.get(key, 1) - 1)
            self._stage_refs[key] = refs
            if refs == 0:
                self._stage_refs.pop(key, None)
                try:
                    client.node_unstage(volume_id,
                                        self._staging_path(plugin_name,
                                                           volume_id))
                except CSIError:
                    pass
        finally:
            self._release_vol(key, lock, drop=refs == 0)
