"""Node fingerprinting pipeline (reference: client/fingerprint_manager.go
+ client/fingerprint/ — arch, cpu, host, memory, storage, nomad, plus the
driver manager's per-driver fingerprints).

Builds Node.attributes and NodeResources from the host, merges driver
fingerprints, and computes the node class hash that powers feasibility
memoization.
"""
from __future__ import annotations

import os
import platform
import shutil
import socket
from typing import Dict, Optional

from ..structs import NetworkResource, Node, NodeReservedResources, \
    NodeResources
from ..utils.ids import generate_uuid

VERSION = "0.1.0"


def _cpu_total_mhz() -> int:
    cores = os.cpu_count() or 1
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except (OSError, ValueError):
        pass
    return int(cores * mhz)


def _memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except (OSError, ValueError):
        pass
    return 1024


def _disk_mb(path: str) -> int:
    try:
        return int(shutil.disk_usage(path).total // (1024 * 1024))
    except OSError:
        return 10 * 1024


def fingerprint_node(data_dir: str = "/tmp",
                     registry=None,
                     datacenter: str = "dc1",
                     node_class: str = "",
                     meta: Optional[Dict[str, str]] = None,
                     device_registry=None) -> Node:
    """Run all fingerprinters and assemble the Node
    (reference: fingerprint.go:31-51 registry + client.go:1295 setup)."""
    attrs: Dict[str, str] = {
        "arch": platform.machine() or "unknown",
        "kernel.name": platform.system().lower(),
        "kernel.version": platform.release(),
        "os.name": platform.system().lower(),
        "cpu.numcores": str(os.cpu_count() or 1),
        "cpu.totalcompute": str(_cpu_total_mhz()),
        "memory.totalbytes": str(_memory_mb() * 1024 * 1024),
        "nomad.version": VERSION,
        "unique.hostname": socket.gethostname(),
    }
    if registry is not None:
        for name, fp in registry.fingerprints().items():
            if fp.health == "healthy":
                attrs.update(fp.attributes)
    node = Node(
        id=generate_uuid(),
        secret_id=generate_uuid(),
        name=socket.gethostname(),
        datacenter=datacenter,
        node_class=node_class,
        attributes=attrs,
        meta=dict(meta or {}),
        node_resources=NodeResources(
            cpu=_cpu_total_mhz(),
            memory_mb=_memory_mb(),
            disk_mb=_disk_mb(data_dir),
            networks=[NetworkResource(device="lo", cidr="127.0.0.1/32",
                                      ip="127.0.0.1", mbits=1000)],
            devices=(device_registry.fingerprint_all()
                     if device_registry is not None else [])),
        reserved_resources=NodeReservedResources(),
        status="initializing",
    )
    node.compute_class()
    return node
