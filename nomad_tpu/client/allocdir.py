"""Allocation directory layout (reference: client/allocdir/).

<data_dir>/allocs/<alloc_id>/
  alloc/            shared between tasks (data/, logs/, tmp/)
  <task>/           per-task working dir
  <task>/local/     task-private scratch
  <task>/secrets/   secrets dir (tmpfs in the reference; plain dir here)

Task stdout/stderr land in alloc/logs/<task>.{stdout,stderr}.0 following
the reference's logmon naming.
"""
from __future__ import annotations

import os
import shutil


class AllocDir:
    def __init__(self, data_dir: str, alloc_id: str):
        self.alloc_id = alloc_id
        self.root = os.path.join(data_dir, "allocs", alloc_id)
        self.shared = os.path.join(self.root, "alloc")
        self.logs = os.path.join(self.shared, "logs")

    def build(self) -> None:
        for sub in ("data", "logs", "tmp"):
            os.makedirs(os.path.join(self.shared, sub), exist_ok=True)

    def task_dir(self, task: str) -> str:
        return os.path.join(self.root, task)

    def secrets_dir(self, task: str) -> str:
        return os.path.join(self.task_dir(task), "secrets")

    def build_task_dir(self, task: str) -> str:
        d = self.task_dir(task)
        for sub in ("local", "secrets", "tmp"):
            os.makedirs(os.path.join(d, sub), exist_ok=True)
        return d

    def stdout_path(self, task: str) -> str:
        return os.path.join(self.logs, f"{task}.stdout.0")

    def stderr_path(self, task: str) -> str:
        return os.path.join(self.logs, f"{task}.stderr.0")

    def exists(self) -> bool:
        return os.path.isdir(self.root)

    def destroy(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)
