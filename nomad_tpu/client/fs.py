"""Alloc filesystem introspection + host/alloc resource stats.

Reference: client/fs_endpoint.go (List/Stat/ReadAt/Stream over the
alloc dir, secrets dirs excluded), client/stats/host.go (host cpu/
memory/disk/uptime gauges), and the task-runner stats hooks
(client/allocrunner/taskrunner — per-task ResourceUsage from pids).

All functions are plain host-side reads; the HTTP layer routes them to
the owning agent (api/http_server.py `_client_route`).
"""
from __future__ import annotations

import os
import stat as statmod
import time
from typing import Dict, List, Optional

#: path components never served (reference: allocdir filters the
#: secrets dir out of every fs listing/read — fs_endpoint.go)
_DENIED_COMPONENTS = {"secrets"}


class FSError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code
        self.msg = msg


def resolve(root: str, rel: str) -> str:
    """Resolve a user path strictly inside `root` (symlink-safe), with
    the secrets dirs denied."""
    rel = (rel or "/").lstrip("/")
    for comp in rel.split("/"):
        if comp in _DENIED_COMPONENTS:
            raise FSError(403, "secrets directories are not accessible "
                               "through the fs API")
    p = os.path.realpath(os.path.join(root, rel))
    rootr = os.path.realpath(root)
    if p != rootr and not p.startswith(rootr + os.sep):
        raise FSError(403, "path escapes the allocation directory")
    # Re-check the *resolved* path's components: a symlink inside the
    # alloc dir may point at a secrets dir that the raw-path check above
    # never saw (reference: fs_endpoint.go checks the final joined path
    # against SecretsDir).
    if p != rootr:
        for comp in os.path.relpath(p, rootr).split(os.sep):
            if comp in _DENIED_COMPONENTS:
                raise FSError(403, "secrets directories are not accessible "
                                   "through the fs API")
    return p


def _entry(path: str, name: str) -> Dict:
    st = os.lstat(path)
    return {
        "name": name,
        "is_dir": statmod.S_ISDIR(st.st_mode),
        "size": st.st_size,
        "file_mode": statmod.filemode(st.st_mode),
        "mod_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                  time.gmtime(st.st_mtime)),
    }


def list_dir(root: str, rel: str) -> List[Dict]:
    p = resolve(root, rel)
    if not os.path.isdir(p):
        raise FSError(400, f"{rel!r} is not a directory")
    out = []
    for name in sorted(os.listdir(p)):
        if name in _DENIED_COMPONENTS:
            continue
        try:
            out.append(_entry(os.path.join(p, name), name))
        except OSError:
            continue
    return out


def stat_path(root: str, rel: str) -> Dict:
    p = resolve(root, rel)
    if not os.path.exists(p):
        raise FSError(404, f"no such file: {rel!r}")
    return _entry(p, os.path.basename(p.rstrip("/")) or "/")


def read_at(root: str, rel: str, offset: int = 0,
            limit: int = 1 << 20) -> bytes:
    p = resolve(root, rel)
    if os.path.isdir(p):
        raise FSError(400, f"{rel!r} is a directory")
    try:
        with open(p, "rb") as f:
            f.seek(max(0, offset))
            return f.read(max(0, min(limit, 1 << 24)))
    except FileNotFoundError:
        raise FSError(404, f"no such file: {rel!r}")


def stream_from(root: str, rel: str, offset: int,
                wait_s: float = 2.0, limit: int = 1 << 20) -> Dict:
    """Blocking tail: wait up to `wait_s` for the file to grow past
    `offset`, then return the new bytes and the next offset
    (reference: fs_endpoint.go Stream's follow frames, recast as a
    long-poll so it proxies as plain JSON)."""
    p = resolve(root, rel)
    deadline = time.monotonic() + max(0.0, min(wait_s, 30.0))
    while True:
        try:
            size = os.stat(p).st_size
        except FileNotFoundError:
            size = 0
        if size > offset or time.monotonic() >= deadline:
            break
        time.sleep(0.1)
    data = read_at(root, rel, offset, limit) if size > offset else b""
    return {"offset": offset + len(data), "data": data,
            "size": max(size, offset)}


# ----------------------------------------------------------- stats
def host_stats(data_dir: str) -> Dict:
    """Host gauges (reference: client/stats/host.go — cpu ticks,
    memory, uptime, and the data_dir disk)."""
    out: Dict = {"timestamp": time.time()}
    try:
        with open("/proc/meminfo") as f:
            mem = {}
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    mem[parts[0].rstrip(":")] = int(parts[1]) * 1024
        out["memory"] = {
            "total": mem.get("MemTotal", 0),
            "available": mem.get("MemAvailable", 0),
            "free": mem.get("MemFree", 0),
            "used": max(0, mem.get("MemTotal", 0)
                        - mem.get("MemAvailable", 0)),
        }
    except OSError:
        out["memory"] = {}
    try:
        with open("/proc/stat") as f:
            first = f.readline().split()
        ticks = [int(x) for x in first[1:8]]
        out["cpu"] = {
            "user_ticks": ticks[0], "system_ticks": ticks[2],
            "idle_ticks": ticks[3],
            "total_ticks": sum(ticks),
        }
    except (OSError, ValueError, IndexError):
        out["cpu"] = {}
    try:
        with open("/proc/uptime") as f:
            out["uptime_s"] = float(f.read().split()[0])
    except (OSError, ValueError):
        out["uptime_s"] = 0.0
    try:
        import shutil
        du = shutil.disk_usage(data_dir)
        out["disk"] = {"path": data_dir, "total": du.total,
                       "used": du.used, "free": du.free}
    except OSError:
        out["disk"] = {}
    return out


def _pid_stats(pid: int) -> Optional[Dict]:
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read().decode("ascii", "replace")
        rest = raw[raw.rfind(")") + 2:].split()
        utime, stime = int(rest[11]), int(rest[12])
        rss_pages = int(rest[21])
        return {"cpu_ticks": utime + stime,
                "rss_bytes": rss_pages * os.sysconf("SC_PAGE_SIZE")}
    except (OSError, ValueError, IndexError):
        return None


def _descendants(pid: int) -> List[int]:
    """pid plus its process subtree via /proc children files."""
    out, queue, seen = [], [pid], set()
    while queue:
        p = queue.pop()
        if p in seen:
            continue
        seen.add(p)
        out.append(p)
        try:
            for tid in os.listdir(f"/proc/{p}/task"):
                try:
                    with open(f"/proc/{p}/task/{tid}/children") as f:
                        queue.extend(int(c) for c in f.read().split())
                except (OSError, ValueError):
                    continue
        except OSError:
            continue
    return out


def task_stats(pid: int) -> Dict:
    """Aggregated ResourceUsage for a task's process subtree
    (reference: drivers/shared/executor pid_collector.go)."""
    cpu = rss = nprocs = 0
    for p in _descendants(pid):
        st = _pid_stats(p)
        if st is None:
            continue
        cpu += st["cpu_ticks"]
        rss += st["rss_bytes"]
        nprocs += 1
    return {"pid": pid, "num_procs": nprocs,
            "cpu_ticks": cpu, "rss_bytes": rss,
            "timestamp": time.time()}
