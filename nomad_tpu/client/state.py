"""Durable client state DB (reference: client/state/ — StateDB iface
interface.go:12, BoltDB impl state_database.go, memdb.go for tests).

SQLite replaces BoltDB: allocs, per-task runner local state (including
the driver TaskHandle re-attach token), and per-task TaskState. An agent
restart restores from here and re-attaches to live workloads instead of
re-running them.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

from ..plugins.drivers import TaskHandle
from ..structs import Allocation, TaskState
from ..utils.codec import from_wire, to_wire

SCHEMA_VERSION = 1


class StateDB:
    """SQLite-backed (reference BoltDB `state.db`)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._lock = threading.Lock()
        self._closed = False
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        with self._lock, self._conn:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, "
                "value TEXT)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS allocs (id TEXT PRIMARY KEY, "
                "data TEXT)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS task_state ("
                "alloc_id TEXT, task TEXT, local TEXT, state TEXT, "
                "PRIMARY KEY (alloc_id, task))")
            self._conn.execute(
                "INSERT OR REPLACE INTO meta VALUES ('schema_version', ?)",
                (str(SCHEMA_VERSION),))

    # -------------------------------------------------------------- allocs
    def put_allocation(self, alloc: Allocation) -> None:
        blob = json.dumps(to_wire(alloc))
        with self._lock:
            if self._closed:
                return                 # racing writers during shutdown
            with self._conn:
                self._conn.execute(
                    "INSERT OR REPLACE INTO allocs VALUES (?, ?)",
                    (alloc.id, blob))

    def get_all_allocations(self) -> List[Allocation]:
        with self._lock:
            rows = self._conn.execute("SELECT data FROM allocs").fetchall()
        return [from_wire(Allocation, json.loads(r[0])) for r in rows]

    def delete_allocation(self, alloc_id: str) -> None:
        with self._lock:
            if self._closed:
                return
            with self._conn:
                self._conn.execute("DELETE FROM allocs WHERE id=?",
                                   (alloc_id,))
                self._conn.execute("DELETE FROM task_state WHERE alloc_id=?",
                                   (alloc_id,))

    # ---------------------------------------------------------- task state
    def put_task_runner_state(self, alloc_id: str, task: str,
                              handle: Optional[TaskHandle],
                              task_state: Optional[TaskState]) -> None:
        """Both columns are written unconditionally: a None handle MEANS
        'no live driver task' and must clear any stale re-attach token
        (otherwise a restarted agent would recover a task that already
        exited and double-count its exit)."""
        local = json.dumps(to_wire(handle)) if handle else None
        state = json.dumps(to_wire(task_state)) if task_state else None
        with self._lock:
            if self._closed:
                return
            with self._conn:
                self._conn.execute(
                    "INSERT OR REPLACE INTO task_state VALUES (?, ?, ?, ?)",
                    (alloc_id, task, local, state))

    def get_task_runner_state(
            self, alloc_id: str, task: str
    ) -> Tuple[Optional[TaskHandle], Optional[TaskState]]:
        with self._lock:
            row = self._conn.execute(
                "SELECT local, state FROM task_state WHERE alloc_id=? "
                "AND task=?", (alloc_id, task)).fetchone()
        if row is None:
            return None, None
        handle = (from_wire(TaskHandle, json.loads(row[0]))
                  if row[0] else None)
        state = (from_wire(TaskState, json.loads(row[1]))
                 if row[1] else None)
        return handle, state

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._conn.close()


class MemDB:
    """In-memory StateDB for tests (reference: client/state/memdb.go)."""

    def __init__(self):
        self._allocs: Dict[str, Allocation] = {}
        self._task: Dict[Tuple[str, str], Tuple[Optional[TaskHandle],
                                                Optional[TaskState]]] = {}
        self._lock = threading.Lock()

    def put_allocation(self, alloc: Allocation) -> None:
        with self._lock:
            self._allocs[alloc.id] = alloc

    def get_all_allocations(self) -> List[Allocation]:
        with self._lock:
            return list(self._allocs.values())

    def delete_allocation(self, alloc_id: str) -> None:
        with self._lock:
            self._allocs.pop(alloc_id, None)
            for key in [k for k in self._task if k[0] == alloc_id]:
                self._task.pop(key, None)

    def put_task_runner_state(self, alloc_id, task, handle, task_state):
        with self._lock:
            self._task[(alloc_id, task)] = (handle, task_state)

    def get_task_runner_state(self, alloc_id, task):
        with self._lock:
            return self._task.get((alloc_id, task), (None, None))

    def close(self) -> None:
        pass
