"""Simulated node agent for in-process end-to-end tests.

A thin stand-in for the full client (reference: client/client.go —
watchAllocations :1924, runAllocs :2147, allocSync :1858): polls the
server for allocs desired on its node, "runs" them through a scriptable
mock driver, and pushes client-status updates back. The real agent
(fingerprinting, task runner hooks, exec drivers) is SURVEY §7.2 step 9.
"""
from __future__ import annotations

import copy
import threading
import time as _time
from typing import Callable, Dict, Optional

from ..structs import (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                       ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING,
                       ALLOC_DESIRED_RUN, Allocation, Node, TaskState)

# mock driver behavior: config key "mock_outcome" on the task drives it
#   run        -> runs until stopped (default)
#   complete   -> finishes successfully after mock_runtime_s
#   fail       -> fails after mock_runtime_s


class SimClient:
    def __init__(self, server, node: Node, poll_interval_s: float = 0.02):
        self.server = server
        self.node = node
        self.poll_interval_s = poll_interval_s
        self._known: Dict[str, str] = {}    # alloc id -> client status
        self._started_at: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.server.register_node(self.node)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    # ------------------------------------------------------------- loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._sync_once()
            except Exception:
                pass
            self._stop.wait(self.poll_interval_s)

    def _sync_once(self) -> None:
        updates = []
        for alloc in self.server.store.allocs_by_node(self.node.id):
            if alloc.desired_status != ALLOC_DESIRED_RUN:
                if (self._known.get(alloc.id) == ALLOC_CLIENT_RUNNING
                        and not alloc.client_terminal_status()):
                    updates.append(self._terminal(alloc,
                                                  ALLOC_CLIENT_COMPLETE))
                continue
            status = self._known.get(alloc.id)
            if status is None and not alloc.client_terminal_status():
                updates.append(self._transition(alloc, ALLOC_CLIENT_RUNNING))
                self._started_at[alloc.id] = _time.time()
            elif status == ALLOC_CLIENT_RUNNING:
                outcome, runtime = self._mock_config(alloc)
                elapsed = _time.time() - self._started_at.get(alloc.id, 0)
                if outcome == "complete" and elapsed >= runtime:
                    updates.append(self._terminal(alloc,
                                                  ALLOC_CLIENT_COMPLETE))
                elif outcome == "fail" and elapsed >= runtime:
                    updates.append(self._terminal(alloc,
                                                  ALLOC_CLIENT_FAILED))
        if updates:
            self.server.update_allocs_from_client(updates)

    def _mock_config(self, alloc: Allocation):
        job = alloc.job
        if job is None:
            return "run", 0.0
        tg = job.lookup_task_group(alloc.task_group)
        if tg is None or not tg.tasks:
            return "run", 0.0
        cfg = tg.tasks[0].config or {}
        return (cfg.get("mock_outcome", "run"),
                float(cfg.get("mock_runtime_s", 0.0)))

    def _transition(self, alloc: Allocation, status: str) -> Allocation:
        self._known[alloc.id] = status
        upd = copy.copy(alloc)
        upd.client_status = status
        upd.task_states = {
            t.name: TaskState(state="running", started_at=_time.time())
            for t in (alloc.job.lookup_task_group(alloc.task_group).tasks
                      if alloc.job else [])}
        # deployment allocs report health immediately on running (the real
        # client's health watcher waits min_healthy_time; the sim keeps
        # e2e deployment tests fast)
        if alloc.deployment_id and status == ALLOC_CLIENT_RUNNING:
            from ..structs import AllocDeploymentStatus
            upd.deployment_status = AllocDeploymentStatus(
                healthy=True, timestamp=_time.time())
        upd.modify_time = _time.time()
        return upd

    def _terminal(self, alloc: Allocation, status: str) -> Allocation:
        self._known[alloc.id] = status
        now = _time.time()
        failed = status == ALLOC_CLIENT_FAILED
        upd = copy.copy(alloc)
        upd.client_status = status
        upd.task_states = {
            t.name: TaskState(state="dead", failed=failed, finished_at=now)
            for t in (alloc.job.lookup_task_group(alloc.task_group).tasks
                      if alloc.job else [])}
        if alloc.deployment_id and failed:
            from ..structs import AllocDeploymentStatus
            upd.deployment_status = AllocDeploymentStatus(
                healthy=False, timestamp=now)
        upd.modify_time = now
        return upd


def wait_until(predicate: Callable[[], bool], timeout: float = 5.0,
               interval: float = 0.02) -> bool:
    """Poll-until-true helper (reference: testutil/wait.go WaitForResult)."""
    deadline = _time.time() + timeout
    while _time.time() < deadline:
        if predicate():
            return True
        _time.sleep(interval)
    return False
