"""Task environment builder (reference: client/taskenv/env.go NewBuilder).

Builds the NOMAD_* env a task sees and interpolates ${...} references
(${attr.*}, ${meta.*}, ${node.*}, ${env.*}, ${NOMAD_*}) in task env
values and driver config strings — the same variable space constraints
use (scheduler/feasible.go:634-667).
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from ..structs import Allocation, Node, Task

_VAR_RE = re.compile(r"\$\{([^}]+)\}")


def node_vars(node: Optional[Node]) -> Dict[str, str]:
    if node is None:
        return {}
    out = {
        "node.unique.id": node.id,
        "node.unique.name": node.name,
        "node.datacenter": node.datacenter,
        "node.class": node.node_class,
        "node.region": getattr(node, "region", "") or "global",
    }
    for k, v in (node.attributes or {}).items():
        out[f"attr.{k}"] = str(v)
    for k, v in (getattr(node, "meta", None) or {}).items():
        out[f"meta.{k}"] = str(v)
    return out


def interpolate(value: str, vars_: Dict[str, str]) -> str:
    def sub(m):
        return vars_.get(m.group(1), m.group(0))
    return _VAR_RE.sub(sub, value)


def build_task_env(alloc: Allocation, task: Task, node: Optional[Node],
                   task_dir: str = "", alloc_dir: str = "",
                   secrets_dir: str = "") -> Dict[str, str]:
    job = alloc.job
    tg = job.lookup_task_group(alloc.task_group) if job else None
    env: Dict[str, str] = {
        "NOMAD_ALLOC_ID": alloc.id,
        "NOMAD_SHORT_ALLOC_ID": alloc.id[:8],
        "NOMAD_ALLOC_NAME": alloc.name,
        "NOMAD_ALLOC_INDEX": str(max(alloc.index(), 0)),
        "NOMAD_GROUP_NAME": alloc.task_group,
        "NOMAD_TASK_NAME": task.name,
        "NOMAD_JOB_ID": alloc.job_id,
        "NOMAD_JOB_NAME": job.name if job else alloc.job_id,
        "NOMAD_NAMESPACE": alloc.namespace,
        "NOMAD_DC": node.datacenter if node else "",
        "NOMAD_REGION": (getattr(node, "region", "") or "global"
                         if node else "global"),
    }
    if task_dir:
        env["NOMAD_TASK_DIR"] = f"{task_dir}/local"
    if alloc_dir:
        env["NOMAD_ALLOC_DIR"] = alloc_dir
    if secrets_dir:
        env["NOMAD_SECRETS_DIR"] = secrets_dir
    if task.resources:
        env["NOMAD_CPU_LIMIT"] = str(task.resources.cpu)
        env["NOMAD_MEMORY_LIMIT"] = str(task.resources.memory_mb)
    # meta: job < group < task precedence, exported upper-cased
    meta: Dict[str, str] = {}
    for layer in ((job.meta if job else {}), (tg.meta if tg else {}),
                  task.meta or {}):
        meta.update(layer or {})
    for k, v in meta.items():
        env[f"NOMAD_META_{k.upper().replace('-', '_')}"] = str(v)
        env[f"NOMAD_META_{k}"] = str(v)
    # ports from the allocated resources
    tr = (alloc.allocated_resources.tasks or {}).get(task.name)
    if tr:
        for net in tr.networks or []:
            for port in list(net.reserved_ports) + list(net.dynamic_ports):
                if not port.label:
                    continue
                label = port.label.upper().replace("-", "_")
                env[f"NOMAD_PORT_{label}"] = str(port.value)
                env[f"NOMAD_IP_{label}"] = net.ip
                env[f"NOMAD_ADDR_{label}"] = f"{net.ip}:{port.value}"
                env[f"NOMAD_HOST_PORT_{label}"] = str(port.value)
    # user-declared env wins, with interpolation over node vars + NOMAD_*
    vars_ = dict(node_vars(node))
    vars_.update({f"env.{k}": v for k, v in env.items()})
    vars_.update(env)
    for k, v in (task.env or {}).items():
        env[k] = interpolate(str(v), vars_)
    return env


def interpolate_config(config, vars_: Dict[str, str]):
    """Recursively interpolate strings in a driver config block."""
    if isinstance(config, str):
        return interpolate(config, vars_)
    if isinstance(config, dict):
        return {k: interpolate_config(v, vars_) for k, v in config.items()}
    if isinstance(config, list):
        return [interpolate_config(v, vars_) for v in config]
    return config
